"""X11 clipboard selection-owner monitor + provider.

The reference runs a monitor thread on the X CLIPBOARD selection
(reference src/selkies/input_handler.py:354-721 ``_X11ClipboardMonitor``):
copy in a remote app -> server notices the new selection owner, reads the
text, pushes ``clipboard`` messages to web clients; and the reverse —
client clipboard writes become an owned X selection that remote apps can
paste from (not just the cut-buffer fallback).

ctypes against libX11 + libXfixes; one dedicated thread owns the display
connection (Xlib connections are not thread-safe). Degrades to
unavailable without an X server, like every other X surface here.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import logging
import threading
from typing import Callable, Optional

logger = logging.getLogger("selkies_tpu.input.clipboard_x11")

_XFIXES_SET_SELECTION_OWNER_NOTIFY_MASK = 1
_SELECTION_NOTIFY = 31
_SELECTION_REQUEST = 30
_SELECTION_CLEAR = 29
_PROP_MODE_REPLACE = 0
_CURRENT_TIME = 0


class _XSelectionRequestEvent(ctypes.Structure):
    _fields_ = [("type", ctypes.c_int), ("serial", ctypes.c_ulong),
                ("send_event", ctypes.c_int), ("display", ctypes.c_void_p),
                ("owner", ctypes.c_ulong), ("requestor", ctypes.c_ulong),
                ("selection", ctypes.c_ulong), ("target", ctypes.c_ulong),
                ("property", ctypes.c_ulong), ("time", ctypes.c_ulong)]


class _XSelectionEvent(ctypes.Structure):
    _fields_ = [("type", ctypes.c_int), ("serial", ctypes.c_ulong),
                ("send_event", ctypes.c_int), ("display", ctypes.c_void_p),
                ("requestor", ctypes.c_ulong), ("selection", ctypes.c_ulong),
                ("target", ctypes.c_ulong), ("property", ctypes.c_ulong),
                ("time", ctypes.c_ulong)]


class X11ClipboardMonitor:
    """Watch + serve the CLIPBOARD selection on a dedicated thread.

    ``on_clipboard(text)`` fires when a remote app takes the selection
    with new text. :meth:`set_clipboard` takes ownership so X apps can
    paste what a web client copied.
    """

    def __init__(self, display: str = ":0",
                 on_clipboard: Optional[Callable[[str], None]] = None,
                 max_bytes: int = 8 * 1024 * 1024):
        x11 = ctypes.util.find_library("X11")
        xfixes = ctypes.util.find_library("Xfixes")
        if not x11 or not xfixes:
            raise RuntimeError("libX11/libXfixes not found")
        self._x = ctypes.CDLL(x11)
        self._xf = ctypes.CDLL(xfixes)
        self._x.XOpenDisplay.restype = ctypes.c_void_p
        self._dpy = self._x.XOpenDisplay(display.encode())
        if not self._dpy:
            raise RuntimeError(f"cannot open display {display}")
        self.on_clipboard = on_clipboard
        self.max_bytes = max_bytes
        self._own_text: Optional[bytes] = None
        self._own_gen = 0           # bumped per set_clipboard request
        self._lock = threading.Lock()
        self._running = False
        self._thread: Optional[threading.Thread] = None

        dpy = ctypes.c_void_p(self._dpy)
        self._x.XInternAtom.restype = ctypes.c_ulong

        def atom(name: str) -> int:
            return self._x.XInternAtom(dpy, name.encode(), 0)

        self._CLIPBOARD = atom("CLIPBOARD")
        self._UTF8 = atom("UTF8_STRING")
        self._TARGETS = atom("TARGETS")
        self._PROP = atom("SELKIES_CLIP")
        self._x.XDefaultRootWindow.restype = ctypes.c_ulong
        root = self._x.XDefaultRootWindow(dpy)
        self._x.XCreateSimpleWindow.restype = ctypes.c_ulong
        self._win = self._x.XCreateSimpleWindow(
            dpy, ctypes.c_ulong(root), 0, 0, 1, 1, 0, 0, 0)
        ev_base = ctypes.c_int(0)
        err_base = ctypes.c_int(0)
        if not self._xf.XFixesQueryExtension(dpy, ctypes.byref(ev_base),
                                             ctypes.byref(err_base)):
            raise RuntimeError("XFixes unavailable")
        self._xfixes_event = ev_base.value      # + XFixesSelectionNotify(0)
        self._xf.XFixesSelectSelectionInput(
            dpy, ctypes.c_ulong(self._win),
            ctypes.c_ulong(self._CLIPBOARD),
            _XFIXES_SET_SELECTION_OWNER_NOTIFY_MASK)
        self._x.XFlush(dpy)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="x11-clipboard", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        # the event loop never blocks in XNextEvent without XPending, so
        # clearing the flag is enough — it exits within one idle tick;
        # no X call from this (foreign) thread (Xlib is not reentrant)
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=3.0)
            self._thread = None

    # ------------------------------------------------------------- provider
    def set_clipboard(self, text: str) -> None:
        """Own the CLIPBOARD selection with ``text`` (client -> X apps).
        No X calls here — Xlib is single-threaded per connection; the
        event thread notices the generation bump within one idle tick
        and asserts ownership itself."""
        with self._lock:
            self._own_text = text.encode()[: self.max_bytes]
            self._own_gen += 1

    # ----------------------------------------------------------- event loop
    def _loop(self) -> None:
        dpy = ctypes.c_void_p(self._dpy)
        ev = ctypes.create_string_buffer(256)    # > sizeof(XEvent)
        served_gen = 0
        while self._running:
            # wake periodically so stop() and set_clipboard() make progress
            while not self._x.XPending(dpy):
                if not self._running:
                    return
                with self._lock:
                    want_gen = self._own_gen
                if want_gen != served_gen:
                    # ONE ownership assertion per set_clipboard request —
                    # re-asserting from current state would steal back any
                    # newer selection a remote app just took
                    served_gen = want_gen
                    self._x.XSetSelectionOwner(
                        dpy, ctypes.c_ulong(self._CLIPBOARD),
                        ctypes.c_ulong(self._win), _CURRENT_TIME)
                    self._x.XFlush(dpy)
                threading.Event().wait(0.05)
            self._x.XNextEvent(dpy, ev)
            etype = ctypes.cast(ev, ctypes.POINTER(ctypes.c_int))[0]
            try:
                if etype == self._xfixes_event:      # owner changed
                    self._on_owner_change(dpy)
                elif etype == _SELECTION_NOTIFY:
                    self._on_selection_ready(dpy)
                elif etype == _SELECTION_REQUEST:
                    self._serve_request(dpy, ev)
                elif etype == _SELECTION_CLEAR:
                    with self._lock:
                        self._own_text = None
            except Exception:
                logger.exception("clipboard event handling failed")

    def _on_owner_change(self, dpy) -> None:
        self._x.XGetSelectionOwner.restype = ctypes.c_ulong
        owner = self._x.XGetSelectionOwner(dpy,
                                           ctypes.c_ulong(self._CLIPBOARD))
        if owner in (0, self._win):
            return                              # nobody / ourselves
        self._x.XConvertSelection(
            dpy, ctypes.c_ulong(self._CLIPBOARD),
            ctypes.c_ulong(self._UTF8), ctypes.c_ulong(self._PROP),
            ctypes.c_ulong(self._win), _CURRENT_TIME)
        self._x.XFlush(dpy)

    def _on_selection_ready(self, dpy) -> None:
        x = self._x
        actual_type = ctypes.c_ulong(0)
        fmt = ctypes.c_int(0)
        nitems = ctypes.c_ulong(0)
        after = ctypes.c_ulong(0)
        data = ctypes.POINTER(ctypes.c_ubyte)()
        rc = x.XGetWindowProperty(
            dpy, ctypes.c_ulong(self._win), ctypes.c_ulong(self._PROP),
            0, self.max_bytes // 4, 1, ctypes.c_ulong(0),  # AnyPropertyType
            ctypes.byref(actual_type), ctypes.byref(fmt),
            ctypes.byref(nitems), ctypes.byref(after), ctypes.byref(data))
        if rc != 0 or not data or fmt.value != 8:
            return
        try:
            raw = ctypes.string_at(data, nitems.value)
        finally:
            x.XFree(data)
        cb = self.on_clipboard
        if cb is not None and raw:
            try:
                cb(raw.decode("utf-8", "replace"))
            except Exception:
                logger.exception("clipboard callback failed")

    def _serve_request(self, dpy, ev) -> None:
        req = ctypes.cast(ev,
                          ctypes.POINTER(_XSelectionRequestEvent)).contents
        with self._lock:
            text = self._own_text
        reply = _XSelectionEvent(
            type=_SELECTION_NOTIFY, serial=0, send_event=1,
            display=self._dpy, requestor=req.requestor,
            selection=req.selection, target=req.target,
            property=req.property or self._PROP, time=req.time)
        ok = False
        if text is not None:
            if req.target == self._TARGETS:
                atoms = (ctypes.c_ulong * 2)(self._TARGETS, self._UTF8)
                self._x.XChangeProperty(
                    dpy, ctypes.c_ulong(req.requestor),
                    ctypes.c_ulong(reply.property),
                    ctypes.c_ulong(4),          # XA_ATOM
                    32, _PROP_MODE_REPLACE,
                    ctypes.cast(atoms, ctypes.POINTER(ctypes.c_ubyte)), 2)
                ok = True
            elif req.target in (self._UTF8, 31):        # UTF8 / XA_STRING
                self._x.XChangeProperty(
                    dpy, ctypes.c_ulong(req.requestor),
                    ctypes.c_ulong(reply.property),
                    ctypes.c_ulong(req.target), 8, _PROP_MODE_REPLACE,
                    text, len(text))
                ok = True
        if not ok:
            reply.property = 0                   # refuse politely
        self._x.XSendEvent(dpy, ctypes.c_ulong(req.requestor), 0, 0,
                           ctypes.byref(reply))
        self._x.XFlush(dpy)
