"""Virtual gamepad data plane: unix-socket servers speaking the joystick
interposer protocol.

Games inside the container open ``/dev/input/js0``.. through the
LD_PRELOAD interposer (addons/js-interposer/), which redirects each device
to a unix socket (``/tmp/selkies_js{N}.sock`` for the legacy joystick API,
``/tmp/selkies_event100{N}.sock`` for evdev). This module is the server
side of those sockets (reference ``SelkiesGamepad``,
input_handler.py:1378-1863; wire contract: joystick_interposer.c:90-130,
344-470):

- on connect, the server sends one 1360-byte config struct
  (name/vendor/product/version/btn+axis maps);
- then streams 8-byte ``struct js_event`` or 24-byte ``struct
  input_event`` records as the browser reports gamepad state.

Browser side uses the W3C Standard Gamepad layout; the mapping below
translates it onto an Xbox-360-class evdev profile, the most widely
probed layout in game engines.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import os
import struct
import time
from typing import Optional

logger = logging.getLogger("selkies_tpu.input.gamepad")

NAME_MAX = 255
MAX_BTNS = 512
MAX_AXES = 64
JS_EVENT_BUTTON = 0x01
JS_EVENT_AXIS = 0x02
JS_EVENT_INIT = 0x80
EV_SYN, EV_KEY, EV_ABS = 0x00, 0x01, 0x03

# Xbox-360-class profile. Button order defines the js-protocol numbering.
XPAD_NAME = "Microsoft X-Box 360 pad"
XPAD_VENDOR, XPAD_PRODUCT, XPAD_VERSION = 0x045E, 0x028E, 0x0114
XPAD_BTNS = [0x130, 0x131, 0x133, 0x134, 0x136, 0x137,   # A B X Y TL TR
             0x13A, 0x13B, 0x13C, 0x13D, 0x13E]          # SEL STA MODE TH_L/R
XPAD_AXES = [0x00, 0x01, 0x02, 0x03, 0x04, 0x05,         # X Y Z RX RY RZ
             0x10, 0x11]                                  # HAT0X HAT0Y

# W3C Standard Gamepad button index -> action on the xpad profile.
# ("b", js_btn_index) | ("a", js_axis_index, pressed_val) | ("h", axis, dir)
_W3C_BTN = {
    0: ("b", 0), 1: ("b", 1), 2: ("b", 2), 3: ("b", 3),
    4: ("b", 4), 5: ("b", 5),
    6: ("a", 2),            # LT -> ABS_Z
    7: ("a", 5),            # RT -> ABS_RZ
    8: ("b", 6), 9: ("b", 7), 16: ("b", 8),
    10: ("b", 9), 11: ("b", 10),
    12: ("h", 7, -1), 13: ("h", 7, 1),    # dpad up/down -> HAT0Y
    14: ("h", 6, -1), 15: ("h", 6, 1),    # dpad left/right -> HAT0X
}
# W3C axes 0..3 -> xpad axis slots (ABS_X, ABS_Y, ABS_RX, ABS_RY)
_W3C_AXIS = {0: 0, 1: 1, 2: 3, 3: 4}


def build_config(name: str = XPAD_NAME) -> bytes:
    """The 1360-byte js_config_t the interposer expects on connect."""
    btn_map = XPAD_BTNS + [0] * (MAX_BTNS - len(XPAD_BTNS))
    axes_map = XPAD_AXES + [0] * (MAX_AXES - len(XPAD_AXES))
    return struct.pack(
        f"<{NAME_MAX}sx4H H{MAX_BTNS}H{MAX_AXES}B6x",
        name.encode()[:NAME_MAX - 1],
        XPAD_VENDOR, XPAD_PRODUCT, XPAD_VERSION, len(XPAD_BTNS),
        len(XPAD_AXES), *btn_map, *axes_map)


def pack_js_event(value: int, ev_type: int, number: int) -> bytes:
    return struct.pack("<IhBB", int(time.monotonic() * 1000) & 0xFFFFFFFF,
                       value, ev_type, number)


def pack_input_event(ev_type: int, code: int, value: int) -> bytes:
    now = time.time()
    return struct.pack("<qqHHi", int(now), int((now % 1) * 1e6),
                       ev_type, code, value)


class GamepadSocketServer:
    """One per gamepad slot: serves both the js and evdev sockets and
    translates W3C Standard Gamepad reports into device events."""

    def __init__(self, index: int, socket_dir: str = "/tmp",
                 name: str = XPAD_NAME):
        self.index = index
        self.name = name
        self.js_path = os.path.join(socket_dir, f"selkies_js{index}.sock")
        self.ev_path = os.path.join(socket_dir,
                                    f"selkies_event100{index}.sock")
        self._servers: list[asyncio.AbstractServer] = []
        self._js_clients: set[asyncio.StreamWriter] = set()
        self._ev_clients: set[asyncio.StreamWriter] = set()
        self._axis_state: dict[int, int] = {}

    async def start(self) -> None:
        for path, clients in ((self.js_path, self._js_clients),
                              (self.ev_path, self._ev_clients)):
            with contextlib.suppress(FileNotFoundError):
                os.unlink(path)
            server = await asyncio.start_unix_server(
                self._make_handler(clients, evdev=(clients is self._ev_clients)),
                path=path)
            self._servers.append(server)
        logger.info("gamepad %d serving %s + %s", self.index,
                    self.js_path, self.ev_path)

    def _make_handler(self, clients: set, evdev: bool):
        async def handler(reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
            try:
                writer.write(build_config(self.name))
                await writer.drain()
            except (ConnectionError, OSError):
                writer.close()
                return
            clients.add(writer)
            logger.info("gamepad %d: %s client connected", self.index,
                        "evdev" if evdev else "js")
            try:
                while await reader.read(4096):   # drain until EOF
                    pass
            except (ConnectionError, OSError):
                pass
            finally:
                clients.discard(writer)
                writer.close()
        return handler

    async def stop(self) -> None:
        # close live client transports FIRST: wait_closed() (3.12+) waits
        # for connection handlers, which loop until their peer EOFs
        for w in list(self._js_clients | self._ev_clients):
            w.close()
        self._js_clients.clear()
        self._ev_clients.clear()
        for s in self._servers:
            s.close()
            with contextlib.suppress(Exception):
                await asyncio.wait_for(s.wait_closed(), 2.0)
        self._servers.clear()
        for path in (self.js_path, self.ev_path):
            with contextlib.suppress(FileNotFoundError):
                os.unlink(path)

    # ----------------------------------------------------------------- sends
    def _fanout(self, js: Optional[bytes], ev: Optional[bytes]) -> None:
        for w in list(self._js_clients):
            if js:
                self._write(w, js, self._js_clients)
        for w in list(self._ev_clients):
            if ev:
                self._write(w, ev + pack_input_event(EV_SYN, 0, 0),
                            self._ev_clients)

    @staticmethod
    def _write(w: asyncio.StreamWriter, data: bytes, pool: set) -> None:
        try:
            w.write(data)
        except (ConnectionError, OSError, RuntimeError):
            pool.discard(w)
            w.close()

    def _axis(self, js_axis: int, raw: int) -> None:
        if self._axis_state.get(js_axis) == raw:
            return
        self._axis_state[js_axis] = raw
        code = XPAD_AXES[js_axis]
        self._fanout(pack_js_event(raw, JS_EVENT_AXIS, js_axis),
                     pack_input_event(EV_ABS, code, raw))

    # ------------------------------------------------------------- W3C input
    def report_button(self, w3c_index: int, value: float) -> None:
        act = _W3C_BTN.get(w3c_index)
        if act is None:
            return
        if act[0] == "b":
            num = act[1]
            pressed = 1 if value > 0.5 else 0
            self._fanout(
                pack_js_event(pressed, JS_EVENT_BUTTON, num),
                pack_input_event(EV_KEY, XPAD_BTNS[num], pressed))
        elif act[0] == "a":      # analog trigger: 0..1 -> 0..32767
            self._axis(act[1], int(max(0.0, min(1.0, value)) * 32767))
        else:                    # hat direction
            _, axis, direction = act
            raw = direction * 32767 if value > 0.5 else 0
            self._axis(axis, raw)

    def report_axis(self, w3c_index: int, value: float) -> None:
        slot = _W3C_AXIS.get(w3c_index)
        if slot is None:
            return
        self._axis(slot, int(max(-1.0, min(1.0, value)) * 32767))


class GamepadManager:
    """Bridges InputHandler's GamepadState verbs onto socket servers,
    creating each slot's server lazily on first ``js,c``."""

    def __init__(self, input_handler, socket_dir: str = "/tmp"):
        self._dir = socket_dir
        self._servers: dict[int, GamepadSocketServer] = {}
        self._handler = input_handler
        for gp in input_handler.gamepads:
            gp.listeners.append(
                lambda kind, num, value, slot=gp.index:
                self._on_event(slot, kind, num, value))

    async def ensure_slot(self, slot: int, name: str) -> None:
        if slot not in self._servers:
            srv = GamepadSocketServer(slot, self._dir, name or XPAD_NAME)
            await srv.start()
            self._servers[slot] = srv

    def _on_event(self, slot: int, kind: str, num: int, value: float) -> None:
        srv = self._servers.get(slot)
        if srv is None:
            return
        if kind == "b":
            srv.report_button(num, value)
        elif kind == "a":
            srv.report_axis(num, value)

    async def sync_slots(self) -> None:
        """Create servers for every connected GamepadState slot."""
        for gp in self._handler.gamepads:
            if gp.connected:
                await self.ensure_slot(gp.index, gp.name)

    async def stop(self) -> None:
        for srv in self._servers.values():
            await srv.stop()
        self._servers.clear()
