"""Input verb dispatcher shared by every transport.

Fresh implementation of the reference's ``WebRTCInput`` responsibilities
(input_handler.py:1866-4807, SURVEY.md §2.1 row 8): keyboard with
server-side auto-repeat and stuck-key recovery, absolute/relative mouse,
scroll, two-way clipboard with bounded multipart transfers, gamepad state,
and the opt-in shell verb.

Verb grammar (client -> server; names match the reference protocol,
SURVEY.md §2.3):

- ``kd,<keysym>`` / ``ku,<keysym>``: key down/up (X11 keysym, decimal)
- ``kr``: release everything (panic reset)
- ``kh,<keysym>[,<keysym>...]``: heartbeat for held keys; keys without a
  heartbeat for ``STALE_KEY_S`` are force-released (reference
  input_handler.py:2408-2467)
- ``m,<x>,<y>``: absolute move; ``m2,<dx>,<dy>``: relative move
- ``mb,<button>,<0|1>``: button event; ``ms,<dx>,<dy>``: scroll
- ``p,<0|1>``: pointer visibility hint
- ``cw,<b64>``: client writes text clipboard; ``cr``: client requests it;
  ``cws``/``cwd,<b64>``/``cwe``: bounded multipart write;
  ``cb*``: binary/image variants with a mime in ``cbs,<mime>``
- ``js,c|b|a,...``: gamepad config/button/axis
- ``cmd,<shell>``: opt-in command execution
"""

from __future__ import annotations

import asyncio
import base64
import logging
import time
from typing import Awaitable, Callable, Optional

from ..taskutil import spawn_retained
from .backends import InputBackend, NullBackend, make_backend

logger = logging.getLogger("selkies_tpu.input.handler")

MAX_PRESSED_KEYS = 1024          # kd-flood cap (reference parity)
STALE_KEY_S = 2.0                # heartbeat-less keys get released
REPEAT_DELAY_S = 0.5
REPEAT_HZ = 25.0


class GamepadState:
    """Virtual gamepad model; the interposer socket server consumes this
    (SURVEY.md §2.2 joystick interposer row)."""

    def __init__(self, index: int):
        self.index = index
        self.name = "Selkies TPU Virtual Gamepad"
        self.buttons: dict[int, float] = {}
        self.axes: dict[int, float] = {}
        self.connected = False
        self.listeners: list[Callable[[str, int, float], None]] = []

    def emit(self, kind: str, num: int, value: float) -> None:
        for fn in list(self.listeners):
            try:
                fn(kind, num, value)
            except Exception:
                logger.exception("gamepad listener failed")


class InputHandler:
    """One per server process; transports feed verbs, clients with input
    authority only (the service enforces viewer/collaborator rules)."""

    def __init__(self, backend: Optional[InputBackend] = None,
                 enable_command_verb: bool = False,
                 clipboard_max_bytes: int = 64 * 1024 * 1024,
                 send_clipboard: Optional[Callable[[bytes, str], Awaitable[None]]] = None,
                 now: Callable[[], float] = time.monotonic):
        self.backend = backend if backend is not None else NullBackend()
        #: optional input.gamepad.GamepadManager — serves the interposer
        #: unix sockets; slots spin up lazily on the first ``js,c``
        self.gamepad_manager = None
        self.enable_command_verb = enable_command_verb
        self.clipboard_max = clipboard_max_bytes
        self.send_clipboard = send_clipboard
        self._now = now  # injectable for deterministic tests
        # keysym -> (first press time, last heartbeat time). Kept separate:
        # repeat delay is measured from the PRESS, staleness from the last
        # heartbeat — conflating them lets a fast-heartbeating client reset
        # the repeat delay forever and suppress auto-repeat entirely.
        self.pressed: dict[int, tuple[float, float]] = {}
        self.gamepads = [GamepadState(i) for i in range(4)]
        self._multipart: Optional[dict] = None
        self._repeat_task: Optional[asyncio.Task] = None
        self._sweep_task: Optional[asyncio.Task] = None
        # strong refs to fire-and-forget tasks (subprocess reaps): the
        # loop only holds weak references
        self._bg_tasks: set = set()
        self.pointer_visible = True

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        loop = asyncio.get_event_loop()
        self._sweep_task = loop.create_task(self._stale_sweep())
        self._repeat_task = loop.create_task(self._repeat_loop())
        # X selection-owner monitor (reference _X11ClipboardMonitor,
        # input_handler.py:354): remote copies push to clients unprompted
        listener_hook = getattr(self.backend, "set_change_listener", None)
        if listener_hook is not None:
            def _changed(data: bytes, mime: str) -> None:
                # monitor-thread -> loop boundary
                if self.send_clipboard is not None:
                    asyncio.run_coroutine_threadsafe(
                        self.send_clipboard(data, mime), loop)
            listener_hook(_changed)

    async def stop(self) -> None:
        for t in (self._sweep_task, self._repeat_task,
                  *list(self._bg_tasks)):
            if t:
                t.cancel()
                try:
                    await t
                except asyncio.CancelledError:
                    pass
        self.release_all()
        self.backend.close()

    # ------------------------------------------------------------ key safety
    def release_all(self) -> None:
        for ks in list(self.pressed):
            self.backend.key(ks, False)
        self.pressed.clear()

    def sweep_stale_once(self) -> list[int]:
        """One stale-key pass: release keys without a heartbeat for
        ``STALE_KEY_S`` (reference input_handler.py:2408-2467)."""
        cutoff = self._now() - STALE_KEY_S
        released = []
        for ks, (_first, hb) in list(self.pressed.items()):
            if hb < cutoff:
                logger.info("releasing stale key %d", ks)
                self.backend.key(ks, False)
                self.pressed.pop(ks, None)
                released.append(ks)
        return released

    def repeat_once(self) -> list[int]:
        """One auto-repeat pass: re-press repeatable keys held beyond the
        delay (measured from PRESS time, not heartbeat time)."""
        now = self._now()
        repeated = []
        for ks, (first, _hb) in self.pressed.items():
            if now - first > REPEAT_DELAY_S and _is_repeatable(ks):
                self.backend.key(ks, True)
                repeated.append(ks)
        return repeated

    async def _stale_sweep(self) -> None:
        while True:
            await asyncio.sleep(STALE_KEY_S / 2)
            self.sweep_stale_once()

    async def _repeat_loop(self) -> None:
        """XTEST holds don't trigger X native auto-repeat; synthesise it
        (reference input_handler.py:2468-2553)."""
        period = 1.0 / REPEAT_HZ
        while True:
            await asyncio.sleep(period)
            self.repeat_once()

    # --------------------------------------------------------------- dispatch
    async def on_message(self, text: str) -> None:
        verb, _, args = text.partition(",")
        fn = getattr(self, f"_v_{verb}", None)
        if fn is None:
            logger.debug("unknown input verb %r", verb)
            return
        await fn(args)

    # keyboard ---------------------------------------------------------------
    async def _v_kd(self, args: str) -> None:
        ks = int(args)
        if len(self.pressed) >= MAX_PRESSED_KEYS:
            return  # kd flood
        if ks not in self.pressed:
            now = self._now()
            self.pressed[ks] = (now, now)
            self.backend.key(ks, True)

    async def _v_ku(self, args: str) -> None:
        ks = int(args)
        self.pressed.pop(ks, None)
        self.backend.key(ks, False)

    async def _v_kr(self, args: str) -> None:
        self.release_all()

    async def _v_kh(self, args: str) -> None:
        now = self._now()
        for part in args.split(","):
            if part:
                ks = int(part)
                if ks in self.pressed:
                    self.pressed[ks] = (self.pressed[ks][0], now)

    # pointer ----------------------------------------------------------------
    async def _v_m(self, args: str) -> None:
        x, y = (int(float(v)) for v in args.split(",")[:2])
        self.backend.pointer_motion(x, y)

    async def _v_m2(self, args: str) -> None:
        dx, dy = (int(float(v)) for v in args.split(",")[:2])
        self.backend.pointer_motion_rel(dx, dy)

    async def _v_mb(self, args: str) -> None:
        btn, down = args.split(",")[:2]
        self.backend.pointer_button(int(btn), down == "1")

    async def _v_ms(self, args: str) -> None:
        dx, dy = (int(float(v)) for v in args.split(",")[:2])
        self.backend.scroll(dx, dy)

    async def _v_p(self, args: str) -> None:
        self.pointer_visible = args.strip() == "1"

    # clipboard --------------------------------------------------------------
    async def _v_cw(self, args: str) -> None:
        data = base64.b64decode(args)
        if len(data) <= self.clipboard_max:
            self.backend.set_clipboard(data, "text/plain")

    async def _v_cr(self, args: str) -> None:
        if self.send_clipboard:
            data, mime = self.backend.get_clipboard()
            await self.send_clipboard(data, mime)

    # reference clients ask with the long verb (SURVEY §2.3)
    _v_REQUEST_CLIPBOARD = _v_cr

    async def _v_cws(self, args: str) -> None:
        self._multipart = {"mime": "text/plain", "parts": [], "size": 0}

    async def _v_cbs(self, args: str) -> None:
        self._multipart = {"mime": args or "application/octet-stream",
                           "parts": [], "size": 0}

    async def _multipart_data(self, args: str) -> None:
        if self._multipart is None:
            return
        chunk = base64.b64decode(args)
        self._multipart["size"] += len(chunk)
        if self._multipart["size"] > self.clipboard_max:
            logger.warning("multipart clipboard exceeded cap; dropping")
            self._multipart = None
            return
        self._multipart["parts"].append(chunk)

    async def _v_cwd(self, args: str) -> None:
        await self._multipart_data(args)

    async def _v_cbd(self, args: str) -> None:
        await self._multipart_data(args)

    async def _multipart_end(self) -> None:
        if self._multipart is None:
            return
        data = b"".join(self._multipart["parts"])
        self.backend.set_clipboard(data, self._multipart["mime"])
        self._multipart = None

    async def _v_cwe(self, args: str) -> None:
        await self._multipart_end()

    async def _v_cbe(self, args: str) -> None:
        await self._multipart_end()

    # gamepad ----------------------------------------------------------------
    async def _v_js(self, args: str) -> None:
        parts = args.split(",")
        kind = parts[0]
        if kind == "c":               # js,c,<slot>,<name...>
            slot = int(parts[1]) if len(parts) > 1 else 0
            if 0 <= slot < len(self.gamepads):
                gp = self.gamepads[slot]
                gp.connected = True
                if len(parts) > 2:
                    gp.name = ",".join(parts[2:])[:255] or gp.name
                if self.gamepad_manager is not None:
                    await self.gamepad_manager.ensure_slot(slot, gp.name)
        elif kind == "d":             # js,d,<slot> disconnect
            slot = int(parts[1]) if len(parts) > 1 else 0
            if 0 <= slot < len(self.gamepads):
                self.gamepads[slot].connected = False
        elif kind == "b":             # js,b,<slot>,<button>,<0|1>
            slot, btn, val = int(parts[1]), int(parts[2]), float(parts[3])
            gp = self.gamepads[slot]
            gp.buttons[btn] = val
            gp.emit("b", btn, val)
        elif kind == "a":             # js,a,<slot>,<axis>,<value>
            slot, axis, val = int(parts[1]), int(parts[2]), float(parts[3])
            gp = self.gamepads[slot]
            gp.axes[axis] = val
            gp.emit("a", axis, val)

    # shell ------------------------------------------------------------------
    async def _v_cmd(self, args: str) -> None:
        if not self.enable_command_verb:
            logger.warning("cmd verb rejected (disabled)")
            return
        proc = await asyncio.create_subprocess_shell(
            args, stdout=asyncio.subprocess.DEVNULL,
            stderr=asyncio.subprocess.DEVNULL)
        # reap the child without blocking the verb; retained so the
        # task can't be garbage-collected before the process exits
        spawn_retained(self._bg_tasks, proc.wait())


def _is_repeatable(keysym: int) -> bool:
    """Printables, arrows, backspace/delete repeat; modifiers must not."""
    if 0xFFE1 <= keysym <= 0xFFEE:   # modifiers
        return False
    return True
