/* Minimal libavcodec decode shim, driven from Python via ctypes.
 *
 * Test oracle for the TPU H.264 encoder: feeds Annex-B access units to the
 * ffmpeg H.264 decoder and returns YUV420 planes. An *independent*
 * implementation decoding our bitstream is the only honest conformance
 * check (SURVEY.md §7 hard-part #3) — the in-tree numpy decoder shares
 * table transcriptions with the encoder, this one shares nothing.
 *
 * Build: gcc -O2 -shared -fPIC -o libavdec_shim.so avdec_shim.c \
 *            -lavcodec -lavutil
 */

#include <libavcodec/avcodec.h>
#include <libavutil/frame.h>
#include <stdlib.h>
#include <string.h>

typedef struct {
    AVCodecContext *ctx;
    AVFrame *frame;
    AVPacket *pkt;
} Dec;

void *dec_open(const char *codec_name)
{
    const AVCodec *codec = avcodec_find_decoder_by_name(codec_name);
    if (!codec)
        return NULL;
    Dec *d = calloc(1, sizeof(Dec));
    if (!d)
        return NULL;
    d->ctx = avcodec_alloc_context3(codec);
    d->frame = av_frame_alloc();
    d->pkt = av_packet_alloc();
    if (!d->ctx || !d->frame || !d->pkt || avcodec_open2(d->ctx, codec, NULL) < 0) {
        free(d);
        return NULL;
    }
    return d;
}

/* Decode one access unit. Returns 0 on success with a decoded frame,
 * 1 on "needs more data", negative on error. Planes are copied into the
 * caller-provided buffers (y: w*h, u/v: (w/2)*(h/2) for yuv420). */
int dec_decode(void *h, const uint8_t *data, int size,
               uint8_t *out_y, uint8_t *out_u, uint8_t *out_v,
               int *out_w, int *out_h)
{
    Dec *d = (Dec *)h;
    int ret = av_new_packet(d->pkt, size);
    if (ret < 0)
        return ret;
    memcpy(d->pkt->data, data, size);
    ret = avcodec_send_packet(d->ctx, d->pkt);
    av_packet_unref(d->pkt);
    if (ret < 0)
        return ret;
    ret = avcodec_receive_frame(d->ctx, d->frame);
    if (ret == AVERROR(EAGAIN))
        return 1;
    if (ret < 0)
        return ret;
    int w = d->frame->width, h2 = d->frame->height;
    *out_w = w;
    *out_h = h2;
    for (int r = 0; r < h2; r++)
        memcpy(out_y + (size_t)r * w,
               d->frame->data[0] + (size_t)r * d->frame->linesize[0], w);
    int cw = w / 2, ch = h2 / 2;
    for (int r = 0; r < ch; r++) {
        memcpy(out_u + (size_t)r * cw,
               d->frame->data[1] + (size_t)r * d->frame->linesize[1], cw);
        memcpy(out_v + (size_t)r * cw,
               d->frame->data[2] + (size_t)r * d->frame->linesize[2], cw);
    }
    av_frame_unref(d->frame);
    return 0;
}

/* Flush the decoder so low-delay single-AU streams emit their frame. */
int dec_flush(void *h, uint8_t *out_y, uint8_t *out_u, uint8_t *out_v,
              int *out_w, int *out_h)
{
    Dec *d = (Dec *)h;
    int ret = avcodec_send_packet(d->ctx, NULL);
    if (ret < 0 && ret != AVERROR_EOF)
        return ret;
    ret = avcodec_receive_frame(d->ctx, d->frame);
    if (ret < 0)
        return ret;
    int w = d->frame->width, h2 = d->frame->height;
    *out_w = w;
    *out_h = h2;
    for (int r = 0; r < h2; r++)
        memcpy(out_y + (size_t)r * w,
               d->frame->data[0] + (size_t)r * d->frame->linesize[0], w);
    int cw = w / 2, ch = h2 / 2;
    for (int r = 0; r < ch; r++) {
        memcpy(out_u + (size_t)r * cw,
               d->frame->data[1] + (size_t)r * d->frame->linesize[1], cw);
        memcpy(out_v + (size_t)r * cw,
               d->frame->data[2] + (size_t)r * d->frame->linesize[2], cw);
    }
    av_frame_unref(d->frame);
    return 0;
}

void dec_close(void *h)
{
    Dec *d = (Dec *)h;
    if (!d)
        return;
    avcodec_free_context(&d->ctx);
    av_frame_free(&d->frame);
    av_packet_free(&d->pkt);
    free(d);
}
