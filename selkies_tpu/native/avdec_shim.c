/* Minimal libavcodec decode shim, driven from Python via ctypes.
 *
 * Test oracle for the TPU H.264 encoder: feeds Annex-B access units to the
 * ffmpeg H.264 decoder and returns YUV420 planes. An *independent*
 * implementation decoding our bitstream is the only honest conformance
 * check (SURVEY.md §7 hard-part #3) — the in-tree numpy decoder shares
 * table transcriptions with the encoder, this one shares nothing.
 *
 * Build: gcc -O2 -shared -fPIC -o libavdec_shim.so avdec_shim.c \
 *            -lavcodec -lavutil
 */

#include <libavcodec/avcodec.h>
#include <libavutil/frame.h>
#include <stdlib.h>
#include <string.h>

typedef struct {
    AVCodecContext *ctx;
    AVFrame *frame;
    AVPacket *pkt;
} Dec;

void *dec_open(const char *codec_name)
{
    const AVCodec *codec = avcodec_find_decoder_by_name(codec_name);
    if (!codec)
        return NULL;
    Dec *d = calloc(1, sizeof(Dec));
    if (!d)
        return NULL;
    d->ctx = avcodec_alloc_context3(codec);
    d->frame = av_frame_alloc();
    d->pkt = av_packet_alloc();
    if (!d->ctx || !d->frame || !d->pkt || avcodec_open2(d->ctx, codec, NULL) < 0) {
        free(d);
        return NULL;
    }
    return d;
}

/* Copy the decoded frame's planes out; chroma_div reports the chroma
 * subsampling divisor (2 for yuv420, 1 for yuv444 — Hi444PP streams).
 * A NULL out_chroma_div means the caller is a legacy dec_decode/dec_flush
 * user whose chroma buffers are sized w*h/4 — copying 4:4:4 chroma there
 * would overflow the heap, so such frames are rejected (-100) instead. */
static int copy_planes(Dec *d, uint8_t *out_y, uint8_t *out_u,
                       uint8_t *out_v, int *out_w, int *out_h,
                       int *out_chroma_div)
{
    int w = d->frame->width, h2 = d->frame->height;
    int fmt = d->frame->format;
    int cd = (fmt == AV_PIX_FMT_YUV444P || fmt == AV_PIX_FMT_YUVJ444P)
        ? 1 : 2;
    if (cd != 2 && !out_chroma_div) {
        av_frame_unref(d->frame);
        return -100;
    }
    *out_w = w;
    *out_h = h2;
    if (out_chroma_div)
        *out_chroma_div = cd;
    for (int r = 0; r < h2; r++)
        memcpy(out_y + (size_t)r * w,
               d->frame->data[0] + (size_t)r * d->frame->linesize[0], w);
    int cw = w / cd, ch = h2 / cd;
    for (int r = 0; r < ch; r++) {
        memcpy(out_u + (size_t)r * cw,
               d->frame->data[1] + (size_t)r * d->frame->linesize[1], cw);
        memcpy(out_v + (size_t)r * cw,
               d->frame->data[2] + (size_t)r * d->frame->linesize[2], cw);
    }
    av_frame_unref(d->frame);
    return 0;
}

/* Decode one access unit. Returns 0 on success with a decoded frame,
 * 1 on "needs more data", negative on error. Planes are copied into the
 * caller-provided buffers (y: w*h; u/v sized w*h for 4:4:4 safety). */
int dec_decode_fmt(void *h, const uint8_t *data, int size,
                   uint8_t *out_y, uint8_t *out_u, uint8_t *out_v,
                   int *out_w, int *out_h, int *out_chroma_div)
{
    Dec *d = (Dec *)h;
    int ret = av_new_packet(d->pkt, size);
    if (ret < 0)
        return ret;
    memcpy(d->pkt->data, data, size);
    ret = avcodec_send_packet(d->ctx, d->pkt);
    av_packet_unref(d->pkt);
    if (ret < 0)
        return ret;
    ret = avcodec_receive_frame(d->ctx, d->frame);
    if (ret == AVERROR(EAGAIN))
        return 1;
    if (ret < 0)
        return ret;
    return copy_planes(d, out_y, out_u, out_v, out_w, out_h,
                       out_chroma_div);
}

int dec_decode(void *h, const uint8_t *data, int size,
               uint8_t *out_y, uint8_t *out_u, uint8_t *out_v,
               int *out_w, int *out_h)
{
    return dec_decode_fmt(h, data, size, out_y, out_u, out_v,
                          out_w, out_h, NULL);
}

/* Flush the decoder so low-delay single-AU streams emit their frame. */
int dec_flush_fmt(void *h, uint8_t *out_y, uint8_t *out_u, uint8_t *out_v,
                  int *out_w, int *out_h, int *out_chroma_div)
{
    Dec *d = (Dec *)h;
    int ret = avcodec_send_packet(d->ctx, NULL);
    if (ret < 0 && ret != AVERROR_EOF)
        return ret;
    ret = avcodec_receive_frame(d->ctx, d->frame);
    if (ret < 0)
        return ret;
    return copy_planes(d, out_y, out_u, out_v, out_w, out_h,
                       out_chroma_div);
}

int dec_flush(void *h, uint8_t *out_y, uint8_t *out_u, uint8_t *out_v,
              int *out_w, int *out_h)
{
    return dec_flush_fmt(h, out_y, out_u, out_v, out_w, out_h, NULL);
}

void dec_close(void *h)
{
    Dec *d = (Dec *)h;
    if (!d)
        return;
    avcodec_free_context(&d->ctx);
    av_frame_free(&d->frame);
    av_packet_free(&d->pkt);
    free(d);
}

/* One-shot x264 CAVLC intra encode of a YUV420 frame -> Annex-B bytes.
 * Gives the test suite real-world H.264 streams to validate the in-tree
 * reference decoder's CAVLC tables against. Returns bitstream size or <0. */
int x264_encode_idr(const uint8_t *y, const uint8_t *u, const uint8_t *v,
                    int w, int h, int qp, uint8_t *out, int out_cap)
{
    const AVCodec *codec = avcodec_find_encoder_by_name("libx264");
    if (!codec)
        return -1;
    AVCodecContext *ctx = avcodec_alloc_context3(codec);
    if (!ctx)
        return -2;
    ctx->width = w;
    ctx->height = h;
    ctx->pix_fmt = AV_PIX_FMT_YUV420P;
    ctx->time_base = (AVRational){1, 30};
    ctx->gop_size = 1;
    ctx->max_b_frames = 0;
    AVDictionary *opts = NULL;
    char qpbuf[16];
    snprintf(qpbuf, sizeof qpbuf, "%d", qp);
    av_dict_set(&opts, "profile", "baseline", 0);   /* CAVLC, no B/8x8 */
    av_dict_set(&opts, "preset", "ultrafast", 0);
    av_dict_set(&opts, "tune", "zerolatency", 0);
    av_dict_set(&opts, "qp", qpbuf, 0);
    /* CAVLC, I16-only, no deblocking: the exact subset the in-tree
     * reference decoder implements, so planes must match byte-exactly. */
    av_dict_set(&opts, "x264-params",
                "annexb=1:cabac=0:analyse=none:partitions=none:no-deblock=1",
                0);
    int ret = avcodec_open2(ctx, codec, &opts);
    av_dict_free(&opts);
    if (ret < 0) {
        avcodec_free_context(&ctx);
        return -3;
    }
    AVFrame *frame = av_frame_alloc();
    if (!frame) {
        avcodec_free_context(&ctx);
        return -6;
    }
    frame->format = AV_PIX_FMT_YUV420P;
    frame->width = w;
    frame->height = h;
    if (av_frame_get_buffer(frame, 0) < 0 || !frame->data[0]) {
        av_frame_free(&frame);
        avcodec_free_context(&ctx);
        return -7;
    }
    for (int r = 0; r < h; r++)
        memcpy(frame->data[0] + (size_t)r * frame->linesize[0],
               y + (size_t)r * w, w);
    for (int r = 0; r < h / 2; r++) {
        memcpy(frame->data[1] + (size_t)r * frame->linesize[1],
               u + (size_t)r * (w / 2), w / 2);
        memcpy(frame->data[2] + (size_t)r * frame->linesize[2],
               v + (size_t)r * (w / 2), w / 2);
    }
    frame->pts = 0;
    AVPacket *pkt = av_packet_alloc();
    int size = -4;
    if (avcodec_send_frame(ctx, frame) >= 0) {
        avcodec_send_frame(ctx, NULL);  /* flush */
        if (avcodec_receive_packet(ctx, pkt) >= 0) {
            size = pkt->size <= out_cap ? pkt->size : -5;
            if (size > 0)
                memcpy(out, pkt->data, pkt->size);
            av_packet_unref(pkt);
        }
    }
    av_packet_free(&pkt);
    av_frame_free(&frame);
    avcodec_free_context(&ctx);
    return size;
}

/* Multi-frame x264 CAVLC baseline encode (IDR then P frames) -> one
 * concatenated Annex-B stream; per-frame sizes land in frame_sizes.
 * subme=0/me=dia restricts motion to full-pel vectors and no-deblock
 * keeps recon in the subset the in-tree reference decoder implements.
 * Gives tests (a) real P/skip/MV streams to validate that decoder and
 * (b) the size baseline the TPU encoder is compared against. */
int x264_encode_seq(const uint8_t *frames_y, const uint8_t *frames_u,
                    const uint8_t *frames_v, int n_frames,
                    int w, int h, int qp,
                    uint8_t *out, int out_cap, int *frame_sizes)
{
    const AVCodec *codec = avcodec_find_encoder_by_name("libx264");
    if (!codec)
        return -1;
    AVCodecContext *ctx = avcodec_alloc_context3(codec);
    if (!ctx)
        return -2;
    ctx->width = w;
    ctx->height = h;
    ctx->pix_fmt = AV_PIX_FMT_YUV420P;
    ctx->time_base = (AVRational){1, 30};
    ctx->gop_size = 600;            /* one IDR, the rest P */
    ctx->max_b_frames = 0;
    AVDictionary *opts = NULL;
    char qpbuf[16];
    snprintf(qpbuf, sizeof qpbuf, "%d", qp);
    av_dict_set(&opts, "profile", "baseline", 0);
    av_dict_set(&opts, "preset", "ultrafast", 0);
    av_dict_set(&opts, "tune", "zerolatency", 0);
    av_dict_set(&opts, "qp", qpbuf, 0);
    av_dict_set(&opts, "x264-params",
                "annexb=1:cabac=0:partitions=none:no-deblock=1:"
                "me=dia:subme=0:ref=1:bframes=0:weightp=0:8x8dct=0:"
                "scenecut=0:keyint=600",
                0);
    int ret = avcodec_open2(ctx, codec, &opts);
    av_dict_free(&opts);
    if (ret < 0) {
        avcodec_free_context(&ctx);
        return -3;
    }
    AVFrame *frame = av_frame_alloc();
    AVPacket *pkt = av_packet_alloc();
    if (!frame || !pkt) {
        av_frame_free(&frame);
        av_packet_free(&pkt);
        avcodec_free_context(&ctx);
        return -6;
    }
    frame->format = AV_PIX_FMT_YUV420P;
    frame->width = w;
    frame->height = h;
    if (av_frame_get_buffer(frame, 0) < 0 || !frame->data[0]) {
        av_frame_free(&frame);
        av_packet_free(&pkt);
        avcodec_free_context(&ctx);
        return -7;
    }
    size_t ysz = (size_t)w * h, csz = (size_t)(w / 2) * (h / 2);
    int total = 0, got = 0, rc = 0;
    for (int f = 0; f <= n_frames && rc >= 0; f++) {
        if (f < n_frames) {
            if (av_frame_make_writable(frame) < 0) { rc = -8; break; }
            for (int r = 0; r < h; r++)
                memcpy(frame->data[0] + (size_t)r * frame->linesize[0],
                       frames_y + ysz * f + (size_t)r * w, w);
            for (int r = 0; r < h / 2; r++) {
                memcpy(frame->data[1] + (size_t)r * frame->linesize[1],
                       frames_u + csz * f + (size_t)r * (w / 2), w / 2);
                memcpy(frame->data[2] + (size_t)r * frame->linesize[2],
                       frames_v + csz * f + (size_t)r * (w / 2), w / 2);
            }
            frame->pts = f;
            rc = avcodec_send_frame(ctx, frame);
        } else {
            rc = avcodec_send_frame(ctx, NULL);   /* flush */
        }
        while (rc >= 0 && got < n_frames) {
            int r2 = avcodec_receive_packet(ctx, pkt);
            if (r2 == AVERROR(EAGAIN) || r2 == AVERROR_EOF)
                break;
            if (r2 < 0) { rc = -9; break; }
            if (total + pkt->size > out_cap) { rc = -5; }
            else {
                memcpy(out + total, pkt->data, pkt->size);
                total += pkt->size;
                if (frame_sizes)
                    frame_sizes[got] = pkt->size;
                got++;
            }
            av_packet_unref(pkt);
        }
    }
    av_packet_free(&pkt);
    av_frame_free(&frame);
    avcodec_free_context(&ctx);
    if (rc < -1)
        return rc;
    return got == n_frames ? total : -10;
}
