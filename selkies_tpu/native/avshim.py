"""ctypes wrapper for the libavcodec decode/encode shim.

Builds ``libavdec_shim.so`` from ``avdec_shim.c`` on first use (gcc +
libavcodec dev headers, both in the image). Used by tests as the
*independent* H.264 oracle: decode our TPU encoder's Annex-B output, and
encode x264 CAVLC streams to validate the in-tree reference decoder.
Degrades to ``available() == False`` when the toolchain is absent.
"""

from __future__ import annotations

import ctypes
import logging
import pathlib
import subprocess
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger("selkies_tpu.native.avshim")

_DIR = pathlib.Path(__file__).resolve().parent
_SRC = _DIR / "avdec_shim.c"
_SO = _DIR / "libavdec_shim.so"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[ctypes.CDLL]:
    global _build_failed
    try:
        if _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
            lib = ctypes.CDLL(str(_SO))
            if hasattr(lib, "dec_decode_fmt"):    # stale-binary guard
                return lib
        subprocess.run(
            ["gcc", "-O2", "-shared", "-fPIC", "-o", str(_SO), str(_SRC),
             "-lavcodec", "-lavutil"],
            check=True, capture_output=True, timeout=120)
        lib = ctypes.CDLL(str(_SO))
        if not hasattr(lib, "dec_decode_fmt"):    # stale-binary guard
            raise OSError("shim missing dec_decode_fmt after rebuild")
        return lib
    except (subprocess.SubprocessError, OSError) as e:
        logger.info("avshim unavailable (%s)", e)
        _build_failed = True
        return None


def _get() -> Optional[ctypes.CDLL]:
    global _lib
    with _lock:
        if _lib is None and not _build_failed:
            lib = _build()
            if lib is not None:
                lib.dec_open.restype = ctypes.c_void_p
                lib.dec_open.argtypes = [ctypes.c_char_p]
                lib.dec_decode.restype = ctypes.c_int
                lib.dec_flush.restype = ctypes.c_int
                lib.dec_decode_fmt.restype = ctypes.c_int
                lib.dec_flush_fmt.restype = ctypes.c_int
                lib.dec_close.argtypes = [ctypes.c_void_p]
                lib.x264_encode_idr.restype = ctypes.c_int
            _lib = lib
        return _lib


def available() -> bool:
    return _get() is not None


def decode_h264(annexb: bytes, max_w: int = 8192, max_h: int = 8192
                ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Decode one Annex-B access unit with ffmpeg's H.264 decoder.

    Returns (Y, U, V) uint8 planes — chroma at /2 for 4:2:0 streams, full
    size for 4:4:4 (Hi444PP fullcolor). Raises on decode failure — a
    failure IS the test signal (our bitstream is non-conformant).
    """
    lib = _get()
    if lib is None:
        raise RuntimeError("avshim unavailable")
    h = lib.dec_open(b"h264")
    if not h:
        raise RuntimeError("h264 decoder open failed")
    try:
        y = np.empty(max_w * max_h, np.uint8)
        u = np.empty(max_w * max_h, np.uint8)   # full size: 4:4:4 safe
        v = np.empty(max_w * max_h, np.uint8)
        w = ctypes.c_int(0)
        hh = ctypes.c_int(0)
        cd = ctypes.c_int(2)
        buf = (ctypes.c_ubyte * len(annexb)).from_buffer_copy(annexb)
        args = (buf, len(annexb),
                y.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
                u.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
                v.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
                ctypes.byref(w), ctypes.byref(hh), ctypes.byref(cd))
        ret = lib.dec_decode_fmt(ctypes.c_void_p(h), *args)
        if ret == 1:  # low-delay decoder wants a flush for single AUs
            ret = lib.dec_flush_fmt(ctypes.c_void_p(h), *args[2:])
        if ret != 0:
            raise ValueError(f"h264 decode failed (ret={ret})")
        W, H, C = w.value, hh.value, cd.value
        cw, ch = W // C, H // C
        return (y[:W * H].reshape(H, W).copy(),
                u[:cw * ch].reshape(ch, cw).copy(),
                v[:cw * ch].reshape(ch, cw).copy())
    finally:
        lib.dec_close(ctypes.c_void_p(h))


def encode_x264_idr(y: np.ndarray, u: np.ndarray, v: np.ndarray,
                    qp: int = 28) -> bytes:
    """Encode one YUV420 frame as a CAVLC baseline IDR via libx264."""
    lib = _get()
    if lib is None:
        raise RuntimeError("avshim unavailable")
    h, w = y.shape
    out = np.empty(w * h * 4 + 65536, np.uint8)
    y = np.ascontiguousarray(y, np.uint8)
    u = np.ascontiguousarray(u, np.uint8)
    v = np.ascontiguousarray(v, np.uint8)
    p = ctypes.POINTER(ctypes.c_ubyte)
    size = lib.x264_encode_idr(
        y.ctypes.data_as(p), u.ctypes.data_as(p), v.ctypes.data_as(p),
        w, h, qp, out.ctypes.data_as(p), out.size)
    if size <= 0:
        raise RuntimeError(f"x264 encode failed ({size})")
    return out[:size].tobytes()


def encode_x264_seq(ys: list[np.ndarray], us: list[np.ndarray],
                    vs: list[np.ndarray], qp: int = 28
                    ) -> list[bytes]:
    """Encode a YUV420 frame sequence with libx264 (CAVLC baseline, one
    IDR then P frames, full-pel motion, deblocking off). Returns one
    Annex-B access unit per frame — real-world P/MV streams for decoder
    validation and the size baseline for the TPU encoder."""
    lib = _get()
    if lib is None:
        raise RuntimeError("avshim unavailable")
    n = len(ys)
    h, w = ys[0].shape
    fy = np.ascontiguousarray(np.stack(ys), np.uint8)
    fu = np.ascontiguousarray(np.stack(us), np.uint8)
    fv = np.ascontiguousarray(np.stack(vs), np.uint8)
    out = np.empty(n * (w * h * 4 + 65536), np.uint8)
    sizes = np.zeros(n, np.int32)
    p = ctypes.POINTER(ctypes.c_ubyte)
    total = lib.x264_encode_seq(
        fy.ctypes.data_as(p), fu.ctypes.data_as(p), fv.ctypes.data_as(p),
        n, w, h, qp, out.ctypes.data_as(p), out.size,
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int)))
    if total <= 0:
        raise RuntimeError(f"x264 seq encode failed ({total})")
    aus = []
    off = 0
    for s in sizes:
        aus.append(out[off:off + int(s)].tobytes())
        off += int(s)
    assert off == total
    return aus


class H264Session:
    """Stateful ffmpeg H.264 decode session: feed Annex-B access units in
    order (I then P frames reference prior pictures). The oracle for
    multi-frame conformance."""

    def __init__(self, max_w: int = 4096, max_h: int = 4096):
        lib = _get()
        if lib is None:
            raise RuntimeError("avshim unavailable")
        self._lib = lib
        self._h = lib.dec_open(b"h264")
        if not self._h:
            raise RuntimeError("h264 decoder open failed")
        self._y = np.empty(max_w * max_h, np.uint8)
        self._u = np.empty(max_w * max_h, np.uint8)   # full: 4:4:4 safe
        self._v = np.empty(max_w * max_h, np.uint8)

    def _planes(self, w, h, cd):
        cw, ch = w // cd, h // cd
        return (self._y[:w * h].reshape(h, w).copy(),
                self._u[:cw * ch].reshape(ch, cw).copy(),
                self._v[:cw * ch].reshape(ch, cw).copy())

    def decode(self, au: bytes):
        """-> (Y, U, V) for the decoded picture, or None when the decoder
        wants more data (delay)."""
        p = ctypes.POINTER(ctypes.c_ubyte)
        buf = (ctypes.c_ubyte * len(au)).from_buffer_copy(au)
        w = ctypes.c_int(0)
        h = ctypes.c_int(0)
        cd = ctypes.c_int(2)
        ret = self._lib.dec_decode_fmt(
            ctypes.c_void_p(self._h), buf, len(au),
            self._y.ctypes.data_as(p), self._u.ctypes.data_as(p),
            self._v.ctypes.data_as(p), ctypes.byref(w), ctypes.byref(h),
            ctypes.byref(cd))
        if ret == 1:
            return None
        if ret != 0:
            raise ValueError(f"h264 decode failed (ret={ret})")
        return self._planes(w.value, h.value, cd.value)

    def flush(self):
        p = ctypes.POINTER(ctypes.c_ubyte)
        w = ctypes.c_int(0)
        h = ctypes.c_int(0)
        cd = ctypes.c_int(2)
        ret = self._lib.dec_flush_fmt(
            ctypes.c_void_p(self._h),
            self._y.ctypes.data_as(p), self._u.ctypes.data_as(p),
            self._v.ctypes.data_as(p), ctypes.byref(w), ctypes.byref(h),
            ctypes.byref(cd))
        if ret != 0:
            return None
        return self._planes(w.value, h.value, cd.value)

    def close(self):
        if getattr(self, "_h", None):
            self._lib.dec_close(ctypes.c_void_p(self._h))
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
