"""Device telemetry + health-verdict plane.

The round-5 verdict found that every perf lever since round 3 was built
blind: the TPU relay was dead for two bench rounds and NOTHING surfaced
it until a human read the bench tail — jax silently initialised on CPU
and the pipeline kept producing plausible numbers. This package makes
that class of failure self-diagnosing:

- :mod:`.health` — named health checks (relay, backend, capture fps,
  stage p99, HBM headroom, audio liveness) each returning
  ``ok | degraded | failed`` with a reason, a liveness/readiness split
  for container orchestration, and a bounded flight recorder of
  structured incidents dumped on SIGTERM;
- :mod:`.device_monitor` — off-hot-path ``Device.memory_stats()``
  sampling (HBM in-use/peak/limit) plus ``jax.monitoring`` listeners
  counting compilations, compile seconds, and persistent-cache
  hits/misses, exported as ``selkies_device_*`` / ``selkies_compile_*``
  metrics and overlaid on the trace timeline;
- :mod:`.profiler` — on-demand ``jax.profiler`` capture behind
  ``POST /api/profile`` and ``bench.py --profile``;
- :mod:`.perf` — performance observability: static
  ``cost_analysis``/``memory_analysis`` per compiled engine step with a
  derived roofline-ms (rank levers with the relay down), plus the
  profiler-capture parser that turns one ``bench.py --profile`` run
  into a per-step device-time table, behind ``GET /api/perf`` and the
  bench ``perf`` block;
- :mod:`.energy` — joules/frame and fps-per-watt from the PR-6 cost
  analysis (per-backend pJ/flop + pJ/HBM-byte proxy with an idle-power
  floor) plus measured host power where the platform exposes it (Linux
  RAPL, device counters), source-labelled in every export; per-frame /
  per-session attribution through the trace summarizer, the ladder's
  energy-budget policy, and the heartbeat ``watts_est`` feed;
- :mod:`.qoe` — per-session wire QoE: ACK-RTT estimation, client fps,
  backpressure windows, relay/congestion-controller counters, the
  composite QoE score behind ``GET /api/sessions``, the ``qoe`` health
  check and the bounded-cardinality Prometheus export;
- :mod:`.clocksync` — NTP-style client↔server clock mapping (min-RTT
  filtered, drift-aware, step-detecting) so client frame timestamps land
  on the server timebase with a quantified error bound;
- :mod:`.slo` — declarative SLOs over g2g / fps / QoE event streams
  with error budgets, multi-window burn rates, ``GET /api/slo``, the
  ``slo`` health check and ``slo_burn`` incidents;
- :mod:`.logctx` — contextvars session/seat log correlation and the
  ``--log_format=json`` structured formatter;
- :mod:`.__main__` — ``python -m selkies_tpu.obs selftest``: the CI
  smoke, runnable with neither jax nor aiohttp installed.

Everything imports without jax/aiohttp; device and metrics touch points
are lazy and guarded (the same contract :mod:`..trace` keeps).
"""

from .clocksync import ClockSyncEstimator  # noqa: F401
from .device_monitor import DeviceMonitor, monitor  # noqa: F401
from .energy import (EnergyBudgetPolicy, EnergyMeter,  # noqa: F401
                     RaplReader, step_energy_j)
from .energy import meter as energy_meter  # noqa: F401
from .health import (DEGRADED, FAILED, OK, FlightRecorder,  # noqa: F401
                     HealthEngine, Verdict, degraded, engine, failed, ok)
from .perf import (PerfRegistry, parse_profile_dir,  # noqa: F401
                   roofline_ms, wrap_step)
from .perf import registry as perf_registry  # noqa: F401
from .profiler import ProfilerSession, profiler  # noqa: F401
from .qoe import (AckRttEstimator, QoERegistry,  # noqa: F401
                  SessionStats, qoe_score)
from .qoe import registry as qoe_registry  # noqa: F401
from .slo import Slo, SloEngine  # noqa: F401
from .slo import engine as slo_engine  # noqa: F401
