"""Offline obs CLI.

``python -m selkies_tpu.obs selftest`` — drive the real health engine,
flight recorder, device monitor, QoE registry, perf plane (cost
registry, roofline math, profiler-capture parser, critical-path
attribution), clock-sync estimator (injected drift/step timelines) and
SLO burn-rate engine (multi-window verdicts, edge-triggered incidents,
recovery — injected clocks, zero sleeps) with synthetic inputs and
verify the full verdict pipeline round-trips (the CI lint smoke,
mirroring ``python -m selkies_tpu.trace selftest``). Exits non-zero on
any contract break.

``python -m selkies_tpu.obs health`` — evaluate the process-wide engine
and print the verbose report as JSON (mostly useful under a debugger or
in a REPL-less container).

Stdlib-only: runs in the lint CI image with no jax/aiohttp installed.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from .device_monitor import DeviceMonitor
from .health import DEGRADED, FAILED, OK, HealthEngine, degraded, failed, ok


def _fail(msg: str) -> int:
    print(f"selftest FAILED: {msg}", file=sys.stderr)
    return 1


def _cmd_selftest(args: argparse.Namespace) -> int:
    eng = HealthEngine()
    state = {"fps": 60.0}

    def fps_check():
        if state["fps"] <= 0:
            return failed("capture produced 0 fps")
        if state["fps"] < 30:
            return degraded(f"{state['fps']:.0f} fps below target")
        return ok(f"{state['fps']:.0f} fps")

    eng.register("capture_fps", fps_check)
    eng.register("service", lambda: ok("active"), liveness=True)
    eng.register("crashy", lambda: 1 / 0)  # must become a failed verdict

    # healthy -> degraded -> failed transitions
    rep = eng.report(verbose=True)
    if rep["checks"]["capture_fps"]["status"] != OK:
        return _fail("fps check should start ok")
    if rep["checks"]["crashy"]["status"] != FAILED:
        return _fail("crashing check must yield a failed verdict")
    if rep["live"] is not True:
        return _fail("liveness must ignore readiness-scope failures")
    if rep["ready"] is not False:
        return _fail("a failed check must fail readiness")
    state["fps"] = 12.0
    if eng.run()["capture_fps"].status != DEGRADED:
        return _fail("fps below target must degrade")
    state["fps"] = 0.0
    if eng.run()["capture_fps"].status != FAILED:
        return _fail("0 fps must fail")
    eng.unregister("crashy")
    state["fps"] = 60.0
    rep = eng.report(verbose=True)
    if not (rep["ok"] and rep["ready"] and rep["status"] == OK):
        return _fail(f"engine should be green again: {rep}")

    # flight recorder: bounded, drop-counted, JSON-dumpable
    for i in range(eng.recorder.capacity + 10):
        eng.recorder.record("relay_death", display=f":{i}")
    snap = eng.recorder.snapshot()
    if len(snap) != eng.recorder.capacity:
        return _fail("recorder must stay bounded")
    if eng.recorder.dropped != 10 or eng.recorder.total != \
            eng.recorder.capacity + 10:
        return _fail("recorder drop accounting broken")
    for line in eng.recorder.dump_text().splitlines():
        json.loads(line)

    # device monitor: synthetic jax.monitoring events, no jax needed
    mon = DeviceMonitor(recorder=eng.recorder)
    mon.on_event("/jax/compilation_cache/cache_hits")
    mon.on_event("/jax/compilation_cache/cache_misses")
    mon.on_event_duration(
        "/jax/core/compile/backend_compile_duration_sec", 1.5)
    mon.on_event_duration(
        "/jax/core/compile/backend_compile_duration_sec", 0.5)
    cs = mon.compile_stats()
    if cs["count"] != 2 or abs(cs["total_s"] - 2.0) > 1e-6:
        return _fail(f"compile accounting broken: {cs}")
    if cs["cache_hits"] != 1 or cs["cache_misses"] != 1:
        return _fail(f"cache accounting broken: {cs}")
    ev = mon.trace_events()
    if len(ev) != 3 or ev[0]["ph"] != "M" \
            or any(e["ph"] != "X" for e in ev[1:]):
        return _fail(f"trace overlay shape broken: {ev}")
    if mon.backend_verdict().status not in (OK, FAILED):
        return _fail("backend verdict must always resolve")

    # qoe plane (ISSUE 4): registry round-trip + verdict emission.
    # Clocks are injected where the API allows; the stall case uses
    # real-monotonic-relative times so health_check()'s internal clock
    # agrees.
    from .qoe import QoERegistry
    reg = QoERegistry()
    reg.recorder = eng.recorder
    st = reg.register("ws", "seat0", 1, now=0.0)
    st.video_active = True
    st.target_fps = lambda: 60.0
    st.reported_fps = 60.0
    st.relay_provider = lambda: {"sent_bytes": 100_000,
                                 "dropped_frames": 0,
                                 "queue_depth": 0, "queued_bytes": 0}
    t = time.monotonic()
    for fid in range(20):
        st.note_sent(fid, t - 1.0 + fid * 0.01)
        st.note_ack(fid, t - 1.0 + fid * 0.01 + 0.005)
    if reg.health_check().status != OK:
        return _fail("healthy 60fps/5ms session must verdict ok")
    pcts = st.ack.percentiles()
    if pcts["n"] != 20 or not (4.0 <= pcts["p50_ms"] <= 6.0):
        return _fail(f"ack rtt percentiles broken: {pcts}")
    doc0 = reg.report(verbose=True)
    json.loads(json.dumps(doc0))           # /api/sessions must round-trip
    if doc0["count"] != 1 or doc0["sessions"][0]["qoe_score"] < 90:
        return _fail(f"healthy session must score high: {doc0}")
    # stall: frames sent 5 s ago, never ACKed -> failed + qoe_collapse
    for fid in range(100, 110):
        st.note_sent(fid, t - 5.0)
    v = reg.health_check()
    if v.status != FAILED:
        return _fail(f"5s ACK stall must fail the qoe check: {v}")
    def _collapses():
        return sum(e["kind"] == "qoe_collapse"
                   for e in eng.recorder.snapshot())

    n_collapse = _collapses()
    if not n_collapse:
        return _fail("qoe collapse must hit the flight recorder")
    if reg.health_check().status != FAILED or _collapses() != n_collapse:
        return _fail("qoe_collapse must be edge-triggered, not per-check")
    reg.unregister(st)
    if reg.health_check().status != OK:
        return _fail("empty registry must verdict ok")

    # perf plane (ISSUE 6): registry round-trip, roofline math, and the
    # profiler-capture parser — all jax-free (synthetic analyses and a
    # synthetic trace.json.gz capture dir)
    from . import perf as perf_mod
    preg = perf_mod.PerfRegistry()
    e = preg.record_analysis(
        "h264.i_step[selftest]",
        cost=[{"flops": 1e9, "bytes accessed": 8e8}],
        memory={"argument_size_in_bytes": 100,
                "output_size_in_bytes": 50,
                "temp_size_in_bytes": 25},
        backend="cpu", compile_s=1.25)
    if abs(e["roofline_ms"] - 1.0) > 1e-9:     # 8e8 B at 800 GB/s = 1 ms
        return _fail(f"roofline math broken: {e}")
    if e["peak_bytes"] != 175 or e["flops"] != 1e9:
        return _fail(f"cost/memory normalisation broken: {e}")
    prep = preg.report()
    json.loads(json.dumps(prep))           # /api/perf must round-trip
    if prep["count"] != 1 or prep["steps"][0]["name"] != \
            "h264.i_step[selftest]":
        return _fail(f"perf report shape broken: {prep}")

    import gzip
    import os
    import tempfile
    d = tempfile.mkdtemp(prefix="selkies-perf-selftest-")
    run = os.path.join(d, "plugins", "profile", "run1")
    os.makedirs(run)
    cap_events = [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 0.0, "dur": 5000.0,
         "name": "jit_h264_i_step"},
        {"ph": "X", "pid": 7, "tid": 2, "ts": 0.0, "dur": 3000.0,
         "name": "fusion.123"},
        # host-side event with a matching name: must NOT be counted
        # once a device lane exists
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 9000.0,
         "name": "jit_h264_i_step"},
    ]
    with gzip.open(os.path.join(run, "host.trace.json.gz"), "wt") as f:
        json.dump({"traceEvents": cap_events}, f)
    table = perf_mod.parse_profile_dir(
        d, step_names=["h264.i_step[64x32]"])
    if not table["device"] or table["trace_files"] != 1:
        return _fail(f"capture discovery broken: {table}")
    step = table["steps"].get("h264.i_step[64x32]")
    if step is None or abs(step["total_ms"] - 5.0) > 1e-9:
        return _fail(f"device-time step attribution broken: {table}")
    if abs(table["total_ms"] - 8.0) > 1e-9:
        return _fail(f"host events leaked into device total: {table}")

    # occupancy / critical path (the trace-side half of the perf plane):
    # a constructed overlapped timeline must attribute the gating stage
    from ..trace.summary import frame_critical_path
    cp = frame_critical_path({
        "display_id": "x", "frame_id": 1,
        "t0_ns": 0, "t1_ns": 12_000_000,
        "spans": [
            {"name": "a", "lane": "l1", "t0_ns": 0,
             "dur_ns": 10_000_000},
            {"name": "b", "lane": "l2", "t0_ns": 2_000_000,
             "dur_ns": 10_000_000},
        ]})
    if cp is None or abs(cp["stages"]["a"] - 2.0) > 1e-9 \
            or abs(cp["stages"]["b"] - 10.0) > 1e-9:
        return _fail(f"critical-path attribution broken: {cp}")
    if abs(cp["overlap_fraction"] - 0.4) > 1e-9 or cp["bubble_ms"] != 0.0:
        return _fail(f"overlap/bubble math broken: {cp}")

    # clock sync (ISSUE 7): the NTP-style estimator under injected
    # clocks — constant offset + 50 ppm drift + symmetric 4 ms wire.
    # client_of(s) = (s - base) * (1 + drift) + C, so the fit's slope
    # (offset per client ms) must read ≈ -drift.
    from .clocksync import ClockSyncEstimator
    cs = ClockSyncEstimator()
    drift = 50e-6

    def client_of(s: float) -> float:
        return (s - 1000.0) * (1.0 + drift) + 5000.0

    for i in range(20):
        s = 1000.0 + i * 500.0             # a ping every 500 ms
        cs.add_sample(client_of(s), s + 2.0, s + 2.1,
                      client_of(s + 4.1))
    if not cs.synced or cs.drift_ppm is None:
        return _fail("estimator must sync on clean samples")
    if abs(cs.drift_ppm + 50.0) > 10.0:
        return _fail(f"50ppm injected drift misread: {cs.drift_ppm}")
    s_probe = 1000.0 + 21 * 500.0          # extrapolate past the window
    mapped = cs.to_server_ms(client_of(s_probe))
    if mapped is None or abs(mapped - s_probe) > 2.0 + cs.error_bound_ms():
        return _fail(f"mapping error too large: {mapped} vs {s_probe}")
    if cs.add_sample(10.0, 0.0, 0.0, 5.0) is not None:
        return _fail("negative-RTT sample must be rejected")
    n_before = cs.steps
    s_step = 1000.0 + 22 * 500.0           # suspend/resume: clock jumps
    cs.add_sample(client_of(s_step) + 10_000.0, s_step + 2.0,
                  s_step + 2.1, client_of(s_step + 4.1) + 10_000.0)
    if cs.steps != n_before + 1:
        return _fail(f"10s clock step must reset the window: {cs.steps}")
    json.loads(json.dumps(cs.quality()))   # export must round-trip

    # SLO burn-rate engine (ISSUE 7): multi-window verdicts, edge-
    # triggered slo_burn incidents, recovery — all on injected clocks.
    from .slo import Slo, SloEngine
    slo_eng = SloEngine()
    slo_eng.recorder = eng.recorder
    slo = slo_eng.register(Slo("g2g", "selftest objective",
                               objective=0.99, burn_threshold=10.0))
    now0 = 50_000.0
    for i in range(100):
        slo.record(True, now=now0 + i)
    rep = slo_eng.report(now=now0 + 100)
    if rep["status"] != OK:
        return _fail(f"clean slo must verdict ok: {rep}")
    for i in range(60):                    # 37% bad = burn 37x > 10x
        slo.record(False, now=now0 + 100 + i)
    rep = slo_eng.report(now=now0 + 160)
    if rep["status"] != FAILED:
        return _fail(f"double-window burn must fail: {rep}")
    if slo.budget_remaining(now=now0 + 160) != 0.0:
        return _fail("37% bad vs 1% budget must exhaust the budget")

    def _burns():
        return sum(e["kind"] == "slo_burn"
                   for e in eng.recorder.snapshot())

    n_burn = _burns()
    if not n_burn:
        return _fail("slo burn must hit the flight recorder")
    slo_eng.report(now=now0 + 161)
    if _burns() != n_burn:
        return _fail("slo_burn must be edge-triggered, not per-report")
    rep = slo_eng.report(now=now0 + 8000.0)   # both windows drained
    if rep["status"] != OK:
        return _fail(f"slo must recover once the windows drain: {rep}")
    slo.record(False, n=60, now=now0 + 8000.0)
    slo.record(True, n=40, now=now0 + 8000.0)
    if slo_eng.report(now=now0 + 8001.0)["status"] != FAILED \
            or _burns() != n_burn + 1:
        return _fail("a fresh excursion must re-arm the slo_burn edge")
    if slo_eng.record("nonexistent", True):
        return _fail("events against undeclared objectives must drop")
    json.loads(json.dumps(slo_eng.report(now=now0 + 8002.0)))

    # energy plane (ISSUE 14): coefficient math, RAPL-absent fallback
    # to proxy, synthetic-RAPL measured watts, idle floor on a stalled
    # pipeline, and the per-frame/per-session attribution round-trip —
    # all stdlib-only (injected clock + RAPL root, synthetic registry)
    from . import energy as energy_mod
    c = energy_mod.coeffs_for("cpu")
    e_j = energy_mod.step_energy_j(1e9, 8e8, "cpu")
    want_j = (1e9 * c.pj_per_flop + 8e8 * c.pj_per_byte) * 1e-12
    if abs(e_j - want_j) > 1e-15:
        return _fail(f"energy coefficient math broken: {e_j} vs {want_j}")
    if energy_mod.coeffs_for("cpu-fallback-relay-dead") is not c:
        return _fail("backend-class normalisation broken")
    perf_entry_j = preg.report()["steps"][0].get("energy_j")
    if perf_entry_j is None or abs(perf_entry_j - round(e_j, 6)) > 1e-12:
        return _fail(f"perf registry energy_j broken: {perf_entry_j}")

    d_empty = tempfile.mkdtemp(prefix="selkies-energy-norapl-")
    clock_box = [100.0]
    m = energy_mod.EnergyMeter(
        perf_registry=preg,
        rapl=energy_mod.RaplReader(root=d_empty),
        clock=lambda: clock_box[0])
    if m.sample_power() is not None:
        return _fail("RAPL-absent host must yield no measured sample")
    est = m.estimate(30.0, backend="cpu")
    if est["source"] != "proxy":
        return _fail(f"RAPL-absent estimate must label proxy: {est}")
    if abs(est["watts"] - round(c.idle_w + e_j * 30.0, 3)) > 1e-9:
        return _fail(f"proxy watts math broken: {est}")
    if abs(est["fps_per_w"] - round(30.0 / est["watts"], 4)) > 1e-9:
        return _fail(f"fps_per_w identity broken: {est}")
    if abs(est["joules_frame"] * 30.0 - est["watts"]) > 1e-3:
        return _fail(f"joules_frame identity broken: {est}")
    stalled = m.estimate(0.0, backend="cpu")
    if stalled["watts"] < c.idle_w or stalled["joules_frame"] is not None:
        return _fail(f"idle floor broken on a stalled pipeline: {stalled}")

    d_rapl = tempfile.mkdtemp(prefix="selkies-energy-rapl-")
    dom = os.path.join(d_rapl, "intel-rapl:0")
    os.makedirs(dom)
    with open(os.path.join(dom, "name"), "w") as f:
        f.write("package-0\n")
    with open(os.path.join(dom, "max_energy_range_uj"), "w") as f:
        f.write(str(2 ** 32) + "\n")
    with open(os.path.join(dom, "energy_uj"), "w") as f:
        f.write("1000000\n")
    m2 = energy_mod.EnergyMeter(
        perf_registry=preg,
        rapl=energy_mod.RaplReader(root=d_rapl),
        clock=lambda: clock_box[0])
    m2.sample_power()                      # baseline read
    with open(os.path.join(dom, "energy_uj"), "w") as f:
        f.write("5000000\n")               # +4 J over...
    clock_box[0] += 2.0                    # ...2 s = 2 W
    s2 = m2.sample_power()
    if s2 is None or s2["source"] != "rapl" \
            or abs(s2["watts"] - 2.0) > 1e-9:
        return _fail(f"RAPL delta watts broken: {s2}")
    est2 = m2.estimate(10.0)
    if est2["source"] != "rapl" or abs(est2["watts"] - 2.0) > 1e-9:
        return _fail(f"measured watts must win over proxy: {est2}")

    att_tl = {
        "display_id": "s0", "frame_id": 1, "t0_ns": 0,
        "t1_ns": 12_000_000,
        "spans": [
            {"name": "a", "lane": "l1", "t0_ns": 0,
             "dur_ns": 10_000_000},
            {"name": "b", "lane": "l2", "t0_ns": 2_000_000,
             "dur_ns": 10_000_000},
        ]}
    att = energy_mod.attribute_timelines([att_tl], watts=10.0)
    if att["frames"] != 1 or abs(att["joules"] - 0.12) > 1e-9:
        return _fail(f"attribution totals broken: {att}")
    if abs(sum(att["per_stage_j"].values()) - att["joules"]) > 1e-9:
        return _fail(f"per-stage round-trip broken: {att}")
    if abs(sum(s["joules"] for s in att["per_session"].values())
           - att["joules"]) > 1e-9:
        return _fail(f"per-session round-trip broken: {att}")
    json.loads(json.dumps(m2.report(fps=10.0, timelines=[att_tl])))

    doc = {"health": eng.report(verbose=True), "monitor": mon.snapshot(),
           "qoe": doc0, "perf": prep, "device_time": table,
           "clock": cs.quality(),
           "energy": m2.report(fps=10.0),
           "slo": slo_eng.report(now=now0 + 8002.0)}
    text = json.dumps(doc)
    json.loads(text)                       # the payload must round-trip
    print(text if args.json else "selftest OK "
          f"({len(text)} bytes of verdict payload)")
    return 0


def _cmd_health(args: argparse.Namespace) -> int:
    from .health import engine
    print(json.dumps(engine.report(verbose=True), default=str))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m selkies_tpu.obs",
                                description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    ps = sub.add_parser("selftest",
                        help="drive engine+recorder+monitor synthetically")
    ps.add_argument("--json", action="store_true",
                    help="print the selftest verdict payload")
    ps.set_defaults(fn=_cmd_selftest)
    ph = sub.add_parser("health", help="verbose report of the live engine")
    ph.set_defaults(fn=_cmd_health)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
