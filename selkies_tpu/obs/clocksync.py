"""Client↔server clock synchronisation — the glass-to-glass enabler.

Every latency number before this module ended at ``ws.send`` (trace
spans, PR 2) or the ACK-RTT proxy (QoE, PR 4): network transit, client
decode and presentation were invisible because client timestamps
(``performance.now()``) live on a clock the server cannot read. This
module maps them onto the server monotonic timebase with a *quantified*
error bound, so a client-reported "presented at C ms" becomes a server
"presented at S ms" a glass-to-glass percentile can be built from.

The exchange is NTP's four-timestamp dance over the text protocol:

- client sends ``CLIENT_CLOCK ping,<seq>,<t0>`` (t0 = client clock, ms);
- server replies ``server_clock <seq>,<t0>,<t1>,<t2>`` (t1 = receive,
  t2 = transmit, both server monotonic ms);
- client echoes ``CLIENT_CLOCK sample,<seq>,<t0>,<t1>,<t2>,<t3>``
  (t3 = client receive) — the server, not the browser, owns estimation.

Per sample::

    offset = ((t1 - t0) + (t2 - t3)) / 2     # server − client, ms
    rtt    = (t3 - t0) - (t2 - t1)           # wire round-trip, ms

The classic error model: a sample's offset is wrong by at most
``rtt / 2`` (asymmetric paths). So the estimator is **min-RTT
filtered** — only samples whose RTT sits within a band of the observed
minimum vote — and **drift-aware**: browser and server monotonic clocks
tick at slightly different rates (crystal tolerance is ±50 ppm; a
50 ppm drift is 3 ms of skew per minute, which would dwarf a 16 ms
glass-to-glass budget within seconds of a stale offset), so the filtered
samples feed a least-squares linear fit ``offset(t) = a + b·t`` whose
slope is the drift and whose extrapolation keeps the mapping fresh
between pings. A sample that lands far off the fit *with a credible
(near-min) RTT* is a clock step — suspend/resume, NTP slew on the
server — and resets the window rather than polluting the fit.

Stdlib-only and clock-injected throughout (``now`` is always a caller
argument), the same contract the rest of :mod:`selkies_tpu.obs` keeps.
"""

from __future__ import annotations

import collections
from typing import Optional

__all__ = ["ClockSyncEstimator"]

#: a sample votes only when its RTT is within this band of the window
#: minimum: ``rtt <= rtt_min + max(RTT_BAND_MS, rtt_min * RTT_BAND_FRAC)``
RTT_BAND_MS = 2.0
RTT_BAND_FRAC = 0.5

#: offset residual (vs the current fit) beyond which a near-min-RTT
#: sample is treated as a clock STEP and the window resets
DEFAULT_STEP_MS = 100.0

#: fit slope is distrusted until this many filtered samples agree
MIN_FIT_SAMPLES = 3

#: ...and until the filtered window spans this much client time: real
#: crystal skew is tens of ppm, so any slope inferred from a sub-second
#: burst of pings (connection open) is measurement jitter amplified by
#: a short lever arm, not drift — extrapolating it would inject ms-level
#: errors into every mapped timestamp. Below the span the estimator runs
#: slope-0 from the best (min-RTT) sample.
MIN_FIT_SPAN_MS = 1000.0


class ClockSyncEstimator:
    """Maps one client's ``performance.now()`` timebase onto server
    monotonic milliseconds. One instance per session, fed by the
    transport; read by the glass-to-glass plumbing.

    All timestamps are milliseconds: t0/t3 on the client clock, t1/t2 on
    the server clock (``time.monotonic() * 1e3`` at the call sites).
    """

    def __init__(self, window: int = 64, step_ms: float = DEFAULT_STEP_MS):
        #: (t_client, offset_ms, rtt_ms) per accepted sample, send-ordered
        self._samples: collections.deque = collections.deque(maxlen=window)
        self.step_ms = float(step_ms)
        self.samples_total = 0
        self.rejected = 0
        self.steps = 0
        # fit cache: recomputed on every accepted sample (the window is
        # tiny; a 64-point least squares is microseconds)
        self._fit: Optional[tuple[float, float, float, float]] = None
        # (intercept_ms, slope, t_ref_ms, residual_rms_ms)

    # -- ingest --------------------------------------------------------------
    def add_sample(self, t0: float, t1: float, t2: float,
                   t3: float) -> Optional[dict]:
        """Feed one 4-timestamp exchange. Returns the derived sample
        (``offset_ms``/``rtt_ms``/``step``) or None when rejected
        (negative RTT = reordered/forged timestamps)."""
        t0, t1, t2, t3 = float(t0), float(t1), float(t2), float(t3)
        rtt = (t3 - t0) - (t2 - t1)
        if rtt < 0.0 or (t3 - t0) < 0.0:
            self.rejected += 1
            return None
        offset = ((t1 - t0) + (t2 - t3)) / 2.0
        step = False
        if self._fit is not None and self._credible_rtt(rtt):
            predicted = self.offset_at(t3)
            if predicted is not None \
                    and abs(offset - predicted) > self.step_ms:
                # a believable sample violently off the fit: the clock
                # itself moved (suspend/resume). History is now lies.
                self._samples.clear()
                self._fit = None
                self.steps += 1
                step = True
        self._samples.append((t3, offset, rtt))
        self.samples_total += 1
        self._refit()
        return {"offset_ms": offset, "rtt_ms": rtt, "step": step}

    def _credible_rtt(self, rtt: float) -> bool:
        rtt_min = self.rtt_min_ms
        if rtt_min is None:
            return True
        return rtt <= rtt_min + max(RTT_BAND_MS, rtt_min * RTT_BAND_FRAC)

    def _refit(self) -> None:
        """Least squares over the min-RTT-filtered window. Falls back to
        the single best sample (slope 0) below MIN_FIT_SAMPLES."""
        if not self._samples:
            self._fit = None
            return
        rtt_min = min(s[2] for s in self._samples)
        band = rtt_min + max(RTT_BAND_MS, rtt_min * RTT_BAND_FRAC)
        pts = [(t, off) for t, off, rtt in self._samples if rtt <= band]
        t_ref = pts[-1][0]
        if len(pts) < MIN_FIT_SAMPLES \
                or pts[-1][0] - pts[0][0] < MIN_FIT_SPAN_MS:
            best = min((s for s in self._samples), key=lambda s: s[2])
            self._fit = (best[1], 0.0, best[0], 0.0)
            return
        n = float(len(pts))
        xs = [t - t_ref for t, _ in pts]
        ys = [off for _, off in pts]
        mx = sum(xs) / n
        my = sum(ys) / n
        sxx = sum((x - mx) ** 2 for x in xs)
        if sxx <= 0.0:
            self._fit = (my, 0.0, t_ref, 0.0)
            return
        slope = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / sxx
        intercept = my - slope * mx
        resid = [y - (intercept + slope * x) for x, y in zip(xs, ys)]
        rms = (sum(r * r for r in resid) / n) ** 0.5
        self._fit = (intercept, slope, t_ref, rms)

    # -- read ----------------------------------------------------------------
    @property
    def synced(self) -> bool:
        return self._fit is not None

    @property
    def rtt_min_ms(self) -> Optional[float]:
        if not self._samples:
            return None
        return min(s[2] for s in self._samples)

    @property
    def drift_ppm(self) -> Optional[float]:
        """Client-vs-server rate skew in parts per million (slope of the
        offset fit: ms of extra offset per ms of client time)."""
        if self._fit is None:
            return None
        return self._fit[1] * 1e6

    def offset_at(self, t_client_ms: float) -> Optional[float]:
        """Predicted ``server − client`` offset at a client timestamp."""
        if self._fit is None:
            return None
        intercept, slope, t_ref, _ = self._fit
        return intercept + slope * (float(t_client_ms) - t_ref)

    def to_server_ms(self, t_client_ms: float) -> Optional[float]:
        off = self.offset_at(t_client_ms)
        if off is None:
            return None
        return float(t_client_ms) + off

    def error_bound_ms(self) -> Optional[float]:
        """Honest mapping uncertainty: half the best observed RTT (path
        asymmetry can hide that much) plus the fit's residual RMS
        (jitter the filter let through)."""
        if self._fit is None:
            return None
        rtt_min = self.rtt_min_ms or 0.0
        return rtt_min / 2.0 + self._fit[3]

    def quality(self) -> dict:
        """The export block (``/api/sessions`` verbose, bench JSON)."""
        off = self.offset_at(self._samples[-1][0]) if self._samples else None
        return {
            "synced": self.synced,
            "samples": len(self._samples),
            "samples_total": self.samples_total,
            "rejected": self.rejected,
            "steps": self.steps,
            "offset_ms": round(off, 3) if off is not None else None,
            "drift_ppm": (round(self.drift_ppm, 1)
                          if self.drift_ppm is not None else None),
            "rtt_min_ms": (round(self.rtt_min_ms, 3)
                           if self.rtt_min_ms is not None else None),
            "error_bound_ms": (round(self.error_bound_ms(), 3)
                               if self.error_bound_ms() is not None
                               else None),
        }
