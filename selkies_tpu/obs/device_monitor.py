"""Device telemetry: HBM occupancy sampling + JAX compile-event accounting.

Two feeds, both OFF the hot path:

- a background daemon thread samples ``Device.memory_stats()`` (HBM
  in-use / peak / limit per device) on an interval. ``memory_stats()``
  issues a runtime RPC that can CONTEND with the encode thread's device
  calls on single-client TPU relay transports (the reason
  ``server/metrics.device_stats`` gates it), so the sampler honours the
  same policy: ``auto`` samples only on the cpu backend unless
  ``SELKIES_DEVICE_MEMSTATS=1``; ``on``/``off`` force it either way.
- :mod:`jax.monitoring` listeners count compilations, total compile
  seconds, and persistent-cache hits/misses as they happen. Listener
  callbacks run inside jax's compile path — they only bump counters
  under a lock and append to a bounded ring, never touch the device.

Everything is exported as ``selkies_device_*`` / ``selkies_compile_*``
metrics, and compile events are kept as (t0, dur) so the trace endpoint
can overlay "recompile happened HERE" on the frame timeline — the
attribution a Perfetto view needs to separate a capture stall from an
XLA recompile.

jax is imported lazily and every touch point is guarded: the module
must import (and the selftest must run) in images with no jax at all.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Optional

from .health import Verdict, degraded, failed, ok

logger = logging.getLogger("selkies_tpu.obs.devmon")

_now_ns = time.perf_counter_ns

_METRICS_UNSET = object()
_metrics_mod = _METRICS_UNSET


def _metrics():
    """The server metrics registry, or None in images without the server
    plane's dependencies (aiohttp is absent from the lint CI image; the
    selftest must still run there). Only SUCCESS is cached: the first
    call can land inside a circular-import window (importing obs before
    server pulls server.core back into the half-initialized obs
    package), and caching that transient failure silently dropped every
    ``selkies_device_*`` gauge for the life of the process."""
    global _metrics_mod
    if _metrics_mod is _METRICS_UNSET or _metrics_mod is None:
        try:
            from ..server import metrics as _m
            _metrics_mod = _m
        except Exception:
            return None
    return _metrics_mod

#: compile events kept for the trace overlay (each ~4 small fields)
EVENT_RING_CAPACITY = 256

#: a "compile storm" = this many compiles inside the window AFTER the
#: warmup grace — steady-state recompiles mean a shape/dtype is unstable
#: and every one stalls the frame path for seconds
STORM_WINDOW_S = 60.0
STORM_THRESHOLD = 5
WARMUP_GRACE_S = 120.0


def _is_cache_hit(name: str) -> bool:
    return "cache" in name and "hit" in name


def _is_cache_miss(name: str) -> bool:
    return "cache" in name and "miss" in name


def _is_compile_duration(name: str) -> bool:
    """A timer that plausibly measures an XLA build. Excludes the
    cache's own bookkeeping and jax's cheap per-call phases
    (jaxpr_trace_duration fires per TRACE, mlir lowering per call) —
    counting those as compiles would report a healthy warm-cache run as
    compile-heavy."""
    if "compil" not in name or "cache" in name:
        return False
    return not any(x in name for x in ("jaxpr", "mlir", "trace_duration"))


def _is_backend_compile(name: str) -> bool:
    """The one-per-XLA-build signal. jax also times cheap per-call
    phases under /jax/core/compile/ (jaxpr_trace_duration fires per
    TRACE, hundreds of times a minute on a live server) — those must
    feed neither the Perfetto overlay nor storm detection, or every
    steady-state jit call reads as a recompile."""
    return "backend_compile" in name


class DeviceMonitor:
    """Process-wide device/compile telemetry. One instance
    (:data:`monitor`) serves the server plane and bench; tests build
    their own and drive :meth:`on_event` / :meth:`on_event_duration`
    with synthetic events and fake device objects."""

    def __init__(self, recorder=None):
        self._lock = threading.Lock()
        #: jax.monitoring duration accounting per event name
        self._durations: dict[str, list] = {}   # name -> [count, total_s]
        self._events: dict[str, int] = collections.defaultdict(int)
        self.cache_hits = 0
        self.cache_misses = 0
        #: (t0_ns, dur_ns, name) of compile duration events, bounded
        self._compile_ring: collections.deque = \
            collections.deque(maxlen=EVENT_RING_CAPACITY)
        self._storm_times: collections.deque = collections.deque(maxlen=64)
        self._storm_reported = 0.0
        self._started_at = time.monotonic()
        self._attached = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.interval_s = 5.0
        self.sampling = "auto"          # auto | on | off
        self.platform: Optional[str] = None
        #: last memory sample per device id, and the process-lifetime peak
        self.devices: list[dict] = []
        self.hbm_peak_bytes = 0
        self._sampled_once = False
        self._recorder = recorder

    # ------------------------------------------------------------ lifecycle
    def attach_jax(self, jax_module=None) -> bool:
        """Register the jax.monitoring listeners (idempotent). Safe to
        call in jax-less images — returns False and stays dormant.

        Deliberately does NOT probe the backend: ``default_backend()``
        forces PJRT initialisation, and on a hung TPU relay that blocks
        forever — on the server's startup path it would keep /api/health
        (the endpoint built to diagnose exactly that state) from ever
        binding. ``self.platform`` is discovered by the first
        :meth:`sample` on the daemon thread instead; bench sets it
        explicitly after its own jax init."""
        if self._attached:
            return True
        try:
            jax = jax_module
            if jax is None:
                import jax  # noqa: PLC0415 - lazy by design
            from jax import monitoring as jmon
            jmon.register_event_listener(self._jax_event)
            jmon.register_event_duration_secs_listener(self._jax_duration)
            self._attached = True
            return True
        except Exception as e:
            logger.debug("jax.monitoring unavailable: %s", e)
            return False

    def start(self, interval_s: Optional[float] = None,
              sampling: Optional[str] = None) -> None:
        """Start the background HBM sampler thread (daemon). The
        listeners fire regardless; the thread only does memory_stats."""
        if interval_s is not None:
            self.interval_s = max(0.5, float(interval_s))
        if sampling is not None:
            self.sampling = sampling
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="obs-devmon")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception:
                logger.exception("device sample failed")

    # ------------------------------------------------------- event listeners
    def _jax_event(self, name: str, **kw) -> None:
        try:
            self.on_event(str(name))
        except Exception:       # listener runs inside jax's compile path
            logger.debug("event accounting failed", exc_info=True)

    def _jax_duration(self, name: str, duration: float, **kw) -> None:
        try:
            self.on_event_duration(str(name), float(duration))
        except Exception:
            logger.debug("duration accounting failed", exc_info=True)

    def on_event(self, name: str) -> None:
        """Counter-style jax.monitoring event (cache hits/misses live
        here). Public so tests can feed synthetic events."""
        metrics = _metrics()
        with self._lock:
            self._events[name] += 1
            if _is_cache_hit(name):
                self.cache_hits += 1
                if metrics:
                    metrics.inc_counter("selkies_compile_cache_hits_total")
            elif _is_cache_miss(name):
                self.cache_misses += 1
                if metrics:
                    metrics.inc_counter("selkies_compile_cache_misses_total")

    def on_event_duration(self, name: str, duration_s: float) -> None:
        """Duration-style jax.monitoring event. Every name is accounted
        per-event; only the backend_compile signal (one per XLA build)
        lands in the trace ring — t0 back-dated by the duration, the
        listener fires when the compile ENDS — and feeds storm
        detection and the selkies_compile_* counters."""
        with self._lock:
            acc = self._durations.setdefault(name, [0, 0.0])
            acc[0] += 1
            acc[1] += duration_s
            if not _is_backend_compile(name):
                return
            dur_ns = int(duration_s * 1e9)
            t0 = _now_ns() - dur_ns
            self._compile_ring.append((t0, dur_ns, name))
            storm = self._note_compile_locked()
        metrics = _metrics()
        if metrics:
            metrics.inc_counter("selkies_compile_events_total")
            metrics.inc_counter("selkies_compile_seconds_total", duration_s)
        if storm is not None:
            self._record_incident("compile_storm", count=storm[0],
                                  window_s=storm[1], event=name)

    def _note_compile_locked(self) -> Optional[tuple]:
        """Storm detection (lock held). Returns (count, window) when a
        NEW storm should be reported, else None."""
        now = time.monotonic()
        self._storm_times.append(now)
        if now - self._started_at < WARMUP_GRACE_S:
            return None             # cold-start compiles are expected
        recent = [t for t in self._storm_times if now - t <= STORM_WINDOW_S]
        if len(recent) >= STORM_THRESHOLD \
                and now - self._storm_reported > STORM_WINDOW_S:
            self._storm_reported = now
            return (len(recent), STORM_WINDOW_S)
        return None

    def _record_incident(self, kind: str, **fields) -> None:
        rec = self._recorder
        if rec is None:
            from .health import engine
            rec = engine.recorder
        try:
            rec.record(kind, **fields)
        except Exception:
            logger.debug("incident record failed", exc_info=True)

    def storm_recent(self, within_s: float = STORM_WINDOW_S) -> bool:
        """True while a reported compile storm is fresh — the pre-warm
        worker (selkies_tpu/prewarm) pauses its background builds then:
        when the frame path is already compile-bound, speculative
        lattice compiles would pile onto the same XLA queue."""
        with self._lock:
            t = self._storm_reported
        return bool(t) and time.monotonic() - t <= within_s

    # -------------------------------------------------------------- sampling
    def _should_sample_mem(self, platform: str) -> bool:
        if self.sampling == "on":
            return True
        if self.sampling == "off":
            return False
        return platform == "cpu" \
            or os.environ.get("SELKIES_DEVICE_MEMSTATS") == "1"

    def sample(self, force: bool = False) -> list[dict]:
        """One memory_stats pass over local devices. BLOCKING (runtime
        RPC per device): call from the monitor thread, an executor, or
        bench code that owns the process — never the event loop."""
        metrics = _metrics()
        try:
            import jax
            devices = list(jax.local_devices())
        except Exception:
            return []
        out: list[dict] = []
        peak_seen = 0
        for d in devices:
            platform = getattr(d, "platform", "?")
            self.platform = self.platform or platform
            ms = {}
            if force or self._should_sample_mem(platform):
                try:
                    ms = d.memory_stats() or {}
                except Exception:
                    ms = {}
            in_use = int(ms.get("bytes_in_use", 0))
            peak = int(ms.get("peak_bytes_in_use", 0) or in_use)
            limit = int(ms.get("bytes_limit", 0)
                        or ms.get("bytes_reservable_limit", 0))
            peak_seen = max(peak_seen, peak)
            labels = {"device": str(getattr(d, "id", len(out))),
                      "platform": platform}
            entry = {"id": getattr(d, "id", len(out)),
                     "platform": platform,
                     "kind": getattr(d, "device_kind", "?"),
                     "hbm_in_use": in_use, "hbm_peak": peak,
                     "hbm_limit": limit,
                     "hbm_pct": round(100.0 * in_use / limit, 1)
                     if limit else 0.0}
            out.append(entry)
            if ms and metrics:
                metrics.set_gauge("selkies_device_hbm_bytes", in_use, labels)
                metrics.set_gauge("selkies_device_hbm_peak_bytes", peak,
                                  labels)
                if limit:
                    metrics.set_gauge("selkies_device_hbm_limit_bytes",
                                      limit, labels)
        with self._lock:
            self.devices = out
            self.hbm_peak_bytes = max(self.hbm_peak_bytes, peak_seen)
            self._sampled_once = True
        # host power telemetry (ISSUE 14) rides the SAME off-hot-path
        # cadence: RAPL / device power counters are blocking reads with
        # exactly the contention profile memory_stats() has, so the
        # energy meter never owns a thread of its own
        try:
            from . import energy as _energy
            if _energy.meter.platform is None:
                _energy.meter.platform = self.platform
            _energy.meter.sample_power()
        except Exception:
            logger.debug("power sample failed", exc_info=True)
        return out

    @property
    def sampler_active(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def cached_sample(self) -> list[dict]:
        """Last sample when the background thread owns the cadence —
        callers (the ws stats loop) must not add a SECOND memory_stats
        RPC pass on top of the sampler's, doubling exactly the
        encode-thread contention the gating exists to avoid. Samples
        inline only when no thread runs (tests, bench)."""
        if self.sampler_active:
            with self._lock:
                if self._sampled_once:
                    return list(self.devices)
        return self.sample()

    # -------------------------------------------------------------- snapshot
    def compile_stats(self) -> dict:
        """{count, total_s, cache_hits, cache_misses, by_event}. Count
        and total come from the busiest compile-duration event name so
        session- and backend-level timers for the same compile are never
        double-counted."""
        with self._lock:
            compile_names = {n: v for n, v in self._durations.items()
                             if _is_compile_duration(n)}
            count = total = 0
            if compile_names:
                # prefer the backend_compile timer when present — it is
                # the one-per-XLA-build signal
                backend = {n: v for n, v in compile_names.items()
                           if "backend_compile" in n}
                pool = backend or compile_names
                best = max(pool.values(), key=lambda v: v[0])
                count, total = best[0], best[1]
            return {
                "count": int(count),
                "total_s": round(float(total), 3),
                "cache_hits": self.cache_hits,
                "cache_misses": self.cache_misses,
                "by_event": {n: {"count": v[0],
                                 "total_s": round(v[1], 3)}
                             for n, v in sorted(self._durations.items())},
            }

    def snapshot(self) -> dict:
        with self._lock:
            devices = list(self.devices)
            peak = self.hbm_peak_bytes
        return {"platform": self.platform, "devices": devices,
                "hbm_peak_bytes": peak,
                "hbm_peak_mb": round(peak / (1024 * 1024), 1),
                "compile": self.compile_stats()}

    def hbm_peak_mb(self) -> float:
        with self._lock:
            return round(self.hbm_peak_bytes / (1024 * 1024), 1)

    def trace_events(self, pid: int = 1, tid: int = 99) -> list[dict]:
        """Compile events as Chrome trace-event dicts on a ``device``
        lane, mergeable into :func:`..trace.export.to_trace_events`
        output (same perf_counter µs timebase)."""
        with self._lock:
            ring = list(self._compile_ring)
        if not ring:
            return []
        events: list[dict] = [{
            "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
            "args": {"name": "device"},
        }]
        for t0, dur, name in ring:
            events.append({
                "name": f"compile:{name.rsplit('/', 1)[-1]}",
                "ph": "X", "pid": pid, "tid": tid,
                "ts": t0 / 1e3, "dur": max(dur, 1) / 1e3,
                "args": {"event": name},
            })
        return events

    # --------------------------------------------------------------- health
    def backend_verdict(self) -> Verdict:
        """Real-device vs cpu-fallback (the r04/r05 silent-failure
        mode). An explicit fallback reason (bench probe, mid-run
        re-exec) is always ``failed``; an intended accelerator that came
        up as cpu is ``failed``; an explicitly-requested cpu backend is
        honest ``ok``."""
        reason = os.environ.get("BENCH_CPU_REASON") \
            or os.environ.get("SELKIES_CPU_FALLBACK_REASON")
        if reason:
            return failed(f"cpu fallback: {reason}",
                          platform=self.platform or "cpu")
        platform = self.platform
        if platform is None:
            return ok("backend not probed yet (no device telemetry)")
        if platform != "cpu":
            return ok(platform, platform=platform)
        wanted = os.environ.get("JAX_PLATFORMS", "")
        if wanted and "cpu" not in wanted.split(","):
            return failed(f"backend is cpu but JAX_PLATFORMS={wanted!r}",
                          platform="cpu")
        if not wanted and os.environ.get("PALLAS_AXON_POOL_IPS"):
            return failed("backend is cpu but a TPU relay pool is "
                          "configured (relay dead?)", platform="cpu")
        if wanted:
            return ok("cpu (explicitly requested)", platform="cpu")
        return ok("cpu (no accelerator requested)", platform="cpu")

    def hbm_verdict(self, degraded_pct: float = 90.0,
                    failed_pct: float = 98.0) -> Verdict:
        """HBM headroom from the last sample; honest ``ok`` when memory
        telemetry is gated off (better no verdict than a stale one)."""
        with self._lock:
            devices = list(self.devices)
        worst_pct, worst_dev = 0.0, None
        for d in devices:
            if d["hbm_limit"] and d["hbm_pct"] >= worst_pct:
                worst_pct, worst_dev = d["hbm_pct"], d
        if worst_dev is None:
            return ok("no device memory telemetry")
        msg = (f"device {worst_dev['id']} ({worst_dev['platform']}) at "
               f"{worst_pct:.1f}% of "
               f"{worst_dev['hbm_limit'] // (1024 * 1024)} MiB")
        if worst_pct >= failed_pct:
            return failed(msg, pct=worst_pct)
        if worst_pct >= degraded_pct:
            return degraded(msg, pct=worst_pct)
        return ok(msg, pct=worst_pct)

    def register_health_checks(self, health_engine=None) -> None:
        eng = health_engine
        if eng is None:
            from .health import engine as eng
        eng.register("backend", self.backend_verdict)
        eng.register("hbm", self.hbm_verdict)


# metric help strings (the registry renders them on first scrape)
def _describe() -> None:
    metrics = _metrics()
    if metrics is None:
        return
    metrics.describe("selkies_device_hbm_bytes",
                     "Accelerator memory in use (memory_stats)")
    metrics.describe("selkies_device_hbm_peak_bytes",
                     "Peak accelerator memory in use")
    metrics.describe("selkies_device_hbm_limit_bytes",
                     "Accelerator memory limit")
    metrics.describe("selkies_compile_events_total",
                     "XLA compilations observed via jax.monitoring")
    metrics.describe("selkies_compile_seconds_total",
                     "Total seconds spent in XLA compilation")
    metrics.describe("selkies_compile_cache_hits_total",
                     "Persistent compile-cache hits")
    metrics.describe("selkies_compile_cache_misses_total",
                     "Persistent compile-cache misses")


_describe()

#: the process-wide monitor (attach_jax + start happen in __main__ /
#: bench; until then it is inert and costs nothing)
monitor = DeviceMonitor()
