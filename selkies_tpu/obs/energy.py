"""Energy observability: joules/frame and fps-per-watt as first-class
axes of the perf plane (ROADMAP 5 / ISSUE 14).

Both encoder-efficiency papers in PAPERS.md — the NVENC HQ/UHQ
longitudinal study and the sustainable 8K60 vehicular-edge study — frame
production encoding as a quality x latency x **energy** Pareto surface.
PRs 2-7 built the first two axes end to end; this module supplies the
third, with the same honesty discipline the perf plane keeps (every
number labelled with how it was obtained, never a silent fallback):

- **Proxy model** (:func:`step_energy_j` + :class:`EnergyMeter`): the
  PR-6 AOT cost analysis already records the two inputs an energy model
  needs — flops and HBM bytes accessed per compiled step — so a
  per-backend (pJ/flop, pJ/HBM-byte) coefficient pair turns the static
  cost table into a dynamic joules-per-frame estimate, the same pattern
  as ``roofline_ms`` at :func:`..perf.roofline_ms`. An **idle-power
  floor** keeps watts from ever reading zero on a stalled pipeline (a
  chip burning 50 W while encoding nothing is the worst fps/W there is,
  and the estimate must say so).

- **Measured power** where the platform exposes it: Linux RAPL via
  ``/sys/class/powercap`` on CPU hosts (:class:`RaplReader` — cumulative
  µJ counters, wraparound-corrected), and backend device power counters
  when present. Sampling is OFF the hot path — the PR-3
  :class:`~.device_monitor.DeviceMonitor` thread drives it on its
  existing cadence — and every export carries a ``source`` label
  (``proxy`` | ``rapl`` | ``device``) so a proxy number can never
  masquerade as telemetry.

- **Attribution** through the PR-2/PR-6 trace summarizer
  (:func:`attribute_timelines`): watts x the per-frame critical-path
  account charges joules to frames, stages and sessions with the exact
  identity ``sum(stage_j) + bubble_j == frame_j`` the occupancy
  analyzer guarantees for time.

- **Control**: :class:`EnergyBudgetPolicy` gives the PR-5 degradation
  ladder an energy-aware mode — under a configured power budget the
  ladder downshifts to the *highest-efficiency* warm rung that still
  meets the SLO rather than the nearest rung (see
  ``resilience/ladder.py``); fleet heartbeats carry ``watts_est`` so
  the seat scheduler can pack against a fleet-wide power budget
  alongside HBM and pixels (``fleet/protocol.py`` / ``scheduler.py``).

Import contract: stdlib-only at import time (the lint CI image has no
jax); jax/metrics touch points are lazy and guarded.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import os
import re
import threading
import time
from typing import Callable, Iterable, Optional, Sequence

logger = logging.getLogger("selkies_tpu.obs.energy")

__all__ = ["EnergyCoeffs", "COEFFS", "coeffs_for", "step_energy_j",
           "RaplReader", "EnergyMeter", "meter", "attribute_timelines",
           "EnergyBudgetPolicy", "DEFAULT_RUNG_EFFICIENCY",
           "ladder_policy_from_settings", "SOURCES"]

#: the honest provenance labels every export carries
SOURCES = ("proxy", "rapl", "device")


@dataclasses.dataclass(frozen=True)
class EnergyCoeffs:
    """Per-backend-class energy coefficients. ``pj_per_flop`` /
    ``pj_per_byte`` price the dynamic work the PR-6 cost analysis
    counts; ``idle_w`` is the floor a powered-on part burns doing
    nothing (the stalled-pipeline case watts must never hide)."""

    pj_per_flop: float
    pj_per_byte: float
    idle_w: float


#: proxy coefficients per backend CLASS (the same normalisation the
#: perf ledger keys baselines on). Literature-scale figures, not
#: calibration: TPU-class parts land near ~1 pJ/flop at the ALU with
#: HBM2-class interfaces around ~30 pJ/byte; commodity CPU hosts pay
#: far more per flop and DDR-class DRAM ~100+ pJ/byte. They exist to
#: rank operating points against each other — absolute joules stay
#: labelled ``proxy`` until a measured source replaces them.
COEFFS: dict = {
    "tpu": EnergyCoeffs(pj_per_flop=1.2, pj_per_byte=30.0, idle_w=55.0),
    "axon": EnergyCoeffs(pj_per_flop=1.2, pj_per_byte=30.0, idle_w=55.0),
    "gpu": EnergyCoeffs(pj_per_flop=2.0, pj_per_byte=40.0, idle_w=30.0),
    "cuda": EnergyCoeffs(pj_per_flop=2.0, pj_per_byte=40.0, idle_w=30.0),
    "cpu": EnergyCoeffs(pj_per_flop=300.0, pj_per_byte=120.0, idle_w=10.0),
}


def coeffs_for(backend: Optional[str]) -> EnergyCoeffs:
    """Coefficients for a backend label ('cpu-fallback-relay-dead' ->
    the cpu class, like tools/perf_ledger.backend_class)."""
    b = (backend or "cpu").lower()
    if b.startswith("cpu"):
        b = "cpu"
    else:
        b = b.split("-", 1)[0]
    return COEFFS.get(b, COEFFS["cpu"])


def step_energy_j(flops: float, bytes_accessed: float,
                  backend: Optional[str] = None) -> float:
    """Dynamic joules for ONE execution of a compiled step — the energy
    twin of :func:`..perf.roofline_ms`, priced from the same
    cost-analysis inputs (flops, HBM bytes accessed)."""
    c = coeffs_for(backend)
    return (max(0.0, float(flops)) * c.pj_per_flop
            + max(0.0, float(bytes_accessed)) * c.pj_per_byte) * 1e-12


# ------------------------------------------------------------------- RAPL
_RAPL_DOMAIN_RE = re.compile(r"^intel-rapl:\d+$")


class RaplReader:
    """Linux RAPL package-energy reader (``/sys/class/powercap``).

    Top-level package domains only (``intel-rapl:N``) — subdomains
    (``intel-rapl:N:M``, core/uncore/dram) are slices of the package
    counter and summing them would double-count. Counters are
    cumulative µJ with a documented wrap range
    (``max_energy_range_uj``); the meter corrects wraps. Everything is
    best-effort: an absent tree, an unreadable node (non-root
    containers), or a parse error all degrade to "unavailable" and the
    caller falls back to the proxy model."""

    def __init__(self, root: Optional[str] = None):
        self.root = root if root is not None else os.environ.get(
            "SELKIES_RAPL_ROOT", "/sys/class/powercap")

    def _domains(self) -> list:
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return []
        return [os.path.join(self.root, n) for n in names
                if _RAPL_DOMAIN_RE.match(n)]

    @staticmethod
    def _read_int(path: str) -> Optional[int]:
        try:
            with open(path, encoding="ascii") as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def read_domains(self) -> dict:
        """{domain_path: energy_uj} for every readable package. Kept
        PER DOMAIN because wraparound is a per-counter event: on a
        multi-socket host one package wrapping must be corrected by
        ITS range, not the sum of every package's (a summed correction
        over-adds a whole counter range per extra socket — a phantom
        hundreds-of-watts spike)."""
        out: dict = {}
        for d in self._domains():
            v = self._read_int(os.path.join(d, "energy_uj"))
            if v is not None:
                out[d] = float(v)
        return out

    def domain_range_uj(self, domain: str) -> Optional[float]:
        v = self._read_int(os.path.join(domain, "max_energy_range_uj"))
        return None if v is None else float(v)

    def read_uj(self) -> Optional[float]:
        """Sum of package energy counters in µJ, or None when RAPL is
        unavailable/unreadable (availability probe only — watts deltas
        go through :meth:`read_domains`)."""
        doms = self.read_domains()
        return sum(doms.values()) if doms else None

    def available(self) -> bool:
        return self.read_uj() is not None


# ------------------------------------------------------------------ meter
#: a measured power sample older than this is stale — better the honest
#: proxy than a reading from before the workload changed
MEASURED_TTL_S = 60.0

#: delivered-frame stamps kept for the live fps estimate
_FRAME_RING = 1024


class EnergyMeter:
    """Process-wide energy estimator. One instance (:data:`meter`)
    serves the engine, ``/api/perf``, bench, heartbeats and metrics;
    tests build their own with an injected clock / RAPL root / perf
    registry.

    Estimation order per :meth:`estimate` call: a fresh measured sample
    (device counters > RAPL, recorded by :meth:`sample_power` on the
    DeviceMonitor's off-hot-path cadence) wins and is labelled with its
    source; otherwise the proxy model prices the heaviest registered
    step's cost analysis at the backend coefficients, plus the idle
    floor. Watts never read below the idle floor in proxy mode — a
    stalled pipeline (fps 0) is ``idle_w`` burning for nothing, the
    worst fps/W there is, not zero."""

    def __init__(self, perf_registry=None, rapl: Optional[RaplReader] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._perf = perf_registry
        self.rapl = rapl if rapl is not None else RaplReader()
        self._clock = clock
        self._lock = threading.Lock()
        #: backend label fallback when estimate() gets none (bench and
        #: the devmon sampler set it; None -> cpu coefficients)
        self.platform: Optional[str] = None
        self._rapl_last: Optional[tuple] = None   # (t, {domain: uj})
        self._measured_w: Optional[float] = None
        self._measured_src: Optional[str] = None
        self._measured_at: Optional[float] = None
        self._frames: collections.deque = collections.deque(
            maxlen=_FRAME_RING)

    # -- inputs --------------------------------------------------------------
    def _registry(self):
        if self._perf is not None:
            return self._perf
        from . import perf as _perf
        return _perf.registry

    def note_frame(self, n: int = 1) -> None:
        """One delivered frame (engine capture loops call this on the
        finalizer side): feeds the live fps estimate heartbeats and
        metrics use. Cheap by design — a timestamp append under a
        lock."""
        now = self._clock()
        with self._lock:
            for _ in range(max(1, int(n))):
                self._frames.append(now)

    def fps_estimate(self, window_s: float = 5.0) -> float:
        now = self._clock()
        with self._lock:
            recent = [t for t in self._frames if now - t <= window_s]
            saturated = (len(recent) == len(self._frames)
                         == self._frames.maxlen)
        if not recent or window_s <= 0:
            return 0.0
        if saturated:
            # the ring evicted stamps still inside the window (a busy
            # multi-seat host outruns it): rate over the span actually
            # observed, or the estimate silently caps at maxlen/window
            # and the fleet under-reports its hottest hosts
            span = now - recent[0]
            if span > 0:
                return len(recent) / span
        return len(recent) / window_s

    # -- measured power (off-hot-path: the DeviceMonitor thread) -------------
    def _device_power_w(self) -> Optional[float]:
        """Backend device power counters, when the runtime exposes any
        (duck-typed — no current PJRT CPU/TPU build does, but the hook
        is where a power_stats()-bearing runtime lands). Only probes a
        jax that is ALREADY imported: the meter must never be the
        thing that initialises a backend (on a hung TPU relay
        ``local_devices()`` blocks forever — the devmon lesson)."""
        try:
            import sys
            jax = sys.modules.get("jax")
            if jax is None:
                return None
            total = None
            for d in jax.local_devices():
                stats = None
                for attr in ("power_stats", "power_usage"):
                    fn = getattr(d, attr, None)
                    if callable(fn):
                        stats = fn()
                        break
                if isinstance(stats, dict):
                    w = stats.get("power_w")
                    if w is None:       # explicit: 0.0 is a real reading
                        w = stats.get("watts")
                    if isinstance(w, (int, float)) and w >= 0:
                        total = (total or 0.0) + float(w)
                elif isinstance(stats, (int, float)) and stats >= 0:
                    total = (total or 0.0) + float(stats)
            # an all-parked 0.0 W total is degenerate for the fps/W
            # axes (division by a floor, absurd fps_per_w): degrade to
            # the next source rather than record it as measured
            return total if total else None
        except Exception:
            return None

    def sample_power(self) -> Optional[dict]:
        """One power sample — BLOCKING file/RPC reads, so only the
        DeviceMonitor thread, bench code, or tests call it (the same
        policy memory_stats() sampling follows). Device counters win
        over RAPL; RAPL watts come from the µJ delta between successive
        samples (wrap-corrected). Returns {"watts", "source"} or None
        when no measured source exists — the estimate then stays an
        honestly-labelled proxy."""
        watts: Optional[float] = None
        source: Optional[str] = None
        w = self._device_power_w()
        if w is not None:
            watts, source = w, "device"
        else:
            try:
                doms = self.rapl.read_domains()
            except Exception:
                doms = {}
            if doms:
                now = self._clock()
                with self._lock:
                    last = self._rapl_last
                    self._rapl_last = (now, doms)
                if last is not None and now > last[0]:
                    # per-domain deltas, wrap-corrected per counter
                    d_uj: Optional[float] = 0.0
                    for dom, uj in doms.items():
                        prev = last[1].get(dom)
                        if prev is None:
                            continue        # new domain: no delta yet
                        d = uj - prev
                        if d < 0:           # THIS counter wrapped
                            rng = self.rapl.domain_range_uj(dom)
                            if rng is None:
                                d_uj = None     # unknown range: rebase
                                break
                            d += rng
                        d_uj += d
                    # strictly positive only: a frozen counter (stub
                    # powercap trees on VMs) or a sample with no
                    # overlapping domains yields delta 0 — that is
                    # "unavailable", not a measured 0 W that would
                    # beat the honest proxy and report absurd fps/W
                    if d_uj is not None and d_uj > 0:
                        watts = d_uj / 1e6 / (now - last[0])
                        source = "rapl"
        if watts is None:
            return None
        with self._lock:
            self._measured_w = float(watts)
            self._measured_src = source
            self._measured_at = self._clock()
        return {"watts": float(watts), "source": source}

    def _fresh_measured(self) -> Optional[tuple]:
        with self._lock:
            if self._measured_w is None or self._measured_at is None:
                return None
            if self._clock() - self._measured_at > MEASURED_TTL_S:
                return None
            return (self._measured_w, self._measured_src)

    # -- proxy model ---------------------------------------------------------
    def dynamic_j_frame(self, backend: Optional[str] = None) -> tuple:
        """(joules, step_name) — the proxy dynamic energy of one frame:
        the HEAVIEST registered step's cost priced at the backend
        coefficients. Max, not sum: a steady-state frame executes one
        engine step (the h264 i/p pair and stale ladder geometries
        coexist in the registry but never run in the same frame), so
        summing the table would overcount a flapping session's history.
        """
        best_j, best_name = 0.0, None
        try:
            steps = self._registry().report()["steps"]
        except Exception:
            return 0.0, None
        for s in steps:
            if s.get("error"):
                continue
            j = step_energy_j(s.get("flops", 0.0),
                              s.get("bytes_accessed", 0.0),
                              backend or s.get("backend"))
            if j > best_j:
                best_j, best_name = j, s.get("name")
        return best_j, best_name

    def estimate(self, fps: float, backend: Optional[str] = None) -> dict:
        """The energy block: watts, joules/frame, fps/W, source label.
        ``joules_frame`` is None (not 0, not infinity) when fps is 0 —
        a stalled pipeline has no per-frame number, only a watts floor.
        """
        backend = backend or self.platform
        c = coeffs_for(backend)
        fps = max(0.0, float(fps or 0.0))
        dyn_j, dyn_step = self.dynamic_j_frame(backend)
        measured = self._fresh_measured()
        if measured is not None:
            watts, source = max(float(measured[0]), 0.001), measured[1]
        else:
            # idle floor: proxy watts never read zero on a stall
            watts, source = c.idle_w + dyn_j * fps, "proxy"
            watts = max(watts, c.idle_w)
        watts = round(watts, 3)
        return {
            "fps": round(fps, 2),
            "watts": watts,
            "joules_frame": round(watts / fps, 5) if fps > 0 else None,
            "fps_per_w": round(fps / watts, 4) if watts > 0 else 0.0,
            "source": source,
            "idle_floor_w": c.idle_w,
            "dynamic_j_frame": round(dyn_j, 6),
            "dynamic_step": dyn_step,
            "backend": backend,
        }

    def watts_estimate(self) -> float:
        """Current watts for the fleet heartbeat's ``watts_est`` field:
        measured when fresh, else proxy at the live fps estimate."""
        return float(self.estimate(self.fps_estimate())["watts"])

    # -- reporting -----------------------------------------------------------
    def report(self, fps: Optional[float] = None,
               backend: Optional[str] = None,
               timelines: Optional[Iterable] = None) -> dict:
        """The ``energy`` block for ``GET /api/perf`` and bench: the
        estimate plus (when frame timelines are supplied) the per-frame
        / per-stage / per-session attribution through the PR-2/PR-6
        trace summarizer."""
        dicts = None
        if timelines is not None:
            dicts = [t if isinstance(t, dict) else t.to_dict()
                     for t in timelines]
        if fps is None:
            fps = _fps_from_dicts(dicts) if dicts else self.fps_estimate()
        est = self.estimate(fps, backend)
        if dicts:
            est["attribution"] = attribute_timelines(dicts, est["watts"])
        self._export_metrics(est)
        return est

    def bench_block(self, fps: float,
                    backend: Optional[str] = None) -> dict:
        """bench.py's ``energy`` block: the estimate keyed the way the
        ledger and the contract test read it (``watts_mean`` is the
        run-window mean — the RAPL delta over the timed loop when
        measured, the proxy at the measured fps otherwise). Contract:
        ``fps_per_w == fps / watts_mean`` by construction."""
        est = self.estimate(fps, backend)
        return {
            "joules_frame": est["joules_frame"],
            "watts_mean": est["watts"],
            "fps_per_w": est["fps_per_w"],
            "source": est["source"],
            "idle_floor_w": est["idle_floor_w"],
            "dynamic_j_frame": est["dynamic_j_frame"],
        }

    def _export_metrics(self, est: dict) -> None:
        try:
            from ..server import metrics
        except Exception:
            return
        metrics.describe("selkies_energy_watts",
                         "Estimated host power draw (source-labelled)")
        metrics.describe("selkies_energy_joules_per_frame",
                         "Estimated energy per delivered frame")
        metrics.describe("selkies_energy_fps_per_watt",
                         "Delivered frames per second per watt")
        # one series per metric: re-label on source flips (proxy ->
        # rapl) instead of stranding the old series at its last value
        for name in ("selkies_energy_watts",
                     "selkies_energy_joules_per_frame",
                     "selkies_energy_fps_per_watt"):
            metrics.clear_metric(name)
        labels = {"source": est["source"]}
        metrics.set_gauge("selkies_energy_watts", est["watts"], labels)
        if est["joules_frame"] is not None:
            metrics.set_gauge("selkies_energy_joules_per_frame",
                              est["joules_frame"], labels)
        metrics.set_gauge("selkies_energy_fps_per_watt",
                          est["fps_per_w"], labels)


#: the process-wide meter (inert until something samples/notes frames)
meter = EnergyMeter()


# ------------------------------------------------------------ attribution
def _fps_from_dicts(dicts: Sequence[dict]) -> float:
    t0 = t1 = None
    n = 0
    for d in dicts:
        if d.get("t1_ns") is None:
            continue
        n += 1
        t0 = d["t0_ns"] if t0 is None else min(t0, d["t0_ns"])
        t1 = d["t1_ns"] if t1 is None else max(t1, d["t1_ns"])
    if not n or t0 is None or t1 is None or t1 <= t0:
        return 0.0
    return n / ((t1 - t0) / 1e9)


def attribute_timelines(timelines: Iterable, watts: float) -> dict:
    """Charge ``watts`` across completed frames through the PR-6
    critical-path account (:func:`..trace.summary.frame_accounts`):
    each frame's joules = watts x its wall window, split over stages by
    the critical-path attribution (plus ``bubble``), and rolled up per
    session (display). The time identity ``stages + bubble == e2e``
    carries over exactly: ``sum(per_stage_j) == total_j`` and
    ``sum(per_session joules) == total_j``."""
    from ..trace.summary import frame_accounts
    accounts = frame_accounts(timelines)
    watts = max(0.0, float(watts))
    per_stage: dict = {}
    per_session: dict = {}
    total_j = 0.0
    for a in accounts:
        frame_j = watts * a["e2e_ms"] / 1e3
        total_j += frame_j
        for name, ms in a["stages"].items():
            per_stage[name] = per_stage.get(name, 0.0) + watts * ms / 1e3
        if a["bubble_ms"] > 0:
            per_stage["bubble"] = per_stage.get("bubble", 0.0) \
                + watts * a["bubble_ms"] / 1e3
        sess = per_session.setdefault(
            str(a.get("display_id", "?")), {"frames": 0, "joules": 0.0})
        sess["frames"] += 1
        sess["joules"] += frame_j
    n = len(accounts)
    for sess in per_session.values():
        sess["joules_per_frame"] = round(sess["joules"] / sess["frames"],
                                         6) if sess["frames"] else None
        sess["joules"] = round(sess["joules"], 6)
    return {
        "frames": n,
        "watts": watts,
        "joules": round(total_j, 6),
        "joules_per_frame": round(total_j / n, 6) if n else None,
        "per_stage_j": {k: round(v, 6) for k, v in
                        sorted(per_stage.items(), key=lambda kv: -kv[1])},
        "per_session": per_session,
    }


# ---------------------------------------------------- ladder energy mode
#: stock per-rung efficiency priors for the default ladder: relative
#: fps/W GAIN of landing the rung (downscale quarters the pixels moved
#: per frame — by far the biggest joules/frame lever; quality cuts
#: bitrate, not device work; fps halves both axes; dropping the
#: pipeline to depth 1 saves in-flight HBM, not much power). Absolute
#: scale is irrelevant — the policy only ranks.
DEFAULT_RUNG_EFFICIENCY: dict = {
    "pipeline": {"fps_per_w": 0.2},
    "fps": {"fps_per_w": 1.0},
    "quality": {"fps_per_w": 0.5},
    "downscale": {"fps_per_w": 3.0},
}


class EnergyBudgetPolicy:
    """The ladder's energy-aware mode (ROADMAP 5): under a configured
    power budget, pick the highest-efficiency warm rung that still
    meets the SLO instead of the nearest rung.

    Duck-typed against ``resilience.ladder.DegradationLadder``'s
    ``energy_policy`` seam:

    - :meth:`over_budget` — True while the watts feed exceeds
      ``budget_w``; the ladder folds this into its trigger reasons, so
      the SAME two-sided hysteresis (down_after_s / hold_s /
      ok_window_s) governs power-driven shifts;
    - :meth:`select_rung` — the target rung index, chosen as the
      highest ``fps_per_w`` entry in ``rung_table`` at or below the
      current level whose ``meets_slo`` predicate holds AND whose
      program is warm (``is_warm`` comes from the ladder's prewarm
      gate). A cheaper-but-SLO-violating rung is skipped by
      construction; None (no warm SLO-meeting candidate) falls back to
      the ladder's stock nearest-rung walk.

    ``rung_table``: {step: {"fps_per_w": float,
    "meets_slo": bool | callable}} — ``meets_slo`` defaults True;
    callables are evaluated per selection so a live SLO predictor can
    plug in.
    """

    def __init__(self, budget_w: float,
                 watts_fn: Callable[[], float],
                 rung_table: Optional[dict] = None):
        self.budget_w = float(budget_w)
        self.watts_fn = watts_fn
        self.rung_table = dict(rung_table if rung_table is not None
                               else DEFAULT_RUNG_EFFICIENCY)
        #: last watts reading (snapshot/debug surface)
        self.last_watts: Optional[float] = None

    def over_budget(self) -> bool:
        try:
            w = self.watts_fn()
        except Exception:
            logger.exception("energy policy watts feed failed")
            return False
        if not isinstance(w, (int, float)) or w != w:    # NaN-safe
            return False
        self.last_watts = float(w)
        return float(w) > self.budget_w

    @staticmethod
    def _slo_ok(info: dict) -> bool:
        v = info.get("meets_slo", True)
        try:
            return bool(v() if callable(v) else v)
        except Exception:
            return False

    def select_rung(self, steps: Sequence[str], level: int,
                    is_warm: Callable[[str], bool]) -> Optional[int]:
        best: Optional[tuple] = None
        for j in range(max(0, int(level)), len(steps)):
            step = steps[j]
            info = self.rung_table.get(step)
            if not isinstance(info, dict):
                continue                 # unpriced rung: not a candidate
            if not self._slo_ok(info):
                continue                 # cheaper but SLO-violating: skip
            try:
                if not is_warm(step):
                    continue             # cold: the worker warms it, the
                                         # stock walk defers — never here
            except Exception:
                continue
            eff = info.get("fps_per_w")
            eff = float(eff) if isinstance(eff, (int, float)) else 0.0
            if best is None or eff > best[0]:
                best = (eff, j)
        return best[1] if best is not None else None

    def snapshot(self) -> dict:
        return {"budget_w": self.budget_w,
                "last_watts": self.last_watts,
                "rungs": sorted(self.rung_table)}


def ladder_policy_from_settings(settings) -> Optional[EnergyBudgetPolicy]:
    """The server-core wiring: a positive ``power_budget_w`` setting
    arms the energy-aware mode against the process-wide meter; 0 (the
    default) leaves the ladder's stock behaviour byte-for-byte
    untouched."""
    try:
        budget = float(getattr(settings, "power_budget_w", 0.0) or 0.0)
    except (TypeError, ValueError):
        return None
    if budget <= 0:
        return None
    return EnergyBudgetPolicy(budget, meter.watts_estimate)
