"""Health-verdict engine + flight recorder.

The r04/r05 failure mode this plane exists for: the TPU relay died, jax
silently initialised on CPU, and two whole bench rounds recorded
plausible-looking fps numbers before a human noticed. A two-field
``{"ok": bool}`` health endpoint cannot express that — it was green the
entire time. The engine replaces it with NAMED checks, each returning
``ok | degraded | failed`` plus a reason string a human (or the bench
driver) can act on, split into liveness (restart me) and readiness
(route traffic to me) scopes for container orchestration.

Design constraints:

- **Dependency-free.** Verdicts must be computable in images without
  jax/aiohttp (the CI lint smoke runs ``python -m selkies_tpu.obs
  selftest`` there). Metrics export is lazy and optional, the same
  pattern :mod:`..trace.core` uses for its stage sink.
- **Checks never raise out.** A crashing check IS a failed verdict —
  the health endpoint answering 500 because a probe threw would be the
  observability plane reproducing the bug it exists to catch.
- **Bounded memory.** The flight recorder is a fixed ring; incident
  floods (relay flap, compile storm) overwrite the oldest entries and
  bump a drop counter instead of growing.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Callable, Optional

__all__ = ["OK", "DEGRADED", "FAILED", "Verdict", "ok", "degraded",
           "failed", "HealthEngine", "FlightRecorder", "engine"]

OK = "ok"
DEGRADED = "degraded"
FAILED = "failed"

#: severity order for aggregation: the overall status is the worst check
_RANK = {OK: 0, DEGRADED: 1, FAILED: 2}


class Verdict:
    """One check's outcome. ``data`` carries structured evidence (the
    numbers the reason string was derived from) for dashboards."""

    __slots__ = ("status", "reason", "data")

    def __init__(self, status: str, reason: str = "",
                 data: Optional[dict] = None):
        if status not in _RANK:
            raise ValueError(f"bad status {status!r}")
        self.status = status
        self.reason = reason
        self.data = data or {}

    def to_dict(self) -> dict:
        out = {"status": self.status, "reason": self.reason}
        if self.data:
            out["data"] = self.data
        return out

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Verdict({self.status!r}, {self.reason!r})"


def ok(reason: str = "", **data) -> Verdict:
    return Verdict(OK, reason, data)


def degraded(reason: str, **data) -> Verdict:
    return Verdict(DEGRADED, reason, data)


def failed(reason: str, **data) -> Verdict:
    return Verdict(FAILED, reason, data)


def worst(statuses) -> str:
    """Aggregate: the most severe status present (ok when empty)."""
    rank = 0
    for s in statuses:
        rank = max(rank, _RANK.get(s, 2))
    return [OK, DEGRADED, FAILED][rank]


def _host_id() -> str:
    """Stable host id stamped onto every incident so multi-host records
    join after the fact (fleet postmortems grep one id across hosts).
    Lazy + cached: compile_cache is stdlib-only, but a broken /proc read
    must never take the recorder down with it."""
    global _HOST_ID
    if _HOST_ID is None:
        try:
            from ..compile_cache import host_id
            _HOST_ID = host_id()
        except Exception:
            _HOST_ID = "unknown"
    return _HOST_ID


_HOST_ID: Optional[str] = None


class FlightRecorder:
    """Bounded ring of structured incidents (relay death, compile storm,
    ACK-stall watchdog trips…), dumped on SIGTERM so a postmortem can
    see WHAT went wrong before the container vanished — the reference
    repo's answer to this is grepping journald."""

    def __init__(self, capacity: int = 256):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=self.capacity)
        self.dropped = 0
        self.total = 0
        # cumulative count-by-kind, surviving ring rollover — the
        # heartbeat incident digest (ISSUE 18) needs monotone counts so
        # the fleet observer can delta-trigger on increases
        self._counts: dict = {}

    def record(self, kind: str, **fields) -> dict:
        entry = {"ts": round(time.time(), 3), "kind": str(kind),
                 "host": _host_id(), **fields}
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append(entry)
            self.total += 1
            self._counts[entry["kind"]] = \
                self._counts.get(entry["kind"], 0) + 1
        _metrics_incident(kind)
        return entry

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def counts(self) -> dict:
        """Cumulative incidents by kind (monotone across ring
        rollover) — the source of the heartbeat incident digest."""
        with self._lock:
            return dict(self._counts)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0
            self.total = 0
            self._counts.clear()

    def dump_text(self) -> str:
        """One JSON line per incident (journald/stderr friendly)."""
        return "\n".join(json.dumps(e) for e in self.snapshot())

    def dump_file(self, dump_dir: str) -> str:
        """Write the post-mortem incident ring to a STABLE path —
        ``<dump_dir>/incidents-<host_id>.json`` — so a fleet harness
        or operator can collect it from a killed process without
        grepping logs. Atomic (tmp + rename on the same filesystem): a
        SIGKILL landing mid-dump leaves either the previous complete
        file or none, never truncated JSON. Returns the final path."""
        import os
        import tempfile
        host = _host_id()
        os.makedirs(dump_dir, exist_ok=True)
        path = os.path.join(dump_dir, f"incidents-{host}.json")
        with self._lock:
            doc = {"host": host, "ts": round(time.time(), 3),
                   "total": self.total, "dropped": self.dropped,
                   "counts": dict(self._counts),
                   "incidents": list(self._ring)}
        fd, tmp = tempfile.mkstemp(prefix=f".incidents-{host}.",
                                   suffix=".tmp", dir=dump_dir)
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path


class _Check:
    __slots__ = ("name", "fn", "liveness", "gate")

    def __init__(self, name: str, fn: Callable[[], Verdict],
                 liveness: bool, gate: bool = False):
        self.name = name
        self.fn = fn
        self.liveness = liveness
        self.gate = gate


class HealthEngine:
    """Named health checks -> verdict set.

    ``liveness=True`` marks a check whose failure means the PROCESS is
    broken and a restart could help (service supervisor dead, event
    loop wedged). Everything else is readiness-scope: a failed relay or
    cpu-fallback backend makes the pod unfit for traffic but restarts
    won't resurrect a dead TPU relay, so the liveness probe must keep
    passing (k8s would otherwise crash-loop the pod against an external
    fault — the exact anti-pattern the probes split exists to avoid).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._checks: dict[str, _Check] = {}
        self.recorder = FlightRecorder()

    # -- registration --------------------------------------------------------
    def register(self, name: str, fn: Callable[[], Verdict],
                 liveness: bool = False, gate: bool = False) -> None:
        """Idempotent: re-registering a name replaces the check (service
        restarts re-register their closures).

        ``gate=True`` marks a *routing gate*: evaluated only by the
        ``?probe=ready`` readiness probe (the load-balancer surface),
        never by the default ``/api/health`` report. The prewarm-complete
        gate is the canonical case — a cold host must answer the LB
        "don't route to me yet" without the operator panel reading the
        whole process as failed while the lattice warms."""
        with self._lock:
            self._checks[name] = _Check(str(name), fn, bool(liveness),
                                        bool(gate))

    def unregister(self, name: str, fn: Optional[Callable] = None) -> None:
        """Remove a check. Pass the registered ``fn`` to make teardown
        owner-safe: register() replaces on name, so a torn-down
        instance's cleanup must not remove a NEWER instance's check."""
        with self._lock:
            c = self._checks.get(name)
            if c is not None and (fn is None or c.fn == fn):
                self._checks.pop(name, None)

    def check_names(self) -> list[str]:
        with self._lock:
            return sorted(self._checks)

    def clear(self) -> None:
        with self._lock:
            self._checks.clear()
        self.recorder.clear()

    # -- evaluation ----------------------------------------------------------
    def run(self, liveness_only: bool = False,
            include_gates: bool = False) -> dict[str, Verdict]:
        """Evaluate every check (or only the liveness-scope ones). A
        check that raises becomes a failed verdict carrying the
        exception — never propagates. Liveness probes must evaluate
        ONLY liveness checks: running readiness closures on the
        liveness path would let a wedged readiness check time the probe
        out and crash-loop the pod over an external fault. Gate-scope
        checks (prewarm-complete) join only when ``include_gates`` —
        the readiness-probe path."""
        with self._lock:
            checks = [c for c in self._checks.values()
                      if (c.liveness or not liveness_only)
                      and (include_gates or not c.gate)]
        out: dict[str, Verdict] = {}
        for c in checks:
            try:
                v = c.fn()
                if not isinstance(v, Verdict):
                    v = failed(f"check returned {type(v).__name__}, "
                               "not a Verdict")
            except Exception as e:
                v = failed(f"check crashed: {type(e).__name__}: {e}")
            out[c.name] = v
            _metrics_status(c.name, v.status)
        return out

    def _liveness_names(self) -> set[str]:
        with self._lock:
            return {n for n, c in self._checks.items() if c.liveness}

    def gate_names(self) -> set[str]:
        """Names of the routing-gate checks — lets a caller evaluate
        everything ONCE (``run(include_gates=True)``) and still derive
        both the process-health status (gates excluded) and the
        readiness answer (gates included) from one verdict map."""
        with self._lock:
            return {n for n, c in self._checks.items() if c.gate}

    def liveness(self) -> dict:
        """The livenessProbe answer: liveness-scope checks only."""
        verdicts = self.run(liveness_only=True)
        live = worst(v.status for v in verdicts.values()) != FAILED
        return {"ok": live, "live": live,
                "failing": sorted(n for n, v in verdicts.items()
                                  if v.status == FAILED)}

    def readiness(self) -> dict:
        """The readinessProbe / load-balancer answer: every readiness
        check PLUS the routing gates. A cold host (prewarm gate failed)
        answers not-ready here while the default report stays honest
        about the rest of the process — route-ability and process
        health are different questions."""
        verdicts = self.run(include_gates=True)
        ready = worst(v.status for v in verdicts.values()) != FAILED
        return {"ok": ready, "ready": ready,
                "status": worst(v.status for v in verdicts.values()),
                "failing": sorted(n for n, v in verdicts.items()
                                  if v.status == FAILED)}

    def report(self, verbose: bool = False) -> dict:
        """The /api/health payload. Always carries ``ok`` (readiness
        bool, backward compatible), ``status`` (worst verdict), ``live``
        and ``ready``; ``verbose`` adds the per-check verdicts and the
        flight-recorder tail."""
        verdicts = self.run()
        live_names = self._liveness_names()
        status = worst(v.status for v in verdicts.values())
        live = worst(verdicts[n].status
                     for n in verdicts if n in live_names) != FAILED
        ready = status != FAILED
        doc: dict = {
            "ok": ready,
            "status": status,
            "live": live,
            "ready": ready,
            "failing": sorted(n for n, v in verdicts.items()
                              if v.status == FAILED),
        }
        if verbose:
            doc["checks"] = {n: v.to_dict()
                             for n, v in sorted(verdicts.items())}
            doc["incidents"] = self.recorder.snapshot()
            doc["incidents_dropped"] = self.recorder.dropped
            doc["incidents_total"] = self.recorder.total
        return doc


# -- optional metrics bridge (lazy; lint image has no server deps) ----------

def _metrics_status(name: str, status: str) -> None:
    try:
        from ..server import metrics
    except Exception:
        return
    metrics.describe("selkies_health_status",
                     "Health check status (0=ok 1=degraded 2=failed)")
    metrics.set_gauge("selkies_health_status", _RANK[status],
                      {"check": name})


def _metrics_incident(kind: str) -> None:
    try:
        from ..server import metrics
    except Exception:
        return
    metrics.describe("selkies_incidents_total",
                     "Flight-recorder incidents by kind")
    metrics.inc_counter("selkies_incidents_total", labels={"kind": kind})


#: the process-wide engine every plane registers against (same singleton
#: pattern as :data:`..trace.core.tracer`); tests build their own.
engine = HealthEngine()
