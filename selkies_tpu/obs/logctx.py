"""Log correlation: session/seat context on ``selkies_tpu.*`` records.

A multi-seat fan-out interleaves every session's log lines; without a
correlation id, "client 7 backpressured" and the relay death two lines
later cannot be tied to the same seat. This module carries the active
session through a :mod:`contextvars` variable (set by the transport at
accept, inherited by everything awaited under that connection's
handler) and injects it into log records via a logging filter, so both
the plain formatter and the ``--log_format=json`` structured output can
carry it without any call-site changes.

Stdlib-only, import-safe everywhere (same contract as the rest of
:mod:`selkies_tpu.obs`).
"""

from __future__ import annotations

import contextvars
import json
import logging
import time
from typing import Optional

__all__ = ["bind", "clear", "current", "SessionContextFilter",
           "JsonFormatter", "install"]

#: (session_id, seat) of the connection being handled, or None
_session_ctx: contextvars.ContextVar[Optional[tuple]] = \
    contextvars.ContextVar("selkies_log_session", default=None)

#: the stable host id (compile_cache.host_id) stamped on every record
#: so interleaved multi-host log streams join on one key — the SAME
#: exception-safe cached wrapper the flight recorder stamps incidents
#: with (one definition; obs.health is dependency-free)
from .health import _host_id  # noqa: E402


def bind(sid, seat) -> contextvars.Token:
    """Attach the current task/thread's log records to a session."""
    return _session_ctx.set((sid, str(seat)))


def clear(token: Optional[contextvars.Token] = None) -> None:
    if token is not None:
        _session_ctx.reset(token)
    else:
        _session_ctx.set(None)


def current() -> Optional[tuple]:
    return _session_ctx.get()


class SessionContextFilter(logging.Filter):
    """Injects ``record.session`` / ``record.seat`` (empty strings when
    no session is bound) plus ``record.session_tag`` — a pre-formatted
    `` [seat#sid]`` suffix the plain format string can use directly.
    Attached to HANDLERS (filters on a logger do not propagate to
    children), so every ``selkies_tpu.*`` record passing through gets
    stamped; it never rejects a record."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.host_id = _host_id()
        ctx = _session_ctx.get()
        if ctx is not None:
            record.session = str(ctx[0])
            record.seat = ctx[1]
            record.session_tag = f" [{ctx[1]}#{ctx[0]}]"
        else:
            record.session = ""
            record.seat = ""
            record.session_tag = ""
        return True


class JsonFormatter(logging.Formatter):
    """One JSON object per line: ``ts``, ``level``, ``logger``, ``msg``,
    plus ``session``/``seat`` when bound and ``exc`` for tracebacks —
    the ``--log_format=json`` structured option."""

    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": round(time.time(), 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
            "host": getattr(record, "host_id", "") or _host_id(),
        }
        session = getattr(record, "session", "")
        if session:
            doc["session"] = session
            doc["seat"] = getattr(record, "seat", "")
        if record.exc_info:
            doc["exc"] = self.formatException(record.exc_info)
        return json.dumps(doc, default=str)


def install(json_format: bool = False,
            logger: Optional[logging.Logger] = None) -> None:
    """Attach the correlation filter (and optionally the JSON
    formatter) to the given logger's handlers — call after
    ``logging.basicConfig`` so the root handler exists."""
    root = logger if logger is not None else logging.getLogger()
    filt = SessionContextFilter()
    for h in root.handlers:
        if not any(isinstance(f, SessionContextFilter) for f in h.filters):
            h.addFilter(filt)
        if json_format:
            h.setFormatter(JsonFormatter())
