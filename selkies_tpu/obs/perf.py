"""Performance observability: static cost attribution per compiled step
+ device-time attribution from jax.profiler captures.

Why this exists (ISSUE 6): every perf lever since round 3 was ranked
blind — the one real-TPU stage attribution (PERF.md, r3) was hand-run,
died mid-profile, and predates the plane-layout rewrite, and with the
TPU relay down there was NO instrument that could rank levers at all.
This module supplies two instruments that work in that state:

- **Static cost attribution** (:class:`PerfRegistry` + :func:`wrap_step`):
  every engine step compiled through :func:`wrap_step` is lowered and
  compiled ahead-of-time (the SAME single XLA build jit would do — the
  wrapper executes the AOT ``Compiled`` object, it never double-builds),
  and ``Lowered.cost_analysis()`` / ``Compiled.memory_analysis()`` are
  recorded at compile time: flops, HBM bytes accessed, argument/output/
  temp bytes, and a derived **roofline-ms** floor at :data:`HBM_GBPS`
  (~800 GB/s, the v5e-class HBM figure PERF.md's layout analysis used).
  Static numbers rank levers like the hierarchical bit-merge packer
  *with the relay down*: bytes-moved deltas don't need a live chip.

- **Device-time attribution** (:func:`parse_profile_dir`): parse the
  ``*.trace.json.gz`` files a PR-3 ``jax.profiler`` capture writes into
  a per-step device-time table (module-level ``jit_<step>`` events on
  the device lanes, plus a top-ops table), so ONE ``bench.py --profile``
  run on the real chip auto-produces the stage attribution ROADMAP
  item 1 needs — no hand-driven cumulative-prefix session required.

Import contract: stdlib-only at import time (the lint CI image has no
jax); every jax touch point is lazy and guarded, and a wrapped step that
cannot be analysed falls back to the plain jitted callable — analysis
must never be able to break encode.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Optional

logger = logging.getLogger("selkies_tpu.obs.perf")

#: roofline bandwidth denominator: v5e-class HBM, the figure the PERF.md
#: layout analysis reasoned with ("hundreds of MB/frame at ~800 GB/s")
HBM_GBPS = 800.0


def roofline_ms(bytes_accessed: float, gbps: float = HBM_GBPS) -> float:
    """Memory-roofline floor for one step execution: the time the HBM
    traffic alone costs at ``gbps``. A measured step time far above its
    roofline-ms means the step is compute- or latency-bound (or the
    layout pads, the r3 failure mode); at ~1x it is bandwidth-bound and
    only moving fewer bytes can help."""
    if bytes_accessed <= 0 or gbps <= 0:
        return 0.0
    return bytes_accessed / (gbps * 1e9) * 1e3


def _norm_cost(cost: Any) -> dict:
    """Normalise a jax cost_analysis result: 0.4.x ``Compiled`` returns a
    one-element list of dicts, ``Lowered`` a plain dict."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return cost if isinstance(cost, dict) else {}


def _norm_memory(mem: Any) -> dict:
    """CompiledMemoryStats -> plain ints (also accepts a dict for
    synthetic selftest input)."""
    if mem is None:
        return {}
    if isinstance(mem, dict):
        src = mem.get
    else:
        src = lambda k, d=0: getattr(mem, k, d)   # noqa: E731
    out = {}
    for k in ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes"):
        try:
            out[k] = int(src(k, 0) or 0)
        except (TypeError, ValueError):
            out[k] = 0
    return out


class PerfRegistry:
    """Process-wide table of per-step static cost analyses. One instance
    (:data:`registry`) serves the engine compile sites, ``/api/perf``
    and bench; tests build their own and feed synthetic analyses.

    Bounded: runtime geometry retargeting (the degradation ladder's
    downscale rung, client resizes, overflow buffer growth) mints a
    fresh step name per visit, so a long-lived flapping session would
    otherwise grow this table without limit. Past ``max_steps`` the
    oldest-recorded entries are evicted (the live operating points are
    always the newest)."""

    #: analysis entries kept; oldest-recorded evicted beyond this
    max_steps = 64

    def __init__(self, max_steps: Optional[int] = None):
        self._lock = threading.Lock()
        self._steps: dict[str, dict] = {}
        self._fallbacks: dict[str, dict] = {}
        if max_steps is not None:
            self.max_steps = int(max_steps)

    def note_fallback(self, name: str, reason: str,
                      signature: Optional[str] = None) -> dict:
        """A wrapped step permanently fell back to plain jit dispatch
        for one signature — the PR-15 round-3 poisoning class.  Beyond
        the log line, surface it where operators look: a
        ``wrapped_step_fallback`` flight-recorder incident (visible in
        ``/api/health``) and the ``selkies_perf_step_fallbacks_total``
        counter.  Lazy + guarded: observability of the fallback must
        never be able to break the fallback."""
        with self._lock:
            e = self._fallbacks.setdefault(
                name, {"step": name, "count": 0})
            e["count"] += 1
            e["reason"] = reason
            e["signature"] = signature
            e["last_at"] = time.time()
            while len(self._fallbacks) > self.max_steps:
                oldest = min(self._fallbacks,
                             key=lambda k: self._fallbacks[k]["last_at"])
                if oldest == name:
                    break
                del self._fallbacks[oldest]
        try:
            from ..server import metrics
            metrics.describe(
                "selkies_perf_step_fallbacks_total",
                "Wrapped-step permanent fallbacks to plain jit "
                "dispatch (per occurrence)")
            metrics.inc_counter("selkies_perf_step_fallbacks_total")
        except Exception:
            pass
        try:
            from .health import engine as _engine
            _engine.recorder.record(
                "wrapped_step_fallback", step=name, reason=reason,
                signature=signature)
        except Exception:
            logger.debug("fallback incident record failed",
                         exc_info=True)
        return e

    def record_analysis(self, name: str, cost: Any = None,
                        memory: Any = None, *,
                        backend: Optional[str] = None,
                        compile_s: Optional[float] = None,
                        signature: Optional[str] = None,
                        error: Optional[str] = None) -> dict:
        """Record (or overwrite — recompiles after buffer growth replace
        the stale entry) one compiled step's static analysis."""
        cost = _norm_cost(cost)
        mem = _norm_memory(memory)
        flops = float(cost.get("flops", 0.0) or 0.0)
        bytes_accessed = float(cost.get("bytes accessed", 0.0) or 0.0)
        peak_bytes = (mem.get("argument_size_in_bytes", 0)
                      + mem.get("output_size_in_bytes", 0)
                      + mem.get("temp_size_in_bytes", 0)
                      + mem.get("alias_size_in_bytes", 0))
        # the energy twin of roofline_ms (ISSUE 14): one execution's
        # dynamic joules at the backend's pJ/flop + pJ/HBM-byte
        # coefficients — the per-step lever-ranking number the energy
        # plane's frame estimate builds on. Lazy + guarded: analysis
        # must never be able to break encode
        energy_j = None
        try:
            from .energy import step_energy_j
            energy_j = round(step_energy_j(flops, bytes_accessed,
                                           backend), 6)
        except Exception:
            pass
        entry = {
            "name": name,
            "backend": backend,
            "flops": flops,
            "bytes_accessed": bytes_accessed,
            "roofline_ms": round(roofline_ms(bytes_accessed), 4),
            "energy_j": energy_j,
            "arg_bytes": mem.get("argument_size_in_bytes", 0),
            "out_bytes": mem.get("output_size_in_bytes", 0),
            "temp_bytes": mem.get("temp_size_in_bytes", 0),
            "peak_bytes": peak_bytes,
            "generated_code_bytes": mem.get(
                "generated_code_size_in_bytes", 0),
            "compile_s": round(compile_s, 3)
            if compile_s is not None else None,
            "signature": signature,
            "error": error,
            "recorded_at": time.time(),
        }
        with self._lock:
            self._steps[name] = entry
            while len(self._steps) > self.max_steps:
                oldest = min(self._steps,
                             key=lambda k: self._steps[k]["recorded_at"])
                if oldest == name:
                    break
                del self._steps[oldest]
        return entry

    def clear(self) -> None:
        with self._lock:
            self._steps.clear()
            self._fallbacks.clear()

    def report(self) -> dict:
        """``/api/perf`` / bench ``perf`` block payload: every recorded
        step, bandwidth-heaviest first, plus the roofline assumptions so
        a reader can re-derive the numbers — and any permanent
        jit-dispatch fallbacks (a step listed there is running without
        its AOT executable: investigate before trusting its numbers)."""
        with self._lock:
            steps = sorted(self._steps.values(),
                           key=lambda e: -e["bytes_accessed"])
            fallbacks = sorted(self._fallbacks.values(),
                               key=lambda e: -e["count"])
        return {
            "hbm_gbps": HBM_GBPS,
            "steps": steps,
            "count": len(steps),
            "fallbacks": fallbacks,
        }


#: the process-wide registry every wrap_step call records into
registry = PerfRegistry()


def _aval_signature(args: tuple) -> tuple:
    """Hashable per-call signature: (shape, dtype, weak) per array leaf,
    a type tag otherwise. Distinct signatures get distinct compiles —
    exactly jit's cache key semantics for the arguments we pass."""
    sig = []
    for a in args:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype),
                        bool(getattr(a, "weak_type", False))))
        else:
            sig.append(("py", type(a).__name__))
    return tuple(sig)


class _WrappedStep:
    """AOT-instrumented jitted step. First call per argument signature
    lowers + compiles (ONE XLA build, same persistent-cache key jit
    would use) and records the static cost analysis; subsequent calls
    execute the AOT ``Compiled`` directly. Any failure — lowering,
    compile, analysis, or an executable call — permanently falls back
    to the plain jitted callable for that signature.

    The per-signature cache is a small LRU (``_CACHE_CAP``): signatures
    are minted by shape/dtype, and a pathological caller cycling
    argument shapes must not pin an unbounded set of compiled
    executables in memory. Eviction only costs a re-prepare (persistent
    compile cache absorbs the rebuild).

    :meth:`warm` is the pre-warm hook (selkies_tpu/prewarm): AOT
    lower+compile for an aval signature WITHOUT executing, so the first
    real frame on that signature dispatches a ready executable."""

    __slots__ = ("name", "_jitted", "_registry", "_cache", "_lock")

    #: sentinel: this signature must use the plain jitted path
    _FALLBACK = object()
    #: compiled signatures kept per step (LRU beyond this)
    _CACHE_CAP = 8

    def __init__(self, name: str, jitted: Callable,
                 registry_: Optional[PerfRegistry] = None):
        self.name = name
        self._jitted = jitted
        self._registry = registry_ or registry
        self._cache: "collections.OrderedDict[tuple, Any]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()

    def _cache_get(self, key: tuple):
        with self._lock:
            entry = self._cache.get(key)
            if entry is not None:
                self._cache.move_to_end(key)
            return entry

    def _cache_put(self, key: tuple, entry) -> None:
        """Caller holds no lock; bounded LRU insert."""
        with self._lock:
            self._cache_set_locked(key, entry)

    def warm(self, args: tuple) -> bool:
        """Pre-compile for this argument signature (``args`` may be
        ``jax.ShapeDtypeStruct`` avals — nothing executes). True when
        the signature ends up warm (freshly compiled or already
        cached); False when it fell back to plain jit dispatch."""
        try:
            key = _aval_signature(args)
        except Exception:
            return False
        entry = self._cache_get(key)
        if entry is None:
            entry = self._prepare(key, args)
        return entry is not self._FALLBACK

    def __call__(self, *args):
        try:
            key = _aval_signature(args)
            entry = self._cache_get(key)
        except Exception:
            return self._jitted(*args)
        if entry is None:
            entry = self._prepare(key, args)
        if entry is self._FALLBACK:
            return self._jitted(*args)
        try:
            return entry(*args)
        except Exception:
            # e.g. a sharding/layout mismatch the jit dispatch would have
            # absorbed with a transfer: stop trying for this signature
            logger.exception("perf-instrumented step %s failed; "
                             "falling back to jit dispatch", self.name)
            self._cache_put(key, self._FALLBACK)
            self._registry.note_fallback(self.name, "execute_failed",
                                         _sig_str(key))
            for a in args:
                deleted = getattr(a, "is_deleted", None)
                if callable(deleted) and deleted():
                    # the executable died mid-run AFTER consuming donated
                    # inputs (reference planes, age counters): a retry
                    # would mask the real device error with "Array has
                    # been deleted" against already-lost session state
                    raise
            return self._jitted(*args)

    def _cache_set_locked(self, key: tuple, entry) -> None:
        self._cache[key] = entry
        self._cache.move_to_end(key)
        while len(self._cache) > self._CACHE_CAP:
            self._cache.popitem(last=False)

    def _prepare(self, key: tuple, args: tuple):
        """Lower + compile + analyse under the lock (first frame only —
        the same compile barrier jit dispatch would impose)."""
        with self._lock:
            entry = self._cache.get(key)
            if entry is not None:
                return entry
            if os.environ.get("SELKIES_PERF_ANALYSIS") == "0":
                self._cache_set_locked(key, self._FALLBACK)
                return self._FALLBACK
            t0 = time.monotonic()
            # fault point encoder.compile:slow — THE compile site every
            # engine step builds through, so an injected 20 s "compile"
            # lands exactly where a real XLA build would stall. Sleeping
            # mode only; lazy import keeps this module stdlib-importable
            try:
                from ..resilience import faults as _faults
            except Exception:
                _faults = None
            try:
                lowered = self._jitted.lower(*args)
                if _faults is not None:
                    _faults.registry.perturb("encoder.compile")
                cost = None
                try:
                    cost = lowered.cost_analysis()
                except Exception:
                    pass
                compiled = lowered.compile()
                compile_s = time.monotonic() - t0
                try:
                    # post-optimisation traffic when available: what the
                    # executable actually moves, not what the jaxpr says
                    cost = compiled.cost_analysis() or cost
                except Exception:
                    pass
                mem = None
                try:
                    mem = compiled.memory_analysis()
                except Exception:
                    pass
                backend = None
                try:
                    import jax
                    backend = jax.default_backend()
                except Exception:
                    pass
                self._registry.record_analysis(
                    self.name, cost, mem, backend=backend,
                    compile_s=compile_s, signature=_sig_str(key))
                self._cache_set_locked(key, compiled)
                return compiled
            except Exception as e:
                logger.warning("perf analysis of step %s unavailable "
                               "(%s: %s); using jit dispatch",
                               self.name, type(e).__name__, e)
                self._registry.record_analysis(
                    self.name, signature=_sig_str(key),
                    error=f"{type(e).__name__}: {e}"[:200])
                self._cache_set_locked(key, self._FALLBACK)
                self._registry.note_fallback(
                    self.name, "compile_failed", _sig_str(key))
                return self._FALLBACK


def _sig_str(key: tuple) -> str:
    parts = []
    for leaf in key:
        if leaf and leaf[0] == "py":
            parts.append(leaf[1])
        else:
            shape, dtype = leaf[0], leaf[1]
            parts.append(f"{dtype}[{','.join(map(str, shape))}]")
    return f"({', '.join(parts)})"


def wrap_step(name: str, jitted: Callable) -> Callable:
    """Instrument a ``jax.jit`` product for static cost attribution.
    Returns a callable with the jitted function's calling convention
    (donation included — the AOT path preserves ``donate_argnums``)."""
    return _WrappedStep(name, jitted)


# --------------------------------------------------------------- profiles
def _load_trace_events(path: str) -> list[dict]:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        doc = json.loads(f.read().decode("utf-8", "replace"))
    events = doc.get("traceEvents", doc) if isinstance(doc, dict) else doc
    return [e for e in events if isinstance(e, dict)] \
        if isinstance(events, list) else []


def parse_profile_dir(trace_dir: str,
                      step_names: Optional[list[str]] = None) -> dict:
    """Per-step device-time table from a ``jax.profiler`` capture.

    Finds every ``*.trace.json[.gz]`` under ``trace_dir`` (the
    TensorBoard layout: ``plugins/profile/<run>/<host>.trace.json.gz``),
    keeps complete-event (``X``) durations on **device** processes
    (process_name containing ``/device:``; host processes only when no
    device lane exists — the CPU-backend case), and attributes them:

    - ``steps``: total/count/mean ms per registered step name (from
      :data:`registry` unless ``step_names`` is given), matched by
      substring against event names — XLA module-level events are named
      ``jit_<step_fn_name>``, which is why the engine names its step
      functions (``h264_i_step`` etc.);
    - ``top_ops``: the heaviest individual event names, the
      "which fusion actually eats the frame" view.
    """
    files = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                  recursive=True)
        + glob.glob(os.path.join(trace_dir, "**", "*.trace.json"),
                    recursive=True))
    if step_names is None:
        step_names = [e["name"] for e in registry.report()["steps"]]
    # profiler-friendly aliases: "h264.i_step[...]" matches events via
    # its function-name stem ("h264_i_step"). Two registry entries can
    # share a stem — the same program compiled at two geometries (e.g.
    # after a ladder downscale rebuilt the session); XLA names both
    # modules identically, so the capture cannot tell them apart
    by_stem: dict[str, list[str]] = {}
    for name in step_names:
        stem = name.split("[", 1)[0].replace(".", "_")
        by_stem.setdefault(stem, []).append(name)
    out: dict = {"trace_dir": trace_dir, "trace_files": len(files),
                 "device": False, "total_ms": 0.0, "n_events": 0,
                 "steps": {}, "top_ops": []}
    if not files:
        return out
    # per-file streaming: a real TPU capture decompresses to hundreds of
    # MB of events — aggregate each file into small {name: [count, ms]}
    # dicts and drop its event list before the next file. Each trace
    # file carries its own process metadata, so device-lane filtering is
    # decidable per file; the device/host FALLBACK (a CPU capture has no
    # device lane at all) is resolved once every file has been seen.
    by_name_device: dict[str, list] = {}
    by_name_all: dict[str, list] = {}
    n_device = n_all = 0
    for path in files:
        try:
            evs = _load_trace_events(path)
        except (OSError, ValueError):
            continue
        device_pids = {
            e.get("pid") for e in evs
            if e.get("ph") == "M" and e.get("name") == "process_name"
            and "/device:" in str((e.get("args") or {}).get("name", ""))}
        for e in evs:
            if e.get("ph") != "X" or "dur" not in e:
                continue
            name = str(e.get("name", "?"))
            ms = float(e["dur"]) / 1e3       # µs -> ms
            if e.get("pid") in device_pids:
                acc = by_name_device.setdefault(name, [0, 0.0])
                n_device += 1
            elif n_device == 0:
                # host events matter only for the no-device-lane-at-all
                # fallback (CPU captures); once any device event exists,
                # stop growing — XLA op names are high-cardinality and a
                # real capture would balloon this dict for nothing
                acc = by_name_all.setdefault(name, [0, 0.0])
                n_all += 1
            else:
                continue
            acc[0] += 1
            acc[1] += ms
        if n_device and by_name_all:
            by_name_all.clear()
    out["device"] = n_device > 0
    by_name = by_name_device if out["device"] else by_name_all
    out["n_events"] = n_device if out["device"] else n_all
    # each event name is claimed by at most ONE stem (most-specific
    # first). A stem shared by several registry entries gets one MERGED
    # row listing its claimants — crediting all the time to whichever
    # geometry sorts first would be a silently-wrong attribution
    steps: dict[str, dict] = {}
    claimed: set[str] = set()
    for stem, names in sorted(by_stem.items(),
                              key=lambda kv: (-len(kv[0]), kv[0])):
        total = count = 0
        for ev_name, (c, ms) in by_name.items():
            if stem and stem in ev_name and ev_name not in claimed:
                total, count = total + ms, count + c
                claimed.add(ev_name)
        if count:
            row = {"count": count, "total_ms": round(total, 3),
                   "mean_ms": round(total / count, 3)}
            if len(names) > 1:
                row["ambiguous"] = sorted(names)
                steps[names[0].split("[", 1)[0] + "[*]"] = row
            else:
                steps[names[0]] = row
    out["steps"] = dict(sorted(steps.items(),
                               key=lambda kv: -kv[1]["total_ms"]))
    out["total_ms"] = round(sum(ms for _, ms in by_name.values()), 3)
    out["top_ops"] = [
        {"name": n, "count": c, "total_ms": round(ms, 3)}
        for n, (c, ms) in sorted(by_name.items(),
                                 key=lambda kv: -kv[1][1])[:12]]
    return out
