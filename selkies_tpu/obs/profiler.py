"""On-demand ``jax.profiler`` capture.

Wraps ``jax.profiler.start_trace`` / ``stop_trace`` behind a small
state machine so the HTTP plane (``POST /api/profile``) and
``bench.py --profile`` share one implementation:

- exactly one capture at a time (XLA's profiler is a process singleton;
  a second start corrupts the first capture's session);
- start/stop both return structured status dicts instead of raising —
  the API endpoint maps them straight to JSON;
- the capture directory defaults to a fresh ``selkies-profile-*``
  tempdir so an operator can hit the endpoint with an empty body.

Both entry points do real file I/O inside jax (``stop_trace`` serialises
the whole capture): callers on an event loop must run them in an
executor — the HTTP handler in ``server/core.py`` does.
"""

from __future__ import annotations

import logging
import tempfile
import threading
import time
from typing import Optional

logger = logging.getLogger("selkies_tpu.obs.profiler")


class ProfilerSession:
    """Process-wide jax.profiler capture guard."""

    def __init__(self):
        self._lock = threading.Lock()
        self.trace_dir: Optional[str] = None
        self.started_at: Optional[float] = None
        self.captures = 0
        #: dir of the last COMPLETED capture — what GET /api/perf
        #: parses for device-time attribution without a path parameter
        self.last_trace_dir: Optional[str] = None
        #: a jax start/stop call is in flight (outside the lock); a new
        #: start must not race a still-serialising stop
        self._busy = False

    @property
    def active(self) -> bool:
        return self.trace_dir is not None

    def start(self, trace_dir: Optional[str] = None) -> dict:
        """The lock guards only the state transition, never the jax
        call: ``stop_trace`` serialises the whole capture to disk and a
        concurrent ``status()`` (served inline on the event loop) must
        not block behind it."""
        with self._lock:
            if self._busy:
                return {"ok": False, "active": self.trace_dir is not None,
                        "error": "capture transition in progress"}
            if self.trace_dir is not None:
                return {"ok": False, "active": True,
                        "error": "capture already running",
                        "trace_dir": self.trace_dir}
            target = trace_dir or tempfile.mkdtemp(prefix="selkies-profile-")
            self.trace_dir = target          # claim before the jax call
            self.started_at = time.monotonic()
            self._busy = True
        try:
            import jax
            jax.profiler.start_trace(target)
        except Exception as e:
            with self._lock:
                self.trace_dir = None
                self.started_at = None
                self._busy = False
            logger.warning("profiler start failed: %s", e)
            return {"ok": False, "active": False,
                    "error": f"{type(e).__name__}: {e}"}
        with self._lock:
            self._busy = False
        logger.info("jax profiler capture started -> %s", target)
        return {"ok": True, "active": True, "trace_dir": target}

    def stop(self) -> dict:
        with self._lock:
            if self._busy:
                return {"ok": False, "active": self.trace_dir is not None,
                        "error": "capture transition in progress"}
            if self.trace_dir is None:
                return {"ok": False, "active": False,
                        "error": "no capture running"}
            target, t0 = self.trace_dir, self.started_at
            self.trace_dir = None
            self.started_at = None
            self._busy = True
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:
            logger.warning("profiler stop failed: %s", e)
            return {"ok": False, "active": False, "trace_dir": target,
                    "error": f"{type(e).__name__}: {e}"}
        finally:
            with self._lock:
                self._busy = False
        with self._lock:
            self.captures += 1
            self.last_trace_dir = target
        dur = round(time.monotonic() - t0, 3) if t0 else None
        logger.info("jax profiler capture stopped (%.1fs) -> %s",
                    dur or 0.0, target)
        return {"ok": True, "active": False, "trace_dir": target,
                "duration_s": dur}

    def status(self) -> dict:
        with self._lock:
            return {"active": self.trace_dir is not None,
                    "trace_dir": self.trace_dir,
                    "last_trace_dir": self.last_trace_dir,
                    "captures": self.captures}


#: process-wide session (jax.profiler itself is a process singleton)
profiler = ProfilerSession()
