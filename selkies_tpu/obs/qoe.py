"""Per-session QoE stats registry — WebRTC ``getStats()`` in spirit.

PR 2 attributed server-side latency and PR 3 surfaced device health,
but the wire stayed dark: ACK RTT, client fps, backpressure windows,
relay drops and the congestion controller's state were all computed and
thrown away. For a multi-seat fan-out, per-session QoE is the signal
that says WHICH seat is suffering and WHY. This module is that plane:

- :class:`AckRttEstimator` — frame-id send-timestamp ring matched
  against ``CLIENT_FRAME_ACK``; EWMA plus a windowed p50/p99. The ACK
  protocol acknowledges the latest *displayed* frame, so an ACK also
  retires every older outstanding entry (relay-dropped frames are never
  ACKed and must not read as a stall).
- :class:`SessionStats` — one per WS client / WebRTC peer: the ACK
  estimator, client fps, backpressure-window accounting, and pull-based
  providers for relay counters (``sent_bytes``/``dropped_frames``/queue
  depth) and congestion-controller internals
  (:meth:`~..webrtc.cc.SendSideCongestionController.stats`).
- :class:`QoERegistry` — the process-wide session set behind
  ``GET /api/sessions``, the bounded-cardinality Prometheus export, the
  ``qoe`` health check (``qoe_collapse`` incidents into the PR-3 flight
  recorder) and the ``qoe`` trace lane (backpressure windows overlaid
  on ``/api/trace``).

Glass-to-glass (ISSUE 7): each session carries a
:class:`~.clocksync.ClockSyncEstimator` fed by the ``CLIENT_CLOCK``
exchange, so ``CLIENT_FRAME_TIMING`` reports (client receive / decode /
present timestamps) map onto the server ``perf_counter`` timebase —
:meth:`SessionStats.note_frame_timing` turns them into per-session
``g2g`` percentiles, the ``selkies_session_g2g_ms`` histogram (0.5 ms–
5 s ladder), and the mapped span boundaries the transport joins onto
``/api/trace`` as a ``client`` lane. ``CLIENT_STATS`` (decoder queue
depth, dropped decodes) lands in the verbose session snapshot as the
client-side overload signal.

**QoE score** (documented contract, also used by ``bench.py``)::

    score     = 100 × fps_term × rtt_term × (1 − drop_rate)
    fps_term  = clamp(client_fps / target_fps, 0, 1)   (1 when unknown)
    rtt_term  = 1 / (1 + rtt_ms / 250)
    rtt_ms    = max(EWMA ack RTT, oldest-unACKed frame age)  [ws]
                TWCC smoothed RTT                            [webrtc]
    drop_rate = relay dropped / offered                      [ws]
                TWCC loss fraction                           [webrtc]

100 is a perfect session; ``degraded`` below
:data:`DEGRADED_SCORE` (50), ``failed`` below :data:`FAILED_SCORE`
(15). A 4 s ACK stall alone scores ~6 — failed, as it should.

Dependency-free (stdlib only): the CI lint smoke runs
``python -m selkies_tpu.obs selftest`` in an image with neither jax nor
aiohttp; metrics touch points are lazy and guarded, the same contract
:mod:`.health` keeps. Clocks are injected (``now``) everywhere tests
need determinism.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Optional

from . import health as _health
from .clocksync import ClockSyncEstimator

__all__ = ["AckRttEstimator", "SessionStats", "QoERegistry", "qoe_score",
           "registry", "DEGRADED_SCORE", "FAILED_SCORE"]

#: score thresholds for the ``qoe`` health check (registry-configurable
#: via the ``qoe_degraded_score`` / ``qoe_failed_score`` settings)
DEGRADED_SCORE = 50.0
FAILED_SCORE = 15.0

#: rtt_term halves every this many ms of round-trip
_RTT_HALF_MS = 250.0

#: gauge encoding of engine/content.CONTENT_CLASSES (kept literal here:
#: the obs package is stdlib-only by contract and the engine package
#: imports jax; drift is pinned by tests/test_content.py)
_CONTENT_CLASSES = ("static", "scroll", "video", "gaming")

#: per-session Prometheus series cap (``qoe_seat_label_cap`` setting);
#: sessions beyond it roll up into the ``seat="_overflow"`` aggregate
DEFAULT_SEAT_LABEL_CAP = 8


def _percentiles(samples) -> dict:
    """Nearest-rank p50/p99 over a sample window (ACK-RTT and g2g share
    this so the two exports can never diverge)."""
    vals = sorted(samples)
    if not vals:
        return {"n": 0, "p50_ms": None, "p99_ms": None}

    def _pct(q: float) -> float:
        return round(vals[min(len(vals) - 1, int(len(vals) * q))], 3)

    return {"n": len(vals), "p50_ms": _pct(0.50), "p99_ms": _pct(0.99)}


def qoe_score(client_fps: Optional[float], target_fps: float,
              rtt_ms: float, drop_rate: float) -> float:
    """The composite score — see the module docstring for the formula.
    ``client_fps=None`` means unknown (scored as on-target rather than
    punishing a session that simply never reported)."""
    if client_fps is None or target_fps <= 0:
        fps_term = 1.0
    else:
        fps_term = min(1.0, max(0.0, client_fps / target_fps))
    rtt_term = 1.0 / (1.0 + max(0.0, rtt_ms) / _RTT_HALF_MS)
    drop_term = 1.0 - min(1.0, max(0.0, drop_rate))
    return round(100.0 * fps_term * rtt_term * drop_term, 1)


class AckRttEstimator:
    """ACK round-trip estimator over the uint16 circular frame-id space.

    ``note_sent`` is on the fan-out hot path: one bounded dict insert,
    no clock read of its own (the caller passes ``now`` once per
    fan-out). ``note_ack`` retires the matched entry AND everything
    sent before it — the client ACKs the latest displayed frame, so
    older outstanding ids are either delivered-unACKed or
    relay-dropped, and neither may masquerade as a stall."""

    def __init__(self, ring: int = 512, window: int = 128,
                 alpha: float = 0.125):
        #: frame_id -> send time (monotonic s), insertion == send order
        self._sent: "collections.OrderedDict[int, float]" = \
            collections.OrderedDict()
        self._ring = int(ring)
        self._samples: collections.deque = collections.deque(maxlen=window)
        self._alpha = float(alpha)
        self.ewma_ms: Optional[float] = None
        self.acked = 0

    def note_sent(self, frame_id: int, now: float) -> None:
        fid = int(frame_id) & 0xFFFF
        self._sent[fid] = now
        self._sent.move_to_end(fid)
        while len(self._sent) > self._ring:
            self._sent.popitem(last=False)

    def note_ack(self, frame_id: int, now: float) -> Optional[float]:
        """-> this ACK's RTT in ms, or None for an unmatched id."""
        t = self._sent.pop(int(frame_id) & 0xFFFF, None)
        if t is None:
            return None
        # retire everything sent at or before the acked frame
        stale = [k for k, v in self._sent.items() if v <= t]
        for k in stale:
            del self._sent[k]
        rtt_ms = max(0.0, (now - t) * 1000.0)
        self.acked += 1
        self._samples.append(rtt_ms)
        if self.ewma_ms is None:
            self.ewma_ms = rtt_ms
        else:
            self.ewma_ms += self._alpha * (rtt_ms - self.ewma_ms)
        return rtt_ms

    def oldest_pending_ms(self, now: float) -> float:
        """Age of the oldest un-ACKed frame — the stall signal an EWMA
        of *completed* round-trips can never show. Scans timestamps
        (bounded by ``ring``) rather than trusting insertion order."""
        if not self._sent:
            return 0.0
        return max(0.0, (now - min(self._sent.values())) * 1000.0)

    def effective_rtt_ms(self, now: float) -> float:
        """RTT for scoring: the EWMA, floored by the oldest pending age
        (a stalled client has a beautiful EWMA and a terrible queue)."""
        return max(self.ewma_ms or 0.0, self.oldest_pending_ms(now))

    def percentiles(self) -> dict:
        return _percentiles(self._samples)

    @property
    def pending(self) -> int:
        return len(self._sent)


class SessionStats:
    """One streaming session's wire-side stats. Counters are written by
    the owning service (``note_sent``/``note_ack``/backpressure edges);
    relay and congestion-controller state is *pulled* at snapshot time
    through provider callables so the numbers are always current."""

    def __init__(self, sid, kind: str, seat: str, raddr: str = "",
                 now: Optional[float] = None,
                 registry: "Optional[QoERegistry]" = None):
        self.sid = sid                        # int (ws) or peer uid str
        self.kind = str(kind)                 # 'ws' | 'webrtc' | 'bench'
        self.seat = str(seat)
        self.raddr = str(raddr)
        self.created = time.monotonic() if now is None else now
        self._registry = registry
        self.ack = AckRttEstimator()
        self.video_active = False
        #: distinct frames offered to this session's wire
        self.frames_sent = 0
        #: chunks offered (striped encoders emit several per frame) —
        #: the drop-rate denominator, same unit as the relay's
        #: dropped_frames counter (queue items)
        self.chunks_sent = 0
        self._last_sent_fid: Optional[int] = None
        self.stalls = 0
        #: client-reported display fps (the ``_f`` verb); None = unknown
        self.reported_fps: Optional[float] = None
        #: fallback fps estimate (ACK cadence), provided by the service
        self.fps_provider: Optional[Callable[[], float]] = None
        #: -> {"sent_bytes", "dropped_frames", "queue_depth",
        #:     "queued_bytes", "relays", "dead"} for the WS relay set
        self.relay_provider: Optional[Callable[[], dict]] = None
        #: -> SendSideCongestionController.stats() for WebRTC peers
        self.cc_provider: Optional[Callable[[], dict]] = None
        #: -> the display capture's content/damage block (ROADMAP 4,
        #: engine/capture.content_state: content class, EWMAs, dirty
        #: fraction) — pulled at snapshot/export time like relay stats
        self.content_provider: Optional[Callable[[], dict]] = None
        #: -> target fps for the score's fps_term
        self.target_fps: Optional[Callable[[], float]] = None
        # backpressure-window accounting
        self.bp_windows = 0
        self.bp_total_s = 0.0
        self._bp_since: Optional[float] = None
        self._bp_since_ns: Optional[int] = None
        # qoe_collapse edge detector (one incident per collapse, not
        # one per health-check evaluation)
        self._collapsed = False
        # glass-to-glass plane (ISSUE 7): the per-session clock mapping
        # plus first-send perf_counter timestamps so a client's
        # CLIENT_FRAME_TIMING report becomes a server-timebase g2g sample
        self.clock = ClockSyncEstimator()
        #: frame_id -> first-chunk send time, perf_counter ms (the
        #: tracer's timebase, NOT the monotonic seconds the ACK ring
        #: keeps — client spans must land on /api/trace coordinates)
        self._send_pc: "collections.OrderedDict[int, float]" = \
            collections.OrderedDict()
        self._g2g: collections.deque = collections.deque(maxlen=256)
        self.frames_timed = 0
        self.timing_rejected = 0
        self.last_timing: Optional[dict] = None
        #: latest CLIENT_STATS payload (decoder queue depth, dropped
        #: decodes, draw fps) — the client-side overload signal
        self.client_stats: Optional[dict] = None
        #: monotonic time of the last chunk offered to this client; None
        #: until the first send. The SLO feed gates on it: a damage-gated
        #: idle session legitimately delivers nothing, and an fps/qoe
        #: "bad" event for it would burn the error budget while healthy.
        self.last_send_mono: Optional[float] = None
        #: broadcast rendition rung this session watches (ISSUE 17):
        #: "" for ordinary seats, the rung name ("src"/"mid"/"low")
        #: for relay viewers — per-rung QoE/g2g attribution
        self.rung: str = ""

    # -- hot-path writers ---------------------------------------------------
    def note_sent(self, frame_id: int, now: float) -> None:
        """Called once per offered chunk; consecutive chunks of one
        striped frame share a frame_id and count as ONE frame."""
        self.chunks_sent += 1
        self.last_send_mono = now
        fid = int(frame_id) & 0xFFFF
        if fid != self._last_sent_fid:
            self._last_sent_fid = fid
            self.frames_sent += 1
            # one perf_counter read per FRAME (not per chunk): the g2g
            # anchor a later CLIENT_FRAME_TIMING report measures against
            self._send_pc[fid] = time.perf_counter_ns() / 1e6
            while len(self._send_pc) > 512:
                self._send_pc.popitem(last=False)
        self.ack.note_sent(frame_id, now)

    def note_frame_timing(self, frame_id: int, recv_c: float,
                          decode_c: float, present_c: float
                          ) -> Optional[dict]:
        """One client timing report (client-clock ms) mapped onto the
        server perf_counter timebase. Returns the mapped sample —
        ``send_ms``/``recv_ms``/``decode_ms``/``present_ms`` (server ms;
        send may be None for an unknown frame id) plus the derived
        ``g2g_ms`` — or None while the clock is unsynced (a mapping
        without an offset estimate would be fiction)."""
        if self.clock.offset_at(present_c) is None:
            return None
        # map each timestamp at ITS OWN instant (drift-aware), then
        # clamp to monotone order: jitter the fit let through must not
        # produce a negative decode or present span
        recv_s = self.clock.to_server_ms(recv_c)
        decode_s = max(recv_s, self.clock.to_server_ms(decode_c))
        present_s = max(decode_s, self.clock.to_server_ms(present_c))
        # plausibility gate: the payload is client-controlled, and a
        # finite-but-absurd timestamp passes the parser. A report about
        # a frame presented in the FUTURE (beyond the mapping's own
        # error bound) or in the distant past would poison the g2g
        # percentiles, the shared histogram, the g2g SLO, and the
        # extended trace envelope — drop it and count the drop.
        now_ms = time.perf_counter_ns() / 1e6
        slack = (self.clock.error_bound_ms() or 0.0) + 50.0
        if present_s > now_ms + slack or present_s < now_ms - 60_000.0:
            self.timing_rejected += 1
            return None
        fid = int(frame_id) & 0xFFFF
        send_ms = self._send_pc.pop(fid, None)
        g2g = None
        if send_ms is not None:
            if present_s >= send_ms:
                g2g = present_s - send_ms
                self._g2g.append(g2g)
                _metrics_g2g(g2g)
            else:
                # mapped present predates the send anchor: clock-sync
                # bias (up to rtt/2) clips the fastest frames first, so
                # a silent skip would bias the percentiles upward with
                # nothing in /api/sessions explaining why
                self.timing_rejected += 1
        self.frames_timed += 1
        self.last_timing = {
            "frame_id": fid,
            "send_ms": send_ms,
            "recv_ms": recv_s,
            "decode_ms": decode_s,
            "present_ms": present_s,
            "g2g_ms": g2g,
            "error_bound_ms": self.clock.error_bound_ms(),
        }
        return self.last_timing

    def note_client_stats(self, body: dict) -> None:
        """Sanitised CLIENT_STATS ingest: known numeric fields only (the
        payload is client-controlled; it must not become an unbounded
        attacker-shaped blob in /api/sessions)."""
        clean: dict = {}
        for key in ("decode_queue", "dropped_decodes", "draw_fps"):
            v = body.get(key)
            if isinstance(v, (int, float)) and -1e12 < v < 1e12:
                clean[key] = round(float(v), 3)
        if clean:
            self.client_stats = clean

    def g2g_percentiles(self) -> dict:
        return _percentiles(self._g2g)

    def note_ack(self, frame_id: int, now: float) -> Optional[float]:
        rtt = self.ack.note_ack(frame_id, now)
        if rtt is not None:
            _metrics_rtt(rtt)
        return rtt

    def note_stall(self) -> None:
        self.stalls += 1

    def backpressure_begin(self, now: float) -> None:
        if self._bp_since is None:
            self._bp_since = now
            self._bp_since_ns = time.perf_counter_ns()
            self.bp_windows += 1

    def backpressure_end(self, now: float) -> Optional[float]:
        """-> the closed window's duration in seconds (None when no
        window was open). Feeds the registry's ``qoe`` trace lane."""
        if self._bp_since is None:
            return None
        dur_s = max(0.0, now - self._bp_since)
        self.bp_total_s += dur_s
        if self._registry is not None and self._bp_since_ns is not None:
            self._registry._note_bp_window(
                self.seat, self.sid, self._bp_since_ns,
                time.perf_counter_ns() - self._bp_since_ns)
        self._bp_since = None
        self._bp_since_ns = None
        return dur_s

    # -- derived state ------------------------------------------------------
    def _pull(self, provider: Optional[Callable[[], dict]]) -> dict:
        if provider is None:
            return {}
        try:
            return dict(provider() or {})
        except Exception:
            return {}

    def client_fps(self) -> Optional[float]:
        if self.reported_fps is not None:
            return self.reported_fps
        if self.fps_provider is not None:
            try:
                return float(self.fps_provider())
            except Exception:
                return None
        return None

    def drop_rate(self, relay: Optional[dict] = None,
                  cc: Optional[dict] = None) -> float:
        if self.kind == "webrtc":
            cc = cc if cc is not None else self._pull(self.cc_provider)
            return float(cc.get("loss_fraction", 0.0) or 0.0)
        relay = relay if relay is not None else self._pull(self.relay_provider)
        dropped = float(relay.get("dropped_frames", 0) or 0)
        # chunks, not frames: the relay's dropped counter is per queued
        # item, so the denominator must be the same unit
        return min(1.0, dropped / max(1.0, float(self.chunks_sent)))

    def rtt_ms(self, now: float, cc: Optional[dict] = None) -> float:
        if self.kind == "webrtc" and self.ack.acked == 0 \
                and not self.ack.pending:
            cc = cc if cc is not None else self._pull(self.cc_provider)
            return float(cc.get("rtt_ms", 0.0) or 0.0)
        return self.ack.effective_rtt_ms(now)

    def score(self, now: Optional[float] = None) -> Optional[float]:
        """None while the session has no media flowing (a fresh viewer
        must not drag the fleet verdict either way)."""
        now = time.monotonic() if now is None else now
        cc = self._pull(self.cc_provider) if self.kind == "webrtc" else None
        if self.kind == "webrtc":
            if not cc:
                return None
        elif not (self.video_active and self.frames_sent):
            return None
        target = 0.0
        if self.target_fps is not None:
            try:
                target = float(self.target_fps())
            except Exception:
                target = 0.0
        return qoe_score(self.client_fps(), target,
                         self.rtt_ms(now, cc=cc),
                         self.drop_rate(cc=cc))

    # -- export -------------------------------------------------------------
    def snapshot(self, now: Optional[float] = None,
                 verbose: bool = False) -> dict:
        now = time.monotonic() if now is None else now
        relay = self._pull(self.relay_provider)
        cc = self._pull(self.cc_provider)
        doc: dict = {
            "sid": self.sid,
            "kind": self.kind,
            "seat": self.seat,
            "age_s": round(max(0.0, now - self.created), 1),
            "video_active": self.video_active,
            "client_fps": self.client_fps(),
            "ack_rtt_ms": round(self.ack.effective_rtt_ms(now), 3),
            "frames_sent": self.frames_sent,
            "dropped_frames": int(relay.get("dropped_frames", 0) or 0),
            "drop_rate": round(self.drop_rate(relay=relay, cc=cc), 4),
            "qoe_score": self.score(now),
        }
        if self.rung:
            doc["rung"] = self.rung
        content = self._pull(self.content_provider)
        if content:
            # content-adaptive encoding (ROADMAP 4): class + dirty
            # fraction ride the summary; the EWMA detail is verbose-only
            doc["content_class"] = content.get("class")
            doc["dirty_fraction"] = content.get("dirty_fraction")
            if verbose:
                doc["content"] = content
        g2g = self.g2g_percentiles()
        doc["g2g_p99_ms"] = g2g["p99_ms"]
        if verbose:
            doc["raddr"] = self.raddr
            doc["clock"] = self.clock.quality()
            doc["g2g"] = {**g2g,
                          "frames_timed": self.frames_timed,
                          "rejected": self.timing_rejected,
                          "last": self.last_timing}
            if self.client_stats is not None:
                doc["client"] = dict(self.client_stats)
            doc["ack"] = {**self.ack.percentiles(),
                          "ewma_ms": (round(self.ack.ewma_ms, 3)
                                      if self.ack.ewma_ms is not None
                                      else None),
                          "pending": self.ack.pending,
                          "oldest_pending_ms": round(
                              self.ack.oldest_pending_ms(now), 1),
                          "acked": self.ack.acked}
            doc["chunks_sent"] = self.chunks_sent
            doc["backpressure"] = {
                "windows": self.bp_windows,
                "total_s": round(self.bp_total_s, 3),
                "active": self._bp_since is not None,
            }
            doc["stalls"] = self.stalls
            if relay:
                doc["relay"] = relay
            if cc:
                doc["cc"] = cc
        elif self.kind == "webrtc" and cc:
            doc["cc"] = {k: cc.get(k) for k in
                         ("target_bps", "acked_bps", "detector_state",
                          "loss_fraction", "rtt_ms")}
        return doc


class QoERegistry:
    """Process-wide per-session stats set (the ``/api/sessions``
    backend). Same singleton pattern as :data:`.health.engine` — one
    instance (:data:`registry`) serves every transport; tests build
    their own."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sessions: "collections.OrderedDict[tuple, SessionStats]" = \
            collections.OrderedDict()
        self.seat_label_cap = DEFAULT_SEAT_LABEL_CAP
        self.degraded_score = DEGRADED_SCORE
        self.failed_score = FAILED_SCORE
        #: closed backpressure windows for the trace overlay:
        #: (seat, sid, t0_ns, dur_ns), bounded
        self._bp_ring: collections.deque = collections.deque(maxlen=256)
        self._collector_hooked = False
        #: qoe_collapse incident sink; None = the process engine's
        #: flight recorder (tests/selftests inject their own)
        self.recorder: Optional[_health.FlightRecorder] = None

    def configure(self, seat_label_cap: Optional[int] = None,
                  degraded_score: Optional[float] = None,
                  failed_score: Optional[float] = None) -> None:
        if seat_label_cap is not None:
            self.seat_label_cap = max(0, int(seat_label_cap))
        if degraded_score is not None:
            self.degraded_score = float(degraded_score)
        if failed_score is not None:
            self.failed_score = float(failed_score)

    # -- membership ---------------------------------------------------------
    def register(self, kind: str, seat: str, sid, raddr: str = "",
                 now: Optional[float] = None) -> SessionStats:
        st = SessionStats(sid, kind, seat, raddr=raddr, now=now,
                          registry=self)
        with self._lock:
            self._sessions[(st.kind, st.sid)] = st
        self._hook_collector()
        return st

    def unregister(self, st: Optional[SessionStats]) -> None:
        if st is None:
            return
        with self._lock:
            self._sessions.pop((st.kind, st.sid), None)

    def sessions(self) -> list[SessionStats]:
        with self._lock:
            return list(self._sessions.values())

    def clear(self) -> None:
        with self._lock:
            self._sessions.clear()
            self._bp_ring.clear()

    # -- reporting ----------------------------------------------------------
    def report(self, verbose: bool = False,
               now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        snaps = [st.snapshot(now=now, verbose=verbose)
                 for st in self.sessions()]
        scores = [s["qoe_score"] for s in snaps
                  if s.get("qoe_score") is not None]
        return {
            "count": len(snaps),
            "worst_score": min(scores) if scores else None,
            "sessions": snaps,
        }

    def health_check(self) -> "_health.Verdict":
        """The ``qoe`` check: worst live session score vs thresholds.
        A session crossing below ``failed_score`` records ONE
        ``qoe_collapse`` incident (edge-triggered; it re-arms once the
        session recovers above ``degraded_score``)."""
        now = time.monotonic()
        scored = [(st, st.score(now)) for st in self.sessions()]
        scored = [(st, s) for st, s in scored if s is not None]
        if not scored:
            return _health.ok("no active sessions")
        rec = self.recorder if self.recorder is not None \
            else _health.engine.recorder
        for st, s in scored:
            if s < self.failed_score and not st._collapsed:
                st._collapsed = True
                rec.record(
                    "qoe_collapse", transport=st.kind, sid=st.sid,
                    seat=st.seat,
                    score=s, rtt_ms=round(st.rtt_ms(now), 1),
                    drop_rate=round(st.drop_rate(), 4),
                    client_fps=st.client_fps())
            elif s >= self.degraded_score:
                st._collapsed = False
        worst_st, worst = min(scored, key=lambda kv: kv[1])
        msg = (f"worst session {worst_st.seat}#{worst_st.sid} "
               f"({worst_st.kind}): score {worst}")
        data = {"worst_score": worst, "sessions": len(scored),
                "seat": worst_st.seat, "sid": worst_st.sid}
        if worst < self.failed_score:
            return _health.failed(msg, **data)
        if worst < self.degraded_score:
            return _health.degraded(msg, **data)
        return _health.ok(msg, **data)

    # -- trace overlay ------------------------------------------------------
    def _note_bp_window(self, seat: str, sid: int, t0_ns: int,
                        dur_ns: int) -> None:
        self._bp_ring.append((seat, sid, t0_ns, dur_ns))

    def trace_events(self, pid: int = 1, tid: int = 98) -> list[dict]:
        """Backpressure windows as Chrome trace events on a ``qoe``
        lane, mergeable into the ``/api/trace`` document (same
        perf_counter µs timebase as the frame and device lanes)."""
        ring = list(self._bp_ring)
        if not ring:
            return []
        events: list[dict] = [{
            "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
            "args": {"name": "qoe"},
        }]
        for seat, sid, t0_ns, dur_ns in ring:
            events.append({
                "name": f"backpressure {seat}#{sid}",
                "ph": "X", "pid": pid, "tid": tid,
                "ts": t0_ns / 1e3, "dur": max(dur_ns, 1) / 1e3,
                "args": {"seat": seat, "sid": sid},
            })
        return events

    # -- metrics (lazy; lint image has no server plane) ----------------------
    def _hook_collector(self) -> None:
        if self._collector_hooked or self is not globals().get("registry"):
            # only the process-wide singleton exports to Prometheus —
            # throwaway test registries must not pile up collectors
            return
        try:
            from ..server import metrics
        except Exception:
            return
        self._collector_hooked = True
        metrics.describe("selkies_session_qoe_score",
                         "Per-session composite QoE score (0-100)")
        metrics.describe("selkies_session_ack_rtt_ewma_ms",
                         "Per-session EWMA ACK round-trip (ms)")
        metrics.describe("selkies_session_client_fps",
                         "Per-session client-reported display fps")
        metrics.describe("selkies_session_sent_bytes_total",
                         "Per-session media bytes handed to the wire")
        metrics.describe("selkies_session_dropped_frames_total",
                         "Per-session frames dropped by the relay budget")
        metrics.describe("selkies_session_backpressure_seconds_total",
                         "Per-session time spent backpressured")
        metrics.describe("selkies_session_clock_offset_ms",
                         "Per-session client clock offset (server-client)")
        metrics.describe("selkies_session_clock_drift_ppm",
                         "Per-session client clock drift vs server (ppm)")
        metrics.describe("selkies_session_clock_rtt_min_ms",
                         "Per-session minimum clock-exchange RTT (ms)")
        metrics.describe("selkies_sessions",
                         "Live streaming sessions by transport kind")
        metrics.describe("selkies_qoe_worst_score",
                         "Worst live session QoE score")
        metrics.describe("selkies_session_dirty_fraction",
                         "Per-session fraction of MB rows damaged in "
                         "the latest encoded frame (ROADMAP 4)")
        metrics.describe("selkies_session_content_class",
                         "Per-session content class (0=static 1=scroll "
                         "2=video 3=gaming — engine/content.py)")
        metrics.register_collector(self._export_metrics)

    def _export_metrics(self) -> None:
        """Scrape-time collector: re-exports the per-session series
        fresh (stale sessions vanish instead of flat-lining) with
        **bounded cardinality** — the first ``seat_label_cap`` sessions
        (oldest first, stable across scrapes) get their own
        ``{seat,sid}`` series; the rest aggregate into
        ``{seat="_overflow",sid="_"}``."""
        try:
            from ..server import metrics
        except Exception:
            return
        sessions = self.sessions()
        now = time.monotonic()
        per_metric = ("selkies_session_qoe_score",
                      "selkies_session_ack_rtt_ewma_ms",
                      "selkies_session_client_fps",
                      "selkies_session_sent_bytes_total",
                      "selkies_session_dropped_frames_total",
                      "selkies_session_backpressure_seconds_total",
                      "selkies_session_clock_offset_ms",
                      "selkies_session_clock_drift_ppm",
                      "selkies_session_clock_rtt_min_ms",
                      "selkies_session_dirty_fraction",
                      "selkies_session_content_class")
        for name in per_metric:
            metrics.clear_metric(name)
        by_kind: dict[str, int] = {}
        worst: Optional[float] = None
        overflow = {"sent_bytes": 0.0, "dropped": 0.0, "bp_s": 0.0,
                    "count": 0}
        for i, st in enumerate(sessions):
            by_kind[st.kind] = by_kind.get(st.kind, 0) + 1
            relay = st._pull(st.relay_provider)
            score = st.score(now)
            if score is not None:
                worst = score if worst is None else min(worst, score)
            if i < self.seat_label_cap:
                labels = {"seat": st.seat, "sid": str(st.sid)}
                if score is not None:
                    metrics.set_gauge("selkies_session_qoe_score", score,
                                      labels)
                if st.ack.ewma_ms is not None:
                    metrics.set_gauge("selkies_session_ack_rtt_ewma_ms",
                                      round(st.ack.ewma_ms, 3), labels)
                fps = st.client_fps()
                if fps is not None:
                    metrics.set_gauge("selkies_session_client_fps", fps,
                                      labels)
                metrics.set_gauge("selkies_session_sent_bytes_total",
                                  float(relay.get("sent_bytes", 0) or 0),
                                  labels)
                metrics.set_gauge("selkies_session_dropped_frames_total",
                                  float(relay.get("dropped_frames", 0)
                                        or 0), labels)
                metrics.set_gauge(
                    "selkies_session_backpressure_seconds_total",
                    round(st.bp_total_s, 3), labels)
                # clock-sync quality (ISSUE 7) — same cardinality cap
                q = st.clock.quality()
                if q["offset_ms"] is not None:
                    metrics.set_gauge("selkies_session_clock_offset_ms",
                                      q["offset_ms"], labels)
                if q["drift_ppm"] is not None:
                    metrics.set_gauge("selkies_session_clock_drift_ppm",
                                      q["drift_ppm"], labels)
                if q["rtt_min_ms"] is not None:
                    metrics.set_gauge("selkies_session_clock_rtt_min_ms",
                                      q["rtt_min_ms"], labels)
                # content-adaptive encoding (ROADMAP 4) — same
                # cardinality cap as every selkies_session_* series
                content = st._pull(st.content_provider)
                df = content.get("dirty_fraction")
                if isinstance(df, (int, float)):
                    metrics.set_gauge("selkies_session_dirty_fraction",
                                      round(float(df), 4), labels)
                cls = content.get("class")
                if cls in _CONTENT_CLASSES:
                    metrics.set_gauge("selkies_session_content_class",
                                      _CONTENT_CLASSES.index(cls), labels)
            else:
                overflow["count"] += 1
                overflow["sent_bytes"] += float(
                    relay.get("sent_bytes", 0) or 0)
                overflow["dropped"] += float(
                    relay.get("dropped_frames", 0) or 0)
                overflow["bp_s"] += st.bp_total_s
        if overflow["count"]:
            labels = {"seat": "_overflow", "sid": "_"}
            metrics.set_gauge("selkies_session_sent_bytes_total",
                              overflow["sent_bytes"], labels)
            metrics.set_gauge("selkies_session_dropped_frames_total",
                              overflow["dropped"], labels)
            metrics.set_gauge(
                "selkies_session_backpressure_seconds_total",
                round(overflow["bp_s"], 3), labels)
        metrics.clear_metric("selkies_sessions")
        for kind, n in by_kind.items():
            metrics.set_gauge("selkies_sessions", n, {"kind": kind})
        if worst is not None:
            metrics.set_gauge("selkies_qoe_worst_score", worst)
        else:
            metrics.clear_metric("selkies_qoe_worst_score")


_rtt_hist_described = False
_g2g_hist_described = False

#: the sub-ms..seconds ladder the ACK-RTT and glass-to-glass histograms
#: share (the default 1..240 fps/ms ladder would collapse both)
_WIRE_MS_BUCKETS = (0.5, 1, 2, 5, 10, 20, 50, 100, 250, 500,
                    1000, 2000, 5000)


def _metrics_g2g(g2g_ms: float) -> None:
    """Glass-to-glass latency histogram (per timed frame). Lazy +
    guarded like the RTT bridge; shares its 0.5 ms–5 s ladder."""
    global _g2g_hist_described
    try:
        from ..server import metrics
    except Exception:
        return
    if not _g2g_hist_described:
        _g2g_hist_described = True
        metrics.describe("selkies_session_g2g_ms",
                         "Frame glass-to-glass latency across sessions "
                         "(send -> client present, ms)",
                         buckets=_WIRE_MS_BUCKETS)
    metrics.observe_hist("selkies_session_g2g_ms", g2g_ms)


def _metrics_rtt(rtt_ms: float) -> None:
    """ACK RTT histogram (per-ack). Lazy + guarded like the health
    bridge; declares the sub-ms..seconds bucket ladder the default
    1..240 fps/ms ladder would collapse — once, this runs per ACK."""
    global _rtt_hist_described
    try:
        from ..server import metrics
    except Exception:
        return
    if not _rtt_hist_described:
        _rtt_hist_described = True
        metrics.describe("selkies_session_ack_rtt_ms",
                         "ACK round-trip time across sessions (ms)",
                         buckets=_WIRE_MS_BUCKETS)
    metrics.observe_hist("selkies_session_ack_rtt_ms", rtt_ms)


#: the process-wide registry every transport registers sessions against
registry = QoERegistry()
