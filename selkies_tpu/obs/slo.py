"""Declarative SLOs with error budgets and multi-window burn rates.

The observability planes (PR 2–6) produce *numbers*; nothing turns them
into a **commitment**. The north star is a latency SLO — 1080p60 at
glass-to-glass p99 < 16 ms — and ROADMAP items 2 and 5 are judged
against it, so this module is the judging instrument: declarative
objectives over boolean event streams ("this frame met the g2g budget",
"this tick the session held its fps target", "this tick QoE was above
water") with Google-SRE-style error budgets and **multi-window
burn-rate** evaluation.

Mechanics (the SRE workbook's alerting chapter, condensed):

- an :class:`Slo` promises a good-event fraction (``objective``, e.g.
  0.99 → a 1% **error budget**);
- ``burn_rate(window) = bad_fraction(window) / error_budget`` — burn 1.0
  consumes exactly the budget, 14.4 torches a 30-day budget in 2 days;
- the verdict is **two-window**: a fast window (5 m) trips instantly on
  a real regression but flaps on noise, a slow window (1 h) is stable
  but late — alert (``failed``) only when BOTH burn past the threshold,
  warn (``degraded``) when the fast window alone burns. Budget
  exhaustion over the slow window (bad fraction ≥ budget, i.e. slow
  burn ≥ 1 with the fast window still burning) also fails: a slow leak
  that ate the whole budget is an incident even if it never spiked.

Events land in fixed-width time buckets (a ring bounded by the slow
window), so memory is constant and evaluation is O(buckets). Clocks are
injected everywhere (``now=``) — burn-rate tests run on synthetic
timelines with zero sleeps, the same discipline the rest of
:mod:`selkies_tpu.obs` keeps. Stdlib-only by the obs contract.

Surfaces: ``GET /api/slo``, the ``slo`` health check, edge-triggered
``slo_burn`` flight-recorder incidents, and ``selkies_slo_*`` gauges.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from . import health as _health

__all__ = ["Slo", "SloEngine", "engine", "DEFAULT_FAST_WINDOW_S",
           "DEFAULT_SLOW_WINDOW_S", "DEFAULT_BURN_THRESHOLD"]

DEFAULT_FAST_WINDOW_S = 300.0      # 5 m: catches a regression quickly
DEFAULT_SLOW_WINDOW_S = 3600.0     # 1 h: confirms it is not a blip
#: both windows must burn this fast to page (SRE workbook's 14.4 = a
#: 30-day budget consumed in 2 days)
DEFAULT_BURN_THRESHOLD = 14.4
#: bucket width for the event ring; fine enough that the fast window
#: sees fresh data, coarse enough that an hour is 360 buckets
BUCKET_S = 10.0


class Slo:
    """One objective over a good/bad event stream. Thread-safe writers
    (frame events arrive from the loop, evaluation from a health check
    on any thread)."""

    def __init__(self, name: str, description: str = "",
                 objective: float = 0.99,
                 fast_window_s: float = DEFAULT_FAST_WINDOW_S,
                 slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
                 burn_threshold: float = DEFAULT_BURN_THRESHOLD,
                 bucket_s: float = BUCKET_S):
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0,1), got {objective}")
        self.name = str(name)
        self.description = str(description)
        self.objective = float(objective)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self.bucket_s = float(bucket_s)
        self._lock = threading.Lock()
        #: bucket_start -> [good, bad]; insertion-ordered by time
        self._buckets: dict[float, list] = {}
        self.good_total = 0
        self.bad_total = 0
        #: edge detector for the slo_burn incident (re-arms on ok)
        self.alerting = False

    @property
    def error_budget(self) -> float:
        return 1.0 - self.objective

    # -- ingest --------------------------------------------------------------
    def record(self, good: bool, n: int = 1,
               now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        b = now - (now % self.bucket_s)
        with self._lock:
            cell = self._buckets.get(b)
            if cell is None:
                cell = self._buckets[b] = [0, 0]
                self._gc(now)
            cell[1 if not good else 0] += int(n)
            if good:
                self.good_total += int(n)
            else:
                self.bad_total += int(n)

    def _gc(self, now: float) -> None:
        horizon = now - self.slow_window_s - self.bucket_s
        for b in [b for b in self._buckets if b < horizon]:
            del self._buckets[b]

    # -- math ----------------------------------------------------------------
    def _window_counts(self, window_s: float, now: float) -> tuple[int, int]:
        lo = now - window_s
        good = bad = 0
        with self._lock:
            for b, (g, x) in self._buckets.items():
                if b + self.bucket_s > lo and b <= now:
                    good += g
                    bad += x
        return good, bad

    def burn_rate(self, window_s: float,
                  now: Optional[float] = None) -> Optional[float]:
        """bad_fraction / budget over the window; None with no events."""
        now = time.monotonic() if now is None else now
        good, bad = self._window_counts(window_s, now)
        total = good + bad
        if total == 0:
            return None
        return (bad / total) / self.error_budget

    def budget_remaining(self, now: Optional[float] = None
                         ) -> Optional[float]:
        """Fraction of the slow window's error budget still unspent
        (1.0 = clean, 0.0 = exhausted)."""
        burn = self.burn_rate(self.slow_window_s, now=now)
        if burn is None:
            return None
        return max(0.0, 1.0 - burn)

    # -- verdict -------------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        fast = self.burn_rate(self.fast_window_s, now=now)
        slow = self.burn_rate(self.slow_window_s, now=now)
        # budget_remaining(), inlined against the slow burn already in
        # hand — it would re-lock and re-scan the bucket ring
        remaining = None if slow is None else max(0.0, 1.0 - slow)
        if fast is None and slow is None:
            status = _health.OK
            reason = "no events yet"
        else:
            fast_burning = fast is not None and fast > self.burn_threshold
            slow_burning = slow is not None and slow > self.burn_threshold
            exhausted = remaining == 0.0
            if fast_burning and (slow_burning or exhausted):
                status = _health.FAILED
                # label the windows like the branches below — the
                # widths are configurable, "(5m)/(1h)" would lie
                reason = (f"burn {fast:.1f}x (fast) / "
                          f"{slow:.1f}x (slow) vs {self.burn_threshold}x"
                          if not exhausted or slow_burning else
                          f"error budget exhausted (burn {fast:.1f}x fast)")
            elif fast_burning:
                status = _health.DEGRADED
                reason = (f"fast-window burn {fast:.1f}x > "
                          f"{self.burn_threshold}x (slow window "
                          f"{'%.1f' % slow if slow is not None else '?'}x)")
            else:
                status = _health.OK
                reason = (f"burn {fast:.2f}x (fast) / "
                          f"{'%.2f' % slow if slow is not None else '?'}x "
                          f"(slow)" if fast is not None else "within budget")
        return {
            "name": self.name,
            "description": self.description,
            "objective": self.objective,
            "status": status,
            "reason": reason,
            "burn_fast": round(fast, 3) if fast is not None else None,
            "burn_slow": round(slow, 3) if slow is not None else None,
            "budget_remaining": (round(remaining, 4)
                                 if remaining is not None else None),
            "windows_s": [self.fast_window_s, self.slow_window_s],
            "burn_threshold": self.burn_threshold,
            "events": {"good": self.good_total, "bad": self.bad_total},
        }

    def set_alerting(self, value: bool) -> bool:
        """Flip the incident edge detector under the lock; True iff the
        value changed (concurrent report() calls race the read-modify-
        write otherwise and double-record the same excursion)."""
        with self._lock:
            changed = self.alerting != value
            self.alerting = value
            return changed

    def clear(self) -> None:
        with self._lock:
            self._buckets.clear()
            self.good_total = 0
            self.bad_total = 0
            self.alerting = False


class SloEngine:
    """The objective set behind ``GET /api/slo`` and the ``slo`` health
    check. Same singleton pattern as :data:`.health.engine` — one
    process-wide instance (:data:`engine`); tests build their own."""

    def __init__(self):
        self._lock = threading.Lock()
        self._slos: dict[str, Slo] = {}
        #: slo_burn incident sink; None = the process health engine's
        #: flight recorder (tests/selftests inject their own)
        self.recorder: Optional[_health.FlightRecorder] = None

    # -- registration --------------------------------------------------------
    def register(self, slo: Slo) -> Slo:
        with self._lock:
            self._slos[slo.name] = slo
        return slo

    def unregister(self, name: str) -> None:
        with self._lock:
            self._slos.pop(name, None)

    def get(self, name: str) -> Optional[Slo]:
        with self._lock:
            return self._slos.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._slos)

    def clear(self) -> None:
        with self._lock:
            self._slos.clear()

    def configure_defaults(self, settings=None) -> None:
        """(Re)declare the stock objectives from settings — called by the
        server core so the SLO set exists whichever transport runs.
        Idempotent: re-configuring replaces the objective definitions
        but keeps nothing stale around."""
        g2g_ms = float(getattr(settings, "slo_g2g_ms", 250.0))
        objective = float(getattr(settings, "slo_objective", 0.99))
        burn = float(getattr(settings, "slo_burn_threshold",
                             DEFAULT_BURN_THRESHOLD))
        fast = float(getattr(settings, "slo_fast_window_s",
                             DEFAULT_FAST_WINDOW_S))
        slow = float(getattr(settings, "slo_slow_window_s",
                             DEFAULT_SLOW_WINDOW_S))
        for name, desc in (
            ("g2g", f"frame glass-to-glass latency <= {g2g_ms:g} ms"),
            ("fps", "session delivered fps >= half the target"),
            ("qoe", "session QoE score above the degraded threshold"),
        ):
            self.register(Slo(name, desc, objective=objective,
                              fast_window_s=fast, slow_window_s=slow,
                              burn_threshold=burn))

    # -- ingest --------------------------------------------------------------
    def record(self, name: str, good: bool, n: int = 1,
               now: Optional[float] = None) -> bool:
        """Record an event against a named objective. Unknown names are
        dropped (transports record unconditionally; whether an objective
        is declared is the core's policy decision)."""
        slo = self.get(name)
        if slo is None:
            return False
        slo.record(good, n=n, now=now)
        return True

    # -- verdict / export ----------------------------------------------------
    def report(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        docs = [s.evaluate(now=now) for s in
                (self.get(n) for n in self.names()) if s is not None]
        worst = _health.worst(d["status"] for d in docs)
        self._edge_incidents(docs)
        self._export_metrics(docs)
        return {"status": worst, "slos": docs}

    def health_check(self) -> "_health.Verdict":
        """The ``slo`` check: worst objective verdict, with the burning
        objective named so the ladder/operator can see WHICH promise is
        being broken."""
        rep = self.report()
        burning = [d for d in rep["slos"]
                   if d["status"] != _health.OK]
        if not burning:
            n = len(rep["slos"])
            return _health.ok(f"{n} objective(s) within budget" if n
                              else "no objectives declared")
        worst = max(burning,
                    key=lambda d: 0 if d["status"] == _health.DEGRADED
                    else 1)
        msg = f"slo {worst['name']}: {worst['reason']}"
        data = {"slo": worst["name"],
                "burn_fast": worst["burn_fast"],
                "burn_slow": worst["burn_slow"],
                "budget_remaining": worst["budget_remaining"]}
        if rep["status"] == _health.FAILED:
            return _health.failed(msg, **data)
        return _health.degraded(msg, **data)

    def _edge_incidents(self, docs: list[dict]) -> None:
        """One ``slo_burn`` incident per excursion into failed; re-arms
        once the objective returns to ok (not merely degraded — a
        flapping fast window must not machine-gun the recorder)."""
        rec = self.recorder if self.recorder is not None \
            else _health.engine.recorder
        for d in docs:
            slo = self.get(d["name"])
            if slo is None:
                continue
            if d["status"] == _health.FAILED:
                if slo.set_alerting(True):
                    rec.record("slo_burn", slo=d["name"],
                               burn_fast=d["burn_fast"],
                               burn_slow=d["burn_slow"],
                               budget_remaining=d["budget_remaining"],
                               reason=d["reason"])
            elif d["status"] == _health.OK:
                slo.set_alerting(False)

    def _export_metrics(self, docs: list[dict]) -> None:
        """``selkies_slo_*`` gauges (lazy + guarded like every obs
        metrics bridge: the lint image has no server plane)."""
        try:
            from ..server import metrics
        except Exception:
            return
        metrics.describe("selkies_slo_burn_rate",
                         "SLO error-budget burn rate per window")
        metrics.describe("selkies_slo_budget_remaining",
                         "Fraction of the slow-window error budget left")
        metrics.describe("selkies_slo_status",
                         "SLO verdict (0=ok 1=degraded 2=failed)")
        rank = {_health.OK: 0, _health.DEGRADED: 1, _health.FAILED: 2}
        for d in docs:
            if d["burn_fast"] is not None:
                metrics.set_gauge("selkies_slo_burn_rate", d["burn_fast"],
                                  {"slo": d["name"], "window": "fast"})
            if d["burn_slow"] is not None:
                metrics.set_gauge("selkies_slo_burn_rate", d["burn_slow"],
                                  {"slo": d["name"], "window": "slow"})
            if d["budget_remaining"] is not None:
                metrics.set_gauge("selkies_slo_budget_remaining",
                                  d["budget_remaining"],
                                  {"slo": d["name"]})
            metrics.set_gauge("selkies_slo_status",
                              rank.get(d["status"], 2), {"slo": d["name"]})


#: the process-wide engine every transport records against (the server
#: core declares the default objectives); tests build their own.
engine = SloEngine()
