"""Device-side media kernels (JAX/Pallas).

The compute path of the framework: colorspace conversion, blockwise
transforms, quantisation, damage detection — everything that runs on TPU.
Host-side entropy coding lives in :mod:`selkies_tpu.codecs`.
"""
