"""Dirty-band geometry: make per-frame encode cost scale with damage.

The damage tracker has always known that a typing frame touches three MB
rows; the P-frame device step still paid full-raster work. Because every
MB row is an independent slice (h264_planes codes no cross-row CAVLC or
MV context — cross-MB-row neighbours are cross-slice, hence unavailable),
a frame's bitstream decomposes into per-row segments that can be built by
DIFFERENT producers and stitched at byte-aligned slice seams:

- rows intersecting the damage map are encoded by a *band step* that
  ``dynamic_slice``s the band out of the frame/reference planes and runs
  the stock plane-layout P encode over just those rows;
- clean rows of delivered stripes become all-skip P slices whose bytes
  are precomputed ON HOST (a handful of ue() codes — see
  codecs.h264.p_skip_slice_rbsp), keyed by (row, frame_num, qp);
- stripes with no damage at all are simply not sent (the stock
  damage-gating contract).

Band geometry is **bucketed** to power-of-two row counts (like the
readback buckets, engine/readback.py) so the jit/prewarm lattice stays
finite: one compiled band program per bucket serves every band position
(the start row is a traced scalar). With motion search enabled, bands are
bucketed in whole *stripes* instead of MB rows: motion windows must equal
the decoder's picture (the stripe), so a band must cover whole stripe
streams for the encoder's window clamp to stay bit-exact with the
decoder's picture-edge clamp. Zero-MV replenishment has no windows, so
motion-off profiles get MB-row-granular bands (the typing/cursor case
this lever exists for).

Stdlib + numpy only — the planning runs on the host per frame.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["band_buckets", "plan_band", "dirty_fraction"]


def band_buckets(n_rows: int, granularity: int = 1) -> tuple:
    """Reachable band sizes for a frame of ``n_rows`` MB rows: power-of-
    two multiples of ``granularity`` (1 for zero-MV bands, rows-per-
    stripe for motion bands), plus the full frame. Ascending, deduped.

    >>> band_buckets(9)
    (1, 2, 4, 8, 9)
    >>> band_buckets(8, granularity=2)
    (2, 4, 8)
    """
    if n_rows <= 0:
        raise ValueError("n_rows must be positive")
    g = max(1, int(granularity))
    out = []
    b = g
    while b < n_rows:
        out.append(b)
        b *= 2
    out.append(n_rows)
    return tuple(out)


def plan_band(rows_needed: np.ndarray, *, granularity: int = 1,
              floor_rows: int = 1) -> Optional[tuple]:
    """Smallest bucketed band covering every needed MB row.

    ``rows_needed``: (R,) bool — rows that must be device-encoded this
    frame (dirty rows plus every row of a paint-over stripe).
    ``granularity``: band alignment/quantum in MB rows (rows-per-stripe
    when motion search is on — see module docstring).
    ``floor_rows``: content-profile floor on the bucket (a flapping
    1-row band under a blinking cursor would churn jit programs; the
    static profile floors it instead).

    -> ``(row0, band_rows)`` with ``row0 % granularity == 0`` and
    ``band_rows`` from :func:`band_buckets`, or None when no row needs
    encoding (the idle frame: the caller skips the device step
    entirely).
    """
    rows_needed = np.asarray(rows_needed, bool)
    R = int(rows_needed.shape[0])
    nz = np.nonzero(rows_needed)[0]
    if nz.size == 0:
        return None
    g = max(1, int(granularity))
    lo = (int(nz[0]) // g) * g
    hi = -(-(int(nz[-1]) + 1) // g) * g          # exclusive, g-aligned
    span = hi - lo
    want = max(span, min(max(1, int(floor_rows)), R))
    for b in band_buckets(R, g):
        if b >= want:
            band_rows = b
            break
    # place the bucket over the span, clipped so it stays in-frame and
    # g-aligned (band_rows is a multiple of g or the full frame)
    row0 = min(lo, R - band_rows)
    row0 = max(0, (row0 // g) * g)
    return row0, band_rows


def dirty_fraction(dirty_rows: np.ndarray) -> float:
    """Fraction of MB rows dirty this frame (the ledger/obs column)."""
    d = np.asarray(dirty_rows, bool)
    return float(d.sum()) / float(max(1, d.shape[0]))
