"""Device-side variable-length bit packing (the entropy-coding back half).

Classic wisdom says entropy coding is "inherently serial" and must live on
the host (SURVEY.md §7 hard-part #1). That is true of the *per-symbol
decision* structure of CABAC, but Huffman/CAVLC-style prefix codes are a
pure data-parallel problem once reframed:

1. every (block, slot) position independently computes its codeword
   ``payload`` (LSB-aligned) and bit length ``nbits`` (0 = no event);
2. stream offsets are exclusive prefix sums of ``nbits`` — a cumsum;
3. each output 32-bit word gathers the <=17 events that overlap it
   (every event is <=32 bits, so it spans at most 2 words).

Everything is static-shaped jnp (cumsum / small argsort / searchsorted /
gather) and runs entirely on TPU; only the final ``W_cap``-word buffer plus
two scalars cross PCIe/ICI. This kills the 8-12 MB/frame coefficient
readback a host entropy coder would need — the bitstream leaves the chip at
bitrate size (~16 KB/frame at 8 Mbps).

Used by the JPEG Huffman encoder (ops/jpeg_entropy.py) and the H.264 CAVLC
encoder; the reference's equivalent work happens inside the closed-source
Rust pixelflux wheel (SURVEY.md §2.2).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

# An event is at most 27 bits (JPEG: 16-bit Huffman code + 11 value bits;
# CAVLC codes are <=28), so one event overlaps at most 2 output words and a
# 32-bit word overlaps at most ceil(32/min_event_bits)+1 events. The JPEG
# minimum event is 2 bits (luma DC cat 0 would be 2; chroma EOB 2) ->
# 16 starts + 1 spanning head = 17.
MAX_EVENTS_PER_WORD = 17


class PackedStream(NamedTuple):
    words: jnp.ndarray       # (W_cap,) uint32, MSB-first bit order
    total_bits: jnp.ndarray  # () int32
    n_events: jnp.ndarray    # () int32
    overflow: jnp.ndarray    # () bool — event or word capacity exceeded


def bit_category(v: jnp.ndarray, max_cat: int = 11) -> jnp.ndarray:
    """JPEG/JFIF 'size' of a value: bits in |v| (0 for 0), exact in ints."""
    mag = jnp.abs(v.astype(jnp.int32))
    cat = jnp.zeros_like(mag)
    for b in range(max_cat):
        cat = cat + (mag >= (1 << b)).astype(jnp.int32)
    return cat


def value_bits(v: jnp.ndarray, cat: jnp.ndarray) -> jnp.ndarray:
    """Signed-magnitude value bits: v if v>=0 else v-1, masked to cat bits."""
    raw = jnp.where(v >= 0, v, v - 1).astype(jnp.int32)
    mask = (jnp.left_shift(jnp.int32(1), cat) - 1).astype(jnp.int32)
    return jnp.bitwise_and(raw, mask).astype(jnp.uint32)


def pack_slot_events(payload: jnp.ndarray, nbits: jnp.ndarray,
                     e_cap: int, w_cap: int,
                     max_events_per_word: int = MAX_EVENTS_PER_WORD
                     ) -> PackedStream:
    """Pack per-slot events into a contiguous MSB-first bitstream on device.

    ``payload``: (M, S) uint32, codeword bits LSB-aligned.
    ``nbits``:   (M, S) int32, 0..31; 0 marks an inactive slot. Slot order
                 (row-major) IS stream order.
    ``e_cap``:   static max active events materialised (overflow flagged).
    ``w_cap``:   static output capacity in 32-bit words.
    ``max_events_per_word``: ceil(32 / min event bits) + 1 — 17 for JPEG
                 (min 2-bit codes), 33 for codes that can be 1 bit (CAVLC).
    """
    m, s = payload.shape
    active = nbits > 0
    nbits = nbits.astype(jnp.int32)

    # --- per-block (row) offsets and front-packing -------------------------
    intra_off = jnp.cumsum(nbits, axis=1) - nbits          # exclusive cumsum
    block_bits = jnp.sum(nbits, axis=1)                    # (M,)
    slot_idx = jax.lax.broadcasted_iota(jnp.int32, (m, s), 1)
    order = jnp.argsort(jnp.where(active, slot_idx, s + slot_idx), axis=1)
    pay_p = jnp.take_along_axis(payload, order, axis=1)
    nb_p = jnp.take_along_axis(nbits, order, axis=1)
    ioff_p = jnp.take_along_axis(intra_off, order, axis=1)

    # --- global offsets ----------------------------------------------------
    block_start_bits = jnp.cumsum(block_bits) - block_bits      # (M,)
    total_bits = jnp.sum(block_bits).astype(jnp.int32)
    c_b = jnp.sum(active.astype(jnp.int32), axis=1)             # events/blk
    block_start_evt = jnp.cumsum(c_b) - c_b
    n_events = jnp.sum(c_b).astype(jnp.int32)

    # --- compaction gather: global event index -> (block, slot) ------------
    e_idx = jnp.arange(e_cap, dtype=jnp.int32)
    b = jnp.clip(
        jnp.searchsorted(block_start_evt, e_idx, side="right") - 1, 0, m - 1
    ).astype(jnp.int32)
    slot = e_idx - block_start_evt[b]
    in_range = (e_idx < n_events) & (slot < s)
    slot = jnp.clip(slot, 0, s - 1)
    pay_g = jnp.where(in_range, pay_p[b, slot], 0).astype(jnp.uint32)
    nb_g = jnp.where(in_range, nb_p[b, slot], 0)
    # sentinel offsets keep searchsorted monotone past the last event
    off_g = jnp.where(in_range, block_start_bits[b] + ioff_p[b, slot],
                      total_bits + (e_idx - n_events))

    # --- word materialisation ---------------------------------------------
    w_idx = jnp.arange(w_cap, dtype=jnp.int32)
    ws = w_idx * 32
    s0 = jnp.clip(jnp.searchsorted(off_g, ws, side="right") - 1, 0, e_cap - 1)

    word = jnp.zeros((w_cap,), dtype=jnp.uint32)
    for k in range(max_events_per_word):
        e = jnp.clip(s0 + k, 0, e_cap - 1)
        rel = off_g[e] - ws                       # event start within word
        nb = nb_g[e]
        end_rel = rel + nb
        valid = (nb > 0) & (rel < 32) & (end_rel > 0)
        sh = 32 - end_rel
        pay = pay_g[e]
        left = jnp.left_shift(pay, jnp.clip(sh, 0, 31).astype(jnp.uint32))
        right = jnp.right_shift(pay, jnp.clip(-sh, 0, 31).astype(jnp.uint32))
        contrib = jnp.where(sh >= 0, left, right)
        word = jnp.bitwise_or(word, jnp.where(valid, contrib, 0))

    overflow = (n_events > e_cap) | (total_bits > w_cap * 32)
    return PackedStream(word, total_bits, n_events, overflow)


def pack_slot_events_scatter(payload: jnp.ndarray, nbits: jnp.ndarray,
                             e_cap: int, w_cap: int,
                             max_events_per_word: int = MAX_EVENTS_PER_WORD
                             ) -> PackedStream:
    """Same contract as :func:`pack_slot_events`, built for the TPU's
    op-cost profile.

    The gather formulation above pays for (a) an argsort front-pack over
    every SLOT (a 105k-key bitonic sort per 1080p MB row) and (b)
    ``max_events_per_word`` gather rounds per output word (33 for CAVLC's
    1-bit codes) — the two op classes XLA:TPU executes worst. Here the
    whole pack is two scatter-adds:

    - stream offsets are still one exclusive cumsum over the slots;
    - every slot's codeword overlaps at most 2 output words; its aligned
      contribution to each is computed in place (no compaction, inactive
      slots contribute 0 bits);
    - different events occupy DISJOINT bit ranges of a word, so
      scatter-ADD is exactly bitwise-OR — ``words.at[w].add(contrib)``.

    No sort, no front-pack, no per-word event search; the slot arrays are
    read once. Bit-exact with pack_slot_events (tests/test_device_entropy,
    test_h264_device run both)."""
    m, s = payload.shape
    nbits = nbits.astype(jnp.int32)
    active = nbits > 0

    block_bits = jnp.sum(nbits, axis=1)                    # (M,)
    block_start_bits = jnp.cumsum(block_bits) - block_bits
    off = (jnp.cumsum(nbits, axis=1) - nbits) \
        + block_start_bits[:, None]                        # (M, S) global
    total_bits = jnp.sum(block_bits).astype(jnp.int32)
    n_events = jnp.sum(active.astype(jnp.int32)).astype(jnp.int32)

    pay = jnp.where(active, payload, 0).astype(jnp.uint32)
    w0 = (off >> 5).astype(jnp.int32)
    rel = (off & 31).astype(jnp.int32)
    end_rel = rel + nbits
    sh = 32 - end_rel
    # word w0: left-shift when the event fits, right-shift for the head
    # of a straddling event; word w0+1 gets the spilled tail
    hi = jnp.where(sh >= 0,
                   jnp.left_shift(pay, jnp.clip(sh, 0, 31)
                                  .astype(jnp.uint32)),
                   jnp.right_shift(pay, jnp.clip(-sh, 0, 31)
                                   .astype(jnp.uint32)))
    hi = jnp.where(active, hi, 0)
    lo = jnp.where((sh < 0) & active,
                   jnp.left_shift(pay, jnp.clip(32 + sh, 0, 31)
                                  .astype(jnp.uint32)),
                   0)
    # inactive/overflowing slots scatter out of range -> dropped
    w0_t = jnp.where(active, w0, w_cap).reshape(-1)
    w1_t = jnp.where(active & (sh < 0), w0 + 1, w_cap).reshape(-1)
    words = jnp.zeros((w_cap,), jnp.uint32)
    words = words.at[w0_t].add(hi.reshape(-1), mode="drop")
    words = words.at[w1_t].add(lo.reshape(-1), mode="drop")

    overflow = (n_events > e_cap) | (total_bits > w_cap * 32)
    return PackedStream(words, total_bits, n_events, overflow)


# ---------------------------------------------------------------------------
# hierarchical bit-merge (PERF.md lever 2, landed with the per-MB-relative
# offsets refactor): bitstream assembly as log2(S) rounds of pairwise DENSE
# stack merges instead of one global scatter-add. A "stack" is a partial
# MSB-first bitstream: (..., cap) uint32 words + a per-stack bit length,
# with every bit past the length ZERO (the invariant that makes merge a
# pure shift-and-OR). The same primitive merges per-event stacks into a
# stream (pack_slot_events_bitmerge), per-MB stacks into a row
# (h264_planes._EventSink), and per-shard row groups at the split-frame
# seam (parallel/stripes) — one formulation, three consumers.
# ---------------------------------------------------------------------------

def merge_bit_stacks(wa: jnp.ndarray, ba: jnp.ndarray,
                     wb: jnp.ndarray, bb: jnp.ndarray,
                     cap_out: int) -> tuple:
    """Append stream ``b`` to stream ``a`` at bit position ``ba``.

    ``wa``: (..., ca) uint32 MSB-first words; ``ba``: (...,) int32 bits
    used (bits past ``ba`` must be zero — the stack invariant). Same for
    ``wb``/``bb``. Returns ``(words (..., cap_out), bits (...,))``. Bits
    that would land past ``cap_out * 32`` are dropped (the caller's
    overflow accounting flags them). Entirely dense: one pad, two
    gathers, two shifts, two ORs — no sort, no scatter."""
    ca = wa.shape[-1]
    cb = wb.shape[-1]
    q = (ba >> 5)[..., None]                     # word offset of the seam
    r = (ba & 31)[..., None]                     # bit offset within it
    idx = jnp.arange(cap_out, dtype=jnp.int32)
    idx = jnp.broadcast_to(idx, ba.shape + (cap_out,))
    if cap_out >= ca:
        a_part = jnp.concatenate(
            [wa, jnp.zeros(wa.shape[:-1] + (cap_out - ca,), jnp.uint32)],
            axis=-1)
    else:
        a_part = wa[..., :cap_out]
    j = idx - q                                  # source word in b (>> r)
    bj = jnp.where((j >= 0) & (j < cb),
                   jnp.take_along_axis(wb, jnp.clip(j, 0, cb - 1), axis=-1),
                   0)
    j1 = j - 1                                   # spill word in b (<< 32-r)
    bj1 = jnp.where((j1 >= 0) & (j1 < cb),
                    jnp.take_along_axis(wb, jnp.clip(j1, 0, cb - 1),
                                        axis=-1),
                    0)
    r_u = r.astype(jnp.uint32)
    hi = jnp.right_shift(bj, r_u)
    lo = jnp.where(r > 0,
                   jnp.left_shift(bj1, (jnp.uint32(32) - r_u)
                                  & jnp.uint32(31)),
                   0)
    return a_part | hi | lo, ba + bb


def hierarchical_merge(words: jnp.ndarray, bits: jnp.ndarray,
                       out_cap: int) -> tuple:
    """Reduce a stack axis by pairwise merges: ``words`` (..., N, c) +
    ``bits`` (..., N) -> ``(stream (..., out_cap), total_bits (...,))``
    in ceil(log2(N)) dense rounds. Stream order is stack order along the
    reduced axis; N is padded to a power of two with empty stacks."""
    n = words.shape[-2]
    c = words.shape[-1]
    npad = 1 if n <= 1 else 1 << (n - 1).bit_length()
    if npad != n:
        words = jnp.concatenate(
            [words, jnp.zeros(words.shape[:-2] + (npad - n, c),
                              jnp.uint32)], axis=-2)
        bits = jnp.concatenate(
            [bits, jnp.zeros(bits.shape[:-1] + (npad - n,), bits.dtype)],
            axis=-1)
    n = npad
    while n > 1:
        wa, ba = words[..., 0::2, :], bits[..., 0::2]
        wb, bb = words[..., 1::2, :], bits[..., 1::2]
        c = min(2 * c, out_cap) if n > 2 else out_cap
        words, bits = merge_bit_stacks(wa, ba, wb, bb, c)
        n //= 2
    if words.shape[-1] < out_cap:
        words = jnp.concatenate(
            [words, jnp.zeros(words.shape[:-1]
                              + (out_cap - words.shape[-1],), jnp.uint32)],
            axis=-1)
    return words[..., 0, :], bits[..., 0]


def event_stacks(payload: jnp.ndarray, nbits: jnp.ndarray) -> jnp.ndarray:
    """Each event as its own 1-word stack (MSB-aligned): the leaves of
    the hierarchical merge. ``payload`` LSB-aligned uint32, ``nbits``
    0..32 (0 = empty stack)."""
    nb = nbits.astype(jnp.int32)
    pay = jnp.where(nb > 0, payload, 0).astype(jnp.uint32)
    sh = ((jnp.int32(32) - nb) & 31).astype(jnp.uint32)
    return jnp.where(nb > 0, jnp.left_shift(pay, sh), 0)[..., None]


def pack_slot_events_bitmerge(payload: jnp.ndarray, nbits: jnp.ndarray,
                              e_cap: int, w_cap: int,
                              max_events_per_word: int = MAX_EVENTS_PER_WORD
                              ) -> PackedStream:
    """Same contract as :func:`pack_slot_events_scatter`, built as a
    hierarchical bit-merge: every slot is a 1-word leaf stack, merged
    pairwise in stream order over ceil(log2(M*S)) dense rounds. No
    cumsum-derived global offsets, no scatter, no sort — the op classes
    the scatter/gather formulations pay for. Bit-exact with both
    (tests/test_stripes.py randomized equivalence)."""
    del max_events_per_word
    m, s = payload.shape
    nb = nbits.astype(jnp.int32)
    active = nb > 0
    total_bits = jnp.sum(nb).astype(jnp.int32)
    n_events = jnp.sum(active.astype(jnp.int32)).astype(jnp.int32)
    leaves = event_stacks(payload.reshape(-1), nb.reshape(-1))
    words, _ = hierarchical_merge(leaves, nb.reshape(-1), w_cap)
    overflow = (n_events > e_cap) | (total_bits > w_cap * 32)
    return PackedStream(words, total_bits, n_events, overflow)


def packer_name() -> str:
    """The selected packer strategy: ``SELKIES_PACKER`` in
    {"gather", "scatter", "bitmerge"}; default scatter."""
    import os
    name = os.environ.get("SELKIES_PACKER", "scatter")
    return name if name in ("gather", "scatter", "bitmerge") else "scatter"


def default_packer():
    """Packer selection: ``SELKIES_PACKER=gather|scatter|bitmerge``
    overrides; the default is the scatter formulation (no sorts, no
    per-word gather rounds — the profile winner on TPU and within noise
    on CPU). ``bitmerge`` selects the hierarchical bit-merge
    (:func:`pack_slot_events_bitmerge`).

    Scope: consumed by the JPEG entropy coder, by the reference-layout
    H.264 module (ops/h264_encode — the bit-exactness oracle, which now
    feeds the packer per-MB event blocks), and — for the scatter vs
    bitmerge choice — by the production event sink
    (ops/h264_planes._EventSink), whose per-MB-relative offsets make the
    merge formulation applicable there too."""
    name = packer_name()
    if name == "gather":
        return pack_slot_events
    if name == "bitmerge":
        return pack_slot_events_bitmerge
    return pack_slot_events_scatter


def words_to_bytes(words, total_bits: int, pad_ones: bool = True) -> bytes:
    """Host-side: trim the word buffer to the bitstream length.

    ``words`` is the (W_cap,) uint32 array (host numpy). Pad bits in the
    final byte are set to 1 (JPEG convention) unless ``pad_ones=False``
    (H.264 rbsp_trailing uses an explicit stop bit instead).
    """
    import numpy as np

    total_bits = int(total_bits)
    nbytes = (total_bits + 7) // 8
    raw = np.ascontiguousarray(np.asarray(words, dtype=np.uint32)).astype(">u4")
    by = np.frombuffer(raw.tobytes(), dtype=np.uint8)[:nbytes].copy()
    rem = total_bits % 8
    if rem and pad_ones:
        by[-1] |= (1 << (8 - rem)) - 1
    return by.tobytes()
