"""Colorspace conversion ops (RGB -> YCbCr) as fusible JAX functions.

TPU-first replacement for the CSC stage the reference performs inside the
Rust ``pixelflux`` encoder (SURVEY.md §2.2: RGB->NV12 conversion feeding
NVENC/VA-API/x264). Two matrices are provided:

- JPEG / JFIF: BT.601 **full-range** (the only colorspace baseline JPEG
  decoders assume).
- H.264: BT.709 **limited-range** (what WebCodecs expects for desktop video
  unless the VUI says otherwise).

Everything is elementwise + a 3x3 contraction, so XLA fuses the whole CSC
into neighbouring ops; the fused Pallas encode kernel reuses the same
constants.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# BT.601 full-range (JFIF), float32. y = Kr*R + Kg*G + Kb*B, Cb/Cr centred
# at +128.
_CSC_601_FULL = np.array(
    [
        [0.299, 0.587, 0.114],
        [-0.168735892, -0.331264108, 0.5],
        [0.5, -0.418687589, -0.081312411],
    ],
    dtype=np.float32,
)
_CSC_601_OFFSET = np.array([0.0, 128.0, 128.0], dtype=np.float32)

def _bt709_limited_matrix() -> np.ndarray:
    """BT.709 limited-range (video): Y in [16,235], C in [16,240]."""
    kr, kb = 0.2126, 0.0722
    kg = 1.0 - kr - kb
    y = np.array([kr, kg, kb])
    cb = (np.array([0.0, 0.0, 1.0]) - y) / (2.0 * (1.0 - kb))
    cr = (np.array([1.0, 0.0, 0.0]) - y) / (2.0 * (1.0 - kr))
    m = np.stack([y * (219.0 / 255.0), cb * (224.0 / 255.0),
                  cr * (224.0 / 255.0)])
    return m.astype(np.float32)


_CSC_709_LIMITED = _bt709_limited_matrix()
_CSC_709_OFFSET = np.array([16.0, 128.0, 128.0], dtype=np.float32)


def rgb_to_ycbcr(rgb: jnp.ndarray, standard: str = "bt601-full") -> jnp.ndarray:
    """(H, W, 3) uint8/float RGB -> (H, W, 3) float32 YCbCr (not level-shifted).

    ``standard``: ``bt601-full`` (JPEG) or ``bt709-limited`` (H.264).
    """
    if standard == "bt601-full":
        m, off = _CSC_601_FULL, _CSC_601_OFFSET
    elif standard == "bt709-limited":
        m, off = _CSC_709_LIMITED, _CSC_709_OFFSET
    else:
        raise ValueError(f"unknown standard {standard!r}")
    x = rgb.astype(jnp.float32)
    out = jnp.einsum("hwc,yc->hwy", x, jnp.asarray(m),
                     precision=jax.lax.Precision.HIGHEST) + jnp.asarray(off)
    return out


def subsample_420(plane: jnp.ndarray) -> jnp.ndarray:
    """(H, W) -> (H/2, W/2) by 2x2 mean (the standard 4:2:0 siting)."""
    h, w = plane.shape
    return plane.reshape(h // 2, 2, w // 2, 2).mean(axis=(1, 3))


def split_ycbcr_420(ycbcr: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(H, W, 3) -> Y (H,W), Cb (H/2,W/2), Cr (H/2,W/2)."""
    y = ycbcr[..., 0]
    cb = subsample_420(ycbcr[..., 1])
    cr = subsample_420(ycbcr[..., 2])
    return y, cb, cr


def ycbcr_to_rgb(ycbcr: jnp.ndarray, standard: str = "bt601-full") -> jnp.ndarray:
    """Inverse CSC for test oracles / paint-over previews."""
    if standard == "bt601-full":
        m, off = _CSC_601_FULL, _CSC_601_OFFSET
    elif standard == "bt709-limited":
        m, off = _CSC_709_LIMITED, _CSC_709_OFFSET
    else:
        raise ValueError(f"unknown standard {standard!r}")
    minv = jnp.asarray(np.linalg.inv(m).astype(np.float32))
    x = ycbcr - jnp.asarray(off)
    return jnp.einsum("hwy,cy->hwc", x, minv,
                      precision=jax.lax.Precision.HIGHEST)
