"""Blockwise 8x8 DCT-II, quantisation and zigzag as MXU-shaped JAX ops.

The transform heart of the JPEG path (and the 8x8 option of H.264 High
profile later). Everything is expressed as batched small matmuls so XLA
tiles it onto the MXU:

- 2-D DCT of a block B is ``D @ B @ D.T`` with the orthonormal DCT-II
  matrix D — two (8x8)x(8x8) matmuls per block, batched over all blocks.
- Zigzag reordering is a 64x64 permutation **matmul** (not a gather): TPUs
  love matmuls and hate gathers, and the permutation fuses into the quant
  epilogue.

Replaces the transform stage inside the reference's closed-source Rust
encoder (SURVEY.md §2.2 pixelflux row).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.cache
def dct8_matrix() -> np.ndarray:
    """Orthonormal 8-point DCT-II matrix (float32), D @ D.T = I."""
    k = np.arange(8)
    n = np.arange(8)
    m = np.cos((2 * n[None, :] + 1) * k[:, None] * np.pi / 16.0)
    m[0, :] *= 1.0 / np.sqrt(2.0)
    m *= 0.5
    return m.astype(np.float32)


@functools.cache
def zigzag_order() -> np.ndarray:
    """JPEG zigzag scan: zz[i] = raster index of the i-th zigzag position."""
    # Odd anti-diagonals run top-right -> bottom-left (order by row), even
    # ones bottom-left -> top-right (order by column).
    order = sorted(
        ((r, c) for r in range(8) for c in range(8)),
        key=lambda rc: (rc[0] + rc[1],
                        rc[0] if (rc[0] + rc[1]) % 2 else rc[1]),
    )
    return np.array([r * 8 + c for r, c in order], dtype=np.int32)


@functools.cache
def zigzag_perm_matrix() -> np.ndarray:
    """(64, 64) float32 P with (flat_block @ P) = zigzag-ordered block."""
    zz = zigzag_order()
    p = np.zeros((64, 64), dtype=np.float32)
    for out_pos, raster_idx in enumerate(zz):
        p[raster_idx, out_pos] = 1.0
    return p


def to_blocks(plane: jnp.ndarray) -> jnp.ndarray:
    """(H, W) -> (H/8 * W/8, 8, 8) raster-ordered 8x8 blocks."""
    h, w = plane.shape
    return (plane.reshape(h // 8, 8, w // 8, 8)
            .transpose(0, 2, 1, 3)
            .reshape(-1, 8, 8))


def from_blocks(blocks: jnp.ndarray, h: int, w: int) -> jnp.ndarray:
    """Inverse of :func:`to_blocks`."""
    return (blocks.reshape(h // 8, w // 8, 8, 8)
            .transpose(0, 2, 1, 3)
            .reshape(h, w))


# MXU matmuls default to bf16 inputs on TPU; DCT coefficients then drift by
# whole quantisation steps. HIGHEST keeps the transforms float32-accurate.
_PREC = jax.lax.Precision.HIGHEST


def dct2d(blocks: jnp.ndarray) -> jnp.ndarray:
    """(N, 8, 8) spatial -> (N, 8, 8) frequency via batched D @ B @ D.T."""
    d = jnp.asarray(dct8_matrix())
    return jnp.einsum("ij,njk,lk->nil", d, blocks, d,
                      precision=_PREC, preferred_element_type=jnp.float32)


def idct2d(coeffs: jnp.ndarray) -> jnp.ndarray:
    """(N, 8, 8) frequency -> spatial; D.T @ C @ D."""
    d = jnp.asarray(dct8_matrix())
    return jnp.einsum("ji,njk,kl->nil", d, coeffs, d,
                      precision=_PREC, preferred_element_type=jnp.float32)


def quantize_zigzag(coeffs: jnp.ndarray, qtable_raster: jnp.ndarray
                    ) -> jnp.ndarray:
    """(N, 8, 8) float coeffs -> (N, 64) int16 zigzag-ordered quantised.

    ``qtable_raster`` is the 64-entry table in **raster** order. Rounding is
    round-half-away-from-zero to match libjpeg's ``DESCALE`` convention.
    """
    flat = coeffs.reshape(-1, 64)
    q = flat / qtable_raster.reshape(1, 64).astype(jnp.float32)
    rounded = jnp.trunc(q + jnp.sign(q) * 0.5)
    zz = rounded @ jnp.asarray(zigzag_perm_matrix())
    return zz.astype(jnp.int16)


def dequantize_from_zigzag(zzcoeffs: jnp.ndarray, qtable_raster: jnp.ndarray
                           ) -> jnp.ndarray:
    """(N, 64) int zigzag -> (N, 8, 8) float dequantised raster coeffs."""
    p = jnp.asarray(zigzag_perm_matrix())
    raster = zzcoeffs.astype(jnp.float32) @ p.T
    return (raster * qtable_raster.reshape(1, 64).astype(jnp.float32)
            ).reshape(-1, 8, 8)
