"""Device-side H.264 Intra_16x16 encoder: RGB frame -> per-MB-row slice
bitstreams, entirely on TPU.

Parallel structure (the TPU-first decomposition of an "inherently serial"
codec; SURVEY.md §7 hard-part #1):

- **slice = one MB row** (codecs/h264.py layout): cross-slice intra
  prediction is forbidden by the spec, so rows are fully independent —
  vmap axis.
- **DC prediction subtracts a constant per MB**, and the 4x4 core
  transform of a constant hits only the DC coefficient: every AC
  coefficient, AC quant, and AC inverse-transform edge contribution is
  computed in PARALLEL over the whole frame before any prediction.
- what remains sequential is a ``lax.scan`` over MB columns carrying the
  16-px luma + 2x8-px chroma reconstructed right edges; each step does
  only the tiny DC pipeline (Hadamard + quant + rescale) for one MB per
  row — O(columns) steps of O(rows) work.
- **CAVLC is parallel too**: the nC context needs only neighbour
  TotalCoeff counts (computable independently), so codewords become
  per-slot (payload, nbits) events fed to the same device bit-packer the
  JPEG engine uses (ops/bitpack.pack_slot_events).

The bitstream produced here is the bit-exact equal of the numpy golden
encoder (codecs/h264.py), which is itself byte-exact under ffmpeg's
decoder — see tests/test_h264_device.py.

ROLE (since the plane rewrite): this module is the REFERENCE-LAYOUT
implementation — the jnp-level oracle that pins ops/h264_planes (the
production TPU-layout twin the engine and the parallel paths import) via
tests/test_h264_planes.py, plus the home of the shared pieces both use
(slot-budget constants, CAVLC event helpers, motion candidate set and
_motion_select). It stays bit-for-bit equal to the golden encoder.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..codecs import h264_tables as HT
from .bitpack import default_packer
from .colorspace import rgb_to_ycbcr
from .h264_transform import (MF4, QPC_TABLE, V4, clip1, forward4x4,
                             inverse4x4)

# static per-MB slot budget (see _mb_events): header 3, luma DC 36,
# 16 luma AC x 34, 2 chroma DC x 12, 8 chroma AC x 34, = 879
SLOTS_HDR = 3
SLOTS_BLK16 = 1 + 3 + 16 + 1 + 15          # coeff_token, signs, lvls, tz, runs
SLOTS_BLK15 = 1 + 3 + 15 + 1 + 14
SLOTS_BLK4 = 1 + 3 + 4 + 1 + 3
SLOTS_MB = SLOTS_HDR + SLOTS_BLK16 + 16 * SLOTS_BLK15 + 2 * SLOTS_BLK4 \
    + 8 * SLOTS_BLK15

LEVEL_CLAMP = 2000   # keeps level_code under the prefix-15 escape and the
#                      dequant result inside the +-2^15 conformance bound

_ZZ = jnp.asarray(HT.ZIGZAG4_NP)            # (16,) raster index per scan pos
_H4 = jnp.asarray(np.array([[1, 1, 1, 1], [1, 1, -1, -1],
                            [1, -1, -1, 1], [1, -1, 1, -1]], np.int32))

_CT_LEN = jnp.asarray(HT.CT_LEN_NP)         # (4 ctx, 4 t1, 17 tc)
_CT_CODE = jnp.asarray(HT.CT_CODE_NP)
_CDC_LEN = jnp.asarray(HT.CT_CDC_LEN_NP)    # (4 t1, 5 tc)
_CDC_CODE = jnp.asarray(HT.CT_CDC_CODE_NP)
_TZ_LEN = jnp.asarray(HT.TZ_LEN_NP)         # (15, 16)
_TZ_CODE = jnp.asarray(HT.TZ_CODE_NP)
_TZC_LEN = jnp.asarray(HT.TZ_CDC_LEN_NP)    # (3, 4)
_TZC_CODE = jnp.asarray(HT.TZ_CDC_CODE_NP)
_RB_LEN = jnp.asarray(HT.RB_LEN_NP)         # (7, 15)
_RB_CODE = jnp.asarray(HT.RB_CODE_NP)


# ---------------------------------------------------------------------------
# quant helpers with traced qp (scalars broadcast fine)
# ---------------------------------------------------------------------------

# In every helper below ``qp`` must be broadcastable to the input's BATCH
# dims (everything up to the trailing 4x4 / element dims): scalars work,
# and per-row rate control passes (R, 1, 1, ...) shapes.

def _quant_ac(w, qp):
    qbits = 15 + qp // 6
    mf = MF4[qp % 6]                              # (..., 4, 4)
    f = jnp.left_shift(jnp.int32(1), qbits) // 3
    mag = (jnp.abs(w) * mf + f[..., None, None]) >> qbits[..., None, None]
    return jnp.clip(jnp.where(w < 0, -mag, mag), -LEVEL_CLAMP, LEVEL_CLAMP)


def _quant_dc(y, qp):
    """``qp`` broadcastable to y's shape directly (elementwise)."""
    qbits = 15 + qp // 6
    mf00 = MF4[qp % 6, 0, 0]
    f2 = 2 * (jnp.left_shift(jnp.int32(1), qbits) // 3)
    mag = (jnp.abs(y) * mf00 + f2) >> (qbits + 1)
    return jnp.clip(jnp.where(y < 0, -mag, mag), -LEVEL_CLAMP, LEVEL_CLAMP)


def _dequant_ac(c, qp):
    ls = 16 * V4[qp % 6]
    t = (qp // 6)[..., None, None]
    hi = jnp.left_shift(c * ls, jnp.maximum(t - 4, 0))
    lo = (c * ls + jnp.left_shift(jnp.int32(1), jnp.maximum(3 - t, 0))) \
        >> jnp.maximum(4 - t, 0)
    return jnp.where(t >= 4, hi, lo)


def _dequant_ldc(f, qp):
    """``qp`` broadcastable to f's shape directly (elementwise)."""
    ls00 = 16 * V4[qp % 6, 0, 0]
    t = qp // 6
    hi = jnp.left_shift(f * ls00, jnp.maximum(t - 6, 0))
    lo = (f * ls00 + jnp.left_shift(jnp.int32(1), jnp.maximum(5 - t, 0))) \
        >> jnp.maximum(6 - t, 0)
    return jnp.where(t >= 6, hi, lo)


def _dequant_cdc(f, qpc):
    ls00 = 16 * V4[qpc % 6, 0, 0]
    return jnp.left_shift(f * ls00, qpc // 6) >> 5


def _had2(x):
    """2x2 Hadamard on (..., 2, 2)."""
    a = x[..., 0, 0] + x[..., 0, 1]
    b = x[..., 0, 0] - x[..., 0, 1]
    c = x[..., 1, 0] + x[..., 1, 1]
    d = x[..., 1, 0] - x[..., 1, 1]
    return jnp.stack([jnp.stack([a + c, b + d], -1),
                      jnp.stack([a - c, b - d], -1)], -2)


# ---------------------------------------------------------------------------
# CAVLC event generation (vectorised over an arbitrary batch of blocks)
# ---------------------------------------------------------------------------

class BlockEvents(NamedTuple):
    payload: jnp.ndarray    # (..., S) uint32
    nbits: jnp.ndarray      # (..., S) int32
    tc: jnp.ndarray         # (...,) int32


def _ue_event(v):
    """Exp-Golomb codeword as one event. v must be < 2^15."""
    code_num = v + 1
    nb = 32 - jax.lax.clz(code_num.astype(jnp.uint32)).astype(jnp.int32)
    return code_num.astype(jnp.uint32), 2 * nb - 1


def _level_event(level_code, suffix_len):
    """(payload, nbits) for one coeff level (§9.2.2.1 inverse). Produces
    prefix <= 15 forms only — levels are clamped upstream."""
    # suffix_len == 0 cases
    p0_lt14 = level_code + 1                       # unary: lc zeros + 1
    pay0_lt14 = jnp.uint32(1)
    pay0_esc14 = (jnp.uint32(1) << 4) | (level_code - 14).astype(jnp.uint32)
    pay0_esc15 = (jnp.uint32(1) << 12) | (level_code - 30).astype(jnp.uint32)
    # suffix_len > 0
    prefix = level_code >> jnp.maximum(suffix_len, 1)
    in_range = prefix < 15
    suffix = (level_code & (jnp.left_shift(jnp.int32(1),
                                           jnp.maximum(suffix_len, 1)) - 1))
    payS = (jnp.uint32(1) << suffix_len.astype(jnp.uint32)) \
        | suffix.astype(jnp.uint32)
    nbS = prefix + 1 + suffix_len
    payS_esc = (jnp.uint32(1) << 12) \
        | (level_code - (15 << jnp.maximum(suffix_len, 1))).astype(jnp.uint32)
    pay = jnp.where(
        suffix_len == 0,
        jnp.where(level_code < 14, pay0_lt14,
                  jnp.where(level_code < 30, pay0_esc14, pay0_esc15)),
        jnp.where(in_range, payS, payS_esc))
    nb = jnp.where(
        suffix_len == 0,
        jnp.where(level_code < 14, p0_lt14,
                  jnp.where(level_code < 30, jnp.int32(19), jnp.int32(28))),
        jnp.where(in_range, nbS, jnp.int32(28)))
    return pay, nb


def cavlc_block_events(levels: jnp.ndarray, nc: jnp.ndarray,
                       max_coeff: int, chroma_dc: bool = False
                       ) -> BlockEvents:
    """``levels``: (..., max_coeff) int32 in scan order. ``nc``: (...,)
    derived context (ignored when chroma_dc). Returns the fixed-slot event
    list: [coeff_token, 3 signs, max_coeff levels, total_zeros,
    max_coeff-1 runs]."""
    mc = max_coeff
    nz = levels != 0
    tc = jnp.sum(nz.astype(jnp.int32), axis=-1)

    # coding order: nonzeros by DESCENDING scan position
    pos = jax.lax.broadcasted_iota(jnp.int32, levels.shape, levels.ndim - 1)
    key = jnp.where(nz, -pos, mc + pos)          # nonzeros first, reversed
    order = jnp.argsort(key, axis=-1)
    lv = jnp.take_along_axis(levels, order, axis=-1)     # coding order
    pv = jnp.take_along_axis(pos, order, axis=-1)        # their positions

    # trailing ones: run of initial |1| values, capped at 3
    isone = (jnp.abs(lv) == 1).astype(jnp.int32)
    runmask = jnp.cumprod(isone, axis=-1)
    idx = jax.lax.broadcasted_iota(jnp.int32, lv.shape, lv.ndim - 1)
    in_tc = idx < tc[..., None]
    t1 = jnp.minimum(jnp.sum(runmask * in_tc, axis=-1), 3)

    S = 1 + 3 + mc + 1 + (mc - 1)
    pay = [None] * S
    nb = [None] * S

    # --- coeff_token
    if chroma_dc:
        ct_len = _CDC_LEN[t1, tc]
        ct_code = _CDC_CODE[t1, tc]
    else:
        ctx = jnp.where(nc < 2, 0, jnp.where(nc < 4, 1,
                        jnp.where(nc < 8, 2, 3)))
        ct_len = _CT_LEN[ctx, t1, tc]
        ct_code = _CT_CODE[ctx, t1, tc]
    pay[0] = ct_code.astype(jnp.uint32)
    nb[0] = ct_len

    # --- trailing one signs (slot i active iff i < t1)
    for i in range(3):
        sign = (lv[..., i] < 0).astype(jnp.uint32)
        pay[1 + i] = sign
        nb[1 + i] = jnp.where(i < t1, 1, 0)

    # --- levels (slots j: coded level index = t1 + j)
    suffix_len = jnp.where((tc > 10) & (t1 < 3), 1, 0)
    for j in range(mc):
        k = t1 + j
        active = k < tc
        level = jnp.take_along_axis(
            lv, jnp.clip(k, 0, mc - 1)[..., None], axis=-1)[..., 0]
        level_code = jnp.where(level > 0, 2 * level - 2, -2 * level - 1)
        level_code = jnp.where((j == 0) & (t1 < 3),
                               level_code - 2, level_code)
        p, n = _level_event(level_code, suffix_len)
        pay[4 + j] = jnp.where(active, p, 0).astype(jnp.uint32)
        nb[4 + j] = jnp.where(active, n, 0)
        new_sl = jnp.maximum(suffix_len, 1)
        new_sl = jnp.where(
            (jnp.abs(level) > (3 << jnp.maximum(new_sl - 1, 0)))
            & (new_sl < 6), new_sl + 1, new_sl)
        suffix_len = jnp.where(active, new_sl, suffix_len)

    # --- total_zeros
    last_pos = pv[..., 0]                         # highest nonzero position
    tz = jnp.where(tc > 0, last_pos + 1 - tc, 0)
    if chroma_dc:
        tz_len = _TZC_LEN[jnp.clip(tc - 1, 0, 2), jnp.clip(tz, 0, 3)]
        tz_code = _TZC_CODE[jnp.clip(tc - 1, 0, 2), jnp.clip(tz, 0, 3)]
    else:
        tz_len = _TZ_LEN[jnp.clip(tc - 1, 0, 14), jnp.clip(tz, 0, 15)]
        tz_code = _TZ_CODE[jnp.clip(tc - 1, 0, 14), jnp.clip(tz, 0, 15)]
    tz_active = (tc > 0) & (tc < mc)
    pay[4 + mc] = jnp.where(tz_active, tz_code, 0).astype(jnp.uint32)
    nb[4 + mc] = jnp.where(tz_active, tz_len, 0)

    # --- run_before (slot i: between coded coeff i and i+1)
    zeros_left = tz
    for i in range(mc - 1):
        active = (i < tc - 1) & (zeros_left > 0)
        run = jnp.clip(pv[..., i] - pv[..., i + 1] - 1, 0, 14)
        zl = jnp.clip(jnp.minimum(zeros_left, 7) - 1, 0, 6)
        rb_len = _RB_LEN[zl, run]
        rb_code = _RB_CODE[zl, run]
        pay[5 + mc + i] = jnp.where(active, rb_code, 0).astype(jnp.uint32)
        nb[5 + mc + i] = jnp.where(active, rb_len, 0)
        # zeros_left decreases for every coded run, even when the run_before
        # slot itself was inactive-but-counted (zeros_left==0 writes no bits)
        zeros_left = jnp.where(i < tc - 1, zeros_left - run, zeros_left)

    return BlockEvents(jnp.stack(pay, -1), jnp.stack(nb, -1), tc)


# ---------------------------------------------------------------------------
# frame pipeline
# ---------------------------------------------------------------------------

def _blocks4(plane):
    """(R, 16k, W) -> (..., nby, nbx, 4, 4) 4x4 tiling of the last 2 dims."""
    *lead, h, w = plane.shape
    return plane.reshape(*lead, h // 4, 4, w // 4, 4).swapaxes(-3, -2)


class H264FrameOut(NamedTuple):
    words: jnp.ndarray       # (R, w_cap) uint32 per-row slice bitstreams
    total_bits: jnp.ndarray  # (R,) int32 (includes the rbsp stop bit)
    overflow: jnp.ndarray    # () bool
    mb_rows: int


def _pack_rows_mb_blocked(prefix_pay, prefix_nb, mb_pay, mb_nb,
                          tail_pay, tail_nb, e_cap: int, w_cap: int):
    """Pack per-row streams from PER-MB event blocks.

    ``prefix_*`` (R, Kp) row-prefix events, ``mb_*`` (R, M, S) per-MB
    slot events, ``tail_*`` (R, Kt) post-body events (trailing skip run,
    stop bit). Slot order — prefix, MBs in order, tail, inactive slots
    skipped — is identical to the old flat row stream, so the packed
    bits are unchanged; but the packer now sees one block per MB whose
    offsets are block-RELATIVE (the hierarchical bit-merge packer's
    input shape, PERF.md lever 2 — and the seam the split-frame sharded
    path merges at)."""
    R, M, S = mb_pay.shape

    def block(p, n):
        k = p.shape[-1]
        return (jnp.concatenate(
            [p.astype(jnp.uint32)[:, None, :],
             jnp.zeros((R, 1, S - k), jnp.uint32)], axis=-1),
            jnp.concatenate(
            [n.astype(jnp.int32)[:, None, :],
             jnp.zeros((R, 1, S - k), jnp.int32)], axis=-1))

    ppay, pnb = block(prefix_pay, prefix_nb)
    tpay, tnb = block(tail_pay, tail_nb)
    pay = jnp.concatenate([ppay, mb_pay.astype(jnp.uint32), tpay], axis=1)
    nb = jnp.concatenate([pnb, mb_nb.astype(jnp.int32), tnb], axis=1)
    return jax.vmap(
        lambda p, n: default_packer()(p, n, e_cap, w_cap,
                                      max_events_per_word=33)
    )(pay, nb)


def rgb_to_yuv420(rgb: jnp.ndarray):
    """(H, W, 3) uint8 -> int32 Y (H, W), U, V (H/2, W/2). BT.601
    full-range (parity with the JPEG path; VUI-less H.264 is
    colour-agnostic at the codec layer)."""
    H, W = rgb.shape[0], rgb.shape[1]
    ycc = rgb_to_ycbcr(rgb, "bt601-full")
    yf = jnp.clip(jnp.round(ycc[..., 0]), 0, 255).astype(jnp.int32)

    def sub2(p):
        return jnp.clip(jnp.round(
            p.reshape(H // 2, 2, W // 2, 2).mean(axis=(1, 3))),
            0, 255).astype(jnp.int32)
    return yf, sub2(ycc[..., 1]), sub2(ycc[..., 2])


def h264_encode_frame(rgb: jnp.ndarray, qp: jnp.ndarray,
                      header_pay: jnp.ndarray, header_nb: jnp.ndarray,
                      e_cap: int, w_cap: int) -> H264FrameOut:
    """(H, W, 3) uint8 RGB -> per-MB-row slice RBSP bit-streams."""
    yf, uf, vf = rgb_to_yuv420(rgb)
    return h264_encode_yuv(yf, uf, vf, qp, header_pay, header_nb,
                           e_cap, w_cap)


def h264_encode_yuv(yf: jnp.ndarray, uf: jnp.ndarray, vf: jnp.ndarray,
                    qp: jnp.ndarray, header_pay: jnp.ndarray,
                    header_nb: jnp.ndarray,
                    e_cap: int, w_cap: int,
                    idr_pic_id: jnp.ndarray | int = 0,
                    want_recon: bool = False):
    """YUV420 int planes -> per-MB-row slice RBSP bit-streams.

    ``qp`` is a traced scalar or (R,) PER-ROW vector (paint-over and rate
    control steer it without recompiling — and, being in the slice header,
    without any host round-trip: the ue(idr_pic_id), se(qp-26) and
    deblock-idc fields are emitted as device events after the
    host-provided header PREFIX).
    ``idr_pic_id``: scalar or (R,) in [0, 1]; consecutive IDRs of one
    stream must alternate it (§7.4.3) — the engine derives it from a
    per-stripe sent counter carried on device.
    ``header_pay/nb``: (R, 2) slice-header prefix events up to but NOT
    including idr_pic_id (host-computed; depend on first_mb_in_slice only).
    Output is bit-identical to codecs/h264.I16Encoder on the same planes.
    """
    H, W = yf.shape[0], yf.shape[1]
    assert H % 16 == 0 and W % 16 == 0
    R, M = H // 16, W // 16
    qp = jnp.broadcast_to(jnp.asarray(qp, jnp.int32), (R,))
    qpc = QPC_TABLE[jnp.clip(qp, 0, 51)]

    yrows = yf.astype(jnp.int32).reshape(R, 16, W)
    urows = uf.astype(jnp.int32).reshape(R, 8, W // 2)
    vrows = vf.astype(jnp.int32).reshape(R, 8, W // 2)

    # ---- parallel forward transforms of the raw source (pred adjusted in
    # the scan: constant pred only shifts W00 by 16*pred)
    yb = _blocks4(yrows)                       # (R, 4, M*4, 4, 4)
    yb = yb.reshape(R, 4, M, 4, 4, 4)          # (R, by, mb, bx, 4, 4)
    wy = forward4x4(yb)                        # int32
    ub = _blocks4(urows).reshape(R, 2, M, 2, 4, 4)
    vb = _blocks4(vrows).reshape(R, 2, M, 2, 4, 4)
    wu = forward4x4(ub)
    wv = forward4x4(vb)
    wc = jnp.stack([wu, wv], axis=1)           # (R, 2, by2, M, bx2, 4, 4)

    # ---- AC levels (parallel; DC slot zeroed afterwards)
    qp_b = qp[:, None, None, None]                # vs (R, by, M, bx, ...)
    qpc_b = qpc[:, None, None, None, None]        # vs (R, 2, by2, M, bx2,...)
    acl_y = _quant_ac(wy, qp_b)                              # (R,4,M,4,4,4)
    acl_c = _quant_ac(wc, qpc_b)
    # zigzag scan vectors with DC removed
    def to_scan(q):
        flat = q.reshape(*q.shape[:-2], 16)
        scan = flat[..., _ZZ]
        return scan.at[..., 0].set(0)
    scan_y = to_scan(acl_y)                    # (R, by, M, bx, 16)
    scan_c = to_scan(acl_c)                    # (R, 2, by2, M, bx2, 16)

    # ---- AC dequant + inverse for the right-edge contribution (bx=3 / 1)
    d_y = _dequant_ac(acl_y.at[..., 0, 0].set(0), qp_b)
    d_c = _dequant_ac(acl_c.at[..., 0, 0].set(0), qpc_b)
    inv_y_edge = inverse4x4(d_y[..., 3, :, :])[..., 3]     # (R, by, M, 4)
    inv_c_edge = inverse4x4(d_c[..., 1, :, :])[..., 3]     # (R, 2, by2, M, 4)
    # full inverses for recon of interior pixels are NOT needed on device:
    # only edges feed prediction; the decoder reconstructs the rest.

    # ---- DC values of every block
    dc_y = wy[..., 0, 0]                       # (R, by, M, bx)
    dc_c = wc[..., 0, 0]                       # (R, 2, by2, M, bx2)

    # ---- scan over MB columns: DC pipeline + edge recon
    def step(carry, k):
        edge_y, edge_c = carry                 # (R, 16), (R, 2, 8)
        first = k == 0
        pred_y = jnp.where(first, 128, (edge_y.sum(-1) + 8) >> 4)  # (R,)
        dcm = dc_y[:, :, k, :] - 16 * pred_y[:, None, None]        # (R,4,4)
        hd = jnp.einsum("ij,rjk,kl->ril", _H4, dcm, _H4) >> 1
        dlvl = _quant_dc(hd, qp[:, None, None])                    # (R,4,4)
        f = jnp.einsum("ij,rjk,kl->ril", _H4, dlvl, _H4)
        dcY = _dequant_ldc(f, qp[:, None, None])
        new_edge_y = clip1(
            pred_y[:, None, None]
            + ((inv_y_edge[:, :, k, :] + dcY[:, :, 3:4] + 32) >> 6)
        ).reshape(R, 16)

        # chroma: per-half preds (top blocks use edge rows 0-3, bottom 4-7)
        pt = jnp.where(first, 128, (edge_c[..., 0:4].sum(-1) + 2) >> 2)
        pb = jnp.where(first, 128, (edge_c[..., 4:8].sum(-1) + 2) >> 2)
        pred_c = jnp.stack([pt, pb], axis=-1)          # (R, 2, by2)
        dcmc = dc_c[:, :, :, k, :] - 16 * pred_c[..., None]   # (R,2,2,2)
        hd2 = _had2(dcmc)
        qpc3 = qpc[:, None, None, None]
        clvl = _quant_dc(hd2, qpc3)
        f2 = _had2(clvl)
        dcC = _dequant_cdc(f2, qpc3)                   # (R, 2, by2, bx2)
        new_edge_c = clip1(
            pred_c[..., None]
            + ((inv_c_edge[:, :, :, k, :] + dcC[..., 1:2] + 32) >> 6)
        ).reshape(R, 2, 8)
        return (new_edge_y, new_edge_c), (dlvl, clvl, pred_y, pred_c)

    # init derived from a (zeroed) slice of the input so the carry carries
    # the same shard_map varying-axis type as the body output; XLA folds
    # the 0* away
    anchor = 0 * yrows[:, 0, 0].astype(jnp.int32)          # (R,)
    init = (jnp.zeros((R, 16), jnp.int32) + anchor[:, None],
            jnp.zeros((R, 2, 8), jnp.int32) + anchor[:, None, None])
    _, (dc_lvls, cdc_lvls, preds_y, preds_c) = jax.lax.scan(
        step, init, jnp.arange(M, dtype=jnp.int32))
    dc_lvls = jnp.moveaxis(dc_lvls, 0, 1)      # (R, M, 4, 4)
    cdc_lvls = jnp.moveaxis(cdc_lvls, 0, 1)    # (R, M, 2, 2, 2)
    preds_y = jnp.moveaxis(preds_y, 0, 1)      # (R, M)
    preds_c = jnp.moveaxis(preds_c, 0, 1)      # (R, M, 2, by2)

    if want_recon:
        # decoder-exact reconstruction of the whole frame (the P path's
        # reference). DC terms recomputed in parallel from the scan's level
        # outputs; everything else was parallel already.
        f_all = jnp.einsum("ij,rmjk,kl->rmil", _H4, dc_lvls, _H4)
        dcY_all = _dequant_ldc(f_all, qp[:, None, None, None])  # (R,M,4,4)
        inv_y_full = inverse4x4(d_y)           # (R, by, M, bx, 4, 4)
        dcY_b = jnp.moveaxis(dcY_all, 1, 2)    # (R, by, M, bx)
        py = preds_y[:, None, :, None, None, None]       # (R,1,M,1,1,1)
        rec_y = clip1(py + ((inv_y_full + dcY_b[..., None, None] + 32) >> 6))
        # (R, by, M, bx, 4, 4) -> (R*16 rows, W)
        recon_y = rec_y.transpose(0, 1, 4, 2, 3, 5).reshape(R * 16, W)
        f2_all = _had2(cdc_lvls)               # (R, M, 2, 2, 2)
        dcC_all = _dequant_cdc(f2_all, qpc[:, None, None, None, None])
        inv_c_full = inverse4x4(d_c)           # (R, 2, by2, M, bx2, 4, 4)
        # dcC_all is (R, M, comp, by2, bx2) -> want (R, comp, by2, M, bx2)
        dcC_b = jnp.transpose(dcC_all, (0, 2, 3, 1, 4))
        pc = jnp.transpose(preds_c, (0, 2, 3, 1))        # (R, 2, by2, M)
        rec_c = clip1(pc[..., None, None, None]
                      + ((inv_c_full + dcC_b[..., None, None] + 32) >> 6))
        # (R, 2, by2, M, bx2, 4, 4) -> (2, R*8, W//2)
        recon_c = rec_c.transpose(1, 0, 2, 5, 3, 4, 6).reshape(
            2, R * 8, W // 2)
        recon = (recon_y.astype(jnp.uint8), recon_c[0].astype(jnp.uint8),
                 recon_c[1].astype(jnp.uint8))
    else:
        recon = None

    # ---- CAVLC ------------------------------------------------------------
    # per-block tc for nC contexts: (R, M, by, bx) luma AC counts
    tc_y = jnp.sum(scan_y != 0, axis=-1).astype(jnp.int32)  # (R,by,M,bx)
    tc_y = jnp.moveaxis(tc_y, 1, 2)            # (R, M, by, bx)
    tc_c = jnp.sum(scan_c != 0, axis=-1).astype(jnp.int32)  # (R,2,by2,M,bx2)
    tc_c = jnp.moveaxis(tc_c, 3, 2)            # (R, 2, M, by2, bx2)

    # cbp decisions per MB
    any_ac = jnp.moveaxis(jnp.any(scan_y != 0, axis=(-1,)), 1, 2)  # R,M,by,bx
    cbp_luma = jnp.any(any_ac, axis=(-1, -2))                       # (R, M)
    any_cac = jnp.any(scan_c != 0, axis=-1)        # (R,2,by2,M,bx2)
    has_cac = jnp.any(jnp.moveaxis(any_cac, 3, 1), axis=(-1, -2, -3))  # (R,M)
    has_cdc = jnp.any(cdc_lvls != 0, axis=(-1, -2, -3))
    cbp_chroma = jnp.where(has_cac, 2, jnp.where(has_cdc, 1, 0))    # (R, M)

    # effective per-block counts for contexts: zero when cbp says not coded
    tc_y_eff = jnp.where(cbp_luma[..., None, None], tc_y, 0)
    tc_c_eff = jnp.where((cbp_chroma == 2)[:, None, :, None, None], tc_c, 0)

    # nC contexts: shared neighbour-rule helpers (also used by the P path)
    nc_y = _nc_from_counts(tc_y_eff)
    nc_c = _nc_from_counts_chroma(tc_c_eff)

    # DC block nC = block(0,0) context
    nc_dc = nc_y[..., 0, 0]                        # (R, M)

    # ---- per-block events
    dc_scan = dc_lvls.reshape(R, M, 16)[..., _ZZ]
    ev_dc = cavlc_block_events(dc_scan, nc_dc, 16)

    scan_y_rm = jnp.moveaxis(scan_y, 1, 2)         # (R, M, by, bx, 16)
    ev_y = cavlc_block_events(scan_y_rm[..., 1:], nc_y, 15)
    cdc_scan = cdc_lvls.reshape(R, M, 2, 4)
    ev_cdc = cavlc_block_events(cdc_scan, jnp.zeros((), jnp.int32), 4,
                                chroma_dc=True)
    scan_c_rm = jnp.moveaxis(scan_c, 3, 2)         # (R, 2, M, by2, bx2, 16)
    scan_c_rm = jnp.moveaxis(scan_c_rm, 1, 2)      # (R, M, 2, by2, bx2, 16)
    nc_c_rm = jnp.moveaxis(nc_c, 1, 2)             # (R, M, 2, by2, bx2)
    ev_cac = cavlc_block_events(scan_c_rm[..., 1:], nc_c_rm, 15)

    # ---- header events per MB
    mb_type = 3 + 4 * cbp_chroma + jnp.where(cbp_luma, 12, 0)  # 1+2+...
    h_pay0, h_nb0 = _ue_event(mb_type)
    hdr_pay = jnp.stack([h_pay0,
                         jnp.ones_like(h_pay0),      # chroma_pred ue(0)='1'
                         jnp.ones_like(h_pay0)], -1)  # qp_delta se(0)='1'
    hdr_nb = jnp.stack([h_nb0, jnp.ones_like(h_nb0, jnp.int32),
                        jnp.ones_like(h_nb0, jnp.int32)], -1)

    # ---- assemble slot stream per MB: header, luma DC, 16 luma AC (in
    # decoding order), 2 chroma DC, 8 chroma AC
    order = np.array([[o[0], o[1]] for o in
                      ((0, 0), (0, 1), (1, 0), (1, 1), (0, 2), (0, 3),
                       (1, 2), (1, 3), (2, 0), (2, 1), (3, 0), (3, 1),
                       (2, 2), (2, 3), (3, 2), (3, 3))])
    oy, ox = jnp.asarray(order[:, 0]), jnp.asarray(order[:, 1])
    # luma AC blocks gated by cbp_luma
    y_pay = ev_y.payload[:, :, oy, ox, :]          # (R, M, 16, S15)
    y_nb = jnp.where(cbp_luma[..., None, None],
                     ev_y.nbits[:, :, oy, ox, :], 0)
    cdc_gate = (cbp_chroma > 0)[..., None, None]
    cdc_pay = ev_cdc.payload
    cdc_nb = jnp.where(cdc_gate, ev_cdc.nbits, 0)
    cac_pay = ev_cac.payload.reshape(R, M, 8, SLOTS_BLK15)
    cac_nb = jnp.where((cbp_chroma == 2)[..., None, None],
                       ev_cac.nbits.reshape(R, M, 8, SLOTS_BLK15), 0)

    mb_pay = jnp.concatenate([
        hdr_pay,
        ev_dc.payload,
        y_pay.reshape(R, M, 16 * SLOTS_BLK15),
        cdc_pay.reshape(R, M, 2 * SLOTS_BLK4),
        cac_pay.reshape(R, M, 8 * SLOTS_BLK15),
    ], axis=-1)
    mb_nb = jnp.concatenate([
        hdr_nb,
        ev_dc.nbits,
        y_nb.reshape(R, M, 16 * SLOTS_BLK15),
        cdc_nb.reshape(R, M, 2 * SLOTS_BLK4),
        cac_nb.reshape(R, M, 8 * SLOTS_BLK15),
    ], axis=-1)

    # ---- per-row stream: header prefix + device header tail + MB slots +
    # stop. ue(idr_pic_id), the two dec_ref_pic_marking flags,
    # slice_qp_delta (se) and disable_deblocking_filter_idc (ue(1)='010')
    # are emitted HERE so neither per-row qp nor the per-stripe IDR id
    # needs a host round-trip.
    idr = jnp.broadcast_to(jnp.asarray(idr_pic_id, jnp.int32), (R,))
    idr_pay, idr_nb = _ue_event(idr)
    dqp = qp - 26
    qp_pay, qp_nb = _ue_event(jnp.where(dqp > 0, 2 * dqp - 1, -2 * dqp))
    prefix_pay = jnp.concatenate([
        header_pay.astype(jnp.uint32),
        idr_pay[:, None],
        jnp.zeros((R, 1), jnp.uint32),             # '00' marking flags
        qp_pay[:, None],
        jnp.full((R, 1), 2, jnp.uint32),           # ue(1) = '010'
    ], axis=-1)
    prefix_nb = jnp.concatenate([
        header_nb.astype(jnp.int32),
        idr_nb[:, None],
        jnp.full((R, 1), 2, jnp.int32),
        qp_nb[:, None],
        jnp.full((R, 1), 3, jnp.int32),
    ], axis=-1)

    packed = _pack_rows_mb_blocked(
        prefix_pay, prefix_nb, mb_pay, mb_nb,
        jnp.ones((R, 1), jnp.uint32),              # rbsp stop bit
        jnp.ones((R, 1), jnp.int32), e_cap, w_cap)
    out = H264FrameOut(packed.words, packed.total_bits,
                       jnp.any(packed.overflow), R)
    if want_recon:
        return out, recon
    return out


# ---------------------------------------------------------------------------
# P-frames: motion-searched conditional replenishment (SURVEY §7 step 5).
# P_Skip for zero-MV MBs whose quantised residual is all-zero, P_L0_16x16
# with mvd + residual for the rest. The TPU-first decomposition stays
# fully parallel even WITH motion:
#
# - the candidate set is STATIC (frame-global scroll/pan offsets), so
#   every "shifted reference" is a constant-index gather and per-MB SAD
#   selection is one argmin over a (K, R, M) cost tensor — no serial
#   search loop;
# - one slice per MB row makes the spec's MV predictor degenerate to
#   "left neighbour" (top/topright are cross-slice, hence unavailable,
#   §8.4.1.3), so MVD coding is a parallel shift, not a scan;
# - the same slice layout pins the P_Skip predicted MV to (0,0)
#   (§8.4.1.1: unavailable mbAddrB), so skip legality stays per-MB local.
#
# Candidates are (dy, dx) FULL-pel luma offsets; chroma uses the spec's
# eighth-sample bilinear at the implied half-pel positions. Vertical
# clamping happens at the STRIPE picture bound (each stripe is an
# independent stream whose decoder clamps at its own edges).
# ---------------------------------------------------------------------------

_CBP2CODE = jnp.asarray(HT.CBP_INTER_CBP2CODE)

P_SLOTS_HDR = 6                 # skip_run, mb_type, mvdx, mvdy, cbp, qp_delta
SLOTS_BLK16F = 1 + 3 + 16 + 1 + 15    # full 16-coeff luma block
P_SLOTS_MB = P_SLOTS_HDR + 16 * SLOTS_BLK16F + 2 * SLOTS_BLK4 \
    + 8 * SLOTS_BLK15

# lagrangian for SAD-vs-mvd-bits mode cost, ~2^((qp-12)/6) (x264's SAD
# lambda curve); integer so device and host selection agree exactly
MV_LAMBDA_NP = np.round(2.0 ** ((np.arange(52) - 12) / 6.0)).astype(np.int32)
_MV_LAMBDA = jnp.asarray(MV_LAMBDA_NP)


def se_bits(v: int) -> int:
    """Host-side exact bit cost of se(v)."""
    cn = 2 * v - 1 if v > 0 else -2 * v
    return 2 * (cn + 1).bit_length() - 1


def _se_event(v):
    """Signed Exp-Golomb codeword as one packer event."""
    return _ue_event(jnp.where(v > 0, 2 * v - 1, -2 * v))


def scroll_candidates(vrange: int = 24, hrange: int = 8) -> tuple:
    """Static MV candidate set for desktop content: zero MV, dense
    vertical scroll offsets (every integer up to ``vrange`` — scroll
    amounts are arbitrary and a miss costs full residual), power-of-two
    horizontal pans up to ``hrange``. (dy, dx) full-pel; (0, 0) first so
    ties prefer the skip-eligible zero vector."""
    c = [(0, 0)]
    for d in range(1, vrange + 1):
        c += [(d, 0), (-d, 0)]
    d = 1
    while d <= hrange:
        c += [(0, d), (0, -d)]
        d *= 2
    return tuple(c)


def _vshift(p, dy: int):
    """(S, win, W): per-window vertical shift with edge clamp — the
    decoder of a stripe stream clamps at its own picture bound."""
    if dy == 0:
        return p
    idx = np.clip(np.arange(p.shape[1]) + dy, 0, p.shape[1] - 1)
    return p[:, idx, :]


def _hshift(p, dx: int):
    """Horizontal shift with edge clamp (picture width is shared)."""
    if dx == 0:
        return p
    idx = np.clip(np.arange(p.shape[-1]) + dx, 0, p.shape[-1] - 1)
    return p[..., idx]


def _shift_chroma(p, dy: int, dx: int):
    """Chroma prediction for a full-pel luma MV: the chroma vector is
    half-pel, realised as the spec's eighth-sample bilinear (§8.4.2.2.2
    with xFracC/yFracC in {0, 4}): a 2- or 4-tap average."""
    by, fy = dy >> 1, dy & 1
    bx, fx = dx >> 1, dx & 1

    def s(a, b):
        return _hshift(_vshift(p, a), b)

    if not fy and not fx:
        return s(by, bx)
    if fy and not fx:
        return (s(by, bx) + s(by + 1, bx) + 1) >> 1
    if fx and not fy:
        return (s(by, bx) + s(by, bx + 1) + 1) >> 1
    return (s(by, bx) + s(by + 1, bx) + s(by, bx + 1)
            + s(by + 1, bx + 1) + 2) >> 2


def _sad_mb16(diff):
    """(H, W) absolute differences -> (R, M) per-16x16-MB sums via
    strided plane folds. Replaces the ``reshape(R, 16, M, 16)`` reduce,
    whose 16-wide minor dim tiled vregs at 1/8 lane occupancy on TPU
    (PERF.md lever 3); integer addition is associative, so the result is
    bit-identical."""
    col = diff[:, 0::16]
    for j in range(1, 16):
        col = col + diff[:, j::16]
    out = col[0::16, :]
    for i in range(1, 16):
        out = out + col[i::16, :]
    return out


def _motion_select(cur_y, rfy, rfu, rfv, qp, candidates, win: int):
    """Pick one candidate MV per macroblock: argmin over SAD(luma) +
    lambda(qp) * mvd-bit-estimate. Returns MC'd prediction planes, the
    (R, M, 2) quarter-pel (mvx, mvy) field, all decoder-exact."""
    H, W = cur_y.shape
    R, M = H // 16, W // 16
    S = H // win
    ry_w = rfy.reshape(S, win, W)
    ru_w = rfu.reshape(S, win // 2, W // 2)
    rv_w = rfv.reshape(S, win // 2, W // 2)
    lam = _MV_LAMBDA[jnp.clip(qp, 0, 51)]                      # (R,)

    shifted = []
    costs = []
    for dy, dx in candidates:
        sh = _hshift(_vshift(ry_w, dy), dx).reshape(H, W)
        shifted.append(sh)
        sad = _sad_mb16(jnp.abs(cur_y - sh))
        bits = se_bits(4 * dx) + se_bits(4 * dy)
        costs.append(sad + lam[:, None] * bits)
    sel = jnp.argmin(jnp.stack(costs), axis=0).astype(jnp.int32)   # (R, M)

    sel_y = jnp.broadcast_to(sel[:, None, :, None],
                             (R, 16, M, 16)).reshape(H, W)
    pred_y = shifted[0]
    for k in range(1, len(candidates)):
        pred_y = jnp.where(sel_y == k, shifted[k], pred_y)

    sel_c = jnp.broadcast_to(sel[:, None, :, None],
                             (R, 8, M, 8)).reshape(H // 2, W // 2)
    pred_u = _shift_chroma(ru_w, *candidates[0]).reshape(H // 2, W // 2)
    pred_v = _shift_chroma(rv_w, *candidates[0]).reshape(H // 2, W // 2)
    for k, (dy, dx) in enumerate(candidates[1:], 1):
        pred_u = jnp.where(
            sel_c == k,
            _shift_chroma(ru_w, dy, dx).reshape(H // 2, W // 2), pred_u)
        pred_v = jnp.where(
            sel_c == k,
            _shift_chroma(rv_w, dy, dx).reshape(H // 2, W // 2), pred_v)

    # (mvx, mvy) quarter-pel per MB
    cand_q = jnp.asarray(np.asarray(candidates, np.int32)[:, ::-1] * 4)
    mv = cand_q[sel]                                           # (R, M, 2)
    return pred_y, pred_u, pred_v, mv


def _quant_ac_inter(w, qp):
    """Inter rounding offset f/6 (JM) — matches the golden encoder."""
    qbits = 15 + qp // 6
    mf = MF4[qp % 6]
    f = jnp.left_shift(jnp.int32(1), qbits) // 6
    mag = (jnp.abs(w) * mf + f[..., None, None]) >> qbits[..., None, None]
    return jnp.clip(jnp.where(w < 0, -mag, mag), -LEVEL_CLAMP, LEVEL_CLAMP)


def _nc_from_counts(tc_eff):
    """nC context gather for (R, M, by, bx)-shaped per-block counts
    (identical neighbour rules as the I path)."""
    shp = tc_eff.shape
    bx = jax.lax.broadcasted_iota(jnp.int32, shp, 3)
    by = jax.lax.broadcasted_iota(jnp.int32, shp, 2)
    mb = jax.lax.broadcasted_iota(jnp.int32, shp, 1)
    left_in = jnp.pad(tc_eff[..., :-1], ((0, 0),) * 3 + ((1, 0),))
    left_mb = jnp.pad(tc_eff[:, :-1, :, 3], ((0, 0), (1, 0), (0, 0)))
    na = jnp.where(bx == 0, left_mb[..., None], left_in)
    a_avail = (bx > 0) | (mb > 0)
    up_in = jnp.pad(tc_eff[..., :-1, :], ((0, 0),) * 2 + ((1, 0), (0, 0)))
    b_avail = by > 0
    both = a_avail & b_avail
    return jnp.where(both, (na + up_in + 1) >> 1,
                     jnp.where(a_avail, na, jnp.where(b_avail, up_in, 0)))


def _nc_from_counts_chroma(tc_eff):
    """(R, comp, M, by2, bx2) chroma variant."""
    shp = tc_eff.shape
    bx = jax.lax.broadcasted_iota(jnp.int32, shp, 4)
    by = jax.lax.broadcasted_iota(jnp.int32, shp, 3)
    mb = jax.lax.broadcasted_iota(jnp.int32, shp, 2)
    left_in = jnp.pad(tc_eff[..., :-1], ((0, 0),) * 4 + ((1, 0),))
    left_mb = jnp.pad(tc_eff[:, :, :-1, :, 1], ((0, 0), (0, 0), (1, 0),
                                                (0, 0)))
    na = jnp.where(bx == 0, left_mb[..., None], left_in)
    a_avail = (bx > 0) | (mb > 0)
    up_in = jnp.pad(tc_eff[..., :-1, :], ((0, 0),) * 3 + ((1, 0), (0, 0)))
    b_avail = by > 0
    both = a_avail & b_avail
    return jnp.where(both, (na + up_in + 1) >> 1,
                     jnp.where(a_avail, na, jnp.where(b_avail, up_in, 0)))


def h264_encode_p_yuv(yf, uf, vf, ref_y, ref_u, ref_v, qp,
                      header_pay, header_nb, frame_num,
                      e_cap: int, w_cap: int,
                      candidates: tuple = ((0, 0),),
                      stripe_rows: int | None = None):
    """P-frame encode against a reference reconstruction.

    All of (yf, uf, vf) and (ref_*) are int32/uint8 planes; ``qp`` and
    ``frame_num`` are scalars or (R,) vectors. ``candidates`` is the
    static full-pel MV candidate set (see :func:`scroll_candidates`);
    ``stripe_rows`` bounds vertical motion clamping to groups of that
    many MB rows — the per-stripe picture bound of striped streams.
    Returns (H264FrameOut, (recon_y, recon_u, recon_v)) — the recon is
    the next frame's reference, decoder-exact.
    """
    H, W = yf.shape[0], yf.shape[1]
    R, M = H // 16, W // 16
    qp = jnp.broadcast_to(jnp.asarray(qp, jnp.int32), (R,))
    qpc = QPC_TABLE[jnp.clip(qp, 0, 51)]
    fn = jnp.broadcast_to(jnp.asarray(frame_num, jnp.int32), (R,))

    cur_y = yf.astype(jnp.int32)
    cur_u = uf.astype(jnp.int32)
    cur_v = vf.astype(jnp.int32)
    rfy = ref_y.astype(jnp.int32)
    rfu = ref_u.astype(jnp.int32)
    rfv = ref_v.astype(jnp.int32)

    win = 16 * (stripe_rows if stripe_rows else R)
    assert H % win == 0, "stripe_rows must tile the frame"
    if len(candidates) > 1:
        pred_y, pred_u, pred_v, mv = _motion_select(
            cur_y, rfy, rfu, rfv, qp, candidates, win)
    else:
        pred_y, pred_u, pred_v = rfy, rfu, rfv
        mv = jnp.zeros((R, M, 2), jnp.int32)

    y = cur_y.reshape(R, 16, W)
    u = cur_u.reshape(R, 8, W // 2)
    v = cur_v.reshape(R, 8, W // 2)
    ry = pred_y.reshape(R, 16, W)
    ru = pred_u.reshape(R, 8, W // 2)
    rv = pred_v.reshape(R, 8, W // 2)

    # ---- residual transforms (fully parallel)
    yb = _blocks4(y - ry).reshape(R, 4, M, 4, 4, 4)     # (R,by,M,bx,4,4)
    wy = forward4x4(yb)
    ub = _blocks4(u - ru).reshape(R, 2, M, 2, 4, 4)
    vb = _blocks4(v - rv).reshape(R, 2, M, 2, 4, 4)
    wc = jnp.stack([forward4x4(ub), forward4x4(vb)], axis=1)

    qp_b = qp[:, None, None, None]
    qpc_b = qpc[:, None, None, None, None]
    lvl_y = _quant_ac_inter(wy, qp_b)                    # 16-coeff blocks
    lvl_c = _quant_ac_inter(wc, qpc_b)

    def to_scan_full(q):
        return q.reshape(*q.shape[:-2], 16)[..., _ZZ]
    scan_y = to_scan_full(lvl_y)                         # (R,by,M,bx,16)
    scan_c_all = to_scan_full(lvl_c)                     # (R,2,by2,M,bx2,16)
    scan_c = scan_c_all.at[..., 0].set(0)                # AC-only (DC sep)

    # chroma DC via 2x2 hadamard of the W00s (intra-style quant offset,
    # matching the golden encoder)
    cdcw = wc[..., 0, 0]                                 # (R,2,by2,M,bx2)
    cdcw = jnp.moveaxis(cdcw, 3, 2)                      # (R,2,M,by2,bx2)
    hd2 = _had2(cdcw)
    clvl = _quant_dc(hd2, qpc[:, None, None, None, None])
    f2 = _had2(clvl)
    dcC = _dequant_cdc(f2, qpc[:, None, None, None, None])  # (R,2,M,2,2)

    # ---- cbp per MB
    any_blk = jnp.any(scan_y != 0, axis=-1)              # (R,by,M,bx)
    any_blk = jnp.moveaxis(any_blk, 1, 2)                # (R,M,by,bx)
    # 8x8 group bit g8 = (by//2)*2 + bx//2
    g = any_blk.reshape(R, M, 2, 2, 2, 2)                # by-> (g_r, r2), bx-> (g_c, c2)
    grp = jnp.any(g, axis=(3, 5))                        # (R,M,2,2)
    cbp_luma = (grp[..., 0, 0].astype(jnp.int32)
                | (grp[..., 0, 1].astype(jnp.int32) << 1)
                | (grp[..., 1, 0].astype(jnp.int32) << 2)
                | (grp[..., 1, 1].astype(jnp.int32) << 3))
    any_cac = jnp.any(scan_c != 0, axis=-1)              # (R,2,by2,M,bx2)
    hc2 = jnp.any(jnp.moveaxis(any_cac, 3, 2), axis=(1, 3, 4))  # (R,M)
    has_cdc_m = jnp.any(clvl != 0, axis=(1, 3, 4))       # (R,M)
    cbp_chroma = jnp.where(hc2, 2, jnp.where(has_cdc_m, 1, 0))
    cbp = cbp_luma | (cbp_chroma << 4)                   # (R, M)
    # P_Skip requires BOTH an all-zero residual and the skip-predicted MV,
    # which our one-slice-per-row layout pins to (0,0) (§8.4.1.1)
    mv_nz = (mv[..., 0] != 0) | (mv[..., 1] != 0)
    coded = (cbp != 0) | mv_nz
    skip = ~coded

    # MV prediction degenerates to the left neighbour (§8.4.1.3 with B/C/D
    # cross-slice-unavailable); first MB of a row predicts (0,0). Skipped
    # MBs carry their true (zero) MV, so one parallel shift is exact.
    mvp = jnp.concatenate(
        [jnp.zeros((R, 1, 2), jnp.int32), mv[:, :-1]], axis=1)
    mvd = mv - mvp

    # ---- effective counts + nC
    tc_y = jnp.moveaxis(jnp.sum(scan_y != 0, axis=-1), 1, 2).astype(jnp.int32)
    g8_of = jnp.asarray(np.array([[0, 0, 1, 1]] * 2 + [[2, 2, 3, 3]] * 2))
    grp_bit = (cbp_luma[..., None, None] >> g8_of) & 1   # (R,M,by,bx)
    tc_y_eff = jnp.where(coded[..., None, None] & (grp_bit == 1), tc_y, 0)
    nc_y = _nc_from_counts(tc_y_eff)
    tc_c = jnp.moveaxis(jnp.sum(scan_c != 0, axis=-1), 3, 2).astype(jnp.int32)
    tc_c_eff = jnp.where((cbp_chroma == 2)[:, None, :, None, None], tc_c, 0)
    nc_c = _nc_from_counts_chroma(tc_c_eff)

    # ---- recon (decoder-exact): zero out blocks in unset groups
    lvl_y_gated = jnp.where(
        jnp.moveaxis(grp_bit & coded[..., None, None], 2, 1)[..., None, None]
        .astype(bool), lvl_y.reshape(R, 4, M, 4, 4, 4), 0)
    d_y = _dequant_ac(lvl_y_gated, qp_b)
    res_y = (inverse4x4(d_y) + 32) >> 6
    rec_y_blocks = clip1(_blocks4(ry).reshape(R, 4, M, 4, 4, 4) + res_y)
    recon_y = rec_y_blocks.transpose(0, 1, 4, 2, 3, 5).reshape(R * 16, W)

    # rebuild chroma coeff blocks for recon: AC from lvl_c (gated on
    # cbp_chroma == 2), DC from dcC (gated on cbp_chroma >= 1)
    cac_gate = (cbp_chroma == 2)                          # (R,M)
    c_blocks = jnp.where(cac_gate[:, None, None, :, None, None, None],
                         lvl_c.reshape(R, 2, 2, M, 2, 4, 4), 0)
    c_blocks = c_blocks.at[..., 0, 0].set(0)
    d_c = _dequant_ac(c_blocks, qpc[:, None, None, None, None])
    dcC_b = jnp.transpose(dcC, (0, 1, 3, 2, 4))          # (R,2,by2,M,bx2)
    dcC_gated = jnp.where((cbp_chroma >= 1)[:, None, None, :, None],
                          dcC_b, 0)
    d_c = d_c.at[..., 0, 0].set(dcC_gated)
    res_c = (inverse4x4(d_c) + 32) >> 6
    ref_c_blocks = jnp.stack([_blocks4(ru).reshape(R, 2, M, 2, 4, 4),
                              _blocks4(rv).reshape(R, 2, M, 2, 4, 4)], 1)
    rec_c_blocks = clip1(ref_c_blocks + res_c)
    recon_c = rec_c_blocks.transpose(1, 0, 2, 5, 3, 4, 6).reshape(
        2, R * 8, W // 2)

    return _assemble_p_rows(
        R, M, qp, qpc, fn, header_pay, header_nb, cbp, coded, skip,
        scan_y, nc_y, clvl, scan_c, nc_c, cbp_luma, cbp_chroma,
        mvd, e_cap, w_cap,
    ), (recon_y.astype(jnp.uint8), recon_c[0].astype(jnp.uint8),
        recon_c[1].astype(jnp.uint8))


def _assemble_p_rows(R, M, qp, qpc, fn, header_pay, header_nb, cbp, coded,
                     skip, scan_y, nc_y, clvl, scan_c, nc_c,
                     cbp_luma, cbp_chroma, mvd, e_cap, w_cap
                     ) -> H264FrameOut:
    """Slot assembly for P rows: skip runs, MB syntax, residual events."""
    # ---- per-MB skip-run values (count of skips since the previous coded
    # MB in the row): prev coded index via an inclusive running max
    idx = jax.lax.broadcasted_iota(jnp.int32, (R, M), 1)
    marked = jnp.where(coded, idx, -1)
    inclusive = jax.lax.associative_scan(jnp.maximum, marked, axis=1)
    prev_excl = jnp.concatenate(
        [jnp.full((R, 1), -1, jnp.int32), inclusive[:, :-1]], axis=1)
    skip_run = idx - prev_excl - 1                       # valid where coded
    last_coded = inclusive[:, -1]                        # (R,), -1 if none
    trailing = (M - 1) - last_coded                      # skips after last

    # ---- header-ish events per MB
    sr_pay, sr_nb = _ue_event(jnp.maximum(skip_run, 0))
    sr_nb = jnp.where(coded, sr_nb, 0)
    mbt_pay = jnp.ones((R, M), jnp.uint32)               # ue(0) = '1'
    mbt_nb = jnp.where(coded, 1, 0)
    mvdx_pay, mvdx_nb = _se_event(mvd[..., 0])           # mvd_l0 x then y
    mvdx_nb = jnp.where(coded, mvdx_nb, 0)
    mvdy_pay, mvdy_nb = _se_event(mvd[..., 1])
    mvdy_nb = jnp.where(coded, mvdy_nb, 0)
    cbp_pay, cbp_nb = _ue_event(_CBP2CODE[cbp])
    cbp_nb = jnp.where(coded, cbp_nb, 0)
    dqp_pay = jnp.ones((R, M), jnp.uint32)               # se(0) = '1'
    # mb_qp_delta exists ONLY when the MB carries residual (§7.3.5: gated
    # on CodedBlockPattern != 0 for inter) — a pure-motion MB (mv != 0,
    # cbp == 0, the scroll fast path) must not emit it
    dqp_nb = jnp.where(coded & (cbp != 0), 1, 0)

    # ---- residual events
    scan_y_rm = jnp.moveaxis(scan_y, 1, 2)               # (R,M,by,bx,16)
    ev_y = cavlc_block_events(scan_y_rm, nc_y, 16)
    g8_of = jnp.asarray(np.array([[0, 0, 1, 1]] * 2 + [[2, 2, 3, 3]] * 2))
    blk_on = ((cbp_luma[..., None, None] >> g8_of) & 1).astype(bool) \
        & coded[..., None, None]
    order = np.array(
        [(0, 0), (0, 1), (1, 0), (1, 1), (0, 2), (0, 3), (1, 2), (1, 3),
         (2, 0), (2, 1), (3, 0), (3, 1), (2, 2), (2, 3), (3, 2), (3, 3)])
    oy, ox = jnp.asarray(order[:, 0]), jnp.asarray(order[:, 1])
    y_pay = ev_y.payload[:, :, oy, ox, :]
    y_nb = jnp.where(blk_on[:, :, oy, ox, None],
                     ev_y.nbits[:, :, oy, ox, :], 0)

    cdc_scan = jnp.moveaxis(clvl, 2, 1).reshape(R, M, 2, 4)
    ev_cdc = cavlc_block_events(cdc_scan, jnp.zeros((), jnp.int32), 4,
                                chroma_dc=True)
    cdc_nb = jnp.where((cbp_chroma > 0)[..., None, None], ev_cdc.nbits, 0)
    scan_c_rm = jnp.moveaxis(jnp.moveaxis(scan_c, 3, 2), 1, 2)
    nc_c_rm = jnp.moveaxis(nc_c, 1, 2)
    ev_cac = cavlc_block_events(scan_c_rm[..., 1:], nc_c_rm, 15)
    cac_pay = ev_cac.payload.reshape(R, M, 8, SLOTS_BLK15)
    cac_nb = jnp.where((cbp_chroma == 2)[..., None, None],
                       ev_cac.nbits.reshape(R, M, 8, SLOTS_BLK15), 0)

    mb_pay = jnp.concatenate([
        sr_pay[..., None], mbt_pay[..., None],
        mvdx_pay[..., None], mvdy_pay[..., None],
        cbp_pay[..., None], dqp_pay[..., None],
        y_pay.reshape(R, M, 16 * SLOTS_BLK16F),
        ev_cdc.payload.reshape(R, M, 2 * SLOTS_BLK4),
        cac_pay.reshape(R, M, 8 * SLOTS_BLK15),
    ], axis=-1)
    mb_nb = jnp.concatenate([
        sr_nb[..., None], mbt_nb[..., None],
        mvdx_nb[..., None], mvdy_nb[..., None],
        cbp_nb[..., None], dqp_nb[..., None],
        y_nb.reshape(R, M, 16 * SLOTS_BLK16F),
        cdc_nb.reshape(R, M, 2 * SLOTS_BLK4),
        cac_nb.reshape(R, M, 8 * SLOTS_BLK15),
    ], axis=-1)

    # ---- row stream: host prefix + device tail (frame_num, flags) +
    # qp tail + per-MB slot blocks + trailing skip run + stop bit
    dqp_h = qp - 26
    qph_pay, qph_nb = _ue_event(jnp.where(dqp_h > 0, 2 * dqp_h - 1,
                                          -2 * dqp_h))
    tr_pay, tr_nb = _ue_event(jnp.maximum(trailing, 0))
    tr_nb = jnp.where(trailing > 0, tr_nb, 0)
    prefix_pay = jnp.concatenate([
        header_pay.astype(jnp.uint32),
        (fn & 0xF).astype(jnp.uint32)[:, None],          # frame_num u(4)
        jnp.zeros((R, 1), jnp.uint32),                   # '000' flags
        qph_pay[:, None],
        jnp.full((R, 1), 2, jnp.uint32),                 # ue(1) deblock off
    ], axis=-1)
    prefix_nb = jnp.concatenate([
        header_nb.astype(jnp.int32),
        jnp.full((R, 1), 4, jnp.int32),
        jnp.full((R, 1), 3, jnp.int32),
        qph_nb[:, None],
        jnp.full((R, 1), 3, jnp.int32),
    ], axis=-1)
    tail_pay = jnp.concatenate([
        tr_pay[:, None],
        jnp.ones((R, 1), jnp.uint32),                    # rbsp stop bit
    ], axis=-1)
    tail_nb = jnp.concatenate([
        tr_nb[:, None],
        jnp.ones((R, 1), jnp.int32),
    ], axis=-1)
    packed = _pack_rows_mb_blocked(prefix_pay, prefix_nb, mb_pay, mb_nb,
                                   tail_pay, tail_nb, e_cap, w_cap)
    return H264FrameOut(packed.words, packed.total_bits,
                        jnp.any(packed.overflow), R)
