"""TPU-layout H.264 encode: the same bitstreams as ops/h264_encode, built
from "coefficient planes" instead of (..., 4, 4) block tensors.

Why: XLA:TPU tiles the last two dims of every array to (8, 128) vector
registers. The original layout carries 4x4 (and 16-wide) minor dims
everywhere, so a 1080p frame's transform tensors pad 32-64x in HBM —
profiling on a real v5e chip put the transform+quant stage alone at
~88 ms/frame. Here every tensor keeps LARGE minor dims:

- a 4x4 block transform is 16 stride-4 plane slices and int butterflies:
  coefficient (i, j) of every block lives in one (H/4, W/4) plane;
- CAVLC runs per-slot over (nby, nbx) block-grid planes — the per-block
  argsort becomes rank-select arithmetic over 16 planes, take_along_axis
  becomes Python list indexing, and VLC tables are packed (len<<16|code)
  single-take lookups;
- bit offsets are exclusive sums over tiny (R, M) per-block totals, and
  the stream is materialised by ONE pair of scatter-adds over all event
  classes (same disjoint-bits trick as ops/bitpack.pack_slot_events_scatter).

Bit-identical to ops/h264_encode.h264_encode_yuv / h264_encode_p_yuv
(tests/test_h264_planes.py), which are themselves pinned to the numpy
golden encoder and ffmpeg. Reference equivalent: the closed Rust
pixelflux encoders (SURVEY.md §2.2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..codecs import h264_tables as HT
from .h264_encode import (H264FrameOut, LEVEL_CLAMP, P_SLOTS_HDR,
                          SLOTS_BLK4, SLOTS_BLK15, SLOTS_BLK16,
                          SLOTS_BLK16F, SLOTS_HDR, SLOTS_MB, P_SLOTS_MB,
                          _motion_select, _ue_event, _se_event,
                          _level_event, _MV_LAMBDA)
from .colorspace import rgb_to_ycbcr
from .h264_transform import ZIGZAG4, _MF, _POS_CLS, _QPC, _V

# ---------------------------------------------------------------------------
# tables (packed len<<16 | code so every VLC lookup is ONE take)
# ---------------------------------------------------------------------------


def _pack_tab(len_np, code_np):
    return jnp.asarray((len_np.astype(np.int32) << 16)
                       | code_np.astype(np.int32))


_CT_PACK = _pack_tab(HT.CT_LEN_NP, HT.CT_CODE_NP).reshape(-1)      # 4*4*17
_CDC_PACK = _pack_tab(HT.CT_CDC_LEN_NP, HT.CT_CDC_CODE_NP).reshape(-1)
_TZ_PACK = _pack_tab(HT.TZ_LEN_NP, HT.TZ_CODE_NP).reshape(-1)      # 15*16
_TZC_PACK = _pack_tab(HT.TZ_CDC_LEN_NP, HT.TZ_CDC_CODE_NP).reshape(-1)
_RB_PACK = _pack_tab(HT.RB_LEN_NP, HT.RB_CODE_NP).reshape(-1)      # 7*15
_CBP2CODE_J = jnp.asarray(HT.CBP_INTER_CBP2CODE)

_MF_J = jnp.asarray(_MF)            # (6, 3) pos-class quant multipliers
_V_J = jnp.asarray(_V)              # (6, 3) rescale multipliers
_QPC_J = jnp.asarray(_QPC)
_ZZ_IJ = [(int(z) // 4, int(z) % 4) for z in ZIGZAG4]   # scan pos -> (i, j)


def _lut(packed, idx):
    """packed (T,) int32 len<<16|code; idx any-shape int32 ->
    (pay uint32, nb int32)."""
    v = jnp.take(packed, idx)
    return (v & 0xFFFF).astype(jnp.uint32), (v >> 16).astype(jnp.int32)


# ---------------------------------------------------------------------------
# plane transforms (stride-4 slices + butterflies; exact int32)
# ---------------------------------------------------------------------------

def fwd4_planes(x):
    """(H, W) int32 -> 4x4 nested list of (H/4, W/4) coefficient planes:
    out[i][j] = (Cf X Cf^T)[i, j] of every 4x4 block."""
    x0, x1, x2, x3 = x[0::4, :], x[1::4, :], x[2::4, :], x[3::4, :]
    s0, s1, d0, d1 = x0 + x3, x1 + x2, x0 - x3, x1 - x2
    rows = (s0 + s1, 2 * d0 + d1, s0 - s1, d0 - 2 * d1)
    out = [[None] * 4 for _ in range(4)]
    for i, r in enumerate(rows):
        c0, c1, c2, c3 = r[:, 0::4], r[:, 1::4], r[:, 2::4], r[:, 3::4]
        s0, s1, d0, d1 = c0 + c3, c1 + c2, c0 - c3, c1 - c2
        out[i] = [s0 + s1, 2 * d0 + d1, s0 - s1, d0 - 2 * d1]
    return out


def inv4_planes(d):
    """Spec 8.5.12.2 inverse (horizontal first, >>1 truncations exact)
    WITHOUT the final (x+32)>>6. d and result are 4x4 plane lists."""
    f = [None] * 4
    for i in range(4):
        e0 = d[i][0] + d[i][2]
        e1 = d[i][0] - d[i][2]
        e2 = (d[i][1] >> 1) - d[i][3]
        e3 = d[i][1] + (d[i][3] >> 1)
        f[i] = [e0 + e3, e1 + e2, e1 - e2, e0 - e3]
    out = [[None] * 4 for _ in range(4)]
    for j in range(4):
        g0 = f[0][j] + f[2][j]
        g1 = f[0][j] - f[2][j]
        g2 = (f[1][j] >> 1) - f[3][j]
        g3 = f[1][j] + (f[3][j] >> 1)
        out[0][j], out[1][j] = g0 + g3, g1 + g2
        out[2][j], out[3][j] = g1 - g2, g0 - g3
    return out


def _clip1(x):
    return jnp.clip(x, 0, 255)


def _merge_planes(planes, bh: int, bw: int):
    """bh x bw nested plane list (h, w) -> interleaved (h*bh, w*bw)."""
    h, w = planes[0][0].shape
    rows = []
    for i in range(bh):
        rows.append(jnp.stack(planes[i], axis=-1).reshape(h, w * bw))
    return jnp.stack(rows, axis=1).reshape(h * bh, w * bw)


def _grid_rm(plane, bh: int, bw: int):
    """(h*bh, w*bw) block-grid plane -> bh x bw list of (h, w) slices."""
    return [[plane[i::bh, j::bw] for j in range(bw)] for i in range(bh)]


# ---------------------------------------------------------------------------
# quant / dequant on planes (qp broadcastable to the plane shape)
# ---------------------------------------------------------------------------

def _quant_plane(w, qp, cls: int, fdiv: int):
    """level = clamp(sign * ((|w| * MF[qp%6, cls] + (1<<qbits)//fdiv)
    >> qbits)); fdiv=3 intra, 6 inter."""
    qbits = 15 + qp // 6
    mf = _MF_J[qp % 6, cls]
    f = jnp.left_shift(jnp.int32(1), qbits) // fdiv
    mag = (jnp.abs(w) * mf + f) >> qbits
    return jnp.clip(jnp.where(w < 0, -mag, mag), -LEVEL_CLAMP, LEVEL_CLAMP)


def _dequant_plane(c, qp, cls: int):
    """Spec 8.5.12.1 AC rescale, elementwise."""
    ls = 16 * _V_J[qp % 6, cls]
    t = qp // 6
    hi = jnp.left_shift(c * ls, jnp.maximum(t - 4, 0))
    lo = (c * ls + jnp.left_shift(jnp.int32(1), jnp.maximum(3 - t, 0))) \
        >> jnp.maximum(4 - t, 0)
    return jnp.where(t >= 4, hi, lo)


def _quant_dc_e(y, qp):
    qbits = 15 + qp // 6
    mf00 = _MF_J[qp % 6, 0]
    f2 = 2 * (jnp.left_shift(jnp.int32(1), qbits) // 3)
    mag = (jnp.abs(y) * mf00 + f2) >> (qbits + 1)
    return jnp.clip(jnp.where(y < 0, -mag, mag), -LEVEL_CLAMP, LEVEL_CLAMP)


def _dequant_ldc_e(f, qp):
    ls00 = 16 * _V_J[qp % 6, 0]
    t = qp // 6
    hi = jnp.left_shift(f * ls00, jnp.maximum(t - 6, 0))
    lo = (f * ls00 + jnp.left_shift(jnp.int32(1), jnp.maximum(5 - t, 0))) \
        >> jnp.maximum(6 - t, 0)
    return jnp.where(t >= 6, hi, lo)


def _dequant_cdc_e(f, qpc):
    ls00 = 16 * _V_J[qpc % 6, 0]
    return jnp.left_shift(f * ls00, qpc // 6) >> 5


# ---------------------------------------------------------------------------
# CAVLC over block-grid planes
# ---------------------------------------------------------------------------

def cavlc_events_planes(scan, nc, chroma_dc: bool = False):
    """``scan``: stacked (mc, ...) levels in scan order (a list of planes
    is stacked on entry). ``nc``: context plane (ignored for chroma_dc).
    Returns (pay (S, ...) uint32, nb (S, ...) int32, tc plane) with the
    slot layout of ops/h264_encode.cavlc_block_events: [coeff_token,
    3 signs, mc levels, total_zeros, mc-1 runs].

    Structured for trace size as much as runtime: coding order comes from
    one one-hot rank reduction (fused by XLA, never materialised), and
    the two genuinely sequential slot chains (level suffix_len, run_before
    zeros_left) are lax.scans — the whole builder traces to ~100 eqns
    where the per-slot formulation took ~2.7k and blew compile time."""
    if isinstance(scan, (list, tuple)):
        scan = jnp.stack(scan)
    mc = scan.shape[0]
    nz = scan != 0
    nzi = nz.astype(jnp.int32)
    tc = nzi.sum(0)

    # coding order (nonzeros by descending position) via suffix ranks:
    # rank[k] = #nonzeros at positions > k; the coded index of a nonzero
    # at scan position k IS rank[k]. One reverse cumsum, no sort.
    rank = jnp.cumsum(nzi[::-1], axis=0)[::-1] - nzi
    kidx = jnp.arange(mc, dtype=jnp.int32)
    kb = kidx.reshape((mc,) + (1,) * (scan.ndim - 1))
    # one-hot selection, contracted immediately (XLA fuses; nothing
    # (mc, mc, ...) ever lands in memory)
    oh = (rank[None] == kb[:, None]) & nz[None]      # (i, k, ...)
    lv = jnp.sum(jnp.where(oh, scan[None], 0), axis=1)
    pv = jnp.sum(jnp.where(oh, kb[None, :], 0), axis=1)

    # trailing ones: run of initial |1| values, capped at 3
    runmask = jnp.cumprod((jnp.abs(lv) == 1).astype(jnp.int32), axis=0)
    t1 = jnp.minimum(jnp.sum(runmask * (kb < tc[None]), axis=0), 3)

    # --- coeff_token
    if chroma_dc:
        ct_pay, ct_nb = _lut(_CDC_PACK, t1 * 5 + tc)
    else:
        ctx = jnp.where(nc < 2, 0, jnp.where(nc < 4, 1,
                        jnp.where(nc < 8, 2, 3)))
        ct_pay, ct_nb = _lut(_CT_PACK, (ctx * 4 + t1) * 17 + tc)

    # --- trailing one signs
    sidx = kb[:3]
    sign_pay = (lv[:3] < 0).astype(jnp.uint32)
    sign_nb = jnp.where(sidx < t1[None], 1, 0)

    # --- levels: lax.scan over coded index j carrying suffix_len.
    # lv[t1 + j] with t1 in 0..3 = a 4-slot dynamic window over a padded
    # stack (old code's clip() semantics are gate-equivalent: padded
    # reads happen only when the slot is inactive).
    lv_pad = jnp.concatenate([lv, jnp.zeros((3,) + lv.shape[1:],
                                            lv.dtype)], axis=0)

    def lv_step(suffix_len, j):
        win = jax.lax.dynamic_slice_in_dim(lv_pad, j, 4, axis=0)
        level = jnp.where(t1 == 0, win[0],
                          jnp.where(t1 == 1, win[1],
                                    jnp.where(t1 == 2, win[2], win[3])))
        active = (t1 + j) < tc
        level_code = jnp.where(level > 0, 2 * level - 2, -2 * level - 1)
        level_code = jnp.where((j == 0) & (t1 < 3), level_code - 2,
                               level_code)
        p, n = _level_event(level_code, suffix_len)
        new_sl = jnp.maximum(suffix_len, 1)
        new_sl = jnp.where(
            (jnp.abs(level) > (3 << jnp.maximum(new_sl - 1, 0)))
            & (new_sl < 6), new_sl + 1, new_sl)
        suffix_len = jnp.where(active, new_sl, suffix_len)
        return suffix_len, (jnp.where(active, p, 0).astype(jnp.uint32),
                            jnp.where(active, n, 0))

    sl0 = jnp.where((tc > 10) & (t1 < 3), 1, 0)
    _, (lvl_pay, lvl_nb) = jax.lax.scan(lv_step, sl0, kidx)

    # --- total_zeros
    last_pos = pv[0]
    tz = jnp.where(tc > 0, last_pos + 1 - tc, 0)
    if chroma_dc:
        tz_pay, tz_nb = _lut(
            _TZC_PACK, jnp.clip(tc - 1, 0, 2) * 4 + jnp.clip(tz, 0, 3))
    else:
        tz_pay, tz_nb = _lut(
            _TZ_PACK, jnp.clip(tc - 1, 0, 14) * 16 + jnp.clip(tz, 0, 15))
    tz_active = (tc > 0) & (tc < mc)
    tz_pay = jnp.where(tz_active, tz_pay, 0).astype(jnp.uint32)
    tz_nb = jnp.where(tz_active, tz_nb, 0)

    # --- run_before: lax.scan over coded index carrying zeros_left
    pv_pad = jnp.concatenate([pv, jnp.zeros((1,) + pv.shape[1:],
                                            pv.dtype)], axis=0)

    def rb_step(zeros_left, i):
        pair = jax.lax.dynamic_slice_in_dim(pv_pad, i, 2, axis=0)
        active = (i < tc - 1) & (zeros_left > 0)
        run = jnp.clip(pair[0] - pair[1] - 1, 0, 14)
        zl = jnp.clip(jnp.minimum(zeros_left, 7) - 1, 0, 6)
        rb_pay, rb_nb = _lut(_RB_PACK, zl * 15 + run)
        out = (jnp.where(active, rb_pay, 0).astype(jnp.uint32),
               jnp.where(active, rb_nb, 0))
        zeros_left = jnp.where(i < tc - 1, zeros_left - run, zeros_left)
        return zeros_left, out

    _, (rb_pay, rb_nb) = jax.lax.scan(rb_step, tz, kidx[:mc - 1])

    shp = tc.shape
    pay = jnp.concatenate([
        ct_pay[None], jnp.broadcast_to(sign_pay, (3,) + shp),
        lvl_pay, tz_pay[None], rb_pay], axis=0)
    nb = jnp.concatenate([
        ct_nb[None], jnp.broadcast_to(sign_nb, (3,) + shp),
        lvl_nb, tz_nb[None], rb_nb], axis=0)
    return pay, nb.astype(jnp.int32), tc


def _nc_planes(tc_eff, mb_bw: int):
    """nC context per block on an (nby, nbx) grid where each MB spans
    ``mb_bw`` block columns/rows. Left neighbour is simply grid col-1
    (in-MB and left-MB cases coincide); top is grid row-1 but only WITHIN
    the MB (one slice per MB row: cross-MB-row blocks are cross-slice,
    hence unavailable — §8.1.3 via h264_encode._nc_from_counts)."""
    nby, nbx = tc_eff.shape
    col = jax.lax.broadcasted_iota(jnp.int32, (nby, nbx), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (nby, nbx), 0)
    na = jnp.pad(tc_eff[:, :-1], ((0, 0), (1, 0)))
    nb_ = jnp.pad(tc_eff[:-1, :], ((1, 0), (0, 0)))
    a_avail = col > 0
    b_avail = (row % mb_bw) > 0
    both = a_avail & b_avail
    return jnp.where(both, (na + nb_ + 1) >> 1,
                     jnp.where(a_avail, na,
                               jnp.where(b_avail, nb_, 0)))


# ---------------------------------------------------------------------------
# event sink: every slot class appends (row, [mb,] offset, payload, nbits)
# tensors with PER-MB-RELATIVE bit offsets (prefix events are relative to
# the row start, tail events to the MB body end). Placement against the
# row layout happens inside pack(): either ONE pair of scatter-adds
# (default) or the hierarchical bit-merge (SELKIES_PACKER=bitmerge,
# PERF.md lever 2) — per-MB word stacks merged over log2(M) dense rounds.
# The relative-offset restructure is exactly what lets the split-frame
# sharded path pack each shard's rows locally and join at the seam.
# ---------------------------------------------------------------------------

class _EventSink:
    def __init__(self, R: int, M: int, w_cap: int):
        self.R, self.M, self.w_cap = R, M, w_cap
        self.prefix_items = []   # (row, off-in-row, pay, nb)
        self.mb_items = []       # (row, mb, off-in-mb, pay, nb)
        self.tail_items = []     # (row, off-past-body, pay, nb)
        self._prefix_bits = None
        self._mb_bits = None
        self._tail_bits = None

    @staticmethod
    def _flat(*args):
        shp = jnp.broadcast_shapes(*(jnp.shape(a) for a in args))
        return [jnp.broadcast_to(a, shp).reshape(-1) for a in args]

    def add_prefix(self, row, off, pay, nb):
        """Row-prefix events; ``off`` is relative to the ROW start."""
        r, o, p, n = self._flat(row, off, pay, nb)
        self.prefix_items.append((r, o, p.astype(jnp.uint32),
                                  n.astype(jnp.int32)))

    def add_mb(self, row, mb, off, pay, nb):
        """MB-body events; ``off`` is relative to THAT MB's start."""
        r, m, o, p, n = self._flat(row, mb, off, pay, nb)
        self.mb_items.append((r, m, o, p.astype(jnp.uint32),
                              n.astype(jnp.int32)))

    def add_tail(self, row, off, pay, nb):
        """Row-tail events; ``off`` is relative to the MB body END."""
        r, o, p, n = self._flat(row, off, pay, nb)
        self.tail_items.append((r, o, p.astype(jnp.uint32),
                                n.astype(jnp.int32)))

    def set_layout(self, prefix_bits, mb_bits, tail_bits):
        """Per-row prefix bits (R,), per-MB body bits (R, M), per-row
        tail bits (R,) — the only global knowledge pack() needs."""
        self._prefix_bits = prefix_bits
        self._mb_bits = mb_bits
        self._tail_bits = tail_bits

    # -- strategy helpers ---------------------------------------------------
    @staticmethod
    def _contribs(off, pay, nb):
        """(hi, lo, straddles) word contributions of events at ``off``
        relative to some word-aligned base."""
        active = nb > 0
        rel = (off & 31).astype(jnp.int32)
        sh = 32 - (rel + nb)
        pay = jnp.where(active, pay, 0)
        hi = jnp.where(sh >= 0,
                       jnp.left_shift(pay, jnp.clip(sh, 0, 31)
                                      .astype(jnp.uint32)),
                       jnp.right_shift(pay, jnp.clip(-sh, 0, 31)
                                       .astype(jnp.uint32)))
        hi = jnp.where(active, hi, 0)
        lo = jnp.where((sh < 0) & active,
                       jnp.left_shift(pay, jnp.clip(32 + sh, 0, 31)
                                      .astype(jnp.uint32)), 0)
        return hi, lo, sh < 0

    @staticmethod
    def _scatter(n_words, w0, straddle, hi, lo, active):
        oob = n_words
        w0_t = jnp.where(active, w0, oob)
        w1_t = jnp.where(active & straddle, w0 + 1, oob)
        words = jnp.zeros((n_words,), jnp.uint32)
        words = words.at[w0_t].add(hi, mode="drop")
        words = words.at[w1_t].add(lo, mode="drop")
        return words

    def _resolved(self, mb_start, body_end):
        """Every item as (row, absolute-off-in-row, pay, nb)."""
        out = [(r, o, p, n) for (r, o, p, n) in self.prefix_items]
        for (r, m, o, p, n) in self.mb_items:
            out.append((r, mb_start[r, m] + o, p, n))
        for (r, o, p, n) in self.tail_items:
            out.append((r, body_end[r] + o, p, n))
        return out

    def _pack_scatter(self, mb_start, body_end):
        R, w_cap = self.R, self.w_cap
        items = self._resolved(mb_start, body_end)
        row = jnp.concatenate([i[0] for i in items])
        off = jnp.concatenate([i[1] for i in items])
        pay = jnp.concatenate([i[2] for i in items])
        nb = jnp.concatenate([i[3] for i in items])
        goff = row * (w_cap * 32) + off
        hi, lo, straddle = self._contribs(goff, pay, nb)
        words = self._scatter(R * w_cap, (goff >> 5).astype(jnp.int32),
                              straddle, hi, lo, nb > 0)
        return words.reshape(R, w_cap)

    def _pack_bitmerge(self):
        """Hierarchical bit-merge materialisation: per-MB word stacks
        built from the MB-RELATIVE offsets (locality-bounded scatter),
        then log2(M) pairwise dense merges per row, then the prefix and
        tail stacks joined at the seams. Bit-exact with the scatter
        strategy."""
        from .bitpack import hierarchical_merge, merge_bit_stacks
        R, M, w_cap = self.R, self.M, self.w_cap

        def stack_cap(items, groups):
            slots = sum(int(i[-1].size) for i in items) // groups
            return max(1, slots)

        # per-MB stacks: offsets are MB-relative, so the scatter index of
        # every event is bounded inside its own mb_cap-word stack
        mb_cap = stack_cap(self.mb_items, R * M)
        row = jnp.concatenate([i[0] for i in self.mb_items])
        mb = jnp.concatenate([i[1] for i in self.mb_items])
        off = jnp.concatenate([i[2] for i in self.mb_items])
        pay = jnp.concatenate([i[3] for i in self.mb_items])
        nb = jnp.concatenate([i[4] for i in self.mb_items])
        hi, lo, straddle = self._contribs(off, pay, nb)
        w0 = (row * M + mb) * mb_cap + (off >> 5).astype(jnp.int32)
        stacks = self._scatter(R * M * mb_cap, w0, straddle, hi, lo,
                               nb > 0).reshape(R, M, mb_cap)
        body, body_bits = hierarchical_merge(stacks, self._mb_bits, w_cap)

        def edge_stack(items, bits):
            cap = stack_cap(items, R)
            row = jnp.concatenate([i[0] for i in items])
            off = jnp.concatenate([i[1] for i in items])
            pay = jnp.concatenate([i[2] for i in items])
            nb = jnp.concatenate([i[3] for i in items])
            hi, lo, straddle = self._contribs(off, pay, nb)
            w0 = row * cap + (off >> 5).astype(jnp.int32)
            return self._scatter(R * cap, w0, straddle, hi, lo,
                                 nb > 0).reshape(R, cap), bits

        pre, pre_bits = edge_stack(self.prefix_items, self._prefix_bits)
        words, bits = merge_bit_stacks(pre, pre_bits, body, body_bits,
                                       w_cap)
        tail, tail_bits = edge_stack(self.tail_items, self._tail_bits)
        words, _ = merge_bit_stacks(words, bits, tail, tail_bits, w_cap)
        return words

    def pack(self):
        """-> (words (R, w_cap) uint32, n_events (R,) int32,
        total_bits (R,) int32)."""
        assert self._mb_bits is not None, "set_layout() before pack()"
        R = self.R
        prefix_bits = self._prefix_bits
        mb_bits = self._mb_bits
        mb_start = prefix_bits[:, None] \
            + jnp.cumsum(mb_bits, axis=1) - mb_bits
        body_end = prefix_bits + jnp.sum(mb_bits, axis=1)
        total_bits = body_end + self._tail_bits

        from .bitpack import packer_name
        if packer_name() == "bitmerge":
            words = self._pack_bitmerge()
        else:
            words = self._pack_scatter(mb_start, body_end)

        n_ev = jnp.zeros((R,), jnp.int32)
        for items in (self.prefix_items, self.tail_items):
            for it in items:
                n_ev = n_ev.at[it[0]].add(
                    (it[-1] > 0).astype(jnp.int32), mode="drop")
        for it in self.mb_items:
            n_ev = n_ev.at[it[0]].add(
                (it[-1] > 0).astype(jnp.int32), mode="drop")
        return words, n_ev, total_bits.astype(jnp.int32)


# ---------------------------------------------------------------------------
# shared frame-level pieces
# ---------------------------------------------------------------------------

def rgb_to_yuv420(rgb):
    H, W = rgb.shape[0], rgb.shape[1]
    ycc = rgb_to_ycbcr(rgb, "bt601-full")
    yf = jnp.clip(jnp.round(ycc[..., 0]), 0, 255).astype(jnp.int32)

    def sub2(p):
        return jnp.clip(jnp.round(
            p.reshape(H // 2, 2, W // 2, 2).mean(axis=(1, 3))),
            0, 255).astype(jnp.int32)
    return yf, sub2(ycc[..., 1]), sub2(ycc[..., 2])


def _had2_parts(x00, x01, x10, x11):
    a, b = x00 + x01, x00 - x01
    c, d = x10 + x11, x10 - x11
    return a + c, b + d, a - c, b - d


def _expand(p, fy: int, fx: int):
    """(R, M)-ish plane -> block grid by repeating fy x fx."""
    return jnp.repeat(jnp.repeat(p, fy, axis=0), fx, axis=1)


_SCAN_ORDER = ((0, 0), (0, 1), (1, 0), (1, 1), (0, 2), (0, 3), (1, 2),
               (1, 3), (2, 0), (2, 1), (3, 0), (3, 1), (2, 2), (2, 3),
               (3, 2), (3, 3))


def _row_of_blocks(nby, nbx, per_mb: int):
    """Block-grid plane of MB-row indices."""
    return jax.lax.broadcasted_iota(jnp.int32, (nby, nbx), 0) // per_mb


def _col_of_blocks(nby, nbx, per_mb: int):
    """Block-grid plane of MB-column indices (the sink's mb axis)."""
    return jax.lax.broadcasted_iota(jnp.int32, (nby, nbx), 1) // per_mb


def _mb_cols(R, M):
    """(1, R, M)-broadcastable MB-column index plane."""
    return jnp.arange(M, dtype=jnp.int32)[None, None, :]


# ---------------------------------------------------------------------------
# I path
# ---------------------------------------------------------------------------

def h264_encode_yuv(yf, uf, vf, qp, header_pay, header_nb,
                    e_cap: int, w_cap: int,
                    idr_pic_id=0, want_recon: bool = False):
    """Plane-layout twin of ops/h264_encode.h264_encode_yuv — same
    signature, bit-identical output."""
    H, W = yf.shape[0], yf.shape[1]
    assert H % 16 == 0 and W % 16 == 0
    R, M = H // 16, W // 16
    nby, nbx = H // 4, W // 4
    qp = jnp.broadcast_to(jnp.asarray(qp, jnp.int32), (R,))
    qpc = _QPC_J[jnp.clip(qp, 0, 51)]
    qp_by = jnp.repeat(qp, 4)[:, None]          # (nby, 1) luma block rows
    qpc_by = jnp.repeat(qpc, 2)[:, None]        # (H/8, 1) chroma block rows

    # ---- transforms + quant, all planes
    wy = fwd4_planes(yf.astype(jnp.int32))
    wu = fwd4_planes(uf.astype(jnp.int32))
    wv = fwd4_planes(vf.astype(jnp.int32))

    def quant_all(w, qp_b, fdiv):
        return [[_quant_plane(w[i][j], qp_b, _POS_CLS[i][j], fdiv)
                 for j in range(4)] for i in range(4)]
    acl_y = quant_all(wy, qp_by, 3)
    acl_u = quant_all(wu, qpc_by, 3)
    acl_v = quant_all(wv, qpc_by, 3)

    # zigzag scans with DC removed (slot 0 zeroed)
    zero_y = jnp.zeros((nby, nbx), jnp.int32)
    scan_y = [acl_y[i][j] if k else zero_y
              for k, (i, j) in enumerate(_ZZ_IJ)]
    zero_c = jnp.zeros((H // 8, W // 8), jnp.int32)
    scan_u = [acl_u[i][j] if k else zero_c
              for k, (i, j) in enumerate(_ZZ_IJ)]
    scan_v = [acl_v[i][j] if k else zero_c
              for k, (i, j) in enumerate(_ZZ_IJ)]

    # ---- AC dequant + inverse right-edge contribution for the DC scan
    def deq_all(acl, qp_b):
        return [[_dequant_plane(
            acl[i][j] if (i, j) != (0, 0) else jnp.zeros_like(acl[0][0]),
            qp_b, _POS_CLS[i][j]) for j in range(4)] for i in range(4)]
    d_y = deq_all(acl_y, qp_by)
    d_u = deq_all(acl_u, qpc_by)
    d_v = deq_all(acl_v, qpc_by)
    inv_y = inv4_planes(d_y)
    inv_u = inv4_planes(d_u)
    inv_v = inv4_planes(d_v)
    # luma right edge: bx=3 blocks' column 3 -> (R, by, M, 4 rows)
    inv_y_edge = jnp.stack(
        [inv_y[i][3][:, 3::4].reshape(R, 4, M) for i in range(4)],
        axis=-1)                                     # (R, by, M, 4)
    # chroma right edge: bx2=1 blocks' column 3 -> (R, comp, by2, M, 4)
    inv_c_edge = jnp.stack([
        jnp.stack([inv_u[i][3][:, 1::2].reshape(R, 2, M)
                   for i in range(4)], axis=-1),
        jnp.stack([inv_v[i][3][:, 1::2].reshape(R, 2, M)
                   for i in range(4)], axis=-1)], axis=1)

    # ---- DC values -> the (small) sequential left-edge scan, reused
    # verbatim from the original decomposition
    dc_y = wy[0][0].reshape(R, 4, M, 4)              # (R, by, M, bx)
    dc_c = jnp.stack([wu[0][0].reshape(R, 2, M, 2),
                      wv[0][0].reshape(R, 2, M, 2)], axis=1)
    dc_lvls, cdc_lvls, preds_y, preds_c = _dc_scan(
        R, M, dc_y, dc_c, inv_y_edge, inv_c_edge, qp, qpc)

    # ---- cbp / counts / nC on the block grid
    nz_y = sum((s != 0).astype(jnp.int32) for s in scan_y)   # = tc per blk
    any_y_mb = jnp.any((nz_y > 0).reshape(R, 4, M, 4), axis=(1, 3))
    cbp_luma = any_y_mb                                      # (R, M) bool
    nz_u = sum((s != 0).astype(jnp.int32) for s in scan_u)
    nz_v = sum((s != 0).astype(jnp.int32) for s in scan_v)
    has_cac = jnp.any(((nz_u + nz_v) > 0).reshape(R, 2, M, 2), axis=(1, 3))
    has_cdc = jnp.any(cdc_lvls != 0, axis=(-1, -2, -3))
    cbp_chroma = jnp.where(has_cac, 2, jnp.where(has_cdc, 1, 0))  # (R, M)

    gate_y = _expand(cbp_luma, 4, 4)
    tc_y_eff = jnp.where(gate_y, nz_y, 0)
    nc_y = _nc_planes(tc_y_eff, 4)
    gate_c = _expand(cbp_chroma == 2, 2, 2)
    nc_u = _nc_planes(jnp.where(gate_c, nz_u, 0), 2)
    nc_v = _nc_planes(jnp.where(gate_c, nz_v, 0), 2)

    # ---- events (each class one stacked (S, ...) pair)
    dc_scan_l = [dc_lvls.reshape(R, M, 16)[..., int(z)] for z in ZIGZAG4]
    dpay, dnb, _ = cavlc_events_planes(dc_scan_l, nc_y[0::4, 0::4])
    ypay, ynb, _ = cavlc_events_planes(scan_y[1:], nc_y)
    ynb = jnp.where(gate_y[None], ynb, 0)
    cdc_u = [cdc_lvls[:, :, 0, 0, 0], cdc_lvls[:, :, 0, 0, 1],
             cdc_lvls[:, :, 0, 1, 0], cdc_lvls[:, :, 0, 1, 1]]
    cdc_v = [cdc_lvls[:, :, 1, 0, 0], cdc_lvls[:, :, 1, 0, 1],
             cdc_lvls[:, :, 1, 1, 0], cdc_lvls[:, :, 1, 1, 1]]
    cdc_gate = cbp_chroma > 0
    upay_dc, unb_dc, _ = cavlc_events_planes(cdc_u, None, chroma_dc=True)
    vpay_dc, vnb_dc, _ = cavlc_events_planes(cdc_v, None, chroma_dc=True)
    unb_dc = jnp.where(cdc_gate[None], unb_dc, 0)
    vnb_dc = jnp.where(cdc_gate[None], vnb_dc, 0)
    upay, unb, _ = cavlc_events_planes(scan_u[1:], nc_u)
    vpay, vnb, _ = cavlc_events_planes(scan_v[1:], nc_v)
    unb = jnp.where(gate_c[None], unb, 0)
    vnb = jnp.where(gate_c[None], vnb, 0)

    # ---- MB header events
    mb_type = 3 + 4 * cbp_chroma + jnp.where(cbp_luma, 12, 0)
    h_pay0, h_nb0 = _ue_event(mb_type)
    one_u = jnp.ones((R, M), jnp.uint32)
    one_n = jnp.ones((R, M), jnp.int32)
    hdr_pays = jnp.stack([h_pay0, one_u, one_u])
    hdr_nbs = jnp.stack([h_nb0, one_n, one_n])

    # ---- slice header prefix + device tail events (per row)
    idr = jnp.broadcast_to(jnp.asarray(idr_pic_id, jnp.int32), (R,))
    idr_pay, idr_nb = _ue_event(idr)
    dqp = qp - 26
    qp_pay, qp_nb = _ue_event(jnp.where(dqp > 0, 2 * dqp - 1, -2 * dqp))
    row_pays = jnp.stack([header_pay[:, 0].astype(jnp.uint32),
                          header_pay[:, 1].astype(jnp.uint32),
                          idr_pay, jnp.zeros((R,), jnp.uint32), qp_pay,
                          jnp.full((R,), 2, jnp.uint32)])
    row_nbs = jnp.stack([header_nb[:, 0].astype(jnp.int32),
                         header_nb[:, 1].astype(jnp.int32),
                         idr_nb, jnp.full((R,), 2, jnp.int32), qp_nb,
                         jnp.full((R,), 3, jnp.int32)])

    out = _assemble_frame(
        R, M, w_cap, e_cap, row_pays, row_nbs,
        hdr_pays, hdr_nbs, dpay, dnb, ypay, ynb,
        upay_dc, unb_dc, vpay_dc, vnb_dc, upay, unb, vpay, vnb)

    if not want_recon:
        return out
    # ---- decoder-exact full recon (DC terms recomputed in parallel)
    f_all = _had4_mb(dc_lvls)                        # (R, M, 4, 4)
    dcY_all = _dequant_ldc_e(f_all, qp[:, None, None, None])
    dcY_plane = _merge_planes(
        [[dcY_all[:, :, i, j] for j in range(4)] for i in range(4)], 4, 4)
    # dcY_plane rows interleave MBs: shape (4R, 4M) == (nby/... careful:
    # merge of (R, M) planes gives (4R, 4M) = block grid. OK.
    pred_plane = _expand(preds_y, 4, 4)
    rec_y = [[_clip1(pred_plane
                     + ((inv_y[i][j] + dcY_plane + 32) >> 6))
              for j in range(4)] for i in range(4)]
    recon_y = _merge_planes(rec_y, 4, 4)
    # chroma: preds_c (R, M, comp, by2); DC from cdc_lvls
    f2 = _had2_mb(cdc_lvls)                          # (R, M, 2, 2, 2)
    dcC = _dequant_cdc_e(f2, qpc[:, None, None, None, None])
    recon_u = _merge_pixel_chroma(inv_u, dcC, preds_c, 0, R, M)
    recon_v = _merge_pixel_chroma(inv_v, dcC, preds_c, 1, R, M)
    return out, (recon_y.astype(jnp.uint8), recon_u.astype(jnp.uint8),
                 recon_v.astype(jnp.uint8))


def _merge_pixel_chroma(inv_c, dcC, preds_c, comp, R, M):
    """Chroma recon (H/2, W/2) from inverse planes + per-block DC +
    per-half preds."""
    # per-block DC plane on the (H/8, W/8) block grid
    dcC_pl = _merge_planes(
        [[dcC[:, :, comp, i, j] for j in range(2)] for i in range(2)], 2, 2)
    pred_pl = _merge_planes(
        [[preds_c[:, :, comp, i] for _ in range(2)] for i in range(2)],
        2, 2)
    rec = [[_clip1(pred_pl + ((inv_c[i][j] + dcC_pl + 32) >> 6))
            for j in range(4)] for i in range(4)]
    return _merge_planes(rec, 4, 4)


def _had4_mb(dc_lvls):
    """(R, M, 4, 4) -> H . X . H (tiny per-MB tensors)."""
    h4 = jnp.asarray(np.array([[1, 1, 1, 1], [1, 1, -1, -1],
                               [1, -1, -1, 1], [1, -1, 1, -1]], np.int32))
    return jnp.einsum("ij,rmjk,kl->rmil", h4, dc_lvls, h4)


def _had2_mb(cdc_lvls):
    """(R, M, comp, 2, 2) -> H2 X H2 per MB."""
    x00, x01 = cdc_lvls[..., 0, 0], cdc_lvls[..., 0, 1]
    x10, x11 = cdc_lvls[..., 1, 0], cdc_lvls[..., 1, 1]
    a, b, c, d = _had2_parts(x00, x01, x10, x11)
    return jnp.stack([jnp.stack([a, b], -1), jnp.stack([c, d], -1)], -2)


def _dc_scan(R, M, dc_y, dc_c, inv_y_edge, inv_c_edge, qp, qpc):
    """The sequential DC/left-edge pipeline (identical math to
    ops/h264_encode.h264_encode_yuv's scan; small tensors only)."""
    h4 = jnp.asarray(np.array([[1, 1, 1, 1], [1, 1, -1, -1],
                               [1, -1, -1, 1], [1, -1, 1, -1]], np.int32))

    def step(carry, k):
        edge_y, edge_c = carry
        first = k == 0
        pred_y = jnp.where(first, 128, (edge_y.sum(-1) + 8) >> 4)
        dcm = dc_y[:, :, k, :] - 16 * pred_y[:, None, None]
        hd = jnp.einsum("ij,rjk,kl->ril", h4, dcm, h4) >> 1
        dlvl = _quant_dc_e(hd, qp[:, None, None])
        f = jnp.einsum("ij,rjk,kl->ril", h4, dlvl, h4)
        dcY = _dequant_ldc_e(f, qp[:, None, None])
        new_edge_y = _clip1(
            pred_y[:, None, None]
            + ((inv_y_edge[:, :, k, :] + dcY[:, :, 3:4] + 32) >> 6)
        ).reshape(R, 16)
        pt = jnp.where(first, 128, (edge_c[..., 0:4].sum(-1) + 2) >> 2)
        pb = jnp.where(first, 128, (edge_c[..., 4:8].sum(-1) + 2) >> 2)
        pred_c = jnp.stack([pt, pb], axis=-1)
        dcmc = dc_c[:, :, :, k, :] - 16 * pred_c[..., None]
        a, b, c_, d = _had2_parts(dcmc[..., 0, 0], dcmc[..., 0, 1],
                                  dcmc[..., 1, 0], dcmc[..., 1, 1])
        hd2 = jnp.stack([jnp.stack([a, b], -1), jnp.stack([c_, d], -1)], -2)
        qpc3 = qpc[:, None, None, None]
        clvl = _quant_dc_e(hd2, qpc3)
        a, b, c_, d = _had2_parts(clvl[..., 0, 0], clvl[..., 0, 1],
                                  clvl[..., 1, 0], clvl[..., 1, 1])
        f2 = jnp.stack([jnp.stack([a, b], -1), jnp.stack([c_, d], -1)], -2)
        dcC = _dequant_cdc_e(f2, qpc3)
        new_edge_c = _clip1(
            pred_c[..., None]
            + ((inv_c_edge[:, :, :, k, :] + dcC[..., 1:2] + 32) >> 6)
        ).reshape(R, 2, 8)
        return (new_edge_y, new_edge_c), (dlvl, clvl, pred_y, pred_c)

    anchor = 0 * dc_y[:, 0, 0, 0]
    init = (jnp.zeros((R, 16), jnp.int32) + anchor[:, None],
            jnp.zeros((R, 2, 8), jnp.int32) + anchor[:, None, None])
    _, (dc_lvls, cdc_lvls, preds_y, preds_c) = jax.lax.scan(
        step, init, jnp.arange(M, dtype=jnp.int32))
    return (jnp.moveaxis(dc_lvls, 0, 1), jnp.moveaxis(cdc_lvls, 0, 1),
            jnp.moveaxis(preds_y, 0, 1), jnp.moveaxis(preds_c, 0, 1))


# ---------------------------------------------------------------------------
# frame assembly (shared I): offsets + sink
# ---------------------------------------------------------------------------

def _excl_cumsum0(nb):
    """Exclusive per-slot bit offsets along the stacked slot axis."""
    return jnp.cumsum(nb, axis=0) - nb


def _assemble_frame(R, M, w_cap, e_cap, row_pays, row_nbs,
                    hdr_pays, hdr_nbs, dpay, dnb, ypay, ynb,
                    upay_dc, unb_dc, vpay_dc, vnb_dc,
                    upay, unb, vpay, vnb):
    """I-frame slot order: row prefix | per MB [hdr(3), lumaDC(36),
    16 luma AC blocks in scan order (34 each), u DC(12), v DC(12),
    8 chroma AC (34 each)] | stop bit. Every event class arrives as one
    stacked (S, ...) pair; offsets are one MB-RELATIVE cumsum per class
    (the sink resolves or merges placement — never this function)."""
    nby, nbx = 4 * R, 4 * M
    cby, cbx = 2 * R, 2 * M

    # per-block/per-MB bit totals
    y_bits_blk = ynb.sum(0)                          # (nby, nbx)
    y_bits_rm = _grid_rm(y_bits_blk, 4, 4)           # (R, M) each
    dc_bits = dnb.sum(0)                             # (R, M)
    hdr_bits = hdr_nbs.sum(0)
    udc_bits = unb_dc.sum(0)
    vdc_bits = vnb_dc.sum(0)
    u_bits_rm = _grid_rm(unb.sum(0), 2, 2)
    v_bits_rm = _grid_rm(vnb.sum(0), 2, 2)

    y_mb = sum(y_bits_rm[i][j] for i, j in _SCAN_ORDER)
    c_mb = (udc_bits + vdc_bits
            + sum(u_bits_rm[i][j] for i in range(2) for j in range(2))
            + sum(v_bits_rm[i][j] for i in range(2) for j in range(2)))
    mb_bits = hdr_bits + dc_bits + y_mb + c_mb       # (R, M)

    prefix_bits = row_nbs.sum(0)                     # (R,)

    sink = _EventSink(R, M, w_cap)
    rows_r = jnp.arange(R, dtype=jnp.int32)
    sink.add_prefix(rows_r[None], _excl_cumsum0(row_nbs),
                    row_pays, row_nbs)

    row_rm = rows_r[None, :, None]
    mb_rm = _mb_cols(R, M)
    sink.add_mb(row_rm, mb_rm, _excl_cumsum0(hdr_nbs), hdr_pays, hdr_nbs)
    dc_base = hdr_bits                               # MB-relative
    sink.add_mb(row_rm, mb_rm, dc_base[None] + _excl_cumsum0(dnb),
                dpay, dnb)

    # luma AC blocks: per-(by,bx) scan-order starts on the block grid
    starts_rm = [[None] * 4 for _ in range(4)]
    acc = dc_base + dc_bits
    for (i, j) in _SCAN_ORDER:
        starts_rm[i][j] = acc
        acc = acc + y_bits_rm[i][j]
    start_plane = _merge_planes(starts_rm, 4, 4)     # (nby, nbx)
    row_blk = _row_of_blocks(nby, nbx, 4)
    col_blk = _col_of_blocks(nby, nbx, 4)
    sink.add_mb(row_blk[None], col_blk[None],
                start_plane[None] + _excl_cumsum0(ynb), ypay, ynb)

    # chroma DC blocks (u then v), then chroma AC (u raster, v raster)
    cdc_base = acc                                   # after all luma blocks
    sink.add_mb(row_rm, mb_rm, cdc_base[None] + _excl_cumsum0(unb_dc),
                upay_dc, unb_dc)
    vdc_base = cdc_base + udc_bits
    sink.add_mb(row_rm, mb_rm, vdc_base[None] + _excl_cumsum0(vnb_dc),
                vpay_dc, vnb_dc)

    cac_base = vdc_base + vdc_bits
    u_starts = [[None] * 2 for _ in range(2)]
    acc_c = cac_base
    for i in range(2):
        for j in range(2):
            u_starts[i][j] = acc_c
            acc_c = acc_c + u_bits_rm[i][j]
    v_starts = [[None] * 2 for _ in range(2)]
    for i in range(2):
        for j in range(2):
            v_starts[i][j] = acc_c
            acc_c = acc_c + v_bits_rm[i][j]
    row_cblk = _row_of_blocks(cby, cbx, 2)
    col_cblk = _col_of_blocks(cby, cbx, 2)
    sink.add_mb(row_cblk[None], col_cblk[None],
                _merge_planes(u_starts, 2, 2)[None] + _excl_cumsum0(unb),
                upay, unb)
    sink.add_mb(row_cblk[None], col_cblk[None],
                _merge_planes(v_starts, 2, 2)[None] + _excl_cumsum0(vnb),
                vpay, vnb)

    # rbsp stop bit (tail-relative offset 0)
    sink.add_tail(rows_r, jnp.zeros((R,), jnp.int32),
                  jnp.ones((R,), jnp.uint32), jnp.ones((R,), jnp.int32))

    sink.set_layout(prefix_bits, mb_bits, jnp.ones((R,), jnp.int32))
    words, n_ev, total_bits = sink.pack()
    overflow = jnp.any((n_ev > e_cap) | (total_bits > w_cap * 32))
    return H264FrameOut(words, total_bits, overflow, R)


# ---------------------------------------------------------------------------
# P path
# ---------------------------------------------------------------------------

def h264_encode_p_yuv(yf, uf, vf, ref_y, ref_u, ref_v, qp,
                      header_pay, header_nb, frame_num,
                      e_cap: int, w_cap: int,
                      candidates: tuple = ((0, 0),),
                      stripe_rows: int | None = None,
                      precomputed_motion=None, qp_mb=None):
    """Plane-layout twin of ops/h264_encode.h264_encode_p_yuv — same
    signature, bit-identical output (P_Skip / P_L0_16x16 with motion,
    one slice per MB row). ``precomputed_motion`` =
    (pred_y, pred_u, pred_v, mv) skips the in-function motion search —
    the split-frame sharded path selects motion against HALO rows first
    (parallel/stripes) and feeds the residual coder here.

    ``qp_mb`` (ROI QP): an optional (R, M) int32 per-macroblock QP
    plane. The slice header still carries the per-row base ``qp``;
    per-MB targets are reached through real ``mb_qp_delta`` syntax (se
    against the previous residual-carrying MB's QP — §7.4.5's carry
    chain, which per-row slices reset), and quant/dequant/recon all run
    at the per-MB value. None leaves every stock code path untouched
    (the always-ue(0) delta)."""
    H, W = yf.shape[0], yf.shape[1]
    R, M = H // 16, W // 16
    nby, nbx = H // 4, W // 4
    qp = jnp.broadcast_to(jnp.asarray(qp, jnp.int32), (R,))
    qpc = _QPC_J[jnp.clip(qp, 0, 51)]
    fn = jnp.broadcast_to(jnp.asarray(frame_num, jnp.int32), (R,))
    if qp_mb is None:
        qp_by = jnp.repeat(qp, 4)[:, None]
        qpc_by = jnp.repeat(qpc, 2)[:, None]
        qpc_rm = qpc[:, None]                        # (R, 1) for (R, M)
    else:
        qp_mb = jnp.asarray(qp_mb, jnp.int32)        # (R, M)
        qp_by = _expand(qp_mb, 4, 4)                 # (nby, nbx)
        qpc_rm = _QPC_J[jnp.clip(qp_mb, 0, 51)]      # (R, M)
        qpc_by = _expand(qpc_rm, 2, 2)               # (H/8, W/8)

    cur_y = yf.astype(jnp.int32)
    cur_u = uf.astype(jnp.int32)
    cur_v = vf.astype(jnp.int32)
    rfy = ref_y.astype(jnp.int32)
    rfu = ref_u.astype(jnp.int32)
    rfv = ref_v.astype(jnp.int32)

    if precomputed_motion is not None:
        pred_y, pred_u, pred_v, mv = precomputed_motion
        pred_y = pred_y.astype(jnp.int32)
        pred_u = pred_u.astype(jnp.int32)
        pred_v = pred_v.astype(jnp.int32)
    elif len(candidates) > 1:
        win = 16 * (stripe_rows if stripe_rows else R)
        assert H % win == 0, "stripe_rows must tile the frame"
        pred_y, pred_u, pred_v, mv = _motion_select(
            cur_y, rfy, rfu, rfv, qp, candidates, win)
    else:
        pred_y, pred_u, pred_v = rfy, rfu, rfv
        mv = jnp.zeros((R, M, 2), jnp.int32)

    # ---- residual transforms + quant (planes)
    wy = fwd4_planes(cur_y - pred_y)
    wu = fwd4_planes(cur_u - pred_u)
    wv = fwd4_planes(cur_v - pred_v)

    def quant_all(w, qp_b):
        return [[_quant_plane(w[i][j], qp_b, _POS_CLS[i][j], 6)
                 for j in range(4)] for i in range(4)]
    acl_y = quant_all(wy, qp_by)                     # full 16, DC included
    acl_u = quant_all(wu, qpc_by)
    acl_v = quant_all(wv, qpc_by)

    scan_y = [acl_y[i][j] for (i, j) in _ZZ_IJ]
    zero_c = jnp.zeros((H // 8, W // 8), jnp.int32)
    scan_u = [acl_u[i][j] if k else zero_c
              for k, (i, j) in enumerate(_ZZ_IJ)]   # AC only (DC separate)
    scan_v = [acl_v[i][j] if k else zero_c
              for k, (i, j) in enumerate(_ZZ_IJ)]

    # ---- chroma DC (2x2 hadamard of the W00s, intra-style quant offset)
    def cdc_chain(w00):
        x = [[w00[i::2, j::2] for j in range(2)] for i in range(2)]
        a, b, c, d = _had2_parts(x[0][0], x[0][1], x[1][0], x[1][1])
        hd = [[a, b], [c, d]]
        cl = [[_quant_dc_e(hd[i][j], qpc_rm) for j in range(2)]
              for i in range(2)]
        a, b, c, d = _had2_parts(cl[0][0], cl[0][1], cl[1][0], cl[1][1])
        f2 = [[a, b], [c, d]]
        dc = [[_dequant_cdc_e(f2[i][j], qpc_rm) for j in range(2)]
              for i in range(2)]
        return cl, dc
    clvl_u, dcC_u = cdc_chain(wu[0][0])
    clvl_v, dcC_v = cdc_chain(wv[0][0])

    # ---- cbp / coded / skip (all (R, M))
    nz_y_blk = sum((s != 0) for s in scan_y)         # (nby, nbx) int-ish
    nz_y_blk = nz_y_blk > 0
    g8 = (nz_y_blk[0::2, :] | nz_y_blk[1::2, :])
    g8 = (g8[:, 0::2] | g8[:, 1::2])                 # (2R, 2M) 8x8 groups
    cbp_luma = (g8[0::2, 0::2].astype(jnp.int32)
                | (g8[0::2, 1::2].astype(jnp.int32) << 1)
                | (g8[1::2, 0::2].astype(jnp.int32) << 2)
                | (g8[1::2, 1::2].astype(jnp.int32) << 3))
    nz_u = sum((s != 0).astype(jnp.int32) for s in scan_u)
    nz_v = sum((s != 0).astype(jnp.int32) for s in scan_v)
    has_cac = jnp.any(((nz_u + nz_v) > 0).reshape(R, 2, M, 2), axis=(1, 3))
    has_cdc = sum(jnp.abs(clvl_u[i][j]) + jnp.abs(clvl_v[i][j])
                  for i in range(2) for j in range(2)) > 0
    cbp_chroma = jnp.where(has_cac, 2, jnp.where(has_cdc, 1, 0))
    cbp = cbp_luma | (cbp_chroma << 4)
    mv_nz = (mv[..., 0] != 0) | (mv[..., 1] != 0)
    coded = (cbp != 0) | mv_nz

    # MV predictor = left neighbour (one slice per MB row, §8.4.1.3)
    mvp = jnp.concatenate(
        [jnp.zeros((R, 1, 2), jnp.int32), mv[:, :-1]], axis=1)
    mvd = mv - mvp

    # ---- per-block gates + nC
    colg = jax.lax.broadcasted_iota(jnp.int32, (nby, nbx), 1)
    rowg = jax.lax.broadcasted_iota(jnp.int32, (nby, nbx), 0)
    g8_idx = ((rowg % 4) >> 1) * 2 + ((colg % 4) >> 1)
    grp_bit = (jnp.right_shift(_expand(cbp_luma, 4, 4), g8_idx) & 1) == 1
    coded_blk = _expand(coded, 4, 4)
    blk_on = grp_bit & coded_blk
    tc_y = sum((s != 0).astype(jnp.int32) for s in scan_y)
    nc_y = _nc_planes(jnp.where(blk_on, tc_y, 0), 4)
    gate_c = _expand(cbp_chroma == 2, 2, 2)
    nc_u = _nc_planes(jnp.where(gate_c, nz_u, 0), 2)
    nc_v = _nc_planes(jnp.where(gate_c, nz_v, 0), 2)

    # ---- events (stacked classes)
    ypay, ynb, _ = cavlc_events_planes(scan_y, nc_y)        # 16-coeff
    ynb = jnp.where(blk_on[None], ynb, 0)
    cdc_u_scan = [clvl_u[0][0], clvl_u[0][1], clvl_u[1][0], clvl_u[1][1]]
    cdc_v_scan = [clvl_v[0][0], clvl_v[0][1], clvl_v[1][0], clvl_v[1][1]]
    upay_dc, unb_dc, _ = cavlc_events_planes(cdc_u_scan, None,
                                             chroma_dc=True)
    vpay_dc, vnb_dc, _ = cavlc_events_planes(cdc_v_scan, None,
                                             chroma_dc=True)
    cdc_gate = cbp_chroma > 0
    unb_dc = jnp.where(cdc_gate[None], unb_dc, 0)
    vnb_dc = jnp.where(cdc_gate[None], vnb_dc, 0)
    upay, unb, _ = cavlc_events_planes(scan_u[1:], nc_u)
    vpay, vnb, _ = cavlc_events_planes(scan_v[1:], nc_v)
    unb = jnp.where(gate_c[None], unb, 0)
    vnb = jnp.where(gate_c[None], vnb, 0)

    # ---- recon (decoder-exact)
    def deq_gated(acl, qp_b, gate):
        return [[_dequant_plane(jnp.where(gate, acl[i][j], 0), qp_b,
                                _POS_CLS[i][j])
                 for j in range(4)] for i in range(4)]
    d_y = deq_gated(acl_y, qp_by, blk_on)
    inv_y = inv4_planes(d_y)
    pred_y_pl = [[pred_y[i::4, j::4] for j in range(4)] for i in range(4)]
    rec_y = [[_clip1(pred_y_pl[i][j] + ((inv_y[i][j] + 32) >> 6))
              for j in range(4)] for i in range(4)]
    recon_y = _merge_planes(rec_y, 4, 4)

    def chroma_recon(acl, dcC, pred, gate_ac, gate_dc):
        d = [[_dequant_plane(
            jnp.where(gate_ac, acl[i][j], 0) if (i, j) != (0, 0)
            else jnp.zeros_like(acl[0][0]), qpc_by, _POS_CLS[i][j])
            for j in range(4)] for i in range(4)]
        dc_pl = _merge_planes(
            [[jnp.where(gate_dc, dcC[i][j], 0) for j in range(2)]
             for i in range(2)], 2, 2)
        d[0][0] = dc_pl
        inv = inv4_planes(d)
        pp = [[pred[i::4, j::4] for j in range(4)] for i in range(4)]
        rec = [[_clip1(pp[i][j] + ((inv[i][j] + 32) >> 6))
                for j in range(4)] for i in range(4)]
        return _merge_planes(rec, 4, 4)
    recon_u = chroma_recon(acl_u, dcC_u, pred_u, gate_c, cbp_chroma >= 1)
    recon_v = chroma_recon(acl_v, dcC_v, pred_v, gate_c, cbp_chroma >= 1)

    out = _assemble_p_frame(
        R, M, w_cap, e_cap, qp, fn, header_pay, header_nb,
        cbp, coded, mvd, ypay, ynb, upay_dc, unb_dc, vpay_dc, vnb_dc,
        upay, unb, vpay, vnb, qp_mb=qp_mb)
    return out, (recon_y.astype(jnp.uint8), recon_u.astype(jnp.uint8),
                 recon_v.astype(jnp.uint8))


def _assemble_p_frame(R, M, w_cap, e_cap, qp, fn, header_pay, header_nb,
                      cbp, coded, mvd, ypay, ynb,
                      upay_dc, unb_dc, vpay_dc, vnb_dc,
                      upay, unb, vpay, vnb, qp_mb=None):
    """P slot order: row prefix [hdr(2), frame_num u(4), '000' flags,
    qp, deblock] | per MB [skip_run, mb_type, mvd_x, mvd_y, cbp,
    mb_qp_delta] + residual blocks | trailing skip run | stop bit."""
    nby, nbx = 4 * R, 4 * M
    cby, cbx = 2 * R, 2 * M

    # ---- skip runs (prev coded index via inclusive running max)
    idx = jax.lax.broadcasted_iota(jnp.int32, (R, M), 1)
    marked = jnp.where(coded, idx, -1)
    inclusive = jax.lax.associative_scan(jnp.maximum, marked, axis=1)
    prev_excl = jnp.concatenate(
        [jnp.full((R, 1), -1, jnp.int32), inclusive[:, :-1]], axis=1)
    skip_run = idx - prev_excl - 1
    last_coded = inclusive[:, -1]
    trailing = (M - 1) - last_coded

    # ---- MB header events
    sr_pay, sr_nb = _ue_event(jnp.maximum(skip_run, 0))
    sr_nb = jnp.where(coded, sr_nb, 0)
    mbt_pay = jnp.ones((R, M), jnp.uint32)
    mbt_nb = jnp.where(coded, 1, 0)
    mvdx_pay, mvdx_nb = _se_event(mvd[..., 0])
    mvdx_nb = jnp.where(coded, mvdx_nb, 0)
    mvdy_pay, mvdy_nb = _se_event(mvd[..., 1])
    mvdy_nb = jnp.where(coded, mvdy_nb, 0)
    cbp_pay, cbp_nb = _ue_event(_CBP2CODE_J[cbp])
    cbp_nb = jnp.where(coded, cbp_nb, 0)
    dqp_gate = coded & (cbp != 0)                    # §7.3.5 gate
    if qp_mb is None:
        dqp_pay = jnp.ones((R, M), jnp.uint32)
        dqp_nb = jnp.where(dqp_gate, 1, 0)
    else:
        # ROI QP: the decoder's QP carry chain is slice QP updated at
        # every residual-carrying MB, so the delta reaching MB m's
        # target is against the PREVIOUS delta-carrying MB's target
        # (or the row base for the first one). Previous carrier index
        # via the same running-max trick as the skip runs.
        idxq = jax.lax.broadcasted_iota(jnp.int32, (R, M), 1)
        markedq = jnp.where(dqp_gate, idxq, -1)
        inclq = jax.lax.associative_scan(jnp.maximum, markedq, axis=1)
        prevq = jnp.concatenate(
            [jnp.full((R, 1), -1, jnp.int32), inclq[:, :-1]], axis=1)
        qp_prev = jnp.where(
            prevq >= 0,
            jnp.take_along_axis(qp_mb, jnp.clip(prevq, 0, M - 1), axis=1),
            qp[:, None])
        dqp_pay, dqp_nb = _se_event(qp_mb - qp_prev)
        dqp_nb = jnp.where(dqp_gate, dqp_nb, 0)
    hdr_pays = jnp.stack([sr_pay, mbt_pay, mvdx_pay, mvdy_pay, cbp_pay,
                          dqp_pay])
    hdr_nbs = jnp.stack([sr_nb, mbt_nb, mvdx_nb, mvdy_nb, cbp_nb,
                         dqp_nb])

    # ---- row prefix events
    dqp_h = qp - 26
    qph_pay, qph_nb = _ue_event(jnp.where(dqp_h > 0, 2 * dqp_h - 1,
                                          -2 * dqp_h))
    row_pays = jnp.stack([header_pay[:, 0].astype(jnp.uint32),
                          header_pay[:, 1].astype(jnp.uint32),
                          (fn & 0xF).astype(jnp.uint32),
                          jnp.zeros((R,), jnp.uint32), qph_pay,
                          jnp.full((R,), 2, jnp.uint32)])
    row_nbs = jnp.stack([header_nb[:, 0].astype(jnp.int32),
                         header_nb[:, 1].astype(jnp.int32),
                         jnp.full((R,), 4, jnp.int32),
                         jnp.full((R,), 3, jnp.int32), qph_nb,
                         jnp.full((R,), 3, jnp.int32)])

    # ---- bit totals
    y_bits_rm = _grid_rm(ynb.sum(0), 4, 4)
    hdr_bits = hdr_nbs.sum(0)
    udc_bits = unb_dc.sum(0)
    vdc_bits = vnb_dc.sum(0)
    u_bits_rm = _grid_rm(unb.sum(0), 2, 2)
    v_bits_rm = _grid_rm(vnb.sum(0), 2, 2)
    y_mb = sum(y_bits_rm[i][j] for i, j in _SCAN_ORDER)
    c_mb = (udc_bits + vdc_bits
            + sum(u_bits_rm[i][j] for i in range(2) for j in range(2))
            + sum(v_bits_rm[i][j] for i in range(2) for j in range(2)))
    mb_bits = hdr_bits + y_mb + c_mb

    tr_pay, tr_nb = _ue_event(jnp.maximum(trailing, 0))
    tr_nb = jnp.where(trailing > 0, tr_nb, 0)

    prefix_bits = row_nbs.sum(0)

    sink = _EventSink(R, M, w_cap)
    rows_r = jnp.arange(R, dtype=jnp.int32)
    sink.add_prefix(rows_r[None], _excl_cumsum0(row_nbs),
                    row_pays, row_nbs)

    row_rm = rows_r[None, :, None]
    mb_rm = _mb_cols(R, M)
    sink.add_mb(row_rm, mb_rm, _excl_cumsum0(hdr_nbs), hdr_pays, hdr_nbs)

    starts_rm = [[None] * 4 for _ in range(4)]
    acc = hdr_bits                                   # MB-relative base
    for (i, j) in _SCAN_ORDER:
        starts_rm[i][j] = acc
        acc = acc + y_bits_rm[i][j]
    start_plane = _merge_planes(starts_rm, 4, 4)
    row_blk = _row_of_blocks(nby, nbx, 4)
    col_blk = _col_of_blocks(nby, nbx, 4)
    sink.add_mb(row_blk[None], col_blk[None],
                start_plane[None] + _excl_cumsum0(ynb), ypay, ynb)

    cdc_base = acc
    sink.add_mb(row_rm, mb_rm, cdc_base[None] + _excl_cumsum0(unb_dc),
                upay_dc, unb_dc)
    vdc_base = cdc_base + udc_bits
    sink.add_mb(row_rm, mb_rm, vdc_base[None] + _excl_cumsum0(vnb_dc),
                vpay_dc, vnb_dc)

    cac_base = vdc_base + vdc_bits
    u_starts = [[None] * 2 for _ in range(2)]
    acc_c = cac_base
    for i in range(2):
        for j in range(2):
            u_starts[i][j] = acc_c
            acc_c = acc_c + u_bits_rm[i][j]
    v_starts = [[None] * 2 for _ in range(2)]
    for i in range(2):
        for j in range(2):
            v_starts[i][j] = acc_c
            acc_c = acc_c + v_bits_rm[i][j]
    row_cblk = _row_of_blocks(cby, cbx, 2)
    col_cblk = _col_of_blocks(cby, cbx, 2)
    sink.add_mb(row_cblk[None], col_cblk[None],
                _merge_planes(u_starts, 2, 2)[None] + _excl_cumsum0(unb),
                upay, unb)
    sink.add_mb(row_cblk[None], col_cblk[None],
                _merge_planes(v_starts, 2, 2)[None] + _excl_cumsum0(vnb),
                vpay, vnb)

    # trailing skip run at tail offset 0, stop bit right after it
    sink.add_tail(rows_r, jnp.zeros((R,), jnp.int32), tr_pay, tr_nb)
    sink.add_tail(rows_r, tr_nb, jnp.ones((R,), jnp.uint32),
                  jnp.ones((R,), jnp.int32))

    sink.set_layout(prefix_bits, mb_bits, tr_nb + 1)
    words, n_ev, total_bits = sink.pack()
    overflow = jnp.any((n_ev > e_cap) | (total_bits > w_cap * 32))
    return H264FrameOut(words, total_bits, overflow, R)
