"""Device-side 4:4:4 (fullcolor) H.264 — High 4:4:4 Predictive, CAVLC,
in the same TPU plane layout as ops/h264_planes.

The reference streams fullcolor by negotiating profile-level-id f4001f
and letting x264/NVENC emit Hi444PP (reference src/selkies/rtc.py:649-717,
settings.py fullcolor rows). Here the codec itself goes 4:4:4: with
ChromaArrayType == 3 each chroma component is coded EXACTLY like luma
(§7.3.5.3 residual_luma per component, per-component nC contexts, no
intra_chroma_pred_mode, the single I_16x16 AC flag / inter cbp group
bits covering all three components) — so this module is mostly the luma
half of h264_planes instantiated three times over full-resolution
planes, sharing its transforms, CAVLC event builder and event sink.

Oracle chain: bit-exact vs codecs/h264.I444Encoder / P444Encoder
(tests/test_h264_444.py), which are themselves byte-exact under
libavcodec's Hi444PP decoder — including the ChromaArrayType-3 me(v)
coded_block_pattern mapping that was derived empirically against ffmpeg
(h264_tables.CBP444_INTER_CBP2CODE).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..codecs import h264_tables as HT
from .colorspace import rgb_to_ycbcr
from .h264_encode import (H264FrameOut, LEVEL_CLAMP, _se_event, _ue_event,
                          _motion_select)
from .h264_planes import (_EventSink, _clip1, _col_of_blocks,
                          _dequant_plane, _expand,
                          _excl_cumsum0, _grid_rm, _mb_cols, _merge_planes,
                          _quant_dc_e, _dequant_ldc_e, _quant_plane,
                          _row_of_blocks, _SCAN_ORDER, cavlc_events_planes,
                          fwd4_planes, inv4_planes)
from .h264_transform import _POS_CLS, _QPC, ZIGZAG4

_QPC_J = jnp.asarray(_QPC)
_ZZ_IJ = [(int(z) // 4, int(z) % 4) for z in ZIGZAG4]
_CBP444_J = jnp.asarray(HT.CBP444_INTER_CBP2CODE)
_H4_NP = np.array([[1, 1, 1, 1], [1, 1, -1, -1],
                   [1, -1, -1, 1], [1, -1, 1, -1]], np.int32)

# per-MB slot budget: hdr [mb_type, qp_delta] + 3 x (DC block 36 +
# 16 AC blocks x 34); P: 6 hdr slots + 3 x 16 full blocks x 36
SLOTS_BLK16 = 1 + 3 + 16 + 1 + 15
SLOTS_BLK15 = 1 + 3 + 15 + 1 + 14
SLOTS_MB_444 = 2 + 3 * (SLOTS_BLK16 + 16 * SLOTS_BLK15)
P_SLOTS_MB_444 = 6 + 3 * 16 * SLOTS_BLK16


def rgb_to_yuv444(rgb):
    """(H, W, 3) uint8 -> three full-resolution int32 planes (BT.601
    full-range, same matrix as the 4:2:0 path — fullcolor means no
    subsampling, not a different colour space)."""
    ycc = rgb_to_ycbcr(rgb, "bt601-full")
    return tuple(jnp.clip(jnp.round(ycc[..., i]), 0, 255).astype(jnp.int32)
                 for i in range(3))


def _dc_scan_comp(R, M, dc, inv_edge, qp):
    """Left-edge DC prediction chain for ONE luma-like component
    (the luma half of h264_planes._dc_scan)."""
    h4 = jnp.asarray(_H4_NP)

    def step(carry, k):
        edge = carry
        first = k == 0
        pred = jnp.where(first, 128, (edge.sum(-1) + 8) >> 4)
        dcm = dc[:, :, k, :] - 16 * pred[:, None, None]
        hd = jnp.einsum("ij,rjk,kl->ril", h4, dcm, h4) >> 1
        dlvl = _quant_dc_e(hd, qp[:, None, None])
        f = jnp.einsum("ij,rjk,kl->ril", h4, dlvl, h4)
        dcQ = _dequant_ldc_e(f, qp[:, None, None])
        new_edge = _clip1(
            pred[:, None, None]
            + ((inv_edge[:, :, k, :] + dcQ[:, :, 3:4] + 32) >> 6)
        ).reshape(R, 16)
        return new_edge, (dlvl, pred)

    anchor = 0 * dc[:, 0, 0, 0]
    init = jnp.zeros((R, 16), jnp.int32) + anchor[:, None]
    _, (dc_lvls, preds) = jax.lax.scan(
        step, init, jnp.arange(M, dtype=jnp.int32))
    return jnp.moveaxis(dc_lvls, 0, 1), jnp.moveaxis(preds, 0, 1)


def _comp_intra(plane, qp_by, qp_rows, R, M):
    """Everything parallel for one component of the I path: transforms,
    quant, scans, edge contributions, DC values."""
    w = fwd4_planes(plane)
    acl = [[_quant_plane(w[i][j], qp_by, _POS_CLS[i][j], 3)
            for j in range(4)] for i in range(4)]
    zero = jnp.zeros_like(acl[0][0])
    scan = [acl[i][j] if k else zero
            for k, (i, j) in enumerate(_ZZ_IJ)]
    d = [[_dequant_plane(
        acl[i][j] if (i, j) != (0, 0) else zero,
        qp_by, _POS_CLS[i][j]) for j in range(4)] for i in range(4)]
    inv = inv4_planes(d)
    inv_edge = jnp.stack(
        [inv[i][3][:, 3::4].reshape(R, 4, M) for i in range(4)], axis=-1)
    dc = w[0][0].reshape(R, 4, M, 4)
    dc_lvls, preds = _dc_scan_comp(R, M, dc, inv_edge, qp_rows)
    return scan, inv, dc_lvls, preds


def h264_encode_yuv444(yf, uf, vf, qp, header_pay, header_nb,
                       e_cap: int, w_cap: int,
                       idr_pic_id=0, want_recon: bool = False):
    """Full-resolution YUV int planes -> per-MB-row Hi444PP slice RBSPs.
    Same contract as h264_planes.h264_encode_yuv; bit-identical to the
    golden I444Encoder."""
    H, W = yf.shape[0], yf.shape[1]
    assert H % 16 == 0 and W % 16 == 0
    R, M = H // 16, W // 16
    nby, nbx = H // 4, W // 4
    qp = jnp.broadcast_to(jnp.asarray(qp, jnp.int32), (R,))
    qpc = _QPC_J[jnp.clip(qp, 0, 51)]
    qp_by = jnp.repeat(qp, 4)[:, None]
    qpc_by = jnp.repeat(qpc, 4)[:, None]

    comps = []
    for plane, qb, qr in ((yf, qp_by, qp), (uf, qpc_by, qpc),
                          (vf, qpc_by, qpc)):
        comps.append(_comp_intra(plane.astype(jnp.int32), qb, qr, R, M))

    # shared AC flag across all three components
    nz = [sum((s != 0).astype(jnp.int32) for s in scan)
          for (scan, _, _, _) in comps]
    any_mb = [jnp.any((n > 0).reshape(R, 4, M, 4), axis=(1, 3))
              for n in nz]
    cbp_luma = any_mb[0] | any_mb[1] | any_mb[2]        # (R, M)
    gate = _expand(cbp_luma, 4, 4)

    # per-component events
    from .h264_planes import _nc_planes
    ev = []
    for ci, (scan, _, dc_lvls, _) in enumerate(comps):
        nc = _nc_planes(jnp.where(gate, nz[ci], 0), 4)
        dc_scan_l = [dc_lvls.reshape(R, M, 16)[..., int(z)]
                     for z in ZIGZAG4]
        dpay, dnb, _ = cavlc_events_planes(dc_scan_l, nc[0::4, 0::4])
        apay, anb, _ = cavlc_events_planes(scan[1:], nc)
        anb = jnp.where(gate[None], anb, 0)
        ev.append((dpay, dnb, apay, anb))

    # MB header: ue(mb_type), se(0) qp_delta — NO intra_chroma_pred_mode
    mb_type = 3 + jnp.where(cbp_luma, 12, 0)
    h_pay0, h_nb0 = _ue_event(mb_type)
    one_u = jnp.ones((R, M), jnp.uint32)
    hdr_pays = jnp.stack([h_pay0, one_u])
    hdr_nbs = jnp.stack([h_nb0, jnp.ones((R, M), jnp.int32)])

    # row prefix (identical to the 4:2:0 I path)
    idr = jnp.broadcast_to(jnp.asarray(idr_pic_id, jnp.int32), (R,))
    idr_pay, idr_nb = _ue_event(idr)
    dqp = qp - 26
    qp_pay, qp_nb = _ue_event(jnp.where(dqp > 0, 2 * dqp - 1, -2 * dqp))
    row_pays = jnp.stack([header_pay[:, 0].astype(jnp.uint32),
                          header_pay[:, 1].astype(jnp.uint32),
                          idr_pay, jnp.zeros((R,), jnp.uint32), qp_pay,
                          jnp.full((R,), 2, jnp.uint32)])
    row_nbs = jnp.stack([header_nb[:, 0].astype(jnp.int32),
                         header_nb[:, 1].astype(jnp.int32),
                         idr_nb, jnp.full((R,), 2, jnp.int32), qp_nb,
                         jnp.full((R,), 3, jnp.int32)])

    out = _assemble_444(R, M, w_cap, e_cap, row_pays, row_nbs,
                        hdr_pays, hdr_nbs, ev)
    if not want_recon:
        return out

    recons = []
    for ci, (scan, inv, dc_lvls, preds) in enumerate(comps):
        qr = qp if ci == 0 else qpc
        h4 = jnp.asarray(_H4_NP)
        f_all = jnp.einsum("ij,rmjk,kl->rmil", h4, dc_lvls, h4)
        dcQ = _dequant_ldc_e(f_all, qr[:, None, None, None])
        dc_pl = _merge_planes(
            [[dcQ[:, :, i, j] for j in range(4)] for i in range(4)], 4, 4)
        pred_pl = _expand(preds, 4, 4)
        rec = [[_clip1(pred_pl + ((inv[i][j] + dc_pl + 32) >> 6))
                for j in range(4)] for i in range(4)]
        recons.append(_merge_planes(rec, 4, 4).astype(jnp.uint8))
    return out, tuple(recons)


def _assemble_444(R, M, w_cap, e_cap, row_pays, row_nbs,
                  hdr_pays, hdr_nbs, ev):
    """Slot order per MB: hdr | per comp [DC block, 16 AC blocks in scan
    order] | ... | stop bit. Offsets are MB-relative (per-MB-relative
    restructure, PERF.md lever 2); the sink resolves placement."""
    nby, nbx = 4 * R, 4 * M
    hdr_bits = hdr_nbs.sum(0)
    comp_dc_bits = [e[1].sum(0) for e in ev]                # (R, M)
    comp_ac_rm = [_grid_rm(e[3].sum(0), 4, 4) for e in ev]  # (R, M) grids
    comp_ac_mb = [sum(rm[i][j] for i, j in _SCAN_ORDER)
                  for rm in comp_ac_rm]
    mb_bits = hdr_bits + sum(comp_dc_bits) + sum(comp_ac_mb)

    prefix_bits = row_nbs.sum(0)

    sink = _EventSink(R, M, w_cap)
    rows_r = jnp.arange(R, dtype=jnp.int32)
    sink.add_prefix(rows_r[None], _excl_cumsum0(row_nbs),
                    row_pays, row_nbs)
    row_rm = rows_r[None, :, None]
    mb_rm = _mb_cols(R, M)
    sink.add_mb(row_rm, mb_rm, _excl_cumsum0(hdr_nbs), hdr_pays, hdr_nbs)

    row_blk = _row_of_blocks(nby, nbx, 4)
    col_blk = _col_of_blocks(nby, nbx, 4)
    base = hdr_bits
    for ci, (dpay, dnb, apay, anb) in enumerate(ev):
        sink.add_mb(row_rm, mb_rm, base[None] + _excl_cumsum0(dnb),
                    dpay, dnb)
        base = base + comp_dc_bits[ci]
        starts_rm = [[None] * 4 for _ in range(4)]
        acc = base
        for (i, j) in _SCAN_ORDER:
            starts_rm[i][j] = acc
            acc = acc + comp_ac_rm[ci][i][j]
        start_pl = _merge_planes(starts_rm, 4, 4)
        sink.add_mb(row_blk[None], col_blk[None],
                    start_pl[None] + _excl_cumsum0(anb), apay, anb)
        base = acc

    sink.add_tail(rows_r, jnp.zeros((R,), jnp.int32),
                  jnp.ones((R,), jnp.uint32), jnp.ones((R,), jnp.int32))
    sink.set_layout(prefix_bits, mb_bits, jnp.ones((R,), jnp.int32))
    words, n_ev, total_bits = sink.pack()
    overflow = jnp.any((n_ev > e_cap) | (total_bits > w_cap * 32))
    return H264FrameOut(words, total_bits, overflow, R)


# ---------------------------------------------------------------------------
# P path
# ---------------------------------------------------------------------------

def _motion_select444(cur_y, rfy, rfu, rfv, qp, candidates, win):
    """Luma-SAD candidate selection as in h264_planes, but chroma rides
    the SAME full-pel shift at full resolution (no eighth-sample
    interpolation in 4:4:4 with full-pel luma vectors)."""
    from .h264_encode import (_MV_LAMBDA, _hshift, _sad_mb16, _vshift,
                              se_bits)
    H, W = cur_y.shape
    R, M = H // 16, W // 16
    S = H // win
    ry_w = rfy.reshape(S, win, W)
    ru_w = rfu.reshape(S, win, W)
    rv_w = rfv.reshape(S, win, W)
    lam = _MV_LAMBDA[jnp.clip(qp, 0, 51)]

    shifted_y, shifted_u, shifted_v, costs = [], [], [], []
    for dy, dx in candidates:
        shy = _hshift(_vshift(ry_w, dy), dx).reshape(H, W)
        shifted_y.append(shy)
        shifted_u.append(_hshift(_vshift(ru_w, dy), dx).reshape(H, W))
        shifted_v.append(_hshift(_vshift(rv_w, dy), dx).reshape(H, W))
        sad = _sad_mb16(jnp.abs(cur_y - shy))
        bits = se_bits(4 * dx) + se_bits(4 * dy)
        costs.append(sad + lam[:, None] * bits)
    sel = jnp.argmin(jnp.stack(costs), axis=0).astype(jnp.int32)
    sel_pix = jnp.broadcast_to(sel[:, None, :, None],
                               (R, 16, M, 16)).reshape(H, W)
    pred_y, pred_u, pred_v = shifted_y[0], shifted_u[0], shifted_v[0]
    for k in range(1, len(candidates)):
        pred_y = jnp.where(sel_pix == k, shifted_y[k], pred_y)
        pred_u = jnp.where(sel_pix == k, shifted_u[k], pred_u)
        pred_v = jnp.where(sel_pix == k, shifted_v[k], pred_v)
    cand_q = jnp.asarray(np.asarray(candidates, np.int32)[:, ::-1] * 4)
    return pred_y, pred_u, pred_v, cand_q[sel]


def h264_encode_p_yuv444(yf, uf, vf, ref_y, ref_u, ref_v, qp,
                         header_pay, header_nb, frame_num,
                         e_cap: int, w_cap: int,
                         candidates: tuple = ((0, 0),),
                         stripe_rows: int | None = None,
                         precomputed_motion=None):
    """4:4:4 P frame: P_Skip / P_L0_16x16, all components luma-style,
    shared cbp group bits, ChromaArrayType-3 me(v) mapping. Returns
    (H264FrameOut, (recon_y, recon_u, recon_v)). ``precomputed_motion``
    = (pred_y, pred_u, pred_v, mv) skips the in-function search (the
    sharded halo path)."""
    H, W = yf.shape[0], yf.shape[1]
    R, M = H // 16, W // 16
    nby, nbx = H // 4, W // 4
    qp = jnp.broadcast_to(jnp.asarray(qp, jnp.int32), (R,))
    qpc = _QPC_J[jnp.clip(qp, 0, 51)]
    fn = jnp.broadcast_to(jnp.asarray(frame_num, jnp.int32), (R,))
    qp_by = jnp.repeat(qp, 4)[:, None]
    qpc_by = jnp.repeat(qpc, 4)[:, None]

    cur = [p.astype(jnp.int32) for p in (yf, uf, vf)]
    rf = [p.astype(jnp.int32) for p in (ref_y, ref_u, ref_v)]

    if precomputed_motion is not None:
        pred_y, pred_u, pred_v, mv = precomputed_motion
        preds = [p.astype(jnp.int32) for p in (pred_y, pred_u, pred_v)]
    elif len(candidates) > 1:
        win = 16 * (stripe_rows if stripe_rows else R)
        assert H % win == 0, "stripe_rows must tile the frame"
        pred_y, pred_u, pred_v, mv = _motion_select444(
            cur[0], rf[0], rf[1], rf[2], qp, candidates, win)
        preds = [pred_y, pred_u, pred_v]
    else:
        preds = rf
        mv = jnp.zeros((R, M, 2), jnp.int32)

    # per-component residual transforms + quant (16-coeff, DC in-block)
    acls, scans = [], []
    for ci in range(3):
        qb = qp_by if ci == 0 else qpc_by
        w = fwd4_planes(cur[ci] - preds[ci])
        acl = [[_quant_plane(w[i][j], qb, _POS_CLS[i][j], 6)
                for j in range(4)] for i in range(4)]
        acls.append(acl)
        scans.append([acl[i][j] for (i, j) in _ZZ_IJ])

    # cbp: group bit g covers the g-th 8x8 region of ALL components
    nz_blk = None
    for scan in scans:
        nzc = sum((s != 0) for s in scan) > 0
        nz_blk = nzc if nz_blk is None else (nz_blk | nzc)
    g8 = (nz_blk[0::2, :] | nz_blk[1::2, :])
    g8 = (g8[:, 0::2] | g8[:, 1::2])                 # (2R, 2M)
    cbp = (g8[0::2, 0::2].astype(jnp.int32)
           | (g8[0::2, 1::2].astype(jnp.int32) << 1)
           | (g8[1::2, 0::2].astype(jnp.int32) << 2)
           | (g8[1::2, 1::2].astype(jnp.int32) << 3))
    mv_nz = (mv[..., 0] != 0) | (mv[..., 1] != 0)
    coded = (cbp != 0) | mv_nz

    mvp = jnp.concatenate(
        [jnp.zeros((R, 1, 2), jnp.int32), mv[:, :-1]], axis=1)
    mvd = mv - mvp

    colg = jax.lax.broadcasted_iota(jnp.int32, (nby, nbx), 1)
    rowg = jax.lax.broadcasted_iota(jnp.int32, (nby, nbx), 0)
    g8_idx = ((rowg % 4) >> 1) * 2 + ((colg % 4) >> 1)
    grp_bit = (jnp.right_shift(_expand(cbp, 4, 4), g8_idx) & 1) == 1
    blk_on = grp_bit & _expand(coded, 4, 4)

    from .h264_planes import _nc_planes
    ev = []
    for ci in range(3):
        tc = sum((s != 0).astype(jnp.int32) for s in scans[ci])
        nc = _nc_planes(jnp.where(blk_on, tc, 0), 4)
        apay, anb, _ = cavlc_events_planes(scans[ci], nc)
        ev.append((apay, jnp.where(blk_on[None], anb, 0)))

    # recon per component
    recons = []
    for ci in range(3):
        qb = qp_by if ci == 0 else qpc_by
        d = [[_dequant_plane(jnp.where(blk_on, acls[ci][i][j], 0), qb,
                             _POS_CLS[i][j])
              for j in range(4)] for i in range(4)]
        inv = inv4_planes(d)
        pp = [[preds[ci][i::4, j::4] for j in range(4)] for i in range(4)]
        rec = [[_clip1(pp[i][j] + ((inv[i][j] + 32) >> 6))
                for j in range(4)] for i in range(4)]
        recons.append(_merge_planes(rec, 4, 4).astype(jnp.uint8))

    out = _assemble_p_444(R, M, w_cap, e_cap, qp, fn, header_pay,
                          header_nb, cbp, coded, mvd, ev)
    return out, tuple(recons)


def _assemble_p_444(R, M, w_cap, e_cap, qp, fn, header_pay, header_nb,
                    cbp, coded, mvd, ev):
    nby, nbx = 4 * R, 4 * M

    idx = jax.lax.broadcasted_iota(jnp.int32, (R, M), 1)
    marked = jnp.where(coded, idx, -1)
    inclusive = jax.lax.associative_scan(jnp.maximum, marked, axis=1)
    prev_excl = jnp.concatenate(
        [jnp.full((R, 1), -1, jnp.int32), inclusive[:, :-1]], axis=1)
    skip_run = idx - prev_excl - 1
    trailing = (M - 1) - inclusive[:, -1]

    sr_pay, sr_nb = _ue_event(jnp.maximum(skip_run, 0))
    sr_nb = jnp.where(coded, sr_nb, 0)
    mbt_pay = jnp.ones((R, M), jnp.uint32)
    mbt_nb = jnp.where(coded, 1, 0)
    mvdx_pay, mvdx_nb = _se_event(mvd[..., 0])
    mvdx_nb = jnp.where(coded, mvdx_nb, 0)
    mvdy_pay, mvdy_nb = _se_event(mvd[..., 1])
    mvdy_nb = jnp.where(coded, mvdy_nb, 0)
    cbp_pay, cbp_nb = _ue_event(_CBP444_J[cbp])
    cbp_nb = jnp.where(coded, cbp_nb, 0)
    dqp_pay = jnp.ones((R, M), jnp.uint32)
    dqp_nb = jnp.where(coded & (cbp != 0), 1, 0)
    hdr_pays = jnp.stack([sr_pay, mbt_pay, mvdx_pay, mvdy_pay, cbp_pay,
                          dqp_pay])
    hdr_nbs = jnp.stack([sr_nb, mbt_nb, mvdx_nb, mvdy_nb, cbp_nb,
                         dqp_nb])

    dqp_h = qp - 26
    qph_pay, qph_nb = _ue_event(jnp.where(dqp_h > 0, 2 * dqp_h - 1,
                                          -2 * dqp_h))
    row_pays = jnp.stack([header_pay[:, 0].astype(jnp.uint32),
                          header_pay[:, 1].astype(jnp.uint32),
                          (fn & 0xF).astype(jnp.uint32),
                          jnp.zeros((R,), jnp.uint32), qph_pay,
                          jnp.full((R,), 2, jnp.uint32)])
    row_nbs = jnp.stack([header_nb[:, 0].astype(jnp.int32),
                         header_nb[:, 1].astype(jnp.int32),
                         jnp.full((R,), 4, jnp.int32),
                         jnp.full((R,), 3, jnp.int32), qph_nb,
                         jnp.full((R,), 3, jnp.int32)])

    hdr_bits = hdr_nbs.sum(0)
    comp_rm = [_grid_rm(anb.sum(0), 4, 4) for _, anb in ev]
    comp_mb = [sum(rm[i][j] for i, j in _SCAN_ORDER) for rm in comp_rm]
    mb_bits = hdr_bits + sum(comp_mb)

    tr_pay, tr_nb = _ue_event(jnp.maximum(trailing, 0))
    tr_nb = jnp.where(trailing > 0, tr_nb, 0)

    prefix_bits = row_nbs.sum(0)

    sink = _EventSink(R, M, w_cap)
    rows_r = jnp.arange(R, dtype=jnp.int32)
    sink.add_prefix(rows_r[None], _excl_cumsum0(row_nbs),
                    row_pays, row_nbs)
    row_rm = rows_r[None, :, None]
    mb_rm = _mb_cols(R, M)
    sink.add_mb(row_rm, mb_rm, _excl_cumsum0(hdr_nbs), hdr_pays, hdr_nbs)

    row_blk = _row_of_blocks(nby, nbx, 4)
    col_blk = _col_of_blocks(nby, nbx, 4)
    base = hdr_bits
    for ci, (apay, anb) in enumerate(ev):
        starts_rm = [[None] * 4 for _ in range(4)]
        acc = base
        for (i, j) in _SCAN_ORDER:
            starts_rm[i][j] = acc
            acc = acc + comp_rm[ci][i][j]
        start_pl = _merge_planes(starts_rm, 4, 4)
        sink.add_mb(row_blk[None], col_blk[None],
                    start_pl[None] + _excl_cumsum0(anb), apay, anb)
        base = acc

    sink.add_tail(rows_r, jnp.zeros((R,), jnp.int32), tr_pay, tr_nb)
    sink.add_tail(rows_r, tr_nb, jnp.ones((R,), jnp.uint32),
                  jnp.ones((R,), jnp.int32))
    sink.set_layout(prefix_bits, mb_bits, tr_nb + 1)
    words, n_ev, total_bits = sink.pack()
    overflow = jnp.any((n_ev > e_cap) | (total_bits > w_cap * 32))
    return H264FrameOut(words, total_bits, overflow, R)
