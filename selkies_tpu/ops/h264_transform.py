"""H.264 4x4 integer transforms, quantisation and rescaling (ITU-T H.264
§8.5) as exact int32 JAX ops.

The TPU half of the h264-tpu encoder (reference equivalent: the H.264
``output_mode`` inside the closed-source Rust pixelflux wheel, SURVEY.md
§2.2). Encoder-side quantisation follows the JM reference formulas; the
DECODER-side operations (rescale + inverse transforms + clipping) follow
the spec bit-exactly — they must, because the encoder reconstructs its own
prediction references with them and any mismatch drifts every decoder on
the planet away from our recon.

All functions are shape-polymorphic over leading batch dims: blocks are
trailing (..., 4, 4) int32 (or (..., 2, 2) for chroma DC).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

# --- tables (spec 8.5.12.1 normAdjust4x4 / JM quant_coef) -------------------
# position classes within a 4x4 block: 0 for (0,0),(0,2),(2,0),(2,2);
# 1 for (1,1),(1,3),(3,1),(3,3); 2 otherwise.
_POS_CLS = np.array([[0, 2, 0, 2],
                     [2, 1, 2, 1],
                     [0, 2, 0, 2],
                     [2, 1, 2, 1]], np.int32)

# MF: encoder quant multipliers, rows qp%6, cols position class (JM).
_MF = np.array([[13107, 5243, 8066],
                [11916, 4660, 7490],
                [10082, 4194, 6554],
                [9362, 3647, 5825],
                [8192, 3355, 5243],
                [7282, 2893, 4559]], np.int32)

# V: decoder rescale multipliers (normAdjust4x4), same indexing.
_V = np.array([[10, 16, 13],
               [11, 18, 14],
               [13, 20, 16],
               [14, 23, 18],
               [16, 25, 20],
               [18, 29, 23]], np.int32)

MF4 = jnp.asarray(_MF[:, _POS_CLS])          # (6, 4, 4)
V4 = jnp.asarray(_V[:, _POS_CLS])            # (6, 4, 4)

# chroma QP mapping (spec table 8-15, chroma_qp_index_offset = 0)
_QPC = np.concatenate([
    np.arange(30),
    np.array([29, 30, 31, 32, 32, 33, 34, 34, 35, 35, 36, 36, 37, 37, 37,
              38, 38, 38, 39, 39, 39, 39])]).astype(np.int32)
QPC_TABLE = jnp.asarray(_QPC)

# zigzag scan for 4x4 blocks (spec 8.5.6)
ZIGZAG4 = np.array([0, 1, 4, 8, 5, 2, 3, 6, 9, 12, 13, 10, 7, 11, 14, 15],
                   np.int32)

_CF = np.array([[1, 1, 1, 1],
                [2, 1, -1, -2],
                [1, -1, -1, 1],
                [1, -2, 2, -1]], np.int32)
_CF_T = _CF.T
_H4 = np.array([[1, 1, 1, 1],
                [1, 1, -1, -1],
                [1, -1, -1, 1],
                [1, -1, 1, -1]], np.int32)


def forward4x4(x: jnp.ndarray) -> jnp.ndarray:
    """Core forward transform W = Cf X Cf^T (exact in int32 for 8-bit
    residuals)."""
    cf = jnp.asarray(_CF)
    cft = jnp.asarray(_CF_T)
    return jnp.einsum("ij,...jk,kl->...il", cf, x.astype(jnp.int32), cft)


def inverse4x4(d: jnp.ndarray) -> jnp.ndarray:
    """Exact inverse core transform (spec 8.5.12.2) WITHOUT the final
    (x+32)>>6 — callers add the DC term first, then shift. The pass order
    (horizontal within rows FIRST, then vertical) is normative: the >>1
    truncations do not commute."""
    d = d.astype(jnp.int32)
    # horizontal (within each row, across columns)
    e0 = d[..., :, 0] + d[..., :, 2]
    e1 = d[..., :, 0] - d[..., :, 2]
    e2 = (d[..., :, 1] >> 1) - d[..., :, 3]
    e3 = d[..., :, 1] + (d[..., :, 3] >> 1)
    f = jnp.stack([e0 + e3, e1 + e2, e1 - e2, e0 - e3], axis=-1)
    # vertical (within each column, across rows)
    g0 = f[..., 0, :] + f[..., 2, :]
    g1 = f[..., 0, :] - f[..., 2, :]
    g2 = (f[..., 1, :] >> 1) - f[..., 3, :]
    g3 = f[..., 1, :] + (f[..., 3, :] >> 1)
    return jnp.stack([g0 + g3, g1 + g2, g1 - g2, g0 - g3], axis=-2)


def hadamard4x4(x: jnp.ndarray) -> jnp.ndarray:
    """H X H^T (used forward on luma DC at the encoder, inverse at the
    decoder — H is its own inverse up to scale)."""
    h = jnp.asarray(_H4)
    return jnp.einsum("ij,...jk,kl->...il", h, x.astype(jnp.int32), h)


def hadamard2x2(x: jnp.ndarray) -> jnp.ndarray:
    a = x[..., 0, 0] + x[..., 0, 1]
    b = x[..., 0, 0] - x[..., 0, 1]
    c = x[..., 1, 0] + x[..., 1, 1]
    d = x[..., 1, 0] - x[..., 1, 1]
    return jnp.stack([jnp.stack([a + c, b + d], axis=-1),
                      jnp.stack([a - c, b - d], axis=-1)], axis=-2)


# --- quantisation (encoder side, JM) ----------------------------------------

def quant4x4(w: jnp.ndarray, qp: jnp.ndarray) -> jnp.ndarray:
    """AC/luma-residual quant: level = sign * ((|W| * MF + f) >> qbits),
    f = (2/3) * 2^qbits for intra."""
    qp = jnp.asarray(qp, jnp.int32)
    qbits = 15 + qp // 6
    mf = MF4[qp % 6]
    f = ((1 << qbits) // 3).astype(jnp.int32) if hasattr(
        (1 << qbits), "astype") else (1 << qbits) // 3
    f = (jnp.left_shift(jnp.int32(1), qbits) // 3)
    mag = (jnp.abs(w) * mf + f) >> qbits
    return jnp.where(w < 0, -mag, mag).astype(jnp.int32)


def quant_dc(y: jnp.ndarray, qp: jnp.ndarray) -> jnp.ndarray:
    """DC (luma 4x4-Hadamard or chroma 2x2-Hadamard) quant:
    level = sign * ((|Y| * MF00 + 2f) >> (qbits + 1))."""
    qp = jnp.asarray(qp, jnp.int32)
    qbits = 15 + qp // 6
    mf00 = MF4[qp % 6, 0, 0]
    f2 = 2 * (jnp.left_shift(jnp.int32(1), qbits) // 3)
    mag = (jnp.abs(y) * mf00 + f2) >> (qbits + 1)
    return jnp.where(y < 0, -mag, mag).astype(jnp.int32)


# --- rescaling (decoder side, spec-exact) -----------------------------------

def dequant4x4_ac(c: jnp.ndarray, qp: jnp.ndarray) -> jnp.ndarray:
    """Spec 8.5.12.1 with flat weightScale (=16): d = (c * 16V) << (qp/6-4)
    for qp>=24, else (c * 16V + 2^(3-qp/6)) >> (4-qp/6). Exact for
    negative c (arithmetic shift on two's complement)."""
    qp = jnp.asarray(qp, jnp.int32)
    ls = 16 * V4[qp % 6]
    t = qp // 6
    hi = jnp.left_shift(c * ls, jnp.maximum(t - 4, 0))
    rnd = jnp.left_shift(jnp.int32(1), jnp.maximum(3 - t, 0))
    lo = (c * ls + rnd) >> jnp.maximum(4 - t, 0)
    return jnp.where(t >= 4, hi, lo).astype(jnp.int32)


def dequant_luma_dc(f: jnp.ndarray, qp: jnp.ndarray) -> jnp.ndarray:
    """Spec 8.5.10: input f = inverse-Hadamard of the DC levels.
    qp>=36: (f*LS00) << (qp/6 - 6); else (f*LS00 + 2^(5-qp/6)) >> (6-qp/6)."""
    qp = jnp.asarray(qp, jnp.int32)
    ls00 = 16 * V4[qp % 6, 0, 0]
    t = qp // 6
    hi = jnp.left_shift(f * ls00, jnp.maximum(t - 6, 0))
    rnd = jnp.left_shift(jnp.int32(1), jnp.maximum(5 - t, 0))
    lo = (f * ls00 + rnd) >> jnp.maximum(6 - t, 0)
    return jnp.where(t >= 6, hi, lo).astype(jnp.int32)


def dequant_chroma_dc(f: jnp.ndarray, qpc: jnp.ndarray) -> jnp.ndarray:
    """Spec 8.5.11 (4:2:0): ((f * LS00) << (qpc/6)) >> 5."""
    qpc = jnp.asarray(qpc, jnp.int32)
    ls00 = 16 * V4[qpc % 6, 0, 0]
    return (jnp.left_shift(f * ls00, qpc // 6) >> 5).astype(jnp.int32)


def chroma_qp(qp: jnp.ndarray) -> jnp.ndarray:
    return QPC_TABLE[jnp.clip(qp, 0, 51)]


def clip1(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.clip(x, 0, 255)
