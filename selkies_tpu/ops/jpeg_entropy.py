"""On-device JPEG Huffman entropy coding.

Turns the quantised zigzag coefficients (still in HBM) into the final
entropy-coded scan bitstream *on the TPU*, using the slot-event reframing
from :mod:`selkies_tpu.ops.bitpack`:

Every (block, zigzag-slot) pair emits at most one codeword, decidable
locally from per-row cumulative statistics — slot order is exactly JPEG
stream order:

- slot 0: the DC codeword (category + value bits), differential against the
  previous same-component block via a precomputed static gather index;
- a nonzero AC slot: the (run%16, size) codeword + value bits;
- a zero AC slot that is the 16th/32nd/48th consecutive zero with a later
  nonzero in the block: a ZRL (0xF0) codeword;
- slot 63 when the last AC nonzero sits before it: the EOB codeword.

The only cross-block dependency (DC prediction) is a gather; the only
cross-event dependency (bit offsets) is a cumsum. No Python/host work
remains on the hot path except trimming the word buffer and 0xFF-stuffing
at bitrate-sized cost.

Reference equivalent: entropy coding inside the Rust pixelflux wheel
(SURVEY.md §2.2); the reframing itself is original to this framework.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..codecs import jpeg as jtab
from .bitpack import (PackedStream, bit_category, default_packer,
                      value_bits)


class ScanLayout(NamedTuple):
    """Static per-(shape, subsampling) gather maps, device-resident."""
    comp: np.ndarray        # (M,) 0=Y 1=Cb 2=Cr in scan order
    gather: np.ndarray      # (M,) block index into the comp's plane array
    prev_same: np.ndarray   # (M,) scan index of previous same-comp block, -1

    @property
    def m(self) -> int:
        return len(self.comp)


@functools.cache
def scan_layout(blocks_h: int, blocks_w: int, subsampling: str) -> ScanLayout:
    comp, gather, _ = jtab._mcu_block_order(blocks_h, blocks_w, subsampling)
    prev_same = np.full(len(comp), -1, dtype=np.int32)
    last = {0: -1, 1: -1, 2: -1}
    for i, c in enumerate(comp):
        prev_same[i] = last[int(c)]
        last[int(c)] = i
    return ScanLayout(comp, gather, prev_same)


@functools.cache
def _host_luts() -> dict[str, np.ndarray]:
    """Huffman LUTs stacked [luma, chroma] (numpy; converted per-trace —
    caching device arrays here would leak tracers across jit traces)."""
    out = {}
    for prefix, kinds in (("dc", ("dc_luma", "dc_chroma")),
                          ("ac", ("ac_luma", "ac_chroma"))):
        codes = np.stack([jtab._huff_lut(k)[0] for k in kinds])
        lens = np.stack([jtab._huff_lut(k)[1].astype(np.int32) for k in kinds])
        out[prefix + "_code"] = codes.astype(np.uint32)
        out[prefix + "_len"] = lens
    return out


def _device_luts() -> dict[str, jnp.ndarray]:
    return {k: jnp.asarray(v) for k, v in _host_luts().items()}


def jpeg_entropy_device(y_zz: jnp.ndarray, cb_zz: jnp.ndarray,
                        cr_zz: jnp.ndarray, layout: ScanLayout,
                        e_cap: int, w_cap: int) -> PackedStream:
    """Entropy-code an interleaved scan fully on device.

    Coefficient arrays are (N, 64) int (zigzag order, plane-raster blocks).
    ``layout`` must come from :func:`scan_layout` for the same shapes.
    """
    luts = _device_luts()
    comp = jnp.asarray(layout.comp)
    gather = jnp.asarray(layout.gather)
    prev_same = jnp.asarray(layout.prev_same)
    is_chroma = (comp != 0).astype(jnp.int32)            # (M,)

    # --- scan-ordered coefficient rows (M, 64) -----------------------------
    y = y_zz.astype(jnp.int32)
    cb = cb_zz.astype(jnp.int32)
    cr = cr_zz.astype(jnp.int32)
    # component planes can have different lengths; gather per component then
    # select (XLA fuses the three gathers + where-chain)
    seq = jnp.where(
        (comp == 0)[:, None], y[jnp.clip(gather, 0, y.shape[0] - 1)],
        jnp.where((comp == 1)[:, None], cb[jnp.clip(gather, 0, cb.shape[0] - 1)],
                  cr[jnp.clip(gather, 0, cr.shape[0] - 1)]))

    m, s = seq.shape
    pos = jax.lax.broadcasted_iota(jnp.int32, (m, s), 1)

    # --- DC events (slot 0) -------------------------------------------------
    dc = seq[:, 0]
    prev_dc = jnp.where(prev_same >= 0, dc[jnp.clip(prev_same, 0, m - 1)], 0)
    dcdiff = dc - prev_dc
    dccat = bit_category(dcdiff, max_cat=11)
    dccode = luts["dc_code"][is_chroma, dccat]
    dclen = luts["dc_len"][is_chroma, dccat]
    dcval = value_bits(dcdiff, dccat)
    dc_payload = jnp.bitwise_or(
        jnp.left_shift(dccode, dccat.astype(jnp.uint32)), dcval)
    dc_nbits = dclen + dccat

    # --- AC run-length statistics along the zigzag axis --------------------
    nz = (seq != 0) & (pos > 0)
    # last position <= j holding a nonzero AC (0 if none): inclusive cummax
    nz_pos = jnp.where(nz, pos, 0)
    incl_cummax = jax.lax.cummax(nz_pos, axis=1)
    prev_nz_excl = jnp.concatenate(
        [jnp.zeros((m, 1), jnp.int32), incl_cummax[:, :-1]], axis=1)
    last_nz = incl_cummax[:, -1:]                         # (M, 1)

    # nonzero AC slots: (run % 16, size) + value bits
    run_total = pos - prev_nz_excl - 1
    accat = bit_category(seq, max_cat=10)
    acsym = jnp.bitwise_and(run_total, 15) * 16 + accat
    accode = luts["ac_code"][is_chroma[:, None], acsym]
    aclen = luts["ac_len"][is_chroma[:, None], acsym]
    acval = value_bits(seq, accat)
    ac_payload = jnp.bitwise_or(
        jnp.left_shift(accode, accat.astype(jnp.uint32)), acval)
    ac_nbits = aclen + accat

    # ZRL slots: the 16th/32nd/48th consecutive zero with a later nonzero
    zeros_since = pos - prev_nz_excl
    is_zrl = (~nz) & (pos > 0) & (pos < last_nz) \
        & (zeros_since > 0) & (jnp.bitwise_and(zeros_since, 15) == 0)
    zrl_payload = luts["ac_code"][is_chroma, 0xF0][:, None]
    zrl_nbits = luts["ac_len"][is_chroma, 0xF0][:, None]

    # EOB at slot 63 when the block's AC tail is zero
    is_eob = (pos == s - 1) & (last_nz < s - 1)
    eob_payload = luts["ac_code"][is_chroma, 0x00][:, None]
    eob_nbits = luts["ac_len"][is_chroma, 0x00][:, None]

    payload = jnp.where(
        pos == 0, dc_payload[:, None],
        jnp.where(nz, ac_payload,
                  jnp.where(is_zrl, zrl_payload,
                            jnp.where(is_eob, eob_payload, 0)))
    ).astype(jnp.uint32)
    nbits = jnp.where(
        pos == 0, dc_nbits[:, None],
        jnp.where(nz, ac_nbits,
                  jnp.where(is_zrl, zrl_nbits,
                            jnp.where(is_eob, eob_nbits, 0))))

    return default_packer()(payload, nbits, e_cap=e_cap, w_cap=w_cap)


def finalize_scan_bytes(words_host: np.ndarray, total_bits: int) -> bytes:
    """Host tail: trim, 1-pad, and 0xFF-stuff the device bitstream."""
    from ..codecs.jpeg import stuff_ff_bytes
    from .bitpack import words_to_bytes

    by = np.frombuffer(words_to_bytes(words_host, total_bits, pad_ones=True),
                       dtype=np.uint8)
    return stuff_ff_bytes(by)
