"""Device-side JPEG forward pipeline: RGB -> quantised zigzag coefficients.

The TPU half of the baseline-JPEG encoder (reference equivalent: the (M)JPEG
``output_mode`` of the Rust pixelflux encoder, SURVEY.md §2.2). The host half
(Huffman entropy coding + JFIF assembly) lives in
:mod:`selkies_tpu.codecs.jpeg`.

Everything here is jit-compatible with static shapes: one compiled executable
per (H, W, subsampling). Quant tables are runtime inputs so quality changes
do NOT retrigger compilation (live-tunable vs structural split — reference
media_pipeline.py:210-320 draws the same line).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .colorspace import rgb_to_ycbcr, split_ycbcr_420
from .dct import dct2d, quantize_zigzag, to_blocks


def jpeg_forward_420(rgb: jnp.ndarray, qy: jnp.ndarray, qc: jnp.ndarray
                     ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(H, W, 3) uint8 RGB -> (Ny,64), (Nc,64), (Nc,64) int16 zigzag coeffs.

    H and W must be multiples of 16. Block order is plane-raster.
    ``qy``/``qc`` are 64-entry raster-order quant tables (float32/int).
    """
    ycc = rgb_to_ycbcr(rgb, "bt601-full")
    y, cb, cr = split_ycbcr_420(ycc)
    out = []
    for plane, q in ((y, qy), (cb, qc), (cr, qc)):
        blocks = to_blocks(plane - 128.0)
        out.append(quantize_zigzag(dct2d(blocks), q))
    return tuple(out)


def jpeg_forward_444(rgb: jnp.ndarray, qy: jnp.ndarray, qc: jnp.ndarray
                     ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """4:4:4 variant (``fullcolor`` setting): H, W multiples of 8."""
    ycc = rgb_to_ycbcr(rgb, "bt601-full")
    out = []
    for ci, q in ((0, qy), (1, qc), (2, qc)):
        blocks = to_blocks(ycc[..., ci] - 128.0)
        out.append(quantize_zigzag(dct2d(blocks), q))
    return tuple(out)


@functools.cache
def jitted_jpeg_forward(subsampling: str = "420"):
    """Compiled forward fn for a fixed subsampling; shapes specialise on
    first call per (H, W). Uses the TPU plane-layout transforms
    (:mod:`.jpeg_planes`), which are verified coefficient-exact against
    the block-layout reference above (tests/test_jpeg.py)."""
    from . import jpeg_planes
    fn = (jpeg_planes.jpeg_forward_420 if subsampling == "420"
          else jpeg_planes.jpeg_forward_444)
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Full on-device encode: RGB -> entropy-coded scan bitstream in HBM.
# Only the w_cap-word buffer + two scalars leave the chip (bitrate-sized),
# which is what makes 1080p60 feasible across a thin host link.
# ---------------------------------------------------------------------------

def jpeg_encode_device(rgb: jnp.ndarray, qy: jnp.ndarray, qc: jnp.ndarray,
                       subsampling: str, e_cap: int, w_cap: int):
    """RGB frame -> PackedStream (scan bits) entirely on device."""
    from . import jpeg_planes
    from .jpeg_entropy import jpeg_entropy_device, scan_layout

    h, w = rgb.shape[:2]
    fwd = (jpeg_planes.jpeg_forward_420 if subsampling == "420"
           else jpeg_planes.jpeg_forward_444)
    y_zz, cb_zz, cr_zz = fwd(rgb, qy, qc)
    layout = scan_layout(h // 8, w // 8, subsampling)
    return jpeg_entropy_device(y_zz, cb_zz, cr_zz, layout,
                               e_cap=e_cap, w_cap=w_cap)


@functools.cache
def jitted_jpeg_encode(subsampling: str, e_cap: int, w_cap: int):
    return jax.jit(functools.partial(jpeg_encode_device,
                                     subsampling=subsampling,
                                     e_cap=e_cap, w_cap=w_cap))
