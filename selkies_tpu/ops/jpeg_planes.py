"""TPU plane-layout JPEG forward transform (PERF.md lever 3).

The original path (:mod:`.dct`) reshapes every plane into ``(N, 8, 8)``
blocks — 8x8 MINOR dims, which XLA:TPU tiles into (8, 128) vector
registers at 1/16th occupancy, so the transform stage moves ~16x the
frame's bytes through HBM (the same layout disaster the H.264 codec had
before :mod:`.h264_planes`). This module is the 8x8 analog of
``fwd4_planes``: spatial position (a, b) of every 8x8 block lives in ONE
``(H/8, W/8)`` plane (minor dims 240x135 at 1080p — full vregs), the 2-D
DCT is 64 scalar-weighted plane FMAs per output coefficient expressed as
two separable 8-term passes, and quantisation + zigzag happen per plane
(zigzag = picking planes in a static order: free).

Output is the same ``(N, 64)`` int16 zigzag contract the entropy stage
consumes (:func:`.jpeg_entropy.jpeg_entropy_device`), produced by one
(64, N) -> (N, 64) transpose of bitrate-light int16 data — the only
layout change that still touches block-minor data, at 2 bytes/coeff
instead of the old path's full float32 transform tensors.

Reference equivalent: the transform stage inside the closed Rust
pixelflux encoder (SURVEY.md §2.2); layout design is original.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np

from .colorspace import rgb_to_ycbcr, split_ycbcr_420
from .dct import dct8_matrix, zigzag_order


@functools.cache
def _zz_ij() -> list[tuple[int, int]]:
    """Zigzag slot k -> (i, j) frequency-plane coordinates."""
    return [(int(z) // 8, int(z) % 8) for z in zigzag_order()]


def _dct_planes(plane: jnp.ndarray) -> list[list[jnp.ndarray]]:
    """(H, W) centered float32 -> 8x8 list of (H/8, W/8) coefficient
    planes: coef[i][j][y, x] = DCT(block (y, x))[i, j].

    Separable: tmp[i][b] = sum_a D[i, a] * X[a][b], then
    coef[i][j] = sum_b D[j, b] * tmp[i][b]. Every term is a scalar *
    full-vreg plane FMA; XLA fuses each 8-term chain into one pass.
    """
    d = np.asarray(dct8_matrix(), np.float32)
    xs = [[plane[a::8, b::8] for b in range(8)] for a in range(8)]
    tmp = [[None] * 8 for _ in range(8)]
    for i in range(8):
        for b in range(8):
            acc = d[i, 0] * xs[0][b]
            for a in range(1, 8):
                acc = acc + d[i, a] * xs[a][b]
            tmp[i][b] = acc
    coef = [[None] * 8 for _ in range(8)]
    for i in range(8):
        for j in range(8):
            acc = d[j, 0] * tmp[i][0]
            for b in range(1, 8):
                acc = acc + d[j, b] * tmp[i][b]
            coef[i][j] = acc
    return coef


def _quant_zigzag_planes(coef, qtable_raster: jnp.ndarray) -> jnp.ndarray:
    """8x8 coefficient planes -> (N, 64) int16 zigzag rows (plane-raster
    block order), matching :func:`.dct.quantize_zigzag` exactly: divide
    by the raster-order table, round half away from zero."""
    qt = qtable_raster.reshape(64).astype(jnp.float32)
    cols = []
    for k, (i, j) in enumerate(_zz_ij()):
        q = coef[i][j] / qt[i * 8 + j]
        cols.append(jnp.trunc(q + jnp.sign(q) * 0.5).astype(jnp.int16))
    # (64, Hb, Wb) -> (Hb, Wb, 64) -> (N, 64): the one block-minor
    # materialisation left, on int16 quantised data (bitrate-sized)
    stack = jnp.stack(cols)
    n = stack.shape[1] * stack.shape[2]
    return jnp.moveaxis(stack, 0, -1).reshape(n, 64)


def _forward_plane(plane: jnp.ndarray, qtable: jnp.ndarray) -> jnp.ndarray:
    return _quant_zigzag_planes(_dct_planes(plane - 128.0), qtable)


def jpeg_forward_420(rgb: jnp.ndarray, qy: jnp.ndarray, qc: jnp.ndarray
                     ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(H, W, 3) uint8 RGB -> (Ny,64), (Nc,64), (Nc,64) int16 zigzag
    coeffs — same contract as :func:`.jpeg_pipeline.jpeg_forward_420`,
    plane-layout transforms."""
    ycc = rgb_to_ycbcr(rgb, "bt601-full")
    y, cb, cr = split_ycbcr_420(ycc)
    return tuple(_forward_plane(p, q)
                 for p, q in ((y, qy), (cb, qc), (cr, qc)))


def jpeg_forward_444(rgb: jnp.ndarray, qy: jnp.ndarray, qc: jnp.ndarray
                     ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """4:4:4 variant (``fullcolor`` setting): H, W multiples of 8."""
    ycc = rgb_to_ycbcr(rgb, "bt601-full")
    return tuple(_forward_plane(ycc[..., ci], q)
                 for ci, q in ((0, qy), (1, qc), (2, qc)))
