"""Device-side stripe-stream concatenation.

Striped encoding (reference SURVEY.md §2.5: each frame is split into row
stripes encoded as independent streams) would naively mean one device->host
readback per stripe per frame. Over a thin host link every readback pays an
RTT, so instead the per-stripe bitstreams are byte-packed into ONE
fixed-capacity buffer on device; the host receives a single
``(out_cap,) uint8`` buffer plus per-stripe byte lengths and slices it.

Each stripe's stream is byte-aligned (JPEG scans and H.264 access units are
byte strings), so this is a byte-level ragged concat: a searchsorted +
gather, the same reframing as ops/bitpack.py one level up.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class FrameBuffer(NamedTuple):
    data: jnp.ndarray       # (out_cap,) uint8 — concatenated stripe bytes
    byte_lens: jnp.ndarray  # (S,) int32 — per-stripe byte length
    overflow: jnp.ndarray   # () bool


def words_to_bytes_device(words: jnp.ndarray, total_bits: jnp.ndarray,
                          pad_ones: bool = True) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(S, Wc) uint32 words + (S,) bit lengths -> (S, Wc*4) uint8 + (S,) byte lens.

    MSB-first within each word; the final partial byte is 1-padded (JPEG
    convention) on device.
    """
    s, wc = words.shape
    shifts = jnp.asarray([24, 16, 8, 0], dtype=jnp.uint32)
    by = jnp.right_shift(words[:, :, None], shifts[None, None, :])
    by = jnp.bitwise_and(by, 0xFF).reshape(s, wc * 4)
    nbytes = (total_bits + 7) // 8
    if pad_ones:
        rem = jnp.mod(total_bits, 8)                       # (S,)
        pad_mask = jnp.where(rem > 0,
                             jnp.left_shift(1, 8 - rem) - 1, 0).astype(jnp.uint32)
        idx = jax.lax.broadcasted_iota(jnp.int32, (s, wc * 4), 1)
        is_last = idx == (nbytes - 1)[:, None]
        by = jnp.where(is_last, jnp.bitwise_or(by, pad_mask[:, None]), by)
    return by.astype(jnp.uint8), nbytes.astype(jnp.int32)


def concat_stripe_bytes(stripe_bytes: jnp.ndarray, byte_lens: jnp.ndarray,
                        out_cap: int) -> FrameBuffer:
    """Ragged byte concat: (S, B) uint8 + (S,) lens -> (out_cap,) uint8.

    Output byte j belongs to stripe b = searchsorted(starts, j) with local
    offset j - starts[b]; bytes past the total are zero.
    """
    s, b = stripe_bytes.shape
    starts = jnp.cumsum(byte_lens) - byte_lens             # (S,) exclusive
    total = jnp.sum(byte_lens)
    j = jnp.arange(out_cap, dtype=jnp.int32)
    sb = jnp.clip(jnp.searchsorted(starts, j, side="right") - 1, 0, s - 1)
    local = jnp.clip(j - starts[sb], 0, b - 1)
    data = jnp.where(j < total, stripe_bytes[sb, local], 0).astype(jnp.uint8)
    return FrameBuffer(data, byte_lens.astype(jnp.int32), total > out_cap)
