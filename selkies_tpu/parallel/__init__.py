"""Device-mesh parallelism: multi-seat fan-out and stripe sharding.

The reference scales out by running N containers, one desktop each
(SURVEY.md §2.5 multi-seat row); the TPU-native design instead shards N
seats over a ``jax.sharding.Mesh`` — one encode dispatch per frame tick
drives every seat's desktop on its own device, collective-free over ICI.
"""

from .h264_seats import MultiSeatH264Encoder
from .seats import MultiSeatEncoder, seat_mesh, synthetic_seat_frames
from .stripes import h264_encode_sharded, stripe_mesh

__all__ = ["MultiSeatEncoder", "MultiSeatH264Encoder", "seat_mesh",
           "synthetic_seat_frames", "h264_encode_sharded", "stripe_mesh"]
