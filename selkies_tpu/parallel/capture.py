"""Multi-seat capture: one device-mesh encode step driving N desktop
displays (the server-side consumer of parallel/seats.py).

API-compatible with engine.capture.ScreenCapture so the WS service can
treat it as just another capture module; emitted chunks carry
``display_id="seat{N}"`` and the service's per-display relays route them
(SURVEY.md §2.5 multi-seat row — the reference scales by running N
containers; here one process + one sharded program serves N seats).

Seat content is synthetic for now (one X display per seat is a deployment
concern — each seat would own an X server in its own namespace); the
encode/fan-out path is the real one.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from ..engine.capture import _ENCODE_TURN, PIPELINE_DEPTH
from ..engine.pipeline import PipelineRing, cause_of, retarget
from ..engine.types import CaptureSettings, EncodedChunk
from ..obs import health as _health
from ..obs.energy import meter as _energy_meter
from ..resilience import faults as _faults
from ..trace import tracer as _tracer
from .h264_seats import MultiSeatH264Encoder
from .seats import MultiSeatEncoder, synthetic_seat_frames

logger = logging.getLogger("selkies_tpu.parallel.capture")


class MultiSeatCapture:
    """ScreenCapture-compatible facade over MultiSeatEncoder."""

    def __init__(self, n_seats: int):
        self.n_seats = n_seats
        self._thread: Optional[threading.Thread] = None
        self._running = threading.Event()
        self._callback: Optional[Callable[[EncodedChunk], None]] = None
        self._settings: Optional[CaptureSettings] = None
        self._enc: Optional[MultiSeatEncoder] = None
        self._force_idr = threading.Event()
        self._cursor_callback = None
        self._api_lock = threading.RLock()
        self.encoded_fps = 0.0
        self.last_frame_bytes = 0
        #: supervision hook (same contract as ScreenCapture.on_death):
        #: called with the exception when the loop DIES, never on stop
        self.on_death: Optional[Callable[[BaseException], None]] = None
        #: runtime frames-in-flight clamp (same contract as
        #: ScreenCapture.set_pipeline_clamp) — written from the loop,
        #: read per tick by the capture thread, so lock-guarded like
        #: ScreenCapture's
        self._lock = threading.Lock()
        self._pipeline_clamp: Optional[int] = None

    # ----------------------------------------------------- reference surface
    def start_capture(self, callback, settings: CaptureSettings) -> None:
        with self._api_lock:
            if self.is_capturing():
                self.stop_capture()
            self._callback = callback
            self._settings = settings
            # the flagship codec rides the flagship axis: honor the
            # configured encoder instead of hard-building jpeg
            cls = MultiSeatH264Encoder if settings.output_mode == "h264" \
                else MultiSeatEncoder
            self._enc = cls(settings, self.n_seats)
            # fresh Event per run (same rationale as ScreenCapture): a
            # thread abandoned by a timed-out join must never observe a
            # later run's flag and resurrect
            self._running = threading.Event()
            self._running.set()
            self._thread = threading.Thread(
                target=self._run, name="tpuflux-seats", daemon=True)
            self._thread.start()

    def stop_capture(self) -> None:
        with self._api_lock:
            self._running.clear()
            if self._thread is not None:
                self._thread.join(timeout=5.0)
                self._thread = None

    def is_capturing(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def request_idr_frame(self) -> None:
        self._force_idr.set()

    def update_framerate(self, fps: float) -> None:
        if self._settings:
            self._settings.target_fps = float(fps)

    def update_video_bitrate(self, kbps: int) -> None:
        if self._settings:
            self._settings.video_bitrate_kbps = int(kbps)

    def update_tunables(self, **kw) -> None:
        # the ladder's rung-0 actuator and any settings-shaped tunable
        # must land on the loop's settings object (the ScreenCapture
        # contract) — the fps/quality paths below additionally reach
        # into the encoder
        if self._settings is not None:
            for k, v in kw.items():
                if hasattr(self._settings, k):
                    setattr(self._settings, k, v)
        enc = self._enc
        if enc is None:
            return
        if isinstance(enc, MultiSeatH264Encoder):
            if "video_crf" in kw:
                enc.qp = int(max(8, min(48, kw["video_crf"])))
                # paint-over must never be WORSE than motion quality
                enc.paint_qp = min(enc.paint_qp, enc.qp)
        elif "jpeg_quality" in kw or "paint_over_quality" in kw:
            enc.update_quality(kw.get("jpeg_quality",
                                      enc.settings.jpeg_quality),
                               kw.get("paint_over_quality"))

    def update_capture_region(self, x: int, y: int, w: int, h: int) -> None:
        assert self._settings is not None
        if (w, h) != (self._settings.capture_width,
                      self._settings.capture_height):
            self._settings.capture_width = w
            self._settings.capture_height = h
            if self._callback is not None:
                self.start_capture(self._callback, self._settings)

    def set_cursor_callback(self, cb) -> None:
        self._cursor_callback = cb

    def set_pipeline_clamp(self, depth: Optional[int]) -> None:
        with self._lock:
            self._pipeline_clamp = None if depth is None \
                else max(1, int(depth))

    def effective_pipeline_depth(self) -> int:
        from ..engine.pipeline import effective_depth
        with self._lock:
            clamp = self._pipeline_clamp
        return effective_depth(self._settings, clamp, PIPELINE_DEPTH)

    def restart(self, settings: Optional[CaptureSettings] = None) -> None:
        with self._api_lock:
            if self._callback is None:
                raise RuntimeError("restart before start_capture")
            self.start_capture(self._callback, settings or self._settings)

    # ------------------------------------------------------------------ loop
    def _deliver(self, out: dict) -> None:
        """Finalize one multi-seat slot + fan per-seat chunks out. Runs
        on the ring's finalizer thread at depth >= 2, inline at depth 1;
        in submission order either way, so per-seat delivery stays in
        order (the seat axis shares ONE slot per tick)."""
        enc = self._enc
        assert enc is not None
        if isinstance(enc, MultiSeatH264Encoder):
            per_seat = enc.finalize(out)
        else:
            per_seat = enc.finalize(out, force_all=out.get("force", False))
        cb = self._callback
        nbytes = 0
        for chunks in per_seat:
            for c in chunks:
                nbytes += len(c.payload)
                if cb is not None:
                    cb(c)
        self.last_frame_bytes = nbytes
        # energy plane (ISSUE 14): one delivered tick = one frame stamp
        # for the live fps->watts estimate
        _energy_meter.note_frame()
        if self._settings is not None:
            _tracer.frame_end(self._settings.display_id, out["frame_id"])

    def _run(self) -> None:
        assert self._settings and self._enc
        s, enc = self._settings, self._enc
        running = self._running     # THIS run's flag only
        tick = 0
        window_frames, window_start = 0, time.monotonic()
        # one timeline covers all seats per tick; alias keys route the
        # per-seat relay send/ACK spans onto it
        seat_aliases = tuple(f"seat{i}" for i in range(self.n_seats))
        # same depth-N pipeline as ScreenCapture (engine/pipeline.py):
        # dispatch the sharded step for tick N+1 while tick N's seats
        # are still being read back / packetized
        ring: Optional[PipelineRing] = None
        try:
            while running.is_set():
                t0 = time.monotonic()
                ring = retarget(ring, self.effective_pipeline_depth(),
                                self._deliver, "seats")
                tl = _tracer.frame_begin(s.display_id)
                with _tracer.span("capture", tl):
                    _faults.registry.perturb("capture.source")
                    frames = synthetic_seat_frames(enc, tick)
                force = self._force_idr.is_set()
                if force:
                    self._force_idr.clear()
                with _ENCODE_TURN:
                    if isinstance(enc, MultiSeatH264Encoder):
                        out = enc.encode(frames, force=force)
                    else:
                        out = enc.encode(frames)
                        out["force"] = force or tick == 0
                    _tracer.bind(tl, out["frame_id"],
                                 aliases=seat_aliases)
                if ring is not None:
                    ring.submit(out)
                else:
                    out["slot"] = 0
                    self._deliver(out)
                tick += 1
                window_frames += 1
                now = time.monotonic()
                if now - window_start >= 1.0:
                    self.encoded_fps = window_frames / (now - window_start)
                    window_frames, window_start = 0, now
                sleep = 1.0 / max(s.target_fps, 1.0) - (time.monotonic() - t0)
                if sleep > 0:
                    time.sleep(sleep)
            if ring is not None:
                ring.close(drain=True)
                ring = None
        except Exception as e:
            cause = cause_of(e)
            logger.exception("multi-seat capture loop died")
            _health.engine.recorder.record(
                "capture_death", display=s.display_id, seats=self.n_seats,
                error=f"{type(cause).__name__}: {cause}"[:200])
            running.clear()
            hook = self.on_death
            if hook is not None:
                try:
                    hook(cause)
                except Exception:
                    logger.exception("multi-seat on_death hook failed")
        finally:
            running.clear()
            if ring is not None:
                ring.close(drain=False)
