"""Multi-seat capture: one device-mesh encode step driving N desktop
displays (the server-side consumer of parallel/seats.py).

API-compatible with engine.capture.ScreenCapture so the WS service can
treat it as just another capture module; emitted chunks carry
``display_id="seat{N}"`` and the service's per-display relays route them
(SURVEY.md §2.5 multi-seat row — the reference scales by running N
containers; here one process + one sharded program serves N seats).

Seat content is synthetic for now (one X display per seat is a deployment
concern — each seat would own an X server in its own namespace); the
encode/fan-out path is the real one.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from ..engine.capture import _ENCODE_TURN
from ..engine.types import CaptureSettings, EncodedChunk
from ..obs import health as _health
from ..resilience import faults as _faults
from ..trace import tracer as _tracer
from .h264_seats import MultiSeatH264Encoder
from .seats import MultiSeatEncoder, synthetic_seat_frames

logger = logging.getLogger("selkies_tpu.parallel.capture")


class MultiSeatCapture:
    """ScreenCapture-compatible facade over MultiSeatEncoder."""

    def __init__(self, n_seats: int):
        self.n_seats = n_seats
        self._thread: Optional[threading.Thread] = None
        self._running = threading.Event()
        self._callback: Optional[Callable[[EncodedChunk], None]] = None
        self._settings: Optional[CaptureSettings] = None
        self._enc: Optional[MultiSeatEncoder] = None
        self._force_idr = threading.Event()
        self._cursor_callback = None
        self._api_lock = threading.RLock()
        self.encoded_fps = 0.0
        self.last_frame_bytes = 0
        #: supervision hook (same contract as ScreenCapture.on_death):
        #: called with the exception when the loop DIES, never on stop
        self.on_death: Optional[Callable[[BaseException], None]] = None

    # ----------------------------------------------------- reference surface
    def start_capture(self, callback, settings: CaptureSettings) -> None:
        with self._api_lock:
            if self.is_capturing():
                self.stop_capture()
            self._callback = callback
            self._settings = settings
            # the flagship codec rides the flagship axis: honor the
            # configured encoder instead of hard-building jpeg
            cls = MultiSeatH264Encoder if settings.output_mode == "h264" \
                else MultiSeatEncoder
            self._enc = cls(settings, self.n_seats)
            # fresh Event per run (same rationale as ScreenCapture): a
            # thread abandoned by a timed-out join must never observe a
            # later run's flag and resurrect
            self._running = threading.Event()
            self._running.set()
            self._thread = threading.Thread(
                target=self._run, name="tpuflux-seats", daemon=True)
            self._thread.start()

    def stop_capture(self) -> None:
        with self._api_lock:
            self._running.clear()
            if self._thread is not None:
                self._thread.join(timeout=5.0)
                self._thread = None

    def is_capturing(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def request_idr_frame(self) -> None:
        self._force_idr.set()

    def update_framerate(self, fps: float) -> None:
        if self._settings:
            self._settings.target_fps = float(fps)

    def update_video_bitrate(self, kbps: int) -> None:
        if self._settings:
            self._settings.video_bitrate_kbps = int(kbps)

    def update_tunables(self, **kw) -> None:
        enc = self._enc
        if enc is None:
            return
        if isinstance(enc, MultiSeatH264Encoder):
            if "video_crf" in kw:
                enc.qp = int(max(8, min(48, kw["video_crf"])))
                # paint-over must never be WORSE than motion quality
                enc.paint_qp = min(enc.paint_qp, enc.qp)
        elif "jpeg_quality" in kw or "paint_over_quality" in kw:
            enc.update_quality(kw.get("jpeg_quality",
                                      enc.settings.jpeg_quality),
                               kw.get("paint_over_quality"))

    def update_capture_region(self, x: int, y: int, w: int, h: int) -> None:
        assert self._settings is not None
        if (w, h) != (self._settings.capture_width,
                      self._settings.capture_height):
            self._settings.capture_width = w
            self._settings.capture_height = h
            if self._callback is not None:
                self.start_capture(self._callback, self._settings)

    def set_cursor_callback(self, cb) -> None:
        self._cursor_callback = cb

    def restart(self, settings: Optional[CaptureSettings] = None) -> None:
        with self._api_lock:
            if self._callback is None:
                raise RuntimeError("restart before start_capture")
            self.start_capture(self._callback, settings or self._settings)

    # ------------------------------------------------------------------ loop
    def _run(self) -> None:
        assert self._settings and self._enc
        s, enc = self._settings, self._enc
        running = self._running     # THIS run's flag only
        tick = 0
        window_frames, window_start = 0, time.monotonic()
        # one timeline covers all seats per tick; alias keys route the
        # per-seat relay send/ACK spans onto it
        seat_aliases = tuple(f"seat{i}" for i in range(self.n_seats))
        try:
            while running.is_set():
                t0 = time.monotonic()
                tl = _tracer.frame_begin(s.display_id)
                with _tracer.span("capture", tl):
                    _faults.registry.perturb("capture.source")
                    frames = synthetic_seat_frames(enc, tick)
                force = self._force_idr.is_set()
                if force:
                    self._force_idr.clear()
                with _ENCODE_TURN:
                    if isinstance(enc, MultiSeatH264Encoder):
                        out = enc.encode(frames, force=force)
                        _tracer.bind(tl, out["frame_id"],
                                     aliases=seat_aliases)
                        per_seat = enc.finalize(out)
                    else:
                        out = enc.encode(frames)
                        _tracer.bind(tl, out["frame_id"],
                                     aliases=seat_aliases)
                        per_seat = enc.finalize(
                            out, force_all=force or tick == 0)
                cb = self._callback
                nbytes = 0
                for chunks in per_seat:
                    for c in chunks:
                        nbytes += len(c.payload)
                        if cb is not None:
                            cb(c)
                self.last_frame_bytes = nbytes
                _tracer.frame_end(s.display_id, out["frame_id"])
                tick += 1
                window_frames += 1
                now = time.monotonic()
                if now - window_start >= 1.0:
                    self.encoded_fps = window_frames / (now - window_start)
                    window_frames, window_start = 0, now
                sleep = 1.0 / max(s.target_fps, 1.0) - (time.monotonic() - t0)
                if sleep > 0:
                    time.sleep(sleep)
        except Exception as e:
            logger.exception("multi-seat capture loop died")
            _health.engine.recorder.record(
                "capture_death", display=s.display_id, seats=self.n_seats,
                error=f"{type(e).__name__}: {e}"[:200])
            running.clear()
            hook = self.on_death
            if hook is not None:
                try:
                    hook(e)
                except Exception:
                    logger.exception("multi-seat on_death hook failed")
        finally:
            running.clear()
