"""Multi-seat H.264 over the seat mesh — the flagship codec on the
flagship parallelism axis.

Same SPMD shape as the JPEG :class:`MultiSeatEncoder` (one desktop per
device slot, zero collectives): the adaptive-I/P device step of
``engine/h264_encoder.py`` gains a leading seat axis via
``shard_map(vmap(step))``. All per-seat codec state (damage ages, stream
counters, decoder-exact reference planes) lives sharded on device; only
the bitstream buffers cross the host link.

Mode policy: the step graph differs between I and P, so a batch encodes
in ONE mode — the first frame and any forced refresh run the IDR step
for every seat (IDRs are rare; a per-seat mode split would need both
programs per frame). Per-seat damage gating still keeps unforced seats'
refreshes cheap.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from ..codecs import h264 as hcodec
from ..engine.h264_encoder import (build_h264_step_fn, h264_buffer_caps,
                                   h264_stripe_payload, plan_h264_grid)
from ..engine.types import CaptureSettings, EncodedChunk
from ..ops.h264_encode import scroll_candidates
from ..trace import tracer as _tracer
from .seats import seat_mesh

try:  # jax>=0.8 top-level; older releases keep it in experimental
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

logger = logging.getLogger("selkies_tpu.parallel.h264_seats")


class MultiSeatH264Encoder:
    """N per-seat adaptive-I/P H.264 sessions fused into one sharded
    device step; API mirrors :class:`MultiSeatEncoder` (encode/finalize
    with a leading seat axis)."""

    def __init__(self, settings: CaptureSettings, n_seats: int,
                 devices: Optional[Sequence] = None, mesh=None):
        self.settings = settings
        self.n_seats = n_seats
        self.grid = plan_h264_grid(settings)
        g = self.grid
        self._e_cap, self._w_cap, self._out_cap = h264_buffer_caps(g)
        self._cap_gen = 0       # buffer-growth generation (pipelined
        #                         stale-cap frames must not re-grow)
        self.mesh = mesh if mesh is not None else seat_mesh(n_seats, devices)
        if n_seats % self.mesh.devices.size:
            raise ValueError(
                f"{self.mesh.devices.size} devices do not divide "
                f"{n_seats} seats")
        self._spec = P("seat")
        self._sharding = NamedSharding(self.mesh, self._spec)
        vr = max(0, int(getattr(settings, "h264_motion_vrange", 0)))
        hr = max(0, int(getattr(settings, "h264_motion_hrange", 0)))
        self._candidates = scroll_candidates(vr, hr) if vr else ((0, 0),)
        self._i_step = self._build("i")
        self._p_step = self._build("p")

        n, R = n_seats, g.n_stripes * g.rows_per_stripe
        self.frame_id = 0
        put = lambda a: jax.device_put(a, self._sharding)  # noqa: E731
        self._age = put(np.zeros((n, g.n_stripes), np.int32))
        self._sent = put(np.zeros((n, g.n_stripes), np.int32))
        self._fnum = put(np.zeros((n, g.n_stripes), np.int32))
        self._prev = put(np.zeros((n, g.height, g.width, 3), np.uint8))
        self._ref_y = put(np.zeros((n, g.height, g.width), np.uint8))
        self._ref_u = put(np.zeros((n, g.height // 2, g.width // 2),
                                   np.uint8))
        self._ref_v = put(np.zeros((n, g.height // 2, g.width // 2),
                                   np.uint8))
        self._force_after_drop = np.zeros((n,), bool)
        self._sps_pps = hcodec.write_sps(g.width, g.stripe_h) \
            + hcodec.write_pps()
        pay, nb = hcodec.slice_header_events(g.mb_w, g.rows_per_stripe)
        self._hdr_pay = put(np.tile(pay, (n, g.n_stripes, 1)))
        self._hdr_nb = put(np.tile(nb, (n, g.n_stripes, 1)))
        ppay, pnb = hcodec.p_slice_header_events(g.mb_w, g.rows_per_stripe)
        self._p_hdr_pay = put(np.tile(ppay, (n, g.n_stripes, 1)))
        self._p_hdr_nb = put(np.tile(pnb, (n, g.n_stripes, 1)))
        self.qp = int(np.clip(settings.video_crf, 8, 48))
        self.paint_qp = int(np.clip(settings.video_min_qp, 8, self.qp))
        del R

    def _build(self, mode: str):
        g, s = self.grid, self.settings
        step = build_h264_step_fn(
            mode, g.width, g.stripe_h, g.n_stripes, self._e_cap,
            self._w_cap, self._out_cap, s.paint_over_delay_frames,
            s.use_damage_gating, s.use_paint_over,
            candidates=self._candidates if mode == "p" else ((0, 0),))
        spec = self._spec
        sharded = shard_map(jax.vmap(step), mesh=self.mesh,
                            in_specs=(spec,) * 13,
                            out_specs=(spec,) * 12)
        # compile as jit_h264_seatsN_{i,p}_step so a profiler capture
        # attributes multi-seat device time to the seats row, distinct
        # from the single-seat h264_{i,p}_step stem
        sharded.__name__ = f"h264_seats{self.n_seats}_{mode}_step"
        from ..obs import perf as _perf
        # prev + codec state donated (deep-pipeline HBM discipline):
        # all are session-owned outputs of the previous step
        from ..engine.encoder import donate_argnums_for_backend
        return _perf.wrap_step(
            f"h264.seats{self.n_seats}_{mode}_step"
            f"[{g.width}x{g.height}]",
            jax.jit(sharded, donate_argnums=donate_argnums_for_backend(
                (1, 2, 3, 4, 5, 6, 7))))

    # ------------------------------------------------------------------ state
    @property
    def input_sharding(self) -> NamedSharding:
        return self._sharding

    # ----------------------------------------------------------------- encode
    def encode(self, frames: jnp.ndarray, force: bool = False
               ) -> dict[str, Any]:
        """One sharded I/P step over all seats. ``force`` (or the first
        frame, or a post-overflow recovery on ANY seat) runs the IDR
        step batch-wide."""
        # generation BEFORE the step refs (growth swaps steps-then-gen;
        # the only possible tear is a benign stale-gen tag)
        cap_gen = self._cap_gen
        if self._force_after_drop.any():
            self._force_after_drop[:] = False
            force = True
        if self.frame_id == 0:
            force = True
        intra = bool(force)
        n = self.n_seats
        step = self._i_step if intra else self._p_step
        hdr_pay = self._hdr_pay if intra else self._p_hdr_pay
        hdr_nb = self._hdr_nb if intra else self._p_hdr_nb
        qp = jax.device_put(np.full((n,), self.qp, np.int32),
                            self._sharding)
        pqp = jax.device_put(np.full((n,), self.paint_qp, np.int32),
                             self._sharding)
        forces = jax.device_put(np.full((n,), bool(force)),
                                self._sharding)
        # covers the step AND the async-copy kicks so backends whose copy
        # kick synchronizes (CPU) still attribute the compute wait here
        with _tracer.span("encode.dispatch"):
            (data, row_lens, send, is_paint, age, sent, fnum,
             ry, ru, rv, prev_out, overflow) = step(
                frames, self._prev, self._age, self._sent, self._fnum,
                self._ref_y, self._ref_u, self._ref_v,
                qp, pqp, forces, hdr_pay, hdr_nb)
            # prev (and codec state) donated: keep the step's output
            self._prev = prev_out
            self._age = age
            self._sent = sent
            self._fnum = fnum
            self._ref_y, self._ref_u, self._ref_v = ry, ru, rv
            fid = self.frame_id
            self.frame_id = (self.frame_id + 1) & 0xFFFF
            # small control arrays only; the stream buffer is fetched
            # minimally at finalize (engine/readback.py)
            for arr in (row_lens, send, is_paint, overflow):
                try:
                    arr.copy_to_host_async()
                except Exception:
                    pass
        return {"data": data, "lens": row_lens, "send": send,
                "overflow": overflow, "frame_id": fid, "intra": intra,
                "cap_gen": cap_gen}

    # --------------------------------------------------------------- finalize
    def finalize(self, out: dict[str, Any], force_all: bool = False
                 ) -> list[list[EncodedChunk]]:
        del force_all                       # encode()-time decision
        g = self.grid
        tl = _tracer.lookup(self.settings.display_id, out["frame_id"])
        rb_t0 = out.get("submitted_ns") or time.perf_counter_ns()
        lens = np.asarray(out["lens"])      # (S, R)
        send = np.asarray(out["send"])      # (S, n_stripes)
        overflow = np.asarray(out["overflow"])   # (S,)
        # minimal readback (engine/readback.py), matching the
        # single-seat shape: per seat only rows through the last SENT
        # stripe count; all-idle frames fetch nothing
        from ..engine.readback import fetch_stream_bytes
        rps_ = g.rows_per_stripe
        total = 0
        for seat in range(self.n_seats):
            if overflow[seat] or not send[seat].any():
                continue
            last_row = (int(np.nonzero(send[seat])[0][-1]) + 1) * rps_
            total = max(total, int(lens[seat, :last_row].sum()))
        data = fetch_stream_bytes(out["data"], total) if total else None
        _tracer.record_span(tl, "encode.readback", rb_t0)
        intra = out["intra"]
        if overflow.any():
            if out["cap_gen"] == self._cap_gen:
                logger.warning(
                    "multi-seat h264 overflow on seats %s; growing",
                    np.nonzero(overflow)[0].tolist())
                self._w_cap *= 2
                self._out_cap *= 2
                # steps BEFORE gen (see encode()'s read order)
                self._i_step = self._build("i")
                self._p_step = self._build("p")
                self._cap_gen += 1
            self._force_after_drop |= overflow
        results: list[list[EncodedChunk]] = []
        rps = g.rows_per_stripe
        for seat in range(self.n_seats):
            if overflow[seat]:
                results.append([])
                continue
            # per-seat lane: each seat gets its own Perfetto track
            with _tracer.span("packetize", tl, lane=f"seat{seat}"):
                starts = np.concatenate([[0], np.cumsum(lens[seat])])
                chunks: list[EncodedChunk] = []
                for i in range(g.n_stripes):
                    if not send[seat, i]:
                        continue
                    rows = [bytes(data[seat, starts[r]:starts[r]
                                       + lens[seat, r]])
                            for r in range(i * rps, (i + 1) * rps)]
                    payload = h264_stripe_payload(intra, rows,
                                                  self._sps_pps)
                    chunks.append(EncodedChunk(
                        payload=payload, frame_id=out["frame_id"],
                        stripe_y=i * g.stripe_h, width=g.width,
                        height=g.stripe_h, is_idr=intra,
                        output_mode="h264", seat_index=seat,
                        display_id=f"seat{seat}"))
            results.append(chunks)
        return results
