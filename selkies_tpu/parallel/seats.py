"""Multi-seat encoding over a TPU device mesh.

One *seat* = one remote desktop (framebuffer + encoder state). The
reference scales seats by running one container per desktop
(docs/component.md:181-187); here N seats are encoded by ONE sharded
program over a ``Mesh('seat')``: per-seat frames, damage state and quant
tables carry a leading seat axis sharded across devices, the per-seat step
is ``vmap``-ed and ``shard_map``-ed, and — because seats never exchange
data — the compiled program contains zero collectives: pure ICI-free
SPMD, each chip encoding its seat's desktop in lockstep.

Seats-per-device > 1 is allowed (the vmap runs the local batch); devices
must divide seats.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..codecs import jpeg as jtab
from ..codecs.jpeg import stuff_ff_bytes
from ..engine.encoder import build_step_fn, jpeg_buffer_caps, plan_grid
from ..engine.types import CaptureSettings, EncodedChunk
from ..trace import tracer as _tracer

try:  # jax>=0.8 top-level; older releases keep it in experimental
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

logger = logging.getLogger("selkies_tpu.parallel.seats")


def seat_mesh(n_seats: int, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D ``Mesh('seat')`` using as many devices as divide ``n_seats``."""
    devs = list(devices) if devices is not None else list(jax.devices())
    n_dev = min(len(devs), n_seats)
    while n_seats % n_dev:
        n_dev -= 1
    return Mesh(np.array(devs[:n_dev]), ("seat",))


class MultiSeatEncoder:
    """N per-seat JPEG stripe encoders fused into one sharded device step.

    API mirrors :class:`~selkies_tpu.engine.encoder.JpegEncoderSession`
    with a leading seat axis: ``encode(frames)`` takes (S, H, W, 3) uint8,
    ``finalize`` returns a list of per-seat chunk lists.
    """

    def __init__(self, settings: CaptureSettings, n_seats: int,
                 devices: Optional[Sequence] = None,
                 mesh: Optional[Mesh] = None):
        if n_seats < 1:
            raise ValueError("n_seats must be >= 1")
        self.settings = settings
        self.n_seats = n_seats
        self.grid = plan_grid(settings)
        self.subsampling = "444" if settings.fullcolor else "420"
        g = self.grid
        # shared sizing policy (engine/encoder.py): the pre-warm planner
        # must compile with the exact caps a live encoder builds with
        self._e_cap, self._w_cap, self._out_cap = jpeg_buffer_caps(
            g, settings.fullcolor)

        self.mesh = mesh if mesh is not None else seat_mesh(n_seats, devices)
        if n_seats % self.mesh.devices.size:
            raise ValueError(
                f"{self.mesh.devices.size} devices do not divide "
                f"{n_seats} seats")
        self._spec = P("seat")
        self._sharding = NamedSharding(self.mesh, self._spec)
        self._step = self._build_step()

        self.frame_id = 0
        self._age = jax.device_put(
            np.zeros((n_seats, g.n_stripes), np.int32), self._sharding)
        self._force_after_drop = np.zeros((n_seats,), bool)
        self._cap_gen = 0   # growth generation: pipelined frames encoded
        #                     with stale caps must not re-grow/re-jit
        self.update_quality(settings.jpeg_quality,
                            settings.paint_over_quality)

    # ------------------------------------------------------------------ build
    def _build_step(self):
        g, s = self.grid, self.settings
        step = build_step_fn(g.width, g.stripe_h, g.n_stripes,
                             self.subsampling, self._e_cap, self._w_cap,
                             self._out_cap, s.paint_over_delay_frames,
                             s.use_damage_gating, s.use_paint_over)
        spec = self._spec
        sharded = shard_map(jax.vmap(step), mesh=self.mesh,
                            in_specs=(spec,) * 7, out_specs=(spec,) * 7)
        # the XLA module must compile as jit_jpeg_seatsN_step (NOT the
        # inner jpeg_step) so a profiler capture attributes multi-seat
        # device time to the seats row, and the single-seat stem
        # ("jpeg_step") can't claim these events
        sharded.__name__ = f"jpeg_seats{self.n_seats}_step"
        from ..obs import perf as _perf
        # prev + age donated (deep-pipeline HBM discipline): both are
        # session-owned outputs of the previous step, so N in-flight
        # slots reuse the same per-seat framebuffer allocations
        from ..engine.encoder import donate_argnums_for_backend
        return _perf.wrap_step(
            f"jpeg.seats{self.n_seats}_step[{g.width}x{g.height}"
            f"@{self.subsampling}]",
            jax.jit(sharded,
                    donate_argnums=donate_argnums_for_backend((1, 2))))

    # --------------------------------------------------------------- tunables
    def update_quality(self, motion_q: int, paint_q: int | None = None):
        self.settings.jpeg_quality = int(motion_q)
        if paint_q is not None:
            self.settings.paint_over_quality = int(paint_q)
        s, n = self.settings, self.n_seats
        self._qt_np = tuple(
            jtab.scale_qtable(base, q)
            for base, q in ((jtab.STD_LUMA_QUANT, s.jpeg_quality),
                            (jtab.STD_CHROMA_QUANT, s.jpeg_quality),
                            (jtab.STD_LUMA_QUANT, s.paint_over_quality),
                            (jtab.STD_CHROMA_QUANT, s.paint_over_quality)))
        # leading seat axis, replicated content, seat-sharded placement
        self._qt_dev = tuple(
            jax.device_put(np.tile(t.astype(np.float32), (n, 1)),
                           self._sharding)
            for t in self._qt_np)

    # ------------------------------------------------------------------ state
    @property
    def input_sharding(self) -> NamedSharding:
        """Sharding callers should ``device_put`` frame batches with."""
        return self._sharding

    def make_prev_buffer(self) -> jnp.ndarray:
        g = self.grid
        return jax.device_put(
            np.zeros((self.n_seats, g.height, g.width, 3), np.uint8),
            self._sharding)

    # ----------------------------------------------------------------- encode
    def encode(self, frames: jnp.ndarray,
               prev: Optional[jnp.ndarray] = None) -> dict[str, Any]:
        """Dispatch one multi-seat encode step (non-blocking).

        ``frames``: (n_seats, grid.height, grid.width, 3) uint8, ideally
        already placed with :attr:`input_sharding`. ``prev`` defaults to
        the internally-tracked previous batch; an explicitly-passed
        ``prev`` is DONATED to the step (its buffer is consumed).
        """
        if prev is None:
            prev = getattr(self, "_prev", None)
            if prev is None:
                prev = self.make_prev_buffer()
        # generation BEFORE step (growth swaps step-then-gen; the only
        # possible tear is a benign stale-gen tag)
        cap_gen = self._cap_gen
        # covers the step AND the async-copy kicks so backends whose copy
        # kick synchronizes (CPU) still attribute the compute wait here
        with _tracer.span("encode.dispatch"):
            data, lens, send, is_paint, age, prev_out, overflow = \
                self._step(frames, prev, self._age, *self._qt_dev)
            # prev/age were donated: the session's reference is the
            # step's materialized output, never the caller's batch
            self._prev = prev_out
            self._age = age
            fid = self.frame_id
            self.frame_id = (self.frame_id + 1) & 0xFFFF
            # small control arrays only; the stream buffer is fetched
            # minimally at finalize (engine/readback.py)
            for arr in (lens, send, is_paint, overflow):
                try:
                    arr.copy_to_host_async()
                except Exception:
                    pass
        return {"data": data, "lens": lens, "send": send,
                "is_paint": is_paint, "overflow": overflow, "frame_id": fid,
                "cap_gen": cap_gen, "qtabs": self._qt_np}

    # --------------------------------------------------------------- finalize
    def finalize(self, out: dict[str, Any], force_all: bool = False
                 ) -> list[list[EncodedChunk]]:
        """Blocks on readback; returns ``chunks[seat]`` lists."""
        g = self.grid
        # ONE readback span per frame (control-array sync + stream
        # fetch); fragments would double the stage count. Epoch: a
        # pipelined slot's in-flight time (submit -> bytes) IS readback.
        tl = _tracer.lookup(self.settings.display_id, out["frame_id"])
        rb_t0 = out.get("submitted_ns") or time.perf_counter_ns()
        lens = np.asarray(out["lens"])        # (S, n_stripes)
        send = np.asarray(out["send"])
        is_paint = np.asarray(out["is_paint"])
        overflow = np.asarray(out["overflow"])  # (S,)
        # minimal readback (engine/readback.py), matching the
        # single-seat shape: per seat only bytes through the last
        # DELIVERED stripe count; all-idle frames fetch nothing.
        # Overflowed seats are skipped here, so the growth pass below
        # (which only flags THOSE seats) can run after the fetch.
        from ..engine.readback import fetch_stream_bytes
        total = 0
        for seat in range(self.n_seats):
            if overflow[seat]:
                continue
            if force_all or self._force_after_drop[seat]:
                total = max(total, int(lens[seat].sum()))
            elif send[seat].any():
                last = int(np.nonzero(send[seat])[0][-1])
                total = max(total, int(lens[seat, :last + 1].sum()))
        data = fetch_stream_bytes(out["data"], total) if total else None
        _tracer.record_span(tl, "encode.readback", rb_t0)
        qy_m, qc_m, qy_p, qc_p = out["qtabs"]

        if overflow.any():
            # same growth policy as the single-seat session: drop the
            # overflowed seats' frames, double the growable buffers ONCE
            # per episode (pipelined frames encoded with stale caps also
            # overflow but must not re-double), recompile, and force
            # their next delivered frame to full
            if out.get("cap_gen", self._cap_gen) == self._cap_gen:
                logger.warning(
                    "multi-seat overflow on seats %s; growing buffers",
                    np.nonzero(overflow)[0].tolist())
                self._w_cap *= 2
                self._out_cap *= 2
                # step BEFORE gen (see encode()'s read order)
                self._step = self._build_step()
                self._cap_gen += 1
            self._force_after_drop |= overflow

        results: list[list[EncodedChunk]] = []
        for seat in range(self.n_seats):
            if overflow[seat]:
                results.append([])
                continue
            force = force_all or self._force_after_drop[seat]
            self._force_after_drop[seat] = False
            # per-seat lane: each seat gets its own Perfetto track
            with _tracer.span("packetize", tl, lane=f"seat{seat}"):
                starts = np.concatenate([[0], np.cumsum(lens[seat])])
                chunks: list[EncodedChunk] = []
                for i in range(g.n_stripes):
                    if not (force or send[seat, i]):
                        continue
                    raw = data[seat, starts[i]:starts[i] + lens[seat, i]]
                    scan = stuff_ff_bytes(raw)
                    paint = bool(is_paint[seat, i])
                    qy = qy_p if paint else qy_m
                    qc = qc_p if paint else qc_m
                    payload = jtab.assemble_jfif(g.stripe_h, g.width, scan,
                                                 qy, qc, self.subsampling)
                    chunks.append(EncodedChunk(
                        payload=payload, frame_id=out["frame_id"],
                        stripe_y=i * g.stripe_h, width=g.width,
                        height=g.stripe_h, is_idr=True, output_mode="jpeg",
                        seat_index=seat, display_id=f"seat{seat}"))
            results.append(chunks)
        return results


def synthetic_seat_frames(enc: MultiSeatEncoder, tick: int) -> jnp.ndarray:
    """Per-seat animated test frames, generated ON the seat mesh: the
    synthetic pattern is vmapped over a per-seat phase so every seat shows
    distinct content (seat fan-out tests depend on that)."""
    from ..engine.sources import _synthetic_fn
    g = enc.grid
    fn = _synthetic_fn(g.height, g.width)
    phases = jax.device_put(
        np.arange(enc.n_seats, dtype=np.int32) * 37 + tick,
        enc.input_sharding)
    spec = enc._spec
    gen = jax.jit(shard_map(jax.vmap(fn), mesh=enc.mesh,
                            in_specs=(spec,), out_specs=spec))
    return gen(phases)
