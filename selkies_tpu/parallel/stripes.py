"""Stripe (sequence) parallelism: ONE frame's MB rows sharded across the
device mesh.

The multi-seat axis (seats.py) is the data-parallel analog; this is the
sequence-parallel one (SURVEY.md §2.5: the reference's striped encoding
maps rows onto parallel encoders — here they map onto DEVICES). It works
because the H.264 design made MB rows fully independent (slice per row,
no cross-row prediction or CAVLC context): ``shard_map`` over the row
axis compiles to a collective-free SPMD program, scaling single-frame
encode latency down with device count — the path to 1080p60 on a slice
no single chip reaches, and to the 8K/multi-monitor stretch workloads
(BASELINE.md stretch rows; ROADMAP item 2).

Three encode entry points:

- :func:`h264_encode_sharded` — I frames (4:2:0 and 4:4:4). Rows are
  independent; zero collectives.
- :func:`h264_encode_p_sharded` — P frames. When the motion window (the
  per-stripe picture bound) nests inside a shard, the program stays
  collective-free. When a stripe SPANS shards (``single_stream``-style
  whole-frame windows), the reference planes are exchanged as HALO row
  bands ahead of the per-shard program and motion is selected against
  them with the window clamps re-derived from global row indices —
  bit-identical to the unsharded search (tests/test_stripes.py).
- the engine's :class:`~selkies_tpu.engine.h264_encoder.
  StripeShardedH264Session` — the serving path: the full damage-gated
  adaptive I/P step shard_mapped over whole stripes, each device's rows
  finalized to the wire as that shard lands.

The per-shard bitstreams meet at the packer seam: each MB row is an
independent byte-aligned slice NAL, so the shard merge is the degenerate
(word-aligned) case of the hierarchical bit-merge the packer itself uses
within a row (ops/bitpack.merge_bit_stacks, PERF.md lever 2 — the same
per-MB-relative offsets restructure powers both).

Consumes the ``tpu_stripe_devices`` setting.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from ..ops import h264_planes as _planes
from ..ops.h264_encode import (H264FrameOut, _MV_LAMBDA, _hshift,
                               _sad_mb16, se_bits)

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

logger = logging.getLogger("selkies_tpu.parallel.stripes")


def _set_stripe_gauge(n: int) -> None:
    """Export the CHOSEN shard count: a silently degraded mesh (fewer
    devices than asked — even 1) must be visible on the metrics plane,
    not just in a log line."""
    try:
        from ..server import metrics as _metrics
        _metrics.set_gauge("selkies_stripe_devices", float(n))
    except Exception:  # pragma: no cover - metrics plane optional
        pass


def resolved_stripe_devices(n_rows: int, requested: int,
                            n_avail: Optional[int] = None) -> int:
    """The shard count :func:`stripe_mesh` would choose — shared with
    the pre-warm planner so warmed program names always match the live
    session's (a divergence would warm a program nobody runs)."""
    if n_avail is None:
        n_avail = len(jax.devices())
    want = max(1, min(int(requested), n_avail))
    n = max(1, min(want, int(n_rows)))
    while n_rows % n:
        n -= 1
    return n


def stripe_mesh(n_rows: int, devices: Optional[Sequence] = None,
                requested: Optional[int] = None) -> Mesh:
    """1-D ``Mesh('stripe')`` with the largest device count dividing
    ``n_rows`` (MB rows), capped at ``requested`` when given.

    Degrading to fewer devices than requested/available is allowed but
    never silent: the chosen count is logged, exported as the
    ``selkies_stripe_devices`` gauge, and (via the bench) recorded in
    the perf-ledger row — a degraded mesh cannot masquerade as a
    scaling result."""
    devs = list(devices) if devices is not None else list(jax.devices())
    avail = len(devs)
    if avail < 1:
        raise ValueError("stripe_mesh needs at least one device")
    want = avail if requested is None else max(1, min(int(requested), avail))
    n = resolved_stripe_devices(n_rows, want, avail)
    if n < want:
        logger.warning(
            "stripe_mesh degraded to %d device(s): %d MB rows not "
            "divisible by %d (available %d)", n, n_rows, want, avail)
    else:
        logger.info("stripe_mesh: %d device(s) over %d MB rows", n, n_rows)
    _set_stripe_gauge(n)
    return Mesh(np.array(devs[:n]), ("stripe",))


# ---------------------------------------------------------------------------
# geometry validation + row padding
# ---------------------------------------------------------------------------

def _check_frame(yf: jnp.ndarray, mesh: Mesh) -> tuple:
    """-> (R, n_dev, pad_rows). Raises ValueError (never a bare assert —
    asserts vanish under ``python -O``) for geometry the shard layout
    cannot represent; rounds the MB-row count UP with throwaway pad rows
    where it can."""
    H, W = int(yf.shape[0]), int(yf.shape[1])
    if H % 16 or W % 16:
        raise ValueError(f"frame {W}x{H} is not macroblock-aligned")
    n_dev = int(mesh.devices.size)
    if n_dev < 1:
        raise ValueError("empty stripe mesh")
    R = H // 16
    if n_dev > R:
        raise ValueError(
            f"{n_dev} devices over {R} MB rows: more shards than rows")
    pad_rows = (-R) % n_dev
    return R, n_dev, pad_rows


def _pad0(arr: jnp.ndarray, pad: int) -> jnp.ndarray:
    """Append ``pad`` zero entries along axis 0 (pixel rows, MB rows or
    per-row vectors — the unit lives at the call site)."""
    if pad == 0:
        return arr
    return jnp.concatenate(
        [arr, jnp.zeros((pad,) + arr.shape[1:], arr.dtype)], axis=0)


def _enc_mods(fullcolor: bool):
    if fullcolor:
        from ..ops import h264_planes444 as _p444
        return _p444.h264_encode_yuv444, _p444.h264_encode_p_yuv444
    return _planes.h264_encode_yuv, _planes.h264_encode_p_yuv


# ---------------------------------------------------------------------------
# I frames
# ---------------------------------------------------------------------------

def h264_encode_sharded(yf: jnp.ndarray, uf: jnp.ndarray, vf: jnp.ndarray,
                        qp, header_pay: jnp.ndarray, header_nb: jnp.ndarray,
                        e_cap: int, w_cap: int, mesh: Mesh,
                        idr_pic_id=0, fullcolor: bool = False,
                        want_recon: bool = False):
    """Shard one frame's MB rows over ``mesh`` and I-encode; outputs are
    bit-identical to the unsharded encoder (rows are independent by
    construction, so the sharded program needs zero collectives). Row
    counts that don't divide the mesh are padded with throwaway rows and
    trimmed from the output."""
    R, n_dev, pad_rows = _check_frame(yf, mesh)
    cdiv = 1 if fullcolor else 2
    qp_rows = jnp.broadcast_to(jnp.asarray(qp, jnp.int32), (R,))
    idr_rows = jnp.broadcast_to(jnp.asarray(idr_pic_id, jnp.int32), (R,))
    hp = jnp.asarray(header_pay)
    hn = jnp.asarray(header_nb)
    if hp.shape[0] != R:
        raise ValueError(
            f"header events carry {hp.shape[0]} rows, frame has {R}")
    if pad_rows:
        yf = _pad0(yf, pad_rows * 16)
        uf = _pad0(uf, pad_rows * 16 // cdiv)
        vf = _pad0(vf, pad_rows * 16 // cdiv)
        qp_rows = _pad0(qp_rows, pad_rows)
        idr_rows = _pad0(idr_rows, pad_rows)
        hp = _pad0(hp, pad_rows)
        hn = _pad0(hn, pad_rows)
    enc_i, _ = _enc_mods(fullcolor)

    def local(y, u, v, qpv, hpv, hnv, idr):
        if want_recon:
            out, rec = enc_i(y, u, v, qpv, hpv, hnv, e_cap, w_cap,
                             idr_pic_id=idr, want_recon=True)
            return (out.words, out.total_bits, out.overflow[None],
                    rec[0], rec[1], rec[2])
        out = enc_i(y, u, v, qpv, hpv, hnv, e_cap, w_cap, idr_pic_id=idr)
        return out.words, out.total_bits, out.overflow[None]

    row_band = P("stripe")
    plane2 = P("stripe", None)
    out_specs = (plane2, row_band, row_band)
    if want_recon:
        out_specs = out_specs + (plane2, plane2, plane2)
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(plane2, plane2, plane2, row_band, plane2, plane2,
                  row_band),
        out_specs=out_specs)
    outs = jax.jit(fn)(yf, uf, vf, qp_rows, hp, hn, idr_rows)
    words, bits, ovf = outs[:3]
    out = H264FrameOut(words[:R], bits[:R], jnp.any(ovf), R)
    if want_recon:
        rec = (outs[3][:R * 16], outs[4][:R * 16 // cdiv],
               outs[5][:R * 16 // cdiv])
        return out, rec
    return out


# ---------------------------------------------------------------------------
# P frames: halo-row exchange for motion at shard boundaries
# ---------------------------------------------------------------------------

def _halo_bands(plane, band_px: int, halo_px: int) -> jnp.ndarray:
    """(H', W) reference plane -> (n_shards, band + 2*halo, W) bands with
    ``halo_px`` rows of neighbour context on each side (edge-clamped at
    the frame bound; the per-candidate STRIPE clamp happens inside the
    shard and never reads the frame-edge copies). This gather is the
    halo-row exchange: it runs ahead of the per-shard program, so the
    program itself stays collective-free."""
    p = jnp.asarray(plane)
    Hp = int(p.shape[0])
    n = Hp // band_px
    idx = np.clip(np.arange(n)[:, None] * band_px
                  + np.arange(-halo_px, band_px + halo_px)[None, :],
                  0, Hp - 1)
    return jnp.take(p, jnp.asarray(idx), axis=0)


def _motion_select_halo(cur_y, hy, hu, hv, qp_rows, candidates,
                        win: int, row0, halo_y: int, halo_c: int,
                        fullcolor: bool):
    """Per-shard motion selection against halo'd reference bands.

    Identical integer math to ops.h264_encode._motion_select — SAD +
    lambda*mvd-bits argmin, first-candidate tie break — with the
    vertical clamp re-derived from GLOBAL row indices: a row's shifted
    source is ``clip(g + dy, window_base, window_base + win - 1)``,
    which always lands within ``halo`` rows of the shard band, so the
    gather never leaves the exchanged halo. Bit-exact vs the unsharded
    search (tests/test_stripes.py halo fixture)."""
    B, W = cur_y.shape
    R_l, M = B // 16, W // 16
    lam = _MV_LAMBDA[jnp.clip(qp_rows, 0, 51)]

    gp = row0 + jnp.arange(B, dtype=jnp.int32)
    wb = (gp // win) * win

    def vshift_y(dy: int):
        src = jnp.clip(gp + dy, wb, wb + win - 1)
        return jnp.take(hy, src - (row0 - halo_y), axis=0)

    if fullcolor:
        def shift_chroma(p, dy: int, dx: int):
            src = jnp.clip(gp + dy, wb, wb + win - 1)
            return _hshift(jnp.take(p, src - (row0 - halo_c), axis=0), dx)
    else:
        winc = win // 2
        c_row0 = row0 // 2
        gpc = c_row0 + jnp.arange(B // 2, dtype=jnp.int32)
        wbc = (gpc // winc) * winc

        def s_c(p, a: int, b: int):
            src = jnp.clip(gpc + a, wbc, wbc + winc - 1)
            return _hshift(jnp.take(p, src - (c_row0 - halo_c), axis=0), b)

        def shift_chroma(p, dy: int, dx: int):
            by, fy = dy >> 1, dy & 1
            bx, fx = dx >> 1, dx & 1
            if not fy and not fx:
                return s_c(p, by, bx)
            if fy and not fx:
                return (s_c(p, by, bx) + s_c(p, by + 1, bx) + 1) >> 1
            if fx and not fy:
                return (s_c(p, by, bx) + s_c(p, by, bx + 1) + 1) >> 1
            return (s_c(p, by, bx) + s_c(p, by + 1, bx)
                    + s_c(p, by, bx + 1) + s_c(p, by + 1, bx + 1) + 2) >> 2

    shifted = []
    costs = []
    for dy, dx in candidates:
        sh = _hshift(vshift_y(dy), dx)
        shifted.append(sh)
        sad = _sad_mb16(jnp.abs(cur_y - sh))
        bits = se_bits(4 * dx) + se_bits(4 * dy)
        costs.append(sad + lam[:, None] * bits)
    sel = jnp.argmin(jnp.stack(costs), axis=0).astype(jnp.int32)

    sel_y = jnp.broadcast_to(sel[:, None, :, None],
                             (R_l, 16, M, 16)).reshape(B, W)
    pred_y = shifted[0]
    for k in range(1, len(candidates)):
        pred_y = jnp.where(sel_y == k, shifted[k], pred_y)

    cw = W if fullcolor else W // 2
    ch = B if fullcolor else B // 2
    blk = 16 if fullcolor else 8
    sel_c = jnp.broadcast_to(sel[:, None, :, None],
                             (R_l, blk, M, blk)).reshape(ch, cw)
    pred_u = shift_chroma(hu, *candidates[0])
    pred_v = shift_chroma(hv, *candidates[0])
    for k, (dy, dx) in enumerate(candidates[1:], 1):
        pred_u = jnp.where(sel_c == k, shift_chroma(hu, dy, dx), pred_u)
        pred_v = jnp.where(sel_c == k, shift_chroma(hv, dy, dx), pred_v)

    cand_q = jnp.asarray(np.asarray(candidates, np.int32)[:, ::-1] * 4)
    return pred_y, pred_u, pred_v, cand_q[sel]


def h264_encode_p_sharded(yf, uf, vf, ref_y, ref_u, ref_v, qp,
                          header_pay, header_nb, frame_num,
                          e_cap: int, w_cap: int, mesh: Mesh,
                          candidates: tuple = ((0, 0),),
                          stripe_rows: int | None = None,
                          fullcolor: bool = False):
    """P-frame encode with the frame's MB rows sharded over ``mesh``.

    Bit-identical to the unsharded ``h264_encode_p_yuv[444]`` with the
    same ``stripe_rows``. Collective-free when each shard holds whole
    motion windows; when a stripe window spans shards the reference
    planes are exchanged as halo row bands ahead of the per-shard
    program (see :func:`_motion_select_halo`). Returns
    ``(H264FrameOut, (recon_y, recon_u, recon_v))``."""
    R, n_dev, pad_rows = _check_frame(yf, mesh)
    cdiv = 1 if fullcolor else 2
    win_rows = int(stripe_rows) if stripe_rows else R
    if R % win_rows:
        raise ValueError(f"stripe_rows={win_rows} does not tile {R} rows")
    qp_rows = jnp.broadcast_to(jnp.asarray(qp, jnp.int32), (R,))
    fn_rows = jnp.broadcast_to(jnp.asarray(frame_num, jnp.int32), (R,))
    hp = jnp.asarray(header_pay)
    hn = jnp.asarray(header_nb)
    if hp.shape[0] != R:
        raise ValueError(
            f"header events carry {hp.shape[0]} rows, frame has {R}")

    rows_per_shard = (R + pad_rows) // n_dev
    motion = len(candidates) > 1
    aligned = rows_per_shard % win_rows == 0
    need_halo = motion and not aligned
    if need_halo and pad_rows:
        raise ValueError(
            f"{n_dev} devices do not divide {R} MB rows and the motion "
            f"window ({win_rows} rows) spans shards: no pad geometry "
            "exists — choose a dividing device count")
    if pad_rows:
        yf = _pad0(yf, pad_rows * 16)
        uf = _pad0(uf, pad_rows * 16 // cdiv)
        vf = _pad0(vf, pad_rows * 16 // cdiv)
        ref_y = _pad0(jnp.asarray(ref_y), pad_rows * 16)
        ref_u = _pad0(jnp.asarray(ref_u), pad_rows * 16 // cdiv)
        ref_v = _pad0(jnp.asarray(ref_v), pad_rows * 16 // cdiv)
        qp_rows = _pad0(qp_rows, pad_rows)
        fn_rows = _pad0(fn_rows, pad_rows)
        hp = _pad0(hp, pad_rows)
        hn = _pad0(hn, pad_rows)
    _, enc_p = _enc_mods(fullcolor)
    row_band = P("stripe")
    plane2 = P("stripe", None)

    if not need_halo:
        # whole windows per shard: pure SPMD, no exchanged rows at all
        local_stripe_rows = win_rows if motion else None

        def local(y, u, v, ry, ru, rv, qpv, hpv, hnv, fnv):
            out, rec = enc_p(y, u, v, ry, ru, rv, qpv, hpv, hnv, fnv,
                             e_cap, w_cap, candidates=candidates,
                             stripe_rows=local_stripe_rows)
            return (out.words, out.total_bits, out.overflow[None],
                    rec[0], rec[1], rec[2])

        fn = shard_map(
            local, mesh=mesh,
            in_specs=(plane2,) * 6 + (row_band, plane2, plane2, row_band),
            out_specs=(plane2, row_band, row_band, plane2, plane2,
                       plane2))
        outs = jax.jit(fn)(yf, uf, vf, jnp.asarray(ref_y),
                           jnp.asarray(ref_u), jnp.asarray(ref_v),
                           qp_rows, hp, hn, fn_rows)
    else:
        band = rows_per_shard * 16
        band_c = band // cdiv
        vmax = max(abs(dy) for dy, _ in candidates)
        halo_y = max(1, vmax)
        halo_c = halo_y if fullcolor else (vmax // 2 + 1)
        hy = _halo_bands(jnp.asarray(ref_y).astype(jnp.int32), band,
                         halo_y)
        hu = _halo_bands(jnp.asarray(ref_u).astype(jnp.int32), band_c,
                         halo_c)
        hv = _halo_bands(jnp.asarray(ref_v).astype(jnp.int32), band_c,
                         halo_c)
        win = win_rows * 16

        def local(y, u, v, hy_l, hu_l, hv_l, qpv, hpv, hnv, fnv):
            hy_l, hu_l, hv_l = hy_l[0], hu_l[0], hv_l[0]
            row0 = jax.lax.axis_index("stripe").astype(jnp.int32) * band
            pre = _motion_select_halo(
                y.astype(jnp.int32), hy_l, hu_l, hv_l, qpv, candidates,
                win, row0, halo_y, halo_c, fullcolor)
            # the ref args are unused with precomputed motion; the halo
            # band centres have the right shapes and keep XLA from
            # carrying a second copy of the reference
            ry = hy_l[halo_y:halo_y + band]
            ru = hu_l[halo_c:halo_c + band_c]
            rv = hv_l[halo_c:halo_c + band_c]
            out, rec = enc_p(y, u, v, ry, ru, rv, qpv, hpv, hnv, fnv,
                             e_cap, w_cap, candidates=candidates,
                             precomputed_motion=pre)
            return (out.words, out.total_bits, out.overflow[None],
                    rec[0], rec[1], rec[2])

        plane3 = P("stripe", None, None)
        fn = shard_map(
            local, mesh=mesh,
            in_specs=(plane2, plane2, plane2, plane3, plane3, plane3,
                      row_band, plane2, plane2, row_band),
            out_specs=(plane2, row_band, row_band, plane2, plane2,
                       plane2))
        outs = jax.jit(fn)(yf, uf, vf, hy, hu, hv, qp_rows, hp, hn,
                           fn_rows)

    words, bits, ovf = outs[:3]
    out = H264FrameOut(words[:R], bits[:R], jnp.any(ovf), R)
    rec = (outs[3][:R * 16], outs[4][:R * 16 // cdiv],
           outs[5][:R * 16 // cdiv])
    return out, rec
