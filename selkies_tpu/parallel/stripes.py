"""Stripe (sequence) parallelism: ONE frame's MB rows sharded across the
device mesh.

The multi-seat axis (seats.py) is the data-parallel analog; this is the
sequence-parallel one (SURVEY.md §2.5: the reference's striped encoding
maps rows onto parallel encoders — here they map onto DEVICES). It works
because the H.264 design made MB rows fully independent (slice per row,
no cross-row prediction or CAVLC context): ``shard_map`` over the row
axis compiles to a collective-free SPMD program, scaling single-frame
encode latency down with device count — the path to 4K/8K single-seat
targets (BASELINE.md stretch rows).

Consumes the ``tpu_stripe_devices`` setting.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..ops.h264_encode import H264FrameOut
from ..ops.h264_planes import h264_encode_yuv

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

logger = logging.getLogger("selkies_tpu.parallel.stripes")


def stripe_mesh(n_rows: int, devices: Optional[Sequence] = None) -> Mesh:
    """1-D ``Mesh('stripe')`` with the largest device count dividing
    ``n_rows`` (MB rows)."""
    devs = list(devices) if devices is not None else list(jax.devices())
    n = min(len(devs), n_rows)
    while n_rows % n:
        n -= 1
    return Mesh(np.array(devs[:n]), ("stripe",))


def h264_encode_sharded(yf: jnp.ndarray, uf: jnp.ndarray, vf: jnp.ndarray,
                        qp, header_pay: jnp.ndarray, header_nb: jnp.ndarray,
                        e_cap: int, w_cap: int, mesh: Mesh,
                        idr_pic_id=0) -> H264FrameOut:
    """Shard one frame's MB rows over ``mesh`` and encode; outputs are
    bit-identical to the unsharded h264_encode_yuv (rows are independent
    by construction, so the sharded program needs zero collectives)."""
    H = yf.shape[0]
    R = H // 16
    n_dev = mesh.devices.size
    assert R % n_dev == 0, f"{n_dev} devices do not divide {R} MB rows"
    qp_rows = jnp.broadcast_to(jnp.asarray(qp, jnp.int32), (R,))
    idr_rows = jnp.broadcast_to(jnp.asarray(idr_pic_id, jnp.int32), (R,))

    def local(y, u, v, qpv, hp, hn, idr):
        out = h264_encode_yuv(y, u, v, qpv, hp, hn, e_cap, w_cap,
                              idr_pic_id=idr)
        return out.words, out.total_bits, out.overflow[None]

    row_band = P("stripe")                    # leading dim = rows / bands
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P("stripe", None), P("stripe", None), P("stripe", None),
                  row_band, P("stripe", None), P("stripe", None), row_band),
        out_specs=(P("stripe", None), row_band, P("stripe")),
    )
    words, bits, overflow = jax.jit(fn)(
        yf, uf, vf, qp_rows,
        jnp.asarray(header_pay), jnp.asarray(header_nb), idr_rows)
    return H264FrameOut(words, bits, jnp.any(overflow), R)
