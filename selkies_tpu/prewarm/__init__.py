"""Compile-plane robustness: AOT pre-warm of the ladder lattice.

A 1080p H.264 program costs ~22 s to build (PERF.md), yet the
degradation ladder (PR 5) retargets geometry at runtime — before this
package, every geometry-changing rung risked a foreground XLA compile
that froze the session the downshift was meant to save, and the compile
monitor (PR 3) could only watch it happen. Three cooperating parts make
encoder reconfiguration a pre-provisioned, never-inline operation (the
discipline the split-frame V-PCC streaming work applies to encoder
reconfig):

- :mod:`.lattice` — enumerate the reachable (resolution x codec x
  quality-tier x seat-count) signature lattice from settings plus the
  ladder's rung table, deduplicated down to distinct compiled programs
  (quality tiers share a program: quant tables travel as runtime
  arguments);
- :mod:`.worker` — a supervised background worker that compiles the
  lattice current-operating-point-first then rung order, pausing while
  the device monitor's compile-storm detector is firing, with progress
  on ``GET /api/prewarm``, ``selkies_prewarm_*`` metrics and a
  ``prewarm`` health check; plus :class:`~.worker.PrewarmGate`, the
  transition gate the degradation ladder consults so a cold rung is
  *deferred* (top-priority enqueued) instead of compiled inline;
- :mod:`.plan` — the jax side: maps a signature onto the exact
  ``wrap_step`` programs the live engine sessions build (same
  ``functools`` factory cache keys), AOT lower+compile via
  ``ShapeDtypeStruct`` avals so nothing executes on the device;
- :mod:`.artifact` — distributable warm-cache artifacts: pack the
  host-fingerprint-keyed persistent XLA cache (PR 2) into a
  manifest-carrying tarball, refuse unpacking on a fingerprint or jax
  version mismatch (the cross-machine SIGILL hazard), so new hosts boot
  hot from a CI-built artifact.

Import contract: this module, :mod:`.lattice`, :mod:`.worker` and
:mod:`.artifact` are stdlib-only (``python -m selkies_tpu.prewarm
selftest`` runs in the lint CI image with neither jax nor aiohttp);
every jax touch point lives in :mod:`.plan` and is imported lazily.
"""

from .lattice import (LatticePlan, Signature,  # noqa: F401
                      enumerate_lattice, lattice_from_settings)
from .worker import PrewarmGate, PrewarmWorker  # noqa: F401
