"""Offline compile-plane CLI.

``python -m selkies_tpu.prewarm selftest`` — drive lattice enumeration,
the pre-warm worker (fake compiler), the ladder's deferred-transition
gate, and the warm-cache artifact pack/unpack/refusal contracts, all
stdlib-only (the CI lint smoke, mirroring ``python -m
selkies_tpu.resilience selftest``). Exits non-zero on any contract
break.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

from ..obs.health import FAILED, OK, HealthEngine
from ..resilience.ladder import DegradationLadder
from . import artifact as _artifact
from .lattice import Signature, enumerate_lattice, lattice_from_settings
from .worker import PrewarmGate, PrewarmWorker


def _fail(msg: str) -> int:
    print(f"selftest FAILED: {msg}", file=sys.stderr)
    return 1


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


class _NS:
    def __init__(self, **kw):
        self.__dict__.update(kw)


def _cmd_selftest(args: argparse.Namespace) -> int:
    import logging
    logging.getLogger("selkies_tpu.prewarm").setLevel(logging.CRITICAL)
    logging.getLogger("selkies_tpu.resilience").setLevel(logging.CRITICAL)

    # -- lattice: dedup, order, floor, seat variants ---------------------
    plan = lattice_from_settings(_NS(encoder="h264-tpu-striped",
                                     initial_width=1920,
                                     initial_height=1080, tpu_seats=1))
    if len(plan.signatures) != 2:
        return _fail(f"default ladder lattice must dedup to 2 programs "
                     f"(base + downscale), got {len(plan.signatures)}")
    if plan.signatures[0].program_key != plan.base.program_key:
        return _fail("base operating point must enumerate first")
    if (plan.signatures[1].width, plan.signatures[1].height) != (960, 540):
        return _fail(f"downscale rung must halve geometry: "
                     f"{plan.signatures[1]}")
    if plan.rung_targets["quality"]["down"] \
            or plan.rung_targets["fps"]["down"]:
        return _fail("fps/quality rungs must be compile-free")
    if plan.rung_targets["downscale"]["down"] \
            != [plan.signatures[1].program_key]:
        return _fail("downscale rung must target the scaled program")
    tiny = enumerate_lattice(Signature(64, 64, "jpeg"),
                             steps=("downscale",))
    if len(tiny.signatures) != 1 or tiny.rung_targets["downscale"]["down"]:
        return _fail("a floor-clamped downscale must be a no-op rung")
    seats = lattice_from_settings(_NS(encoder="jpeg-tpu",
                                      initial_width=640,
                                      initial_height=480, tpu_seats=4))
    if any(s.seats != 4 for s in seats.signatures):
        return _fail("seat-count variants must carry the seat axis")
    if seats.signatures[0].program_key \
            == plan.signatures[0].program_key:
        return _fail("seat programs must be distinct compile identities")
    multi = enumerate_lattice(Signature(1024, 768, "jpeg"),
                              steps=("downscale", "downscale4"))
    if [(s.width, s.height) for s in multi.signatures] \
            != [(1024, 768), (512, 384), (128, 96)]:
        return _fail(f"downscaleN rungs must stack cumulatively: "
                     f"{[(s.width, s.height) for s in multi.signatures]}")

    # -- worker: order, request, pause, failure, health ------------------
    clk = _Clock()
    compiled: list = []
    storm = {"on": False}

    def fake_compiler(sig):
        compiled.append(sig.program_key)
        if sig.width == 13:
            raise RuntimeError("boom")
        return {"programs": [f"fake[{sig.width}x{sig.height}]"]}

    w = PrewarmWorker(multi, compiler=fake_compiler, clock=clk,
                      storm_check=lambda: storm["on"])
    w.note_operating_point(512, 384)   # mid-rung operating point first
    w.run_pending_sync()
    if compiled != [multi.signatures[1].program_key,
                    multi.signatures[0].program_key,
                    multi.signatures[2].program_key]:
        return _fail(f"compile order must be operating-point-first then "
                     f"lattice order: {compiled}")
    if w.query(multi.program_keys) != "warm":
        return _fail("fully-compiled lattice must query warm")
    if w.query(["nonexistent"]) != "cold":
        return _fail("unknown program keys must query cold")
    if w.health_check().status != OK:
        return _fail("warm lattice must verdict ok")
    bad_key = w.ensure(Signature(13, 13, "jpeg"))
    w.run_pending_sync()
    if w.states()[bad_key] != "failed" \
            or w.health_check().status != FAILED:
        return _fail("a failed program must fail the prewarm verdict")
    w2 = PrewarmWorker(tiny, compiler=fake_compiler, clock=clk,
                       storm_check=lambda: storm["on"])
    storm["on"] = True
    if w2._storming() is not True:
        return _fail("storm_check must hold the worker")
    storm["on"] = False

    # -- gate + ladder: defer, request, land, deadline force -------------
    eng = HealthEngine()
    worker = PrewarmWorker(multi, compiler=fake_compiler, clock=clk)
    gate = PrewarmGate(worker, multi.rung_targets)
    lad = DegradationLadder(steps=("downscale", "downscale4"),
                            down_after_s=1.0, hold_s=1.0,
                            ok_window_s=10.0, gate=gate,
                            defer_deadline_s=5.0, clock=clk,
                            recorder=eng.recorder)
    bad = {"qoe": FAILED}
    lad.observe(bad, now=0.0)
    lad.observe(bad, now=1.5)
    if lad.level != 0 or lad.deferred_transitions != 1:
        return _fail(f"cold rung must defer: level={lad.level} "
                     f"deferred={lad.deferred_transitions}")
    kinds = [e["kind"] for e in eng.recorder.snapshot()]
    if "transition_deferred" not in kinds:
        return _fail(f"deferral must record an incident: {kinds}")
    # the deferral promoted the target: next sync compiles it FIRST
    worker.run_pending_sync()
    lad.observe(bad, now=2.0)
    if lad.level != 1:
        return _fail("a warmed rung must land on the next tick")
    # deadline-forced nearest warm rung: re-cool /4? use a fresh ladder
    w3 = PrewarmWorker(multi, compiler=fake_compiler, clock=clk)
    # warm ONLY the /4 program; /2 stays cold
    w3.request([multi.signatures[2].program_key])
    w3._compile_one(multi.signatures[2].program_key)
    g3 = PrewarmGate(w3, multi.rung_targets)
    lad3 = DegradationLadder(steps=("downscale", "downscale4"),
                             down_after_s=1.0, hold_s=1.0,
                             ok_window_s=10.0, gate=g3,
                             defer_deadline_s=2.0, clock=clk,
                             recorder=eng.recorder)
    lad3.observe(bad, now=0.0)
    lad3.observe(bad, now=1.5)      # defers (downscale cold)
    lad3.observe(bad, now=4.0)      # deadline passed -> force downscale4
    if lad3.level != 2:
        return _fail(f"deadline must force the nearest warm rung: "
                     f"level={lad3.level}")
    last_step = [e for e in eng.recorder.snapshot()
                 if e["kind"] == "degradation_step"][-1]
    if last_step.get("skipped") != ["downscale"]:
        return _fail(f"forced shift must name skipped cold rungs: "
                     f"{last_step}")

    # -- artifact: round-trip, refusal, traversal guard ------------------
    with tempfile.TemporaryDirectory() as tmp:
        cache = os.path.join(tmp, "cache")
        os.makedirs(os.path.join(cache, "sub"))
        for rel in ("a.bin", os.path.join("sub", "b.bin")):
            with open(os.path.join(cache, rel), "wb") as f:
                f.write(rel.encode() * 7)
        art = os.path.join(tmp, "warm.tgz")
        manifest = _artifact.pack(art, cache_dir=cache,
                                  fingerprint="fpA", jax_ver="1.2.3")
        if manifest["files"] != 2:
            return _fail(f"pack must record 2 files: {manifest}")
        _artifact.verify(art, fingerprint="fpA", jax_ver="1.2.3")
        try:
            _artifact.unpack(art, root=os.path.join(tmp, "out"),
                             fingerprint="fpB", jax_ver="1.2.3")
            return _fail("fingerprint mismatch must refuse unpack")
        except _artifact.FingerprintMismatch as e:
            if e.field != "fingerprint":
                return _fail(f"wrong mismatch field: {e.field}")
        try:
            _artifact.unpack(art, root=os.path.join(tmp, "out"),
                             fingerprint="fpA", jax_ver="9.9.9")
            return _fail("jax-version mismatch must refuse unpack")
        except _artifact.FingerprintMismatch:
            pass
        res = _artifact.unpack(art, root=os.path.join(tmp, "out"),
                               fingerprint="fpA", jax_ver="1.2.3")
        got = os.path.join(res["dir"], "sub", "b.bin")
        with open(got, "rb") as f:
            if f.read() != os.path.join("sub", "b.bin").encode() * 7:
                return _fail("unpack must restore file contents")
        if _artifact._safe_member("cache/ok") != "cache/ok":
            return _fail("safe member normalisation broken")
        for evil in ("/abs/path", "../up", "cache/../../up"):
            try:
                _artifact._safe_member(evil)
                return _fail(f"unsafe member {evil!r} must be rejected")
            except _artifact.ArtifactError:
                pass
        status = _artifact.unpack_if_configured(
            _NS(warm_cache_artifact=os.path.join(tmp, "nope.tgz")))
        if status["status"] != "missing":
            return _fail(f"missing artifact must report missing: {status}")

    doc = {"lattice": multi.to_dict(), "worker": w.snapshot(),
           "ladder": lad3.snapshot(),
           "incidents": eng.recorder.snapshot()[-4:]}
    text = json.dumps(doc)
    json.loads(text)
    print(text if args.json
          else f"selftest OK ({len(text)} bytes of compile-plane state)")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m selkies_tpu.prewarm",
        description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    ps = sub.add_parser("selftest",
                        help="drive lattice+worker+gate+artifact "
                             "contracts with fakes")
    ps.add_argument("--json", action="store_true",
                    help="print the selftest state payload")
    ps.set_defaults(fn=_cmd_selftest)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
