"""Distributable warm-cache artifacts.

The persistent XLA compile cache (PR 2) is keyed by a host fingerprint
precisely because reusing compiled code across heterogeneous machines
produces "compile machine features don't match host" warnings and a
SIGILL risk (seen live in the r05 bench tail). That makes the cache
*shippable* — build it once per microarchitecture fingerprint in CI,
distribute the tarball, and every new host of that microarch boots hot —
as long as unpacking ENFORCES the key. This module owns that contract:

- :func:`pack` tars a fingerprint-keyed cache subtree together with a
  ``manifest.json`` (fingerprint, jax version, per-file sha256);
- :func:`verify` checks a tarball's integrity and its compatibility
  with THIS host, raising :class:`FingerprintMismatch` on the hazard;
- :func:`unpack` refuses a fingerprint mismatch outright (there is no
  force flag for it: shipping wrong-microarch machine code is the bug
  class this exists to prevent), refuses a jax-version mismatch unless
  forced (serialized executables are not stable across jax releases),
  and extracts with path-traversal guards;
- :func:`unpack_if_configured` is the server-boot hook: unpack the
  ``warm_cache_artifact`` setting's tarball before the first compile so
  the first session build cache-hits.

Stdlib-only; jax is touched only to read ``jax.__version__`` when the
caller doesn't supply one.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import tarfile
import time
from typing import Optional

from ..compile_cache import cache_root, host_fingerprint

logger = logging.getLogger("selkies_tpu.prewarm.artifact")

__all__ = ["ArtifactError", "FingerprintMismatch", "pack", "verify",
           "unpack", "unpack_if_configured", "MANIFEST_NAME"]

MANIFEST_NAME = "manifest.json"
ARTIFACT_KIND = "selkies-warm-cache"
ARTIFACT_VERSION = 1
#: archive member prefix for cache files
_PREFIX = "cache/"


class ArtifactError(RuntimeError):
    """Malformed / unreadable / unsafe artifact."""


class FingerprintMismatch(ArtifactError):
    """The artifact was built for a different host fingerprint (or jax
    version): unpacking it risks SIGILL (or deserialize failures) on
    this machine."""

    def __init__(self, field: str, want: str, got: str):
        super().__init__(
            f"warm-cache artifact {field} mismatch: artifact is for "
            f"{want!r}, this host is {got!r}")
        self.field = field
        self.want = want
        self.got = got


def jax_version() -> str:
    try:
        import jax
        return str(jax.__version__)
    except Exception:
        return "unknown"


def _walk(cache_dir: str):
    for root, _dirs, files in os.walk(cache_dir):
        for name in sorted(files):
            full = os.path.join(root, name)
            if os.path.islink(full):
                continue
            yield os.path.relpath(full, cache_dir), full


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _safe_member(name: str) -> str:
    """Reject absolute / traversal member names before extraction."""
    norm = os.path.normpath(name)
    if norm.startswith(("/", "..")) or os.path.isabs(norm) \
            or ".." in norm.split(os.sep):
        raise ArtifactError(f"unsafe archive member {name!r}")
    return norm


def pack(out_path: str, cache_dir: Optional[str] = None, *,
         fingerprint: Optional[str] = None,
         jax_ver: Optional[str] = None) -> dict:
    """Tar the fingerprint-keyed cache subtree + manifest; -> manifest.
    An empty cache dir is an error — shipping a hollow artifact would
    read as "warm" while every host still compiles cold."""
    fingerprint = fingerprint or host_fingerprint()
    if cache_dir is None:
        cache_dir = os.path.join(cache_root(), fingerprint)
    if not os.path.isdir(cache_dir):
        raise ArtifactError(f"cache dir {cache_dir} does not exist "
                            "(warm something first)")
    files = []
    total = 0
    for rel, full in _walk(cache_dir):
        size = os.path.getsize(full)
        files.append({"path": rel, "bytes": size,
                      "sha256": _sha256(full)})
        total += size
    if not files:
        raise ArtifactError(f"cache dir {cache_dir} is empty "
                            "(warm something first)")
    manifest = {
        "kind": ARTIFACT_KIND, "version": ARTIFACT_VERSION,
        "fingerprint": fingerprint,
        "jax_version": jax_ver if jax_ver is not None else jax_version(),
        "created": round(time.time(), 3),
        "files": len(files), "bytes": total,
        "entries": files,
    }
    blob = json.dumps(manifest, indent=1).encode()
    with tarfile.open(out_path, "w:gz") as tar:
        info = tarfile.TarInfo(MANIFEST_NAME)
        info.size = len(blob)
        info.mtime = int(time.time())
        tar.addfile(info, io.BytesIO(blob))
        for entry in files:
            tar.add(os.path.join(cache_dir, entry["path"]),
                    arcname=_PREFIX + entry["path"], recursive=False)
    logger.info("packed %d cache files (%.1f MB) for %s -> %s",
                len(files), total / 1e6, fingerprint, out_path)
    return manifest


def read_manifest(path: str) -> dict:
    # KeyError: tarfile.extractfile raises it for a missing member —
    # "any tarball that is not an artifact" must be ArtifactError, not
    # a stray exception that aborts the boot hook / CLI contract
    try:
        with tarfile.open(path, "r:*") as tar:
            member = tar.extractfile(MANIFEST_NAME)
            if member is None:
                raise ArtifactError(f"{path}: no {MANIFEST_NAME}")
            manifest = json.loads(member.read().decode())
    except (OSError, tarfile.TarError, KeyError, ValueError) as e:
        raise ArtifactError(f"{path}: unreadable artifact "
                            f"({type(e).__name__}: {e})") from e
    if not isinstance(manifest, dict) \
            or manifest.get("kind") != ARTIFACT_KIND:
        raise ArtifactError(f"{path}: not a {ARTIFACT_KIND} artifact")
    if int(manifest.get("version", 0)) > ARTIFACT_VERSION:
        raise ArtifactError(
            f"{path}: artifact version {manifest.get('version')} is "
            f"newer than this reader ({ARTIFACT_VERSION})")
    return manifest


def verify(path: str, *, fingerprint: Optional[str] = None,
           jax_ver: Optional[str] = None,
           check_host: bool = True) -> dict:
    """Integrity + compatibility check. Raises :class:`ArtifactError`
    (malformed) or :class:`FingerprintMismatch` (wrong host/jax);
    returns the manifest with a ``verified`` summary on success."""
    manifest = read_manifest(path)
    try:
        want = {e["path"]: e for e in manifest.get("entries", [])}
    except (TypeError, KeyError) as e:
        raise ArtifactError(f"{path}: malformed manifest entries") from e
    seen = set()
    try:
        with tarfile.open(path, "r:*") as tar:
            for member in tar.getmembers():
                if member.name == MANIFEST_NAME:
                    continue
                name = _safe_member(member.name)
                if not name.startswith(_PREFIX):
                    raise ArtifactError(
                        f"{path}: unexpected member {member.name!r}")
                if not member.isfile():
                    raise ArtifactError(
                        f"{path}: non-file member {member.name!r}")
                rel = name[len(_PREFIX):]
                entry = want.get(rel)
                if entry is None:
                    raise ArtifactError(
                        f"{path}: member {rel!r} missing from manifest")
                f = tar.extractfile(member)
                h = hashlib.sha256()
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
                if h.hexdigest() != entry.get("sha256"):
                    raise ArtifactError(f"{path}: {rel} sha256 mismatch")
                seen.add(rel)
    except (OSError, tarfile.TarError, KeyError) as e:
        # a tarball truncated PAST the manifest still fails as a
        # malformed artifact, never as a stray traceback
        raise ArtifactError(f"{path}: unreadable artifact body "
                            f"({type(e).__name__}: {e})") from e
    missing = sorted(set(want) - seen)
    if missing:
        raise ArtifactError(
            f"{path}: manifest entries missing from archive: "
            f"{missing[:3]}")
    if check_host:
        fp = fingerprint or host_fingerprint()
        if manifest.get("fingerprint") != fp:
            raise FingerprintMismatch("fingerprint",
                                      str(manifest.get("fingerprint")),
                                      fp)
        jv = jax_ver if jax_ver is not None else jax_version()
        if manifest.get("jax_version") not in (jv, "unknown") \
                and jv != "unknown":
            raise FingerprintMismatch("jax_version",
                                      str(manifest.get("jax_version")),
                                      jv)
    manifest["verified"] = {"files": len(seen), "host_checked": check_host}
    return manifest


def unpack(path: str, root: Optional[str] = None, *,
           fingerprint: Optional[str] = None,
           jax_ver: Optional[str] = None,
           force_version: bool = False) -> dict:
    """Verify then extract into ``root/<fingerprint>/``. A fingerprint
    mismatch is ALWAYS refused (the SIGILL hazard has no override); a
    jax-version mismatch is refused unless ``force_version``."""
    try:
        manifest = verify(path, fingerprint=fingerprint, jax_ver=jax_ver)
    except FingerprintMismatch as e:
        if e.field == "jax_version" and force_version:
            manifest = verify(path, fingerprint=fingerprint,
                              jax_ver=jax_ver, check_host=False)
            fp = fingerprint or host_fingerprint()
            if manifest.get("fingerprint") != fp:
                raise FingerprintMismatch(
                    "fingerprint", str(manifest.get("fingerprint")),
                    fp) from e
            logger.warning("unpacking despite jax-version mismatch "
                           "(%s); deserialize failures fall back to a "
                           "cold compile", e)
        else:
            raise
    root = root or cache_root()
    dest = os.path.join(root, manifest["fingerprint"])
    os.makedirs(dest, exist_ok=True)
    extracted = 0
    try:
        with tarfile.open(path, "r:*") as tar:
            for member in tar.getmembers():
                if member.name == MANIFEST_NAME or not member.isfile():
                    continue
                rel = _safe_member(member.name)[len(_PREFIX):]
                target = os.path.join(dest, rel)
                os.makedirs(os.path.dirname(target) or dest,
                            exist_ok=True)
                src = tar.extractfile(member)
                with open(target, "wb") as out:
                    for chunk in iter(lambda: src.read(1 << 20), b""):
                        out.write(chunk)
                extracted += 1
    except (OSError, tarfile.TarError, KeyError) as e:
        raise ArtifactError(f"{path}: extraction failed "
                            f"({type(e).__name__}: {e})") from e
    logger.info("unpacked %d warm-cache files into %s", extracted, dest)
    return {"dir": dest, "files": extracted,
            "bytes": manifest.get("bytes"),
            "fingerprint": manifest["fingerprint"],
            "jax_version": manifest.get("jax_version")}


def unpack_if_configured(settings, recorder=None) -> Optional[dict]:
    """Server-boot hook: unpack ``settings.warm_cache_artifact`` before
    the first compile. Refusals and errors are reported (incident +
    log) but never fatal — a mismatched artifact means a cold boot, not
    no boot."""
    path = str(getattr(settings, "warm_cache_artifact", "") or "")
    if not path:
        return None
    def _incident(kind, **fields):
        if recorder is not None:
            try:
                recorder.record(kind, **fields)
            except Exception:
                logger.debug("incident record failed", exc_info=True)
    if not os.path.exists(path):
        logger.warning("warm_cache_artifact %s not found; booting cold",
                       path)
        return {"status": "missing", "path": path}
    try:
        res = unpack(path)
        _incident("warm_cache_unpacked", path=path,
                  files=res["files"], fingerprint=res["fingerprint"])
        return {"status": "unpacked", "path": path, **res}
    except FingerprintMismatch as e:
        logger.error("REFUSING warm-cache artifact %s: %s "
                     "(cross-machine reuse risks SIGILL); booting cold",
                     path, e)
        _incident("warm_cache_refused", path=path, field=e.field,
                  want=e.want, got=e.got)
        return {"status": "refused", "path": path, "field": e.field,
                "error": str(e)}
    except ArtifactError as e:
        logger.error("warm-cache artifact %s unusable: %s; booting cold",
                     path, e)
        _incident("warm_cache_error", path=path, error=str(e)[:200])
        return {"status": "error", "path": path, "error": str(e)[:200]}
