"""Reachable-signature lattice enumeration.

The degradation ladder's rung table plus the configured operating point
determine every (resolution x codec x quality-tier x seat-count)
combination a running server can be asked to encode. This module derives
that set AHEAD of time so the pre-warm worker can compile it before the
ladder needs it.

Two identities matter and they are not the same:

- a :class:`Signature` is one ladder-reachable operating point
  (geometry, codec, quality tier, seats, and the session knobs that
  change the compiled program);
- its :attr:`~Signature.program_key` is the *compile* identity — the
  quality tier is excluded because quant tables / qp travel as runtime
  arguments, so the "base" and "degraded" tiers of one geometry share a
  compiled program. Lattice dedup happens on program_key: the lattice
  for the default ladder (fps -> quality -> downscale) collapses to two
  programs per codec (full geometry + downscaled geometry), not six.

Stdlib-only: the lint CI image enumerates lattices with no jax
installed; the jax mapping from a signature onto actual compiled
programs lives in :mod:`.plan`.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

__all__ = ["Signature", "LatticePlan", "enumerate_lattice",
           "lattice_from_settings", "downscale_factor",
           "broadcast_rung_signatures", "GEOMETRY_FLOOR_PX",
           "BROADCAST_RUNG_FACTORS"]

#: the ladder's capture-downscale floor (mirrors
#: ``ws_service._apply_ladder_scale``: ``max(64, dim // factor)``)
GEOMETRY_FLOOR_PX = 64


@dataclasses.dataclass(frozen=True)
class Signature:
    """One reachable operating point. Fields beyond ``quality_tier``
    all change the compiled XLA program (geometry/striping feed the
    grid planner, gating/paint/motion knobs are trace-time constants,
    seats select the sharded program)."""

    width: int
    height: int
    codec: str                      # "jpeg" | "h264"
    quality_tier: str = "base"      # metadata only: NOT compile identity
    seats: int = 1
    #: split-frame device parallelism (ROADMAP 2): stripes of ONE
    #: session's frame sharded over this many devices. >1 selects the
    #: shard_map-wrapped step — a distinct compiled program
    stripe_devices: int = 1
    fullcolor: bool = False
    stripe_height: int = 64
    single_stream: bool = False
    use_damage_gating: bool = True
    use_paint_over: bool = True
    paint_over_delay_frames: int = 15
    h264_motion_vrange: int = 24
    h264_motion_hrange: int = 8
    #: damage-proportional encoding (ROADMAP 4): the partial path adds
    #: the band-bucket program family (one per power-of-two row count)
    #: plus the row probe — a distinct compile surface
    partial_encode: bool = False
    #: ROI QP changes the band programs' trace (per-MB qp plane +
    #: mb_qp_delta events) — compile identity, runtime-off by default.
    #: The bias value is part of the identity too: it is baked into the
    #: compiled program (a traced constant), so bias=4 and bias=6 band
    #: steps are different XLA builds
    roi_qp: bool = False
    roi_qp_bias: int = 4

    @property
    def program_key(self) -> str:
        """Compile identity: every field except the quality tier."""
        s = self
        parts = [f"{s.width}x{s.height}", s.codec, f"seats{s.seats}",
                 f"stripe{s.stripe_height}"]
        if s.stripe_devices > 1:
            parts.append(f"stripes{s.stripe_devices}")
        if s.partial_encode and s.codec == "h264":
            parts.append("bands")
            if s.roi_qp:
                parts.append(f"roi{s.roi_qp_bias}")
        if s.fullcolor:
            parts.append("444")
        if s.single_stream:
            parts.append("single")
        if not s.use_damage_gating:
            parts.append("nogate")
        if not s.use_paint_over:
            parts.append("nopaint")
        else:
            parts.append(f"paint{s.paint_over_delay_frames}")
        if s.codec == "h264":
            parts.append(f"mv{s.h264_motion_vrange}"
                         f"h{s.h264_motion_hrange}")
        return "/".join(parts)

    def scaled(self, factor: int) -> "Signature":
        """The capture-downscale rung's target geometry (same floor
        math as the ws actuator)."""
        return dataclasses.replace(
            self,
            width=max(GEOMETRY_FLOOR_PX, self.width // factor),
            height=max(GEOMETRY_FLOOR_PX, self.height // factor))

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["program_key"] = self.program_key
        return d


def downscale_factor(step: str) -> Optional[int]:
    """Downscale rungs carry their divisor in the name: ``downscale``
    (the stock rung, /2) or ``downscaleN``. None for non-geometry
    rungs."""
    if not step.startswith("downscale"):
        return None
    suffix = step[len("downscale"):]
    if not suffix:
        return 2
    try:
        f = int(suffix)
    except ValueError:
        return None
    return f if f >= 2 else None


@dataclasses.dataclass
class LatticePlan:
    """Enumeration result: the ordered, program-deduped signature list
    (base operating point first, then rung order — the worker's default
    compile order) plus the per-rung transition targets the ladder gate
    queries (program_keys needed by a down / up shift of each rung)."""

    base: Signature
    signatures: list
    #: step name -> {"down": [program_key...], "up": [program_key...]}
    rung_targets: dict

    @property
    def program_keys(self) -> list:
        return [s.program_key for s in self.signatures]

    def to_dict(self) -> dict:
        return {"base": self.base.to_dict(),
                "signatures": [s.to_dict() for s in self.signatures],
                "rung_targets": self.rung_targets}


def enumerate_lattice(base: Signature,
                      steps: Sequence[str] = ("fps", "quality",
                                              "downscale")) -> LatticePlan:
    """Walk the rung table cumulatively from ``base`` (the way the
    ladder actually degrades: each rung applies on top of the previous
    one) and collect every distinct compiled program along the way.

    - ``fps`` rungs never change a program (frame pacing is host-side);
    - ``quality`` rungs mint a "degraded" tier signature that DEDUPS
      onto the same program (quant/qp are runtime args) — enumerated so
      the lattice is honest about reachable operating points, deduped
      so the worker never compiles twice;
    - ``downscale[N]`` rungs mint a genuinely new program at the scaled
      geometry (the only rung class that can go cold).
    """
    signatures: list = []
    seen: set = set()

    def add(sig: Signature) -> None:
        if sig.program_key not in seen:
            seen.add(sig.program_key)
            signatures.append(sig)

    add(base)
    rung_targets: dict = {}
    current = base
    for step in steps:
        factor = downscale_factor(step)
        if factor is not None:
            nxt = current.scaled(factor)
            if nxt.program_key == current.program_key:
                # already at the geometry floor: rung is a no-op
                rung_targets[step] = {"down": [], "up": []}
                continue
            rung_targets[step] = {"down": [nxt.program_key],
                                  "up": [current.program_key]}
            add(nxt)
            current = nxt
        elif step == "quality":
            nxt = dataclasses.replace(current, quality_tier="degraded")
            # same program by construction — compile-free either way
            rung_targets[step] = {"down": [], "up": []}
            add(nxt)
            current = nxt
        else:
            # fps (and any unknown host-side rung): compile-free
            rung_targets[step] = {"down": [], "up": []}
    return LatticePlan(base=base, signatures=signatures,
                       rung_targets=rung_targets)


def lattice_from_settings(settings,
                          steps: Sequence[str] = ("fps", "quality",
                                                  "downscale"),
                          ) -> LatticePlan:
    """Base signature from an AppSettings-shaped object (any object with
    the attribute names; missing ones fall back to the engine defaults,
    so bench and tools can pass a plain namespace)."""
    def g(name, default):
        return getattr(settings, name, default)

    encoder = str(g("encoder", "jpeg-tpu"))
    base = Signature(
        width=int(g("initial_width", 1920)),
        height=int(g("initial_height", 1080)),
        codec="jpeg" if encoder.startswith("jpeg") else "h264",
        seats=max(1, int(g("tpu_seats", 1))),
        stripe_devices=max(1, int(g("tpu_stripe_devices", 1)))
        if not encoder.startswith("jpeg") else 1,
        fullcolor=bool(g("fullcolor", False)),
        stripe_height=int(g("stripe_height", 64)),
        single_stream=(encoder == "h264-tpu"),
        use_damage_gating=bool(g("use_damage_gating", True)),
        use_paint_over=bool(g("use_paint_over", True)),
        paint_over_delay_frames=int(g("paint_over_delay_frames", 15)),
        h264_motion_vrange=int(g("h264_motion_vrange", 24)),
        h264_motion_hrange=int(g("h264_motion_hrange", 8)),
        partial_encode=bool(g("h264_partial_encode", True))
        and bool(g("use_damage_gating", True))
        and not encoder.startswith("jpeg"),
        roi_qp=bool(g("h264_roi_qp", False)),
        roi_qp_bias=int(g("h264_roi_qp_bias", 4)),
    )
    plan = enumerate_lattice(base, steps)
    if bool(g("enable_broadcast", False)):
        # broadcast rendition rungs (ISSUE 17) warm alongside the
        # ladder's own points: every rung a viewer can be routed to is
        # compiled before the first viewer arrives
        have = set(plan.program_keys)
        for sig in broadcast_rung_signatures(
                base, max_rungs=int(g("broadcast_renditions", 3))):
            if sig.program_key not in have:
                have.add(sig.program_key)
                plan.signatures.append(sig)
    return plan


#: the broadcast rendition ladder's spatial factors (ISSUE 17):
#: src /1, mid /2, low /4 — the same ``scaled()`` derivation as the
#: degradation ladder's downscale rung, so broadcast rungs warm
#: through the identical step factories and never mint a compile
#: surface the lattice doesn't know
BROADCAST_RUNG_FACTORS = (1, 2, 4)


def broadcast_rung_signatures(base: Signature,
                              max_rungs: int = 3) -> list:
    """The rendition-ladder signatures for one broadcast desktop,
    program-deduped (a tiny desktop collapses the ladder at the
    geometry floor). The prewarm worker compiles these like any other
    lattice point; ``broadcast/ladder.py`` enumerates its rungs from
    the same derivation."""
    out: list = []
    seen: set = set()
    for factor in BROADCAST_RUNG_FACTORS[:max(1, int(max_rungs))]:
        sig = base if factor == 1 else base.scaled(factor)
        if sig.program_key in seen:
            continue
        seen.add(sig.program_key)
        out.append(sig)
    return out


def rung_targets_from(plan_or_mapping) -> Mapping:
    """Accept a LatticePlan or a bare mapping (test fakes)."""
    if isinstance(plan_or_mapping, LatticePlan):
        return plan_or_mapping.rung_targets
    return plan_or_mapping
