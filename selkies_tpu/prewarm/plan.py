"""Signature -> compiled-program mapping (the jax side of pre-warm).

A warm is only useful if it lands on the EXACT program the live session
will ask for, so this module goes through the same factory functions the
engine sessions use — :func:`engine.encoder._jitted_step` and
:func:`engine.h264_encoder._jitted_h264_step` are ``functools``-cached
on their build parameters, which means the pre-warmed
:class:`~..obs.perf._WrappedStep` IS the object a later session gets
back: its per-signature AOT cache already holds the compiled executable
and the first frame never compiles. Grid and buffer-capacity math is
imported from the engine (never duplicated) for the same reason: a
one-off divergence would warm a program nobody runs.

Compilation is AOT (``lower(...).compile()`` over ``ShapeDtypeStruct``
avals): nothing executes on the device, so a background warm never
steals a device slot from the encoder. The handful of small REAL arrays
a step signature needs (scalar qp/force, slice-header event tables) are
allocated under the engine's frame-turn lock so even those allocations
serialize against live capture threads. Multi-seat programs additionally
need a mesh + shardings; those warms build a throwaway encoder instance
(state arrays, no compile) and AOT-compile through its wrapped step —
the executable lands in the persistent compile cache (PR 2), which is
what a later real encoder's first call hits.
"""

from __future__ import annotations

import logging
import threading

from .lattice import Signature

logger = logging.getLogger("selkies_tpu.prewarm.plan")

__all__ = ["capture_settings_for", "program_names", "step_specs",
           "warm_signature"]

#: seat-program keys already AOT-compiled this process (their wrapped
#: steps are per-encoder-instance, so without this a re-warm would
#: rebuild mesh state for a program the persistent cache already holds)
_seat_warmed: set = set()
_seat_lock = threading.Lock()


def capture_settings_for(sig: Signature):
    """The CaptureSettings a live session would be built from at this
    operating point (quality knobs are runtime-only and irrelevant to
    the compiled program — defaults are fine)."""
    from ..engine.types import CaptureSettings
    return CaptureSettings(
        capture_width=sig.width, capture_height=sig.height,
        output_mode=sig.codec, fullcolor=sig.fullcolor,
        stripe_devices=max(1, int(getattr(sig, "stripe_devices", 1))),
        stripe_height=sig.stripe_height, single_stream=sig.single_stream,
        use_damage_gating=sig.use_damage_gating,
        use_paint_over=sig.use_paint_over,
        paint_over_delay_frames=sig.paint_over_delay_frames,
        h264_motion_vrange=sig.h264_motion_vrange,
        h264_motion_hrange=sig.h264_motion_hrange,
        h264_partial_encode=bool(getattr(sig, "partial_encode", False)),
        h264_roi_qp=bool(getattr(sig, "roi_qp", False)),
        h264_roi_qp_bias=int(getattr(sig, "roi_qp_bias", 4)))


def program_names(sig: Signature) -> list:
    """The ``obs.perf`` registry names this signature's programs carry
    (what ``wrap_step`` stamps at the engine compile sites)."""
    cs = capture_settings_for(sig)
    if sig.codec == "jpeg":
        from ..engine.encoder import _plan_grid
        g = _plan_grid(cs)
        sub = "444" if sig.fullcolor else "420"
        if sig.seats > 1:
            return [f"jpeg.seats{sig.seats}_step"
                    f"[{g.width}x{g.height}@{sub}]"]
        return [f"jpeg.step[{g.width}x{g.stripe_h * g.n_stripes}@{sub}]"]
    from ..engine.h264_encoder import plan_h264_grid
    g = plan_h264_grid(cs)
    if sig.seats > 1:
        return [f"h264.seats{sig.seats}_{m}_step[{g.width}x{g.height}]"
                for m in ("i", "p")]
    tag = "@444" if sig.fullcolor else ""
    if getattr(sig, "stripe_devices", 1) > 1:
        # the live session DEGRADES to the largest dividing count; the
        # warm must predict the same choice or it warms a ghost program
        from ..parallel.stripes import resolved_stripe_devices
        n = resolved_stripe_devices(g.n_stripes, sig.stripe_devices)
        if n > 1:
            # sharded sessions keep the stock device-parallel steps —
            # the partial path gates itself off (engine/h264_encoder),
            # so no band programs belong to this signature
            return [f"h264.stripes{n}.{m}_step"
                    f"[{g.width}x{g.stripe_h * g.n_stripes}{tag}]"
                    for m in ("i", "p")]
    names = [f"h264.{m}_step[{g.width}x{g.stripe_h * g.n_stripes}{tag}]"
             for m in ("i", "p")]
    names += _band_program_names(sig, g, tag)
    return names


def _band_buckets_for(sig: Signature, g) -> list:
    """The band-bucket row counts this signature's partial path can
    dispatch (ops/bands.band_buckets at the signature's granularity)."""
    if sig.codec == "jpeg" or not getattr(sig, "partial_encode", False) \
            or sig.seats > 1:
        return []
    from ..ops.bands import band_buckets
    n_rows = g.n_stripes * g.rows_per_stripe
    gran = g.rows_per_stripe if sig.h264_motion_vrange > 0 else 1
    return list(band_buckets(n_rows, gran))


def _band_program_names(sig: Signature, g, tag: str) -> list:
    buckets = _band_buckets_for(sig, g)
    if not buckets:
        return []
    # roi band steps carry the bias in the program name (it is baked
    # into the trace): a bias=4 warm must never satisfy a bias=6 gate
    roi = int(getattr(sig, "roi_qp_bias", 4)) \
        if getattr(sig, "roi_qp", False) else 0
    band_tag = f"{tag}+roi{roi}" if roi else tag
    names = [f"h264.row_probe[{g.width}x{g.stripe_h * g.n_stripes}]"]
    names += [f"h264.band{b}.p_step"
              f"[{g.width}x{g.stripe_h * g.n_stripes}{band_tag}]"
              for b in buckets]
    return names


def _aval(shape, dtype):
    import jax
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _specs_jpeg(sig: Signature) -> list:
    import jax.numpy as jnp

    from ..engine import encoder as _enc
    cs = capture_settings_for(sig)
    g = _enc.plan_grid(cs)
    sub = "444" if sig.fullcolor else "420"
    e_cap, w_cap, out_cap = _enc.jpeg_buffer_caps(g, sig.fullcolor)
    step = _enc._jitted_step(
        g.width, g.stripe_h, g.n_stripes, sub, e_cap, w_cap, out_cap,
        cs.paint_over_delay_frames, cs.use_damage_gating,
        cs.use_paint_over)
    frame = _aval((g.height, g.width, 3), jnp.uint8)
    age = _aval((g.n_stripes,), jnp.int32)
    qt = _aval((64,), jnp.float32)
    return [(step, (frame, frame, age, qt, qt, qt, qt))]


def _h264_headers(g, n_stripes: int):
    """Slice-header event tables, shaped exactly as the session builds
    them (small device arrays: allocated under the frame-turn lock by
    the caller)."""
    import jax.numpy as jnp
    import numpy as np

    from ..codecs import h264 as hcodec
    pay, nb = hcodec.slice_header_events(g.mb_w, g.rows_per_stripe)
    ppay, pnb = hcodec.p_slice_header_events(g.mb_w, g.rows_per_stripe)
    return (jnp.asarray(np.tile(pay, (n_stripes, 1))),
            jnp.asarray(np.tile(nb, (n_stripes, 1))),
            jnp.asarray(np.tile(ppay, (n_stripes, 1))),
            jnp.asarray(np.tile(pnb, (n_stripes, 1))))


def _specs_h264(sig: Signature) -> list:
    import jax.numpy as jnp

    from ..engine import h264_encoder as _h
    from ..engine.capture import _ENCODE_TURN
    from ..ops.h264_encode import scroll_candidates
    cs = capture_settings_for(sig)
    g = _h.plan_h264_grid(cs)
    e_cap, w_cap, out_cap = _h.h264_buffer_caps(g, sig.fullcolor)
    vr, hr = max(0, sig.h264_motion_vrange), max(0, sig.h264_motion_hrange)
    cdiv = 1 if sig.fullcolor else 2
    frame = _aval((g.height, g.width, 3), jnp.uint8)
    svec = _aval((g.n_stripes,), jnp.int32)
    ref_y = _aval((g.height, g.width), jnp.uint8)
    ref_c = _aval((g.height // cdiv, g.width // cdiv), jnp.uint8)
    with _ENCODE_TURN:      # small real allocations: serialize vs encode
        hdr_pay, hdr_nb, p_hdr_pay, p_hdr_nb = _h264_headers(
            g, g.n_stripes)
        qp = jnp.int32(0)
        force = jnp.asarray(True)
    specs = []
    for mode in ("i", "p"):
        cands = scroll_candidates(vr, hr) if (mode == "p" and vr) \
            else ((0, 0),)
        step = _h._jitted_h264_step(
            mode, g.width, g.stripe_h, g.n_stripes, e_cap, w_cap,
            out_cap, cs.paint_over_delay_frames, cs.use_damage_gating,
            cs.use_paint_over, candidates=cands,
            fullcolor=sig.fullcolor)
        pay, nb = (hdr_pay, hdr_nb) if mode == "i" \
            else (p_hdr_pay, p_hdr_nb)
        specs.append((step, (frame, frame, svec, svec, svec,
                             ref_y, ref_c, ref_c, qp, qp, force, pay, nb)))
    specs += _specs_h264_bands(sig, g, e_cap, w_cap, out_cap,
                               p_hdr_pay, p_hdr_nb)
    return specs


def _specs_h264_bands(sig: Signature, g, e_cap: int, w_cap: int,
                      out_cap: int, p_hdr_pay, p_hdr_nb) -> list:
    """The partial path's band-bucket family + row probe (ROADMAP 4) —
    the programs a partial-encode session can dispatch at runtime as
    the damage geometry moves between buckets."""
    buckets = _band_buckets_for(sig, g)
    if not buckets:
        return []
    import jax.numpy as jnp

    from ..engine import h264_encoder as _h
    from ..ops.h264_encode import scroll_candidates
    vr, hr = max(0, sig.h264_motion_vrange), max(0, sig.h264_motion_hrange)
    cands = scroll_candidates(vr, hr) if vr else ((0, 0),)
    cdiv = 1 if sig.fullcolor else 2
    # the SAME bias the runtime session will dispatch with — a traced
    # constant, so a different bias is a different program
    roi = int(getattr(sig, "roi_qp_bias", 4)) \
        if getattr(sig, "roi_qp", False) else 0
    frame = _aval((g.height, g.width, 3), jnp.uint8)
    svec = _aval((g.n_stripes,), jnp.int32)
    sbool = _aval((g.n_stripes,), jnp.bool_)
    ref_y = _aval((g.height, g.width), jnp.uint8)
    ref_c = _aval((g.height // cdiv, g.width // cdiv), jnp.uint8)
    row0 = _aval((), jnp.int32)
    probe = _h._jitted_row_damage_probe(g.width, g.height)
    specs = [(probe, (frame, frame))]
    for b in buckets:
        qp_rows = _aval((b,), jnp.int32)
        step = _h._jitted_h264_band_step(
            g.width, g.stripe_h, g.n_stripes, b, e_cap, w_cap, out_cap,
            cands, fullcolor=sig.fullcolor, roi_qp=roi)
        specs.append((step, (frame, frame, svec, svec, ref_y, ref_c,
                             ref_c, qp_rows, sbool, row0,
                             p_hdr_pay, p_hdr_nb)))
    return specs


def _specs_h264_stripes(sig: Signature, n_dev: int) -> list:
    """The split-frame sharded i/p steps (ROADMAP 2): same aval surface
    as the single-device warm, through the SAME
    ``_jitted_h264_sharded_step`` factory the live session uses."""
    import jax.numpy as jnp

    from ..engine import h264_encoder as _h
    from ..engine.capture import _ENCODE_TURN
    from ..ops.h264_encode import scroll_candidates
    cs = capture_settings_for(sig)
    g = _h.plan_h264_grid(cs)
    e_cap, w_cap, out_cap = _h.h264_buffer_caps(g, sig.fullcolor)
    out_cap_local = -(-out_cap // n_dev)
    vr, hr = max(0, sig.h264_motion_vrange), max(0, sig.h264_motion_hrange)
    cdiv = 1 if sig.fullcolor else 2
    frame = _aval((g.height, g.width, 3), jnp.uint8)
    svec = _aval((g.n_stripes,), jnp.int32)
    ref_y = _aval((g.height, g.width), jnp.uint8)
    ref_c = _aval((g.height // cdiv, g.width // cdiv), jnp.uint8)
    with _ENCODE_TURN:
        hdr_pay, hdr_nb, p_hdr_pay, p_hdr_nb = _h264_headers(
            g, g.n_stripes)
        qp = jnp.int32(0)
        force = jnp.asarray(True)
    specs = []
    for mode in ("i", "p"):
        cands = scroll_candidates(vr, hr) if (mode == "p" and vr) \
            else ((0, 0),)
        step = _h._jitted_h264_sharded_step(
            mode, g.width, g.stripe_h, g.n_stripes, e_cap, w_cap,
            out_cap_local, cs.paint_over_delay_frames,
            cs.use_damage_gating, cs.use_paint_over, candidates=cands,
            fullcolor=sig.fullcolor, n_dev=n_dev)
        pay, nb = (hdr_pay, hdr_nb) if mode == "i" \
            else (p_hdr_pay, p_hdr_nb)
        specs.append((step, (frame, frame, svec, svec, svec,
                             ref_y, ref_c, ref_c, qp, qp, force,
                             pay, nb)))
    return specs


def _specs_jpeg_seats(sig: Signature) -> list:
    import jax
    import jax.numpy as jnp

    from ..engine.capture import _ENCODE_TURN
    from ..parallel.seats import MultiSeatEncoder
    cs = capture_settings_for(sig)
    with _ENCODE_TURN:      # constructor device_puts: serialize
        enc = MultiSeatEncoder(cs, sig.seats)
    g = enc.grid
    frames = jax.ShapeDtypeStruct(
        (sig.seats, g.height, g.width, 3), jnp.uint8,
        sharding=enc.input_sharding)
    return [(enc._step, (frames, frames, enc._age, *enc._qt_dev))]


def _specs_h264_seats(sig: Signature) -> list:
    import jax.numpy as jnp
    import numpy as np
    import jax

    from ..engine.capture import _ENCODE_TURN
    from ..parallel.h264_seats import MultiSeatH264Encoder
    cs = capture_settings_for(sig)
    with _ENCODE_TURN:
        enc = MultiSeatH264Encoder(cs, sig.seats)
        n = sig.seats
        qp = jax.device_put(np.zeros((n,), np.int32), enc.input_sharding)
        forces = jax.device_put(np.ones((n,), bool), enc.input_sharding)
    g = enc.grid
    frames = jax.ShapeDtypeStruct(
        (n, g.height, g.width, 3), jnp.uint8, sharding=enc.input_sharding)
    specs = []
    for step, pay, nb in ((enc._i_step, enc._hdr_pay, enc._hdr_nb),
                          (enc._p_step, enc._p_hdr_pay, enc._p_hdr_nb)):
        specs.append((step, (frames, frames, enc._age, enc._sent,
                             enc._fnum, enc._ref_y, enc._ref_u,
                             enc._ref_v, qp, qp, forces, pay, nb)))
    return specs


def _step_specs(sig: Signature) -> tuple:
    """-> ``(specs, meta)``: every ``(wrapped_step, trace_args)`` pair
    behind ``sig``, built through the SAME factories the live sessions
    use. ``meta`` carries an ``unreachable`` note when the signature's
    requested device parallelism cannot be realised on this host (the
    worker reports those points distinctly from failures)."""
    meta: dict = {}
    if sig.seats > 1:
        specs = _specs_jpeg_seats(sig) if sig.codec == "jpeg" \
            else _specs_h264_seats(sig)
        return specs, meta
    if sig.codec != "jpeg" and getattr(sig, "stripe_devices", 1) > 1:
        from ..engine.h264_encoder import plan_h264_grid
        from ..parallel.stripes import resolved_stripe_devices
        g = plan_h264_grid(capture_settings_for(sig))
        n = resolved_stripe_devices(g.n_stripes, sig.stripe_devices)
        if n > 1:
            if n != sig.stripe_devices:
                meta["unreachable"] = (
                    f"stripe_devices={sig.stripe_devices} resolves to "
                    f"{n} on this host; stripes{n} programs warm "
                    "instead")
            return _specs_h264_stripes(sig, n), meta
        # degraded all the way to one device: the plain program IS the
        # operating point — fall through to the single-device specs
        meta["unreachable"] = (
            f"stripe_devices={sig.stripe_devices} resolves to 1 on "
            "this host; single-device programs warm instead")
    specs = _specs_jpeg(sig) if sig.codec == "jpeg" else _specs_h264(sig)
    return specs, meta


def step_specs(sig: Signature) -> list:
    """The analyzer surface (graftlint v3, analysis/surface.py): the
    exact ``(wrapped_step, trace_args)`` pairs :func:`warm_signature`
    would AOT-compile for ``sig`` — same factories, same avals, nothing
    executed. Keeping one enumeration for warm AND lint is the point:
    a program the analyzer traces is BY CONSTRUCTION a program prewarm
    warms and a session dispatches."""
    return _step_specs(sig)[0]


def warm_signature(sig: Signature) -> dict:
    """AOT-compile every program behind ``sig``; -> {"programs": [names]}.
    Raises on any program that cannot be built (the worker records the
    signature as failed — the ladder then never routes through it).

    ``SELKIES_PERF_ANALYSIS=0`` (the obs.perf kill switch) disables the
    AOT path entirely — every signature dispatches through plain jit —
    so there is nothing to pre-warm: report ``disabled`` (the worker
    marks the entry skipped and the ladder gate FAILS OPEN, restoring
    the pre-compile-plane behaviour) instead of reading the fallback as
    a compile failure that would flip /api/health to failed.

    A signature whose device parallelism degrades on this host (e.g.
    ``stripe_devices=4`` on a 1-device box) warms the program the
    runtime would actually dispatch and additionally reports
    ``unreachable`` — the worker surfaces those lattice points
    distinctly so LATTICE-COMPLETENESS findings and runtime deferrals
    can be cross-referenced."""
    import os
    if os.environ.get("SELKIES_PERF_ANALYSIS") == "0":
        return {"programs": [], "disabled": "SELKIES_PERF_ANALYSIS=0"}
    if sig.seats > 1:
        key = sig.program_key
        with _seat_lock:
            if key in _seat_warmed:
                return {"programs": program_names(sig), "cached": True}
    specs, meta = _step_specs(sig)
    names = []
    for step, args in specs:
        if not step.warm(args):
            raise RuntimeError(f"{step.name} warm failed "
                               "(see obs.perf log)")
        names.append(step.name)
    if sig.seats > 1:
        with _seat_lock:
            _seat_warmed.add(sig.program_key)
    result = {"programs": names}
    result.update(meta)
    return result
