"""Background pre-warm worker + the ladder's transition gate.

The worker owns a registry of lattice signatures and drives each one
``pending -> compiling -> warm`` (or ``failed``) on a dedicated thread:

- **order**: the current operating point's programs first (the rung the
  ladder would visit next under load is a neighbour of where the server
  IS, so the live geometry's neighbourhood warms before speculative
  corners), then lattice order — which :func:`..lattice.enumerate_lattice`
  emits lowest-rung-first. :meth:`request` promotes keys to the front
  (the ladder's deferred-transition path);
- **pacing**: the worker pauses while ``storm_check()`` reports the
  device monitor's compile-storm detector firing — when the frame path
  is already compile-bound, speculative background builds would pile
  onto the same XLA queue. Compilation itself is host-side AOT
  (:mod:`.plan` lowers ``ShapeDtypeStruct`` avals — nothing executes on
  the device), so a warm never steals a device slot from the encoder;
- **supervision**: the thread reports its own death through
  :attr:`on_death` (the PR-5 supervisor adopts :meth:`restart`), and
  :meth:`health_check` is the ``prewarm`` verdict: failed when any
  program failed to build, degraded when the worker died with work
  pending, ok otherwise (warming is progress, not degradation).

:class:`PrewarmGate` adapts the worker to the degradation ladder's gate
protocol: ``query(step, direction)`` answers warm/cold from the rung's
target programs, ``request`` promotes them. Rungs with no compiled
target (fps, quality) are warm by construction.

Stdlib-only: the injectable ``compiler`` seam keeps jax out of this
module (the default lazily imports :mod:`.plan`); the selftest and unit
tests drive everything with fakes.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable, Optional

from .lattice import LatticePlan, Signature

logger = logging.getLogger("selkies_tpu.prewarm.worker")

__all__ = ["PrewarmWorker", "PrewarmGate",
           "PENDING", "COMPILING", "WARM", "FAILED", "SKIPPED",
           "UNREACHABLE"]

PENDING = "pending"
COMPILING = "compiling"
WARM = "warm"
FAILED = "failed"
#: pre-warm is disabled for this program (perf-analysis kill switch):
#: not warm, not failed — the gate fails OPEN for skipped programs
SKIPPED = "skipped"
#: the lattice point's requested device parallelism cannot be realised
#: on this host (e.g. stripe_devices=4 on a 1-device box): the DEGRADED
#: program the runtime would actually dispatch was warmed instead.
#: Distinct from FAILED (nothing broke) and from SKIPPED (nothing was
#: disabled) so /api/prewarm and the health check can be cross-
#: referenced against LATTICE-COMPLETENESS findings (graftlint v3)
UNREACHABLE = "unreachable"

#: how often the paused/idle loop re-checks for work or storm clearance
_POLL_S = 1.0


def _default_compiler(sig: Signature) -> dict:
    """AOT-compile every program behind ``sig`` (jax side, lazy)."""
    from . import plan
    return plan.warm_signature(sig)


class PrewarmWorker:
    """Lattice compile driver. One instance per server (``core`` owns
    it); bench and tests build their own with fake compilers."""

    def __init__(self, plan_: Optional[LatticePlan] = None, *,
                 compiler: Optional[Callable[[Signature], dict]] = None,
                 storm_check: Optional[Callable[[], bool]] = None,
                 recorder=None,
                 clock: Callable[[], float] = time.monotonic,
                 poll_s: float = _POLL_S):
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.compiler = compiler or _default_compiler
        self.storm_check = storm_check
        self.recorder = recorder
        self._clock = clock
        self.poll_s = float(poll_s)
        self.paused = False             # storm (or manual) hold
        self._manual_pause = False
        self.started_at: Optional[float] = None
        self.on_death: Optional[Callable[[BaseException], None]] = None
        #: program_key -> entry dict (insertion order == compile order)
        self._entries: collections.OrderedDict = collections.OrderedDict()
        self._order: list = []          # pending keys, priority order
        self.current_op: Optional[tuple] = None
        self.compile_seconds_total = 0.0
        if plan_ is not None:
            for sig in plan_.signatures:
                self.ensure(sig)

    # -- registry ------------------------------------------------------------
    def ensure(self, sig: Signature, front: bool = False) -> str:
        """Track a signature (idempotent); -> its program_key."""
        key = sig.program_key
        with self._lock:
            if key not in self._entries:
                self._entries[key] = {
                    "sig": sig, "state": PENDING, "seconds": None,
                    "error": None, "programs": [], "attempts": 0,
                }
                if front:
                    self._order.insert(0, key)
                else:
                    self._order.append(key)
        self._wake.set()
        return key

    def request(self, keys) -> int:
        """Promote ``keys`` to the front of the queue (deferred ladder
        transitions land here); -> how many were still pending."""
        promoted = 0
        with self._lock:
            for key in reversed(list(keys)):
                if key in self._order:
                    self._order.remove(key)
                    self._order.insert(0, key)
                    promoted += 1
        if promoted:
            self._wake.set()
        return promoted

    def note_operating_point(self, width: int, height: int) -> None:
        """The live engine's current geometry: its programs compile
        first, then the rest of the lattice in rung order."""
        with self._lock:
            self.current_op = (int(width), int(height))
            front = [k for k in self._order
                     if (self._entries[k]["sig"].width,
                         self._entries[k]["sig"].height)
                     == self.current_op]
            rest = [k for k in self._order if k not in front]
            self._order = front + rest
        if front:
            self._wake.set()

    def query(self, keys) -> str:
        """'warm' when every key's program is compiled, else 'cold'
        (unknown keys are cold — a rung outside the tracked lattice
        must defer, not sail into a foreground compile). SKIPPED
        programs answer warm: pre-warm is disabled there, and the gate
        failing open restores the pre-compile-plane behaviour instead
        of deferring a transition nothing will ever warm."""
        with self._lock:
            for key in keys:
                e = self._entries.get(key)
                if e is None or e["state"] not in (WARM, SKIPPED,
                                                   UNREACHABLE):
                    return "cold"
        return "warm"

    def states(self) -> dict:
        with self._lock:
            return {k: e["state"] for k, e in self._entries.items()}

    def mark_warm_from_names(self, warm_names,
                             names_fn: Callable[[Signature], list]) -> int:
        """Adopt already-compiled programs (e.g. the perf registry's
        record of what this process built): an entry whose every program
        name is in ``warm_names`` is warm without recompiling."""
        warm_names = set(warm_names)
        adopted = 0
        with self._lock:
            entries = list(self._entries.items())
        for key, e in entries:
            if e["state"] == WARM:
                continue
            try:
                names = list(names_fn(e["sig"]))
            except Exception:
                continue
            if names and all(n in warm_names for n in names):
                with self._lock:
                    e["state"] = WARM
                    e["programs"] = names
                    if key in self._order:
                        self._order.remove(key)
                adopted += 1
        if adopted:
            self._update_metrics()
        return adopted

    # -- lifecycle -----------------------------------------------------------
    @property
    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> None:
        if self.alive:
            return
        self._stop.clear()
        self.started_at = self._clock()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="prewarm")
        self._thread.start()

    def restart(self) -> None:
        """Supervisor restart callable: join the dead thread, start a
        fresh one over the same registry (compiled entries stay warm)."""
        self.stop(join_s=2.0)
        with self._lock:
            # a death mid-compile leaves a stale 'compiling' entry
            for key, e in self._entries.items():
                if e["state"] == COMPILING:
                    e["state"] = PENDING
                    if key not in self._order:
                        self._order.insert(0, key)
        self.start()

    def stop(self, join_s: float = 5.0) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=join_s)
        self._thread = None

    def pause(self) -> None:
        self._manual_pause = True

    def resume(self) -> None:
        self._manual_pause = False
        self._wake.set()

    # -- compile loop --------------------------------------------------------
    def _next_pending(self) -> Optional[str]:
        with self._lock:
            while self._order:
                key = self._order[0]
                e = self._entries.get(key)
                if e is None or e["state"] not in (PENDING,):
                    self._order.pop(0)
                    continue
                return key
        return None

    def _storming(self) -> bool:
        if self._manual_pause:
            return True
        if self.storm_check is None:
            return False
        try:
            return bool(self.storm_check())
        except Exception:
            return False

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                key = self._next_pending()
                if key is None:
                    self._update_metrics()
                    self._wake.clear()
                    self._wake.wait(self.poll_s * 10)
                    continue
                if self._storming():
                    if not self.paused:
                        self.paused = True
                        logger.warning("prewarm paused: compile storm "
                                       "active on the frame path")
                        self._update_metrics()
                    self._stop.wait(self.poll_s)
                    continue
                if self.paused:
                    self.paused = False
                    logger.info("prewarm resumed")
                self._compile_one(key)
        except BaseException as e:   # noqa: BLE001 — supervision hook
            if not self._stop.is_set():
                logger.exception("prewarm worker died")
                hook = self.on_death
                if hook is not None:
                    try:
                        hook(e)
                    except Exception:
                        logger.exception("prewarm on_death hook failed")
            if not isinstance(e, Exception):
                raise

    def run_pending_sync(self, budget_s: Optional[float] = None) -> int:
        """Compile everything pending on the CALLER's thread (tools /
        image-build warm where no background thread makes sense).
        -> number of programs that reached warm."""
        done = 0
        deadline = None if budget_s is None else self._clock() + budget_s
        while True:
            if deadline is not None and self._clock() >= deadline:
                break
            key = self._next_pending()
            if key is None:
                break
            if self._compile_one(key):
                done += 1
        return done

    def _compile_one(self, key: str) -> bool:
        with self._lock:
            e = self._entries.get(key)
            if e is None or e["state"] != PENDING:
                return False
            e["state"] = COMPILING
            e["attempts"] += 1
            if key in self._order:
                self._order.remove(key)
        self._update_metrics()
        sig = e["sig"]
        t0 = self._clock()
        try:
            result = self.compiler(sig) or {}
            seconds = round(self._clock() - t0, 3)
            disabled = result.get("disabled")
            unreachable = result.get("unreachable")
            with self._lock:
                e["state"] = SKIPPED if disabled \
                    else (UNREACHABLE if unreachable else WARM)
                e["seconds"] = seconds
                e["programs"] = list(result.get("programs", []))
                if disabled:
                    e["error"] = f"prewarm disabled: {disabled}"
                elif unreachable:
                    e["error"] = f"unreachable: {unreachable}"
                self.compile_seconds_total += seconds
            if disabled:
                logger.info("prewarm: %s skipped (%s)", key, disabled)
            elif unreachable:
                # the degraded programs (if any) DID warm; the lattice
                # point as enumerated cannot exist on this host
                logger.info("prewarm: %s unreachable (%s)", key,
                            unreachable)
                self._record("prewarm_unreachable", key=key,
                             reason=str(unreachable))
            else:
                logger.info("prewarm: %s warm in %.1fs", key, seconds)
                self._record("prewarm_compiled", key=key,
                             seconds=seconds)
            self._update_metrics()
            return True
        except Exception as exc:
            seconds = round(self._clock() - t0, 3)
            with self._lock:
                e["state"] = FAILED
                e["seconds"] = seconds
                e["error"] = f"{type(exc).__name__}: {exc}"[:200]
            logger.exception("prewarm: %s failed after %.1fs", key, seconds)
            self._record("prewarm_failed", key=key, error=e["error"])
            self._update_metrics()
            return False

    def _record(self, kind: str, **fields) -> None:
        rec = self.recorder
        if rec is None:
            return
        try:
            rec.record(kind, **fields)
        except Exception:
            logger.debug("prewarm incident record failed", exc_info=True)

    # -- reporting -----------------------------------------------------------
    def counts(self) -> dict:
        with self._lock:
            c = collections.Counter(e["state"]
                                    for e in self._entries.values())
        return {"lattice_size": sum(c.values()), "warmed": c[WARM],
                "pending": c[PENDING], "compiling": c[COMPILING],
                "failed": c[FAILED], "skipped": c[SKIPPED],
                "unreachable": c[UNREACHABLE]}

    def snapshot(self) -> dict:
        with self._lock:
            entries = [{
                "key": k, "state": e["state"], "seconds": e["seconds"],
                "error": e["error"], "programs": list(e["programs"]),
                "attempts": e["attempts"],
                "geometry": f'{e["sig"].width}x{e["sig"].height}',
                "codec": e["sig"].codec, "seats": e["sig"].seats,
                "quality_tier": e["sig"].quality_tier,
            } for k, e in self._entries.items()]
            current_op = self.current_op
        doc = self.counts()
        doc.update({
            "alive": self.alive, "paused": self.paused,
            "current_op": (f"{current_op[0]}x{current_op[1]}"
                           if current_op else None),
            "compile_seconds_total": round(self.compile_seconds_total, 3),
            "entries": entries,
        })
        return doc

    def health_check(self):
        """The ``prewarm`` verdict. Warming is not a degradation (the
        live session keeps encoding while the lattice fills); a FAILED
        program is — that rung would defer forever."""
        from ..obs import health as _health
        c = self.counts()
        if c["failed"]:
            with self._lock:
                bad = sorted(k for k, e in self._entries.items()
                             if e["state"] == FAILED)
            return _health.failed(
                f"{c['failed']}/{c['lattice_size']} lattice programs "
                f"failed to warm: {', '.join(bad[:3])}", **c)
        backlog = c["pending"] + c["compiling"]
        if backlog and self.started_at is not None and not self.alive:
            return _health.degraded(
                f"prewarm worker not running with {backlog} programs "
                "cold", **c)
        if self.paused and backlog:
            return _health.degraded(
                f"prewarm paused (compile storm) with {backlog} "
                "programs cold", **c)
        if backlog:
            return _health.ok(
                f"warming: {c['warmed']}/{c['lattice_size']} warm", **c)
        if c["skipped"]:
            return _health.ok(
                f"prewarm disabled for {c['skipped']} programs "
                "(perf-analysis kill switch); gate fails open", **c)
        if c["unreachable"]:
            # not a degradation: the host simply cannot realise those
            # lattice points (the runtime would degrade identically);
            # named distinctly so operators can cross-reference against
            # LATTICE-COMPLETENESS findings
            return _health.ok(
                f"lattice warm ({c['warmed']} programs; "
                f"{c['unreachable']} points unreachable on this host)",
                **c)
        return _health.ok(
            f"lattice warm ({c['warmed']} programs)", **c)

    def warm_geometries(self) -> list:
        """Sorted ``"WxH"`` strings whose every tracked program is warm
        (or skipped) — the fleet heartbeat's warm-host signal: the
        scheduler scores a host up when a session's geometry appears
        here (placing there costs no foreground compile).

        Split-frame sharded operating points (ROADMAP 2) advertise as
        ``"WxH@sN"`` entries so a stripe-sharded warm is schedulable
        capacity in its own right and never masquerades as (or hides)
        the single-device program at the same geometry."""
        by_geo: dict = {}
        with self._lock:
            for e in self._entries.values():
                sig = e["sig"]
                if e["state"] == UNREACHABLE:
                    # never advertise capacity the host cannot realise
                    # (an @sN entry for a mesh that degraded away would
                    # tell the scheduler this host shards when it
                    # cannot) — and never block the geometry either
                    continue
                geo = (sig.width, sig.height,
                       max(1, int(getattr(sig, "stripe_devices", 1))))
                ok_ = e["state"] in (WARM, SKIPPED)
                by_geo[geo] = by_geo.get(geo, True) and ok_
        return sorted(
            (f"{w}x{h}" if sd <= 1 else f"{w}x{h}@s{sd}")
            for (w, h, sd), ok_ in by_geo.items() if ok_)

    def current_op_ready(self):
        """The ``prewarm_ready`` routing-gate verdict (ISSUE 11 /
        ROADMAP 3): FAILED until every program behind the CURRENT
        operating point is warm — the load balancer's "don't route to a
        cold host" answer. This is deliberately a gate, not a health
        check: a warming host is healthy, it is just not routable yet.

        Fail-open cases: no tracked lattice (prewarm disabled upstream)
        and an operating point outside the lattice (nothing will ever
        warm it — deferring forever would blackhole the host) both
        answer ok."""
        from ..obs import health as _health
        with self._lock:
            if not self._entries:
                return _health.ok("no lattice tracked; gate open")
            op = self.current_op
            if op is None:
                return _health.failed(
                    "no operating point recorded yet (cold boot)")
            entries = [e for e in self._entries.values()
                       if (e["sig"].width, e["sig"].height) == op]
            if not entries:
                return _health.ok(
                    f"operating point {op[0]}x{op[1]} outside the "
                    "lattice; gate fails open")
            cold = [e["sig"].program_key for e in entries
                    if e["state"] not in (WARM, SKIPPED, UNREACHABLE)]
            bad = [e["sig"].program_key for e in entries
                   if e["state"] == FAILED]
        if bad:
            return _health.failed(
                f"operating-point program(s) failed to warm: "
                f"{', '.join(sorted(bad)[:3])}")
        if cold:
            return _health.failed(
                f"warming {op[0]}x{op[1]}: {len(cold)} program(s) "
                f"cold ({', '.join(sorted(cold)[:3])})")
        return _health.ok(
            f"operating point {op[0]}x{op[1]} warm")

    def _update_metrics(self) -> None:
        try:
            from ..server import metrics
        except Exception:
            return
        c = self.counts()
        metrics.describe("selkies_prewarm_lattice_size",
                         "Reachable signature-lattice programs tracked")
        metrics.describe("selkies_prewarm_warmed",
                         "Lattice programs compiled and ready")
        metrics.describe("selkies_prewarm_pending",
                         "Lattice programs still cold")
        metrics.describe("selkies_prewarm_failed",
                         "Lattice programs that failed to compile")
        metrics.describe("selkies_prewarm_paused",
                         "1 while the worker is holding for a compile "
                         "storm")
        metrics.describe("selkies_prewarm_unreachable",
                         "Lattice points whose requested device "
                         "parallelism this host cannot realise")
        metrics.set_gauge("selkies_prewarm_lattice_size",
                          c["lattice_size"])
        metrics.set_gauge("selkies_prewarm_warmed", c["warmed"])
        metrics.set_gauge("selkies_prewarm_pending",
                          c["pending"] + c["compiling"])
        metrics.set_gauge("selkies_prewarm_failed", c["failed"])
        metrics.set_gauge("selkies_prewarm_unreachable",
                          c["unreachable"])
        metrics.set_gauge("selkies_prewarm_paused",
                          1 if self.paused else 0)


class PrewarmGate:
    """The degradation ladder's transition gate over a worker.

    ``rung_targets`` is the lattice plan's ``{step: {"down": [keys],
    "up": [keys]}}`` mapping. A rung with no mapped programs (fps,
    quality — or any rung the lattice never heard of) is warm by
    construction: only geometry/signature-changing rungs can defer.
    """

    def __init__(self, worker: PrewarmWorker, rung_targets: dict):
        self.worker = worker
        self.rung_targets = dict(rung_targets)

    def _keys(self, step: str, direction: int) -> list:
        t = self.rung_targets.get(step)
        if not t:
            return []
        return list(t.get("down" if direction > 0 else "up", []))

    def query(self, step: str, direction: int) -> str:
        keys = self._keys(step, direction)
        if not keys:
            return "warm"
        return self.worker.query(keys)

    def request(self, step: str, direction: int) -> None:
        keys = self._keys(step, direction)
        if keys:
            self.worker.request(keys)
