"""Binary wire protocol + text verb surface.

Implements the reference's client/server framing exactly (SURVEY.md §2.3;
reference parse sites: addons/selkies-web-core/selkies-ws-core.js:4255-4460,
src/selkies/selkies.py:604-621, 2504-3235):

Binary frames (first byte = opcode):
- ``0x01`` audio (server→client): ``[0x01, n_red]`` + Opus payload. When
  ``n_red > 0`` the payload is RFC-2198 RED framed:
  ``u32 pts + n_red*(4-byte block hdr) + 1-byte primary hdr + blocks``.
- ``0x02`` mic (client→server): raw PCM chunk.
- ``0x03`` JPEG stripe (server→client), 6-byte header:
  ``[0x03, flags, u16 frame_id, u16 stripe_y]`` + JFIF bytes.
- ``0x04`` H.264 stripe (server→client), 10-byte header:
  ``[0x04, frame_type(0x01=IDR), u16 frame_id, u16 y_start, u16 w, u16 h]``
  + Annex-B access unit.
- ``0x05`` gzip-compressed control text, both directions, only for messages
  over the compression threshold.

All u16/u32 are big-endian (network order), matching the JS DataView default
reads in the reference client.
"""

from __future__ import annotations

import dataclasses
import gzip
import math
import struct
import zlib
from typing import Iterable

# Bounded control-message sizes (reference settings.py:37-60): text frames
# above WS_COMPRESSION_THRESHOLD are gzip'd with opcode 0x05; inflation is
# bounded to defeat zip bombs.
WS_MAX_MESSAGE_BYTES = 8 * 1024 * 1024
WS_MESSAGE_SIZE_HARD_CAP = 64 * 1024 * 1024
WS_COMPRESSION_THRESHOLD = 512


def inflate_gz_bounded(data: bytes, limit: int = WS_MAX_MESSAGE_BYTES) -> bytes:
    """Gunzip ``data`` refusing to inflate beyond ``limit`` bytes.

    Mirrors the reference's bounded gzip helper (settings.py:37-60): client
    supplied gzip blobs must never balloon server memory, and both truncated
    streams and trailing garbage are rejected.
    """
    out = bytearray()
    dec = zlib.decompressobj(16 + zlib.MAX_WBITS)
    out += dec.decompress(data, limit + 1)
    while dec.unconsumed_tail and len(out) <= limit:
        out += dec.decompress(dec.unconsumed_tail, limit + 1 - len(out))
    if len(out) > limit:
        raise ValueError(f"gzip payload inflates beyond {limit} bytes")
    if not dec.eof:
        raise ValueError("truncated gzip payload")
    if dec.unused_data:
        raise ValueError("trailing garbage after gzip payload")
    return bytes(out)

OP_AUDIO = 0x01
OP_MIC = 0x02
OP_JPEG = 0x03
OP_H264 = 0x04
OP_GZ_CONTROL = 0x05

FRAME_TYPE_DELTA = 0x00
FRAME_TYPE_IDR = 0x01

# uint16 circular frame-id space for ACK distance math
# (reference selkies.py:1590-1717).
FRAME_ID_MOD = 1 << 16

_H264_HDR = struct.Struct(">BBHHHH")
_JPEG_HDR = struct.Struct(">BBHH")


def pack_h264_stripe(frame_id: int, y_start: int, width: int, height: int,
                     payload: bytes | memoryview, idr: bool) -> bytes:
    """10-byte ``0x04`` header + Annex-B payload (selkies-ws-core.js:4338-4352)."""
    hdr = _H264_HDR.pack(OP_H264, FRAME_TYPE_IDR if idr else FRAME_TYPE_DELTA,
                         frame_id % FRAME_ID_MOD, y_start, width, height)
    return hdr + bytes(payload)


def unpack_h264_header(buf: bytes | memoryview) -> tuple[int, int, int, int, int]:
    """→ (frame_type, frame_id, y_start, w, h). Payload begins at byte 10."""
    try:
        op, ftype, fid, y, w, h = _H264_HDR.unpack_from(buf, 0)
    except struct.error as e:
        raise ValueError(f"malformed h264 frame header: {e}") from e
    if op != OP_H264:
        raise ValueError(f"not an h264 frame (op={op:#x})")
    return ftype, fid, y, w, h


def pack_jpeg_stripe(frame_id: int, stripe_y: int, payload: bytes | memoryview,
                     flags: int = 0) -> bytes:
    """6-byte ``0x03`` header + JPEG bytes (selkies-ws-core.js:4317-4337)."""
    return _JPEG_HDR.pack(OP_JPEG, flags, frame_id % FRAME_ID_MOD, stripe_y) \
        + bytes(payload)


def unpack_jpeg_header(buf: bytes | memoryview) -> tuple[int, int, int]:
    """→ (flags, frame_id, stripe_y). Payload begins at byte 6."""
    try:
        op, flags, fid, y = _JPEG_HDR.unpack_from(buf, 0)
    except struct.error as e:
        raise ValueError(f"malformed jpeg frame header: {e}") from e
    if op != OP_JPEG:
        raise ValueError(f"not a jpeg frame (op={op:#x})")
    return flags, fid, y


def pack_audio(payload: bytes, n_red: int = 0) -> bytes:
    """``[0x01, n_red]`` + Opus/RED payload (selkies-ws-core.js:36-38)."""
    return bytes((OP_AUDIO, n_red)) + payload


def pack_red_payload(pts_90k: int, primary: bytes,
                     redundant: Iterable[tuple[int, bytes]]) -> bytes:
    """RFC-2198 RED framing for Opus (reference pcmflux native framing).

    ``redundant`` is oldest-first ``(ts_offset_90k, opus_frame)`` pairs.
    Block header: 1 bit F=1, 7-bit PT, 14-bit ts offset, 10-bit length;
    primary header: F=0 + 7-bit PT. PT is fixed 111 (dynamic Opus).
    """
    pt = 111
    out = bytearray(struct.pack(">I", pts_90k & 0xFFFFFFFF))
    red_list = list(redundant)
    for ts_off, blk in red_list:
        if len(blk) >= 1 << 10:
            raise ValueError("RED block too large for 10-bit length")
        if not 0 <= ts_off < 1 << 14:
            raise ValueError("RED ts offset out of 14-bit range")
        word = (1 << 31) | (pt << 24) | (ts_off << 10) | len(blk)
        out += struct.pack(">I", word)
    out.append(pt)  # F=0 primary header
    for _, blk in red_list:
        out += blk
    out += primary
    return bytes(out)


def frame_id_distance(newest: int, acked: int) -> int:
    """Forward distance in uint16 circular space (reference selkies.py:61-110)."""
    return (newest - acked) % FRAME_ID_MOD


def maybe_compress_text(text: str, threshold: int = WS_COMPRESSION_THRESHOLD
                        ) -> bytes | str:
    """Return ``0x05`` + gzip when the message is worth compressing, else the
    original text (reference selkies.py:375, 2381-2395)."""
    raw = text.encode("utf-8")
    if len(raw) < threshold:
        return text
    return bytes((OP_GZ_CONTROL,)) + gzip.compress(raw, 6)


def decompress_control(buf: bytes | memoryview) -> str:
    b = bytes(buf)
    if not b or b[0] != OP_GZ_CONTROL:
        raise ValueError("not a 0x05 control frame")
    return inflate_gz_bounded(b[1:]).decode("utf-8")


# ---------------------------------------------------------------------------
# Text verbs (client→server), SURVEY §2.3. A thin parsed representation so
# the dispatcher (server/websockets_service.py, input/handler.py) stays flat.
# ---------------------------------------------------------------------------

#: verbs a view-only client may still send (reference
#: input_handler.py:110-128 viewer-authority prefix lists).
VIEWER_ALLOWED_PREFIXES = (
    "_gz", "SETTINGS", "CLIENT_FRAME_ACK", "CLIENT_FRAME_TIMING",
    "CLIENT_CLOCK", "CLIENT_STATS", "START_VIDEO", "STOP_VIDEO",
    "REQUEST_KEYFRAME", "START_AUDIO", "STOP_AUDIO", "pong", "_f", "_l",
    "_stats_video", "_stats_audio", "p",
    # broadcast plane (ISSUE 17): viewer seats are view-only by
    # construction, yet must pick a rendition rung and report QoE
    "BROADCAST_VIEW", "BROADCAST_QOE",
)

#: verbs that mutate the session and need input authority
INPUT_PREFIXES = (
    "kd", "ku", "kr", "kh", "m", "m2", "vb", "ab", "js", "r", "s",
    "cw", "cb", "cr", "cws", "cbs", "cwd", "cbd", "cwe", "cbe", "co",
    "REQUEST_CLIPBOARD", "SET_NATIVE_CURSOR_RENDERING", "cmd",
)


@dataclasses.dataclass(frozen=True)
class Verb:
    name: str
    args: str  # raw remainder after the first comma/space (verb-specific)

    @property
    def arg_list(self) -> list[str]:
        return self.args.split(",") if self.args else []


def parse_verb(text: str) -> Verb:
    """Split a text message into verb + remainder.

    The reference protocol mixes comma verbs (``kd,65``) and space verbs
    (``CLIENT_FRAME_ACK 123``, ``SETTINGS,{json}``); we take the first
    separator of either kind.
    """
    ci = text.find(",")
    si = text.find(" ")
    cut = min(x for x in (ci, si, len(text)) if x >= 0)
    return Verb(name=text[:cut], args=text[cut + 1:] if cut < len(text) else "")


# ---------------------------------------------------------------------------
# Client timing protocol (ISSUE 7): glass-to-glass frame timing and the
# NTP-style clock exchange. Parsers are STRICT — a malformed token raises
# ValueError and the transport drops the message (counting it in
# ``selkies_protocol_errors_total{kind}``) instead of crashing the
# receive loop. All timestamps are client-clock milliseconds
# (``performance.now()``) except the server_clock reply's t1/t2, which
# are server ``perf_counter`` milliseconds.
# ---------------------------------------------------------------------------

#: batch cap for ``CLIENT_FRAME_TIMING``: the client flushes every 16
#: entries / 250 ms, so anything past this is a malformed (or hostile)
#: batch, not backlog
FRAME_TIMING_MAX_BATCH = 64


def _finite(v: float) -> float:
    """float() that rejects nan/inf (both parse, neither is a time)."""
    f = float(v)
    if math.isnan(f) or math.isinf(f):
        raise ValueError(f"non-finite timestamp {v!r}")
    return f


def parse_frame_timing(args: str,
                       max_entries: int = FRAME_TIMING_MAX_BATCH
                       ) -> list[tuple[int, float, float, float]]:
    """Parse a ``CLIENT_FRAME_TIMING`` batch:
    ``fid:recv:decode:present[;fid:recv:decode:present...]`` →
    ``[(frame_id, recv_ms, decode_ms, present_ms), ...]`` (client clock).

    Raises ValueError on an empty batch, a truncated token, a
    non-integer frame id, or a non-finite timestamp."""
    body = args.strip()
    if not body:
        raise ValueError("empty timing batch")
    entries: list[tuple[int, float, float, float]] = []
    for tok in body.split(";"):
        parts = tok.split(":")
        if len(parts) != 4:
            raise ValueError(
                f"timing token needs fid:recv:decode:present, got {tok!r}")
        fid = int(parts[0])
        recv, decode, present = (_finite(p) for p in parts[1:])
        entries.append((fid % FRAME_ID_MOD, recv, decode, present))
        if len(entries) > max_entries:
            raise ValueError(f"timing batch exceeds {max_entries} entries")
    return entries


def parse_client_clock(args: str) -> tuple[str, int, tuple[float, ...]]:
    """Parse a ``CLIENT_CLOCK`` message → ``(kind, seq, timestamps)``:

    - ``ping,<seq>,<t0>`` → ``("ping", seq, (t0,))`` — the server replies
      ``server_clock <seq>,<t0>,<t1>,<t2>``;
    - ``sample,<seq>,<t0>,<t1>,<t2>,<t3>`` → the full 4-timestamp
      exchange for the estimator.
    """
    parts = args.split(",")
    kind = parts[0]
    if kind == "ping":
        if len(parts) != 3:
            raise ValueError(f"ping wants seq,t0 ({len(parts) - 1} fields)")
        return kind, int(parts[1]), (_finite(parts[2]),)
    if kind == "sample":
        if len(parts) != 6:
            raise ValueError(
                f"sample wants seq,t0..t3 ({len(parts) - 1} fields)")
        return kind, int(parts[1]), tuple(_finite(p) for p in parts[2:6])
    raise ValueError(f"unknown CLIENT_CLOCK kind {kind!r}")
