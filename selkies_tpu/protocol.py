"""Binary wire protocol + text verb surface.

Implements the reference's client/server framing exactly (SURVEY.md §2.3;
reference parse sites: addons/selkies-web-core/selkies-ws-core.js:4255-4460,
src/selkies/selkies.py:604-621, 2504-3235):

Binary frames (first byte = opcode):
- ``0x01`` audio (server→client): ``[0x01, n_red]`` + Opus payload. When
  ``n_red > 0`` the payload is RFC-2198 RED framed:
  ``u32 pts + n_red*(4-byte block hdr) + 1-byte primary hdr + blocks``.
- ``0x02`` mic (client→server): raw PCM chunk.
- ``0x03`` JPEG stripe (server→client), 6-byte header:
  ``[0x03, flags, u16 frame_id, u16 stripe_y]`` + JFIF bytes.
- ``0x04`` H.264 stripe (server→client), 10-byte header:
  ``[0x04, frame_type(0x01=IDR), u16 frame_id, u16 y_start, u16 w, u16 h]``
  + Annex-B access unit.
- ``0x05`` gzip-compressed control text, both directions, only for messages
  over the compression threshold.

All u16/u32 are big-endian (network order), matching the JS DataView default
reads in the reference client.
"""

from __future__ import annotations

import dataclasses
import gzip
import struct
import zlib
from typing import Iterable

# Bounded control-message sizes (reference settings.py:37-60): text frames
# above WS_COMPRESSION_THRESHOLD are gzip'd with opcode 0x05; inflation is
# bounded to defeat zip bombs.
WS_MAX_MESSAGE_BYTES = 8 * 1024 * 1024
WS_MESSAGE_SIZE_HARD_CAP = 64 * 1024 * 1024
WS_COMPRESSION_THRESHOLD = 512


def inflate_gz_bounded(data: bytes, limit: int = WS_MAX_MESSAGE_BYTES) -> bytes:
    """Gunzip ``data`` refusing to inflate beyond ``limit`` bytes.

    Mirrors the reference's bounded gzip helper (settings.py:37-60): client
    supplied gzip blobs must never balloon server memory, and both truncated
    streams and trailing garbage are rejected.
    """
    out = bytearray()
    dec = zlib.decompressobj(16 + zlib.MAX_WBITS)
    out += dec.decompress(data, limit + 1)
    while dec.unconsumed_tail and len(out) <= limit:
        out += dec.decompress(dec.unconsumed_tail, limit + 1 - len(out))
    if len(out) > limit:
        raise ValueError(f"gzip payload inflates beyond {limit} bytes")
    if not dec.eof:
        raise ValueError("truncated gzip payload")
    if dec.unused_data:
        raise ValueError("trailing garbage after gzip payload")
    return bytes(out)

OP_AUDIO = 0x01
OP_MIC = 0x02
OP_JPEG = 0x03
OP_H264 = 0x04
OP_GZ_CONTROL = 0x05

FRAME_TYPE_DELTA = 0x00
FRAME_TYPE_IDR = 0x01

# uint16 circular frame-id space for ACK distance math
# (reference selkies.py:1590-1717).
FRAME_ID_MOD = 1 << 16

_H264_HDR = struct.Struct(">BBHHHH")
_JPEG_HDR = struct.Struct(">BBHH")


def pack_h264_stripe(frame_id: int, y_start: int, width: int, height: int,
                     payload: bytes | memoryview, idr: bool) -> bytes:
    """10-byte ``0x04`` header + Annex-B payload (selkies-ws-core.js:4338-4352)."""
    hdr = _H264_HDR.pack(OP_H264, FRAME_TYPE_IDR if idr else FRAME_TYPE_DELTA,
                         frame_id % FRAME_ID_MOD, y_start, width, height)
    return hdr + bytes(payload)


def unpack_h264_header(buf: bytes | memoryview) -> tuple[int, int, int, int, int]:
    """→ (frame_type, frame_id, y_start, w, h). Payload begins at byte 10."""
    try:
        op, ftype, fid, y, w, h = _H264_HDR.unpack_from(buf, 0)
    except struct.error as e:
        raise ValueError(f"malformed h264 frame header: {e}") from e
    if op != OP_H264:
        raise ValueError(f"not an h264 frame (op={op:#x})")
    return ftype, fid, y, w, h


def pack_jpeg_stripe(frame_id: int, stripe_y: int, payload: bytes | memoryview,
                     flags: int = 0) -> bytes:
    """6-byte ``0x03`` header + JPEG bytes (selkies-ws-core.js:4317-4337)."""
    return _JPEG_HDR.pack(OP_JPEG, flags, frame_id % FRAME_ID_MOD, stripe_y) \
        + bytes(payload)


def unpack_jpeg_header(buf: bytes | memoryview) -> tuple[int, int, int]:
    """→ (flags, frame_id, stripe_y). Payload begins at byte 6."""
    try:
        op, flags, fid, y = _JPEG_HDR.unpack_from(buf, 0)
    except struct.error as e:
        raise ValueError(f"malformed jpeg frame header: {e}") from e
    if op != OP_JPEG:
        raise ValueError(f"not a jpeg frame (op={op:#x})")
    return flags, fid, y


def pack_audio(payload: bytes, n_red: int = 0) -> bytes:
    """``[0x01, n_red]`` + Opus/RED payload (selkies-ws-core.js:36-38)."""
    return bytes((OP_AUDIO, n_red)) + payload


def pack_red_payload(pts_90k: int, primary: bytes,
                     redundant: Iterable[tuple[int, bytes]]) -> bytes:
    """RFC-2198 RED framing for Opus (reference pcmflux native framing).

    ``redundant`` is oldest-first ``(ts_offset_90k, opus_frame)`` pairs.
    Block header: 1 bit F=1, 7-bit PT, 14-bit ts offset, 10-bit length;
    primary header: F=0 + 7-bit PT. PT is fixed 111 (dynamic Opus).
    """
    pt = 111
    out = bytearray(struct.pack(">I", pts_90k & 0xFFFFFFFF))
    red_list = list(redundant)
    for ts_off, blk in red_list:
        if len(blk) >= 1 << 10:
            raise ValueError("RED block too large for 10-bit length")
        if not 0 <= ts_off < 1 << 14:
            raise ValueError("RED ts offset out of 14-bit range")
        word = (1 << 31) | (pt << 24) | (ts_off << 10) | len(blk)
        out += struct.pack(">I", word)
    out.append(pt)  # F=0 primary header
    for _, blk in red_list:
        out += blk
    out += primary
    return bytes(out)


def frame_id_distance(newest: int, acked: int) -> int:
    """Forward distance in uint16 circular space (reference selkies.py:61-110)."""
    return (newest - acked) % FRAME_ID_MOD


def maybe_compress_text(text: str, threshold: int = WS_COMPRESSION_THRESHOLD
                        ) -> bytes | str:
    """Return ``0x05`` + gzip when the message is worth compressing, else the
    original text (reference selkies.py:375, 2381-2395)."""
    raw = text.encode("utf-8")
    if len(raw) < threshold:
        return text
    return bytes((OP_GZ_CONTROL,)) + gzip.compress(raw, 6)


def decompress_control(buf: bytes | memoryview) -> str:
    b = bytes(buf)
    if not b or b[0] != OP_GZ_CONTROL:
        raise ValueError("not a 0x05 control frame")
    return inflate_gz_bounded(b[1:]).decode("utf-8")


# ---------------------------------------------------------------------------
# Text verbs (client→server), SURVEY §2.3. A thin parsed representation so
# the dispatcher (server/websockets_service.py, input/handler.py) stays flat.
# ---------------------------------------------------------------------------

#: verbs a view-only client may still send (reference
#: input_handler.py:110-128 viewer-authority prefix lists).
VIEWER_ALLOWED_PREFIXES = (
    "_gz", "SETTINGS", "CLIENT_FRAME_ACK", "START_VIDEO", "STOP_VIDEO",
    "REQUEST_KEYFRAME", "START_AUDIO", "STOP_AUDIO", "pong", "_f", "_l",
    "_stats_video", "_stats_audio", "p",
)

#: verbs that mutate the session and need input authority
INPUT_PREFIXES = (
    "kd", "ku", "kr", "kh", "m", "m2", "vb", "ab", "js", "r", "s",
    "cw", "cb", "cr", "cws", "cbs", "cwd", "cbd", "cwe", "cbe", "co",
    "REQUEST_CLIPBOARD", "SET_NATIVE_CURSOR_RENDERING", "cmd",
)


@dataclasses.dataclass(frozen=True)
class Verb:
    name: str
    args: str  # raw remainder after the first comma/space (verb-specific)

    @property
    def arg_list(self) -> list[str]:
        return self.args.split(",") if self.args else []


def parse_verb(text: str) -> Verb:
    """Split a text message into verb + remainder.

    The reference protocol mixes comma verbs (``kd,65``) and space verbs
    (``CLIENT_FRAME_ACK 123``, ``SETTINGS,{json}``); we take the first
    separator of either kind.
    """
    ci = text.find(",")
    si = text.find(" ")
    cut = min(x for x in (ci, si, len(text)) if x >= 0)
    return Verb(name=text[:cut], args=text[cut + 1:] if cut < len(text) else "")
