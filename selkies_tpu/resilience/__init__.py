"""Resilience plane: supervised recovery, degradation, fault injection.

Four PRs of observability (trace spans, health verdicts, device
telemetry, session QoE) gave the pipeline eyes; this package gives it
reflexes. Three cooperating pieces:

- :mod:`.supervisor` — restart-policy engine (exponential backoff +
  seeded jitter, restart budgets, crash-loop escalation) adopting the
  previously-unsupervised lifetimes: the capture thread, the transport
  service task, per-client video relays, and the audio pipeline;
- :mod:`.ladder` — verdict-driven degradation ladder (fps -> quality ->
  downscale) with hysteresis and sustained-ok recovery, consuming the
  PR-3/PR-4 health verdicts;
- :mod:`.faults` — deterministic, seeded fault registry armed via
  ``--fault_inject`` / ``POST /api/faults``, with injection points in
  relay send, capture source, encoder dispatch and ws accept — the
  reason every recovery path above has a test that actually runs it.

Everything imports without jax/aiohttp; ``python -m
selkies_tpu.resilience selftest`` is the CI lint smoke (same contract
as :mod:`..trace` and :mod:`..obs`).
"""

from .faults import (FaultError, FaultRegistry, FaultSpec,  # noqa: F401
                     parse_spec)
from .faults import registry as fault_registry  # noqa: F401
from .ladder import DegradationLadder  # noqa: F401
from .supervisor import RestartPolicy, Supervisor  # noqa: F401
