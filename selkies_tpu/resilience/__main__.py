"""Offline resilience CLI.

``python -m selkies_tpu.resilience selftest`` — drive the real restart
policy, supervisor, degradation ladder, and fault registry with
injected clocks/schedulers and verify the contracts (the CI lint smoke,
mirroring ``python -m selkies_tpu.trace selftest`` and ``python -m
selkies_tpu.obs selftest``). Exits non-zero on any contract break.

Stdlib-only: runs in the lint CI image with no jax/aiohttp installed.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..obs.health import DEGRADED, FAILED, OK, HealthEngine
from .faults import FaultError, FaultRegistry, parse_spec
from .ladder import DegradationLadder
from .supervisor import RestartPolicy, Supervisor


def _fail(msg: str) -> int:
    print(f"selftest FAILED: {msg}", file=sys.stderr)
    return 1


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


class _Sched:
    """Manual scheduler: collects (delay, cb); fire() runs them."""

    class _Handle:
        def __init__(self, sched, entry):
            self._sched, self._entry = sched, entry

        def cancel(self):
            if self._entry in self._sched.pending:
                self._sched.pending.remove(self._entry)

    def __init__(self):
        self.pending: list = []

    def __call__(self, delay, cb):
        entry = (delay, cb)
        self.pending.append(entry)
        return self._Handle(self, entry)

    def fire(self) -> int:
        pending, self.pending = self.pending, []
        for _, cb in pending:
            cb()
        return len(pending)


def _cmd_selftest(args: argparse.Namespace) -> int:
    import logging
    logging.getLogger("selkies_tpu.resilience").setLevel(logging.CRITICAL)
    # -- restart policy: exact backoff sequence under an injected clock --
    clk = _Clock()
    pol = RestartPolicy(max_restarts=3, window_s=100.0, base_backoff_s=1.0,
                        max_backoff_s=8.0, jitter=0.0, min_uptime_s=5.0,
                        clock=clk)
    pol.record_started()
    clk.t = 10.0                        # healthy 10 s: streak resets
    if pol.next_backoff() != 1.0:
        return _fail("first backoff after healthy uptime must be base")
    pol.record_started()
    clk.t = 10.5                        # died in 0.5 s: fast death
    if pol.crash_looping:
        return _fail("one fast death must not flag crash loop yet")
    if pol.next_backoff() != 2.0:
        return _fail("second backoff must double")
    pol.record_started()
    clk.t = 11.0                        # 3rd consecutive fast death
    b = pol.next_backoff()
    if not pol.crash_looping:
        return _fail("3 sub-min_uptime deaths must flag crash loop")
    if b != 4.0:
        return _fail(f"third backoff must ramp 2^n (got {b})")
    pol.record_started()
    clk.t = 11.5
    if pol.next_backoff() is not None:
        return _fail("4th death inside the window must exhaust the budget")
    # jitter determinism: same seed -> same sequence
    seq = []
    for _ in range(2):
        c2 = _Clock()
        p2 = RestartPolicy(base_backoff_s=1.0, jitter=0.25, seed=42,
                           min_uptime_s=0.0, clock=c2)
        p2.record_started()
        seq.append([p2.next_backoff() for _ in range(3)])
    if seq[0] != seq[1]:
        return _fail(f"seeded jitter must be deterministic: {seq}")
    if any(not (1.0 <= b) for b in seq[0][:1]):
        return _fail(f"jitter must only add: {seq[0]}")

    # -- supervisor: restart scheduling, give-up, health verdicts --------
    eng = HealthEngine()
    sched = _Sched()
    state = {"restarts": 0, "gave_up": False}
    sup = Supervisor(recorder=eng.recorder, schedule=sched,
                     policy_factory=lambda: RestartPolicy(
                         max_restarts=2, window_s=100.0, base_backoff_s=1.0,
                         jitter=0.0, min_uptime_s=0.0, clock=clk))
    sup.adopt("capture::0",
              lambda: state.__setitem__("restarts", state["restarts"] + 1),
              on_give_up=lambda: state.__setitem__("gave_up", True))
    eng.register("supervision", sup.health_check)
    if eng.run()["supervision"].status != OK:
        return _fail("idle supervisor must verdict ok")
    sup.report_death("capture::0", "injected")
    if eng.run()["supervision"].status != DEGRADED:
        return _fail("backing-off component must degrade supervision")
    sup.report_death("capture::0", "coalesce me")   # pending: must coalesce
    if len(sched.pending) != 1:
        return _fail("a pending restart must coalesce repeat deaths")
    sched.fire()
    if state["restarts"] != 1:
        return _fail("firing the schedule must run the restart fn")
    if eng.run()["supervision"].status != OK:
        return _fail("restarted component must return supervision to ok")
    sup.report_death("capture::0", "again")
    sched.fire()
    sup.report_death("capture::0", "third death: budget is 2")
    if not state["gave_up"]:
        return _fail("budget exhaustion must call the give-up hook")
    if eng.run()["supervision"].status != FAILED:
        return _fail("budget exhaustion must fail supervision")
    kinds = [e["kind"] for e in eng.recorder.snapshot()]
    if kinds.count("supervisor_restart") != 2 or "crash_loop" not in kinds:
        return _fail(f"incident trail wrong: {kinds}")

    # -- ladder: hysteresis down, hold, sustained-ok up ------------------
    lclk = _Clock()
    calls: list[str] = []
    # explicit two-rung table: this block tests the hysteresis state
    # machine, not the default rung walk (which now opens with the
    # deep-pipeline rung — covered by tests/test_resilience.py)
    lad = DegradationLadder(steps=("fps", "quality"),
                            down_after_s=4.0, hold_s=10.0, ok_window_s=30.0,
                            clock=lclk, recorder=eng.recorder)
    lad.bind_controls({
        "fps": (lambda: calls.append("fps-"), lambda: calls.append("fps+")),
        "quality": (lambda: calls.append("q-"), lambda: calls.append("q+")),
    })
    bad = {"qoe": FAILED}
    lad.observe(bad, now=0.0)
    if lad.level != 0:
        return _fail("a transient trigger must not downshift immediately")
    lad.observe(bad, now=4.0)
    if lad.level != 1 or calls != ["fps-"]:
        return _fail(f"4s persistent trigger must downshift: "
                     f"{lad.level} {calls}")
    lad.observe(bad, now=8.0)
    if lad.level != 1:
        return _fail("hold_s must block back-to-back downshifts")
    lad.observe(bad, now=15.0)
    if lad.level != 2 or calls[-1] != "q-":
        return _fail("persistent trigger past hold must step again")
    ok_v = {"qoe": OK}
    lad.observe(ok_v, now=16.0)
    lad.observe(ok_v, now=40.0)
    if lad.level != 2:
        return _fail("ok shorter than ok_window_s must not step up")
    lad.observe(ok_v, now=46.5)
    if lad.level != 1 or calls[-1] != "q+":
        return _fail(f"sustained ok must step up: {lad.level} {calls}")
    lad.observe(bad, now=47.0)
    lad.observe(bad, now=51.5)
    if lad.level != 1:
        return _fail("hold after a step-up must block an instant downshift")
    ev = lad.trace_events()
    if not ev or ev[0]["ph"] != "M" or len(ev) != 1 + lad.transitions:
        return _fail(f"trace overlay shape broken: {len(ev)} events "
                     f"for {lad.transitions} transitions")
    snap = lad.snapshot()
    json.loads(json.dumps(snap))
    if snap["level"] != 1 or snap["step"] != "fps":
        return _fail(f"snapshot wrong: {snap}")

    # -- faults: grammar round-trip, schedule exactness, determinism -----
    text = ("relay.send:error;capture.source:raise:after=2,count=1;"
            "encoder.dispatch:slow:delay_s=0.5,count=3;"
            "ws.accept:close:prob=0.5")
    specs = parse_spec(text)
    round_tripped = parse_spec(";".join(s.to_spec() for s in specs))
    if [s.to_dict() for s in specs] != [s.to_dict() for s in round_tripped]:
        return _fail("fault spec must round-trip through to_spec()")
    for bad_spec in ("nope:error", "relay.send:bogus", "relay.send",
                     "relay.send:error:count=x", "relay.send:error:zzz=1"):
        try:
            parse_spec(bad_spec)
            return _fail(f"bad spec {bad_spec!r} must raise")
        except ValueError:
            pass
    reg = FaultRegistry(seed=7)
    reg.arm("capture.source:raise:after=2,count=1")
    reg.pull("relay.send")              # wrong point: no hit consumed
    for i in range(2):
        if reg.pull("capture.source") is not None:
            return _fail(f"after=2 must skip hit {i + 1}")
    try:
        reg.perturb("capture.source")
        return _fail("3rd hit must fire the raise fault")
    except FaultError as e:
        if (e.point, e.mode) != ("capture.source", "raise"):
            return _fail(f"FaultError carries wrong identity: {e}")
    if reg.pull("capture.source") is not None:
        return _fail("count=1 must exhaust after one fire")
    if reg.remaining() != 0 or len(reg.fired_log) != 1:
        return _fail("remaining/fired accounting broken")
    # seeded prob: identical draw sequence across registries
    fires = []
    for _ in range(2):
        r = FaultRegistry(seed=1234)
        r.arm("relay.send:error:prob=0.5,count=100")
        fires.append([r.pull("relay.send") is not None for _ in range(20)])
    if fires[0] != fires[1]:
        return _fail("seeded prob faults must replay identically")
    if not any(fires[0]) or all(fires[0]):
        return _fail(f"prob=0.5 over 20 draws should mix: {fires[0]}")
    reg.disarm()
    if reg.active():
        return _fail("disarm must clear the registry")

    doc = {"supervisor": sup.components(), "ladder": snap,
           "incidents": eng.recorder.snapshot()[-4:]}
    text = json.dumps(doc)
    json.loads(text)
    print(text if args.json
          else f"selftest OK ({len(text)} bytes of resilience state)")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m selkies_tpu.resilience",
        description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    ps = sub.add_parser("selftest", help="drive policy+supervisor+ladder+"
                                         "faults with injected clocks")
    ps.add_argument("--json", action="store_true",
                    help="print the selftest state payload")
    ps.set_defaults(fn=_cmd_selftest)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
