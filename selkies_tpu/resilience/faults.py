"""Deterministic, seeded fault injection.

Every recovery path PR 5 adds (supervised capture restart, relay
re-offer, service resurrection, degradation ladder) is exactly the kind
of code that rots untested: the failure it handles never happens on a
developer laptop, so the first real execution is in production. This
registry makes faults first-class inputs instead — armed via
``--fault_inject=<spec>`` or ``POST /api/faults``, fired at named
injection points compiled into the hot paths, and **deterministic**:
``after``/``count`` schedules are exact trigger-hit counts, and the only
randomness (``prob``) draws from a seeded ``random.Random`` so a chaos
run replays bit-identically from its seed.

Spec grammar (round-trips through :func:`parse_spec` / ``to_spec()``)::

    spec     := clause (";" clause)*
    clause   := point ":" mode [":" kv ("," kv)*]
    kv       := key "=" value
    keys     := after | count | delay_s | prob

    relay.send:error                      # next send raises
    capture.source:raise:after=40,count=1 # 41st get_frame raises
    encoder.dispatch:slow:delay_s=0.2     # one slow dispatch
    ws.accept:close:count=2               # reject the next two upgrades

Injection points and their modes:

========================  =======================================
``relay.send``            ``stall`` (sleep past the send bound),
                          ``error`` (ConnectionError)
``relay.stripe``          ``reorder`` (swap the two newest queued
                          stripes in a relay — the out-of-order wire
                          delivery the per-row chain gate + IDR
                          resync must absorb)
``capture.source``        ``raise`` (source throws), ``freeze``
                          (source blocks ``delay_s``)
``encoder.dispatch``      ``slow`` (sleep ``delay_s``),
                          ``device_error`` (fake XLA runtime error)
``encoder.compile``       ``slow`` (sleep ``delay_s`` inside the step
                          compile site — the injected 20 s XLA build
                          the compile-plane contract defends against)
``readback.fetch``        ``slow`` (sleep ``delay_s``), ``error``
                          (mid-pipeline readback death: the ring must
                          drain, never wedge — bench --chaos proves it)
``ws.accept``             ``close`` / ``error`` (upgrade rejected)
``fleet.spawn``           ``fail`` (actuator host spawn raises),
                          ``slow`` (spawn stalls ``delay_s``)
``fleet.drain``           ``hang`` (engine accepts the drain request
                          but never starts it — ``drain.done`` never
                          fires, forcing the actuator's bounded-await
                          escalation path)
``fleet.heartbeat``       ``drop`` (the next ``count`` gateway pushes
                          are silently skipped — a control-plane
                          partition), ``delay`` (push stalls
                          ``delay_s`` first)
========================  =======================================

Fleet-plane points (ISSUE 20) are also armable through the
``SELKIES_FAULT_INJECT`` environment variable (same grammar), so the
chaos bench can arm faults inside engine-host subprocesses the
actuator spawns — no CLI flag or control-plane round-trip needed
before the process is even serving. :func:`arm_from_env` is idempotent
per process: the engine entrypoint and the server core both call it,
whichever runs first wins.

The disarmed fast path is one attribute read (``self._armed``) — the
capture/encode loops pay nothing when no fault is armed. Stdlib-only:
the CI lint image runs ``python -m selkies_tpu.resilience selftest``
with neither jax nor aiohttp installed.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import random
import threading
import time
from typing import Optional

logger = logging.getLogger("selkies_tpu.resilience.faults")

__all__ = ["FaultError", "FaultSpec", "FaultRegistry", "parse_spec",
           "arm_from_env", "registry", "POINTS"]

#: injection points -> their valid modes. Parsing validates against this
#: so a typo'd spec fails at arm time, never silently no-ops in a run.
POINTS: dict[str, tuple[str, ...]] = {
    "relay.send": ("stall", "error"),
    "relay.stripe": ("reorder",),
    "capture.source": ("raise", "freeze"),
    "encoder.dispatch": ("slow", "device_error"),
    "encoder.compile": ("slow",),
    "readback.fetch": ("slow", "error"),
    "ws.accept": ("close", "error"),
    "fleet.spawn": ("fail", "slow"),
    "fleet.drain": ("hang",),
    "fleet.heartbeat": ("drop", "delay"),
}

#: modes that raise at the injection site. ``hang`` and ``drop`` are
#: marker modes their sites interpret via ``pull()`` directly (skip the
#: heartbeat POST, skip starting the drain) — ``perturb()`` never sees
#: them; the rest of the non-raising modes sleep/stall.
_RAISING_MODES = frozenset({"error", "raise", "device_error", "close",
                            "fail"})

#: bounded history of fired faults (chaos-run forensics)
_FIRED_CAP = 256


class FaultError(RuntimeError):
    """Raised at an injection site by a raising-mode fault. Carries the
    point/mode so recovery tests can assert the failure they injected is
    the failure that was handled."""

    def __init__(self, point: str, mode: str):
        super().__init__(f"injected fault: {point}:{mode}")
        self.point = point
        self.mode = mode


class FaultSpec:
    """One armed fault clause.

    ``after`` trigger-hits are skipped, then the fault fires on the next
    ``count`` hits (each hit subject to ``prob``). ``delay_s`` is the
    stall duration for sleeping modes.
    """

    __slots__ = ("point", "mode", "after", "count", "delay_s", "prob",
                 "hits", "fired")

    def __init__(self, point: str, mode: str, after: int = 0,
                 count: int = 1, delay_s: float = 2.0, prob: float = 1.0):
        if point not in POINTS:
            raise ValueError(f"unknown fault point {point!r} "
                             f"(want one of {sorted(POINTS)})")
        if mode not in POINTS[point]:
            raise ValueError(f"mode {mode!r} invalid for {point} "
                             f"(want one of {POINTS[point]})")
        if after < 0 or count < 1:
            raise ValueError("after must be >= 0 and count >= 1")
        if not (0.0 < prob <= 1.0):
            raise ValueError("prob must be in (0, 1]")
        self.point = point
        self.mode = mode
        self.after = int(after)
        self.count = int(count)
        self.delay_s = float(delay_s)
        self.prob = float(prob)
        self.hits = 0       # trigger-site visits seen by this clause
        self.fired = 0      # times this clause actually fired

    @property
    def exhausted(self) -> bool:
        return self.fired >= self.count

    def to_spec(self) -> str:
        """The clause in spec grammar (parse/format round-trip)."""
        kv = []
        if self.after:
            kv.append(f"after={self.after}")
        if self.count != 1:
            kv.append(f"count={self.count}")
        if self.delay_s != 2.0:
            kv.append(f"delay_s={self.delay_s:g}")
        if self.prob != 1.0:
            kv.append(f"prob={self.prob:g}")
        base = f"{self.point}:{self.mode}"
        return base + (":" + ",".join(kv) if kv else "")

    def to_dict(self) -> dict:
        return {"point": self.point, "mode": self.mode,
                "after": self.after, "count": self.count,
                "delay_s": self.delay_s, "prob": self.prob,
                "hits": self.hits, "fired": self.fired,
                "exhausted": self.exhausted}


def parse_spec(text: str) -> list[FaultSpec]:
    """Parse the ``--fault_inject`` grammar; raises ``ValueError`` with
    the offending clause on any contract break."""
    specs: list[FaultSpec] = []
    for clause in str(text).split(";"):
        clause = clause.strip()
        if not clause:
            continue
        parts = clause.split(":")
        if len(parts) < 2:
            raise ValueError(f"bad fault clause {clause!r} "
                             "(want point:mode[:k=v,...])")
        point, mode = parts[0].strip(), parts[1].strip()
        kw: dict = {}
        if len(parts) > 2:
            for kv in ":".join(parts[2:]).split(","):
                kv = kv.strip()
                if not kv:
                    continue
                if "=" not in kv:
                    raise ValueError(f"bad fault option {kv!r} in "
                                     f"{clause!r} (want key=value)")
                k, v = kv.split("=", 1)
                k = k.strip()
                if k in ("after", "count"):
                    kw[k] = int(v)
                elif k in ("delay_s", "prob"):
                    kw[k] = float(v)
                else:
                    raise ValueError(f"unknown fault option {k!r} in "
                                     f"{clause!r}")
        specs.append(FaultSpec(point, mode, **kw))
    return specs


class FaultRegistry:
    """Process-wide armed-fault state. Thread-safe: sync injection sites
    live on the capture thread, async ones on the event loop, and the
    control plane arms/disarms from HTTP handlers."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._specs: list[FaultSpec] = []
        self._armed = False            # lock-free fast-path flag
        self._rng = random.Random(seed)
        self.seed = seed
        self.fired_log: collections.deque = collections.deque(
            maxlen=_FIRED_CAP)
        #: injectable sleeps so stalls are testable without wall-clock
        self.sleep = time.sleep
        self.sleep_async = asyncio.sleep

    # -- control plane -------------------------------------------------------
    def arm(self, spec, seed: Optional[int] = None) -> list[FaultSpec]:
        """Arm a spec string / FaultSpec / list thereof. Re-seeding is
        explicit so a chaos run can pin its RNG."""
        if isinstance(spec, str):
            specs = parse_spec(spec)
        elif isinstance(spec, FaultSpec):
            specs = [spec]
        else:
            specs = list(spec)
        with self._lock:
            if seed is not None:
                self.seed = int(seed)
                self._rng = random.Random(self.seed)
            self._specs.extend(specs)
            self._armed = bool(self._specs)
        if specs:
            logger.warning("fault injection armed: %s",
                           "; ".join(s.to_spec() for s in specs))
        return specs

    def disarm(self, point: Optional[str] = None) -> int:
        """Disarm every clause (or only one point's). -> clauses removed."""
        with self._lock:
            before = len(self._specs)
            self._specs = [] if point is None else \
                [s for s in self._specs if s.point != point]
            self._armed = bool(self._specs)
            return before - len(self._specs)

    def active(self) -> list[dict]:
        with self._lock:
            return [s.to_dict() for s in self._specs]

    def remaining(self) -> int:
        """Armed clauses that have not exhausted their count yet."""
        with self._lock:
            return sum(1 for s in self._specs if not s.exhausted)

    # -- injection sites -----------------------------------------------------
    def pull(self, point: str) -> Optional[FaultSpec]:
        """One trigger-site visit: returns the spec to act on, or None.
        Counts the hit against every armed clause for the point (so
        ``after`` schedules stay exact even with overlapping clauses)."""
        if not self._armed:
            return None
        with self._lock:
            chosen = None
            for s in self._specs:
                if s.point != point:
                    continue
                s.hits += 1
                if chosen is None and not s.exhausted \
                        and s.hits > s.after \
                        and (s.prob >= 1.0 or self._rng.random() < s.prob):
                    s.fired += 1
                    chosen = s
            if chosen is not None:
                entry = {"ts": round(time.time(), 3),
                         "point": chosen.point, "mode": chosen.mode,
                         "hit": chosen.hits, "fired": chosen.fired}
                self.fired_log.append(entry)
                self._record_incident(entry)
                logger.warning("fault fired: %s:%s (hit %d)", chosen.point,
                               chosen.mode, chosen.hits)
            return chosen

    def perturb(self, point: str) -> None:
        """Sync injection site (capture thread, encoder dispatch): raise
        or sleep per the armed spec; no-op otherwise."""
        s = self.pull(point)
        if s is None:
            return
        if s.mode in _RAISING_MODES:
            raise FaultError(s.point, s.mode)
        self.sleep(s.delay_s)

    async def perturb_async(self, point: str) -> None:
        """Async injection site (relay sender, ws accept)."""
        s = self.pull(point)
        if s is None:
            return
        if s.mode in _RAISING_MODES:
            raise FaultError(s.point, s.mode)
        await self.sleep_async(s.delay_s)

    # -- incident bridge (lazy; mirrors health's metrics bridge) -------------
    def _record_incident(self, entry: dict) -> None:
        try:
            from ..obs import health as _health
        except Exception:  # pragma: no cover - obs is stdlib-only
            return
        _health.engine.recorder.record(
            "fault_injected", point=entry["point"], mode=entry["mode"],
            hit=entry["hit"])


#: the process-wide registry every injection site reads (tests and the
#: bench chaos harness build their own instances)
registry = FaultRegistry()

#: latched by :func:`arm_from_env` so the env spec arms exactly once
#: per process no matter how many entrypoints call it (``arm`` extends
#: the clause list — double-arming would double every schedule).
_env_armed = False


def arm_from_env(environ: Optional[dict] = None) -> list[FaultSpec]:
    """Arm the process-wide registry from ``SELKIES_FAULT_INJECT``
    (optional ``SELKIES_FAULT_SEED`` pins the RNG).  Idempotent: only
    the first call in a process arms; later calls return ``[]``.  A
    malformed spec raises ``ValueError`` — an env-armed chaos run must
    fail loudly at boot, never silently run fault-free."""
    global _env_armed
    env = os.environ if environ is None else environ
    text = (env.get("SELKIES_FAULT_INJECT") or "").strip()
    if not text or _env_armed:
        return []
    _env_armed = True
    seed = (env.get("SELKIES_FAULT_SEED") or "").strip()
    return registry.arm(text, seed=int(seed) if seed else None)
