"""Verdict-driven degradation ladder.

The split-frame V-PCC streaming work sheds load when the encoder
saturates, and the vehicular 8K60 NVENC study holds sustained real-time
by trading fidelity down *before* the pipeline collapses. This module is
that discipline for selkies-tpu: a controller that consumes the health
verdicts the PR-3/PR-4 planes already compute (``qoe`` failed,
``hbm_headroom`` degraded, ``stage_latency`` over budget) and walks a
configurable ladder of fidelity concessions —

    level 0  full fidelity
    level 1  target fps halved (floor: ``min_fps``)
    level 2  quality/rate cut (JPEG quality down, H.264 bitrate down)
    level 3  capture downscale

— with **hysteresis** in both directions: a trigger must persist
``down_after_s`` before the first downshift, ``hold_s`` must elapse
between any two transitions (no flapping), and a step *up* requires a
sustained all-ok window of ``ok_window_s``. Every transition is recorded
as a ``degradation_step`` / ``degradation_recover`` incident, exported
as the ``selkies_degradation_level`` gauge, and kept in a bounded event
ring that ``/api/trace`` overlays as a ``resilience`` lane.

The ladder itself is pure state machine (injected clock, no asyncio, no
deps): transports bind concrete ``down``/``up`` callables per step via
:meth:`bind_controls`; with nothing bound the ladder still tracks and
reports level transitions (the verdict trail stays honest even when no
actuator exists, e.g. webrtc mode before its controls land).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable, Mapping, Optional

from ..obs import health as _health

logger = logging.getLogger("selkies_tpu.resilience.ladder")

__all__ = ["DegradationLadder", "DEFAULT_TRIGGERS", "DEFAULT_STEPS"]

#: verdict name -> statuses that count as a degradation trigger.
#: qoe only on failed (degraded QoE is what the ladder CAUSES while
#: shedding — reacting to it would latch the bottom rung).
DEFAULT_TRIGGERS: dict[str, frozenset] = {
    "qoe": frozenset({_health.FAILED}),
    "hbm_headroom": frozenset({_health.DEGRADED, _health.FAILED}),
    "hbm": frozenset({_health.DEGRADED, _health.FAILED}),
    "stage_latency": frozenset({_health.DEGRADED, _health.FAILED}),
}

#: rung names above level 0, in downshift order
DEFAULT_STEPS = ("fps", "quality", "downscale")

_EVENT_CAP = 64


class DegradationLadder:
    def __init__(self, *,
                 steps: tuple = DEFAULT_STEPS,
                 triggers: Optional[Mapping] = None,
                 down_after_s: float = 4.0,
                 hold_s: float = 10.0,
                 ok_window_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 recorder: Optional[_health.FlightRecorder] = None):
        self.steps = tuple(steps)
        self.triggers = dict(triggers if triggers is not None
                             else DEFAULT_TRIGGERS)
        self.down_after_s = float(down_after_s)
        self.hold_s = float(hold_s)
        self.ok_window_s = float(ok_window_s)
        self._clock = clock
        self.recorder = recorder if recorder is not None \
            else _health.engine.recorder
        self._lock = threading.Lock()
        self.level = 0
        self.transitions = 0
        self._bad_since: Optional[float] = None
        self._ok_since: Optional[float] = None
        self._last_change: Optional[float] = None
        self._last_reasons: list[str] = []
        #: step name -> (down_fn, up_fn); bound by the active transport
        self._controls: dict[str, tuple[Callable, Callable]] = {}
        #: (name, perf_ns, level, reasons) ring for the trace overlay
        self._events: collections.deque = collections.deque(
            maxlen=_EVENT_CAP)

    # -- controls ------------------------------------------------------------
    def bind_controls(self, controls: Mapping[str, tuple]) -> None:
        """``{step: (down_fn, up_fn)}`` from the active transport. Steps
        with no control still transition (tracked + recorded), they just
        actuate nothing."""
        with self._lock:
            self._controls.update(controls)

    def unbind_controls(self) -> None:
        with self._lock:
            self._controls.clear()

    # -- state machine -------------------------------------------------------
    def _trigger_reasons(self, verdicts: Mapping) -> list[str]:
        reasons = []
        for name, bad in self.triggers.items():
            v = verdicts.get(name)
            status = getattr(v, "status", v)
            if status in bad:
                reasons.append(f"{name}={status}")
        return sorted(reasons)

    def observe(self, verdicts: Mapping, now: Optional[float] = None) -> None:
        """One controller tick against the current verdict set (values
        may be Verdict objects or bare status strings)."""
        if now is None:
            now = self._clock()
        reasons = self._trigger_reasons(verdicts)
        if reasons:
            self._ok_since = None
            if self._bad_since is None:
                self._bad_since = now
            self._last_reasons = reasons
            if self.level >= len(self.steps):
                return
            if now - self._bad_since < self.down_after_s:
                return
            if self._last_change is not None \
                    and now - self._last_change < self.hold_s:
                return
            self._shift(now, +1, reasons)
            # a further downshift needs the trigger to PERSIST past the
            # hold from this new level, not re-accumulate from zero
            self._bad_since = now
        else:
            self._bad_since = None
            if self._ok_since is None:
                self._ok_since = now
            if self.level == 0:
                return
            if now - self._ok_since < self.ok_window_s:
                return
            if self._last_change is not None \
                    and now - self._last_change < self.hold_s:
                return
            self._shift(now, -1, ["sustained-ok "
                                  f"{self.ok_window_s:g}s"])

    def _shift(self, now: float, direction: int, reasons: list[str]) -> None:
        if direction > 0:
            step = self.steps[self.level]
            self.level += 1
            fn_idx, kind = 0, "degradation_step"
        else:
            self.level -= 1
            step = self.steps[self.level]
            fn_idx, kind = 1, "degradation_recover"
        self.transitions += 1
        self._last_change = now
        with self._lock:
            ctl = self._controls.get(step)
        applied = False
        if ctl is not None:
            try:
                # a control returning the explicit sentinel False says
                # "nothing to shed/restore here" (e.g. fps already at
                # the floor) — the incident must not claim otherwise
                applied = ctl[fn_idx]() is not False
            except Exception:
                logger.exception("ladder %s control for step %s failed",
                                 "down" if direction > 0 else "up", step)
        self.recorder.record(kind, step=step, level=self.level,
                             reasons=reasons, applied=applied)
        self._events.append((kind, time.perf_counter_ns(), self.level,
                             step, reasons))
        _metrics_level(self.level)
        logger.warning("degradation ladder %s -> level %d (%s: %s)%s",
                       "down" if direction > 0 else "up", self.level,
                       step, ", ".join(reasons),
                       "" if applied else " [no control bound]")

    # -- reporting -----------------------------------------------------------
    def snapshot(self) -> dict:
        return {
            "level": self.level,
            "step": self.steps[self.level - 1] if self.level else None,
            "steps": list(self.steps),
            "transitions": self.transitions,
            "active_triggers": list(self._last_reasons)
            if self._bad_since is not None else [],
            "controls_bound": sorted(self._controls),
        }

    def trace_events(self, pid: int = 1, tid: int = 97) -> list[dict]:
        """Ladder transitions as Chrome trace instants on a
        ``resilience`` lane (same perf_counter µs timebase as the frame,
        device and qoe lanes at ``/api/trace``)."""
        events = list(self._events)
        if not events:
            return []
        out: list[dict] = [{
            "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
            "args": {"name": "resilience"},
        }]
        for kind, t_ns, level, step, reasons in events:
            out.append({
                "name": f"{kind} L{level} ({step})",
                "ph": "i", "s": "g", "pid": pid, "tid": tid,
                "ts": t_ns / 1e3,
                "args": {"level": level, "step": step,
                         "reasons": list(reasons)},
            })
        return out


# -- optional metrics bridge (lazy; lint image has no server deps) ----------

def _metrics_level(level: int) -> None:
    try:
        from ..server import metrics
    except Exception:
        return
    metrics.describe("selkies_degradation_level",
                     "Current degradation-ladder level (0 = full fidelity)")
    metrics.set_gauge("selkies_degradation_level", level)
