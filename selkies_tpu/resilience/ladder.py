"""Verdict-driven degradation ladder.

The split-frame V-PCC streaming work sheds load when the encoder
saturates, and the vehicular 8K60 NVENC study holds sustained real-time
by trading fidelity down *before* the pipeline collapses. This module is
that discipline for selkies-tpu: a controller that consumes the health
verdicts the PR-3/PR-4 planes already compute (``qoe`` failed,
``hbm_headroom`` degraded, ``stage_latency`` over budget) and walks a
configurable ladder of fidelity concessions —

    level 0  full fidelity
    level 1  pipeline depth -> 1 (frame-serial: sheds the in-flight
             frames' worth of latency/HBM before touching fidelity —
             the deep-pipeline rung, ROADMAP 2)
    level 2  target fps halved (floor: ``min_fps``)
    level 3  quality/rate cut (JPEG quality down, H.264 bitrate down)
    level 4  capture downscale

— with **hysteresis** in both directions: a trigger must persist
``down_after_s`` before the first downshift, ``hold_s`` must elapse
between any two transitions (no flapping), and a step *up* requires a
sustained all-ok window of ``ok_window_s``. Every transition is recorded
as a ``degradation_step`` / ``degradation_recover`` incident, exported
as the ``selkies_degradation_level`` gauge, and kept in a bounded event
ring that ``/api/trace`` overlays as a ``resilience`` lane.

**Compile-free-or-deferred transitions** (ISSUE 8): a signature-changing
rung (capture downscale rebuilds the encoder session at a new geometry)
risks a ~22 s foreground XLA compile — a downshift that freezes the
session it was meant to save. When a ``gate`` is injected (the pre-warm
plane's :class:`~selkies_tpu.prewarm.worker.PrewarmGate`), the ladder
consults it before actuating ANY rung: a ``warm`` answer switches
immediately; a ``cold`` one is enqueued at top priority via
``gate.request`` and the shift is *deferred* — the ladder holds at its
current (compiled) rung, records a ``transition_deferred`` incident, and
re-queries every tick. Past ``defer_deadline_s`` it forces the nearest
warm rung further down the table instead (skipped cold rungs are named
in the incident); with nothing warm it keeps holding, renewing the
deadline. No gate (or a crashing gate) fails OPEN — shedding fidelity
must never be blocked by the machinery meant to make it cheap.

**Energy-aware mode** (ISSUE 14): with an ``energy_policy`` injected
(``obs/energy.EnergyBudgetPolicy`` — a watts feed plus a per-rung
efficiency/SLO table), an exceeded power budget becomes a trigger
reason under the SAME two-sided hysteresis, and the downshift target
becomes the **highest-efficiency warm rung that still meets the SLO**
instead of the nearest rung (skipped rungs named in the incident, like
the deadline-force path). A cheaper-but-SLO-violating rung is never
picked; with no warm SLO-meeting candidate the stock nearest-rung walk
(including its deferral machinery) takes over. No policy (the default)
leaves every stock code path byte-for-byte untouched.

The ladder itself is pure state machine (injected clock, no asyncio, no
deps; the gate is duck-typed ``query(step, direction) -> "warm"|"cold"``
/ ``request(step, direction)``): transports bind concrete ``down``/
``up`` callables per step via :meth:`bind_controls`; with nothing bound
the ladder still tracks and reports level transitions (the verdict
trail stays honest even when no actuator exists, e.g. webrtc mode
before its controls land).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable, Mapping, Optional

from ..obs import health as _health

logger = logging.getLogger("selkies_tpu.resilience.ladder")

__all__ = ["DegradationLadder", "DEFAULT_TRIGGERS", "DEFAULT_STEPS"]

#: verdict name -> statuses that count as a degradation trigger.
#: qoe only on failed (degraded QoE is what the ladder CAUSES while
#: shedding — reacting to it would latch the bottom rung).
DEFAULT_TRIGGERS: dict[str, frozenset] = {
    "qoe": frozenset({_health.FAILED}),
    "hbm_headroom": frozenset({_health.DEGRADED, _health.FAILED}),
    "hbm": frozenset({_health.DEGRADED, _health.FAILED}),
    "stage_latency": frozenset({_health.DEGRADED, _health.FAILED}),
}

#: rung names above level 0, in downshift order. "pipeline" (drop the
#: engine to frame-serial, depth 1) sheds latency without costing any
#: fidelity, so it is the first thing to give up and the first restored.
DEFAULT_STEPS = ("pipeline", "fps", "quality", "downscale")

_EVENT_CAP = 64


class DegradationLadder:
    def __init__(self, *,
                 steps: tuple = DEFAULT_STEPS,
                 triggers: Optional[Mapping] = None,
                 down_after_s: float = 4.0,
                 hold_s: float = 10.0,
                 ok_window_s: float = 30.0,
                 gate=None,
                 defer_deadline_s: float = 30.0,
                 energy_policy=None,
                 clock: Callable[[], float] = time.monotonic,
                 recorder: Optional[_health.FlightRecorder] = None):
        self.steps = tuple(steps)
        self.triggers = dict(triggers if triggers is not None
                             else DEFAULT_TRIGGERS)
        self.down_after_s = float(down_after_s)
        self.hold_s = float(hold_s)
        self.ok_window_s = float(ok_window_s)
        #: transition gate (prewarm plane); None = every rung is warm
        self.gate = gate
        self.defer_deadline_s = float(defer_deadline_s)
        #: energy-aware mode (ISSUE 14, obs/energy.EnergyBudgetPolicy
        #: duck type: over_budget() + select_rung(steps, level,
        #: is_warm)). None (the default) leaves every code path of the
        #: stock walk untouched.
        self.energy_policy = energy_policy
        self.deferred_transitions = 0
        #: the in-flight deferral: {step, direction, since, deadline}
        self._deferral: Optional[dict] = None
        self._clock = clock
        self.recorder = recorder if recorder is not None \
            else _health.engine.recorder
        self._lock = threading.Lock()
        self.level = 0
        self.transitions = 0
        self._bad_since: Optional[float] = None
        self._ok_since: Optional[float] = None
        self._last_change: Optional[float] = None
        self._last_reasons: list[str] = []
        #: step name -> (down_fn, up_fn); bound by the active transport
        self._controls: dict[str, tuple[Callable, Callable]] = {}
        #: content-aware rung table (ROADMAP 4): rungs the current
        #: content class makes pointless are skipped on the way down
        #: (e.g. halving fps of a static desktop sheds nothing — its
        #: frames are already idle-skipped by the partial encoder)
        self._content_class: Optional[str] = None
        self._content_skips: frozenset = frozenset()
        #: (name, perf_ns, level, reasons) ring for the trace overlay
        self._events: collections.deque = collections.deque(
            maxlen=_EVENT_CAP)

    # -- controls ------------------------------------------------------------
    def bind_controls(self, controls: Mapping[str, tuple]) -> None:
        """``{step: (down_fn, up_fn)}`` from the active transport. Steps
        with no control still transition (tracked + recorded), they just
        actuate nothing."""
        with self._lock:
            self._controls.update(controls)

    def unbind_controls(self) -> None:
        with self._lock:
            self._controls.clear()

    def set_content_profile(self, name: Optional[str],
                            skip_steps=()) -> None:
        """Content-profile-aware rungs (ROADMAP 4, engine/content.py):
        record the session's content class and the downshift rungs it
        makes pointless. Skipped rungs are passed over on the way down
        (named in the incident, like the deadline-force path); the walk
        back up is untouched — a rung that actuated before the class
        changed must still be restored. ``None`` clears."""
        skips = frozenset(skip_steps)
        with self._lock:
            changed = (name != self._content_class
                       or skips != self._content_skips)
            self._content_class = name
            self._content_skips = skips
        if changed and name is not None:
            self.recorder.record("ladder_content_profile",
                                 content_class=name,
                                 skipped_rungs=sorted(skips))

    # -- state machine -------------------------------------------------------
    def _trigger_reasons(self, verdicts: Mapping) -> list[str]:
        reasons = []
        for name, bad in self.triggers.items():
            v = verdicts.get(name)
            status = getattr(v, "status", v)
            if status in bad:
                reasons.append(f"{name}={status}")
        return sorted(reasons)

    def observe(self, verdicts: Mapping, now: Optional[float] = None) -> None:
        """One controller tick against the current verdict set (values
        may be Verdict objects or bare status strings)."""
        if now is None:
            now = self._clock()
        reasons = self._trigger_reasons(verdicts)
        # energy-aware mode (ISSUE 14): an exceeded power budget is a
        # trigger like any verdict — folding it into the SAME reason
        # set means the two-sided hysteresis (down_after_s / hold_s /
        # ok_window_s) governs power-driven shifts identically, and a
        # still-over-budget ladder can never step up
        if self.energy_policy is not None and self._power_over_budget():
            reasons = sorted(reasons + ["power=over_budget"])
        if reasons:
            self._ok_since = None
            # the trigger is back: a pending step-UP deferral is moot
            if self._deferral is not None \
                    and self._deferral["direction"] < 0:
                self._deferral = None
            if self._bad_since is None:
                self._bad_since = now
            self._last_reasons = reasons
            if self.level >= len(self.steps):
                return
            if now - self._bad_since < self.down_after_s:
                return
            if self._last_change is not None \
                    and now - self._last_change < self.hold_s:
                return
            if self._attempt_shift(now, +1, reasons):
                # a further downshift needs the trigger to PERSIST past
                # the hold from this new level, not re-accumulate
                self._bad_since = now
        else:
            self._bad_since = None
            # recovered before the deferred DOWNshift's program warmed:
            # cancel it — shedding is no longer wanted
            if self._deferral is not None \
                    and self._deferral["direction"] > 0:
                self._deferral = None
            if self._ok_since is None:
                self._ok_since = now
            if self.level == 0:
                return
            if now - self._ok_since < self.ok_window_s:
                return
            if self._last_change is not None \
                    and now - self._last_change < self.hold_s:
                return
            self._attempt_shift(now, -1, ["sustained-ok "
                                          f"{self.ok_window_s:g}s"])

    # -- compile-free-or-deferred gating -------------------------------------
    def _gate_query(self, step: str, direction: int) -> str:
        if self.gate is None:
            return "warm"
        try:
            return str(self.gate.query(step, direction))
        except Exception:
            # fail OPEN: a broken gate must not block fidelity shedding
            logger.exception("transition gate query failed; failing open")
            return "warm"

    def _gate_request(self, step: str, direction: int) -> None:
        if self.gate is None:
            return
        try:
            self.gate.request(step, direction)
        except Exception:
            logger.exception("transition gate request failed")

    def _power_over_budget(self) -> bool:
        try:
            return bool(self.energy_policy.over_budget())
        except Exception:
            # fail CLOSED on the trigger side (a broken watts feed must
            # not shed fidelity), unlike the gate's fail-open
            logger.exception("energy policy over_budget failed")
            return False

    def _energy_pick(self) -> Optional[int]:
        """Energy-aware target selection (ISSUE 14): while the power
        budget is exceeded, the downshift target is the
        highest-efficiency WARM rung that still meets the SLO — not the
        nearest rung. None (policy absent, under budget, no warm
        SLO-meeting candidate, or any policy failure) falls back to
        the stock nearest-rung walk."""
        pol = self.energy_policy
        if pol is None:
            return None
        try:
            if not pol.over_budget():
                return None
            j = pol.select_rung(
                self.steps, self.level,
                lambda s: self._gate_query(s, +1) != "cold")
        except Exception:
            logger.exception("energy policy selection failed; "
                             "using the nearest rung")
            return None
        if j is None:
            return None
        j = int(j)
        if not (self.level <= j < len(self.steps)):
            return None
        return j

    def _attempt_shift(self, now: float, direction: int,
                       reasons: list[str]) -> bool:
        """Gate-checked shift. True when a transition actually happened
        (warm target, or a deadline-forced warm alternative)."""
        to_level: Optional[int] = None
        skipped: Optional[list] = None
        if direction > 0:
            step = self.steps[self.level]
            pick = self._energy_pick()
            if pick is not None and pick != self.level:
                step = self.steps[pick]
                to_level = pick + 1
                skipped = list(self.steps[self.level:pick])
                reasons = reasons + [f"energy-efficient:{step}"]
            elif pick is None and step in self._content_skips:
                # content-profile skip: walk to the first rung the
                # current content class doesn't make pointless
                for j in range(self.level, len(self.steps)):
                    if self.steps[j] not in self._content_skips:
                        step = self.steps[j]
                        to_level = j + 1
                        skipped = list(self.steps[self.level:j])
                        reasons = reasons + [
                            f"content-skip:{self._content_class}"]
                        break
                else:
                    # every remaining rung skipped: nothing to shed
                    return False
        else:
            step = self.steps[self.level - 1]
        if self._gate_query(step, direction) != "cold":
            self._deferral = None
            if to_level is not None:
                self._shift(now, direction, reasons, step=step,
                            to_level=to_level, skipped=skipped)
            else:
                self._shift(now, direction, reasons)
            return True
        d = self._deferral
        if d is None or d["step"] != step \
                or d["direction"] != direction:
            # new deferral episode: top-priority enqueue, hold in place
            self._deferral = {"step": step, "direction": direction,
                              "since": now,
                              "deadline": now + self.defer_deadline_s}
            self.deferred_transitions += 1
            self._gate_request(step, direction)
            self.recorder.record(
                "transition_deferred", step=step,
                direction="down" if direction > 0 else "up",
                level=self.level, reasons=reasons,
                deadline_s=self.defer_deadline_s)
            self._events.append(("transition_deferred",
                                 time.perf_counter_ns(), self.level,
                                 step, reasons))
            logger.warning(
                "ladder %s to rung %s deferred: program cold; holding "
                "at level %d while it pre-warms (deadline %gs)",
                "down" if direction > 0 else "up", step, self.level,
                self.defer_deadline_s)
            return False
        if now < d["deadline"]:
            return False
        if direction > 0:
            # deadline passed: force the nearest warm rung further down
            # the table — shedding LESS precisely beats not shedding
            for j in range(self.level + 1, len(self.steps)):
                alt = self.steps[j]
                if self._gate_query(alt, +1) == "cold":
                    continue
                skipped = list(self.steps[self.level:j])
                self._deferral = None
                logger.warning(
                    "ladder deferral deadline passed: forcing warm rung "
                    "%s (skipping cold %s)", alt, ", ".join(skipped))
                self._shift(now, +1, reasons + [f"forced-warm:{alt}"],
                            step=alt, to_level=j + 1, skipped=skipped)
                return True
        # nothing warm to force (or an up-shift): keep holding, renew
        d["deadline"] = now + self.defer_deadline_s
        self._gate_request(step, direction)
        return False

    def _shift(self, now: float, direction: int, reasons: list[str], *,
               step: Optional[str] = None, to_level: Optional[int] = None,
               skipped: Optional[list] = None) -> None:
        if direction > 0:
            step = step if step is not None else self.steps[self.level]
            self.level = to_level if to_level is not None \
                else self.level + 1
            fn_idx, kind = 0, "degradation_step"
        else:
            self.level -= 1
            step = step if step is not None else self.steps[self.level]
            fn_idx, kind = 1, "degradation_recover"
        self.transitions += 1
        self._last_change = now
        with self._lock:
            ctl = self._controls.get(step)
        applied = False
        if ctl is not None:
            try:
                # a control returning the explicit sentinel False says
                # "nothing to shed/restore here" (e.g. fps already at
                # the floor) — the incident must not claim otherwise
                applied = ctl[fn_idx]() is not False
            except Exception:
                logger.exception("ladder %s control for step %s failed",
                                 "down" if direction > 0 else "up", step)
        extra = {"skipped": skipped} if skipped else {}
        self.recorder.record(kind, step=step, level=self.level,
                             reasons=reasons, applied=applied, **extra)
        self._events.append((kind, time.perf_counter_ns(), self.level,
                             step, reasons))
        _metrics_level(self.level)
        logger.warning("degradation ladder %s -> level %d (%s: %s)%s",
                       "down" if direction > 0 else "up", self.level,
                       step, ", ".join(reasons),
                       "" if applied else " [no control bound]")

    # -- reporting -----------------------------------------------------------
    def snapshot(self) -> dict:
        d = self._deferral
        return {
            "level": self.level,
            "step": self.steps[self.level - 1] if self.level else None,
            "steps": list(self.steps),
            "transitions": self.transitions,
            "active_triggers": list(self._last_reasons)
            if self._bad_since is not None else [],
            "controls_bound": sorted(self._controls),
            "content_class": self._content_class,
            "content_skips": sorted(self._content_skips),
            "gated": self.gate is not None,
            "energy_mode": self.energy_policy is not None,
            "energy": (self.energy_policy.snapshot()
                       if self.energy_policy is not None
                       and hasattr(self.energy_policy, "snapshot")
                       else None),
            "deferred_transitions": self.deferred_transitions,
            "deferred": ({"step": d["step"],
                          "direction": "down" if d["direction"] > 0
                          else "up",
                          "since": d["since"], "deadline": d["deadline"]}
                         if d else None),
        }

    def trace_events(self, pid: int = 1, tid: int = 97) -> list[dict]:
        """Ladder transitions as Chrome trace instants on a
        ``resilience`` lane (same perf_counter µs timebase as the frame,
        device and qoe lanes at ``/api/trace``)."""
        events = list(self._events)
        if not events:
            return []
        out: list[dict] = [{
            "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
            "args": {"name": "resilience"},
        }]
        for kind, t_ns, level, step, reasons in events:
            out.append({
                "name": f"{kind} L{level} ({step})",
                "ph": "i", "s": "g", "pid": pid, "tid": tid,
                "ts": t_ns / 1e3,
                "args": {"level": level, "step": step,
                         "reasons": list(reasons)},
            })
        return out


# -- optional metrics bridge (lazy; lint image has no server deps) ----------

def _metrics_level(level: int) -> None:
    try:
        from ..server import metrics
    except Exception:
        return
    metrics.describe("selkies_degradation_level",
                     "Current degradation-ladder level (0 = full fidelity)")
    metrics.set_gauge("selkies_degradation_level", level)
