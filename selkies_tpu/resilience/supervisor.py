"""Restart-policy engine: the recovery half of the observability loop.

PRs 2-4 made every failure mode visible — relay deaths, capture-loop
crashes, dead transport services, QoE collapse — and none of them
*recoverable*: the capture thread logged "capture loop died" and went
dark, ``switch_to_mode`` cleared ``active_mode`` and waited for a human.
This module owns the decision that was missing: **when a component dies,
restart it — but never in a tight loop, never forever, and always
visibly.**

Pieces:

- :class:`RestartPolicy` — pure backoff math, fully injectable clock +
  seeded jitter so tests and the selftest assert exact sequences:
  exponential backoff (``base * 2^n`` capped at ``max``), deterministic
  jitter, a restart budget inside a sliding window, and crash-loop
  detection (deaths faster than ``min_uptime_s`` escalate straight to
  the backoff cap).
- :class:`Supervisor` — component registry: ``adopt()`` a name +
  restart callable, ``report_death()`` when it dies. Scheduling is an
  injectable ``schedule(delay, cb) -> handle`` seam (default: the
  running asyncio loop's ``call_later``) so recovery tests never sleep
  wall-clock. Each restart emits a ``supervisor_restart`` incident and
  ``selkies_supervisor_restarts_total{component}``; budget exhaustion
  emits ``crash_loop`` and parks the component in ``failed``.
- :meth:`Supervisor.health_check` — the ``supervision`` health verdict:
  ``degraded`` while any component is backing off, ``failed`` once any
  exhausted its budget.

Stdlib-only (asyncio used lazily): the CI lint image drives the selftest
with neither jax nor aiohttp installed.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, Optional

from ..obs import health as _health

logger = logging.getLogger("selkies_tpu.resilience.supervisor")

__all__ = ["RestartPolicy", "SupervisedComponent", "Supervisor",
           "DrainHandle"]

#: component states
RUNNING = "running"
BACKING_OFF = "backing_off"
FAILED = "failed"
#: terminal drain state: the component died (or its pending restart was
#: cancelled) while the supervisor was draining — deliberately NOT
#: restarted, counted as stopped for drain completion
STOPPED = "stopped"


class DrainHandle:
    """Completion signal for :meth:`Supervisor.drain` — usable from
    both worlds the supervisor straddles: thread-side callers ``wait()``
    on the embedded event, asyncio callers ``await`` the handle (the
    bridge hops through ``call_soon_threadsafe``, so completion may be
    signalled from any thread). ``add_done_callback`` fires immediately
    when already done."""

    def __init__(self):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._cbs: list = []

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def add_done_callback(self, cb: Callable[[], None]) -> None:
        with self._lock:
            if not self._event.is_set():
                self._cbs.append(cb)
                return
        cb()

    def _fire(self) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._event.set()
            cbs, self._cbs = self._cbs, []
        for cb in cbs:
            try:
                cb()
            except Exception:
                logger.exception("drain-done callback failed")

    def __await__(self):
        import asyncio
        if self._event.is_set():
            return None
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def _signal():
            loop.call_soon_threadsafe(
                lambda: fut.done() or fut.set_result(None))

        self.add_done_callback(_signal)
        return (yield from fut.__await__())


class RestartPolicy:
    """Backoff/budget math for one supervised component.

    Deterministic by construction: the clock is injected and jitter
    draws from a seeded RNG, so ``next_backoff()`` sequences are exact
    in tests. A fresh policy instance is made per component (it carries
    death-history state).
    """

    #: consecutive fast deaths before the crash-loop escalation flags
    CRASH_LOOP_AFTER = 3

    def __init__(self, max_restarts: int = 5, window_s: float = 300.0,
                 base_backoff_s: float = 0.5, max_backoff_s: float = 30.0,
                 jitter: float = 0.1, min_uptime_s: float = 5.0,
                 seed: int = 0, clock: Callable[[], float] = time.monotonic):
        self.max_restarts = int(max_restarts)
        self.window_s = float(window_s)
        self.base_backoff_s = float(base_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.jitter = float(jitter)
        self.min_uptime_s = float(min_uptime_s)
        self._rng = random.Random(seed)
        self._clock = clock
        self._deaths: list[float] = []     # death times inside the window
        self._streak = 0                   # consecutive fast deaths
        self._last_restart: Optional[float] = None

    def record_started(self) -> None:
        """The component is (re)started; uptime measurement begins."""
        self._last_restart = self._clock()

    @property
    def crash_looping(self) -> bool:
        """True once ``CRASH_LOOP_AFTER`` consecutive deaths arrived
        faster than ``min_uptime_s`` each — the escalation flag carried
        on incidents (and the point where the exponential ramp has
        already driven the backoff to its cap region)."""
        return self._streak >= self.CRASH_LOOP_AFTER

    def restarts_in_window(self) -> int:
        now = self._clock()
        self._deaths = [t for t in self._deaths if now - t <= self.window_s]
        return len(self._deaths)

    def next_backoff(self) -> Optional[float]:
        """Record a death; -> backoff seconds before the next restart,
        or None when the budget inside the window is exhausted."""
        now = self._clock()
        uptime = None if self._last_restart is None \
            else now - self._last_restart
        if uptime is not None and uptime >= self.min_uptime_s:
            self._streak = 0
        self._streak += 1
        self._deaths.append(now)
        if self.restarts_in_window() > self.max_restarts:
            return None
        # consecutive fast deaths ramp 2^n toward the cap; a healthy
        # stretch (>= min_uptime_s) resets the ramp to the base
        backoff = min(self.max_backoff_s,
                      self.base_backoff_s * (2 ** (self._streak - 1)))
        if self.jitter > 0:
            backoff += backoff * self.jitter * self._rng.random()
        return backoff


class SupervisedComponent:
    __slots__ = ("name", "restart_fn", "policy", "state", "restarts",
                 "last_error", "on_give_up", "_handle", "_task",
                 "_pending_death")

    def __init__(self, name: str, restart_fn: Callable, policy: RestartPolicy,
                 on_give_up: Optional[Callable[[], None]] = None):
        self.name = name
        self.restart_fn = restart_fn
        self.policy = policy
        self.state = RUNNING
        self.restarts = 0
        self.last_error = ""
        self.on_give_up = on_give_up
        self._handle = None         # pending backoff-timer handle
        self._task = None           # in-flight async restart (strong ref)
        self._pending_death = None  # death queued behind that restart

    def to_dict(self) -> dict:
        return {"name": self.name, "state": self.state,
                "restarts": self.restarts, "last_error": self.last_error,
                "crash_looping": self.policy.crash_looping}


def _default_schedule(delay: float, cb: Callable[[], None]):
    """Default scheduler: the running asyncio loop. Imported lazily so
    the policy math stays usable in loop-less contexts (selftest)."""
    import asyncio
    return asyncio.get_running_loop().call_later(delay, cb)


class Supervisor:
    """Component registry + restart driver.

    ``schedule`` is the injection seam: ``schedule(delay_s, cb)`` must
    return a handle with ``.cancel()``. The default uses the running
    asyncio loop; deterministic tests pass a manual scheduler and fire
    callbacks by hand. ``report_death`` is loop-thread affine in the
    default configuration (capture threads hop via
    ``call_soon_threadsafe`` at the wiring site).
    """

    def __init__(self, recorder: Optional[_health.FlightRecorder] = None,
                 policy_factory: Optional[Callable[[], RestartPolicy]] = None,
                 schedule: Callable = _default_schedule):
        self._components: dict[str, SupervisedComponent] = {}
        self._lock = threading.Lock()
        self.recorder = recorder if recorder is not None \
            else _health.engine.recorder
        self.policy_factory = policy_factory or RestartPolicy
        self.schedule = schedule
        self.total_restarts = 0
        self._closed = False
        self._draining = False
        self._drain_handle: Optional[DrainHandle] = None
        self._drain_scope = None

    # -- registry ------------------------------------------------------------
    def adopt(self, name: str, restart_fn: Callable,
              policy: Optional[RestartPolicy] = None,
              on_give_up: Optional[Callable[[], None]] = None
              ) -> SupervisedComponent:
        """Register (or re-register) a component. Re-adoption keeps the
        existing policy state — a service that re-registers its closure
        on every (re)start must not reset its own crash accounting.

        Re-adopting a FAILED component un-parks it: adoption happens on
        deliberate (re)starts (operator switch, client START_VIDEO), so
        the next death must be SUPERVISED again, not silently ignored.
        The policy's sliding-window death history is kept, so a death
        arriving before the old ones age out immediately re-exhausts the
        budget — visibly, with a fresh ``crash_loop`` incident."""
        with self._lock:
            comp = self._components.get(name)
            if comp is None:
                comp = SupervisedComponent(
                    name, restart_fn, policy or self.policy_factory(),
                    on_give_up)
                comp.policy.record_started()
                self._components[name] = comp
            else:
                comp.restart_fn = restart_fn
                if on_give_up is not None:
                    comp.on_give_up = on_give_up
                if policy is not None:
                    comp.policy = policy
                if comp.state == FAILED:
                    comp.state = RUNNING
                    comp.policy.record_started()
            return comp

    def drop(self, name: str) -> None:
        """Deliberate teardown (client left, service stopping): cancel
        any pending restart and forget the component."""
        with self._lock:
            comp = self._components.pop(name, None)
        if comp is not None:
            for h in (comp._handle, comp._task):
                if h is not None:
                    try:
                        h.cancel()
                    except Exception:
                        pass
        self._check_drained()

    def get(self, name: str) -> Optional[SupervisedComponent]:
        with self._lock:
            return self._components.get(name)

    def components(self) -> list[dict]:
        with self._lock:
            comps = list(self._components.values())
        return [c.to_dict() for c in comps]

    def close(self) -> None:
        self._closed = True
        with self._lock:
            comps = list(self._components.values())
            self._components.clear()
        for c in comps:
            for h in (c._handle, c._task):
                if h is not None:
                    try:
                        h.cancel()
                    except Exception:
                        pass
        self._check_drained()

    # -- drain ---------------------------------------------------------------
    def drain(self, scope=None) -> DrainHandle:
        """Stop restarting and answer WHEN everything has stopped.

        From this call on, the supervisor's job inverts: a component
        death is no longer a fault to recover but a step toward done —
        it is marked ``stopped`` instead of rescheduled, pending backoff
        timers are cancelled (those components already died; they count
        as stopped now), and the returned :class:`DrainHandle` fires
        once every supervised component is terminal (``stopped`` /
        ``failed``) or dropped. Callers that poll component state to
        know when a host is evacuated (the old migration shape) race
        the restart engine; awaiting the handle cannot.

        ``scope`` (optional ``name -> bool`` predicate) narrows the
        drain to a subset of components: only in-scope components are
        tracked by the handle and stop-on-death; out-of-scope ones keep
        full supervision (deaths restart). A host evacuation needs
        exactly this split — the seat-serving components must stop, but
        the control plane (the service itself, the prewarm worker, the
        fleet heartbeat push) must OUTLIVE the drain so the gateway can
        watch it finish. ``scope=None`` drains everything (process
        shutdown).

        Idempotent: repeat calls return the same handle (the FIRST
        call's scope wins). ``drop()`` of still-running components (the
        services' deliberate-teardown path) advances the same
        completion check."""
        first = False
        with self._lock:
            if self._drain_handle is not None:
                handle = self._drain_handle
                comps = []
            else:
                first = True
                self._draining = True
                self._drain_scope = scope
                handle = self._drain_handle = DrainHandle()
                comps = [c for c in self._components.values()
                         if scope is None or scope(c.name)]
        if first:
            self.recorder.record("supervisor_drain",
                                 components=len(comps),
                                 scoped=scope is not None)
        for c in comps:
            if c.state == BACKING_OFF:
                # the component is already dead; cancelling the pending
                # restart IS its stop
                if c._handle is not None:
                    try:
                        c._handle.cancel()
                    except Exception:
                        pass
                    c._handle = None
                c.state = STOPPED
        self._check_drained()
        return handle

    @property
    def draining(self) -> bool:
        return self._draining

    def _in_drain_scope(self, name: str) -> bool:
        scope = self._drain_scope
        return scope is None or bool(scope(name))

    def _check_drained(self) -> None:
        handle = self._drain_handle
        if handle is None or handle.done:
            return
        with self._lock:
            pending = [c.name for c in self._components.values()
                       if c.state not in (STOPPED, FAILED)
                       and self._in_drain_scope(c.name)]
        if not pending:
            handle._fire()

    # -- death handling ------------------------------------------------------
    def report_death(self, name: str, reason: str = "") -> None:
        """A supervised component died. Decide: restart after backoff,
        or give up (budget exhausted / crash loop past budget)."""
        if self._closed:
            return
        comp = self.get(name)
        if comp is None or comp.state in (FAILED, STOPPED):
            return
        if self._draining and self._in_drain_scope(name):
            # the drain inversion: a death while draining is the
            # component stopping, not a fault to recover — but only for
            # in-scope components; out-of-scope ones (the control plane
            # of a scoped host evacuation) keep restarting
            comp.last_error = str(reason)[:200]
            comp.state = STOPPED
            self._check_drained()
            return
        if comp.state == BACKING_OFF:
            return      # a restart is already pending; coalesce
        if comp._task is not None:
            # an async restart is still in flight: a second schedule now
            # would run two restarts concurrently. QUEUE the death — the
            # restart may well succeed (e.g. the new capture thread
            # started, then crashed before the executor future resolved)
            # and dropping this report would abandon the component with
            # supervision reading ok.
            comp._pending_death = str(reason)[:200]
            return
        comp.last_error = str(reason)[:200]
        backoff = comp.policy.next_backoff()
        if backoff is None:
            comp.state = FAILED
            self.recorder.record(
                "crash_loop", component=name, reason=comp.last_error,
                restarts=comp.restarts)
            logger.error("component %s exhausted its restart budget "
                         "(%d restarts); giving up", name, comp.restarts)
            if comp.on_give_up is not None:
                try:
                    comp.on_give_up()
                except Exception:
                    logger.exception("give-up hook for %s failed", name)
            return
        comp.state = BACKING_OFF
        comp.restarts += 1
        self.total_restarts += 1
        self.recorder.record(
            "supervisor_restart", component=name, reason=comp.last_error,
            backoff_s=round(backoff, 3), restart=comp.restarts,
            crash_looping=comp.policy.crash_looping)
        _metrics_restart(name)
        logger.warning("component %s died (%s); restart %d in %.2fs%s",
                       name, comp.last_error or "no reason", comp.restarts,
                       backoff, " [crash-looping]"
                       if comp.policy.crash_looping else "")
        comp._handle = self.schedule(backoff, lambda: self._fire(name))

    def _fire(self, name: str) -> None:
        """Backoff elapsed: run the restart callable. A sync callable
        that raises (or an awaitable that fails) counts as another
        death, feeding the policy again."""
        comp = self.get(name)
        if comp is None or self._closed \
                or (self._draining and self._in_drain_scope(name)):
            return
        comp._handle = None
        comp.state = RUNNING
        comp.policy.record_started()
        try:
            res = comp.restart_fn()
        except Exception as e:
            logger.exception("restart of %s failed", name)
            self.report_death(name, f"restart failed: "
                              f"{type(e).__name__}: {e}")
            return
        if res is not None and hasattr(res, "__await__"):
            import asyncio
            task = asyncio.ensure_future(res)
            # strong-ref the in-flight restart on its OWN slot (the
            # timer handle slot gets reused by the next death report;
            # sharing would drop this task's only strong reference)
            comp._task = task

            def _done(t, name=name):
                c = self.get(name)
                pending = None
                if c is not None:
                    if c._task is t:
                        c._task = None
                    # always consume the queued death: a stale one must
                    # not replay against a LATER restart's completion
                    pending, c._pending_death = c._pending_death, None
                if t.cancelled():
                    return
                exc = t.exception()
                if exc is not None:
                    self.report_death(name, f"restart failed: "
                                      f"{type(exc).__name__}: {exc}")
                elif pending is not None:
                    # the restart succeeded but the component died again
                    # while it was in flight: replay the queued death
                    self.report_death(name, pending)
            task.add_done_callback(_done)

    # -- health --------------------------------------------------------------
    def health_check(self) -> _health.Verdict:
        """The ``supervision`` check: failed once any component
        exhausted its budget, degraded while any is backing off."""
        comps = self.components()
        dead = [c["name"] for c in comps if c["state"] == FAILED]
        if dead:
            return _health.failed(
                f"restart budget exhausted: {', '.join(sorted(dead))}",
                components=dead)
        waiting = [c["name"] for c in comps if c["state"] == BACKING_OFF]
        if waiting:
            return _health.degraded(
                f"backing off before restart: {', '.join(sorted(waiting))}",
                components=waiting)
        n = sum(c["restarts"] for c in comps)
        return _health.ok(f"{len(comps)} supervised, {n} restarts",
                          supervised=len(comps), restarts=n)


# -- optional metrics bridge (lazy; lint image has no server deps) ----------

def _metrics_restart(component: str) -> None:
    try:
        from ..server import metrics
    except Exception:
        return
    metrics.describe("selkies_supervisor_restarts_total",
                     "Supervised component restarts by component")
    metrics.inc_counter("selkies_supervisor_restarts_total",
                        labels={"component": component})
