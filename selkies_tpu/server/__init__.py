"""Control plane: single-port aiohttp server + transport services.

Layer 3/4 of SURVEY.md §1: one HTTP app serves static client files, the
``/api/*`` surface, and exactly one active streaming transport (WebSockets
by default, WebRTC opt-in), mirroring the reference's
``CentralizedStreamServer`` architecture (stream_server.py:390) without
porting its code.
"""

from .core import BaseStreamingService, CentralizedStreamServer  # noqa: F401
