"""Single-port HTTP core / service supervisor.

Re-implements the responsibilities of the reference's
``CentralizedStreamServer`` (stream_server.py:390-1421) on aiohttp,
designed fresh:

- auth middleware: HTTP basic auth, a view-only password granting
  input-less sessions, and a master bearer token — all compared
  timing-safely (reference :689-792);
- WebSocket Origin guard (reference :647-686);
- static client serving from the packaged ``web/`` directory or
  ``--web_root``;
- ``/api/status``, ``/api/health`` (named verdicts via
  ``selkies_tpu.obs``: ``?verbose=1`` for the full check set and the
  incident flight recorder, ``?probe=live|ready`` for container
  orchestration), ``/api/metrics``, ``/api/switch`` (live transport
  swap when ``enable_dual_mode``, reference :804-895), ``/api/profile``
  (on-demand jax.profiler capture, full-role gated), ``/api/perf``
  (static step cost attribution + pipeline occupancy, ISSUE 6),
  ``/api/slo`` (error-budget burn-rate verdicts, ISSUE 7);
- chunked file upload with path-traversal + symlink defences and a
  JSON/HTML download index (reference :897-1299);
- TLS with live certificate reload (reference :552-632);
- ``BaseStreamingService`` ABC so transports are pluggable and fakeable
  (the testability seam SURVEY.md §4.5 calls out).
"""

from __future__ import annotations

import abc
import asyncio
import base64
import hmac
import html
import json
import logging
import os
import pathlib
import ssl
import time
import urllib.parse
from typing import Optional
from urllib.parse import urlparse

from aiohttp import web

from ..obs import energy as _energy
from ..obs import health as _health
from ..obs import qoe as _qoe
from ..obs import slo as _slo
from ..resilience import faults as _faults
from ..resilience.ladder import DegradationLadder
from ..resilience.supervisor import RestartPolicy, Supervisor
from ..settings import AppSettings, is_sensitive

logger = logging.getLogger("selkies_tpu.server.core")

WEB_ROOT = pathlib.Path(__file__).resolve().parent.parent / "web"


class BaseStreamingService(abc.ABC):
    """Transport service contract (reference stream_server.py:372-387)."""

    name: str = "base"
    core: "Optional[CentralizedStreamServer]" = None  # set on register

    @abc.abstractmethod
    async def start(self) -> None: ...

    @abc.abstractmethod
    async def stop(self) -> None: ...

    def register_routes(self, app: web.Application) -> None:
        """Add the service's endpoints to the shared app."""


def _timing_safe_eq(a: str, b: str) -> bool:
    return hmac.compare_digest(a.encode(), b.encode())


class CentralizedStreamServer:
    def __init__(self, settings: AppSettings):
        self.settings = settings
        self.services: dict[str, BaseStreamingService] = {}
        self.active_mode: Optional[str] = None
        self._service_task: Optional[asyncio.Task] = None
        self.app = web.Application(middlewares=[self._auth_middleware])
        self._runner: Optional[web.AppRunner] = None
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        self._cert_watch_task: Optional[asyncio.Task] = None
        self.started_at = time.time()
        #: secure-mode WS tokens: token -> {role, created, uses}
        self.ws_tokens: dict[str, dict] = {}
        #: fleet drain state (POST /api/drain): while True the
        #: readiness probe fails (gateway routes nothing new here) and
        #: the fleet heartbeat carries draining=true
        self.draining = False
        self._drain_handle = None
        self._fleet_seq = 0
        #: supervised heartbeat push loop (ISSUE 19): state for the
        #: fleet_push health check + /api/fleet push diagnostics. The
        #: clock sample is the NTP-style [t0,t1,t2,t3] completed by the
        #: previous push's response, echoed in the next heartbeat so
        #: the gateway's per-host clocksync estimator converges.
        self._fleet_push_task: Optional[asyncio.Task] = None
        self._fleet_push_stats = {"sent": 0, "errors": 0,
                                  "rejected": 0, "last_ok": None,
                                  "last_error": "", "backoff_s": 0.0}
        self._fleet_clock_sample: Optional[list] = None
        #: the process-wide health engine; services register their
        #: checks against it in start() (tests may swap it out)
        self.health = _health.engine
        self.health.register("service", self._check_service, liveness=True)
        self.health.register("stage_latency", self._check_stage_latency)
        # per-session wire QoE (obs.qoe): registered here — not in a
        # transport — so the check exists whichever mode is active.
        # Per-instance wrapper: bound methods of the registry singleton
        # compare equal across server instances, which would defeat the
        # owner-matched unregister in shutdown()
        _qoe.registry.configure(
            seat_label_cap=getattr(settings, "qoe_seat_label_cap", None),
            degraded_score=getattr(settings, "qoe_degraded_score", None),
            failed_score=getattr(settings, "qoe_failed_score", None))
        self._check_qoe = lambda: _qoe.registry.health_check()
        self.health.register("qoe", self._check_qoe)
        # SLO burn-rate engine (obs.slo): the stock objectives (g2g /
        # fps / qoe) are declared HERE — not in a transport — so the
        # promise set exists whichever mode is active; transports just
        # record events against the named objectives.
        _slo.engine.configure_defaults(settings)
        self._check_slo = lambda: _slo.engine.health_check()
        self.health.register("slo", self._check_slo)
        # resilience plane (selkies_tpu/resilience): the supervisor owns
        # every restart decision (transport service here; captures,
        # relays and audio adopt through it from the services), the
        # ladder sheds fidelity on bad verdicts. Policy knobs from
        # settings; per-component policies share the factory.
        self.supervisor = Supervisor(
            recorder=self.health.recorder,
            policy_factory=lambda: RestartPolicy(
                max_restarts=int(getattr(
                    settings, "supervisor_max_restarts", 5)),
                window_s=float(getattr(
                    settings, "supervisor_window_s", 300.0)),
                base_backoff_s=float(getattr(
                    settings, "supervisor_backoff_base_s", 0.5)),
                max_backoff_s=float(getattr(
                    settings, "supervisor_backoff_max_s", 30.0))))
        self._check_supervision = self.supervisor.health_check
        self.health.register("supervision", self._check_supervision)
        self.ladder: Optional[DegradationLadder] = None
        if getattr(settings, "enable_degradation_ladder", True):
            self.ladder = DegradationLadder(
                down_after_s=float(getattr(
                    settings, "ladder_down_after_s", 4.0)),
                hold_s=float(getattr(settings, "ladder_hold_s", 10.0)),
                ok_window_s=float(getattr(
                    settings, "ladder_ok_window_s", 30.0)),
                defer_deadline_s=float(getattr(
                    settings, "prewarm_defer_deadline_s", 30.0)),
                # energy-aware mode (ISSUE 14): armed only by a
                # positive power_budget_w — None leaves the stock walk
                # byte-for-byte untouched
                energy_policy=_energy.ladder_policy_from_settings(
                    settings),
                recorder=self.health.recorder)
        self._ladder_task: Optional[asyncio.Task] = None
        # compile plane (selkies_tpu/prewarm, ISSUE 8): enumerate the
        # ladder-reachable signature lattice and gate every ladder
        # transition on it — a cold rung defers instead of compiling in
        # the foreground. The worker THREAD starts in run() (unit tests
        # build servers without ever wanting background XLA builds).
        self.prewarm = None
        self._prewarm_artifact: Optional[dict] = None
        if getattr(settings, "enable_prewarm", True):
            from ..obs import monitor as _devmon
            from ..prewarm.lattice import lattice_from_settings
            from ..prewarm.worker import PrewarmGate, PrewarmWorker
            plan = lattice_from_settings(
                settings,
                steps=self.ladder.steps if self.ladder is not None
                else ("fps", "quality", "downscale"))
            self.prewarm = PrewarmWorker(
                plan, storm_check=_devmon.storm_recent,
                recorder=self.health.recorder)
            self._check_prewarm = self.prewarm.health_check
            self.health.register("prewarm", self._check_prewarm)
            # the prewarm-complete ROUTING GATE (ISSUE 11 / ROADMAP 3):
            # ?probe=ready answers failed until the current operating
            # point's programs are warm, so a load balancer never
            # routes onto a cold host. Gate-scope: the default
            # /api/health report stays about process health — a
            # warming host is healthy, just not routable yet.
            self._check_prewarm_ready = self.prewarm.current_op_ready
            self.health.register("prewarm_ready",
                                 self._check_prewarm_ready, gate=True)
            if self.ladder is not None:
                self.ladder.gate = PrewarmGate(self.prewarm,
                                               plan.rung_targets)
        # drain gate: readiness fails the moment an evacuation starts,
        # whatever else is healthy (a draining host must drop out of
        # the gateway's feasible set before its seats start moving)
        self._check_draining = lambda: (
            _health.failed("host draining (evacuation in progress)")
            if self.draining else _health.ok("not draining"))
        self.health.register("draining", self._check_draining, gate=True)
        # fleet push health: only meaningful when a gateway is
        # configured — an unconfigured host must not carry a forever-
        # degraded check
        self._check_fleet_push = self._fleet_push_check
        if getattr(settings, "fleet_gateway", ""):
            self.health.register("fleet_push", self._check_fleet_push)
        #: serialises switch_to_mode: two overlapping switches must not
        #: interleave stop/start and strand a service
        self._switch_lock = asyncio.Lock()
        if getattr(settings, "fault_inject", ""):
            _faults.registry.arm(settings.fault_inject)
        # env seam (ISSUE 20): the chaos bench arms fault points inside
        # engine-host subprocesses the actuator spawns, before any
        # control-plane endpoint is reachable. Idempotent with the
        # entrypoint's own arm_from_env call.
        _faults.arm_from_env()
        self._setup_routes()

    # ------------------------------------------------------------------ auth
    def _role_for_request(self, request: web.Request) -> Optional[str]:
        """None = reject; 'full' | 'viewonly' otherwise."""
        s = self.settings
        # master bearer token always wins
        token = s.master_token
        auth = request.headers.get("Authorization", "")
        if token and auth.startswith("Bearer ") \
                and _timing_safe_eq(auth[7:], token):
            return "full"
        if not s.enable_basic_auth:
            return "full"
        if auth.startswith("Basic "):
            try:
                user, _, pw = base64.b64decode(auth[6:]).decode().partition(":")
            except Exception:
                return None
            user_ok = _timing_safe_eq(user, s.basic_auth_user or "")
            if user_ok and _timing_safe_eq(pw, s.basic_auth_password or ""):
                return "full"
            if user_ok and s.viewonly_password \
                    and _timing_safe_eq(pw, s.viewonly_password):
                return "viewonly"
        return None

    @web.middleware
    async def _auth_middleware(self, request: web.Request, handler):
        role = self._role_for_request(request)
        if role is None:
            return web.Response(
                status=401, headers={"WWW-Authenticate": 'Basic realm="selkies"'})
        request["role"] = role
        if request.headers.get("Upgrade", "").lower() == "websocket" \
                and not self._is_ws_origin_allowed(request):
            logger.warning("rejected WS upgrade from origin %s",
                           request.headers.get("Origin"))
            return web.Response(status=403, text="origin not allowed")
        return await handler(request)

    def _is_ws_origin_allowed(self, request: web.Request) -> bool:
        """Same-host by default; explicit allow-list via settings
        (reference stream_server.py:647-686)."""
        origin = request.headers.get("Origin")
        if not origin:
            return True  # non-browser clients
        allowed = self.settings.allowed_ws_origins
        if allowed and origin in allowed:
            return True
        try:
            o = urlparse(origin)
        except ValueError:
            return False
        host = request.headers.get("Host", "")
        return o.netloc == host or (o.hostname in ("localhost", "127.0.0.1"))

    # ---------------------------------------------------------------- routes
    def _setup_routes(self) -> None:
        r = self.app.router
        r.add_get("/api/status", self.handle_status)
        r.add_get("/api/health", self.handle_health)
        r.add_post("/api/switch", self.handle_switch)
        r.add_get("/api/trace", self.handle_trace)
        r.add_post("/api/trace", self.handle_trace_control)
        r.add_get("/api/perf", self.handle_perf)
        r.add_get("/api/sessions", self.handle_sessions)
        r.add_get("/api/slo", self.handle_slo)
        r.add_post("/api/profile", self.handle_profile)
        r.add_get("/api/faults", self.handle_faults)
        r.add_post("/api/faults", self.handle_faults_control)
        r.add_get("/api/resilience", self.handle_resilience)
        r.add_get("/api/prewarm", self.handle_prewarm)
        r.add_get("/api/fleet", self.handle_fleet)
        r.add_post("/api/drain", self.handle_drain)
        if self.settings.secure_api:
            r.add_post("/api/tokens", self.handle_mint_token)
            r.add_get("/api/tokens", self.handle_list_tokens)
            r.add_delete("/api/tokens", self.handle_revoke_token)
        if self.settings.enable_metrics:
            r.add_get("/api/metrics", self.handle_metrics)
        if self.settings.enable_file_transfer:
            r.add_post("/api/upload", self.handle_upload)
            r.add_get("/api/files", self.handle_file_index)
            r.add_get("/api/files/{name:.+}", self.handle_file_download)

    def register_static(self) -> None:
        """Added last so /api/* wins; serves the packaged web client plus
        the optional dashboard / touch-gamepad addons when the repo layout
        carries them (reference serves dashboards as separate addon
        bundles, docs/component.md:163-165)."""
        root = WEB_ROOT
        addons = root.parent.parent / "addons"
        dash = addons / "selkies-dashboard"
        if dash.is_dir():
            async def _dash_index(request, d=dash):
                return web.FileResponse(d / "index.html")
            self.app.router.add_get("/dashboard/", _dash_index)
            self.app.router.add_static("/dashboard/", dash,
                                       show_index=False)
        tg = addons / "universal-touch-gamepad"
        if tg.is_dir():
            self.app.router.add_static("/touch-gamepad/", tg,
                                       show_index=False)
        if root.is_dir():
            self.app.router.add_get("/", self._index)
            self.app.router.add_static("/", root, show_index=False)

    async def _index(self, request: web.Request) -> web.StreamResponse:
        return web.FileResponse(WEB_ROOT / "index.html")

    async def handle_status(self, request: web.Request) -> web.Response:
        return web.json_response({
            "app": self.settings.app_name,
            "mode": self.active_mode,
            "uptime_s": round(time.time() - self.started_at, 1),
            "dual_mode": self.settings.enable_dual_mode,
            "role": request["role"],
        })

    # ---------------------------------------------------------------- health
    def _check_service(self) -> "_health.Verdict":
        """Liveness-scope: the transport supervisor itself. A dead
        active service means a restart can actually help."""
        if self.active_mode in self.services:
            return _health.ok(f"mode {self.active_mode}")
        return _health.failed(
            f"active mode {self.active_mode!r} is not a running service")

    def _check_stage_latency(self) -> "_health.Verdict":
        """Stage p99 vs budget from the trace summarizer (PR-2). Honest
        ok when tracing is off — a missing verdict must not read as a
        healthy pipeline, so the reason says WHY there is no number."""
        from ..trace import tracer
        from ..trace.summary import summarize_timelines
        if not tracer.enabled:
            return _health.ok("tracing disabled (enable via /api/trace)")
        summary = summarize_timelines(
            t for t in tracer.snapshot() if t.done)
        if not summary:
            return _health.ok("tracing on, no completed frames yet")
        budget = float(getattr(self.settings, "health_stage_budget_ms",
                               50.0))
        name, stat = max(summary.items(), key=lambda kv: kv[1]["p99_ms"])
        msg = f"worst stage {name} p99={stat['p99_ms']}ms " \
              f"(budget {budget}ms)"
        if stat["p99_ms"] > 2 * budget:
            return _health.failed(msg, stage=name, p99_ms=stat["p99_ms"])
        if stat["p99_ms"] > budget:
            return _health.degraded(msg, stage=name, p99_ms=stat["p99_ms"])
        return _health.ok(msg, stage=name, p99_ms=stat["p99_ms"])

    async def handle_health(self, request: web.Request) -> web.Response:
        """Named verdicts (selkies_tpu/obs). Default payload keeps the
        legacy ``ok``/``mode`` fields; ``?verbose=1`` adds every check's
        verdict + the incident ring; ``?probe=live`` answers only the
        liveness scope (k8s livenessProbe must not crash-loop a pod over
        a dead external relay — that is readiness's job)."""
        if request.query.get("probe") == "live":
            # liveness-scope checks ONLY — a wedged readiness closure
            # must not be able to time this probe out
            report = self.health.liveness()
            report["mode"] = self.active_mode
            return web.json_response(
                report, status=200 if report["live"] else 503)
        if request.query.get("probe") == "ready":
            # readiness + routing gates (prewarm-complete, draining):
            # the load balancer's answer — failed until the current
            # operating point is warm, so traffic never lands on a
            # cold host mid-first-compile (ROADMAP 3's /api/prewarm
            # probe, folded into the probe the LB already polls)
            report = self.health.readiness()
            report["mode"] = self.active_mode
            return web.json_response(
                report, status=200 if report["ready"] else 503)
        report = self.health.report(
            verbose=request.query.get("verbose") in ("1", "true"))
        report["mode"] = self.active_mode
        return web.json_response(report,
                                 status=200 if report["ready"] else 503)

    async def handle_profile(self, request: web.Request) -> web.Response:
        """POST {"action": "start"|"stop"|"status"[, "dir": path]} —
        on-demand jax.profiler capture (full-role gated; start/stop do
        file I/O inside jax, so they run in an executor)."""
        if request["role"] != "full":
            return web.Response(status=403, text="view-only")
        from ..obs import profiler
        try:
            body = await request.json()
        except Exception:
            body = {}
        if not isinstance(body, dict):
            return web.Response(status=400, text="JSON object body required")
        action = body.get("action")
        loop = asyncio.get_running_loop()
        if action == "start":
            trace_dir = body.get("dir") \
                or (self.settings.profile_dir or None)
            res = await loop.run_in_executor(
                None, lambda: profiler.start(trace_dir))
        elif action == "stop":
            res = await loop.run_in_executor(None, profiler.stop)
        elif action == "status":
            res = profiler.status()
        else:
            return web.Response(
                status=400,
                text=f"unknown action {action!r} (want start|stop|status)")
        return web.json_response(res,
                                 status=200 if res.get("ok", True) else 409)

    async def handle_perf(self, request: web.Request) -> web.Response:
        """Performance observability (obs.perf, ISSUE 6): static
        per-step cost analysis (flops / HBM bytes / roofline-ms recorded
        at compile time) plus occupancy / critical-path analysis over
        the live trace ring. ``?profile=1`` additionally parses the last
        completed jax.profiler capture into a per-step device-time table
        (full-role: it reads capture files off disk)."""
        from ..obs import perf as _perf
        from ..obs import profiler
        from ..trace import tracer
        from ..trace.summary import occupancy_report
        done = [t for t in tracer.snapshot() if t.done]
        doc = {
            "perf": _perf.registry.report(),
            "occupancy": occupancy_report(done),
            # energy plane (ISSUE 14): watts / joules-per-frame /
            # fps-per-W (source-labelled proxy|rapl|device) plus the
            # per-frame/per-session attribution over the live ring
            "energy": _energy.meter.report(timelines=done),
            "tracing": tracer.enabled,
        }
        if request.query.get("profile") in ("1", "true"):
            if request["role"] != "full":
                return web.Response(status=403, text="view-only")
            last = profiler.status().get("last_trace_dir")
            if last:
                loop = asyncio.get_running_loop()
                doc["profile"] = await loop.run_in_executor(
                    None, lambda: _perf.parse_profile_dir(last))
            else:
                doc["profile"] = None
        return web.json_response(doc)

    async def handle_slo(self, request: web.Request) -> web.Response:
        """Declarative SLO verdicts (obs.slo): per-objective fast/slow
        burn rates, remaining error budget, and the multi-window
        alerting verdict. Ungated like /api/health — the burn-rate
        panel is the first thing an on-call dashboard polls."""
        return web.json_response(_slo.engine.report())

    async def handle_sessions(self, request: web.Request) -> web.Response:
        """Per-session wire QoE (the ``getStats()`` analog): summary
        list by default, ``?verbose=1`` for the full per-session detail
        (ACK percentiles, backpressure windows, relay counters, CC
        internals). Full-role gated like the other operator surfaces —
        it carries peer addresses and per-client wire state."""
        if request["role"] != "full":
            return web.Response(status=403, text="view-only")
        verbose = request.query.get("verbose") in ("1", "true")
        return web.json_response(_qoe.registry.report(verbose=verbose))

    async def handle_faults(self, request: web.Request) -> web.Response:
        """Armed fault-injection state (full-role: fault specs reveal —
        and steer — failure behaviour)."""
        if request["role"] != "full":
            return web.Response(status=403, text="view-only")
        return web.json_response({
            "active": _faults.registry.active(),
            "remaining": _faults.registry.remaining(),
            "fired": list(_faults.registry.fired_log),
            "seed": _faults.registry.seed,
        })

    async def handle_faults_control(self, request: web.Request
                                    ) -> web.Response:
        """POST {"action": "arm", "spec": "point:mode[:k=v,...];..."
        [, "seed": N]} | {"action": "disarm"[, "point": p]}."""
        if request["role"] != "full":
            return web.Response(status=403, text="view-only")
        try:
            body = await request.json()
        except Exception:
            body = {}
        if not isinstance(body, dict):
            return web.Response(status=400, text="JSON object body required")
        action = body.get("action", "arm")
        if action == "arm":
            spec = body.get("spec", "")
            try:
                armed = _faults.registry.arm(spec, seed=body.get("seed"))
            except (ValueError, TypeError) as e:
                return web.Response(status=400, text=f"bad fault spec: {e}")
            if not armed:
                return web.Response(status=400, text="empty fault spec")
            return web.json_response({"armed": [s.to_dict() for s in armed]})
        if action == "disarm":
            removed = _faults.registry.disarm(body.get("point"))
            return web.json_response({"removed": removed})
        return web.Response(
            status=400, text=f"unknown action {action!r} (want arm|disarm)")

    async def handle_prewarm(self, request: web.Request) -> web.Response:
        """Compile-plane state (selkies_tpu/prewarm): lattice progress,
        per-program states, pause/storm status, the startup warm-cache
        artifact outcome, and the ladder's deferred-transition state.
        Ungated like /api/health — it is the first panel an operator
        checks when a quality downshift is 'taking a while'."""
        ladder = None
        if self.ladder is not None:
            snap = self.ladder.snapshot()
            ladder = {"deferred": snap["deferred"],
                      "deferred_transitions": snap["deferred_transitions"],
                      "gated": snap["gated"], "level": snap["level"]}
        return web.json_response({
            "enabled": self.prewarm is not None,
            "worker": self.prewarm.snapshot() if self.prewarm else None,
            "artifact": self._prewarm_artifact,
            "ladder": ladder,
        })

    async def handle_fleet(self, request: web.Request) -> web.Response:
        """This engine host's fleet heartbeat document (ISSUE 11): the
        capacity/health/SLO/warm snapshot the gateway's scheduler bins
        on. Full-role gated — it enumerates sessions and capacity, the
        same sensitivity as /api/sessions. A push deployment POSTs this
        same document to the gateway's /fleet/heartbeat; a pull
        deployment lets the gateway poll here."""
        if request["role"] != "full":
            return web.Response(status=403, text="view-only")
        doc = self._fleet_heartbeat_doc()
        doc["push"] = dict(self._fleet_push_stats)
        return web.json_response(doc)

    def _fleet_advertise_url(self) -> str:
        """A ROUTABLE base url for heartbeats: the bind address is
        0.0.0.0 by default, which the gateway would dutifully proxy to
        itself."""
        s = self.settings
        url = str(getattr(s, "fleet_url", "") or "")
        if not url:
            import socket as _socket
            host = s.addr if s.addr not in ("0.0.0.0", "::", "") \
                else _socket.gethostname()
            scheme = "https" if s.enable_https else "http"
            url = f"{scheme}://{host}:{s.port}"
        return url

    def _fleet_heartbeat_doc(self) -> dict:
        from ..fleet.protocol import heartbeat_from_core
        self._fleet_seq += 1
        hb = heartbeat_from_core(self, url=self._fleet_advertise_url(),
                                 seq=self._fleet_seq)
        doc = hb.to_dict()
        if self._drain_handle is not None:
            doc["drain"] = {"done": self._drain_handle.done}
        return doc

    # -------------------------------------------------- fleet push loop
    def _fleet_push_check(self):
        """The ``fleet_push`` health verdict: a host whose pushes are
        failing is invisible to the gateway — past the gateway's
        host-timeout horizon that IS host death, so the verdict
        escalates with silence age."""
        st = self._fleet_push_stats
        interval = float(getattr(self.settings,
                                 "fleet_push_interval_s", 2.0))
        if st["last_ok"] is None:
            if st["errors"] or st["rejected"]:
                return _health.degraded(
                    "no successful push yet: " + st["last_error"],
                    **{k: v for k, v in st.items() if k != "last_error"})
            return _health.ok("push loop starting")
        age = time.monotonic() - st["last_ok"]
        if age > 10 * interval:
            return _health.failed(
                f"no successful push for {age:.1f}s "
                f"(gateway sees this host as dead)",
                age_s=round(age, 1), **{"errors": st["errors"]})
        if age > 3 * interval or st["backoff_s"]:
            return _health.degraded(
                f"push degraded (last ok {age:.1f}s ago): "
                + st["last_error"],
                age_s=round(age, 1), backoff_s=st["backoff_s"])
        return _health.ok(f"pushing every {interval}s",
                          sent=st["sent"])

    def _start_fleet_push(self) -> None:
        self._fleet_push_task = asyncio.create_task(
            self._fleet_push_guarded())

    async def _fleet_push_guarded(self) -> None:
        try:
            await self._fleet_push_loop()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # supervised like the prewarm worker: the supervisor
            # restarts the loop with backoff; budget exhaustion parks
            # it and the fleet_push check goes failed on silence age
            self.supervisor.report_death(
                "fleet_push", f"{type(e).__name__}: {e}")

    async def _fleet_push_loop(self) -> None:
        """POST heartbeats to the gateway on a cadence, with
        exponential backoff on gateway loss, completing one NTP-style
        clock sample per round trip (t0/t3 here, t1/t2 from the
        gateway's response) and echoing it in the NEXT heartbeat — the
        gateway side runs the PR-7 clocksync estimator over these to
        map this host's trace timebase onto its own."""
        import aiohttp
        s = self.settings
        gw = str(getattr(s, "fleet_gateway", "")).rstrip("/")
        interval = float(getattr(s, "fleet_push_interval_s", 2.0))
        max_backoff = max(30.0, 4 * interval)
        token = str(getattr(s, "fleet_token", ""))
        headers = {"Authorization": f"Bearer {token}"} if token else {}
        st = self._fleet_push_stats
        timeout = aiohttp.ClientTimeout(total=max(2.0, 2 * interval))
        async with aiohttp.ClientSession(timeout=timeout) as http:
            while True:
                await asyncio.sleep(st["backoff_s"] or interval)
                # fleet.heartbeat fault point (ISSUE 20): "drop" skips
                # this push entirely (control-plane partition — the
                # gateway must declare the host lost and fail seats
                # over while the data plane keeps streaming); "delay"
                # stalls the push to exercise staleness windows.
                flt = _faults.registry.pull("fleet.heartbeat")
                if flt is not None:
                    if flt.mode == "drop":
                        continue
                    await _faults.registry.sleep_async(flt.delay_s)
                try:
                    doc = self._fleet_heartbeat_doc()
                    if self._fleet_clock_sample is not None:
                        doc["clock"] = self._fleet_clock_sample
                    t0 = time.perf_counter() * 1000.0
                    async with http.post(gw + "/fleet/heartbeat",
                                         json=doc,
                                         headers=headers) as resp:
                        body = await resp.json(content_type=None)
                        t3 = time.perf_counter() * 1000.0
                        if resp.status == 200:
                            st["sent"] += 1
                            st["last_ok"] = time.monotonic()
                            st["last_error"] = ""
                            st["backoff_s"] = 0.0
                            self._fleet_push_metric("ok")
                            clk = (body or {}).get("clock") or {}
                            t1, t2 = clk.get("t1"), clk.get("t2")
                            self._fleet_clock_sample = (
                                [t0, float(t1), float(t2), t3]
                                if isinstance(t1, (int, float))
                                and isinstance(t2, (int, float))
                                else None)
                        else:
                            # the gateway answered: not gateway loss.
                            # A 4xx means OUR document (or token) is
                            # bad — retrying faster cannot help, so
                            # keep the normal cadence, count it, and
                            # let the health check surface it.
                            st["rejected"] += 1
                            st["last_error"] = \
                                f"HTTP {resp.status}: " \
                                f"{str(body)[:120]}"
                            self._fleet_push_metric("rejected")
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    st["errors"] += 1
                    st["last_error"] = f"{type(e).__name__}: {e}"[:200]
                    st["backoff_s"] = round(min(
                        max_backoff,
                        (st["backoff_s"] or interval) * 2), 2)
                    self._fleet_clock_sample = None
                    self._fleet_push_metric("error")

    def _fleet_push_metric(self, outcome: str) -> None:
        try:
            from . import metrics
            metrics.describe("selkies_fleet_push_total",
                             "Heartbeat pushes to the gateway, by "
                             "outcome (ok/rejected/error)")
            metrics.inc_counter("selkies_fleet_push_total",
                                labels={"outcome": outcome})
        except Exception:
            logger.debug("fleet push metric update failed",
                         exc_info=True)

    async def handle_drain(self, request: web.Request) -> web.Response:
        """POST {"target_url": optional} — start evacuating this host:
        readiness flips failed immediately (the gateway stops routing
        here), connected clients get the ``migrate,{json}`` control
        message (they reconnect through the gateway, landing on their
        re-placed seat with an IDR resync), and the supervisor's drain
        handle starts tracking when every supervised component has
        actually stopped (poll /api/fleet for ``drain.done``)."""
        if request["role"] != "full":
            return web.Response(status=403, text="view-only")
        try:
            body = await request.json()
        except Exception:
            body = {}
        if not isinstance(body, dict):
            return web.Response(status=400, text="JSON object body required")
        target_url = str(body.get("target_url", ""))
        # fleet.drain:hang fault point (ISSUE 20): a wedged engine —
        # the request is accepted and readiness drops, but the drain
        # never starts, clients are never told to migrate and
        # ``drain.done`` never fires. The actuator's bounded await
        # must escalate (drain_wedged) and force-tear the host down
        # only after the gateway's failover path evacuated the seats.
        flt = _faults.registry.pull("fleet.drain")
        if flt is not None and flt.mode == "hang":
            self.draining = True
            return web.json_response({"draining": True,
                                      "wedged": True,
                                      "clients_notified": 0,
                                      "drain_done": False})
        first = not self.draining
        self.draining = True
        if first:
            self.health.recorder.record(
                "host_drain_requested", target_url=target_url)
        # scoped drain (ISSUE 19): done == every SEAT-SERVING component
        # (captures, per-client relays) stopped. The control plane —
        # the service itself, the prewarm worker and above all the
        # fleet heartbeat push — must outlive the evacuation, or the
        # gateway loses sight of the drain it is watching; an unscoped
        # drain can therefore never report done on a live host.
        self._drain_handle = self.supervisor.drain(
            scope=lambda name: name.startswith(("capture:", "relay:")))
        svc = self.services.get(self.active_mode or "")
        notified = 0
        if svc is not None and hasattr(svc, "announce_migration"):
            try:
                notified = await svc.announce_migration(target_url)
            except Exception:
                logger.exception("migration announce failed")
        return web.json_response({
            "draining": True,
            "clients_notified": notified,
            "drain_done": self._drain_handle.done,
        })

    async def handle_resilience(self, request: web.Request) -> web.Response:
        """Supervisor + ladder + faults in one operator snapshot."""
        if request["role"] != "full":
            return web.Response(status=403, text="view-only")
        return web.json_response({
            "supervisor": {
                "components": self.supervisor.components(),
                "total_restarts": self.supervisor.total_restarts,
            },
            "ladder": self.ladder.snapshot() if self.ladder else None,
            "faults": {"active": _faults.registry.active(),
                       "fired": len(_faults.registry.fired_log)},
        })

    async def handle_metrics(self, request: web.Request) -> web.Response:
        from .metrics import render_prometheus
        return web.Response(text=render_prometheus(),
                            content_type="text/plain")

    async def handle_trace(self, request: web.Request) -> web.Response:
        """Current frame timelines as Chrome trace-event JSON — save the
        body and load it in Perfetto / chrome://tracing. ``otherData``
        carries the tracer state so dashboards can poll one endpoint."""
        from ..obs import monitor
        from ..trace import tracer
        from ..trace.export import to_trace_events
        snap = tracer.snapshot()
        doc = to_trace_events(snap, process_name=self.settings.app_name)
        # device-lane overlay: XLA compile events from jax.monitoring,
        # so a Perfetto view shows "recompile happened HERE" against the
        # frame timeline (same perf_counter timebase)
        doc["traceEvents"].extend(monitor.trace_events())
        # qoe-lane overlay: backpressure windows against the frame
        # timeline, so a Perfetto view shows WHEN a seat was paused
        doc["traceEvents"].extend(_qoe.registry.trace_events())
        # resilience-lane overlay: ladder transitions, so a Perfetto
        # view shows WHERE fidelity was shed against the frame timeline
        if self.ladder is not None:
            doc["traceEvents"].extend(self.ladder.trace_events())
        doc["otherData"] = tracer.stats(frames=len(snap))
        doc["otherData"]["compile"] = monitor.compile_stats()
        return web.json_response(doc)

    async def handle_trace_control(self, request: web.Request) -> web.Response:
        """POST {"action": "start"|"stop"|"clear"[, "capacity": N]}."""
        if request["role"] != "full":
            return web.Response(status=403, text="view-only")
        from ..trace import tracer
        try:
            body = await request.json()
        except Exception:
            body = {}
        if not isinstance(body, dict):
            return web.Response(status=400, text="JSON object body required")
        action = body.get("action")
        if action == "start":
            cap = body.get("capacity")
            if cap is not None:
                try:
                    cap = int(cap)
                except (TypeError, ValueError):
                    cap = 0
                if cap <= 0:
                    return web.Response(
                        status=400, text="capacity must be a positive int")
            tracer.enable(cap)
        elif action == "stop":
            tracer.disable()
        elif action == "clear":
            tracer.clear()
        else:
            return web.Response(
                status=400, text=f"unknown action {action!r} "
                "(want start|stop|clear)")
        return web.json_response(tracer.stats())

    async def handle_switch(self, request: web.Request) -> web.Response:
        if request["role"] != "full":
            return web.Response(status=403, text="view-only")
        if not self.settings.enable_dual_mode:
            return web.Response(status=403, text="dual mode disabled")
        body = await request.json()
        mode = body.get("mode")
        if mode not in self.services:
            return web.Response(status=400, text=f"unknown mode {mode!r}")
        await self.switch_to_mode(mode)
        return web.json_response({"mode": self.active_mode})

    # ---------------------------------------------------------------- tokens
    TOKEN_TTL_S = 24 * 3600
    TOKEN_CAP = 512

    def _prune_tokens(self) -> None:
        cutoff = time.time() - self.TOKEN_TTL_S
        for t in [t for t, m in self.ws_tokens.items()
                  if m["created"] < cutoff]:
            del self.ws_tokens[t]
        while len(self.ws_tokens) > self.TOKEN_CAP:  # oldest-first overflow
            self.ws_tokens.pop(next(iter(self.ws_tokens)))

    async def handle_mint_token(self, request: web.Request) -> web.Response:
        """Secure-token mode (reference /api/tokens, selkies.py:4516-4550):
        a full-authority caller mints role-carrying WS tokens; clients
        present them as ?token= on the WS endpoint. Tokens expire after
        TOKEN_TTL_S and can be revoked with DELETE."""
        if request["role"] != "full":
            return web.Response(status=403, text="view-only")
        try:
            body = await request.json()
        except Exception:
            body = {}
        role = body.get("role", "full")
        if role not in ("full", "viewonly"):
            return web.Response(status=400, text="role must be full|viewonly")
        import secrets
        self._prune_tokens()
        token = secrets.token_urlsafe(24)
        self.ws_tokens[token] = {"role": role,
                                 "created": time.time(),
                                 "uses": 0}
        return web.json_response({"token": token, "role": role,
                                  "ttl_s": self.TOKEN_TTL_S})

    async def handle_list_tokens(self, request: web.Request) -> web.Response:
        if request["role"] != "full":
            return web.Response(status=403, text="view-only")
        self._prune_tokens()
        return web.json_response({
            "tokens": [{"token": t[:6] + "…", "role": m["role"],
                        "uses": m["uses"]}
                       for t, m in self.ws_tokens.items()]})

    async def handle_revoke_token(self, request: web.Request) -> web.Response:
        if request["role"] != "full":
            return web.Response(status=403, text="view-only")
        try:
            body = await request.json()
        except Exception:
            body = {}
        revoked = self.ws_tokens.pop(body.get("token", ""), None)
        return web.json_response({"revoked": revoked is not None})

    def check_ws_token(self, token: str) -> Optional[str]:
        """-> role for a live minted token, else None (timing-safe)."""
        self._prune_tokens()
        for t, meta in self.ws_tokens.items():
            if _timing_safe_eq(t, token):
                meta["uses"] += 1
                return meta["role"]
        return None

    # ---------------------------------------------------------------- upload
    def _transfer_root(self) -> pathlib.Path:
        return pathlib.Path(
            os.path.expanduser(self.settings.file_transfer_dir)).resolve()

    def _safe_target(self, name: str) -> pathlib.Path:
        """Reject traversal; refuse symlink targets (reference O_NOFOLLOW
        defence, stream_server.py:947-1098)."""
        root = self._transfer_root()
        target = (root / name).resolve()
        if not str(target).startswith(str(root) + os.sep) and target != root:
            raise web.HTTPBadRequest(text="path escapes transfer dir")
        if target.is_symlink():
            raise web.HTTPBadRequest(text="refusing symlink target")
        return target

    def _transfer_allowed(self, request: web.Request, direction: str) -> bool:
        """Direction gating (reference stream_server.py:980,1171) plus a
        per-role layer: view-only sessions are denied unless the
        direction is explicitly opened to them."""
        allowed = {d.strip() for d in
                   str(getattr(self.settings, "file_transfers",
                               "upload,download")).split(",")}
        if direction not in allowed:
            return False
        if request.get("role") == "full":
            return True
        vo = {d.strip() for d in
              str(getattr(self.settings, "viewonly_file_transfers",
                          "")).split(",")}
        return direction in vo

    async def handle_upload(self, request: web.Request) -> web.Response:
        if not self._transfer_allowed(request, "upload"):
            return web.Response(status=403, text="upload not allowed")
        name = request.headers.get("X-Upload-Name")
        if not name:
            return web.Response(status=400, text="X-Upload-Name required")
        # the client percent-encodes (headers are Latin-1 only; filenames
        # are not); plain names pass through unquote unchanged
        name = urllib.parse.unquote(name)
        try:
            offset = int(request.headers.get("X-Upload-Offset", "0"))
            total = int(request.headers.get("X-Upload-Total", "-1"))
        except ValueError:
            return web.Response(status=400, text="bad offset/total")
        if offset < 0:
            return web.Response(status=400, text="bad offset/total")
        target = self._safe_target(name)
        part = target.with_name(target.name + ".part")
        target.parent.mkdir(parents=True, exist_ok=True)
        mode = "r+b" if part.exists() else "wb"
        max_slice = self.settings.upload_chunk_bytes
        written = 0
        # O_NOFOLLOW equivalent: refuse to write through symlinks
        if part.is_symlink():
            return web.Response(status=400, text="refusing symlink part")
        chunks: list[bytes] = []
        async for chunk in request.content.iter_chunked(1 << 20):
            written += len(chunk)
            if written > max_slice:
                return web.Response(status=413, text="slice too large")
            chunks.append(chunk)

        def _write() -> int:
            # blocking disk I/O off the event loop; a slow disk must not
            # stall frame pacing (buffer is bounded by max_slice above)
            with open(part, mode) as f:
                f.seek(offset)
                for c in chunks:
                    f.write(c)
            return part.stat().st_size

        loop = asyncio.get_running_loop()
        size = await loop.run_in_executor(None, _write)
        if total >= 0 and size >= total:
            part.replace(target)
            return web.json_response({"complete": True, "size": size})
        return web.json_response({"complete": False, "size": size})

    async def handle_file_index(self, request: web.Request) -> web.Response:
        if not self._transfer_allowed(request, "download"):
            raise web.HTTPForbidden(text="download not allowed")
        root = self._transfer_root()
        entries = []
        if root.is_dir():
            for p in sorted(root.iterdir()):
                if p.name.endswith(".part") or p.is_symlink():
                    continue
                entries.append({"name": p.name, "dir": p.is_dir(),
                                "size": p.stat().st_size if p.is_file() else 0})
        if "text/html" in request.headers.get("Accept", ""):
            rows = "".join(
                '<li><a href="/api/files/'
                f'{urllib.parse.quote(e["name"])}">'
                f'{html.escape(e["name"])}</a> ({e["size"]} B)</li>'
                for e in entries if not e["dir"])
            return web.Response(
                text=f"<html><body><h1>Downloads</h1><ul>{rows}</ul></body></html>",
                content_type="text/html")
        return web.json_response({"files": entries})

    async def handle_file_download(self, request: web.Request) -> web.StreamResponse:
        if not self._transfer_allowed(request, "download"):
            raise web.HTTPForbidden(text="download not allowed")
        target = self._safe_target(request.match_info["name"])
        if not target.is_file():
            raise web.HTTPNotFound()
        return web.FileResponse(target)

    # -------------------------------------------------------------- services
    def register_service(self, name: str, service: BaseStreamingService) -> None:
        self.services[name] = service
        service.core = self          # back-ref for token checks etc.
        service.register_routes(self.app)

    async def switch_to_mode(self, mode: str) -> None:
        """Stop the active transport, start the requested one (reference
        stream_server.py:804-895). Serialised: two overlapping switches
        used to interleave stop/start and strand a service. Service
        death is SUPERVISED — restarts with backoff, and only a
        crash-loop past the budget clears active_mode."""
        async with self._switch_lock:
            if mode == self.active_mode:
                return
            old = self.active_mode
            if old and old in self.services:
                await self.services[old].stop()
                self.supervisor.drop(f"service:{old}")
                if self._service_task:
                    # await the cancelled task: its finally-blocks must
                    # finish before the next service starts, or the two
                    # lifetimes interleave
                    self._service_task.cancel()
                    try:
                        await self._service_task
                    except asyncio.CancelledError:
                        pass
                    except Exception:
                        logger.exception("service %s teardown error", old)
                    self._service_task = None
            svc = self.services[mode]
            self.active_mode = mode

            async def _restart_service(mode=mode, svc=svc):
                # same lock as switch_to_mode: a supervised restart must
                # not interleave with an operator-driven switch
                async with self._switch_lock:
                    if self.active_mode != mode:
                        return
                    try:
                        await svc.stop()    # clear half-started state
                    except Exception:
                        logger.exception("pre-restart stop of %s failed",
                                         mode)
                    self._start_service_task(mode, svc)

            def _give_up(mode=mode):
                if self.active_mode == mode:
                    self.active_mode = None

            self.supervisor.adopt(f"service:{mode}", _restart_service,
                                  on_give_up=_give_up)
            self._start_service_task(mode, svc)

    def _start_service_task(self, mode: str, svc: BaseStreamingService
                            ) -> None:
        async def _run_service():
            try:
                await svc.start()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.exception("service %s died", mode)
                if self.active_mode == mode:
                    self.supervisor.report_death(
                        f"service:{mode}", f"{type(e).__name__}: {e}")

        self._service_task = asyncio.create_task(_run_service())

    # ------------------------------------------------------------------- tls
    def _build_ssl(self) -> Optional[ssl.SSLContext]:
        s = self.settings
        if not s.enable_https:
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(s.https_cert, s.https_key)
        return ctx

    async def _watch_and_reload_certs(self) -> None:
        """Hot-reload the cert when the file changes, never dropping the
        listener (reference stream_server.py:552-632)."""
        s = self.settings
        last = None
        while True:
            await asyncio.sleep(5.0)
            try:
                stat = os.stat(s.https_cert)
                key = (stat.st_mtime_ns, stat.st_size)
                if last is None:
                    last = key
                elif key != last:
                    last = key
                    assert self._ssl_ctx is not None
                    self._ssl_ctx.load_cert_chain(s.https_cert, s.https_key)
                    logger.info("TLS certificate reloaded")
            except FileNotFoundError:
                continue
            except ssl.SSLError:
                logger.exception("cert reload failed; keeping old cert")

    # ------------------------------------------------------------------- run
    async def run(self) -> web.AppRunner:
        # warm-cache artifact (prewarm plane): unpack BEFORE anything
        # can compile so the first session build cache-hits; a
        # fingerprint mismatch is refused (incident recorded) and the
        # server boots cold instead. Executor-side: it is tar+disk I/O.
        if getattr(self.settings, "warm_cache_artifact", ""):
            from ..prewarm import artifact as _artifact
            loop = asyncio.get_running_loop()
            self._prewarm_artifact = await loop.run_in_executor(
                None, lambda: _artifact.unpack_if_configured(
                    self.settings, recorder=self.health.recorder))
        if self.prewarm is not None:
            loop = asyncio.get_running_loop()
            # supervised: a dead worker thread restarts with backoff,
            # budget exhaustion parks it (prewarm check goes degraded)
            self.prewarm.on_death = \
                lambda exc, loop=loop: loop.call_soon_threadsafe(
                    self.supervisor.report_death, "prewarm",
                    f"{type(exc).__name__}: {exc}")
            self.supervisor.adopt("prewarm", self.prewarm.restart)
            self.prewarm.note_operating_point(
                int(self.settings.initial_width),
                int(self.settings.initial_height))
            self.prewarm.start()
        self.register_static()
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        self._ssl_ctx = self._build_ssl()
        site = web.TCPSite(self._runner, self.settings.addr,
                           self.settings.port, ssl_context=self._ssl_ctx)
        await site.start()
        if self._ssl_ctx is not None:
            self._cert_watch_task = asyncio.create_task(
                self._watch_and_reload_certs())
        if self.ladder is not None:
            self._ladder_task = asyncio.create_task(self._ladder_loop())
        if getattr(self.settings, "fleet_gateway", ""):
            self.supervisor.adopt("fleet_push", self._start_fleet_push)
            self._start_fleet_push()
        logger.info("listening on %s:%d (%s)", self.settings.addr,
                    self.settings.port,
                    "https" if self._ssl_ctx else "http")
        return self._runner

    async def _ladder_loop(self) -> None:
        """Degradation-controller driver: evaluate the health checks on
        a cadence and feed the verdict set to the ladder."""
        assert self.ladder is not None
        interval = float(getattr(self.settings, "ladder_interval_s", 2.0))
        while True:
            await asyncio.sleep(interval)
            try:
                self._feed_content_profile()
                self.ladder.observe(self.health.run())
            except Exception:
                logger.exception("degradation ladder tick failed")

    def _feed_content_profile(self) -> None:
        """Content-profile-aware rungs (ROADMAP 4): tell the ladder the
        primary session's content class so downshifts skip rungs the
        class makes pointless (engine/content.CONTENT_LADDER_SKIPS)."""
        assert self.ladder is not None
        svc = self.services.get(self.active_mode or "")
        getter = getattr(svc, "primary_content_class", None)
        if getter is None:
            # mode switched to a service without a classifier: a stale
            # profile must not keep steering the rung walk
            self.ladder.set_content_profile(None)
            return
        try:
            cls = getter()
        except Exception:
            cls = None
        if cls is None:
            self.ladder.set_content_profile(None)
            return
        from ..engine.content import CONTENT_LADDER_SKIPS
        self.ladder.set_content_profile(
            cls, CONTENT_LADDER_SKIPS.get(cls, ()))

    async def shutdown(self) -> None:
        # owner-matched: a newer in-process server may have replaced
        # these names; only OUR closures are removed
        self.health.unregister("service", self._check_service)
        self.health.unregister("stage_latency", self._check_stage_latency)
        self.health.unregister("qoe", self._check_qoe)
        self.health.unregister("slo", self._check_slo)
        self.health.unregister("supervision", self._check_supervision)
        self.health.unregister("draining", self._check_draining)
        self.health.unregister("fleet_push", self._check_fleet_push)
        if self.prewarm is not None:
            self.health.unregister("prewarm", self._check_prewarm)
            self.health.unregister("prewarm_ready",
                                   self._check_prewarm_ready)
            self.prewarm.stop(join_s=2.0)
        self.supervisor.close()
        if self._ladder_task:
            self._ladder_task.cancel()
        if self._fleet_push_task:
            self._fleet_push_task.cancel()
        if self._cert_watch_task:
            self._cert_watch_task.cancel()
        if self.active_mode and self.active_mode in self.services:
            await self.services[self.active_mode].stop()
        if self._service_task:
            self._service_task.cancel()
            try:
                await self._service_task
            except asyncio.CancelledError:
                pass
            except Exception:
                logger.exception("service task teardown error")
        if self._runner:
            await self._runner.cleanup()
