"""Vendor-spanning GPU telemetry (reference src/selkies/gpu_stats.py:57-311).

The TPU is the encode device here (server/metrics.device_stats covers it
via the JAX runtime), but hybrid hosts still carry GPUs whose load users
expect in the dashboard's stats feed. Resolution chain, like the
reference's NVML -> aitop -> nvidia-smi -> DRM sysfs:

1. **pynvml** when importable (NVIDIA, full fidelity);
2. **nvidia-smi** CSV query as the no-bindings fallback;
3. **DRM sysfs** backfill for every /sys/class/drm/card* node —
   vendor id, amdgpu VRAM gauges, gpu_busy_percent — which covers
   AMD/Intel without vendor libraries.

Each stage fills only the devices the earlier stages missed (matched by
PCI bus id when known). All probing is best-effort and cached-negative:
a host with no GPUs costs one directory scan."""

from __future__ import annotations

import dataclasses
import logging
import os
import subprocess
from typing import Optional

logger = logging.getLogger("selkies_tpu.server.gpu_stats")

_PCI_VENDORS = {0x10DE: "nvidia", 0x1002: "amd", 0x8086: "intel"}


@dataclasses.dataclass
class GPUStat:
    index: int
    name: str
    vendor: str
    load_percent: Optional[float] = None
    memory_used_mb: Optional[float] = None
    memory_total_mb: Optional[float] = None
    temperature_c: Optional[float] = None
    pci_bus: Optional[str] = None
    source: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _nvml_gpus() -> list[GPUStat]:
    try:
        import pynvml
    except ImportError:
        return []
    out: list[GPUStat] = []
    try:
        pynvml.nvmlInit()
        for i in range(pynvml.nvmlDeviceGetCount()):
            h = pynvml.nvmlDeviceGetHandleByIndex(i)
            name = pynvml.nvmlDeviceGetName(h)
            if isinstance(name, bytes):
                name = name.decode()
            mem = pynvml.nvmlDeviceGetMemoryInfo(h)
            util = pynvml.nvmlDeviceGetUtilizationRates(h)
            try:
                temp = pynvml.nvmlDeviceGetTemperature(
                    h, pynvml.NVML_TEMPERATURE_GPU)
            except Exception:
                temp = None
            try:
                bus = pynvml.nvmlDeviceGetPciInfo(h).busId
                if isinstance(bus, bytes):
                    bus = bus.decode()
            except Exception:
                bus = None
            out.append(GPUStat(
                index=i, name=name, vendor="nvidia",
                load_percent=float(util.gpu),
                memory_used_mb=mem.used / 2**20,
                memory_total_mb=mem.total / 2**20,
                temperature_c=float(temp) if temp is not None else None,
                pci_bus=bus.lower() if bus else None, source="nvml"))
        pynvml.nvmlShutdown()
    except Exception:
        logger.debug("nvml probe failed", exc_info=True)
    return out


def _nvidia_smi_gpus() -> list[GPUStat]:
    try:
        r = subprocess.run(
            ["nvidia-smi", "--query-gpu=index,name,utilization.gpu,"
             "memory.used,memory.total,temperature.gpu,pci.bus_id",
             "--format=csv,noheader,nounits"],
            capture_output=True, text=True, timeout=5)
    except (OSError, subprocess.TimeoutExpired):
        return []
    if r.returncode != 0:
        return []
    out = []
    for line in r.stdout.strip().splitlines():
        try:
            idx, name, util, used, total, temp, bus = \
                (f.strip() for f in line.split(","))
            out.append(GPUStat(
                index=int(idx), name=name, vendor="nvidia",
                load_percent=float(util), memory_used_mb=float(used),
                memory_total_mb=float(total), temperature_c=float(temp),
                pci_bus=bus.lower(), source="nvidia-smi"))
        except ValueError:
            continue
    return out


def _read(path: str) -> Optional[str]:
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return None


def _drm_sysfs_gpus(root: str = "/sys/class/drm",
                    start_index: int = 0) -> list[GPUStat]:
    """AMD/Intel (and anything else) via the DRM device nodes; the
    amdgpu gauges (mem_info_vram_*, gpu_busy_percent) are plain sysfs
    files, Intel exposes at least vendor/name."""
    out: list[GPUStat] = []
    try:
        cards = sorted(e for e in os.listdir(root)
                       if e.startswith("card") and "-" not in e)
    except OSError:
        return []
    idx = start_index
    for card in cards:
        dev = os.path.join(root, card, "device")
        vendor_raw = _read(os.path.join(dev, "vendor"))
        if vendor_raw is None:
            continue
        try:
            vid = int(vendor_raw, 16)
        except ValueError:
            continue
        vendor = _PCI_VENDORS.get(vid, f"pci:{vendor_raw}")
        busy = _read(os.path.join(dev, "gpu_busy_percent"))
        used = _read(os.path.join(dev, "mem_info_vram_used"))
        total = _read(os.path.join(dev, "mem_info_vram_total"))
        # PCI bus from the device symlink target (.../0000:c1:00.0)
        bus = None
        try:
            tgt = os.path.basename(os.path.realpath(dev))
            if ":" in tgt:
                bus = tgt.lower()
        except OSError:
            pass
        name = _read(os.path.join(dev, "product_name")) or \
            f"{vendor} {card}"
        out.append(GPUStat(
            index=idx, name=name, vendor=vendor,
            load_percent=float(busy) if busy else None,
            memory_used_mb=int(used) / 2**20 if used else None,
            memory_total_mb=int(total) / 2**20 if total else None,
            pci_bus=bus, source="drm-sysfs"))
        idx += 1
    return out


_dead_stages: dict = {}         # stage -> time it yielded nothing
_DEAD_RETRY_S = 300.0           # re-probe every 5 min: a driver/device
#                                 that comes up later (container start
#                                 races) must not be invisible forever


def _stage_dead(name: str) -> bool:
    import time
    t = _dead_stages.get(name)
    if t is None:
        return False
    if time.monotonic() - t > _DEAD_RETRY_S:
        del _dead_stages[name]
        return False
    return True


def _mark_dead(name: str) -> None:
    import time
    _dead_stages[name] = time.monotonic()


def get_gpus(drm_root: str = "/sys/class/drm") -> list[GPUStat]:
    """Full chain; later stages only add devices not already reported
    (PCI-bus match, falling back to never-duplicating nvidia entries).
    A stage that reports nothing is cached dead for _DEAD_RETRY_S — no
    per-tick subprocess forks on GPU-less hosts, but late-arriving
    drivers are still picked up."""
    gpus: list[GPUStat] = []
    if not _stage_dead("nvml"):
        gpus = _nvml_gpus()
        if not gpus:
            # only a stage that actually ran this call may refresh its
            # dead timestamp — marking on the skip path would keep the
            # timestamp forever fresh and the stage dead forever
            _mark_dead("nvml")
    if not gpus and not _stage_dead("smi"):
        gpus = _nvidia_smi_gpus()
        if not gpus:
            _mark_dead("smi")
    seen_bus = {g.pci_bus for g in gpus if g.pci_bus}
    have_nvidia = any(g.vendor == "nvidia" for g in gpus)
    for g in _drm_sysfs_gpus(drm_root, start_index=len(gpus)):
        if g.pci_bus and g.pci_bus in seen_bus:
            continue
        if g.vendor == "nvidia" and have_nvidia and not g.pci_bus:
            continue
        gpus.append(g)
    return gpus


def gpu_stats_payload(drm_root: str = "/sys/class/drm") -> list[dict]:
    return [g.to_dict() for g in get_gpus(drm_root)]
