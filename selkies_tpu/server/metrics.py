"""Observability: process-global metrics registry + Prometheus rendering.

Mirrors the reference's metric surface (webrtc_utils.py:877-1259: ``fps``,
``latency``, GPU/system gauges exposed at /api/metrics) with a tiny
dependency-free registry: gauges and counters with optional labels,
rendered in Prometheus text exposition format. A histogram covers the
fps_hist parity case.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import defaultdict
from typing import Iterable

logger = logging.getLogger("selkies_tpu.server.metrics")

_lock = threading.Lock()
_gauges: dict[tuple[str, tuple], float] = {}
_counters: dict[tuple[str, tuple], float] = defaultdict(float)
_helps: dict[str, str] = {}
_hist_buckets = (1, 5, 10, 15, 20, 30, 45, 60, 90, 120, 240)
_hists: dict[tuple[str, tuple], list] = {}
#: per-metric bucket ladders: declared via ``describe(buckets=...)``,
#: pinned per metric at first observation (a ladder change mid-series
#: would corrupt the cumulative rendering contract)
_bucket_overrides: dict[str, tuple] = {}
_hist_ladders: dict[str, tuple] = {}
#: scrape-time collectors: called (outside the lock) by
#: :func:`render_prometheus` so pull-model planes (per-session QoE)
#: export fresh gauges at scrape time without owning a write cadence
_collectors: list = []


def _key(name: str, labels: dict | None) -> tuple[str, tuple]:
    return name, tuple(sorted((labels or {}).items()))


def describe(name: str, help_text: str,
             buckets: Iterable | None = None) -> None:
    """Register help text; ``buckets`` optionally overrides the global
    histogram ladder for this metric (must be declared before the first
    ``observe_hist`` — the ladder pins then and stays pinned)."""
    _helps[name] = help_text
    if buckets is not None:
        _bucket_overrides[name] = tuple(sorted(float(b) for b in buckets))


def register_collector(fn) -> None:
    """Add a zero-arg callable run at every render (idempotent)."""
    if fn not in _collectors:
        _collectors.append(fn)


def unregister_collector(fn) -> None:
    try:
        _collectors.remove(fn)
    except ValueError:
        pass


def set_gauge(name: str, value: float, labels: dict | None = None) -> None:
    with _lock:
        _gauges[_key(name, labels)] = float(value)


def clear_metric(name: str) -> None:
    """Drop every sample of one metric (all label sets). Collectors use
    this to re-export live-membership gauges so departed sessions
    vanish instead of flat-lining at their last value."""
    with _lock:
        for store in (_gauges, _counters, _hists):
            for k in [k for k in store if k[0] == name]:
                del store[k]
        _hist_ladders.pop(name, None)


def inc_counter(name: str, value: float = 1.0, labels: dict | None = None) -> None:
    with _lock:
        _counters[_key(name, labels)] += value


def counter_value(name: str, labels: dict | None = None) -> float:
    """Current value of one counter sample (tests / diagnostics)."""
    with _lock:
        return _counters.get(_key(name, labels), 0.0)


def _ladder(name: str) -> tuple:
    lad = _hist_ladders.get(name)
    if lad is None:
        lad = _hist_ladders[name] = _bucket_overrides.get(name,
                                                         _hist_buckets)
    return lad


def observe_hist(name: str, value: float, labels: dict | None = None) -> None:
    with _lock:
        buckets = _ladder(name)
        k = _key(name, labels)
        h = _hists.setdefault(k, [0] * (len(buckets) + 1) + [0.0, 0])
        for i, b in enumerate(buckets):
            if value <= b:
                h[i] += 1
        h[len(buckets)] += 1                # +Inf
        h[-2] += value                      # sum
        h[-1] += 1                          # count


def clear() -> None:
    with _lock:
        _gauges.clear()
        _counters.clear()
        _hists.clear()
        _hist_ladders.clear()


def _escape_label_value(v) -> str:
    """Prometheus text exposition escaping: backslash, double-quote and
    newline must be escaped inside label values (spec 'Text format
    details'); anything else passes through verbatim."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_labels(labels: tuple, extra: str = "") -> str:
    parts = [f'{k}="{_escape_label_value(v)}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_prometheus() -> str:
    # collectors first, OUTSIDE the lock (they call set_gauge themselves);
    # a crashing collector must never take the scrape down with it
    for fn in list(_collectors):
        try:
            fn()
        except Exception:
            logger.debug("metrics collector %r failed", fn, exc_info=True)
    out: list[str] = []
    with _lock:
        seen: set[str] = set()

        def emit_help(name: str, mtype: str):
            if name not in seen:
                seen.add(name)
                if name in _helps:
                    out.append(f"# HELP {name} {_helps[name]}")
                out.append(f"# TYPE {name} {mtype}")

        for (name, labels), v in sorted(_gauges.items()):
            emit_help(name, "gauge")
            out.append(f"{name}{_fmt_labels(labels)} {v}")
        for (name, labels), v in sorted(_counters.items()):
            emit_help(name, "counter")
            out.append(f"{name}{_fmt_labels(labels)} {v}")
        for (name, labels), h in sorted(_hists.items()):
            emit_help(name, "histogram")
            buckets = _ladder(name)
            for i, b in enumerate(buckets):
                b_txt = int(b) if float(b).is_integer() else b
                le = f'le="{b_txt}"'
                out.append(f"{name}_bucket{_fmt_labels(labels, le)} {h[i]}")
            inf = 'le="+Inf"'
            out.append(f"{name}_bucket{_fmt_labels(labels, inf)} "
                       f"{h[len(buckets)]}")
            out.append(f"{name}_sum{_fmt_labels(labels)} {h[-2]}")
            out.append(f"{name}_count{_fmt_labels(labels)} {h[-1]}")
    return "\n".join(out) + "\n"


describe("selkies_fps", "Encoded frames per second per display")
describe("selkies_latency_ms", "Client-reported round-trip latency")
describe("selkies_clients", "Connected clients")
describe("selkies_bytes_sent_total", "Media bytes sent")
describe("selkies_frames_encoded_total", "Frames encoded")
describe("selkies_backpressure_events_total", "ACK backpressure activations")


def device_stats() -> list[dict]:
    """Accelerator telemetry — the TPU-era equivalent of the reference's
    vendor-spanning gpu_stats.py (NVML/aitop/sysfs): per-device HBM
    in-use/limit for the per-client system_stats payload.

    Delegates to the obs device monitor, which owns the sampling policy
    (memory_stats() is a runtime RPC that would CONTEND with the encode
    thread's device calls — fatal on single-client relay transports —
    so ``auto`` queries only the cpu backend unless
    SELKIES_DEVICE_MEMSTATS=1; the ``device_hbm_sampling`` setting
    forces it). BLOCKING (jax import on first call, RPC per device):
    callers on an event loop must run it in an executor (the ws stats
    loop does)."""
    try:
        from ..obs import monitor
        out = []
        for d in monitor.cached_sample():
            out.append({
                "id": d["id"],
                "platform": d["platform"],
                "kind": d["kind"],
                "mem_in_use": d["hbm_in_use"],
                "mem_limit": d["hbm_limit"],
                "mem_pct": d["hbm_pct"],
            })
            # legacy gauge names kept for existing dashboards; the
            # monitor exports the selkies_device_hbm_* family itself
            set_gauge("selkies_device_mem_bytes", d["hbm_in_use"],
                      {"device": str(d["id"]), "platform": d["platform"]})
            if d["hbm_limit"]:
                set_gauge("selkies_device_mem_limit_bytes", d["hbm_limit"],
                          {"device": str(d["id"]),
                           "platform": d["platform"]})
        return out
    except Exception:
        return []


describe("selkies_device_mem_bytes", "Accelerator memory in use")
describe("selkies_device_mem_limit_bytes", "Accelerator memory limit")
