"""Per-client video relay: bounded fan-out with skip-ahead semantics.

Carries the reference's hard-won flow-control rules (selkies.py:61-110,
529-673 — the issue-#282 class of bugs) into a fresh implementation:

- **Broadcast contract**: one encode feeds N clients; a slow client skips
  ahead, it never paces the others. ``offer()`` is synchronous — no awaits
  in the fan-out path.
- **Byte budget** per relay = ``budget_s`` seconds of the stream bitrate
  with a floor, so a stalled TCP connection cannot queue unbounded memory.
- **Drop semantics**: when over budget, drop whole queued frames oldest
  first. For H.264 delta stripes, a drop breaks the decode chain of that
  stripe row, so the relay gates further deltas of the row until an IDR
  for it passes, and asks the encoder for one (rate-limited).
- **Bounded sends**: a send that exceeds ``send_timeout`` means a dead or
  hopeless socket; a cancelled send could tear a frame mid-write, so the
  socket is never reused afterwards (reference selkies.py:79-101).
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from collections import deque
from typing import Awaitable, Callable, Optional

from ..obs import health as _health
from ..protocol import (FRAME_TYPE_IDR, OP_H264, OP_JPEG,
                        unpack_h264_header, unpack_jpeg_header)
from ..resilience import faults as _faults
from ..trace import tracer as _tracer
from . import metrics

logger = logging.getLogger("selkies_tpu.server.relay")

IDR_REQUEST_MIN_INTERVAL_S = 0.5
SEND_TIMEOUT_S = 1.0
RELAY_FLOOR_BYTES = 4 * 1024 * 1024

metrics.describe("selkies_relay_deaths_total",
                 "Relays marked dead (stalled/failed media sends)")
metrics.describe("selkies_relay_alive", "Currently-alive video relays")
metrics.describe("selkies_relay_sent_bytes_total",
                 "Media bytes sent per display across relays")
metrics.describe("selkies_relay_dropped_frames_total",
                 "Frames dropped by relay byte budgets per display")

# alive-relay accounting: counted at start(), released exactly once at
# death or close (whichever comes first)
_alive_lock = threading.Lock()
_alive_count = 0


def _alive_delta(d: int) -> None:
    global _alive_count
    with _alive_lock:
        _alive_count = max(0, _alive_count + d)
        metrics.set_gauge("selkies_relay_alive", _alive_count)


def _wire_frame_id(item: bytes) -> Optional[int]:
    """frame id from a packed media frame (trace correlation only)."""
    try:
        if item[0] == OP_H264:
            return unpack_h264_header(item)[1]
        if item[0] == OP_JPEG:
            return unpack_jpeg_header(item)[1]
    except (ValueError, IndexError):
        pass
    return None


class VideoRelay:
    """One per (client, display). Feed with ``offer()``; runs its own
    sender task against the client's ``send_bytes``."""

    def __init__(self, send_bytes: Callable[[bytes], Awaitable[None]],
                 budget_bytes: int = RELAY_FLOOR_BYTES,
                 request_idr: Optional[Callable[[], None]] = None,
                 on_dead: Optional[Callable[[], None]] = None,
                 display: Optional[str] = None,
                 send_timeout_s: float = SEND_TIMEOUT_S):
        self._send = send_bytes
        self.send_timeout_s = float(send_timeout_s)
        self.budget = max(budget_bytes, RELAY_FLOOR_BYTES)
        self._request_idr = request_idr
        self._on_dead = on_dead
        #: display this relay serves — trace correlation key for send spans
        self.display = display
        self._counted_alive = False
        self._q: deque[bytes] = deque()
        self._q_bytes = 0
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self.dead = False
        self._last_idr_req = 0.0
        # per-stripe-row H.264 chain gate: row y -> True once its IDR passed
        self._row_open: dict[int, bool] = {}
        self.sent_bytes = 0
        self.dropped_frames = 0

    def start(self) -> None:
        self._task = asyncio.create_task(self._run())
        self._counted_alive = True
        _alive_delta(+1)

    # ------------------------------------------------------------- producers
    def drained(self) -> bool:
        """True when nothing is queued — the backpressure resume signal
        (callers must not peek at queue internals)."""
        return self._q_bytes == 0

    @property
    def queue_depth(self) -> int:
        return len(self._q)

    @property
    def queued_bytes(self) -> int:
        return self._q_bytes

    def counters(self) -> dict:
        """Wire counters for the per-session QoE snapshot (the numbers
        the debug snapshot used to keep to itself)."""
        return {"sent_bytes": self.sent_bytes,
                "dropped_frames": self.dropped_frames,
                "queue_depth": len(self._q),
                "queued_bytes": self._q_bytes,
                "dead": self.dead}

    def offer(self, item: bytes) -> None:
        """Synchronous enqueue. NEVER awaits (fan-out contract)."""
        if self.dead:
            return
        if item[0] == OP_H264:
            ftype, _, y, _, _ = unpack_h264_header(item)
            if ftype == FRAME_TYPE_IDR:
                self._row_open[y] = True
            elif not self._row_open.get(y, False):
                # delta for a broken/unstarted row: useless to this client
                self._ask_idr()
                return
        self._q.append(item)
        self._q_bytes += len(item)
        # fault point relay.stripe:reorder — swap the two newest queued
        # stripes so the wire delivers them out of order (stripe
        # streaming makes per-stripe sends the common case; the decode
        # contract must survive reordering: JPEG stripes are
        # independent, H.264 rows re-sync through the chain gate + IDR).
        # Queue-depth check FIRST: a clause must not be consumed (and
        # counted as fired) on an offer that cannot inject anything.
        if len(self._q) >= 2 \
                and _faults.registry.pull("relay.stripe") is not None:
            self._q[-1], self._q[-2] = self._q[-2], self._q[-1]
            if item[0] == OP_H264:
                # a swapped delta may now precede its row's reference:
                # treat it like a break and ask for a clean restart
                self._ask_idr()
        while self._q_bytes > self.budget and len(self._q) > 1:
            victim = self._q.popleft()
            self._q_bytes -= len(victim)
            self.dropped_frames += 1
            metrics.inc_counter("selkies_relay_dropped_frames_total",
                                labels={"display": self.display or "?"})
            if victim and victim[0] == OP_H264:
                _, _, y, _, _ = unpack_h264_header(victim)
                self._row_open[y] = False   # chain broken for that row
                self._ask_idr()
        self._wake.set()

    def _ask_idr(self) -> None:
        now = time.monotonic()
        if self._request_idr and now - self._last_idr_req > IDR_REQUEST_MIN_INTERVAL_S:
            self._last_idr_req = now
            self._request_idr()

    # --------------------------------------------------------------- sender
    async def _run(self) -> None:
        try:
            while not self.dead:
                if not self._q:
                    self._wake.clear()
                    await self._wake.wait()
                    continue
                item = self._q.popleft()
                self._q_bytes -= len(item)
                traced = _tracer.enabled and self.display is not None
                try:
                    t0 = time.perf_counter_ns() if traced else 0
                    await asyncio.wait_for(self._guarded_send(item),
                                           self.send_timeout_s)
                    if traced:
                        fid = _wire_frame_id(item)
                        if fid is not None:
                            _tracer.attach_span(
                                self.display, fid, "ws.send", t0,
                                time.perf_counter_ns() - t0, lane="ws")
                    self.sent_bytes += len(item)
                    metrics.inc_counter("selkies_relay_sent_bytes_total",
                                        len(item),
                                        labels={"display":
                                                self.display or "?"})
                except (asyncio.TimeoutError, ConnectionError, OSError,
                        _faults.FaultError):
                    # cancelled mid-send = possibly torn frame; this socket
                    # must never carry media again.
                    logger.info("relay send failed/stalled; marking dead")
                    self._mark_dead()
                    return
        except asyncio.CancelledError:
            pass

    async def _guarded_send(self, item: bytes) -> None:
        """The media send plus its fault point (``relay.send``: a
        ``stall`` sleeps past the send bound so wait_for trips exactly
        like a wedged TCP socket; an ``error`` raises)."""
        await _faults.registry.perturb_async("relay.send")
        await self._send(item)

    def _mark_dead(self) -> None:
        """A send stalled/failed: this socket never carries media again.
        Surfaced at /api/metrics (ISSUE 2 satellite: relay death must be
        visible beyond the bench's fallback string). Idempotent — a
        control-path death verdict and the sender task's own failure can
        both land on the same relay."""
        if self.dead:
            return
        self.dead = True
        self._q.clear()
        self._q_bytes = 0
        metrics.inc_counter("selkies_relay_deaths_total")
        _health.engine.recorder.record(
            "relay_death", display=self.display,
            sent_bytes=self.sent_bytes, dropped_frames=self.dropped_frames)
        if self._counted_alive:
            self._counted_alive = False
            _alive_delta(-1)
        if self._on_dead:
            self._on_dead()

    def mark_dead(self) -> None:
        """External death verdict (e.g. a control send to the same socket
        timed out) — same accounting as an in-relay send failure."""
        self._mark_dead()
        self._wake.set()

    async def close(self) -> None:
        self.dead = True
        if self._counted_alive:
            self._counted_alive = False
            _alive_delta(-1)
        self._wake.set()
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
