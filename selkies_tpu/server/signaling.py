"""In-process WebRTC signaling server.

Fresh implementation of the reference's signaling layer
(signaling_server.py:25-969): browser peers and the streaming server's own
peer register over one WS endpoint with a text protocol —

    client -> ``HELLO <peer_type> <json_meta>``   server -> ``HELLO``
    client -> ``SESSION server``                  server -> ``SESSION_OK <id>``
       and the callee (server peer) receives
       ``SESSION_START <caller_id> <client_type> <display_id> <position>``
    in-session peers exchange raw JSON blobs (SDP/ICE), relayed verbatim
    to their partner; ``SESSION_END`` tears down.

Controller-slot uniqueness is newest-wins (the reference's eviction
semantics for reconnecting displays). The media path itself
(RTCPeerConnection graphs) lives in webrtc_service.py and activates when
an aiortc-compatible stack is installed; this signaling layer is complete
and transport-agnostic either way.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
from dataclasses import dataclass, field
from typing import Optional

from aiohttp import WSMsgType, web

logger = logging.getLogger("selkies_tpu.server.signaling")


@dataclass
class Peer:
    uid: str
    ws: web.WebSocketResponse
    peer_type: str = "client"            # 'client' | 'server'
    meta: dict = field(default_factory=dict)
    status: Optional[str] = None         # None | 'session'
    partner: Optional[str] = None
    #: gateway session id (ISSUE 19): carried on the signaling upgrade
    #: (?fleet_sid=) the same way the WS transport carries it, so fleet
    #: affinity covers WebRTC signaling — the gateway's /fleet/route
    #: answer and a drain's migrate command address THIS id, not the
    #: engine-local uid
    fleet_sid: str = ""


class LocalServerPeer:
    """ws-compatible shim for the in-process server peer: inbound sends
    become callbacks; outbound messages are injected straight into the
    dispatcher. ``on_text`` must be quick (enqueue, don't block) — it runs
    under the signaling send timeout."""

    def __init__(self, sig: "SignalingServer", on_text):
        self._sig = sig
        self._on_text = on_text
        self.peer: Optional[Peer] = None

    # ws interface consumed by SignalingServer
    async def send_str(self, text: str) -> None:
        await self._on_text(text)

    async def close(self, code: int = 1000, message: bytes = b"") -> None:
        return None

    # service-facing interface
    async def send(self, text: str) -> None:
        if self.peer is not None:
            await self._sig.dispatch_from(self.peer, text)

    async def detach(self) -> None:
        if self.peer is not None:
            await self._sig.detach(self.peer)
            self.peer = None


class SignalingServer:
    def __init__(self):
        self.peers: dict[str, Peer] = {}
        self._uid = itertools.count(1)
        self.lock = asyncio.Lock()
        self._bg_tasks: set[asyncio.Task] = set()

    # ------------------------------------------------------------- utilities
    def server_peer(self) -> Optional[Peer]:
        for p in self.peers.values():
            if p.peer_type == "server":
                return p
        return None

    async def _safe_send(self, peer: Peer, text: str) -> None:
        try:
            await asyncio.wait_for(peer.ws.send_str(text), 2.0)
        except (asyncio.TimeoutError, ConnectionError, RuntimeError):
            logger.info("signaling send to %s failed", peer.uid)

    # --------------------------------------------------------------- handler
    async def handler(self, request: web.Request) -> web.WebSocketResponse:
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        peer = await self._hello(ws, request)
        if peer is None:
            return ws
        # fleet affinity (ISSUE 19): the gateway's signaling proxy
        # forwards the session id it placed under, exactly as the WS
        # transport does — sanitised the same way (it goes back out on
        # the wire in migrate commands)
        fleet_sid = request.query.get("fleet_sid", "")[:128]
        peer.fleet_sid = "".join(
            c for c in fleet_sid if c.isalnum() or c in "._:-")
        try:
            async for msg in ws:
                if msg.type != WSMsgType.TEXT:
                    break
                await self._dispatch(peer, msg.data)
        finally:
            await self._disconnect(peer)
        return ws

    async def attach_server_peer(self, on_text) -> "LocalServerPeer":
        """Register the streaming server's own peer WITHOUT a WebSocket
        hop (the reference runs a real client connection to its own
        in-process signaling server — webrtc_signaling.py:114; this is
        that loop, minus the socket): same newest-wins eviction and relay
        semantics as a ``HELLO server`` peer. ``on_text`` receives every
        message the signaling plane would have sent over the wire."""
        local = LocalServerPeer(self, on_text)
        peer = await self._register(local, "server", {})
        local.peer = peer
        return local

    async def _hello(self, ws: web.WebSocketResponse,
                     request: web.Request) -> Optional[Peer]:
        msg = await ws.receive()
        if msg.type != WSMsgType.TEXT or not msg.data.startswith("HELLO"):
            await ws.close(code=1002, message=b"expected HELLO")
            return None
        toks = msg.data.split(maxsplit=2)
        peer_type = toks[1] if len(toks) > 1 else "client"
        meta = {}
        if len(toks) > 2:
            try:
                meta = json.loads(toks[2])
            except json.JSONDecodeError:
                meta = {}
        return await self._register(ws, peer_type, meta)

    async def _register(self, ws, peer_type: str, meta: dict) -> Peer:
        evicted = None
        async with self.lock:
            # newest-wins eviction for a reconnecting server peer; the
            # close happens OUTSIDE the lock (it can take aiohttp's whole
            # close-handshake timeout and must not stall other HELLOs)
            if peer_type == "server":
                evicted = self.server_peer()
                if evicted is not None:
                    self.peers.pop(evicted.uid, None)
            uid = str(next(self._uid))
            peer = Peer(uid=uid, ws=ws, peer_type=peer_type, meta=meta)
            self.peers[uid] = peer
        if evicted is not None:
            await self._orphan_sessions_of(evicted)

            async def _close_old(p=evicted):
                try:
                    await p.ws.close(code=4001, message=b"superseded")
                except (OSError, RuntimeError, ConnectionError,
                        asyncio.TimeoutError):
                    logger.debug("superseded peer %s close failed",
                                 p.uid, exc_info=True)
            task = asyncio.get_running_loop().create_task(_close_old())
            self._bg_tasks.add(task)        # strong ref: loop weak-refs tasks
            task.add_done_callback(self._bg_tasks.discard)
        await self._safe_send(peer, "HELLO")
        logger.info("signaling peer %s registered (%s)", uid, peer_type)
        return peer

    async def dispatch_from(self, peer: Peer, text: str) -> None:
        """Inject a message as if ``peer`` sent it over its socket (the
        local server peer's send path)."""
        await self._dispatch(peer, text)

    async def detach(self, peer: Peer) -> None:
        await self._disconnect(peer)

    async def _dispatch(self, peer: Peer, text: str) -> None:
        if text.startswith("SESSION_END"):
            parts = text.split(maxsplit=1)
            if peer.peer_type == "server":
                # the server holds many sessions: it must name the caller
                # ("SESSION_END <uid>"); its own status only clears when no
                # session remains
                target = self.peers.get(parts[1]) if len(parts) > 1 else None
                if target is not None and target.partner == peer.uid:
                    target.status = None
                    target.partner = None
                    await self._safe_send(target, f"SESSION_END {peer.uid}")
                self._refresh_server_status(peer)
            else:
                await self._end_session(peer, notify_partner=True)
            return
        if text.startswith("SESSION"):
            parts = text.split(maxsplit=1)
            callee = None
            if len(parts) > 1 and parts[1] != "server":
                callee = self.peers.get(parts[1])
            if callee is None:
                callee = self.server_peer()
            if callee is None or callee.uid == peer.uid:
                await self._safe_send(peer, "ERROR peer server not found")
                return
            await self._safe_send(peer, f"SESSION_OK {callee.uid}")
            meta = peer.meta
            start = "SESSION_START {} {} {} {}".format(
                peer.uid, meta.get("client_type", "controller"),
                meta.get("display_id", "primary"),
                meta.get("display_position", "right"))
            await self._safe_send(callee, start)
            peer.status = callee.status = "session"
            peer.partner = callee.uid
            # the server peer holds many concurrent sessions (addressed via
            # the MSG <uid> envelope); a CLIENT callee is 1:1 and needs the
            # back-pointer or it could never relay its answer/ICE
            if callee.peer_type != "server":
                callee.partner = peer.uid
            return
        if peer.status == "session":
            # JSON SDP/ICE blobs relay verbatim to the partner; the server
            # peer addresses a specific caller with "MSG <uid> <json>"
            if peer.peer_type == "server" and text.startswith("MSG "):
                parts = text.split(maxsplit=2)
                if len(parts) < 3:   # malformed: never tear down signaling
                    await self._safe_send(peer, "ERROR malformed MSG")
                    return
                target = self.peers.get(parts[1])
                if target:
                    await self._safe_send(target, parts[2])
                return
            target = self.peers.get(peer.partner or "")
            if target is None:
                await self._safe_send(peer, "ERROR no session partner")
                return
            if target.peer_type == "server":
                await self._safe_send(target, f"MSG {peer.uid} {text}")
            else:
                await self._safe_send(target, text)
            return
        await self._safe_send(peer, "ERROR invalid state for message")

    def _refresh_server_status(self, server: Peer) -> None:
        """The server peer stays 'session' while ANY caller still points at
        it — ending one session must not break relay for the others."""
        live = any(p.partner == server.uid for p in self.peers.values())
        server.status = "session" if live else None

    async def _end_session(self, peer: Peer, notify_partner: bool) -> None:
        partner = self.peers.get(peer.partner or "")
        peer.status = None
        peer.partner = None
        if partner is not None and notify_partner:
            await self._safe_send(partner, f"SESSION_END {peer.uid}")
            if partner.peer_type != "server":
                partner.status = None
                partner.partner = None
            else:
                self._refresh_server_status(partner)

    async def _orphan_sessions_of(self, gone: Peer) -> None:
        """Notify and release every peer whose session pointed at ``gone``
        (the server peer disconnected or was superseded)."""
        for p in list(self.peers.values()):
            if p.partner == gone.uid:
                p.status = None
                p.partner = None
                await self._safe_send(p, f"SESSION_END {gone.uid}")

    async def _disconnect(self, peer: Peer) -> None:
        self.peers.pop(peer.uid, None)
        if peer.peer_type == "server":
            await self._orphan_sessions_of(peer)
        elif peer.status == "session":
            await self._end_session(peer, notify_partner=True)
        logger.info("signaling peer %s left", peer.uid)
