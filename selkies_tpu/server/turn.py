"""RTC/TURN configuration resolution.

Fresh implementation of the reference's ICE-server resolution chain
(webrtc_utils.py:816-875: trusted JSON file -> TURN REST API -> legacy
user/pass -> HMAC shared-secret -> default STUN), producing the JSON the
web client feeds to RTCPeerConnection. Every resolver is pure/testable;
network resolvers are best-effort with bounded timeouts.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import logging
import os
import stat
import time
from typing import Optional

logger = logging.getLogger("selkies_tpu.server.turn")

DEFAULT_STUN = {"urls": ["stun:stun.l.google.com:19302"]}


def hmac_turn_credential(shared_secret: str, user: str = "selkies",
                         ttl_s: int = 86400,
                         now: Optional[float] = None) -> tuple[str, str]:
    """RFC 'TURN REST API' ephemeral credentials: username is
    ``expiry:user``, password is base64(HMAC-SHA1(secret, username))
    (reference webrtc_utils.py:113-158, coturn --use-auth-secret)."""
    expiry = int((now if now is not None else time.time()) + ttl_s)
    username = f"{expiry}:{user}"
    digest = hmac.new(shared_secret.encode(), username.encode(),
                      hashlib.sha1).digest()
    return username, base64.b64encode(digest).decode()


def _turn_urls(host: str, port: int, tls: bool = False) -> list[str]:
    scheme = "turns" if tls else "turn"
    return [f"{scheme}:{host}:{port}?transport=udp",
            f"{scheme}:{host}:{port}?transport=tcp"]


def load_rtc_config_file(path: str) -> Optional[dict]:
    """Trusted JSON ICE-server file; refuse group/world-writable files
    (reference RTCConfigFileMonitor's ownership checks,
    webrtc_utils.py:354-460)."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    if st.st_mode & (stat.S_IWGRP | stat.S_IWOTH):
        logger.warning("rtc config file %s is group/world-writable; "
                       "refusing", path)
        return None
    try:
        with open(path) as f:
            cfg = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        logger.warning("rtc config file unreadable: %s", e)
        return None
    if not isinstance(cfg, dict) or "iceServers" not in cfg:
        return None
    return cfg


async def fetch_rest_api(uri: str, user: str = "selkies",
                         timeout_s: float = 5.0) -> Optional[dict]:
    """TURN REST service (reference addons/turn-rest protocol)."""
    try:
        import aiohttp
        async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=timeout_s)) as s:
            async with s.get(uri, params={"service": "turn",
                                          "username": user}) as r:
                if r.status != 200:
                    return None
                return await r.json()
    except Exception as e:
        logger.info("turn REST fetch failed: %s", e)
        return None


async def get_rtc_configuration(settings) -> dict:
    """Resolution chain -> {"lifetimeDuration", "iceServers": [...]}."""
    ice: list[dict] = []
    lifetime = 86400

    cfg_file = getattr(settings, "rtc_config_file", "")
    if cfg_file:
        cfg = load_rtc_config_file(cfg_file)
        if cfg:
            return cfg

    rest = getattr(settings, "turn_rest_uri", "")
    if rest:
        cfg = await fetch_rest_api(rest)
        if cfg and cfg.get("iceServers"):
            return cfg

    host = getattr(settings, "turn_host", "")
    port = int(getattr(settings, "turn_port", 3478) or 3478)
    secret = getattr(settings, "turn_shared_secret", "")
    user = getattr(settings, "turn_username", "") or "selkies"
    password = getattr(settings, "turn_password", "")
    if host and secret:
        u, p = hmac_turn_credential(secret, user)
        ice.append({"urls": _turn_urls(host, port),
                    "username": u, "credential": p})
    elif host and password:
        ice.append({"urls": _turn_urls(host, port),
                    "username": user, "credential": password})

    ice.append(DEFAULT_STUN)
    return {"lifetimeDuration": f"{lifetime}s", "iceServers": ice}
