"""RTC/TURN configuration resolution.

Fresh implementation of the reference's ICE-server resolution chain
(webrtc_utils.py:816-875: trusted JSON file -> TURN REST API -> legacy
user/pass -> HMAC shared-secret -> default STUN), producing the JSON the
web client feeds to RTCPeerConnection. Every resolver is pure/testable;
network resolvers are best-effort with bounded timeouts.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import logging
import os
import stat
import time
from typing import Optional

logger = logging.getLogger("selkies_tpu.server.turn")

DEFAULT_STUN = {"urls": ["stun:stun.l.google.com:19302"]}


def hmac_turn_credential(shared_secret: str, user: str = "selkies",
                         ttl_s: int = 86400,
                         now: Optional[float] = None) -> tuple[str, str]:
    """RFC 'TURN REST API' ephemeral credentials: username is
    ``expiry:user``, password is base64(HMAC-SHA1(secret, username))
    (reference webrtc_utils.py:113-158, coturn --use-auth-secret)."""
    expiry = int((now if now is not None else time.time()) + ttl_s)
    username = f"{expiry}:{user}"
    digest = hmac.new(shared_secret.encode(), username.encode(),
                      hashlib.sha1).digest()
    return username, base64.b64encode(digest).decode()


def _turn_urls(host: str, port: int, tls: bool = False) -> list[str]:
    scheme = "turns" if tls else "turn"
    return [f"{scheme}:{host}:{port}?transport=udp",
            f"{scheme}:{host}:{port}?transport=tcp"]


def load_rtc_config_file(path: str) -> Optional[dict]:
    """Trusted JSON ICE-server file; refuse group/world-writable files
    (reference RTCConfigFileMonitor's ownership checks,
    webrtc_utils.py:354-460)."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    if st.st_mode & (stat.S_IWGRP | stat.S_IWOTH):
        logger.warning("rtc config file %s is group/world-writable; "
                       "refusing", path)
        return None
    try:
        with open(path) as f:
            cfg = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        logger.warning("rtc config file unreadable: %s", e)
        return None
    if not isinstance(cfg, dict) or "iceServers" not in cfg:
        return None
    return cfg


async def fetch_rest_api(uri: str, user: str = "selkies",
                         timeout_s: float = 5.0) -> Optional[dict]:
    """TURN REST service (reference addons/turn-rest protocol)."""
    try:
        import aiohttp
        async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=timeout_s)) as s:
            async with s.get(uri, params={"service": "turn",
                                          "username": user}) as r:
                if r.status != 200:
                    return None
                return await r.json()
    except Exception as e:
        logger.info("turn REST fetch failed: %s", e)
        return None


CLOUDFLARE_TURN_API = ("https://rtc.live.cloudflare.com/v1/turn/keys/"
                       "{key_id}/credentials/generate")


async def fetch_cloudflare(key_id: str, api_token: str,
                           ttl_s: int = 86400,
                           timeout_s: float = 5.0,
                           api_url: str = "") -> Optional[dict]:
    """Cloudflare Calls TURN credentials (reference
    webrtc_utils.py:298-352 fetch_cloudflare_turn_config): POST the key
    API with a bearer token; the response's iceServers entry carries
    ephemeral username/credential for turn.cloudflare.com. ``api_url``
    overrides the endpoint for tests."""
    url = api_url or CLOUDFLARE_TURN_API.format(key_id=key_id)
    try:
        import aiohttp
        async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=timeout_s)) as s:
            async with s.post(
                    url,
                    headers={"Authorization": f"Bearer {api_token}"},
                    json={"ttl": ttl_s}) as r:
                if r.status != 200 and r.status != 201:
                    logger.info("cloudflare turn API: HTTP %d", r.status)
                    return None
                body = await r.json()
    except Exception as e:
        logger.info("cloudflare turn fetch failed: %s", e)
        return None
    servers = body.get("iceServers")
    if isinstance(servers, dict):       # API returns a single object
        servers = [servers]
    if not servers:
        return None
    return {"lifetimeDuration": f"{ttl_s}s", "iceServers": servers}


class RtcConfigMonitor:
    """Watch the trusted RTC config file and push changes to interested
    parties (reference RTCConfigFileMonitor, webrtc_utils.py:354-460,
    rebuilt on an mtime poll — the watchdog package isn't in this image
    and a 1 s poll on one file is free). ``on_change(cfg_dict)`` fires
    from the event loop whenever the file appears or its content
    changes AND passes ``load_rtc_config_file``'s permission checks."""

    def __init__(self, path: str, on_change, poll_s: float = 1.0):
        self.path = path
        self.on_change = on_change
        self.poll_s = poll_s
        self._sig: Optional[tuple] = None
        self._task = None

    def start(self) -> None:
        import asyncio
        if self.path and self._task is None:
            self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def _signature(self) -> Optional[tuple]:
        try:
            st = os.stat(self.path)
            return (st.st_mtime_ns, st.st_size)
        except OSError:
            return None

    async def _run(self) -> None:
        import asyncio
        self._sig = self._signature()
        # fire once at startup when the file is already present
        if self._sig is not None:
            cfg = load_rtc_config_file(self.path)
            if cfg:
                self._emit(cfg)
        while True:
            await asyncio.sleep(self.poll_s)
            sig = self._signature()
            if sig == self._sig:
                continue
            self._sig = sig
            if sig is None:
                continue                   # file removed: keep last cfg
            cfg = load_rtc_config_file(self.path)
            if cfg:
                self._emit(cfg)

    def _emit(self, cfg: dict) -> None:
        try:
            self.on_change(cfg)
        except Exception:
            logger.exception("rtc config on_change callback failed")


async def get_rtc_configuration(settings) -> dict:
    """Resolution chain -> {"lifetimeDuration", "iceServers": [...]}."""
    ice: list[dict] = []
    lifetime = 86400

    cfg_file = getattr(settings, "rtc_config_file", "")
    if cfg_file:
        cfg = load_rtc_config_file(cfg_file)
        if cfg:
            return cfg

    rest = getattr(settings, "turn_rest_uri", "")
    if rest:
        cfg = await fetch_rest_api(rest)
        if cfg and cfg.get("iceServers"):
            return cfg

    cf_key = getattr(settings, "cloudflare_turn_key_id", "")
    cf_token = getattr(settings, "cloudflare_turn_api_token", "")
    if cf_key and cf_token:
        cfg = await fetch_cloudflare(cf_key, cf_token)
        if cfg and cfg.get("iceServers"):
            return cfg

    host = getattr(settings, "turn_host", "")
    port = int(getattr(settings, "turn_port", 3478) or 3478)
    secret = getattr(settings, "turn_shared_secret", "")
    user = getattr(settings, "turn_username", "") or "selkies"
    password = getattr(settings, "turn_password", "")
    if host and secret:
        u, p = hmac_turn_credential(secret, user)
        ice.append({"urls": _turn_urls(host, port),
                    "username": u, "credential": p})
    elif host and password:
        ice.append({"urls": _turn_urls(host, port),
                    "username": user, "credential": password})

    ice.append(DEFAULT_STUN)
    return {"lifetimeDuration": f"{lifetime}s", "iceServers": ice}
