"""WebRTC transport service (opt-in, reference webrtc_mode.py:142-2029).

The signaling plane (/api/signaling, SignalingServer) and the RTC
configuration plane (/api/turn, the TURN resolution chain) are complete
and always available — they are plain asyncio/aiohttp code. The MEDIA
plane (RTCPeerConnection graphs feeding pre-encoded TPU H.264 into RTP,
the reference's aiortc-fork role) requires an aiortc-compatible stack at
runtime: when ``aiortc`` is importable the service builds per-peer
pipelines; otherwise it serves signaling and reports the degraded state
on /api/status-style queries, matching the reference's own
degrade-when-wheel-missing posture (selkies.py:148-189).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

from aiohttp import web

from ..settings import AppSettings
from .core import BaseStreamingService
from .signaling import SignalingServer
from .turn import get_rtc_configuration

logger = logging.getLogger("selkies_tpu.server.webrtc")

try:
    import aiortc  # noqa: F401
    HAVE_AIORTC = True
except ImportError:
    HAVE_AIORTC = False


class WebRTCService(BaseStreamingService):
    name = "webrtc"

    def __init__(self, settings: AppSettings, input_handler=None,
                 capture_factory=None, audio_pipeline=None):
        self.settings = settings
        self.signaling = SignalingServer()
        self.input_handler = input_handler
        self._capture_factory = capture_factory
        self.audio = audio_pipeline
        self._running = False
        self._server_peer_task: Optional[asyncio.Task] = None

    # ---------------------------------------------------------------- routes
    def register_routes(self, app: web.Application) -> None:
        app.router.add_get("/api/signaling", self.signaling.handler)
        app.router.add_get("/api/turn", self.handle_turn)

    async def handle_turn(self, request: web.Request) -> web.Response:
        cfg = await get_rtc_configuration(self.settings)
        return web.json_response(cfg)

    # ------------------------------------------------------------- lifecycle
    async def start(self) -> None:
        self._running = True
        if not HAVE_AIORTC:
            logger.warning(
                "webrtc mode: aiortc not installed — signaling + TURN are "
                "serving, media sessions will not be established "
                "(install aiortc for the full transport)")
            return
        if self.input_handler is not None:
            self.input_handler.start()
        # Media path: the server registers its own peer against the
        # in-process signaling server and answers SESSION_STARTs with
        # RTCPeerConnection graphs fed by the TPU encoder's pre-encoded
        # H.264 access units. Activated only with aiortc present.
        logger.info("webrtc media plane starting (aiortc present)")

    async def stop(self) -> None:
        self._running = False
        if self._server_peer_task:
            self._server_peer_task.cancel()
        for peer in list(self.signaling.peers.values()):
            try:
                await peer.ws.close()
            except Exception:
                pass
        if self.input_handler is not None:
            await self.input_handler.stop()

    @property
    def media_available(self) -> bool:
        return HAVE_AIORTC
