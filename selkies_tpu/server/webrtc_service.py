"""WebRTC transport service (opt-in, reference webrtc_mode.py:142-2029).

All three planes are in-house and real:

- signaling (/api/signaling, SignalingServer) and the TURN resolution
  chain (/api/turn) — plain asyncio/aiohttp;
- the MEDIA plane — ``selkies_tpu.webrtc``: ICE-lite + DTLS (system
  OpenSSL) + SRTP + RFC 6184 packetization of the TPU encoder's
  PRE-ENCODED H.264 access units, the role the reference fork's
  ``Encoder.pack()`` seam plays (rtcrtpsender.py:364-393). No aiortc.

Per browser session the service answers SESSION_START with an SDP offer
(one bundled sendonly video track on an ICE-lite host candidate), and on
DTLS completion streams the single-stream capture. PLI/FIR from the
browser triggers an IDR request into the engine, mirroring the
reference's on_pli path (rtc.py:1138-1170).
"""

from __future__ import annotations

import asyncio
import json
import logging
import socket
import threading
from typing import Optional

from aiohttp import web

from ..obs import qoe as _qoe
from ..settings import AppSettings
from .core import BaseStreamingService
from .signaling import SignalingServer
from .turn import get_rtc_configuration

logger = logging.getLogger("selkies_tpu.server.webrtc")

try:
    from ..webrtc import RTCPeer
    HAVE_MEDIA = True
except Exception as _e:                      # e.g. no usable OpenSSL
    RTCPeer = None
    HAVE_MEDIA = False
    _MEDIA_ERR = str(_e)


def _default_media_ip() -> str:
    """The host's outbound-route IP (no traffic is sent); 127.0.0.1 when
    isolated."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


class _Session:
    def __init__(self, caller_uid: str, peer, display_id: str):
        self.caller_uid = caller_uid
        self.peer = peer
        self.display_id = display_id
        #: per-session QoE stats (obs.qoe), set at session start
        self.qoe = None
        #: per-session Opus decoder for the browser-mic stream — Opus
        #: decode is STATEFUL (prediction/PLC carry across frames), so
        #: two peers' interleaved packets through one decoder would
        #: garble both streams
        self.mic_decoder = None


class WebRTCService(BaseStreamingService):
    name = "webrtc"

    def __init__(self, settings: AppSettings, input_handler=None,
                 capture_factory=None, audio_pipeline=None):
        self.settings = settings
        self.signaling = SignalingServer()
        self.input_handler = input_handler
        self._capture_factory = capture_factory
        self.audio = audio_pipeline
        self._running = False
        self._local_peer = None
        self._sessions: dict[str, _Session] = {}
        self._sig_queue: asyncio.Queue[str] = asyncio.Queue()
        self._sig_task: Optional[asyncio.Task] = None
        #: per-display media graphs (reference webrtc_mode.py:1193-1406):
        #: one capture per display_id, sessions subscribe by display
        self._captures: dict[str, object] = {}
        self._cap_stoppers: list[threading.Thread] = []
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    # ---------------------------------------------------------------- routes
    def register_routes(self, app: web.Application) -> None:
        app.router.add_get("/api/signaling", self.signaling.handler)
        app.router.add_get("/api/turn", self.handle_turn)

    async def handle_turn(self, request: web.Request) -> web.Response:
        cfg = await get_rtc_configuration(self.settings)
        return web.json_response(cfg)

    # ------------------------------------------------------------- lifecycle
    async def start(self) -> None:
        self._running = True
        self._loop = asyncio.get_running_loop()
        if self.input_handler is not None:
            self.input_handler.start()
        if not HAVE_MEDIA:
            logger.warning(
                "webrtc mode: media stack unavailable (%s) — signaling + "
                "TURN serve, sessions will not get media", _MEDIA_ERR)
            return
        if self.audio is not None \
                and (getattr(self.settings, "enable_audio", False)
                     or getattr(self.settings, "enable_microphone", False)):
            try:
                # mic-only: provision mic playback without the encode
                # loop; the offer then carries a recvonly audio m-line
                await self.audio.start(mic_only=not getattr(
                    self.settings, "enable_audio", False))
            except Exception:
                logger.exception("webrtc audio pipeline failed to start")
                self.audio = None
        if getattr(self.settings, "enable_microphone", False) \
                and self.audio is None:
            # operator-facing: the setting promises a mic but no
            # pipeline exists to play it back (ADVICE r5 silent mode)
            logger.warning(
                "enable_microphone=True but no audio pipeline is "
                "available (libopus/PulseAudio missing?) — client mic "
                "input will be discarded")
        self._local_peer = await self.signaling.attach_server_peer(
            self._sig_queue.put)
        self._sig_task = self._loop.create_task(self._signal_loop())
        logger.info("webrtc media plane up (in-house ICE-lite/DTLS/SRTP)")

    async def stop(self) -> None:
        self._running = False
        if self._sig_task:
            self._sig_task.cancel()
            self._sig_task = None
        for s in list(self._sessions.values()):
            s.peer.close()
            _qoe.registry.unregister(s.qoe)
        self._sessions.clear()
        self._stop_captures()
        # stop() IS the cross-service boundary (/api/switch): the next
        # service may start its own capture the moment we return, so wait
        # for the encode threads here — off-loop, bounded
        stoppers = [t for t in self._cap_stoppers if t.is_alive()]
        if stoppers:
            await asyncio.get_running_loop().run_in_executor(
                None, lambda: [t.join(30) for t in stoppers])
        self._cap_stoppers.clear()
        if self._local_peer is not None:
            await self._local_peer.detach()
            self._local_peer = None
        if self.audio is not None:
            try:
                self.audio.on_raw_frame = None
                await self.audio.stop()
            except (OSError, RuntimeError, asyncio.TimeoutError):
                # audio teardown failing must not block the service
                # switch, but it must be visible (graftlint
                # ASYNC-SWALLOWED-EXC: narrowed from a silent
                # except-Exception)
                logger.debug("audio pipeline stop failed", exc_info=True)
        for peer in list(self.signaling.peers.values()):
            try:
                await peer.ws.close()
            except (OSError, RuntimeError, ConnectionError,
                    asyncio.TimeoutError):
                logger.debug("signaling peer close failed (%s)",
                             peer.uid, exc_info=True)
        if self.input_handler is not None:
            await self.input_handler.stop()

    @property
    def media_available(self) -> bool:
        return HAVE_MEDIA

    # ------------------------------------------------------------- signaling
    async def _signal_loop(self) -> None:
        while self._running:
            text = await self._sig_queue.get()
            try:
                await self._handle_signal(text)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("webrtc signal handling failed: %.80s",
                                 text)

    async def _handle_signal(self, text: str) -> None:
        if text.startswith("SESSION_START"):
            parts = text.split()
            caller = parts[1] if len(parts) > 1 else ""
            display = parts[3] if len(parts) > 3 else "primary"
            await self._start_session(caller, display)
        elif text.startswith("SESSION_END"):
            parts = text.split()
            if len(parts) > 1:
                self._end_session(parts[1])
        elif text.startswith("MSG "):
            parts = text.split(maxsplit=2)
            if len(parts) == 3:
                await self._handle_peer_json(parts[1], parts[2])

    async def _start_session(self, caller_uid: str, display_id: str) -> None:
        old = self._sessions.pop(caller_uid, None)
        if old is not None:
            old.peer.close()
        host = getattr(self.settings, "webrtc_media_ip", "") \
            or _default_media_ip()
        # fullcolor follows the user setting: the capture encodes Hi444PP
        # (ops/h264_planes444, oracle chain tests/test_h264_444.py) and
        # the offer advertises f4001f so the browser picks the matching
        # decoder profile (reference rtc.py:649-717 profile munge)
        fullcolor = bool(getattr(self.settings, "fullcolor", False))
        with_audio = self.audio is not None \
            and bool(getattr(self.settings, "enable_audio", False))
        with_mic = self.audio is not None \
            and bool(getattr(self.settings, "enable_microphone", False))
        peer = RTCPeer(host=host,
                       on_request_keyframe=(
                           lambda d=display_id: self._request_idr(d)),
                       with_audio=with_audio, fullcolor=fullcolor,
                       on_datachannel_message=(
                           lambda label, text, d=display_id:
                           self._on_input_verb(label, text, d)),
                       on_bitrate_estimate=(
                           lambda bps, d=display_id:
                           self._on_remb(bps, d)),
                       turn_config=self._turn_config(),
                       with_mic=with_mic,
                       on_audio_packet=(
                           (lambda pl, seq, ts, uid=caller_uid:
                            self._on_mic_packet(uid, pl))
                           if with_mic else None),
                       audio_params=(getattr(self.audio,
                                             "multistream_params", None)
                                     if with_audio else None))
        if with_audio and self.audio.on_raw_frame is None:
            self.audio.on_raw_frame = self._on_audio_frame
        await peer.listen()
        sess = _Session(caller_uid, peer, display_id)
        # wire QoE: the peer's stats() snapshots the congestion
        # controller + packetizer counters (GET /api/sessions)
        sess.qoe = _qoe.registry.register("webrtc", display_id, caller_uid)
        sess.qoe.cc_provider = peer.stats
        sess.qoe.target_fps = lambda: float(self.settings.framerate)
        self._sessions[caller_uid] = sess
        await self._ensure_capture(display_id)
        offer = peer.create_offer()
        await self._local_peer.send("MSG {} {}".format(
            caller_uid,
            json.dumps({"sdp": {"type": "offer", "sdp": offer}})))
        logger.info("webrtc session %s: offer sent (media %s:%d)",
                    caller_uid, host, peer.port)

    async def _handle_peer_json(self, caller_uid: str, payload: str) -> None:
        sess = self._sessions.get(caller_uid)
        if sess is None:
            return
        try:
            msg = json.loads(payload)
        except json.JSONDecodeError:
            return
        sdp = msg.get("sdp")
        if isinstance(sdp, dict) and sdp.get("type") == "answer":
            sess.peer.set_remote_answer(sdp.get("sdp", ""))
            logger.info("webrtc session %s: answer applied", caller_uid)
        # trickled ICE candidates: the direct path needs no action
        # (ICE-lite answers checks on the host candidate), but the TURN
        # relay only forwards peers we hold permissions for
        ice = msg.get("ice")
        if isinstance(ice, dict):
            sess.peer.add_remote_candidate(str(ice.get("candidate", "")))

    def _end_session(self, caller_uid: str) -> None:
        sess = self._sessions.pop(caller_uid, None)
        if sess is not None:
            sess.peer.close()
            _qoe.registry.unregister(sess.qoe)
            logger.info("webrtc session %s closed", caller_uid)
        # reap captures with no remaining viewers, display by display
        viewed = {s.display_id for s in self._sessions.values()}
        for did in [d for d in self._captures if d not in viewed]:
            self._stop_capture(did)

    def _turn_config(self) -> dict | None:
        """Server-side TURN relay credentials from settings: static
        user/pass or the coturn shared-secret (REST API) scheme
        (server/turn.py, reference webrtc_utils.py:113-158). None when
        no TURN host is configured — direct host candidate only."""
        s = self.settings
        host = str(getattr(s, "turn_host", "") or "")
        if not host:
            return None
        port = int(getattr(s, "turn_port", 3478) or 3478)
        secret = str(getattr(s, "turn_shared_secret", "") or "")
        user = str(getattr(s, "turn_username", "") or "selkies")
        password = str(getattr(s, "turn_password", "") or "")
        if secret:
            from .turn import hmac_turn_credential
            user, password = hmac_turn_credential(secret, user)
        elif not password:
            return None
        return {"host": host, "port": port,
                "username": user, "password": password}

    # ----------------------------------------------------------------- media
    def _display_rect(self, display_id: str) -> tuple[int, int]:
        """Capture-origin offsets inside the X framebuffer, honouring
        ``display2_position`` with the same dual-layout math the WS
        service uses (ws_service.py _apply_display_layout) — a
        left/above secondary also MOVES the primary's origin, so both
        sides come from compute_dual_layout (ADVICE r5: secondaries were
        pinned to (initial_width, 0) regardless of the setting)."""
        from ..display import compute_dual_layout
        s = self.settings
        w = int(getattr(s, "initial_width", 1920) or 1920)
        h = int(getattr(s, "initial_height", 1080) or 1080)
        # both displays share the service's single geometry setting
        _, _, o1, o2 = compute_dual_layout(
            w, h, w, h, str(getattr(s, "display2_position", "right")))
        primary = ("primary", s.display_id, "")
        return o1 if display_id in primary else o2

    async def _ensure_capture(self, display_id: str = "primary") -> None:
        if display_id in self._captures:
            return
        # previous captures may still be tearing down off-loop: wait for
        # them before starting another (teardown joins the encode
        # thread). LIVE concurrent captures are fine — each frame's
        # dispatch+readback is serialized by the engine's global
        # _ENCODE_TURN lock (engine/capture.py:42), the same discipline
        # the WS multi-display path relies on.
        stoppers = [t for t in self._cap_stoppers if t.is_alive()]
        if stoppers:
            await self._loop.run_in_executor(
                None, lambda: [t.join() for t in stoppers])
        cap = None
        try:
            if self._capture_factory is not None:
                cap = self._capture_factory()
            else:
                from ..engine.capture import ScreenCapture
                cap = ScreenCapture(
                    "wayland" if getattr(self.settings, "wayland", False)
                    else "auto")
            from ..engine.types import CaptureSettings
            s = self.settings
            cs = CaptureSettings(
                capture_width=int(getattr(s, "initial_width", 1920)
                                  or 1920),
                capture_height=int(getattr(s, "initial_height", 1080)
                                   or 1080),
                target_fps=float(s.framerate),
                output_mode="h264",
                single_stream=True,    # one RTP track = one H.264 stream
                video_crf=s.video_crf,
                video_bitrate_kbps=s.video_bitrate_kbps,
                keyframe_interval_s=s.keyframe_interval_s,
                use_damage_gating=True,
                use_cbr=True,      # webrtc is CBR-steered (the reference
                #                    congestion loop is CBR-only,
                #                    webrtc_mode.py:1652) — REMB needs it
                use_paint_over=s.use_paint_over,
                h264_motion_vrange=s.h264_motion_vrange,
                h264_motion_hrange=s.h264_motion_hrange,
                fullcolor=bool(getattr(s, "fullcolor", False)),
                display_id=display_id,
                x_display=s.display_id,
                capture_x=self._display_rect(display_id)[0],
                capture_y=self._display_rect(display_id)[1],
            )
            cap.start_capture(self._on_chunk, cs)
        except Exception:
            logger.exception("webrtc capture unavailable (%s)", display_id)
            if cap is not None:
                try:
                    cap.stop_capture()
                except (OSError, RuntimeError, ValueError):
                    logger.debug("cleanup of failed capture also failed",
                                 exc_info=True)
            return
        self._captures[display_id] = cap
        logger.info("webrtc capture started (single-stream h264, %s)",
                    display_id)

    def _stop_capture(self, display_id: str) -> None:
        """Non-blocking: the capture thread join (up to 5 s, longer mid
        jit-compile) must never stall the event loop."""
        cap = self._captures.pop(display_id, None)
        if cap is None:
            return

        def _stop():
            try:
                cap.stop_capture()
            except (OSError, RuntimeError, ValueError):
                # off-loop stopper thread: nothing above us to catch it
                logger.warning("webrtc capture stop failed (%s)",
                               display_id, exc_info=True)

        t = threading.Thread(target=_stop, name="webrtc-capture-stop",
                             daemon=True)
        self._cap_stoppers = [x for x in self._cap_stoppers
                              if x.is_alive()] + [t]
        t.start()

    def _stop_captures(self) -> None:
        for did in list(self._captures):
            self._stop_capture(did)

    def _on_chunk(self, chunk) -> None:
        """Capture-thread callback -> loop-side fan-out (the only
        thread->loop entry, reference selkies.py:4294 discipline)."""
        if self._loop is None or not self._sessions:
            return
        self._loop.call_soon_threadsafe(self._fanout, chunk)

    def _fanout(self, chunk) -> None:
        # route by the chunk's display: sessions view ONE display each
        did = getattr(chunk, "display_id", "primary")
        for sess in self._sessions.values():
            if sess.display_id != did and did in self._captures \
                    and sess.display_id in self._captures:
                continue
            try:
                sess.peer.send_video_au(chunk.payload)
            except Exception:
                logger.exception("webrtc send failed (%s)",
                                 sess.caller_uid)

    def _request_idr(self, display_id: str = "primary") -> None:
        cap = self._captures.get(display_id) \
            or next(iter(self._captures.values()), None)
        if cap is not None:
            try:
                cap.request_idr_frame()
            except (OSError, RuntimeError):
                logger.debug("IDR request failed (%s)", display_id,
                             exc_info=True)

    def _on_remb(self, bps: int, display_id: str = "primary") -> None:
        """Receiver bitrate estimate -> CBR target, user setting as the
        ceiling (the reference's congestion rule, webrtc_mode.py:
        1652-1716: estimate steers, never exceeds the configured rate)."""
        cap = self._captures.get(display_id) \
            or next(iter(self._captures.values()), None)
        if cap is None:
            return
        ceiling = int(self.settings.video_bitrate_kbps)
        # floor first, ceiling LAST: the configured rate is a hard cap
        kbps = min(ceiling, max(250, bps // 1000))
        try:
            cap.update_video_bitrate(kbps)
        except (OSError, RuntimeError, ValueError):
            logger.debug("REMB bitrate update failed (%s)", display_id,
                         exc_info=True)

    def _make_mic_decoder(self):
        """Decoder matching what the m-line negotiated: plain mono Opus,
        or a multistream decoder with the surround layout when the offer
        advertised multiopus (the browser then encodes its mic with that
        codec — a plain decoder can't parse multistream payloads)."""
        from ..audio import opus as _opus
        params = getattr(self.audio, "multistream_params", None)
        if params:
            return _opus.MultistreamDecoder(
                48000, int(params["channels"]),
                int(params["num_streams"]), int(params["coupled_streams"]),
                bytes(params["channel_mapping"]))
        return _opus.Decoder(48000, 1)

    def _on_mic_packet(self, caller_uid: str, opus_payload: bytes) -> None:
        """Browser mic over the sendrecv audio m-line (reference
        rtc.py:1303 mic receiver): decode with the SESSION's decoder and
        feed the same virtual-mic path the WS 0x02 frames use,
        downmixed/downsampled to its 24 kHz mono contract
        (audio/pipeline.play_mic_pcm)."""
        sess = self._sessions.get(caller_uid)
        if self.audio is None or sess is None:
            return
        try:
            if sess.mic_decoder is None:
                sess.mic_decoder = self._make_mic_decoder()
            pcm = sess.mic_decoder.decode(opus_payload)    # (n, ch) int16
        except Exception:
            logger.debug("mic opus decode failed", exc_info=True)
            return
        if pcm.shape[1] > 1:                               # downmix
            pcm = pcm.astype("int32").mean(axis=1).astype("int16")
        flat = pcm.reshape(-1)
        if flat.size < 2:
            return
        # 48 kHz -> 24 kHz: average sample pairs (cheap anti-alias)
        half = ((flat[0:flat.size - flat.size % 2:2].astype("int32")
                 + flat[1::2].astype("int32")) // 2).astype("int16")
        try:
            self.audio.play_mic_pcm(half.tobytes())
        except Exception:
            logger.debug("mic playback failed", exc_info=True)

    def _on_audio_frame(self, opus_packet: bytes, ts48: int) -> None:
        """Audio pipeline raw tap (loop thread): unframed Opus -> every
        connected peer's audio track (RFC 7587)."""
        for sess in self._sessions.values():
            try:
                sess.peer.send_audio_frame(opus_packet, ts48)
            except (OSError, RuntimeError, ValueError):
                # per-packet path: one peer's dead transport must not
                # mute the others, but a persistently failing send is
                # debuggable only if it logs
                logger.debug("audio send failed (%s)", sess.caller_uid,
                             exc_info=True)

    def _on_input_verb(self, label: str, text,
                       display_id: str = "primary") -> None:
        """Data-channel input: same verb grammar as the WS transport
        (the reference shares one input handler across transports,
        input_handler.py:1866). Control verbs the WS service would own
        (REQUEST_KEYFRAME / vb / r) are handled here — bound to the
        SENDING session's display like the RTCP PLI/REMB paths;
        everything else forwards to the shared input handler."""
        if not isinstance(text, str) or self._loop is None:
            return
        verb, _, args = text.partition(",")
        if text == "REQUEST_KEYFRAME":
            self._loop.call_soon_threadsafe(self._request_idr, display_id)
            return
        if verb == "vb":
            try:
                kbps = int(args)
            except ValueError:
                return
            self._loop.call_soon_threadsafe(self._on_remb, kbps * 1000,
                                            display_id)
            return
        if verb == "r" and self.settings.enable_resize:
            try:
                w, h = (int(v) for v in args.lower().split("x"))
            except ValueError:
                return
            self._loop.call_soon_threadsafe(
                lambda: self._loop.create_task(
                    self._resize(w, h, display_id)))
            return
        if self.input_handler is not None:
            self._loop.call_soon_threadsafe(
                lambda: self._loop.create_task(
                    self.input_handler.on_message(text)))

    async def _resize(self, w: int, h: int,
                      display_id: str = "primary") -> None:
        """Data-channel resize: retarget the REQUESTING display's capture
        (and the real X screen when one exists — reference
        webrtc_mode.py mirrors the WS on_resize logic)."""
        geo = (max(64, min(w, 16384)), max(64, min(h, 16384)))
        # through the settings layer, not attribute assignment — a plain
        # setattr would shadow _resolved and hide later settings updates
        self.settings.set_server("initial_width", geo[0])
        self.settings.set_server("initial_height", geo[1])
        try:
            from ..display import DisplayManager
            dm = DisplayManager(self.settings.display_id or ":0")
            if dm.available():
                await dm.resize(*geo, float(self.settings.framerate))
        except Exception:
            logger.debug("webrtc resize: no real display to resize")
        # retarget EVERY live capture, not just the requester's: a
        # primary resize shifts the secondary's origin (and with
        # left/above layouts, vice versa), so a live secondary keeping
        # its stale sub-rect would capture the wrong framebuffer region
        # (ADVICE r5)
        for did, cap in list(self._captures.items()):
            if not cap.is_capturing():
                continue
            ox, oy = self._display_rect(did)
            await self._loop.run_in_executor(
                None, lambda c=cap, o=(ox, oy): c.update_capture_region(
                    o[0], o[1], *geo))
