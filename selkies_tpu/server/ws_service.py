"""WebSockets streaming service — the default transport.

Fresh design carrying the reference's invariants (DataStreamingServer,
selkies.py:813-4883; SURVEY.md §2.1/§3.2):

- one WS endpoint ``/api/websockets``; handshake sends ``MODE websockets``,
  cursor state, then the ``server_settings`` JSON payload;
- per-(client, display) :class:`VideoRelay` — a slow client skips ahead and
  never paces others; the fan-out path never awaits;
- ACK-driven backpressure in uint16 circular frame-id space, with the
  desync window scaled by the measured client fps and a 4 s no-ACK stall
  trigger (reference selkies.py:1590-1717);
- capture modules are persistent per display and stay warm across client
  reconnects for ``reconnect_grace_s`` (reference selkies.py:827-830,
  940-946);
- viewer-authority verb gating (reference input_handler.py:110-128);
- gzip (0x05) control compression negotiated via ``_gz,1``.
"""

from __future__ import annotations

import asyncio
import base64
import collections
import json
import logging
import os
import threading
import time
from typing import Optional

from aiohttp import WSMsgType, web

from .. import protocol as P
from ..engine import CaptureSettings, ScreenCapture
from ..engine.types import EncodedChunk
from ..obs import health as _health
from ..obs import logctx as _logctx
from ..obs import qoe as _qoe
from ..obs import slo as _slo
from ..resilience import faults as _faults
from ..settings import AppSettings, SettingsError
from ..taskutil import spawn_retained
from ..trace import tracer as _tracer
from . import metrics
from .core import BaseStreamingService
from .relay import VideoRelay

logger = logging.getLogger("selkies_tpu.server.ws")

ACK_STALL_S = 4.0
RECONNECT_DEBOUNCE_S = 0.5
CONTROL_SEND_TIMEOUT_S = 2.0  # reference 2 s control bound (selkies.py:79-101)
#: backpressure logging: one INFO line per window, and windows that
#: start within this many seconds of the last logged one are summarised
#: (count carried on the next INFO line) instead of flooding the log
BACKPRESSURE_LOG_EVERY_S = 5.0

metrics.describe("selkies_protocol_errors_total",
                 "Malformed client text-protocol messages dropped, by "
                 "message kind")


class _FpsEstimator:
    """Client display fps from ACK cadence; ``now`` injected so tests are
    deterministic (the reference documents the same seam,
    selkies.py:1694-1696)."""

    def __init__(self, window: int = 30):
        self._times: list[float] = []
        self._window = window

    def tick(self, now: float) -> None:
        self._times.append(now)
        if len(self._times) > self._window:
            self._times.pop(0)

    def fps(self) -> float:
        if len(self._times) < 2:
            return 60.0
        span = self._times[-1] - self._times[0]
        return (len(self._times) - 1) / span if span > 0 else 60.0

    @property
    def has_samples(self) -> bool:
        """True once the estimate is measured rather than the 60 fps
        default (the QoE snapshot must not report a guess as data)."""
        return len(self._times) >= 2


def _relay_counters(relays: dict) -> dict:
    """Summed wire counters across a client's relays — the QoE
    session's pull-based relay provider."""
    out = {"sent_bytes": 0, "dropped_frames": 0, "queue_depth": 0,
           "queued_bytes": 0, "relays": len(relays), "dead": 0}
    for r in relays.values():
        c = r.counters()
        out["sent_bytes"] += c["sent_bytes"]
        out["dropped_frames"] += c["dropped_frames"]
        out["queue_depth"] += c["queue_depth"]
        out["queued_bytes"] += c["queued_bytes"]
        out["dead"] += c["dead"]
    return out


class ClientConnection:
    _next_id = 0

    def __init__(self, ws: web.WebSocketResponse, role: str, raddr: str,
                 display: str = ":0"):
        ClientConnection._next_id += 1
        self.id = ClientConnection._next_id
        self.ws = ws
        self.role = role                  # 'full' | 'viewonly'
        self.display = display            # the display this client views
        self.raddr = raddr
        self.gzip_ok = False
        #: gateway-side session id (?fleet_sid=): the affinity key a
        #: migrate command must carry; empty for direct connections
        self.fleet_sid = ""
        self.video_active = False
        self.audio_active = False
        self.relays: dict[str, VideoRelay] = {}
        self.last_sent_id = 0
        self.last_ack_id = 0
        self.last_ack_time = time.monotonic()
        self.paused = False
        self.fps_est = _FpsEstimator()
        self.reported_fps = 0.0
        self.reported_latency_ms = 0.0
        #: per-session QoE stats (obs.qoe), set by the service at accept
        self.qoe = None
        #: outstanding CLIENT_CLOCK pings (seq -> the t0/t1/t2 we stamped
        #: into the server_clock reply): a sample must echo one of these
        #: or the estimator would trust fully client-fabricated tuples
        self.clock_pings: collections.OrderedDict = collections.OrderedDict()
        # backpressure log rate limiting (one INFO per window, flapping
        # windows summarised)
        self._bp_last_log = 0.0
        self._bp_suppressed = 0

    async def send_text_maybe_gz(self, text: str) -> None:
        if self.gzip_ok:
            out = P.maybe_compress_text(text)
            if isinstance(out, bytes):
                await self.ws.send_bytes(out)
                return
        await self.ws.send_str(text)


class WebSocketsService(BaseStreamingService):
    name = "websockets"

    def __init__(self, settings: AppSettings, input_handler=None,
                 capture_factory=None, audio_pipeline=None,
                 display_manager=None):
        self.settings = settings
        self.clients: dict[int, ClientConnection] = {}
        self.captures: dict[str, ScreenCapture] = {}
        self.display_geometry: dict[str, tuple[int, int]] = {}
        #: extended-desktop origin of each display inside the X framebuffer
        self.display_offsets: dict[str, tuple[int, int]] = {}
        self._ext_desktop = None        # ExtendedDesktop, built lazily
        self._custom_factory = capture_factory is not None
        default_kind = "wayland" if getattr(settings, "wayland", False) \
            else "auto"
        self._capture_factory = capture_factory \
            or (lambda: ScreenCapture(default_kind))
        self.input_handler = input_handler
        self.audio = audio_pipeline
        if display_manager is None:
            from ..display import DisplayManager
            display_manager = DisplayManager(settings.display_id)
        self.display_manager = display_manager
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._running = False
        self._bg_tasks: set[asyncio.Task] = set()
        self._starting_captures: set[str] = set()
        # recording tap: _rec_buf is loop-affine (swapped on the loop
        # before dispatch), but _rec_file is opened/written on executor
        # threads and closed by stop() on the loop — the lock makes
        # close-vs-inflight-write an ordering, not a ValueError
        # (graftlint THREAD-SHARED-MUTATION)
        self._rec_lock = threading.Lock()
        self._rec_file = None
        self._rec_closed = False     # stop() ran: a late executor flush
        #                              must NOT reopen the file (fd leak
        #                              + write-after-teardown)
        self._rec_buf = bytearray()
        self._last_conn_by_ip: dict[str, float] = {}
        self._grace_task: Optional[asyncio.Task] = None
        self._stats_task: Optional[asyncio.Task] = None
        #: pre-degradation values the ladder's step-up rungs restore
        self._pre_degrade: dict = {}
        self._ladder_bound = False

    # ---------------------------------------------------------------- routes
    def register_routes(self, app: web.Application) -> None:
        app.router.add_get("/api/websockets", self.ws_endpoint)
        if self.settings.enable_computer_use:
            app.router.add_get("/api/screenshot", self.handle_screenshot)
            app.router.add_post("/api/computer_use",
                                self.handle_computer_use)

    # ------------------------------------------------------- agent endpoints
    async def handle_screenshot(self, request: web.Request) -> web.Response:
        """Current framebuffer as PNG (reference start_computer_use's
        screenshot surface)."""
        if request.get("role") != "full":
            return web.Response(status=403, text="view-only")
        cap = self.captures.get(self._default_display()) \
            or self.captures.get("__seats__") \
            or next(iter(self.captures.values()), None)
        if cap is None or not hasattr(cap, "screenshot"):
            return web.Response(status=503, text="no active capture")
        def _grab_png():
            shot = cap.screenshot()
            if shot is None:
                return None
            import io as _io

            from PIL import Image
            buf = _io.BytesIO()
            Image.fromarray(shot, "RGB").save(buf, "PNG")
            return buf.getvalue()

        png = await asyncio.get_running_loop().run_in_executor(None, _grab_png)
        if png is None:
            return web.Response(status=503, text="no frame yet")
        return web.Response(body=png, content_type="image/png")

    async def handle_computer_use(self, request: web.Request) -> web.Response:
        """Agent input injection: {"action": "move|click|type|key|scroll",
        ...} (reference computer-use HTTP server parity)."""
        if request.get("role") != "full":
            return web.Response(status=403, text="view-only")
        if self.input_handler is None or not self.settings.enable_input:
            return web.Response(status=503, text="input disabled")
        try:
            body = await request.json()
        except Exception:
            return web.Response(status=400, text="json body required")
        action = body.get("action")
        h = self.input_handler
        try:
            if action == "move":
                await h.on_message(f"m,{int(body['x'])},{int(body['y'])}")
            elif action == "click":
                btn = int(body.get("button", 1))
                await h.on_message(f"m,{int(body['x'])},{int(body['y'])}")
                await h.on_message(f"mb,{btn},1")
                await h.on_message(f"mb,{btn},0")
            elif action == "scroll":
                await h.on_message(
                    f"ms,{int(body.get('dx', 0))},{int(body.get('dy', 0))}")
            elif action == "key":
                ks = int(body["keysym"])
                await h.on_message(f"kd,{ks}")
                await h.on_message(f"ku,{ks}")
            elif action == "type":
                for ch in str(body.get("text", ""))[:4096]:
                    cp = ord(ch)
                    ks = cp if cp < 0x100 else 0x01000000 + cp
                    await h.on_message(f"kd,{ks}")
                    await h.on_message(f"ku,{ks}")
            else:
                return web.Response(status=400,
                                    text=f"unknown action {action!r}")
        except (KeyError, ValueError) as e:
            return web.Response(status=400, text=f"bad arguments: {e}")
        return web.json_response({"ok": True})

    @property
    def _seats(self) -> int:
        return max(1, int(getattr(self.settings, "tpu_seats", 1)))

    def _default_display(self) -> str:
        return "seat0" if self._seats > 1 else self.settings.display_id

    async def start(self) -> None:
        self._loop = asyncio.get_event_loop()
        self._running = True
        with self._rec_lock:
            self._rec_closed = False    # a restart records again
        if self.input_handler is not None \
                and self.input_handler.send_clipboard is None:
            async def _push_clipboard(data: bytes, mime: str) -> None:
                # clipboard contents go ONLY to input-authorized clients
                # (view-only is denied the request verb; it must not get
                # the payload by broadcast either), and only when the
                # server->client direction is enabled
                if self.settings.enable_clipboard not in ("both", "out"):
                    return
                import base64
                msg = "clipboard," + base64.b64encode(data).decode()
                for c in list(self.clients.values()):
                    if c.role != "full":
                        continue
                    try:
                        await asyncio.wait_for(c.send_text_maybe_gz(msg),
                                               CONTROL_SEND_TIMEOUT_S)
                    except (asyncio.TimeoutError, ConnectionError,
                            RuntimeError, OSError):
                        pass
            self.input_handler.send_clipboard = _push_clipboard
        if self._seats > 1 and not self.display_geometry:
            # multi-seat: one display entry per seat, one sharded capture
            for i in range(self._seats):
                self.display_geometry[f"seat{i}"] = (
                    self.settings.initial_width, self.settings.initial_height)
        if self.input_handler is not None:
            self.input_handler.start()
        if self.audio is not None:
            # enable_microphone without enable_audio: mic playback only,
            # no capture/encode loop (ADVICE r5)
            await self.audio.start(
                mic_only=not self.settings.enable_audio)
            sup = self._supervisor()
            if sup is not None \
                    and hasattr(self.audio, "restart_encode_loop"):
                # supervised audio: the pipeline reports its encode-loop
                # death instead of self-retrying on a fixed 1 s beat
                sup.adopt("audio", self.audio.restart_encode_loop)
                self.audio.on_death = \
                    lambda exc: sup.report_death(
                        "audio", f"{type(exc).__name__}: {exc}")
        self._bind_ladder()
        self._stats_task = asyncio.create_task(self._stats_loop())
        # watched RTC config file: edits reach connected clients as an
        # rtc_config push, so ICE-server rotation needs no reconnect
        # (reference RTCConfigFileMonitor, webrtc_utils.py:354-460)
        cfg_path = str(getattr(self.settings, "rtc_config_file", "") or "")
        if cfg_path:
            from .turn import RtcConfigMonitor

            def _push_cfg(cfg: dict) -> None:
                self._spawn_retained(self._broadcast_control(
                    "rtc_config," + json.dumps(cfg)))
            self._rtc_cfg_monitor = RtcConfigMonitor(cfg_path, _push_cfg)
            self._rtc_cfg_monitor.start()
        self._register_health_checks()
        logger.info("websockets service started")

    def _spawn_retained(self, coro, component: str = "ws_service"
                        ) -> asyncio.Task:
        """Background task retained on the service; cancelled in
        stop()."""
        return spawn_retained(self._bg_tasks, coro, component)

    def _supervisor(self):
        """The core's restart-policy engine; None when the service runs
        without a core (some unit tests) — wiring then degrades to the
        pre-PR-5 unsupervised behaviour."""
        return getattr(getattr(self, "core", None), "supervisor", None)

    # --------------------------------------------------------------- health
    def _register_health_checks(self) -> None:
        """Transport-scope checks on the process-wide engine (replaced on
        every service (re)start so the closures track THIS instance)."""
        _health.engine.register("relay", self._check_relays)
        _health.engine.register("capture_fps", self._check_capture_fps)
        _health.engine.register("audio", self._check_audio)

    def _check_relays(self) -> _health.Verdict:
        """Relay alive vs deaths: the r04/r05 class of failure where
        media sends stall and every viewer silently goes dark."""
        active = [c for c in self.clients.values() if c.video_active]
        if not active:
            return _health.ok("no active viewers")
        total = dead = 0
        for c in active:
            for r in c.relays.values():
                total += 1
                dead += r.dead
        if total and dead == total:
            return _health.failed(
                f"all {total} video relays dead", dead=dead, total=total)
        if dead:
            return _health.degraded(
                f"{dead}/{total} video relays dead", dead=dead, total=total)
        return _health.ok(f"{total} relays alive", total=total)

    def _check_capture_fps(self) -> _health.Verdict:
        active = [c for c in self.clients.values() if c.video_active]
        if not active:
            return _health.ok("no active viewers")
        caps = {d: c for d, c in self.captures.items() if c.is_capturing()}
        if not caps:
            if self._starting_captures:
                return _health.degraded(
                    "capture starting (first compile on a new geometry "
                    "can take minutes)")
            return _health.failed("viewers active but no capture running")
        target = float(self.settings.framerate)
        ratio = float(getattr(self.settings, "health_fps_degraded_ratio",
                              0.5))
        worst_did, worst_fps = min(
            ((d, float(getattr(c, "encoded_fps", 0.0)))
             for d, c in caps.items()), key=lambda kv: kv[1])
        msg = f"{worst_did}: {worst_fps:.1f} fps vs target {target:.0f}"
        if worst_fps < target * ratio:
            return _health.degraded(msg, fps=worst_fps, target=target)
        return _health.ok(msg, fps=worst_fps, target=target)

    def _check_audio(self) -> _health.Verdict:
        s = self.settings
        if not s.enable_audio and not s.enable_microphone:
            return _health.ok("audio disabled")
        if self.audio is None:
            want = "audio" if s.enable_audio else "microphone"
            return _health.degraded(
                f"{want} enabled but the pipeline failed to start "
                "(no libopus/PulseAudio?)")
        if s.enable_audio and not getattr(self.audio, "alive", True):
            return _health.failed("audio encode task is dead")
        if s.enable_microphone \
                and getattr(self.audio, "mic_ok", None) is False:
            return _health.degraded(
                "virtual mic provisioning failed (no PulseAudio?) — "
                "client mic input will not reach desktop apps")
        return _health.ok("mic-only pipeline" if not s.enable_audio
                          else "audio pipeline running")

    # --------------------------------------------------------- compile plane
    def _note_prewarm(self, display_id: str) -> None:
        """Tell the pre-warm worker (selkies_tpu/prewarm) the CURRENT
        operating point, so the live geometry's ladder neighbourhood
        compiles before speculative lattice corners — the rung the
        ladder would visit next under load is a neighbour of where the
        server IS."""
        worker = getattr(getattr(self, "core", None), "prewarm", None)
        if worker is None:
            return
        try:
            w, h = self._capture_geometry(display_id)
            worker.note_operating_point(w, h)
        except Exception:
            logger.debug("prewarm operating-point note failed",
                         exc_info=True)

    # ----------------------------------------------------- degradation ladder
    def _bind_ladder(self) -> None:
        """Bind concrete actuators to the core's degradation ladder:
        rung 1 halves target fps (floor ``ladder_min_fps``), rung 2 cuts
        JPEG quality / H.264 bitrate, rung 3 downscales the capture.
        Step-up restores the values captured at downshift time."""
        ladder = getattr(getattr(self, "core", None), "ladder", None)
        if ladder is None:
            return
        ladder.bind_controls({
            "pipeline": (self._ladder_pipeline_down,
                         self._ladder_pipeline_up),
            "fps": (self._ladder_fps_down, self._ladder_fps_up),
            "quality": (self._ladder_quality_down, self._ladder_quality_up),
            "downscale": (self._ladder_scale_down, self._ladder_scale_up),
        })
        self._ladder_bound = True

    def _ladder_restore(self, key: str, current) -> "Optional[int]":
        """Pop a (original, what_we_set) pre-degradation record; -> the
        original to restore, or None when the operator/client changed
        the value since the downshift — their choice wins, the ladder
        must not clobber it on step-up."""
        rec = self._pre_degrade.pop(key, None)
        if rec is None:
            return None
        orig, set_to = rec
        if current != set_to:
            logger.info("ladder: %s changed to %s while degraded; "
                        "not restoring %s", key, current, orig)
            return None
        return orig

    def _ladder_pipeline_down(self):
        """Rung 0 (deep pipeline): drop to frame-serial. Sheds the
        in-flight frames' worth of queueing latency and HBM without
        costing any fidelity — always the first concession."""
        s = self.settings
        cur = int(getattr(s, "pipeline_depth", 2))
        if cur <= 1:
            return False            # already serial: not applied
        self._pre_degrade.setdefault("pipeline_depth", (cur, 1))
        s.set_server("pipeline_depth", 1)
        for cap in self.captures.values():
            cap.update_tunables(pipeline_depth=1)
        logger.warning("ladder: pipeline depth %d -> 1 (serial)", cur)

    def _ladder_pipeline_up(self):
        old = self._ladder_restore(
            "pipeline_depth", int(getattr(self.settings,
                                          "pipeline_depth", 2)))
        if old is None:
            return False            # nothing to restore: not applied
        self.settings.set_server("pipeline_depth", int(old))
        for cap in self.captures.values():
            cap.update_tunables(pipeline_depth=int(old))
        logger.info("ladder: pipeline depth restored to %d", old)

    def _ladder_fps_down(self):
        s = self.settings
        cur = int(s.framerate)
        new = int(max(float(getattr(s, "ladder_min_fps", 15.0)), cur / 2))
        if new >= cur:
            return False            # already at the floor: not applied
        self._pre_degrade.setdefault("framerate", (cur, new))
        s.set_server("framerate", new)
        for cap in self.captures.values():
            cap.update_framerate(float(new))
        logger.warning("ladder: target fps %d -> %d", cur, new)

    def _ladder_fps_up(self):
        old = self._ladder_restore("framerate", int(self.settings.framerate))
        if old is None:
            return False            # nothing to restore: not applied
        self.settings.set_server("framerate", int(old))
        for cap in self.captures.values():
            cap.update_framerate(float(old))
        logger.info("ladder: target fps restored to %d", old)

    def _ladder_quality_down(self) -> None:
        s = self.settings
        q, kbps = int(s.jpeg_quality), int(s.video_bitrate_kbps)
        new_q = max(15, q - 25)
        new_kbps = max(500, kbps // 2)
        self._pre_degrade.setdefault("jpeg_quality", (q, new_q))
        self._pre_degrade.setdefault("video_bitrate_kbps", (kbps, new_kbps))
        s.set_server("jpeg_quality", new_q)
        s.set_server("video_bitrate_kbps", new_kbps)
        for cap in self.captures.values():
            cap.update_tunables(jpeg_quality=new_q,
                                paint_over_quality=s.paint_over_quality)
            cap.update_video_bitrate(new_kbps)
        logger.warning("ladder: quality %d -> %d, bitrate %d -> %d kbps",
                       q, new_q, kbps, new_kbps)

    def _ladder_quality_up(self):
        s = self.settings
        q = self._ladder_restore("jpeg_quality", int(s.jpeg_quality))
        kbps = self._ladder_restore("video_bitrate_kbps",
                                    int(s.video_bitrate_kbps))
        if q is None and kbps is None:
            return False            # nothing to restore: not applied
        if q is not None:
            s.set_server("jpeg_quality", int(q))
        if kbps is not None:
            s.set_server("video_bitrate_kbps", int(kbps))
        for cap in self.captures.values():
            if q is not None:
                cap.update_tunables(jpeg_quality=int(q),
                                    paint_over_quality=s.paint_over_quality)
            if kbps is not None:
                cap.update_video_bitrate(int(kbps))
        logger.info("ladder: quality/bitrate restored")

    def _ladder_scale_down(self) -> None:
        # geometry work joins capture threads: retained background task
        self._spawn_retained(self._apply_ladder_scale(2), "ladder-scale")

    def _ladder_scale_up(self) -> None:
        self._spawn_retained(self._apply_ladder_scale(None), "ladder-scale")

    async def _apply_ladder_scale(self, factor) -> None:
        """``factor=N`` divides every display geometry by N (capture
        downscale — on a live X server the screen itself resizes so it
        is a true scale, headless captures shrink their grab);
        ``factor=None`` restores the pre-degradation geometry."""
        if factor is not None:
            scaled = {did: (max(64, w // factor), max(64, h // factor))
                      for did, (w, h) in self.display_geometry.items()}
            self._pre_degrade.setdefault(
                "geometry", (dict(self.display_geometry), dict(scaled)))
            new_geo = scaled
        else:
            rec = self._pre_degrade.pop("geometry", None)
            if not rec:
                return
            orig_geo, set_geo = rec
            if self.display_geometry != set_geo:
                # a client resized while degraded: its geometry wins
                logger.info("ladder: geometry changed while degraded; "
                            "not restoring %s", orig_geo)
                return
            new_geo = orig_geo
        self.display_geometry.update(new_geo)
        if self.display_manager is not None \
                and self.display_manager.available() \
                and len(new_geo) == 1:
            did, geo = next(iter(new_geo.items()))
            await self.display_manager.resize(
                *geo, float(self.settings.framerate))
        loop = asyncio.get_running_loop()
        targets = ["__seats__"] if self._seats > 1 \
            else list(self.display_geometry)
        for tdid in targets:
            cap = self.captures.get(tdid)
            if not (cap and cap.is_capturing()):
                continue
            geo = self._capture_geometry(tdid)
            ox, oy = self.display_offsets.get(tdid, (0, 0))
            await loop.run_in_executor(
                None, lambda c=cap, o=(ox, oy), g=geo:
                c.update_capture_region(o[0], o[1], *g))
        await self._broadcast_control(self._server_settings_payload())
        # re-anchor the pre-warm order on the NEW operating point (the
        # restore geometry's neighbourhood is now the speculative one)
        self._note_prewarm(self._default_display())
        logger.warning("ladder: capture geometry %s",
                       "downscaled /%d" % factor if factor else "restored")

    async def stop(self) -> None:
        self._running = False
        for name, fn in (("relay", self._check_relays),
                         ("capture_fps", self._check_capture_fps),
                         ("audio", self._check_audio)):
            _health.engine.unregister(name, fn)
        sup = self._supervisor()
        if sup is not None:
            # deliberate teardown: pending restarts must not resurrect
            # captures/relays into a stopping service
            for did in list(self.captures):
                sup.drop(f"capture:{did}")
            for c in self.clients.values():
                for did in c.relays:
                    sup.drop(f"relay:{c.id}:{did}")
            sup.drop("audio")
        if self.audio is not None:
            self.audio.on_death = None
        if self._ladder_bound:
            ladder = getattr(getattr(self, "core", None), "ladder", None)
            if ladder is not None:
                ladder.unbind_controls()
            self._ladder_bound = False
        bg = list(self._bg_tasks)
        for task in bg:
            task.cancel()
        if bg:
            # deliver the CancelledError so finally-blocks run before
            # the loop can be closed
            await asyncio.gather(*bg, return_exceptions=True)
        if self._stats_task:
            self._stats_task.cancel()
        if getattr(self, "_rtc_cfg_monitor", None) is not None:
            await self._rtc_cfg_monitor.stop()
            self._rtc_cfg_monitor = None
        for c in list(self.clients.values()):
            await c.ws.close()
        for cap in self.captures.values():
            cap.stop_capture()
        self.captures.clear()
        if self.audio is not None:
            await self.audio.stop()
        if self.input_handler is not None:
            await self.input_handler.stop()
        if self._rec_buf:
            buf, self._rec_buf = self._rec_buf, bytearray()
        else:
            buf = b""

        def _close_recording() -> None:
            # final flush + close run OFF-LOOP: _rec_lock is held across
            # disk writes by executor flushes, so acquiring it on the
            # loop could stall every session behind a slow filesystem
            if buf:
                try:
                    self._flush_recording(buf)
                except OSError:
                    # losing the recording tail on teardown is
                    # acceptable; losing the stop path is not
                    logger.warning("final recording flush failed",
                                   exc_info=True)
            with self._rec_lock:
                self._rec_closed = True
                if self._rec_file is not None:
                    try:
                        self._rec_file.close()
                    except OSError:
                        pass
                    self._rec_file = None

        await asyncio.get_running_loop().run_in_executor(
            None, _close_recording)

    def _flush_recording(self, buf: bytes) -> None:
        """Executor-side disk append for the recording tap. The lock
        orders this against stop()'s close: an in-flight flush completes
        before the file handle dies (previously a write-after-close
        ValueError when teardown raced the stats-loop flush), and a
        flush that arrives AFTER the close drops its tail instead of
        reopening the file (an fd nothing would ever close again)."""
        try:
            with self._rec_lock:
                if self._rec_closed:
                    logger.debug("recording flush after stop: %d bytes "
                                 "dropped", len(buf))
                    return
                if self._rec_file is None:
                    self._rec_file = open(self.settings.recording_path,
                                          "ab")
                self._rec_file.write(buf)
                self._rec_file.flush()
        except OSError as e:
            logger.warning("recording tap failed: %s; disabling", e)
            self.settings.set_server("recording_path", "")
        # NOTE: callers swap self._rec_buf BEFORE dispatching here; touching
        # it from this executor thread would drop concurrently-appended
        # chunks

    # -------------------------------------------------------------- settings
    def _server_settings_payload(self) -> str:
        payload = {
            "type": "server_settings",
            "app_name": self.settings.app_name,
            "settings": self.settings.build_client_settings_payload(),
            "displays": [
                {"id": did, "width": w, "height": h}
                for did, (w, h) in sorted(self.display_geometry.items())
            ] or [{"id": self.settings.display_id,
                   "width": self.settings.initial_width,
                   "height": self.settings.initial_height}],
            # surround (>2ch) streams carry the RFC 7845 OpusHead the
            # browser AudioDecoder needs as `description`
            "audio_head": (base64.b64encode(self.audio.opus_head).decode()
                           if self.audio is not None
                           and getattr(self.audio, "opus_head", None)
                           else None),
            "features": {
                "audio": self.audio is not None and self.settings.enable_audio,
                "microphone": self.audio is not None and self.settings.enable_microphone,
                "clipboard": self.settings.enable_clipboard != "none",
                "gamepad": self.settings.enable_gamepad,
                "file_transfer": self.settings.enable_file_transfer,
                "file_transfers": str(getattr(
                    self.settings, "file_transfers", "upload,download")),
                "resize": self.settings.enable_resize,
            },
        }
        return "server_settings " + json.dumps(payload)

    # --------------------------------------------------------------- capture
    def _capture_geometry(self, display_id: str) -> tuple[int, int]:
        s = self.settings
        if display_id == "__seats__":
            # seats share one geometry; any seat entry carries it
            display_id = "seat0"
        return self.display_geometry.get(
            display_id, (s.initial_width, s.initial_height))

    def _content_state_for(self, display_id: str) -> dict:
        """Content/damage block of a display's capture (ROADMAP 4) —
        {} when the capture is absent or pre-classifier."""
        cap = self.captures.get(display_id) \
            or self.captures.get("__seats__")
        state = getattr(cap, "content_state", None)
        if state is None:
            return {}
        try:
            return state() or {}
        except Exception:
            return {}

    def primary_content_class(self):
        """The default display's content class (the core's ladder feed);
        None before classification."""
        return self._content_state_for(self._default_display()).get(
            "class")

    def _capture_settings(self, display_id: str) -> CaptureSettings:
        s = self.settings
        w, h = self._capture_geometry(display_id)
        return CaptureSettings(
            single_stream=(s.encoder == "h264-tpu"),
            capture_width=w, capture_height=h,
            target_fps=float(s.framerate),
            output_mode="jpeg" if s.encoder.startswith("jpeg") else "h264",
            video_bitrate_kbps=s.video_bitrate_kbps,
            video_crf=s.video_crf,
            use_cbr=bool(getattr(s, "use_cbr", False)),
            video_min_qp=s.video_min_qp, video_max_qp=s.video_max_qp,
            keyframe_interval_s=s.keyframe_interval_s,
            jpeg_quality=s.jpeg_quality,
            fullcolor=s.fullcolor,
            use_damage_gating=s.use_damage_gating,
            use_paint_over=s.use_paint_over,
            paint_over_quality=s.paint_over_quality,
            stripe_height=s.stripe_height,
            stripe_devices=int(getattr(s, "tpu_stripe_devices", 1)),
            pipeline_depth=int(getattr(s, "pipeline_depth", 2)),
            stripe_streaming=bool(getattr(s, "stripe_streaming", True)),
            h264_motion_vrange=s.h264_motion_vrange,
            h264_motion_hrange=s.h264_motion_hrange,
            h264_partial_encode=bool(getattr(s, "h264_partial_encode",
                                             True)),
            h264_content_adaptive=bool(getattr(s, "h264_content_adaptive",
                                               True)),
            h264_roi_qp=bool(getattr(s, "h264_roi_qp", False)),
            h264_roi_qp_bias=int(getattr(s, "h264_roi_qp_bias", 4)),
            capture_x=self.display_offsets.get(display_id, (0, 0))[0],
            capture_y=self.display_offsets.get(display_id, (0, 0))[1],
            display_id=display_id,
            # the logical id ("display2") is NOT an X address: every
            # capture opens the configured server display and reads its
            # own sub-rect
            x_display=s.display_id,
            watermark_path=s.watermark_path,
            watermark_location=s.watermark_location,
        )

    def _apply_display_layout(self) -> None:
        """Extended-desktop layout: primary + display2 origins inside one
        union framebuffer (reference display_utils.py:340-835 dual-layout
        math). Headless servers get capture offsets only; a live X server
        additionally gets the union framebuffer and ``selkies-N`` logical
        monitors so the WM tiles per display."""
        prim = self._default_display()
        self.display_offsets.setdefault(prim, (0, 0))
        others = sorted(d for d in self.display_geometry if d != prim)
        if not others or self._seats > 1:
            self.display_offsets[prim] = (0, 0)
            return
        from ..display import ExtendedDesktop, compute_dual_layout
        s = self.settings
        w1, h1 = self.display_geometry.get(
            prim, (s.initial_width, s.initial_height))
        w2, h2 = self.display_geometry[others[0]]
        _, _, o1, o2 = compute_dual_layout(
            w1, h1, w2, h2, getattr(s, "display2_position", "right"))
        self.display_offsets[prim] = o1
        self.display_offsets[others[0]] = o2
        if self.display_manager is not None \
                and self.display_manager.available():
            if self._ext_desktop is None:
                self._ext_desktop = ExtendedDesktop(self.display_manager)
            rects = [(o1[0], o1[1], w1, h1), (o2[0], o2[1], w2, h2)]
            task = asyncio.get_running_loop().create_task(
                self._ext_desktop.apply(rects, float(s.framerate)))
            task.add_done_callback(
                lambda t: t.cancelled() or t.exception())

    def _ensure_capture(self, display_id: str) -> None:
        if any(c.video_active for c in self.clients.values()):
            # multi-seat: ONE sharded capture feeds every seat display
            if self._seats > 1:
                display_id = "__seats__"
            cap = self.captures.get(display_id)
            if cap is None:
                if display_id == "__seats__" and not self._custom_factory:
                    from ..parallel.capture import MultiSeatCapture
                    cap = MultiSeatCapture(self._seats)
                else:
                    cap = self._capture_factory()
                self.captures[display_id] = cap
            self._adopt_capture(display_id, cap)
            if not cap.is_capturing() \
                    and display_id not in self._starting_captures:
                loop = self._loop
                assert loop is not None

                def cb(chunk: EncodedChunk) -> None:
                    # thread -> loop boundary: the ONLY entry point
                    # (reference selkies.py:4294)
                    loop.call_soon_threadsafe(self._do_fanout, chunk)

                def cursor_cb(cur: dict) -> None:
                    loop.call_soon_threadsafe(self._on_cursor, cur)

                if self.settings.enable_cursors:
                    cap.set_cursor_callback(cursor_cb)
                # session construction does device transfers/mesh setup:
                # off the loop, guarded against double-dispatch
                self._starting_captures.add(display_id)
                cs = self._capture_settings(display_id)
                self._note_prewarm(display_id)
                # cold-start UX: session construction may trigger a
                # minutes-long first XLA compile of this geometry — tell
                # viewers instead of leaving a silent black screen
                # (VERDICT r3 weak 4); the client clears the message when
                # the first stripe draws
                self._spawn_retained(self._broadcast_control(
                    "system_msg,preparing encoder for "
                    f"{cs.capture_width}x{cs.capture_height} (first "
                    "start on a new geometry compiles; warm caches "
                    "take seconds)"))

                def _start():
                    try:
                        cap.start_capture(cb, cs)
                        logger.info("capture started for display %s",
                                    display_id)
                        # a resize may have landed while the session was
                        # constructing (is_capturing() was False then, so
                        # _h_resize skipped it): reconcile to the CURRENT
                        # geometry before handing the thread back
                        cur = self._capture_geometry(display_id)
                        if cur != (cs.capture_width, cs.capture_height):
                            cap.update_capture_region(0, 0, *cur)
                    except Exception:
                        logger.exception(
                            "capture start failed for display %s "
                            "(clients will see no video until the next "
                            "START_VIDEO)", display_id)
                    finally:
                        loop.call_soon_threadsafe(
                            self._starting_captures.discard, display_id)

                loop.run_in_executor(None, _start)

    def _adopt_capture(self, display_id: str, cap) -> None:
        """Supervise the capture thread: a loop death (source raise,
        device error mid-encode) reports to the restart-policy engine
        instead of logging and going dark. The restart joins the old
        thread and rebuilds the session — executor-side, never on the
        loop."""
        sup = self._supervisor()
        loop = self._loop
        if sup is None or loop is None or not hasattr(cap, "restart"):
            return
        comp = f"capture:{display_id}"

        def _restart(cap=cap):
            return loop.run_in_executor(None, cap.restart)

        sup.adopt(comp, _restart)
        # capture-thread -> loop hop: report_death is loop-affine
        cap.on_death = lambda exc, c=comp: loop.call_soon_threadsafe(
            sup.report_death, c, f"{type(exc).__name__}: {exc}")

    def _maybe_stop_captures(self) -> None:
        """Stop capture after the reconnect grace window if nobody watches
        (reference keeps encoders warm 3 s across reloads)."""
        if any(c.video_active for c in self.clients.values()):
            return

        async def _grace():
            await asyncio.sleep(self.settings.reconnect_grace_s)
            if not any(c.video_active for c in self.clients.values()):
                sup = self._supervisor()
                for did, cap in self.captures.items():
                    cap.stop_capture()
                    # deliberate stop, same discipline as stop(): the
                    # restart engine must forget the capture (a drain
                    # handle waits on exactly this; _ensure_capture
                    # re-adopts on the next viewer)
                    if sup is not None:
                        sup.drop(f"capture:{did}")
                    logger.info("capture stopped for display %s", did)

        if self._grace_task is None or self._grace_task.done():
            self._grace_task = asyncio.create_task(_grace())

    # ---------------------------------------------------------------- cursor
    def _on_cursor(self, cur: dict) -> None:
        """Runs on the loop: PNG-encode the XFixes cursor image and
        broadcast a ``cursor,{json}`` message (reference
        display_utils.py:1730, format_pixelflux_cursor)."""
        import base64
        import io

        from PIL import Image
        try:
            img = Image.fromarray(cur["rgba"], "RGBA")
            buf = io.BytesIO()
            img.save(buf, "PNG")
            payload = json.dumps({
                "png_b64": base64.b64encode(buf.getvalue()).decode(),
                "xhot": cur["xhot"], "yhot": cur["yhot"],
                "serial": cur["serial"],
            })
        except Exception:
            logger.debug("cursor encode failed", exc_info=True)
            return
        self._last_cursor_msg = "cursor," + payload
        self._spawn_retained(self._broadcast_control(self._last_cursor_msg))

    # ---------------------------------------------------------------- fanout
    def _do_fanout(self, chunk: EncodedChunk) -> None:
        """Runs on the loop; wire-frames once, offers to every relay.
        Synchronous — no awaits (reference selkies.py:4234-4292)."""
        with _tracer.span("fanout",
                          _tracer.lookup(chunk.display_id, chunk.frame_id),
                          lane="loop"):
            if chunk.output_mode == "jpeg":
                frame = P.pack_jpeg_stripe(chunk.frame_id, chunk.stripe_y,
                                           chunk.payload)
            else:
                frame = P.pack_h264_stripe(chunk.frame_id, chunk.stripe_y,
                                           chunk.width, chunk.height,
                                           chunk.payload, idr=chunk.is_idr)
        metrics.inc_counter("selkies_frames_encoded_total")
        # out-of-band recording tap: raw Annex-B / MJPEG of the primary
        # display (reference recording socket, settings.py:640-645)
        if self.settings.recording_path \
                and chunk.display_id == self._default_display():
            # buffered on the loop (cheap append), flushed to disk from an
            # executor — a slow disk must never pace the fan-out
            self._rec_buf += chunk.payload
        now_m = time.monotonic()
        for c in self.clients.values():
            if not c.video_active or c.paused:
                continue
            relay = c.relays.get(chunk.display_id)
            if relay is None or relay.dead:
                continue
            c.last_sent_id = chunk.frame_id
            relay.offer(frame)
            if c.qoe is not None:
                c.qoe.note_sent(chunk.frame_id, now_m)

    async def announce_migration(self, target_url: str,
                                 resync: bool = True) -> int:
        """Fleet drain (ISSUE 11): tell every connected client to
        reconnect elsewhere. Each client gets its OWN ``migrate,{json}``
        (the sid rides along so the gateway's affinity map routes the
        reconnect to the re-placed seat); captures stay warm — the
        normal reconnect-grace machinery holds them when the clients
        drop, so a client that bounces straight back (aborted drain)
        still finds a frame. -> clients notified."""
        from ..fleet.protocol import migrate_command

        async def _one(c: ClientConnection) -> int:
            try:
                await asyncio.wait_for(
                    c.send_text_maybe_gz(
                        migrate_command(target_url,
                                        c.fleet_sid or str(c.id),
                                        resync=resync)),
                    CONTROL_SEND_TIMEOUT_S)
                return 1
            except (asyncio.TimeoutError, ConnectionError,
                    RuntimeError, OSError):
                logger.info("migrate notify to client %d failed", c.id)
                return 0

        # concurrent like _broadcast_control: a drain of N clients with
        # stalled sockets must cost ONE control timeout, not N of them
        notified = sum(await asyncio.gather(
            *(_one(c) for c in list(self.clients.values()))))
        if notified:
            logger.warning("fleet drain: told %d client(s) to migrate "
                           "to %s", notified, target_url or "(gateway)")
        return notified

    async def _broadcast_control(self, text: str) -> None:
        """Bounded CONCURRENT broadcast: one stalled client must never pace
        the loop or the other clients (reference bounded-send rule,
        selkies.py:79-101) — the per-client bounds run in parallel so the
        whole broadcast costs one timeout, not one per stalled client. A
        send that exceeds the bound marks the socket dead and closes it —
        a cancelled send may have torn a frame, so it is never reused."""
        async def _one(c: ClientConnection) -> None:
            try:
                await asyncio.wait_for(c.send_text_maybe_gz(text),
                                       CONTROL_SEND_TIMEOUT_S)
            except (asyncio.TimeoutError, ConnectionError,
                    RuntimeError, OSError):
                logger.info("control send to client %d failed; closing", c.id)
                for relay in c.relays.values():
                    relay.mark_dead()
                try:
                    await c.ws.close()
                except (ConnectionError, RuntimeError, OSError):
                    pass  # already torn down by the peer

        await asyncio.gather(*(_one(c) for c in list(self.clients.values())))

    # ------------------------------------------------------------- endpoint
    async def ws_endpoint(self, request: web.Request) -> web.WebSocketResponse:
        ws = web.WebSocketResponse(max_msg_size=P.WS_MESSAGE_SIZE_HARD_CAP,
                                   compress=False)  # media must not deflate
        await ws.prepare(request)
        # fault point: an injected accept failure closes the fresh
        # socket (1013 Try Again Later) — the client reconnect path
        try:
            await _faults.registry.perturb_async("ws.accept")
        except _faults.FaultError:
            await ws.close(code=1013, message=b"fault injected")
            return ws
        role = request.get("role", "full")
        raddr = request.remote or "?"

        # secure-token mode: the HTTP-auth role is not enough — the client
        # must present a minted token (reference selkies.py:2147-2200)
        if self.settings.secure_api:
            core = getattr(self, "core", None)
            token_role = core.check_ws_token(
                request.query.get("token", "")) if core else None
            if token_role is None:
                await ws.close(code=4401, message=b"token required")
                return ws
            role = token_role

        # reconnect debounce per IP (reference selkies.py:2202-2217)
        now = time.monotonic()
        last = self._last_conn_by_ip.get(raddr, 0.0)
        self._last_conn_by_ip[raddr] = now
        if now - last < RECONNECT_DEBOUNCE_S:
            await asyncio.sleep(RECONNECT_DEBOUNCE_S)

        # sharing enforcement
        if not self.settings.enable_sharing and self.clients:
            await ws.close(code=4000, message=b"sharing disabled")
            return ws

        # validate ?display= against KNOWN displays always — an arbitrary
        # string must never become a capture key (it would spawn a whole
        # extra pipeline per distinct value). The ONE sanctioned new name
        # is "display2": the reference's extended second display
        # (display_utils.py:340-835), registered lazily up to max_displays.
        display = request.query.get("display") or self._default_display()
        known = set(self.display_geometry) or {self._default_display()}
        if display not in known:
            if (display == "display2" and self._seats == 1
                    and self.settings.max_displays >= 2):
                s = self.settings
                self.display_geometry.setdefault(
                    self._default_display(),
                    (s.initial_width, s.initial_height))
                self.display_geometry[display] = (s.initial_width,
                                                  s.initial_height)
                self._apply_display_layout()
            else:
                display = self._default_display()
        client = ClientConnection(ws, role, raddr, display=display)
        # fleet affinity (ISSUE 11): the gateway's WS proxy forwards the
        # session id it placed under (?fleet_sid=); a drain's migrate
        # command must carry THAT id — the engine-local client id means
        # nothing to the gateway's affinity map. Bounded+sanitised: it
        # goes back out on the wire in the migrate command.
        fleet_sid = request.query.get("fleet_sid", "")[:128]
        client.fleet_sid = "".join(
            c for c in fleet_sid if c.isalnum() or c in "._:-")
        # broadcast rung pin (ISSUE 17): the gateway's rendition
        # upstream dials ?rung=<name>; attach as a broadcast viewer on
        # that rung before the first START_VIDEO so the relay is keyed
        # to the rung's capture from frame one
        rung_q = request.query.get("rung", "")[:32]
        rung_q = "".join(c for c in rung_q if c.isalnum() or c in "._-")
        # only the first full client gets input authority unless collab
        if role == "full" and not self.settings.enable_collab:
            if any(c.role == "full" for c in self.clients.values()):
                client.role = "viewonly"
        self.clients[client.id] = client
        # per-session QoE stats: wire counters pull from the client's
        # live relays, fps prefers the client's own report
        client.qoe = _qoe.registry.register("ws", client.display,
                                            client.id, raddr=raddr)
        client.qoe.fps_provider = (
            lambda c=client: c.fps_est.fps() if c.fps_est.has_samples
            else None)
        client.qoe.target_fps = lambda: float(self.settings.framerate)
        client.qoe.relay_provider = \
            lambda c=client: _relay_counters(c.relays)
        # content-adaptive encoding (ROADMAP 4): class + dirty fraction
        # from the display's capture, pulled at snapshot/export time
        client.qoe.content_provider = \
            lambda c=client: self._content_state_for(c.display)
        # log correlation: selkies_tpu.* records emitted while handling
        # this connection carry its session/seat id (obs.logctx filter)
        _logctx.bind(client.id, client.display)
        metrics.set_gauge("selkies_clients", len(self.clients))
        logger.info("client %d connected (%s, %s)", client.id, client.role, raddr)
        if len(self.clients) == 1 and self.settings.run_after_connect:
            self._fire_hook(self.settings.run_after_connect)

        try:
            await ws.send_str("MODE websockets")
            await ws.send_str(self._server_settings_payload())
            # late joiners get the current cursor immediately
            if getattr(self, "_last_cursor_msg", None):
                await ws.send_str(self._last_cursor_msg)
            if rung_q and bool(getattr(self.settings,
                                       "enable_broadcast", False)):
                await self._h_broadcast_view(client, rung_q)
            async for msg in ws:
                if msg.type == WSMsgType.TEXT:
                    await self._on_text(client, msg.data)
                elif msg.type == WSMsgType.BINARY:
                    await self._on_binary(client, msg.data)
                elif msg.type == WSMsgType.ERROR:
                    break
        finally:
            await self._disconnect(client)
        return ws

    async def _disconnect(self, client: ClientConnection) -> None:
        self.clients.pop(client.id, None)
        # a paused client leaving must not strand the depth clamp
        if client.paused:
            self._apply_pipeline_clamp()
        _qoe.registry.unregister(client.qoe)
        self._broadcast_detach(client)
        self._drop_relay_supervision(client)
        for relay in client.relays.values():
            await relay.close()
        client.relays.clear()
        # release held keys/gamepads when the driver seat leaves
        if client.role == "full" and self.input_handler is not None:
            self.input_handler.release_all()
        metrics.set_gauge("selkies_clients", len(self.clients))
        self._maybe_stop_captures()
        logger.info("client %d disconnected", client.id)
        if not self.clients and self.settings.run_after_disconnect:
            self._fire_hook(self.settings.run_after_disconnect)

    def _fire_hook(self, cmd: str) -> None:
        """First-connect / last-disconnect lifecycle hooks (reference
        run_after_connect/disconnect, stream_server.py). Fire-and-forget
        as an independent task: _disconnect runs inside a ws handler that
        is being CANCELLED during connection teardown, so awaiting the
        subprocess there would lose the hook to the cancellation."""
        async def _run():
            try:
                proc = await asyncio.create_subprocess_shell(
                    cmd, stdout=asyncio.subprocess.DEVNULL,
                    stderr=asyncio.subprocess.DEVNULL)
                await proc.wait()
            except OSError as e:
                logger.warning("lifecycle hook failed: %s", e)

        self._spawn_retained(_run())

    # -------------------------------------------------------------- messages
    async def _on_binary(self, client: ClientConnection, data: bytes) -> None:
        if not data:
            return
        if data[0] == P.OP_GZ_CONTROL:
            try:
                await self._on_text(client, P.decompress_control(data))
            except ValueError as e:
                logger.warning("bad 0x05 frame from client %d: %s", client.id, e)
        elif data[0] == P.OP_MIC:
            if self.audio is not None and self.settings.enable_microphone \
                    and client.role == "full":
                self.audio.play_mic_pcm(data[1:])
            elif not getattr(client, "mic_denied_told", False):
                # reference parity (selkies.py MICROPHONE_DISABLED): tell
                # the sender ONCE so its UI can stop the capture instead
                # of streaming into a void
                client.mic_denied_told = True
                try:
                    await client.ws.send_str("MICROPHONE_DISABLED")
                except (ConnectionError, RuntimeError):
                    pass

    async def _on_text(self, client: ClientConnection, text: str) -> None:
        verb = P.parse_verb(text)
        name = verb.name

        # viewer authority gate (reference input_handler.py:110-128)
        if client.role == "viewonly" and name not in P.VIEWER_ALLOWED_PREFIXES:
            return

        handler = {
            "_gz": self._h_gz, "SETTINGS": self._h_settings,
            "CLIENT_FRAME_ACK": self._h_ack,
            "CLIENT_FRAME_TIMING": self._h_frame_timing,
            "CLIENT_CLOCK": self._h_client_clock,
            "CLIENT_STATS": self._h_client_stats,
            "START_VIDEO": self._h_start_video, "STOP_VIDEO": self._h_stop_video,
            "REQUEST_KEYFRAME": self._h_keyframe,
            "START_AUDIO": self._h_start_audio, "STOP_AUDIO": self._h_stop_audio,
            "r": self._h_resize, "s": self._h_dpi,
            "vb": self._h_video_bitrate, "ab": self._h_audio_bitrate,
            "pong": self._h_pong, "_f": self._h_client_fps,
            "_l": self._h_client_latency,
            "SET_NATIVE_CURSOR_RENDERING": self._h_cursor_mode,
            "BROADCAST_VIEW": self._h_broadcast_view,
            "BROADCAST_QOE": self._h_broadcast_qoe,
        }.get(name)
        if handler is not None:
            await handler(client, verb.args)
            return
        if self.input_handler is not None and self.settings.enable_input:
            try:
                await self.input_handler.on_message(text)
            except (ValueError, IndexError, KeyError) as e:
                # malformed verb args must never tear down the WS connection
                # (the reference parses tolerantly; SURVEY §2.3)
                logger.warning("bad input verb from client %d: %r (%s)",
                               client.id, text[:80], e)

    # ---- control verbs ------------------------------------------------------
    async def _h_gz(self, client: ClientConnection, args: str) -> None:
        client.gzip_ok = args.strip() == "1"

    async def _h_settings(self, client: ClientConnection, args: str) -> None:
        # SETTINGS mutates SERVER state (encoder/bitrate/framerate for every
        # client); view-only clients may send the verb (the reference client
        # always does) but must not steer the shared stream — the reference
        # splits per-client display prefs from server settings
        # (selkies.py:1833-2141); here server-side knobs need input authority.
        if client.role != "full":
            await client.send_text_maybe_gz("settings_applied {}")
            return
        try:
            body = json.loads(args)
        except json.JSONDecodeError:
            await client.ws.send_str("ERROR bad SETTINGS payload")
            return
        applied = {}
        for k, v in body.items():
            try:
                applied[k] = self.settings.apply_client_setting(k, v)
            except SettingsError as e:
                logger.info("client %d setting rejected: %s", client.id, e)
        if applied:
            await self._apply_live_settings(applied)
            await client.send_text_maybe_gz(
                "settings_applied " + json.dumps(applied, default=list))

    async def _apply_live_settings(self, applied: dict) -> None:
        for cap in self.captures.values():
            if "framerate" in applied:
                cap.update_framerate(float(applied["framerate"]))
            if "video_bitrate_kbps" in applied:
                cap.update_video_bitrate(int(applied["video_bitrate_kbps"]))
            if "jpeg_quality" in applied or "paint_over_quality" in applied:
                cap.update_tunables(
                    jpeg_quality=self.settings.jpeg_quality,
                    paint_over_quality=self.settings.paint_over_quality)
        # structural changes (encoder, fullcolor) need a capture rebuild;
        # restart joins the capture thread, so it runs in an executor to
        # keep the event loop responsive (SURVEY §7 hard-part #4)
        if {"encoder", "fullcolor"} & set(applied):
            loop = asyncio.get_running_loop()
            for did, cap in self.captures.items():
                if cap.is_capturing():
                    new_settings = self._capture_settings(did)
                    await loop.run_in_executor(
                        None, lambda c=cap, s=new_settings: c.restart(s))
        if "audio_bitrate" in applied and self.audio is not None:
            self.audio.update_bitrate(int(applied["audio_bitrate"]))
        if "audio_red_distance" in applied and self.audio is not None:
            # live regate: the pipeline reads red_distance per frame
            self.audio.red_distance = int(applied["audio_red_distance"])
        if "keyboard_layout" in applied:
            await self._apply_keyboard_layout(str(applied["keyboard_layout"]))
        if applied.get("window_manager"):
            # live WM swap (reference webrtc_mode WM detect/swap).
            # Safelist enforcement lives in the setting's choices= — a
            # rejected value never reaches here. Reuse the long-lived
            # manager so its _wm_name cache invalidates (set_dpi's DE
            # chain reads it) and the DI hook stays honoured.
            await self.display_manager.swap_window_manager(
                str(applied["window_manager"]))

    async def _apply_keyboard_layout(self, layout: str) -> None:
        """Align the X keymap with the client's detected layout
        (reference lib/keyboard-layout.js + server XKB alignment) so
        scancode-reading apps agree with the browser; character input is
        already layout-independent (keysyms + spare-keycode overlay)."""
        if not layout.isalnum() or len(layout) > 8:
            return
        import shutil as _shutil
        if not _shutil.which("setxkbmap"):
            return
        try:
            proc = await asyncio.create_subprocess_exec(
                "setxkbmap", layout,
                env=dict(os.environ, DISPLAY=self.settings.display_id),
                stdout=asyncio.subprocess.DEVNULL,
                stderr=asyncio.subprocess.DEVNULL)
            await proc.communicate()
        except OSError:
            pass

    def _protocol_error(self, client: ClientConnection, kind: str,
                        text: str, exc: Exception) -> None:
        """Malformed client message: count it (by kind) and drop it —
        the receive loop must survive any byte sequence a client can
        produce (ISSUE 7 satellite; the input-verb path already parses
        tolerantly)."""
        metrics.inc_counter("selkies_protocol_errors_total",
                            labels={"kind": kind})
        logger.debug("malformed %s from client %d: %r (%s)",
                     kind, client.id, text[:80], exc)

    async def _h_ack(self, client: ClientConnection, args: str) -> None:
        try:
            acked = int(args)
        except ValueError as e:
            self._protocol_error(client, "client_frame_ack", args, e)
            return
        now = time.monotonic()
        client.last_ack_id = acked
        client.last_ack_time = now
        client.fps_est.tick(now)
        if client.qoe is not None:
            client.qoe.note_ack(acked, now)
        if _tracer.enabled:
            # close the glass-to-glass loop on the frame's timeline
            _tracer.instant(client.display, acked, "ack", lane="ws")
        self._update_backpressure(client)

    async def _h_client_clock(self, client: ClientConnection,
                              args: str) -> None:
        """NTP-style clock exchange (obs.clocksync): ``ping`` gets a
        ``server_clock`` reply stamped with two perf_counter reads;
        ``sample`` feeds the session's offset/drift estimator. The
        server — not the browser — owns estimation."""
        try:
            kind, seq, ts = P.parse_client_clock(args)
        except (ValueError, IndexError) as e:
            self._protocol_error(client, "client_clock", args, e)
            return
        if kind == "ping":
            t1 = time.perf_counter_ns() / 1e6
            head = f"server_clock {seq},{ts[0]:.3f},{t1:.3f},"
            t2 = time.perf_counter_ns() / 1e6   # just before the send
            # remember what we stamped: the eventual sample must echo it
            client.clock_pings[seq] = (float(f"{ts[0]:.3f}"),
                                       float(f"{t1:.3f}"),
                                       float(f"{t2:.3f}"))
            while len(client.clock_pings) > 32:
                client.clock_pings.popitem(last=False)
            try:
                await client.ws.send_str(head + f"{t2:.3f}")
            except (ConnectionError, RuntimeError, OSError):
                pass
        elif client.qoe is not None:
            # t1/t2 are OUR perf_counter stamps: accept them only from a
            # sample that echoes an outstanding ping, or a client could
            # fabricate self-consistent tuples, steer its clock fit to an
            # arbitrary offset, and poison the shared g2g histogram/SLO
            # with fictitious multi-second frames. (t0/t3 stay client-
            # asserted by design — the client owns its own clock.)
            expect = client.clock_pings.pop(seq, None)
            if expect is None or any(abs(a - b) > 0.002
                                     for a, b in zip(expect, ts[:3])):
                self._protocol_error(
                    client, "client_clock", args,
                    ValueError("sample does not echo an outstanding ping"))
                return
            client.qoe.clock.add_sample(*ts)

    async def _h_frame_timing(self, client: ClientConnection,
                              args: str) -> None:
        """Batched per-frame client timing (receive / decode-complete /
        present, client-clock ms): mapped onto the server timebase by
        the session's clock estimator, each report becomes a g2g sample
        (qoe + selkies_session_g2g_ms), a g2g SLO event, and — when
        tracing — a ``client`` lane on the frame's /api/trace timeline
        with the frame envelope extended to true glass-to-glass."""
        try:
            entries = P.parse_frame_timing(args)
        except ValueError as e:
            self._protocol_error(client, "client_frame_timing", args, e)
            return
        if client.qoe is None:
            return
        budget_ms = float(getattr(self.settings, "slo_g2g_ms", 250.0))
        for fid, recv_c, decode_c, present_c in entries:
            m = client.qoe.note_frame_timing(fid, recv_c, decode_c,
                                             present_c)
            if m is None:
                continue            # clock not synced yet
            if m["g2g_ms"] is not None:
                _slo.engine.record("g2g", good=m["g2g_ms"] <= budget_ms)
            if _tracer.enabled:
                self._attach_client_spans(client.display, fid, m)

    @staticmethod
    def _attach_client_spans(display: str, fid: int, m: dict) -> None:
        """Join one mapped timing report onto the frame timeline:
        ``net`` (send -> client receive), ``client.decode``,
        ``client.present`` — the lanes that turn a post-readback bubble
        into attributable stages."""
        def ns(ms: float) -> int:
            return int(ms * 1e6)

        spans = []
        if m["send_ms"] is not None and m["recv_ms"] >= m["send_ms"]:
            spans.append(("net", m["send_ms"], m["recv_ms"]))
        spans.append(("client.decode", m["recv_ms"], m["decode_ms"]))
        spans.append(("client.present", m["decode_ms"], m["present_ms"]))
        for name, a, b in spans:
            _tracer.attach_span(display, fid, name, ns(a),
                                max(0, ns(b) - ns(a)),
                                lane="client", extend_frame=True)

    async def _h_client_stats(self, client: ClientConnection,
                              args: str) -> None:
        """Periodic client-side decoder stats (queue depth, dropped
        decodes, draw fps) — surfaced per session in
        ``/api/sessions?verbose=1`` as the client overload signal."""
        try:
            body = json.loads(args)
            if not isinstance(body, dict):
                raise ValueError("object body required")
        except (ValueError, RecursionError) as e:
            # RecursionError: json.loads on a deeply nested payload
            # ('['*100000) is NOT a ValueError and would tear down the
            # receive loop — exactly what the hardening contract forbids
            self._protocol_error(client, "client_stats", args, e)
            return
        if client.qoe is not None:
            client.qoe.note_client_stats(body)

    def _apply_pipeline_clamp(self) -> None:
        """Relay-backpressure clamp on the deep pipeline (ROADMAP 2):
        while any client of a display is paused, its capture runs at
        depth 1 — frames in flight would just age in the relay queue of
        a stalled wire, costing glass-to-glass latency and HBM for
        nothing. Lifted the moment no viewer is paused."""
        paused = {c.display for c in self.clients.values() if c.paused}
        for did, cap in self.captures.items():
            clamp_fn = getattr(cap, "set_pipeline_clamp", None)
            if clamp_fn is None:
                continue
            clamped = did in paused or (did == "__seats__" and paused)
            clamp_fn(1 if clamped else None)

    def _update_backpressure(self, client: ClientConnection) -> None:
        """Desync window scales with measured client fps; RTT forgiveness is
        capped upstream by the ACK cadence itself (reference
        selkies.py:1590-1717)."""
        dist = P.frame_id_distance(client.last_sent_id, client.last_ack_id)
        window = max(10, int(client.fps_est.fps() *
                             self.settings.ack_desync_frames / 60.0))
        if not client.paused and dist > window:
            client.paused = True
            self._apply_pipeline_clamp()
            metrics.inc_counter("selkies_backpressure_events_total")
            now = time.monotonic()
            if client.qoe is not None:
                client.qoe.backpressure_begin(now)
            # one INFO line per window; flapping windows within the
            # rate-limit interval are summarised, never one-per-frame
            if now - client._bp_last_log >= BACKPRESSURE_LOG_EVERY_S:
                suffix = (f" ({client._bp_suppressed} windows suppressed)"
                          if client._bp_suppressed else "")
                logger.info("client %d backpressured (dist %d > %d)%s",
                            client.id, dist, window, suffix)
                client._bp_last_log = now
                client._bp_suppressed = 0
            else:
                client._bp_suppressed += 1
                logger.debug("client %d backpressured (dist %d > %d)",
                             client.id, dist, window)
        elif client.paused:
            # Resume when the client caught up with everything queued — the
            # relay drained (dropped frames never get ACKed, so distance to
            # last_sent_id alone could deadlock the pause).
            drained = all(r.drained() for r in client.relays.values())
            if dist < window // 2 or drained:
                client.paused = False
                self._apply_pipeline_clamp()
                if client.qoe is not None:
                    dur = client.qoe.backpressure_end(time.monotonic())
                    if dur is not None:
                        logger.debug("client %d backpressure window "
                                     "closed after %.3fs", client.id, dur)
                # refresh only the displays this client actually views
                for did in client.relays:
                    self._request_idr(did)

    async def _h_start_video(self, client: ClientConnection, args: str) -> None:
        client.video_active = True
        if client.qoe is not None:
            client.qoe.video_active = True
        # each client views ONE display (its ?display= pin); multi-seat
        # clients on different seats share the single sharded capture
        did = client.display
        if did not in client.relays:
            self._make_relay(client, did)
        self._ensure_capture(did)
        # fresh joiner needs a full frame — of ITS display only (an IDR
        # on every capture would storm unrelated displays/seats)
        self._request_idr(did)
        await client.ws.send_str("VIDEO_STARTED")

    async def _h_stop_video(self, client: ClientConnection, args: str) -> None:
        client.video_active = False
        if client.qoe is not None:
            client.qoe.video_active = False
        self._drop_relay_supervision(client)
        for relay in client.relays.values():
            await relay.close()
        client.relays.clear()
        self._maybe_stop_captures()
        await client.ws.send_str("VIDEO_STOPPED")

    def _make_relay(self, client: ClientConnection, did: str) -> None:
        """Build (or rebuild) the client's video relay, supervised: a
        relay death (stalled/failed media send) reports to the restart
        engine, which re-offers a FRESH relay on the same client after
        backoff — with an IDR request so every stripe row's decode chain
        restarts clean. The dead relay's socket contract holds: the ws
        itself is only reused because the chain gate + IDR resync make a
        torn frame recoverable at the codec layer; a socket the CLIENT
        side tore down just fails the first send and feeds the policy
        until the budget parks it (or the client reconnects)."""
        sup = self._supervisor()
        on_dead = None
        if sup is not None:
            comp = f"relay:{client.id}:{did}"

            def _reoffer(c=client, d=did, comp=comp):
                if c.id not in self.clients or not c.video_active:
                    sup.drop(comp)
                    return
                old = c.relays.get(d)
                if old is not None and not old.dead:
                    return
                self._make_relay(c, d)
                # the fresh relay starts every H.264 row gated shut; a
                # keyframe reopens them (and repaints JPEG viewers)
                self._request_idr(d)
                logger.info("relay for client %d display %s re-offered",
                            c.id, d)

            sup.adopt(comp, _reoffer)

            def on_dead(comp=comp):
                sup.report_death(comp, "media send stalled/failed")

        relay = VideoRelay(
            client.ws.send_bytes,
            budget_bytes=int(self.settings.video_relay_budget_s
                             * self.settings.video_bitrate_kbps * 125),
            request_idr=lambda d=did: self._request_idr(d),
            on_dead=on_dead,
            display=did)
        relay.start()
        client.relays[did] = relay

    def _drop_relay_supervision(self, client: ClientConnection) -> None:
        sup = self._supervisor()
        if sup is not None:
            for did in client.relays:
                sup.drop(f"relay:{client.id}:{did}")

    def _request_idr(self, display_id: str) -> None:
        cap = self.captures.get(display_id) \
            or self.captures.get("__seats__")
        if cap:
            cap.request_idr_frame()

    async def _h_keyframe(self, client: ClientConnection, args: str) -> None:
        # only the requesting client's display: REQUEST_KEYFRAME from one
        # viewer must not IDR-storm every capture (VERDICT r3 weak 7)
        self._request_idr(client.display)

    # ---------------------------------------------- broadcast plane (ISSUE 17)
    def _broadcast_state(self) -> dict:
        """Lazy broadcast-plane state: the desktop's rendition ladder
        plus the viewer registry routing clients onto its rungs."""
        st = getattr(self, "_bcast_state", None)
        if st is None:
            from ..broadcast.ladder import ladder_from_settings
            from ..broadcast.registry import ViewerRegistry
            ladder = ladder_from_settings(self.settings)
            reg = ViewerRegistry(
                ladder, source=self._default_display(),
                label_cap=int(getattr(self.settings,
                                      "qoe_seat_label_cap", 8)),
                on_switch=self._on_broadcast_switch)
            st = {"ladder": ladder, "registry": reg, "clients": {}}
            self._bcast_state = st
        return st

    def _rung_display(self, rend) -> str:
        """Display id carrying a rung's capture. The source rung rides
        the desktop's own capture; downscaled rungs get derived display
        ids (``:0@mid``) so ``_ensure_capture`` builds them through the
        exact same capture/step factories as any seat — the rendition
        encode surface is the lattice's, not a new one."""
        base = self._default_display()
        if rend.downscale <= 1:
            return base
        did = f"{base}@{rend.name}"
        self.display_geometry.setdefault(did, (rend.width, rend.height))
        return did

    def _on_broadcast_switch(self, state, old: int, new: int) -> None:
        """ViewerRegistry on_switch hook (sync, called outside its
        lock): re-key the viewer's relay onto the new rung, IDR first
        frame. Registry already counted the idr_resync."""
        st = self._broadcast_state()
        client = st["clients"].get(state.sid)
        if client is None:
            return
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return                    # no loop: sync test rigs route only
        self._spawn_retained(
            self._apply_broadcast_rung(client, st["ladder"].rung(new)),
            "broadcast_switch")

    async def _apply_broadcast_rung(self, client: ClientConnection,
                                    rend) -> None:
        """Move a viewer's relay onto a rung's capture. Every switch is
        IDR-resynced: the new rung's chain gates start shut and only a
        keyframe reopens them, so the first delivered frame is a clean
        decoder entry point."""
        did = self._rung_display(rend)
        old_did = client.display
        if client.qoe is not None:
            client.qoe.rung = rend.name
        if did == old_did and did in client.relays:
            self._request_idr(did)
            return
        client.display = did
        old = client.relays.pop(old_did, None)
        if old is not None:
            sup = self._supervisor()
            if sup is not None:
                sup.drop(f"relay:{client.id}:{old_did}")
            await old.close()
        if did not in client.relays:
            self._make_relay(client, did)
        if client.video_active:
            self._ensure_capture(did)
            self._request_idr(did)
        self._maybe_stop_captures()

    async def _h_broadcast_view(self, client: ClientConnection,
                                args: str) -> None:
        """``BROADCAST_VIEW[,rung]``: attach this client as a broadcast
        viewer on a ladder rung (default: the source rung)."""
        if not bool(getattr(self.settings, "enable_broadcast", False)):
            await client.ws.send_str("BROADCAST_DISABLED")
            return
        st = self._broadcast_state()
        ladder = st["ladder"]
        name = (args or "").strip().partition(",")[0]
        idx = ladder.index_of(name) if name else 0
        st["clients"][str(client.id)] = client
        state = st["registry"].attach(str(client.id), rung=idx)
        rend = ladder.rung(state.rung)
        await self._apply_broadcast_rung(client, rend)
        st["registry"].export_metrics()
        await client.ws.send_str(f"BROADCAST_RUNG,{rend.name}")

    async def _h_broadcast_qoe(self, client: ClientConnection,
                               args: str) -> None:
        """``BROADCAST_QOE,<score 0-100>``: the viewer's QoE verdict.
        Ladder-per-session routing with dwell hysteresis; a landed
        switch re-keys the relay and IDR-resyncs (on_switch hook)."""
        st = getattr(self, "_bcast_state", None)
        if st is None or str(client.id) not in st["clients"]:
            return
        try:
            score = float((args or "").partition(",")[0])
        except ValueError:
            return
        content = self._content_state_for(
            self._default_display()).get("class")
        st["registry"].route(str(client.id), score=score,
                             content_class=content)
        st["registry"].export_metrics()

    def _broadcast_detach(self, client: ClientConnection) -> None:
        st = getattr(self, "_bcast_state", None)
        if st is not None \
                and st["clients"].pop(str(client.id), None) is not None:
            st["registry"].detach(str(client.id))
            st["registry"].export_metrics()

    async def _h_start_audio(self, client: ClientConnection, args: str) -> None:
        if self.audio is None or not self.settings.enable_audio:
            await client.ws.send_str("AUDIO_DISABLED")
            return
        client.audio_active = True
        self.audio.add_listener(client)

    async def _h_stop_audio(self, client: ClientConnection, args: str) -> None:
        client.audio_active = False
        if self.audio is not None:
            self.audio.remove_listener(client)

    async def _h_resize(self, client: ClientConnection, args: str) -> None:
        if not self.settings.enable_resize:
            return
        try:
            w, h = (int(v) for v in args.lower().split("x"))
        except ValueError:
            return
        # resize the CLIENT'S display, never a phantom entry; in multi-seat
        # mode the sharded capture is shared, so every seat resizes together
        did = client.display
        if did not in self.display_geometry and self.display_geometry:
            did = self._default_display()
        geo = (max(64, min(w, 16384)), max(64, min(h, 16384)))
        if self._seats > 1:
            for seat_did in self.display_geometry:
                self.display_geometry[seat_did] = geo
        else:
            self.display_geometry[did] = geo
        # resize the REAL X screen first (CVT-RB modeline via xrandr,
        # reference display_utils.py:223-1076); headless setups skip this
        # and only the capture geometry changes. With an extended desktop
        # the union layout drives the framebuffer instead of one display.
        multi = self._seats == 1 and len(self.display_geometry) > 1
        if multi:
            self._apply_display_layout()
        elif self.display_manager is not None \
                and self.display_manager.available():
            await self.display_manager.resize(*geo,
                                              float(self.settings.framerate))
        # retarget EVERY display's capture: a layout pass moves the OTHER
        # displays' origins too (their sub-rects shift when this one grows)
        targets = [did] if not multi else list(self.display_geometry)
        if self._seats > 1:
            targets = ["__seats__"]
        loop = asyncio.get_running_loop()
        for tdid in targets:
            cap = self.captures.get(tdid)
            if not (cap and cap.is_capturing()):
                continue
            tgeo = geo if tdid in (did, "__seats__") \
                else self.display_geometry[tdid]
            ox, oy = self.display_offsets.get(tdid, (0, 0))
            # size change rebuilds the capture session (joins a thread):
            # never on the event loop
            await loop.run_in_executor(
                None, lambda c=cap, o=(ox, oy), g=tgeo:
                c.update_capture_region(o[0], o[1], *g))
        # broadcast realized geometry (bounded sends)
        await self._broadcast_control(self._server_settings_payload())

    async def _h_dpi(self, client: ClientConnection, args: str) -> None:
        try:
            dpi = self.settings.apply_client_setting("dpi", int(args))
        except (SettingsError, ValueError):
            return
        if self.display_manager is not None \
                and self.display_manager.available():
            await self.display_manager.set_dpi(int(dpi))

    async def _h_video_bitrate(self, client: ClientConnection, args: str) -> None:
        try:
            kbps = int(args)
        except ValueError:
            return
        try:
            self.settings.apply_client_setting("video_bitrate_kbps", kbps)
        except SettingsError:
            return
        for cap in self.captures.values():
            cap.update_video_bitrate(kbps)

    async def _h_audio_bitrate(self, client: ClientConnection, args: str) -> None:
        if self.audio is None:
            return
        try:
            self.audio.update_bitrate(int(args))
        except ValueError:
            pass

    async def _h_pong(self, client: ClientConnection, args: str) -> None:
        pass

    async def _h_client_fps(self, client: ClientConnection, args: str) -> None:
        try:
            client.reported_fps = float(args)
            if client.qoe is not None:
                client.qoe.reported_fps = client.reported_fps
            metrics.set_gauge("selkies_fps", client.reported_fps,
                              {"client": str(client.id)})
            metrics.observe_hist("selkies_fps_hist", client.reported_fps)
        except ValueError:
            pass

    async def _h_client_latency(self, client: ClientConnection, args: str) -> None:
        try:
            client.reported_latency_ms = float(args)
            metrics.set_gauge("selkies_latency_ms", client.reported_latency_ms,
                              {"client": str(client.id)})
        except ValueError:
            pass

    async def _h_cursor_mode(self, client: ClientConnection, args: str) -> None:
        pass  # cursor streaming lands with the cursor monitor

    # ----------------------------------------------------------------- stats
    async def _stats_loop(self) -> None:
        """Periodic per-client system stats (reference selkies.py:4586-4722)."""
        import psutil
        while self._running:
            await asyncio.sleep(self.settings.stats_interval_s)
            stalled = time.monotonic() - ACK_STALL_S
            for c in list(self.clients.values()):
                # ACK stall forces backpressure (reference 4 s rule)
                if c.video_active and not c.paused \
                        and c.last_sent_id != c.last_ack_id \
                        and c.last_ack_time < stalled:
                    c.paused = True
                    self._apply_pipeline_clamp()
                    metrics.inc_counter("selkies_backpressure_events_total")
                    if c.qoe is not None:
                        c.qoe.note_stall()
                        c.qoe.backpressure_begin(time.monotonic())
                    _health.engine.recorder.record(
                        "ack_stall", client=c.id, display=c.display,
                        last_sent=c.last_sent_id, last_ack=c.last_ack_id)
            # SLO event feed (obs.slo): one fps + one qoe good/bad event
            # per active session per tick. g2g events arrive per frame
            # from _h_frame_timing; these two close the objective set.
            target = float(self.settings.framerate)
            now_m = time.monotonic()
            idle_after = 2.0 * float(self.settings.stats_interval_s)
            for c in list(self.clients.values()):
                if not c.video_active or c.qoe is None:
                    continue
                # idle gate: damage gating means a static desktop
                # legitimately delivers no frames — fps 0 / score 0 on a
                # session we offered nothing is not a broken promise,
                # and recording it bad would burn the budget while
                # perfectly healthy
                last = c.qoe.last_send_mono
                if last is None or now_m - last > idle_after:
                    continue
                fps = c.qoe.client_fps()
                if fps is not None and target > 0:
                    _slo.engine.record("fps", good=fps >= target * 0.5)
                score = c.qoe.score()
                if score is not None:
                    _slo.engine.record(
                        "qoe", good=score >= _qoe.registry.degraded_score)
            try:
                stats = {
                    "type": "system_stats",
                    "cpu_percent": psutil.cpu_percent(),
                    "mem_percent": psutil.virtual_memory().percent,
                    "clients": len(self.clients),
                    "encoded_fps": {
                        did: cap.encoded_fps
                        for did, cap in self.captures.items()},
                    # TPU/accelerator telemetry (gpu_stats.py equivalent);
                    # executor: device queries must not stall the loop
                    "devices": await asyncio.get_running_loop()
                    .run_in_executor(None, metrics.device_stats),
                }
                await self._broadcast_control("system_stats " + json.dumps(stats))
                # vendor-spanning GPU chain (reference selkies.py:4586+
                # gpu_stats messages); separate verb so clients with no
                # GPU interest skip the parse
                from . import gpu_stats as _gs
                gpus = await asyncio.get_running_loop().run_in_executor(
                    None, _gs.gpu_stats_payload)
                if gpus:
                    await self._broadcast_control(
                        "gpu_stats " + json.dumps({"gpus": gpus}))
                if self.settings.stats_csv_path:
                    await asyncio.get_running_loop().run_in_executor(
                        None, self._append_stats_csv, stats)
                if self._rec_buf:
                    buf, self._rec_buf = self._rec_buf, bytearray()
                    await asyncio.get_running_loop().run_in_executor(
                        None, self._flush_recording, buf)
            except Exception:
                logger.exception("stats loop error")

    def _append_stats_csv(self, stats: dict) -> None:
        """Schema-stable CSV stats dump (reference webrtc_utils.py:958-1259
        role)."""
        import csv
        path = self.settings.stats_csv_path
        row = {
            "ts": round(time.time(), 3),
            "cpu_percent": stats.get("cpu_percent"),
            "mem_percent": stats.get("mem_percent"),
            "clients": stats.get("clients"),
            "encoded_fps": ";".join(
                f"{k}={v:.1f}" for k, v in stats.get("encoded_fps", {}).items()),
        }
        try:
            new = not os.path.exists(path)
            with open(path, "a", newline="") as f:
                w = csv.DictWriter(f, fieldnames=list(row))
                if new:
                    w.writeheader()
                w.writerow(row)
        except OSError as e:
            logger.warning("stats csv failed: %s; disabling", e)
            self.settings.set_server("stats_csv_path", "")
