"""Declarative settings system.

Re-designs the reference's settings layer (src/selkies/settings.py:12-27
precedence rules, 62-912 definitions, 914-930 sensitive-name redaction,
1271-1398 client payload + sanitization) as a typed, testable module:

- One declarative list ``SETTING_DEFINITIONS`` drives argparse flags, env
  parsing, the client-visible settings payload, and per-setting locking.
- Precedence: CLI flag > ``SELKIES_<NAME>`` env > fallback env names > default.
- A string value may carry a ``|locked`` suffix to pin it against client
  writes; numeric range settings may be locked to a sub-range with
  ``lo-hi`` syntax (``60-60`` pins the value) — reference settings.py:12-27.
- Sensitive names are redacted from any dump (reference settings.py:914-930).
- ``build_client_settings_payload()`` emits the JSON the client UI consumes;
  ``sanitize_client_setting()`` validates every client write server-side
  (reference settings.py:1271-1398).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
import sys
from typing import Any, Mapping, Sequence


class SType(enum.Enum):
    BOOL = "bool"
    INT = "int"
    FLOAT = "float"
    STR = "str"
    ENUM = "enum"
    LIST = "list"  # comma-separated list of strings


@dataclasses.dataclass(frozen=True)
class Setting:
    """One declarative setting definition.

    ``client=True`` settings appear in the client settings payload and may be
    written by clients (subject to lock state and sanitisation).
    """

    name: str
    stype: SType
    default: Any
    help: str = ""
    choices: tuple[str, ...] | None = None  # ENUM only
    vmin: float | None = None  # INT/FLOAT range
    vmax: float | None = None
    client: bool = False
    sensitive: bool = False
    fallback_env: tuple[str, ...] = ()

    def env_name(self) -> str:
        return "SELKIES_" + self.name.upper()


def _s(name, stype, default, help="", **kw) -> Setting:
    return Setting(name=name, stype=stype, default=default, help=help, **kw)


# ---------------------------------------------------------------------------
# The definitions. Grouped as in the reference (settings.py:62-912). This is
# the single source of truth: argparse, env, client payload, docs all derive
# from this list.
# ---------------------------------------------------------------------------
SETTING_DEFINITIONS: tuple[Setting, ...] = (
    # --- process / mode -----------------------------------------------------
    _s("mode", SType.ENUM, "websockets", "Streaming transport to start.",
       choices=("websockets", "webrtc")),
    _s("enable_dual_mode", SType.BOOL, False,
       "Allow live switching between transports via /api/switch."),
    _s("addr", SType.STR, "0.0.0.0", "Bind address for the single-port server."),
    _s("port", SType.INT, 8080, "Bind port.", vmin=1, vmax=65535),
    _s("fleet_url", SType.STR, "",
       "Routable base URL this host advertises in fleet heartbeats "
       "(/api/fleet). Empty: derived from addr:port, falling back to "
       "the hostname when bound to 0.0.0.0 — set explicitly behind "
       "NAT or when the gateway reaches hosts on another network."),
    _s("fleet_gateway", SType.STR, "",
       "Fleet gateway base URL (e.g. http://gw:8100). Non-empty: the "
       "server core runs a supervised push loop POSTing heartbeats to "
       "<gateway>/fleet/heartbeat with exponential backoff on gateway "
       "loss. Empty: push loop disabled (pull-only /api/fleet stays)."),
    _s("fleet_token", SType.STR, "",
       "Bearer token presented on fleet heartbeat pushes (must match "
       "the gateway's --token).", sensitive=True),
    _s("fleet_push_interval_s", SType.FLOAT, 2.0,
       "Heartbeat push period in seconds (the gateway treats silence "
       "past its host_timeout as host death; keep this well under it).",
       vmin=0.05, vmax=300.0),
    _s("debug", SType.BOOL, False, "Verbose logging."),
    _s("app_name", SType.STR, "selkies-tpu", "Display name for the client UI."),
    _s("app_ready_file", SType.STR, "",
       "Optional sidecar file polled before serving (reference __main__.py:20-26)."),

    # --- auth ---------------------------------------------------------------
    _s("enable_basic_auth", SType.BOOL, False, "HTTP basic auth toggle."),
    _s("basic_auth_user", SType.STR, "", "Basic auth username."),
    _s("basic_auth_password", SType.STR, "", "Basic auth password.", sensitive=True),
    _s("viewonly_password", SType.STR, "",
       "Secondary password granting view-only access.", sensitive=True),
    _s("master_token", SType.STR, "",
       "Bearer token with full API access (timing-safe compare).", sensitive=True),
    _s("enable_sharing", SType.BOOL, True, "Allow >1 concurrent client."),
    _s("enable_collab", SType.BOOL, False,
       "Allow non-primary clients input authority (collaborator role)."),
    _s("secure_api", SType.BOOL, False,
       "Secure token mode: clients must present a minted token (reference selkies.py:2147-2200)."),
    _s("allowed_ws_origins", SType.LIST, "",
       "Origin allow-list for WS upgrades; empty = same-host only."),

    # --- TLS ----------------------------------------------------------------
    _s("enable_https", SType.BOOL, False, "Serve TLS."),
    _s("https_cert", SType.STR, "", "Path to TLS certificate (hot-reloaded)."),
    _s("https_key", SType.STR, "", "Path to TLS key.", sensitive=True),

    # --- video --------------------------------------------------------------
    _s("encoder", SType.ENUM, "jpeg-tpu",
       "Video encoder backend; all transforms + entropy coding run on the "
       "TPU. h264-tpu = one stream per display; h264-tpu-striped = one "
       "independent stream per stripe row (reference h264enc-striped).",
       choices=("jpeg-tpu", "h264-tpu", "h264-tpu-striped"),
       client=True),
    _s("framerate", SType.INT, 60, "Target capture/encode fps.", vmin=8, vmax=240,
       client=True),
    _s("video_bitrate_kbps", SType.INT, 8000, "CBR target bitrate (kbps).",
       vmin=100, vmax=1_000_000, client=True),
    _s("video_crf", SType.INT, 25, "Constant-rate-factor quality (lower=better).",
       vmin=5, vmax=50, client=True),
    _s("use_cbr", SType.BOOL, False,
       "CBR rate control on the WS path: per-frame leaky-bucket qp "
       "steering toward video_bitrate_kbps (webrtc mode is always CBR).",
       client=True),
    _s("video_min_qp", SType.INT, 10, "QP floor for rate control.", vmin=0, vmax=51),
    _s("video_max_qp", SType.INT, 35,
       "QP ceiling; reference measured +19dB PSNR at 2.5x bitrate with 35 "
       "(settings.py:177-183).", vmin=0, vmax=51),
    _s("keyframe_interval_s", SType.FLOAT, 10.0,
       "Forced IDR cadence in seconds; <=0 disables.", vmin=-1, vmax=600),
    _s("fullcolor", SType.BOOL, False, "4:4:4 chroma (else 4:2:0).", client=True),
    _s("stripe_height", SType.INT, 64,
       "Row-stripe height in px for intra-frame parallel encode "
       "(reference striped encoding, SURVEY §2.5).", vmin=16, vmax=1088),
    _s("pipeline_depth", SType.INT, 2,
       "Frames in flight between device dispatch and delivery (deep "
       "pipeline, ROADMAP 2). 1 = frame-serial; >=2 overlaps frame N+1's "
       "jitted step with frame N's readback/packetize on a finalizer "
       "thread. Clamped to 1 at runtime while a client is backpressured, "
       "and by the degradation ladder's rung-0 'pipeline' action.",
       vmin=1, vmax=8),
    _s("stripe_streaming", SType.BOOL, True,
       "Ship each stripe's bytes as its readback lands (per-stripe "
       "device fetch) instead of waiting on the frame barrier — client "
       "first-stripe receive decouples from frame-complete."),
    _s("h264_motion_vrange", SType.INT, 24,
       "H.264 inter motion search: dense vertical scroll candidates up to "
       "this many px (0 disables motion search).", vmin=0, vmax=64),
    _s("h264_motion_hrange", SType.INT, 8,
       "H.264 inter motion search: power-of-two horizontal pan candidates "
       "up to this many px.", vmin=0, vmax=64),
    _s("use_paint_over", SType.BOOL, True,
       "Re-encode static scenes at higher quality after damage settles "
       "(reference settings.py:560-585)."),
    _s("paint_over_quality", SType.INT, 90, "JPEG quality / h264 QP boost for paint-over.",
       vmin=1, vmax=100, client=True),
    _s("jpeg_quality", SType.INT, 60, "Baseline JPEG quality for motion frames.",
       vmin=1, vmax=100, client=True),
    _s("use_damage_gating", SType.BOOL, True,
       "Only encode stripes whose content changed (device-side diff)."),
    _s("h264_partial_encode", SType.BOOL, True,
       "Damage-proportional P encode (ROADMAP 4): dispatch the device "
       "step only over the MB-row band intersecting the damage map; "
       "clean rows of delivered stripes ship as host-precomputed "
       "all-skip slices and idle frames skip the device entirely. "
       "Requires use_damage_gating."),
    _s("h264_content_adaptive", SType.BOOL, True,
       "Classify each session's content (static/scroll/video/gaming) "
       "from damage-plane signals and apply the matching rate-control "
       "profile (qp bias, band bucket floor, IDR cadence) — "
       "engine/content.py; class + dirty fraction surface in "
       "/api/sessions and the selkies_session_* gauges."),
    _s("h264_roi_qp", SType.BOOL, False,
       "ROI QP: per-macroblock QP plane derived from the damage map — "
       "freshly-damaged regions sharpen by h264_roi_qp_bias below the "
       "frame qp, coded as real mb_qp_delta syntax (4:2:0 P frames)."),
    _s("h264_roi_qp_bias", SType.INT, 4,
       "QP sharpening applied to freshly-damaged macroblocks when "
       "h264_roi_qp is on.", vmin=0, vmax=12),
    _s("enable_broadcast", SType.BOOL, False,
       "Broadcast plane (ROADMAP 3): encode this desktop at a rendition "
       "ladder and let the fleet gateway fan each rung out to relay-only "
       "viewers; rung signatures prewarm through the standard lattice."),
    _s("broadcast_renditions", SType.INT, 3,
       "Rendition ladder rungs per broadcast desktop (src/mid/low); "
       "device work per frame is bounded by this count, never by the "
       "viewer count.", vmin=1, vmax=3),
    _s("watermark_path", SType.STR, "", "PNG burned into the framebuffer on device."),
    _s("watermark_location", SType.INT, 6, "0-6 anchor enum (reference parity).",
       vmin=0, vmax=6),

    # --- display ------------------------------------------------------------
    _s("display_id", SType.STR, ":0", "X display / seat identifier."),
    _s("wayland", SType.BOOL, False,
       "Capture/inject via a Wayland compositor instead of X11 "
       "(reference settings.py:615-620; needs wayland_host_display or "
       "$WAYLAND_DISPLAY pointing at a headless compositor)."),
    _s("wayland_host_display", SType.STR, "",
       "Wayland socket of the EXTERNAL compositor to capture by "
       "screencopy and inject into (reference settings.py:636-638); "
       "empty uses $WAYLAND_DISPLAY."),
    _s("app_wayland_display", SType.STR, "",
       "Wayland socket where APPS run when it differs from the capture "
       "compositor (reference settings.py:622-626); the input/clipboard "
       "target. Empty follows wayland_host_display."),
    _s("wayland_compositor", SType.STR, "",
       "Command for OWN-compositor mode when no external socket is "
       "alive (reference stream_server.py:420-447 "
       "ensure_wayland_display); empty probes labwc/sway/cage/weston "
       "with the wlroots headless backend."),
    _s("webrtc_media_ip", SType.STR, "",
       "IP advertised as the ICE-lite media candidate (empty = "
       "auto-detect the outbound-route address; the reference's "
       "webrtc_public_ip NAT1TO1 analog)."),
    _s("initial_width", SType.INT, 1920, "Initial framebuffer width.", vmin=64, vmax=16384),
    _s("initial_height", SType.INT, 1080, "Initial framebuffer height.", vmin=64, vmax=16384),
    _s("enable_resize", SType.BOOL, True, "Clients may resize the remote display.",
       client=True),
    _s("keyboard_layout", SType.STR, "us",
       "XKB layout aligned to the client's detected keyboard "
       "(client-writable; applied via setxkbmap when X is live).",
       client=True),
    _s("window_manager", SType.STR, "",
       "Live window-manager swap: exec'd with --replace (reference "
       "display_utils.py WM detect/swap). Safelisted at the settings "
       "layer — a client-writable exec must never run arbitrary "
       "binaries. Empty keeps the running WM.",
       choices=("", "xfwm4", "openbox", "mutter", "kwin_x11", "i3",
                "twm", "fluxbox", "icewm", "marco", "metacity"),
       client=True),
    _s("display2_position", SType.STR, "right",
       "Where display2 extends the desktop relative to the primary.",
       choices=("right", "left", "above", "below"), client=True),
    _s("max_displays", SType.INT, 2, "Maximum concurrent displays per seat.",
       vmin=1, vmax=4),
    _s("dpi", SType.INT, 96, "Initial DPI.", vmin=48, vmax=384, client=True),
    _s("cursor_size", SType.INT, 24, "Pointer size in px.", vmin=8, vmax=128),
    _s("enable_cursors", SType.BOOL, True, "Stream cursor image updates."),
    _s("native_cursor_rendering", SType.BOOL, True,
       "Client renders cursor locally from cursor messages.", client=True),

    # --- audio --------------------------------------------------------------
    _s("enable_audio", SType.BOOL, True, "Capture+stream Opus audio.", client=True),
    _s("audio_bitrate", SType.INT, 128000, "Opus bitrate (bps).",
       vmin=6000, vmax=510000, client=True),
    _s("audio_frame_ms", SType.FLOAT, 10.0, "Opus frame duration (ms).",
       vmin=2.5, vmax=60.0),
    _s("audio_channels", SType.INT, 2, "Capture channels.", vmin=1, vmax=8),
    _s("audio_red_distance", SType.INT, 2,
       "Opus RED (RFC 2198) redundancy depth; client-writable so a "
       "RED-incapable client can zero it — the all-clients-capable "
       "regate (reference selkies.py:949-973).", vmin=0, vmax=4,
       client=True),
    _s("audio_backpressure_queue", SType.INT, 120,
       "Max queued audio chunks per client before drop (reference settings.py:899-905)."),
    _s("enable_microphone", SType.BOOL, True, "Accept client mic and play back."),

    # --- input --------------------------------------------------------------
    _s("enable_input", SType.BOOL, True, "Inject keyboard/mouse input."),
    _s("enable_gamepad", SType.BOOL, True, "Virtual gamepad support."),
    _s("enable_clipboard", SType.ENUM, "both",
       "Clipboard sync direction.", choices=("both", "in", "out", "none"),
       client=True),
    _s("clipboard_max_bytes", SType.INT, 64 * 1024 * 1024,
       "Multipart clipboard transfer cap (reference parity 64MiB)."),
    _s("enable_command_verb", SType.BOOL, False,
       "Allow the cmd,<shell> verb (opt-in, dangerous)."),
    _s("enable_binary_clipboard", SType.BOOL, True, "Allow image/binary clipboard."),

    # --- file transfer ------------------------------------------------------
    _s("enable_file_transfer", SType.BOOL, True, "Uploads/downloads."),
    _s("file_transfers", SType.STR, "upload,download",
       "Allowed transfer directions (comma-separated 'upload,download'; "
       "'' or 'none' disables — reference settings.py file_transfers)."),
    _s("viewonly_file_transfers", SType.STR, "",
       "Transfer directions additionally allowed for the view-only role "
       "(default: none — view-only sessions get 403 on /api/files/* "
       "and uploads)."),
    _s("file_transfer_dir", SType.STR, "~/Desktop",
       "Root directory for uploads and the download index."),
    _s("upload_chunk_bytes", SType.INT, 64 * 1024 * 1024, "Max upload slice size."),

    # --- network / relays ---------------------------------------------------
    _s("video_relay_budget_s", SType.FLOAT, 2.0,
       "Per-client video queue budget in seconds of stream bitrate "
       "(reference selkies.py:89-101)."),
    _s("video_relay_floor_bytes", SType.INT, 4 * 1024 * 1024,
       "Relay budget floor (4 MiB reference floor)."),
    _s("ack_desync_frames", SType.INT, 30,
       "Backpressure trigger distance in frames, scaled by measured client fps."),
    _s("reconnect_grace_s", SType.FLOAT, 3.0,
       "Keep capture warm across client reconnects (reference selkies.py:827-830)."),

    # --- TPU ----------------------------------------------------------------
    _s("tpu_seats", SType.INT, 1,
       "Concurrent desktop seats encoded over the device mesh (one per device).",
       vmin=1, vmax=256),
    _s("tpu_stripe_devices", SType.INT, 1,
       "Devices to shard a single frame's stripes across (sequence-parallel analog).",
       vmin=1, vmax=64),
    _s("tpu_precision", SType.ENUM, "int32", "Transform arithmetic precision.",
       choices=("int32", "bf16-preview")),

    # --- webrtc (opt-in transport) ------------------------------------------
    _s("turn_host", SType.STR, "", "TURN server host."),
    _s("turn_port", SType.INT, 3478, "TURN server port."),
    _s("turn_username", SType.STR, "", "Legacy TURN username."),
    _s("turn_password", SType.STR, "", "Legacy TURN password.", sensitive=True),
    _s("turn_shared_secret", SType.STR, "", "HMAC TURN shared secret.", sensitive=True),
    _s("turn_rest_uri", SType.STR, "", "TURN REST API endpoint."),
    _s("rtc_config_file", SType.STR, "",
       "Trusted JSON ICE-server file; watched for changes and pushed "
       "to clients (reference RTCConfigFileMonitor)."),
    _s("cloudflare_turn_key_id", SType.STR, "",
       "Cloudflare Calls TURN key id (reference "
       "webrtc_utils.py:298-352)."),
    _s("cloudflare_turn_api_token", SType.STR, "",
       "Cloudflare Calls API bearer token.", sensitive=True),
    _s("webrtc_public_ip", SType.STR, "", "NAT1TO1 public IP substitution."),

    # --- recording / agent APIs ---------------------------------------------
    _s("recording_path", SType.STR, "",
       "Append the primary display's encoded stream here (raw Annex-B for "
       "h264, concatenated JFIF/MJPEG for jpeg) — the out-of-band recording "
       "tap (reference settings.py:640-645)."),
    _s("stats_csv_path", SType.STR, "",
       "Append periodic system/encode stats rows as CSV "
       "(reference webrtc_utils.py:958-1259 stats dump)."),
    _s("enable_computer_use", SType.BOOL, False,
       "HTTP agent API: GET /api/screenshot, POST /api/computer_use "
       "(reference pixelflux start_computer_use, __main__.py:38-43)."),

    # --- lifecycle hooks ----------------------------------------------------
    _s("run_after_connect", SType.STR, "",
       "Shell command spawned when the FIRST client connects "
       "(reference stream_server.py run_after_connect hook)."),
    _s("run_after_disconnect", SType.STR, "",
       "Shell command spawned when the LAST client disconnects."),

    # --- metrics ------------------------------------------------------------
    _s("enable_metrics", SType.BOOL, True, "Prometheus /api/metrics endpoint."),
    _s("enable_trace", SType.BOOL, False,
       "Per-frame span tracing from boot (selkies_tpu/trace): stage "
       "latency attribution at /api/trace as Perfetto-loadable trace-event "
       "JSON. Also togglable live via POST /api/trace."),
    _s("stats_interval_s", SType.FLOAT, 5.0, "Per-client system stats cadence."),

    # --- observability (selkies_tpu/obs) ------------------------------------
    _s("enable_device_monitor", SType.BOOL, True,
       "Background device telemetry: HBM sampling + jax.monitoring "
       "compile accounting (selkies_device_*/selkies_compile_* metrics)."),
    _s("device_monitor_interval_s", SType.FLOAT, 5.0,
       "HBM sampler cadence.", vmin=0.5, vmax=300),
    _s("device_hbm_sampling", SType.ENUM, "auto",
       "memory_stats() policy: 'auto' samples only on the cpu backend "
       "(the runtime RPC contends with encode-thread device calls on "
       "single-client TPU relays; SELKIES_DEVICE_MEMSTATS=1 overrides), "
       "'on'/'off' force it.", choices=("auto", "on", "off")),
    _s("health_stage_budget_ms", SType.FLOAT, 50.0,
       "Per-stage p99 budget for the stage_latency health check "
       "(degraded above 1x, failed above 2x).", vmin=1, vmax=60000),
    _s("health_fps_degraded_ratio", SType.FLOAT, 0.5,
       "capture_fps health check degrades below ratio*framerate.",
       vmin=0.05, vmax=1.0),
    _s("profile_dir", SType.STR, "",
       "Default output dir for POST /api/profile jax.profiler captures "
       "(empty: a fresh selkies-profile-* tempdir per capture)."),
    _s("qoe_seat_label_cap", SType.INT, 8,
       "Per-session Prometheus series cap (selkies_session_*): the first "
       "N sessions keep their own {seat,sid} labels, the rest roll up "
       "into the seat=\"_overflow\" aggregate.", vmin=0, vmax=256),
    _s("qoe_degraded_score", SType.FLOAT, 50.0,
       "The qoe health check degrades when any session's composite score "
       "falls below this.", vmin=0, vmax=100),
    _s("qoe_failed_score", SType.FLOAT, 15.0,
       "The qoe health check fails below this score and records a "
       "qoe_collapse incident in the flight recorder.", vmin=0, vmax=100),
    _s("log_format", SType.ENUM, "plain",
       "Log output: 'plain' (human) or 'json' (one structured object per "
       "line, carrying the session/seat correlation fields).",
       choices=("plain", "json")),
    _s("slo_g2g_ms", SType.FLOAT, 250.0,
       "Glass-to-glass frame budget for the g2g SLO: a timed frame "
       "whose send->client-present latency exceeds this is a bad event "
       "against the g2g error budget (the 16 ms north star is the "
       "eventual value; 250 ms is today's honest bar).",
       vmin=1, vmax=60000),
    _s("slo_objective", SType.FLOAT, 0.99,
       "Good-event fraction every stock SLO promises (0.99 = a 1% "
       "error budget).", vmin=0.5, vmax=0.99999),
    _s("slo_burn_threshold", SType.FLOAT, 14.4,
       "Burn-rate multiple both windows must exceed before the slo "
       "check fails (SRE workbook's 14.4 = a 30-day budget torched in "
       "2 days).", vmin=1, vmax=1000),
    _s("slo_fast_window_s", SType.FLOAT, 300.0,
       "Fast burn-rate window: trips quickly on a real regression.",
       vmin=10, vmax=3600),
    _s("slo_slow_window_s", SType.FLOAT, 3600.0,
       "Slow burn-rate window: confirms the fast window is not a "
       "blip; also bounds the SLO event ring's memory.",
       vmin=60, vmax=86400),

    # --- resilience (selkies_tpu/resilience) --------------------------------
    _s("fault_inject", SType.STR, "",
       "Arm deterministic fault injection at boot: "
       "'point:mode[:k=v,...];...' clauses (points: relay.send, "
       "capture.source, encoder.dispatch, ws.accept, fleet.spawn, "
       "fleet.drain, fleet.heartbeat; see resilience/faults.py). "
       "Also armable live via POST /api/faults, or via the "
       "SELKIES_FAULT_INJECT env var for subprocesses spawned "
       "without CLI flags (the fleet actuator's engine hosts)."),
    _s("supervisor_max_restarts", SType.INT, 5,
       "Restart budget per supervised component inside "
       "supervisor_window_s; the component parks as failed (and the "
       "supervision health check fails) once exhausted.",
       vmin=0, vmax=1000),
    _s("supervisor_window_s", SType.FLOAT, 300.0,
       "Sliding window for the restart budget.", vmin=1, vmax=86400),
    _s("supervisor_backoff_base_s", SType.FLOAT, 0.5,
       "First-restart backoff; consecutive fast deaths double it.",
       vmin=0.01, vmax=300),
    _s("supervisor_backoff_max_s", SType.FLOAT, 30.0,
       "Backoff ceiling for crash-looping components.",
       vmin=0.01, vmax=3600),
    _s("enable_degradation_ladder", SType.BOOL, True,
       "Verdict-driven fidelity shedding: qoe/hbm/stage-latency "
       "verdicts walk fps -> quality -> downscale down (and back up "
       "after a sustained-ok window)."),
    _s("ladder_interval_s", SType.FLOAT, 2.0,
       "Degradation-controller tick cadence.", vmin=0.1, vmax=300),
    _s("ladder_down_after_s", SType.FLOAT, 4.0,
       "A trigger verdict must persist this long before the first "
       "downshift (hysteresis).", vmin=0, vmax=3600),
    _s("ladder_hold_s", SType.FLOAT, 10.0,
       "Minimum dwell between any two ladder transitions (no "
       "flapping).", vmin=0, vmax=3600),
    _s("ladder_ok_window_s", SType.FLOAT, 30.0,
       "Sustained all-ok window required before stepping fidelity back "
       "up.", vmin=1, vmax=86400),
    _s("ladder_min_fps", SType.FLOAT, 15.0,
       "Floor for the ladder's fps rung.", vmin=1, vmax=240),
    _s("power_budget_w", SType.FLOAT, 0.0,
       "Host power budget in watts for the ladder's energy-aware mode "
       "(obs/energy): while the estimated draw exceeds it, downshifts "
       "target the highest-efficiency warm rung that still meets the "
       "SLO instead of the nearest rung. 0 disables (stock ladder "
       "behaviour).", vmin=0, vmax=1_000_000),

    # --- compile plane (selkies_tpu/prewarm) --------------------------------
    _s("enable_prewarm", SType.BOOL, True,
       "Background AOT pre-warm of the reachable (resolution x codec x "
       "seat-count) program lattice the degradation ladder can visit, so "
       "geometry-changing rungs switch compile-free (progress at "
       "GET /api/prewarm; pauses during compile storms)."),
    _s("prewarm_defer_deadline_s", SType.FLOAT, 30.0,
       "How long a ladder transition to a cold (uncompiled) rung stays "
       "deferred — holding at a compiled rung while the target "
       "pre-warms — before the nearest warm rung is forced instead.",
       vmin=0.1, vmax=3600),
    _s("warm_cache_artifact", SType.STR, "",
       "Path to a warm-cache artifact (tools/warm_cache.py pack) "
       "unpacked at startup before the first compile so new hosts boot "
       "hot; REFUSED on a host-fingerprint mismatch (the cross-machine "
       "SIGILL hazard)."),
)

_DEFS_BY_NAME: dict[str, Setting] = {d.name: d for d in SETTING_DEFINITIONS}

# Names whose values must never appear in logs/dumps even beyond the
# explicitly-sensitive flags (reference settings.py:914-930). "key" matches
# only as a whole underscore-separated segment so e.g. keyframe_interval_s
# is not falsely redacted.
_SENSITIVE_SUBSTRINGS = ("password", "secret", "token")
_SENSITIVE_SEGMENTS = ("key",)


def is_sensitive(name: str) -> bool:
    d = _DEFS_BY_NAME.get(name)
    if d is not None and d.sensitive:
        return True
    low = name.lower()
    if any(m in low for m in _SENSITIVE_SUBSTRINGS):
        return True
    return any(seg in _SENSITIVE_SEGMENTS for seg in low.split("_"))


class SettingsError(ValueError):
    pass


def _parse_scalar(d: Setting, raw: str) -> Any:
    if d.stype is SType.BOOL:
        v = raw.strip().lower()
        if v in ("1", "true", "yes", "on"):
            return True
        if v in ("0", "false", "no", "off", ""):
            return False
        raise SettingsError(f"{d.name}: not a boolean: {raw!r}")
    if d.stype is SType.INT:
        try:
            val = int(raw)
        except ValueError as e:
            raise SettingsError(f"{d.name}: not an int: {raw!r}") from e
        return val
    if d.stype is SType.FLOAT:
        try:
            return float(raw)
        except ValueError as e:
            raise SettingsError(f"{d.name}: not a float: {raw!r}") from e
    if d.stype is SType.ENUM:
        if d.choices and raw not in d.choices:
            raise SettingsError(f"{d.name}: {raw!r} not in {d.choices}")
        return raw
    if d.stype is SType.LIST:
        return tuple(x.strip() for x in raw.split(",") if x.strip())
    return raw


def _clamp(d: Setting, val: Any) -> Any:
    if d.stype in (SType.INT, SType.FLOAT):
        if d.vmin is not None and val < d.vmin:
            raise SettingsError(f"{d.name}: {val} below min {d.vmin}")
        if d.vmax is not None and val > d.vmax:
            raise SettingsError(f"{d.name}: {val} above max {d.vmax}")
    return val


@dataclasses.dataclass
class _Resolved:
    value: Any
    locked: bool = False
    # For numeric client settings: optionally restricted [lo, hi] from env
    # "lo-hi" syntax (reference range-lock, settings.py:12-27).
    lo: float | None = None
    hi: float | None = None
    source: str = "default"


class AppSettings:
    """Resolved settings with attribute access.

    ``AppSettings.parse(argv, env)`` applies the precedence chain; the result
    is mutable only through ``apply_client_setting`` (sanitised) or
    ``set_server`` (trusted server-side updates).
    """

    def __init__(self, resolved: dict[str, _Resolved]):
        self._resolved = resolved

    # -- construction --------------------------------------------------------
    @classmethod
    def parse(cls, argv: Sequence[str] | None = None,
              env: Mapping[str, str] | None = None) -> "AppSettings":
        argv = list(argv if argv is not None else sys.argv[1:])
        env = dict(env if env is not None else os.environ)
        cli: dict[str, str] = {}
        i = 0
        while i < len(argv):
            a = argv[i]
            if not a.startswith("--"):
                raise SettingsError(f"unexpected argument {a!r}")
            body = a[2:]
            if "=" in body:
                k, v = body.split("=", 1)
            else:
                k = body
                if i + 1 < len(argv) and not argv[i + 1].startswith("--"):
                    i += 1
                    v = argv[i]
                else:
                    # Bare flag form is only valid for booleans; a missing
                    # value on any other type must fail fast.
                    d = _DEFS_BY_NAME.get(k.replace("-", "_"))
                    if d is None or d.stype is not SType.BOOL:
                        raise SettingsError(f"--{k} requires a value")
                    v = "true"
            k = k.replace("-", "_")
            if k not in _DEFS_BY_NAME:
                raise SettingsError(f"unknown setting --{k}")
            cli[k] = v
            i += 1

        resolved: dict[str, _Resolved] = {}
        for d in SETTING_DEFINITIONS:
            raw: str | None = None
            source = "default"
            if d.name in cli:
                raw, source = cli[d.name], "cli"
            elif d.env_name() in env:
                raw, source = env[d.env_name()], "env"
            else:
                for fb in d.fallback_env:
                    if fb in env:
                        raw, source = env[fb], "fallback_env"
                        break
            if raw is None:
                resolved[d.name] = _Resolved(value=d.default)
                continue
            locked = False
            if raw.endswith("|locked"):
                locked, raw = True, raw[: -len("|locked")]
            lo = hi = None
            if d.stype in (SType.INT, SType.FLOAT) and d.client and _is_range(raw):
                lo_s, hi_s = raw.split("-", 1)
                lo, hi = float(lo_s), float(hi_s)
                if lo > hi:
                    raise SettingsError(f"{d.name}: inverted range {raw!r}")
                # Value = default clamped into the restricted range.
                val = min(max(d.default, lo), hi)
                if d.stype is SType.INT:
                    val = int(val)
                val = _clamp(d, val)
                locked = locked or (lo == hi)
            else:
                val = _clamp(d, _parse_scalar(d, raw))
            resolved[d.name] = _Resolved(value=val, locked=locked, lo=lo, hi=hi,
                                         source=source)
        return cls(resolved)

    # -- access --------------------------------------------------------------
    def __getattr__(self, name: str) -> Any:
        try:
            return self._resolved[name].value
        except KeyError as e:
            raise AttributeError(name) from e

    def get(self, name: str) -> Any:
        return self._resolved[name].value

    def is_locked(self, name: str) -> bool:
        return self._resolved[name].locked

    def set_server(self, name: str, value: Any) -> None:
        """Trusted server-side update (bypasses lock, not validation)."""
        d = _DEFS_BY_NAME[name]
        if d.stype in (SType.INT, SType.FLOAT):
            value = _clamp(d, value)
        elif d.stype is SType.ENUM and d.choices and value not in d.choices:
            raise SettingsError(f"{name}: {value!r} not in {d.choices}")
        self._resolved[name].value = value

    # -- client-facing surface ----------------------------------------------
    def build_client_settings_payload(self) -> dict[str, Any]:
        """JSON payload of client-visible settings with lock/range metadata
        (reference settings.py:1271-1313)."""
        out: dict[str, Any] = {}
        for d in SETTING_DEFINITIONS:
            if not d.client:
                continue
            r = self._resolved[d.name]
            entry: dict[str, Any] = {"value": r.value, "locked": r.locked}
            if d.stype in (SType.INT, SType.FLOAT):
                entry["min"] = r.lo if r.lo is not None else d.vmin
                entry["max"] = r.hi if r.hi is not None else d.vmax
            if d.stype is SType.ENUM:
                entry["choices"] = list(d.choices or ())
            out[d.name] = entry
        return out

    def sanitize_client_setting(self, name: str, value: Any) -> Any:
        """Validate a client-supplied settings write; raises SettingsError on
        anything out of contract (reference settings.py:1315-1398)."""
        d = _DEFS_BY_NAME.get(name)
        if d is None or not d.client:
            raise SettingsError(f"setting {name!r} is not client-writable")
        r = self._resolved[name]
        if r.locked:
            raise SettingsError(f"setting {name!r} is locked")
        if d.stype is SType.BOOL:
            if isinstance(value, bool):
                return value
            return _parse_scalar(d, str(value))
        if d.stype in (SType.INT, SType.FLOAT):
            try:
                val = (int if d.stype is SType.INT else float)(value)
            except (TypeError, ValueError) as e:
                raise SettingsError(f"{name}: bad value {value!r}") from e
            lo = r.lo if r.lo is not None else d.vmin
            hi = r.hi if r.hi is not None else d.vmax
            if lo is not None and val < lo:
                raise SettingsError(f"{name}: {val} below {lo}")
            if hi is not None and val > hi:
                raise SettingsError(f"{name}: {val} above {hi}")
            return val
        if d.stype is SType.ENUM:
            if not isinstance(value, str) or (d.choices and value not in d.choices):
                raise SettingsError(f"{name}: {value!r} not in {d.choices}")
            return value
        if not isinstance(value, str):
            raise SettingsError(f"{name}: expected string")
        # STR settings may carry a choices safelist too (window_manager,
        # display2_position) — the CLI/env parser enforces it at :345,
        # and the client path must be no laxer
        if d.choices and value not in d.choices:
            raise SettingsError(f"{name}: {value!r} not in {d.choices}")
        return value

    def apply_client_setting(self, name: str, value: Any) -> Any:
        val = self.sanitize_client_setting(name, value)
        self._resolved[name].value = val
        return val

    # -- dumps ---------------------------------------------------------------
    def dump(self, redact: bool = True) -> dict[str, Any]:
        out = {}
        for name, r in self._resolved.items():
            out[name] = "<redacted>" if (redact and is_sensitive(name) and r.value) \
                else r.value
        return out

    def to_json(self, redact: bool = True) -> str:
        return json.dumps(self.dump(redact=redact), default=list)


def _is_range(raw: str) -> bool:
    """True when ``raw`` is 'lo-hi' (two non-negative numerics).

    A leading '-' means a negative scalar, never a range — the split in
    ``parse`` uses the same first-'-' convention, so detection and parsing
    agree by construction.
    """
    if raw.startswith("-") or "-" not in raw:
        return False
    lo, _, hi = raw.partition("-")
    try:
        float(lo), float(hi)
        return True
    except ValueError:
        return False


def load(argv: Sequence[str] | None = None,
         env: Mapping[str, str] | None = None) -> AppSettings:
    return AppSettings.parse(argv, env)
