"""Shared asyncio task-retention helper.

The event loop holds only a WEAK reference to pending tasks: a bare
``asyncio.ensure_future(coro)`` whose return value is discarded can be
garbage-collected before it ever runs (ADVICE r5; enforced repo-wide by
graftlint's ASYNC-ORPHAN-TASK rule).  Every fire-and-forget spawn goes
through here so the retain idiom lives in exactly one place — and so no
spawned task can die silently: an uncaught exception used to surface
only as GC-time "Task exception was never retrieved" noise, long after
the failure, with no component attribution.
"""
from __future__ import annotations

import asyncio
import logging
from typing import Coroutine

logger = logging.getLogger("selkies_tpu.taskutil")


def spawn_retained(tasks: set, coro: Coroutine,
                   component: str = "") -> asyncio.Task:
    """Schedule ``coro`` and hold a strong reference in ``tasks`` until
    it completes.  Callers that need cancellation on shutdown iterate
    their own set (e.g. ``for t in tasks: t.cancel()``).

    The done-callback retrieves the task's exception: an uncaught
    failure is logged AT completion time with ``component`` (or the
    coroutine's name) attached, instead of leaking into the garbage
    collector's "exception was never retrieved" warning minutes later.
    """
    task = asyncio.ensure_future(coro)
    tasks.add(task)
    label = component or getattr(coro, "__qualname__", None) \
        or type(coro).__name__

    def _done(t: asyncio.Task) -> None:
        tasks.discard(t)
        if t.cancelled():
            return
        exc = t.exception()        # marks the exception retrieved
        if exc is not None:
            logger.error("background task %r died: %s: %s",
                         label, type(exc).__name__, exc,
                         exc_info=exc)

    task.add_done_callback(_done)
    return task
