"""Shared asyncio task-retention helper.

The event loop holds only a WEAK reference to pending tasks: a bare
``asyncio.ensure_future(coro)`` whose return value is discarded can be
garbage-collected before it ever runs (ADVICE r5; enforced repo-wide by
graftlint's ASYNC-ORPHAN-TASK rule).  Every fire-and-forget spawn goes
through here so the retain idiom lives in exactly one place.
"""
from __future__ import annotations

import asyncio
from typing import Coroutine


def spawn_retained(tasks: set, coro: Coroutine) -> asyncio.Task:
    """Schedule ``coro`` and hold a strong reference in ``tasks`` until
    it completes.  Callers that need cancellation on shutdown iterate
    their own set (e.g. ``for t in tasks: t.cancel()``)."""
    task = asyncio.ensure_future(coro)
    tasks.add(task)
    task.add_done_callback(tasks.discard)
    return task
