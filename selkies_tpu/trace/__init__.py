"""Per-frame span tracing with latency attribution.

The bench round that motivated this package measured 0.42 fps at ~790 ms
p99 glass-to-glass and could not say WHICH stage (capture, CSC, DCT+quant,
entropy, packetize, ws send) ate the budget — ``server/metrics.py`` only
carries endpoint-level gauges. This package is the attribution layer:

- :mod:`.core` — a dependency-free, low-overhead span tracer: monotonic
  spans correlated by frame id, thread/task-safe via ``contextvars``, a
  fixed-size ring of completed frame timelines, and near-zero cost when
  disabled (the disabled ``span()`` path is one flag check returning a
  shared singleton — no allocation per frame);
- :mod:`.export` — Chrome trace-event JSON, loadable in Perfetto or
  ``chrome://tracing``;
- :mod:`.summary` — per-stage p50/p99 percentiles, fed into the
  ``server.metrics`` registry as ``selkies_stage_ms`` histograms;
- :mod:`.__main__` — offline CLI: ``python -m selkies_tpu.trace
  summarize <trace.json>``.

Everything here is stdlib-only: the CLI and exporter must run in images
with neither jax nor aiohttp installed (the CI lint job).

Stage names used across the repo (the bench breakdown contract):
``capture``, ``convert``, ``encode.dispatch``, ``encode.readback``,
``packetize``, ``fanout``, ``ws.send`` — plus the ``ack`` instant.
"""

from .core import FrameTracer, FrameTimeline, tracer  # noqa: F401

#: the repo-wide stage-name contract (bench reports every one of these,
#: zero-filled when a stage cannot occur in its loop, e.g. ws.send)
STAGES = ("capture", "convert", "encode.dispatch", "encode.readback",
          "packetize", "fanout", "ws.send")
