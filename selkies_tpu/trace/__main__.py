"""Offline trace CLI.

``python -m selkies_tpu.trace summarize <trace.json>`` — per-stage
p50/p99 table (``--json`` for machine-readable) over a saved /api/trace
snapshot or any Chrome trace-event file.

``python -m selkies_tpu.trace selftest [out.json]`` — emit a synthetic
timeline through the real tracer + exporter (the CI smoke path) and
summarize it; exits non-zero when the round-trip drops a stage.

Stdlib-only: runs in the lint CI image with no jax/aiohttp installed.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import STAGES
from .core import FrameTracer
from .export import (events_from_document, timelines_from_events,
                     to_trace_events)
from .summary import (occupancy_report, render_occupancy, render_table,
                      summarize_events)


def _cmd_summarize(args: argparse.Namespace) -> int:
    try:
        with open(args.file, encoding="utf-8") as f:
            doc = json.load(f)
        events = events_from_document(doc)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {args.file}: {e}", file=sys.stderr)
        return 2
    summary = summarize_events(events)
    occ = occupancy_report(timelines_from_events(events)) \
        if args.occupancy else None
    if args.json:
        doc_out = {"version": 1, "file": args.file, "stages": summary}
        if occ is not None:
            doc_out["occupancy"] = occ
        print(json.dumps(doc_out))
    else:
        if not summary:
            print("no complete spans in trace", file=sys.stderr)
        print(render_table(summary))
        if occ is not None:
            print(render_occupancy(occ))
    return 0


def _cmd_selftest(args: argparse.Namespace) -> int:
    tr = FrameTracer(capacity=16)
    tr.enable()
    import time
    for fid in range(4):
        tl = tr.frame_begin("selftest")
        tr.bind(tl, fid)
        for stage in STAGES:
            with tr.span(stage, tl):
                time.sleep(0.001)
        tr.frame_end("selftest", fid)
        tr.instant("selftest", fid, "ack")
    doc = to_trace_events(tr.snapshot())
    out = args.out or "-"
    text = json.dumps(doc)
    if out == "-":
        print(text)
    else:
        with open(out, "w", encoding="utf-8") as f:
            f.write(text)
    summary = summarize_events(events_from_document(json.loads(text)))
    missing = [s for s in STAGES if s not in summary]
    if missing:
        print(f"selftest FAILED: stages lost in round-trip: {missing}",
              file=sys.stderr)
        return 1
    # occupancy must round-trip through the exported form too: the
    # synthetic timeline is fully serial, so no overlap may be detected
    # and the critical path may only name real stages (or bubble)
    occ = occupancy_report(
        timelines_from_events(events_from_document(json.loads(text))))
    if occ["frames"] != 4 or occ["overlap_fraction"] > 0.05:
        print(f"selftest FAILED: serial timeline misread as overlapped: "
              f"{occ}", file=sys.stderr)
        return 1
    from .summary import BUBBLE
    if not set(occ["critical_path"]) <= set(STAGES) | {BUBBLE}:
        print(f"selftest FAILED: critical path names unknown stages: "
              f"{sorted(occ['critical_path'])}", file=sys.stderr)
        return 1
    print(render_table(summary), file=sys.stderr)
    print(render_occupancy(occ), file=sys.stderr)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m selkies_tpu.trace",
                                description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    ps = sub.add_parser("summarize",
                        help="per-stage p50/p99 over a trace-event file")
    ps.add_argument("file")
    ps.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ps.add_argument("--occupancy", action="store_true",
                    help="add overlap/critical-path/lane-occupancy "
                         "analysis (completed frames only)")
    ps.set_defaults(fn=_cmd_summarize)
    pt = sub.add_parser("selftest",
                        help="synthetic timeline through tracer+exporter")
    pt.add_argument("out", nargs="?", default="",
                    help="write the trace JSON here ('-' or empty: stdout)")
    pt.set_defaults(fn=_cmd_selftest)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
