"""Span tracer core: frame timelines, spans, the ring buffer.

Design constraints (ISSUE 2 tentpole):

- **Disabled = free.** Every per-frame entry point (``span()``,
  ``frame_begin``, ``bind``, ``frame_end``, ``attach``) starts with one
  flag check and returns a shared singleton / ``None`` — no allocation,
  no lock, no clock read. The capture loop calls these at 60 Hz per
  display; the disabled cost must be unmeasurable.
- **Thread/task-safe.** The capture thread dispatches frame N while the
  asyncio loop is still sending frame N-3, and multi-seat finalize fans
  out from yet another thread. The *current* timeline travels in a
  ``contextvars.ContextVar`` (per-thread AND per-task), and all ring
  mutations take one uncontended lock.
- **Frame-id correlation.** A frame's life spans several loop turns
  (dispatch at tick N, readback at N+PIPELINE_DEPTH, ws send later, ACK
  last). Spans recorded outside the dispatch context attach by
  ``(display_id, frame_id)`` through a bounded index that shares the
  ring's eviction.
- **Monotonic clock.** ``time.perf_counter_ns`` everywhere; wall-clock
  never enters a duration.
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

__all__ = ["FrameTimeline", "FrameTracer", "tracer"]

#: completed frame timelines kept for export (per process, across displays)
DEFAULT_CAPACITY = 512

_now_ns = time.perf_counter_ns

#: the timeline the current thread/task is dispatching (set by frame_begin)
_current: contextvars.ContextVar[Optional["FrameTimeline"]] = \
    contextvars.ContextVar("selkies_trace_frame", default=None)


class FrameTimeline:
    """One frame's spans. ``spans`` holds ``(name, lane, t0_ns, dur_ns)``
    tuples; ``lane`` maps to a Perfetto track (thread name, ``seatN``,
    ``clientN``…). Mutated via the tracer only."""

    __slots__ = ("display_id", "frame_id", "t0_ns", "t1_ns", "spans")

    def __init__(self, display_id: str):
        self.display_id = display_id
        self.frame_id: Optional[int] = None
        self.t0_ns = _now_ns()
        self.t1_ns: Optional[int] = None
        self.spans: list[tuple[str, str, int, int]] = []

    @property
    def done(self) -> bool:
        return self.t1_ns is not None

    def wall_ms(self) -> float:
        """frame_begin -> frame_end span in ms (0.0 while open)."""
        if self.t1_ns is None:
            return 0.0
        return (self.t1_ns - self.t0_ns) / 1e6

    def to_dict(self) -> dict:
        return {
            "display_id": self.display_id,
            "frame_id": self.frame_id,
            "t0_ns": self.t0_ns,
            "t1_ns": self.t1_ns,
            "spans": [{"name": n, "lane": la, "t0_ns": t0, "dur_ns": d}
                      for n, la, t0, d in self.spans],
        }


class _NullSpan:
    """Shared do-nothing context manager: the disabled/unattached path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()

#: default for ``span(tl=...)``: distinct from an explicit None
_USE_CURRENT = object()


class _Span:
    """Live span context manager bound to one timeline."""

    __slots__ = ("_tracer", "_tl", "_name", "_lane", "_t0")

    def __init__(self, tracer_: "FrameTracer", tl: FrameTimeline,
                 name: str, lane: Optional[str]):
        self._tracer = tracer_
        self._tl = tl
        self._name = name
        self._lane = lane

    def __enter__(self):
        self._t0 = _now_ns()
        return self

    def __exit__(self, *exc):
        self._tracer._record(self._tl, self._name, self._lane,
                             self._t0, _now_ns() - self._t0)
        return False


class FrameTracer:
    """Process-wide span tracer. One instance (:data:`tracer`) serves every
    capture module, session, and server plane; tests build their own."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._enabled = False
        self._lock = threading.Lock()
        # insertion-ordered (display_id, frame_id) -> timeline; doubles as
        # the ring (eviction pops the oldest entry) and the attach index
        self._ring: "OrderedDict[tuple[str, int], FrameTimeline]" = \
            OrderedDict()
        self._unbound: list[FrameTimeline] = []   # begun, not yet bind()ed
        #: optional (stage_name, dur_ms) sink — wired to the metrics
        #: registry by :meth:`enable` when the server plane is importable
        self.stage_sink: Optional[Callable[[str, float], None]] = None
        self._dropped = 0

    # -- lifecycle -----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self, capacity: Optional[int] = None) -> None:
        if capacity is not None:
            self.capacity = int(capacity)
        if self.stage_sink is None:
            self.stage_sink = _metrics_sink()
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._unbound.clear()
            self._dropped = 0

    # -- frame lifecycle -----------------------------------------------------
    def frame_begin(self, display_id: str) -> Optional[FrameTimeline]:
        """Open a timeline and make it the current dispatch context.
        Returns None when disabled (every later call accepts that)."""
        if not self._enabled:
            return None
        tl = FrameTimeline(display_id)
        _current.set(tl)
        with self._lock:
            self._unbound.append(tl)
            if len(self._unbound) > 64:      # leak guard: begun, never bound
                del self._unbound[:32]
        return tl

    def bind(self, tl: Optional[FrameTimeline], frame_id: int,
             aliases: tuple[str, ...] = ()) -> None:
        """Register the timeline under its (display, frame_id) so spans
        recorded on other threads/turns can attach. Called once the
        encoder assigned the id (encode() returns it).

        ``aliases`` registers extra display keys for the SAME timeline —
        the multi-seat capture encodes N seats in one sharded step, so
        one timeline answers for ``seat0..seatN-1`` relay sends. Alias
        entries count against ``capacity`` (they live in the same ring)."""
        if tl is None or not self._enabled:
            return
        tl.frame_id = int(frame_id)
        with self._lock:
            try:
                self._unbound.remove(tl)
            except ValueError:
                pass
            for disp in (tl.display_id, *aliases):
                key = (disp, tl.frame_id)
                self._ring[key] = tl         # wrap collision: last wins
                self._ring.move_to_end(key)
            while len(self._ring) > self.capacity:
                self._ring.popitem(last=False)
                self._dropped += 1

    def frame_end(self, display_id: str, frame_id: int) -> None:
        """Close the timeline (delivery finished). Late spans (ws send,
        ACK) may still attach while it sits in the ring."""
        if not self._enabled:
            return
        with self._lock:
            tl = self._ring.get((display_id, int(frame_id)))
        if tl is not None and tl.t1_ns is None:
            tl.t1_ns = _now_ns()
        if _current.get() is tl:
            _current.set(None)

    # -- spans ---------------------------------------------------------------
    def span(self, name: str, tl=_USE_CURRENT, lane: Optional[str] = None):
        """Context manager timing one stage. Targets ``tl`` when given;
        defaults to the current dispatch context. No-op when disabled,
        when no context exists (engine code runs unchanged under scripts
        that never call frame_begin), or when ``tl`` is explicitly None
        (a finalize whose frame already left the ring must NOT fall back
        to the current context — that is a different, newer frame)."""
        if not self._enabled:
            return _NULL_SPAN
        if tl is _USE_CURRENT:
            tl = _current.get()
        if tl is None:
            return _NULL_SPAN
        return _Span(self, tl, name, lane)

    def attach_span(self, display_id: str, frame_id: int, name: str,
                    t0_ns: int, dur_ns: int,
                    lane: Optional[str] = None,
                    extend_frame: bool = False) -> bool:
        """Record a span measured elsewhere (the relay's send, timed on
        the loop) onto the frame's timeline by id. Returns False when the
        frame already left the ring.

        ``extend_frame`` stretches a CLOSED frame's envelope to cover the
        span: client-side spans (net / decode / present, ISSUE 7) land
        after ``frame_end`` by construction, and without the extension
        the occupancy analyzer would clip them out — e2e must become
        glass-to-glass, not stay at ws.send."""
        if not self._enabled:
            return False
        with self._lock:
            tl = self._ring.get((display_id, int(frame_id)))
        if tl is None:
            return False
        self._record(tl, name, lane, t0_ns, dur_ns)
        if extend_frame and tl.t1_ns is not None:
            tl.t1_ns = max(tl.t1_ns, t0_ns + max(0, dur_ns))
        return True

    def record_span(self, tl: Optional[FrameTimeline], name: str,
                    t0_ns: int, lane: Optional[str] = None) -> None:
        """Record a span with an EXPLICIT start ending now — the deep
        pipeline's readback span starts at the slot's submit instant
        (frames-in-flight time is readback time, not bubble), which no
        context manager entered on this thread can know."""
        if tl is None or not self._enabled:
            return
        self._record(tl, name, lane, t0_ns, max(0, _now_ns() - t0_ns))

    def instant(self, display_id: str, frame_id: int, name: str,
                lane: Optional[str] = None) -> bool:
        """Zero-duration marker (exported as a trace-event instant)."""
        return self.attach_span(display_id, frame_id, name, _now_ns(), 0,
                                lane=lane)

    def lookup(self, display_id: str, frame_id: int
               ) -> Optional[FrameTimeline]:
        if not self._enabled:
            return None
        with self._lock:
            return self._ring.get((display_id, int(frame_id)))

    def _record(self, tl: FrameTimeline, name: str, lane: Optional[str],
                t0_ns: int, dur_ns: int) -> None:
        if lane is None:
            lane = threading.current_thread().name
        tl.spans.append((name, lane, t0_ns, dur_ns))
        sink = self.stage_sink
        if sink is not None and dur_ns > 0:
            try:
                sink(name, dur_ns / 1e6)
            except Exception:
                pass

    # -- export --------------------------------------------------------------
    def snapshot(self) -> list[FrameTimeline]:
        """Timelines oldest-first (open frames included, marked undone;
        alias keys deduped)."""
        with self._lock:
            seen: set[int] = set()
            out: list[FrameTimeline] = []
            for tl in self._ring.values():
                if id(tl) not in seen:
                    seen.add(id(tl))
                    out.append(tl)
            return out

    def stats(self, frames: Optional[int] = None) -> dict:
        """``frames`` lets callers that already hold a snapshot skip the
        second dedup pass (GET /api/trace does both)."""
        if frames is None:
            frames = len(self.snapshot())
        return {"enabled": self._enabled, "frames": frames,
                "capacity": self.capacity, "dropped": self._dropped}


def _metrics_sink() -> Optional[Callable[[str, float], None]]:
    """Wire stage durations into the Prometheus registry as the
    ``selkies_stage_ms`` histogram. Lazy + guarded: the trace package
    must work in images without the server plane's dependencies."""
    try:
        from ..server import metrics
    except Exception:
        return None
    metrics.describe("selkies_stage_ms",
                     "Per-frame stage latency (trace spans)")
    return lambda name, ms: metrics.observe_hist(
        "selkies_stage_ms", ms, {"stage": name})


#: the process-wide tracer every instrumentation site uses; call sites
#: import this object and use ``tracer.span(...)`` — one entry point
tracer = FrameTracer()
