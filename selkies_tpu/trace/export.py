"""Chrome trace-event JSON export (the Perfetto / chrome://tracing format).

Emits the JSON Object Format: ``{"traceEvents": [...], "displayTimeUnit":
"ms"}`` with complete (``ph: "X"``) events for spans, instant (``ph:
"i"``) events for zero-duration markers, and ``M`` metadata events naming
the process and one thread track per lane. Timestamps are microseconds of
``perf_counter`` — relative, monotonic, exactly what the viewers expect.
"""

from __future__ import annotations

from typing import Iterable, Union

from .core import FrameTimeline

PID = 1
#: tid reserved for the per-frame envelope track
FRAME_TID = 0


def _as_dict(tl: Union[FrameTimeline, dict]) -> dict:
    return tl if isinstance(tl, dict) else tl.to_dict()


def to_trace_events(timelines: Iterable[Union[FrameTimeline, dict]],
                    process_name: str = "selkies-tpu") -> dict:
    """Render timelines to a Chrome trace-event document (plain dict,
    ``json.dumps``-ready)."""
    events: list[dict] = [{
        "ph": "M", "pid": PID, "tid": FRAME_TID, "name": "process_name",
        "args": {"name": process_name},
    }, {
        "ph": "M", "pid": PID, "tid": FRAME_TID, "name": "thread_name",
        "args": {"name": "frames"},
    }]
    lanes: dict[str, int] = {}

    def tid_for(lane: str) -> int:
        tid = lanes.get(lane)
        if tid is None:
            tid = lanes[lane] = len(lanes) + 1
            events.append({"ph": "M", "pid": PID, "tid": tid,
                           "name": "thread_name", "args": {"name": lane}})
        return tid

    for tl in timelines:
        d = _as_dict(tl)
        fid = d.get("frame_id")
        frame_args = {"frame_id": fid, "display": d.get("display_id")}
        if d.get("t1_ns") is not None:
            events.append({
                "name": f"frame {fid}", "ph": "X", "pid": PID,
                "tid": FRAME_TID, "ts": d["t0_ns"] / 1e3,
                "dur": (d["t1_ns"] - d["t0_ns"]) / 1e3, "args": frame_args,
            })
        for s in d.get("spans", []):
            tid = tid_for(s["lane"])
            if s["dur_ns"] <= 0:
                events.append({
                    "name": s["name"], "ph": "i", "s": "t", "pid": PID,
                    "tid": tid, "ts": s["t0_ns"] / 1e3, "args": frame_args,
                })
            else:
                events.append({
                    "name": s["name"], "ph": "X", "pid": PID, "tid": tid,
                    "ts": s["t0_ns"] / 1e3, "dur": s["dur_ns"] / 1e3,
                    "args": frame_args,
                })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def timelines_from_events(events: Iterable[dict]) -> list[dict]:
    """Inverse of :func:`to_trace_events` for COMPLETED frames: rebuild
    timeline dicts (t0_ns/t1_ns/spans) from an exported document so the
    occupancy analyzer runs identically on a saved /api/trace snapshot.
    Spans re-attach by ``args.frame_id``+``args.display``; lanes come
    from the thread_name metadata. Frames whose envelope event was never
    exported (still open at export time) are dropped — interval math
    needs a closed window."""
    thread_names: dict[tuple, str] = {}
    frames: dict[tuple, dict] = {}
    spans: list[tuple[tuple, dict, object]] = []
    for e in events:
        if not isinstance(e, dict):
            continue
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            thread_names[(e.get("pid"), e.get("tid"))] = str(
                (e.get("args") or {}).get("name", ""))
            continue
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        fid = args.get("frame_id")
        if fid is None:
            continue
        key = (args.get("display"), fid)
        name = str(e.get("name", "?"))
        if name.startswith("frame "):
            frames[key] = {
                "display_id": args.get("display"), "frame_id": fid,
                "t0_ns": int(float(e["ts"]) * 1e3),
                "t1_ns": int((float(e["ts"]) + float(e["dur"])) * 1e3),
                "spans": [],
            }
        else:
            spans.append((key, e, (e.get("pid"), e.get("tid"))))
    for key, e, tkey in spans:
        tl = frames.get(key)
        if tl is None:
            continue
        tl["spans"].append({
            "name": str(e.get("name", "?")),
            "lane": thread_names.get(tkey) or str(tkey[1]),
            "t0_ns": int(float(e["ts"]) * 1e3),
            "dur_ns": int(float(e.get("dur", 0)) * 1e3),
        })
    return [frames[k] for k in sorted(frames, key=lambda k: frames[k]["t0_ns"])]


def events_from_document(doc) -> list[dict]:
    """Accept either the object form ({"traceEvents": [...]}) or the bare
    JSON-array form — both are valid on the import side of the viewers."""
    if isinstance(doc, dict):
        events = doc.get("traceEvents", [])
    elif isinstance(doc, list):
        events = doc
    else:
        raise ValueError("not a trace-event document")
    if not isinstance(events, list):
        raise ValueError("traceEvents must be a list")
    return [e for e in events if isinstance(e, dict)]
