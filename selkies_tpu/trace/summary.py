"""Per-stage latency summarizer + pipeline occupancy analyzer.

Two instruments over the same frame timelines:

- **per-stage percentiles** (:func:`summarize_timelines`): p50/p99/mean
  per span name — the BENCH_r*.json breakdown and the
  ``selkies_stage_ms`` histogram feed;
- **occupancy / critical path** (:func:`occupancy_report`): which stage
  actually *bounded* each frame's end-to-end time. Stage-sum coverage
  (the PR-2 20% contract) stops being meaningful the moment stages
  overlap — a deep pipeline's stage sum exceeds e2e by design — so the
  acceptance instrument for the pipeline rework is interval math:
  per-frame critical-path attribution (each instant of the frame window
  is charged to the covering span that ends last — the stage still
  gating completion — or to ``bubble`` when nothing runs), an overlap
  fraction (0 for a fully-serial pipeline), and per-lane occupancy /
  largest-gap detection over the whole timeline window.

Consumes either live :class:`~.core.FrameTimeline`s or the exported
Chrome trace-event JSON (the offline CLI path), so a BENCH_r*.json
breakdown and a saved /api/trace snapshot summarize identically.
"""

from __future__ import annotations

from typing import Iterable, Optional, Union

from .core import FrameTimeline


def _pct(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile on an ascending list (the same convention
    bench.py uses for its p50/p99 line)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(len(sorted_vals) * q))
    return sorted_vals[i]


def summarize_durations(by_stage: dict[str, list[float]]) -> dict[str, dict]:
    """{stage: [ms, ...]} -> {stage: {count, p50_ms, p99_ms, mean_ms,
    total_ms}}, stages sorted by total time descending."""
    out: dict[str, dict] = {}
    for name, vals in by_stage.items():
        vals = sorted(vals)
        total = sum(vals)
        out[name] = {
            "count": len(vals),
            "p50_ms": round(_pct(vals, 0.50), 3),
            "p99_ms": round(_pct(vals, 0.99), 3),
            "mean_ms": round(total / len(vals), 3) if vals else 0.0,
            "total_ms": round(total, 3),
        }
    return dict(sorted(out.items(),
                       key=lambda kv: -kv[1]["total_ms"]))


def summarize_timelines(timelines: Iterable[Union[FrameTimeline, dict]]
                        ) -> dict[str, dict]:
    by_stage: dict[str, list[float]] = {}
    for tl in timelines:
        d = tl if isinstance(tl, dict) else tl.to_dict()
        for s in d.get("spans", []):
            if s["dur_ns"] > 0:
                by_stage.setdefault(s["name"], []).append(s["dur_ns"] / 1e6)
    return summarize_durations(by_stage)


def summarize_events(events: Iterable[dict]) -> dict[str, dict]:
    """Summarize exported trace events: complete (``X``) spans only; the
    per-frame envelope track ('frame N' names) is excluded so stage sums
    aren't double-counted."""
    by_stage: dict[str, list[float]] = {}
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        name = str(e.get("name", "?"))
        if name.startswith("frame "):
            continue
        by_stage.setdefault(name, []).append(float(e["dur"]) / 1e3)
    return summarize_durations(by_stage)


def frame_latency_ms(timelines: Iterable[Union[FrameTimeline, dict]]
                     ) -> list[float]:
    """Completed frames' begin->end wall times (the e2e the stage sum is
    validated against)."""
    out = []
    for tl in timelines:
        d = tl if isinstance(tl, dict) else tl.to_dict()
        if d.get("t1_ns") is not None:
            out.append((d["t1_ns"] - d["t0_ns"]) / 1e6)
    return out


#: pseudo-stage charged with frame-window time no span covers (host
#: gaps, scheduling stalls, untraced work)
BUBBLE = "bubble"


def frame_critical_path(tl: Union[FrameTimeline, dict]) -> Optional[dict]:
    """Interval attribution for ONE completed frame.

    Every instant of ``[t0, t1]`` is charged to exactly one account:
    the covering span that ends last (several stages running at once —
    the one finishing last is the one gating completion), or
    :data:`BUBBLE` when no span covers it. By construction
    ``sum(stages) + bubble == e2e`` exactly; for a fully-serial pipeline
    each stage's charge equals its duration, so the critical path
    equals the stage sum.

    ``overlap_fraction`` = 1 - union/stage-sum: 0.0 when no two spans
    ever overlap, approaching 1.0 as everything runs concurrently.
    Returns None for open frames or frames with no positive spans.
    """
    d = tl if isinstance(tl, dict) else tl.to_dict()
    if d.get("t1_ns") is None:
        return None
    t0f, t1f = d["t0_ns"], d["t1_ns"]
    ivs: list[tuple[int, int, str]] = []
    for s in d.get("spans", []):
        if s["dur_ns"] <= 0:
            continue
        a = max(s["t0_ns"], t0f)
        b = min(s["t0_ns"] + s["dur_ns"], t1f)
        if b > a:
            ivs.append((a, b, s["name"]))
    if not ivs:
        return None
    points = sorted({t0f, t1f, *(a for a, _, _ in ivs),
                     *(b for _, b, _ in ivs)})
    stages: dict[str, float] = {}
    bubble_ns = 0
    for p, q in zip(points, points[1:]):
        cover = [iv for iv in ivs if iv[0] <= p and iv[1] >= q]
        if not cover:
            bubble_ns += q - p
            continue
        # the gating span: latest end, then latest start for stability
        _, _, name = max(cover, key=lambda iv: (iv[1], iv[0], iv[2]))
        stages[name] = stages.get(name, 0.0) + (q - p)
    e2e_ns = t1f - t0f
    sum_ns = sum(b - a for a, b, _ in ivs)
    union_ns = e2e_ns - bubble_ns
    return {
        "e2e_ms": e2e_ns / 1e6,
        "bubble_ms": bubble_ns / 1e6,
        "stage_sum_ms": sum_ns / 1e6,
        "overlap_fraction": max(0.0, 1.0 - union_ns / sum_ns)
        if sum_ns > 0 else 0.0,
        "stages": {n: v / 1e6 for n, v in stages.items()},
    }


def frame_accounts(timelines: Iterable[Union[FrameTimeline, dict]]
                   ) -> list[dict]:
    """Per-frame critical-path accounts with their frame/session
    identity attached: one dict per COMPLETED frame carrying
    ``display_id`` / ``frame_id`` / the wall window plus the
    :func:`frame_critical_path` attribution (``stages + bubble == e2e``
    exactly). This is the join surface the energy plane charges watts
    against (obs/energy.attribute_timelines): any account that sums to
    the frame window in milliseconds sums to the frame's joules at a
    fixed power draw."""
    out: list[dict] = []
    for tl in timelines:
        d = tl if isinstance(tl, dict) else tl.to_dict()
        cp = frame_critical_path(d)
        if cp is None:
            continue
        out.append({
            "display_id": d.get("display_id"),
            "frame_id": d.get("frame_id"),
            "t0_ns": d["t0_ns"],
            "t1_ns": d["t1_ns"],
            "e2e_ms": cp["e2e_ms"],
            "bubble_ms": cp["bubble_ms"],
            "stages": cp["stages"],
        })
    return out


def _merge_intervals(ivs: list[tuple[int, int]]) -> list[tuple[int, int]]:
    merged: list[list[int]] = []
    for a, b in sorted(ivs):
        if merged and a <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], b)
        else:
            merged.append([a, b])
    return [(a, b) for a, b in merged]


def lane_occupancy(timelines: Iterable[Union[FrameTimeline, dict]]
                   ) -> dict[str, dict]:
    """Per-lane busy fraction over the whole observed window — the
    deep-pipeline acceptance view: after the rework every lane should
    stay busy (occupancy -> 1 for the bottleneck lane) instead of the
    frame-serial pattern where every lane idles while one works.
    ``largest_gap_ms`` is the worst bubble inside the window."""
    by_lane: dict[str, list[tuple[int, int]]] = {}
    w0: Optional[int] = None
    w1: Optional[int] = None
    for tl in timelines:
        d = tl if isinstance(tl, dict) else tl.to_dict()
        if d.get("t1_ns") is None:
            continue
        w0 = d["t0_ns"] if w0 is None else min(w0, d["t0_ns"])
        w1 = d["t1_ns"] if w1 is None else max(w1, d["t1_ns"])
        for s in d.get("spans", []):
            if s["dur_ns"] > 0:
                by_lane.setdefault(s.get("lane") or "?", []).append(
                    (s["t0_ns"], s["t0_ns"] + s["dur_ns"]))
    if w0 is None or w1 is None or w1 <= w0:
        return {}
    window_ns = w1 - w0
    out: dict[str, dict] = {}
    for lane, ivs in by_lane.items():
        # clip to the frame-envelope window (a ws.send span adopted by
        # frame-id can outlive its frame's t1): busy must never exceed
        # the denominator, or occupancy reads > 100%
        clipped = [(max(a, w0), min(b, w1)) for a, b in ivs
                   if min(b, w1) > max(a, w0)]
        merged = _merge_intervals(clipped)
        busy = sum(b - a for a, b in merged)
        gaps = []
        prev = w0
        for a, b in merged:
            gaps.append(a - prev)
            prev = max(prev, b)
        gaps.append(w1 - prev)
        out[lane] = {
            "busy_ms": round(busy / 1e6, 3),
            "window_ms": round(window_ns / 1e6, 3),
            "occupancy": round(busy / window_ns, 4),
            "largest_gap_ms": round(max(0, *gaps) / 1e6, 3),
        }
    return dict(sorted(out.items(), key=lambda kv: -kv[1]["occupancy"]))


def window_overlap_fraction(dicts: list) -> float:
    """Cross-frame span overlap over the whole observed window:
    ``1 - union(all spans)/sum(all spans)`` across every completed
    frame's spans together. A frame-serial engine reads ~0 (consecutive
    frames' spans never coexist); a depth-N pipeline reads the fraction
    of span time that genuinely ran concurrently — frame N+1's
    ``encode.dispatch`` under frame N's readback/packetize. This is THE
    deep-pipeline acceptance number (ROADMAP 2): per-frame stages of a
    pipelined engine still run in sequence *within* each frame, so only
    the window view can see the overlap."""
    ivs: list[tuple[int, int]] = []
    total = 0
    for d in dicts:
        if d.get("t1_ns") is None:
            continue
        for s in d.get("spans", []):
            if s["dur_ns"] > 0:
                ivs.append((s["t0_ns"], s["t0_ns"] + s["dur_ns"]))
                total += s["dur_ns"]
    if total <= 0:
        return 0.0
    union = sum(b - a for a, b in _merge_intervals(ivs))
    return max(0.0, 1.0 - union / total)


def occupancy_report(timelines: Iterable[Union[FrameTimeline, dict]]
                     ) -> dict:
    """Aggregate occupancy / critical-path analysis over completed
    frames. ``overlap_fraction`` is the WINDOW-level cross-frame overlap
    (:func:`window_overlap_fraction`); the per-frame identity
    ``stages + bubble == e2e`` still holds exactly per frame, and the
    per-stage ``critical_path`` shares come from the per-frame totals
    (not a mean of ratios), so long frames weigh what they should."""
    dicts = [tl if isinstance(tl, dict) else tl.to_dict()
             for tl in timelines]
    per = [cp for cp in (frame_critical_path(d) for d in dicts)
           if cp is not None]
    if not per:
        return {"frames": 0, "overlap_fraction": 0.0, "bubble_share": 0.0,
                "critical_path": {}, "e2e_ms": {}, "lanes": {}}
    e2e = sorted(cp["e2e_ms"] for cp in per)
    e2e_total = sum(e2e)
    bubble_total = sum(cp["bubble_ms"] for cp in per)
    stage_tot: dict[str, float] = {}
    for cp in per:
        for name, ms in cp["stages"].items():
            stage_tot[name] = stage_tot.get(name, 0.0) + ms
    critical = {
        name: {"ms": round(tot / len(per), 3),
               "share": round(tot / e2e_total, 4) if e2e_total else 0.0}
        for name, tot in sorted(stage_tot.items(), key=lambda kv: -kv[1])}
    return {
        "frames": len(per),
        "overlap_fraction": round(window_overlap_fraction(dicts), 4),
        "bubble_share": round(bubble_total / e2e_total, 4)
        if e2e_total else 0.0,
        "critical_path": critical,
        "e2e_ms": {"mean": round(e2e_total / len(e2e), 3),
                   "p50": round(_pct(e2e, 0.50), 3),
                   "p99": round(_pct(e2e, 0.99), 3)},
        "lanes": lane_occupancy(dicts),
    }


def render_occupancy(report: dict) -> str:
    """Human table for the CLI / bench stderr."""
    lines = [f"frames={report['frames']} "
             f"overlap={report['overlap_fraction']:.1%} "
             f"bubble={report['bubble_share']:.1%} "
             f"e2e_p50={report['e2e_ms'].get('p50', 0.0)}ms"]
    lines.append(f"{'critical path':<18} {'mean_ms':>9} {'share':>7}")
    for name, s in report["critical_path"].items():
        lines.append(f"{name:<18} {s['ms']:>9.3f} {s['share']:>6.1%}")
    if report["lanes"]:
        lines.append(f"{'lane':<18} {'busy_ms':>9} {'occup':>7} "
                     f"{'max_gap_ms':>11}")
        for lane, s in report["lanes"].items():
            lines.append(f"{lane:<18} {s['busy_ms']:>9.3f} "
                         f"{s['occupancy']:>6.1%} "
                         f"{s['largest_gap_ms']:>11.3f}")
    return "\n".join(lines)


def render_table(summary: dict[str, dict]) -> str:
    """Fixed-width human table for the CLI / bench stderr."""
    lines = [f"{'stage':<18} {'count':>6} {'p50_ms':>9} {'p99_ms':>9} "
             f"{'mean_ms':>9} {'total_ms':>10}"]
    for name, s in summary.items():
        lines.append(f"{name:<18} {s['count']:>6} {s['p50_ms']:>9.3f} "
                     f"{s['p99_ms']:>9.3f} {s['mean_ms']:>9.3f} "
                     f"{s['total_ms']:>10.3f}")
    return "\n".join(lines)
