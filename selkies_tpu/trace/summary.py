"""Per-stage latency summarizer: p50/p99 per span name.

Consumes either live :class:`~.core.FrameTimeline`s or the exported
Chrome trace-event JSON (the offline CLI path), so a BENCH_r*.json
breakdown and a saved /api/trace snapshot summarize identically.
"""

from __future__ import annotations

from typing import Iterable, Union

from .core import FrameTimeline


def _pct(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile on an ascending list (the same convention
    bench.py uses for its p50/p99 line)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(len(sorted_vals) * q))
    return sorted_vals[i]


def summarize_durations(by_stage: dict[str, list[float]]) -> dict[str, dict]:
    """{stage: [ms, ...]} -> {stage: {count, p50_ms, p99_ms, mean_ms,
    total_ms}}, stages sorted by total time descending."""
    out: dict[str, dict] = {}
    for name, vals in by_stage.items():
        vals = sorted(vals)
        total = sum(vals)
        out[name] = {
            "count": len(vals),
            "p50_ms": round(_pct(vals, 0.50), 3),
            "p99_ms": round(_pct(vals, 0.99), 3),
            "mean_ms": round(total / len(vals), 3) if vals else 0.0,
            "total_ms": round(total, 3),
        }
    return dict(sorted(out.items(),
                       key=lambda kv: -kv[1]["total_ms"]))


def summarize_timelines(timelines: Iterable[Union[FrameTimeline, dict]]
                        ) -> dict[str, dict]:
    by_stage: dict[str, list[float]] = {}
    for tl in timelines:
        d = tl if isinstance(tl, dict) else tl.to_dict()
        for s in d.get("spans", []):
            if s["dur_ns"] > 0:
                by_stage.setdefault(s["name"], []).append(s["dur_ns"] / 1e6)
    return summarize_durations(by_stage)


def summarize_events(events: Iterable[dict]) -> dict[str, dict]:
    """Summarize exported trace events: complete (``X``) spans only; the
    per-frame envelope track ('frame N' names) is excluded so stage sums
    aren't double-counted."""
    by_stage: dict[str, list[float]] = {}
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        name = str(e.get("name", "?"))
        if name.startswith("frame "):
            continue
        by_stage.setdefault(name, []).append(float(e["dur"]) / 1e3)
    return summarize_durations(by_stage)


def frame_latency_ms(timelines: Iterable[Union[FrameTimeline, dict]]
                     ) -> list[float]:
    """Completed frames' begin->end wall times (the e2e the stage sum is
    validated against)."""
    out = []
    for tl in timelines:
        d = tl if isinstance(tl, dict) else tl.to_dict()
        if d.get("t1_ns") is not None:
            out.append((d["t1_ns"] - d["t0_ns"]) / 1e6)
    return out


def render_table(summary: dict[str, dict]) -> str:
    """Fixed-width human table for the CLI / bench stderr."""
    lines = [f"{'stage':<18} {'count':>6} {'p50_ms':>9} {'p99_ms':>9} "
             f"{'mean_ms':>9} {'total_ms':>10}"]
    for name, s in summary.items():
        lines.append(f"{name:<18} {s['count']:>6} {s['p50_ms']:>9.3f} "
                     f"{s['p99_ms']:>9.3f} {s['mean_ms']:>9.3f} "
                     f"{s['total_ms']:>10.3f}")
    return "\n".join(lines)
