"""Wayland plane: wire-protocol client, screencopy capture, virtual input.

TPU-era equivalent of pixelflux's external-compositor mode (reference
settings.py:636-638 ``wayland_host_display``): attach to a headless
wlroots-style compositor as a client; frames by zwlr_screencopy into shm,
input by zwp_virtual_keyboard + zwlr_virtual_pointer."""

from .client import (BTN_EXTRA, BTN_LEFT, BTN_MIDDLE, BTN_RIGHT, BTN_SIDE,
                     WaylandClient)
from .keymap import DynamicKeymap
from .wire import WaylandConnection, WireError

__all__ = [
    "WaylandClient", "WaylandConnection", "WireError", "DynamicKeymap",
    "BTN_LEFT", "BTN_RIGHT", "BTN_MIDDLE", "BTN_SIDE", "BTN_EXTRA",
]
