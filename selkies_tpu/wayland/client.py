"""Wayland compositor client: screencopy capture + virtual input.

Implements the external-compositor role the reference's pixelflux plays
when ``wayland_host_display`` is set (reference settings.py:636-638):
frames arrive by zwlr_screencopy into client-allocated shm buffers, input
is injected through zwp_virtual_keyboard / zwlr_virtual_pointer. The
compositor composits; we are a plain (privileged-protocol) client.

All blocking waits are bounded; a missing global degrades the feature
(no screencopy manager -> capture unavailable; no virtual-input managers
-> input unavailable) instead of failing the session.
"""

from __future__ import annotations

import logging
import mmap
import os
import struct
import tempfile
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .wire import (ArgReader, WaylandConnection, WireError, arg_fixed,
                   arg_i32, arg_string, arg_u32)

logger = logging.getLogger("selkies_tpu.wayland")

# wl_shm / drm fourcc format codes we can convert
FMT_ARGB8888 = 0            # little-endian memory: B G R A
FMT_XRGB8888 = 1            # little-endian memory: B G R X
FMT_XBGR8888 = 0x34324258   # 'XB24': R G B X
FMT_ABGR8888 = 0x34324241   # 'AB24': R G B A

_RGB_SLICES = {
    FMT_ARGB8888: (2, 1, 0),
    FMT_XRGB8888: (2, 1, 0),
    FMT_XBGR8888: (0, 1, 2),
    FMT_ABGR8888: (0, 1, 2),
}

# linux input-event codes for the buttons the input plane speaks
BTN_LEFT, BTN_RIGHT, BTN_MIDDLE, BTN_SIDE, BTN_EXTRA = \
    0x110, 0x111, 0x112, 0x113, 0x114


@dataclass
class _Global:
    name: int
    interface: str
    version: int


@dataclass
class _Output:
    proxy: int
    width: int = 0
    height: int = 0
    done: bool = False


@dataclass
class _ShmBuffer:
    pool_id: int
    buffer_id: int
    fd: int
    map: mmap.mmap
    width: int
    height: int
    stride: int
    format: int
    busy: bool = False


@dataclass
class _FrameState:
    """Per-capture screencopy state machine."""
    frame_id: int
    format: int = -1
    width: int = 0
    height: int = 0
    stride: int = 0
    buffer_done: bool = False
    ready: bool = False
    failed: bool = False
    damage: list = field(default_factory=list)


class WaylandClient:
    """One connection driving capture and/or input against a live
    compositor. Single-threaded use per instance (the capture thread or
    the input thread owns its own client)."""

    def __init__(self, display: Optional[str] = None,
                 conn: Optional[WaylandConnection] = None):
        self.conn = conn or WaylandConnection.connect(display)
        c = self.conn
        self.globals: dict[str, _Global] = {}
        self.outputs: list[_Output] = []
        self._shm_id = 0
        self._seat_id = 0
        self._screencopy_id = 0
        self._vkbd_mgr_id = 0
        self._vptr_mgr_id = 0
        self._vkbd_id = 0
        self._vptr_id = 0
        self._buffer: Optional[_ShmBuffer] = None
        self._frame: Optional[_FrameState] = None
        self._registry_id = c.new_id()
        c.handlers[self._registry_id] = self._on_registry
        c.send(c.DISPLAY_ID, 1, arg_u32(self._registry_id))  # get_registry
        c.roundtrip()                      # collect globals
        self._bind_core()
        c.roundtrip()                      # collect output modes/shm formats

    # ------------------------------------------------------------- registry
    def _on_registry(self, opcode: int, r: ArgReader) -> None:
        if opcode == 0:                                  # global
            name, iface, ver = r.u32(), r.string(), r.u32()
            self.globals[iface] = _Global(name, iface, ver)
        elif opcode == 1:                                # global_remove
            name = r.u32()
            for k, g in list(self.globals.items()):
                if g.name == name:
                    del self.globals[k]

    def _bind(self, iface: str, version: int) -> int:
        g = self.globals.get(iface)
        if g is None:
            return 0
        nid = self.conn.new_id()
        v = min(version, g.version)
        # wl_registry.bind carries a TYPED new_id: (interface, version, id)
        self.conn.send(self._registry_id, 0,
                       arg_u32(g.name) + arg_string(iface) + arg_u32(v)
                       + arg_u32(nid))
        return nid

    def _bind_core(self) -> None:
        self._shm_id = self._bind("wl_shm", 1)
        self._seat_id = self._bind("wl_seat", 5)
        if self._seat_id:
            self.conn.handlers[self._seat_id] = lambda op, r: None
        self._screencopy_id = self._bind("zwlr_screencopy_manager_v1", 3)
        self._vkbd_mgr_id = self._bind("zwp_virtual_keyboard_manager_v1", 1)
        self._vptr_mgr_id = self._bind("zwlr_virtual_pointer_manager_v1", 2)
        g = self.globals.get("wl_output")
        if g is not None:
            oid = self._bind("wl_output", 2)
            out = _Output(proxy=oid)
            self.outputs.append(out)
            self.conn.handlers[oid] = self._make_output_handler(out)

    def _make_output_handler(self, out: _Output):
        def h(opcode: int, r: ArgReader) -> None:
            if opcode == 1:                              # mode
                flags = r.u32()
                w, hgt = r.i32(), r.i32()
                if flags & 0x1:                          # current
                    out.width, out.height = w, hgt
            elif opcode == 2:                            # done
                out.done = True
        return h

    # -------------------------------------------------------------- queries
    @property
    def can_capture(self) -> bool:
        return bool(self._screencopy_id and self._shm_id and self.outputs)

    @property
    def can_input(self) -> bool:
        return bool(self._seat_id
                    and (self._vkbd_mgr_id or self._vptr_mgr_id))

    def output_size(self) -> tuple[int, int]:
        if not self.outputs:
            return (0, 0)
        o = self.outputs[0]
        return (o.width, o.height)

    # -------------------------------------------------------------- capture
    def _ensure_buffer(self, fmt: int, w: int, h: int, stride: int
                       ) -> _ShmBuffer:
        b = self._buffer
        if b and (b.width, b.height, b.stride, b.format) == (w, h, stride,
                                                             fmt):
            return b
        if b is not None:
            self._destroy_buffer(b)
        size = stride * h
        fd = os.memfd_create("selkies-shm") \
            if hasattr(os, "memfd_create") else _tmp_fd(size)
        os.ftruncate(fd, size)
        m = mmap.mmap(fd, size)
        pool_id = self.conn.new_id()
        self.conn.send(self._shm_id, 0,
                       arg_u32(pool_id) + arg_i32(size), fds=(fd,))
        buf_id = self.conn.new_id()
        self.conn.send(pool_id, 0, arg_u32(buf_id) + arg_i32(0)
                       + arg_i32(w) + arg_i32(h) + arg_i32(stride)
                       + arg_u32(fmt))
        b = _ShmBuffer(pool_id=pool_id, buffer_id=buf_id, fd=fd, map=m,
                       width=w, height=h, stride=stride, format=fmt)

        def _on_buffer(opcode: int, r: ArgReader) -> None:
            if opcode == 0:                              # release
                b.busy = False
        self.conn.handlers[buf_id] = _on_buffer
        self._buffer = b
        return b

    def _destroy_buffer(self, b: _ShmBuffer) -> None:
        try:
            self.conn.send(b.buffer_id, 0)               # wl_buffer.destroy
            self.conn.send(b.pool_id, 1)                 # wl_shm_pool.destroy
        except (WireError, OSError):
            pass
        b.map.close()
        os.close(b.fd)
        if self._buffer is b:
            self._buffer = None

    def capture_frame(self, overlay_cursor: bool = True,
                      timeout: float = 5.0) -> Optional[np.ndarray]:
        """One screencopy pass -> (H, W, 3) uint8 RGB, or None when the
        compositor reports failure (output gone, mid-modeset)."""
        if not self.can_capture:
            raise WireError("compositor lacks zwlr_screencopy/wl_shm")
        c = self.conn
        frame_id = c.new_id()
        st = _FrameState(frame_id=frame_id)
        self._frame = st
        c.handlers[frame_id] = self._make_frame_handler(st)
        c.send(self._screencopy_id, 0,
               arg_u32(frame_id) + arg_i32(1 if overlay_cursor else 0)
               + arg_u32(self.outputs[0].proxy))
        deadline = time.monotonic() + timeout
        try:
            # phase 1: buffer parameters (wait for buffer_done on v3, or
            # the first buffer event on older compositors)
            while not (st.buffer_done or st.failed or st.format >= 0):
                self._pump(deadline)
            if st.failed:
                c.send(frame_id, 1)                      # destroy
                return None
            b = self._ensure_buffer(st.format, st.width, st.height,
                                    st.stride)
            c.send(frame_id, 0, arg_u32(b.buffer_id))    # copy
            while not (st.ready or st.failed):
                self._pump(deadline)
            c.send(frame_id, 1)                          # destroy
        finally:
            # every exit (failed / ready / timeout raise) releases the
            # handler — a per-capture leak would grow for outage minutes
            c.handlers.pop(frame_id, None)
        if st.failed:
            return None
        flat = np.frombuffer(b.map, dtype=np.uint8,
                             count=st.stride * st.height)
        px = flat.reshape(st.height, st.stride // 4, 4)[:, :st.width, :]
        r, g, bl = _RGB_SLICES.get(st.format, (2, 1, 0))
        return np.stack([px[..., r], px[..., g], px[..., bl]], axis=-1)

    def _make_frame_handler(self, st: _FrameState):
        def h(opcode: int, r: ArgReader) -> None:
            if opcode == 0:                              # buffer
                st.format, st.width = r.u32(), r.u32()
                st.height, st.stride = r.u32(), r.u32()
            elif opcode == 1:                            # flags
                r.u32()
            elif opcode == 2:                            # ready
                st.ready = True
            elif opcode == 3:                            # failed
                st.failed = True
            elif opcode == 4:                            # damage
                st.damage.append((r.u32(), r.u32(), r.u32(), r.u32()))
            elif opcode == 6:                            # buffer_done (v3)
                st.buffer_done = True
        return h

    def _pump(self, deadline: float) -> None:
        left = deadline - time.monotonic()
        if left <= 0:
            raise WireError("screencopy timed out")
        self.conn.dispatch(timeout=left)

    # ---------------------------------------------------------------- input
    def ensure_virtual_keyboard(self, keymap_text: str) -> bool:
        """Create (or re-keymap) the virtual keyboard. xkb_v1 keymaps ride
        a sealed shm fd; size excludes the terminating NUL reader-side."""
        if not (self._vkbd_mgr_id and self._seat_id):
            return False
        c = self.conn
        if not self._vkbd_id:
            self._vkbd_id = c.new_id()
            c.send(self._vkbd_mgr_id, 0,
                   arg_u32(self._seat_id) + arg_u32(self._vkbd_id))
        raw = keymap_text.encode() + b"\x00"
        fd = os.memfd_create("selkies-keymap") \
            if hasattr(os, "memfd_create") else _tmp_fd(len(raw))
        os.ftruncate(fd, len(raw))
        with mmap.mmap(fd, len(raw)) as m:
            m.write(raw)
        c.send(self._vkbd_id, 0,
               arg_u32(1) + arg_u32(len(raw)), fds=(fd,))   # keymap xkb_v1
        os.close(fd)
        return True

    def keyboard_key(self, evdev_key: int, down: bool) -> None:
        """key codes are EVDEV (xkb keycode - 8), per the protocol."""
        if not self._vkbd_id:
            return
        self.conn.send(self._vkbd_id, 1,
                       arg_u32(_ms()) + arg_u32(evdev_key)
                       + arg_u32(1 if down else 0))

    def keyboard_modifiers(self, depressed: int, latched: int = 0,
                           locked: int = 0, group: int = 0) -> None:
        if not self._vkbd_id:
            return
        self.conn.send(self._vkbd_id, 2,
                       arg_u32(depressed) + arg_u32(latched)
                       + arg_u32(locked) + arg_u32(group))

    def ensure_virtual_pointer(self) -> bool:
        if not self._vptr_mgr_id:
            return False
        if not self._vptr_id:
            self._vptr_id = self.conn.new_id()
            # seat is nullable (id 0 lets the compositor pick)
            self.conn.send(self._vptr_mgr_id, 0,
                           arg_u32(self._seat_id) + arg_u32(self._vptr_id))
        return True

    def pointer_motion_abs(self, x: int, y: int, ew: int, eh: int) -> None:
        if self.ensure_virtual_pointer():
            self.conn.send(self._vptr_id, 1,
                           arg_u32(_ms()) + arg_u32(max(0, x))
                           + arg_u32(max(0, y)) + arg_u32(ew) + arg_u32(eh))
            self.conn.send(self._vptr_id, 4)             # frame

    def pointer_motion_rel(self, dx: float, dy: float) -> None:
        if self.ensure_virtual_pointer():
            self.conn.send(self._vptr_id, 0,
                           arg_u32(_ms()) + arg_fixed(dx) + arg_fixed(dy))
            self.conn.send(self._vptr_id, 4)

    def pointer_button(self, btn_code: int, down: bool) -> None:
        if self.ensure_virtual_pointer():
            self.conn.send(self._vptr_id, 2,
                           arg_u32(_ms()) + arg_u32(btn_code)
                           + arg_u32(1 if down else 0))
            self.conn.send(self._vptr_id, 4)

    def pointer_axis(self, axis: int, value: float) -> None:
        """axis: 0 vertical, 1 horizontal; value in wl_pointer units
        (one wheel notch ~ 15)."""
        if self.ensure_virtual_pointer():
            self.conn.send(self._vptr_id, 3,
                           arg_u32(_ms()) + arg_u32(axis) + arg_fixed(value))
            self.conn.send(self._vptr_id, 4)

    # ------------------------------------------------------------- lifecycle
    def flush_events(self) -> None:
        """Drain pending compositor events (buffer releases etc.)."""
        try:
            self.conn.dispatch(timeout=0.0)
        except WireError:
            pass

    def close(self) -> None:
        if self._buffer is not None:
            self._destroy_buffer(self._buffer)
        self.conn.close()


def _tmp_fd(size: int) -> int:
    f = tempfile.TemporaryFile()
    fd = os.dup(f.fileno())
    f.close()
    return fd


def _ms() -> int:
    return int(time.monotonic() * 1000) & 0xFFFFFFFF
